"""Always-on refit scheduler: the ingest->fit->publish->serve loop as a
daemon with a data-to-forecast freshness SLO.

PR 13 made one delta-refit cycle cheap (``tsspark_tpu.refit``: a 10%
churn cycle runs in ~15% of the cold fit+publish wall) — but a cycle
only happened when someone invoked ``run_refit`` by hand, so the system
had no notion of how STALE its forecasts were, which is the latency
production consumers actually page on.  This module closes ROADMAP
item 4:

* **The loop** — ``RefitScheduler`` watches the data plane's
  ``delta_seq`` and triggers cycles continuously under a
  debounce/backoff policy.  Crash-safe by construction: every cycle
  rides the refit plan protocol (``refit_plan.json`` pinned at detect,
  chunk flushes landed under leases, copy-forward publish, manifest
  flip), so a scheduler killed at ANY stage is succeeded by one that
  resumes the pinned plan — never a fresh detect racing deltas landed
  after the kill.  The ``sched_state.json`` file is advisory telemetry
  (cycle counts, freshness summary), not correctness state.

* **Pipelining** — consecutive cycles overlap: cycle N+1's detect,
  claim compaction, and spill (all mmap reads) run while cycle N's
  copy-forward publish and pool flip run on the publisher thread.  The
  resident fit is the only exclusive resource; it waits for cycle N's
  publish (its copy-forward base must exist in the registry) and for
  nothing else.  Cycle N+1's warm init for rows refit in N comes from
  N's in-memory solution (bitwise what N's plane will hold), so the
  overlap never reads a half-written plane.

* **Speculation** (the arXiv 2511.18191 bet, applied to refits) —
  during idle ticks the scheduler pre-gathers theta and pre-compacts
  claim sets for the series its arrival model predicts will advance
  next (per-series inter-arrival EWMA off the landed patch stream).
  When the real delta lands, predicted rows skip the plane page reads;
  mispredictions are discarded as cheaply as a rejected draft token.
  A speculative init is bitwise the plane gather it replaces, so
  speculation is a latency lever, never a numerics input.

* **Freshness** — the product metric: wall time from a row's
  ``deltaok_`` sentinel landing (``data/plane.py``) to the first
  request served from a version containing it (version manifests carry
  the ``data_stamp`` the snapshot was fitted at; serve request spans
  carry the version).  Tracked live as ``refit.freshness`` spans +
  ``tsspark_sched_freshness*`` metrics (``obs watch`` shows the
  trailing p95), normalized into RUNHISTORY rows by the freshness
  bench, and budgeted under ``[tool.tsspark.slo.freshness]``.

``bench --freshness`` (:func:`run_freshness_bench`) drives a sustained
churn stream through the loop in serialized and pipelined modes and
reports steady-state p50/p95 freshness — the pipelined win is the
overlap.  The chaos ``loop-storm`` class kills the scheduler and every
stage it drives mid-cycle (``tsspark_tpu.chaos``).

See docs/PERF.md "Continuous refit & freshness" for engage rules.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import shutil
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tsspark_tpu import refit
from tsspark_tpu.obs import context as obs
from tsspark_tpu.obs.metrics import DEFAULT as METRICS
from tsspark_tpu.resilience import faults
from tsspark_tpu.io import (
    StorageError,
    active_ladder,
    atomic_write,
    current_state,
)

#: Advisory scheduler telemetry (cycles, freshness summary, backoff
#: state) — replaced atomically after every cycle so ``obs watch`` and
#: operators never parse a torn record.  Correctness state lives in the
#: refit plan protocol, NOT here: a successor ignores a missing file.
SCHED_STATE_FILE = "sched_state.json"

#: Bounded freshness sample window (the daemon runs indefinitely).
FRESHNESS_WINDOW = 4096

#: A series is data-overdue when no delta arrived for more than this
#: multiple of its EWMA inter-arrival (the ``tsspark_sched_overdue_series``
#: gauge and the alert stream's data-liveness kind share this default).
OVERDUE_K = 3.0


class ArrivalModel:
    """Per-series inter-arrival EWMA off the landed patch stream.

    Every landed delta's (unix, changed rows) updates one EWMA of the
    inter-arrival gap per series; prediction returns the rows most
    OVERDUE (smallest predicted next-arrival time) — the likely-stale
    set the scheduler pre-gathers during idle.  Bounded: the tracked
    set is capped by least-recently-advanced eviction so a million-row
    fleet with uniform churn cannot grow the dicts without bound."""

    def __init__(self, alpha: float = 0.3, max_tracked: int = 65536):
        self.alpha = float(alpha)
        self.max_tracked = int(max_tracked)
        self._last: Dict[int, float] = {}
        self._ewma: Dict[int, float] = {}
        self._seen_seq = 0

    def seen_seq(self) -> int:
        """Highest delta seq already folded in — callers gate their
        patch reads on this so an always-on daemon never re-opens every
        historical patch zip per detect (O(T^2) over its lifetime)."""
        return self._seen_seq

    def note_delta(self, seq: int, unix: float, rows) -> None:
        """Fold one landed delta into the model (idempotent by seq)."""
        if rows is None or int(seq) <= self._seen_seq:
            return
        self._seen_seq = int(seq)
        a = self.alpha
        for r in np.asarray(rows, np.int64).tolist():
            last = self._last.get(r)
            if last is not None:
                dt = max(float(unix) - last, 1e-3)
                prev = self._ewma.get(r)
                self._ewma[r] = (dt if prev is None
                                 else (1.0 - a) * prev + a * dt)
            self._last[r] = float(unix)
        if len(self._last) > self.max_tracked:
            drop = sorted(self._last, key=self._last.get)[
                : len(self._last) - self.max_tracked
            ]
            for r in drop:
                self._last.pop(r, None)
                self._ewma.pop(r, None)

    def predicted_rows(self, cap: int) -> np.ndarray:
        """Up to ``cap`` rows most overdue to advance (smallest
        predicted next-arrival), sorted by row index (the claim-set
        order a refit plan uses).  Only rows with a LEARNED cadence
        (seen advancing at least twice) are predictable — a one-shot
        row has no inter-arrival estimate, and ranking it by the global
        fallback would make every fresh arrival look overdue, burning
        the speculation budget on series that may never recur."""
        if not self._ewma or cap <= 0:
            return np.empty(0, np.int64)
        rows = np.fromiter(self._ewma.keys(), np.int64,
                           count=len(self._ewma))
        dts = np.fromiter(self._ewma.values(), np.float64,
                          count=len(self._ewma))
        last = np.asarray([self._last[int(r)] for r in rows],
                          np.float64)
        due = last + dts
        order = np.argsort(due, kind="stable")
        return np.sort(rows[order[: int(cap)]])

    def overdue_rows(self, now: float, k: float = 3.0) -> Dict[int, float]:
        """Rows whose learned cadence says a delta is OVERDUE: no
        arrival for more than ``k``x the series' EWMA inter-arrival.
        Returns ``{row: seconds overdue beyond the threshold}`` — the
        data-liveness complement to value anomalies (a series that
        stops arriving pages just like one that breaches its interval).
        Like :meth:`predicted_rows`, only rows with a LEARNED cadence
        qualify; a one-shot row has no baseline to be overdue against."""
        out: Dict[int, float] = {}
        now = float(now)
        for r, dt in self._ewma.items():
            gap = now - self._last[r] - float(k) * dt
            if gap > 0.0:
                out[int(r)] = gap
        return out

    def tracked(self) -> int:
        return len(self._last)


def _pct(samples: Sequence[float], q: float) -> Optional[float]:
    if not samples:
        return None
    return round(float(np.percentile(np.asarray(samples, np.float64),
                                     q)), 4)


def _merge_busy(intervals: List[Tuple[float, float]]) -> float:
    """Union length of (t0, t1) wall intervals — the loop's busy time
    with pipeline overlap counted ONCE (two overlapped stages are one
    busy window, not two)."""
    total = 0.0
    cur_hi: Optional[float] = None
    for t0, t1 in sorted(intervals):
        if t1 <= t0:
            continue
        if cur_hi is None or t0 > cur_hi:
            total += t1 - t0
            cur_hi = t1
        elif t1 > cur_hi:
            total += t1 - cur_hi
            cur_hi = t1
    return total


class RefitScheduler:
    """The always-on loop: watch ``delta_seq``, run pipelined refit
    cycles, track freshness.  One instance per (data_dir, registry,
    scratch) — crash recovery is a NEW instance over the same scratch.

    Flip routing mirrors ``refit.run_refit``: ``pool.activate`` when a
    pool is attached, else ``flip_fn(version)``, else
    ``registry.activate`` (``activate=False`` publishes without
    flipping — a front elsewhere owns the flip).

    ``freshness_probe(version) -> served_version`` closes the loop on
    the serving side: after each flip the scheduler probes until a
    request is served at (or past) the new version, and THAT wall time
    stamps the freshness of every delta the version covers.  Without a
    probe (the bare CLI daemon), freshness is measured to flip
    completion and the span says so (``probe="flip"``)."""

    def __init__(
        self,
        data_dir: str,
        registry,
        scratch: str,
        *,
        chunk: int = 512,
        solver_config=None,
        phase1_iters: int = 0,
        no_phase1_tune: bool = True,
        warm_start: bool = True,
        pool=None,
        flip_fn: Optional[Callable[[int], None]] = None,
        activate: bool = True,
        hot_series: Optional[Sequence[str]] = None,
        horizons: Sequence[int] = (7, 14),
        poll_s: float = 0.05,
        debounce_s: float = 0.1,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 30.0,
        pipeline: bool = True,
        speculate: bool = True,
        spec_refresh_s: float = 0.5,
        spec_cap: Optional[int] = None,
        freshness_probe: Optional[Callable[[int], Optional[int]]] = None,
        probe_timeout_s: float = 10.0,
    ):
        from tsspark_tpu.config import SolverConfig

        self.data_dir = data_dir
        self.registry = registry
        self.scratch = scratch
        self.chunk = int(chunk)
        self.solver_config = solver_config or SolverConfig()
        self.phase1_iters = int(phase1_iters)
        self.no_phase1_tune = bool(no_phase1_tune)
        self.warm_start = bool(warm_start)
        self.pool = pool
        self.flip_fn = flip_fn
        self.activate = bool(activate)
        self.hot_series = list(hot_series or ())
        self.horizons = tuple(horizons)
        self.poll_s = float(poll_s)
        self.debounce_s = float(debounce_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.pipeline = bool(pipeline)
        self.speculate = bool(speculate)
        self.spec_refresh_s = float(spec_refresh_s)
        self.spec_cap = spec_cap
        self.freshness_probe = freshness_probe
        self.probe_timeout_s = float(probe_timeout_s)

        self.model = ArrivalModel()
        self.freshness: "collections.deque" = collections.deque(
            maxlen=FRESHNESS_WINDOW
        )
        self.cycles = 0
        self.resumed_cycles = 0
        self.failures = 0
        self.probe_failures = 0
        self.probe_errors = 0
        self.wrong_version = 0
        self.spec_predicted = 0
        self.spec_hits = 0
        self.spec_cycles = 0
        self._fail_streak = 0
        self._pending: Dict[int, float] = {}
        self._recent_changed: "collections.deque" = collections.deque(
            maxlen=8
        )
        self._head_version: Optional[int] = None
        self._head_stamp: Optional[int] = None
        self._carry: Optional[Dict] = None
        self._spec: Optional[Dict] = None
        self._spec_rows: Optional[np.ndarray] = None
        self._last_spec = 0.0
        self._last_reprobe = 0.0
        self._max_served = 0
        self._seq_seen = 0
        self._busy: List[Tuple[float, float]] = []
        self._pub_thread: Optional[threading.Thread] = None
        self._pub_result: Optional[Dict] = None
        # The cycle handed to the publisher, kept until its publish
        # SUCCEEDS: a transient publish/flip failure is retried from
        # here (under backoff) — without it the daemon would idle on a
        # completed fit until the next delta happened to land.
        self._inflight: Optional[Tuple[Dict, Optional[Dict]]] = None
        self._stop = threading.Event()
        self._m_fresh = METRICS.gauge(
            "tsspark_sched_freshness_last_seconds"
        )
        self._m_fresh_hist = METRICS.histogram(
            "tsspark_sched_freshness_seconds"
        )
        self._m_cycles = METRICS.counter("tsspark_sched_cycles_total")
        self._m_backlog = METRICS.gauge("tsspark_sched_backlog_deltas")
        self._m_spec_pred = METRICS.counter(
            "tsspark_sched_spec_predicted_total"
        )
        self._m_spec_hit = METRICS.counter(
            "tsspark_sched_spec_hits_total"
        )
        self._m_overdue = METRICS.gauge("tsspark_sched_overdue_series")
        self._m_spec_fail = METRICS.counter(
            "tsspark_sched_spec_attach_failures_total"
        )
        self._last_overdue_probe = 0.0

    # -- lifecycle -------------------------------------------------------------

    def stop(self) -> None:
        """Ask the loop to exit after the current tick (thread-safe)."""
        self._stop.set()

    def run(self, *, duration_s: Optional[float] = None,
            max_cycles: Optional[int] = None,
            until_stamp: Optional[int] = None) -> Dict:
        """Drive the loop until a bound is hit: ``duration_s`` of wall,
        ``max_cycles`` completed cycles, or ``until_stamp`` — exit once
        a version covering that delta seq has published AND its
        freshness resolved (the bench's drain condition).  With no
        bound, runs until :meth:`stop` (the daemon mode).  Returns the
        run summary (also printed by the CLI as its one JSON line)."""
        t_start = time.monotonic()
        t_wall0 = time.time()
        self._busy = []  # busy/overhead accounting is per-run
        os.makedirs(self.scratch, exist_ok=True)
        self._startup_resume()
        while not self._stop.is_set():
            if duration_s is not None and \
                    time.monotonic() - t_start >= duration_s:
                break
            if max_cycles is not None and self.cycles >= max_cycles:
                break
            if until_stamp is not None \
                    and (self._head_stamp or 0) >= int(until_stamp) \
                    and self._pub_thread is None \
                    and self._inflight is None \
                    and not self._pending:
                break
            self._tick()
        if not self._join_publisher(block=True):
            # Exiting with the last publish failed: count it so the
            # summary (and exit code) reflect the unpublished cycle —
            # the plan stays pinned for the successor.
            self.failures += 1
            self._fail_streak += 1
        wall = time.time() - t_wall0
        busy = _merge_busy(self._busy)
        summary = {
            "kind": "sched-summary",
            "cycles": self.cycles,
            "resumed_cycles": self.resumed_cycles,
            "failures": self.failures,
            "head_version": self._head_version,
            "head_stamp": self._head_stamp,
            "pending_deltas": len(self._pending),
            "wall_s": round(wall, 3),
            "busy_s": round(busy, 3),
            "cycle_overhead_frac": (round(busy / wall, 4) if wall
                                    else None),
            "freshness": self.freshness_summary(),
            "spec": self.spec_summary(),
            "wrong_version": self.wrong_version,
            "probe_failures": self.probe_failures,
            "probe_errors": self.probe_errors,
            "pipeline": self.pipeline,
            "disk_ladder": current_state(self.scratch),
            "ok": self._fail_streak == 0,
        }
        self._write_sched_state(summary)
        return summary

    def freshness_summary(self) -> Dict:
        vals = [fr for _seq, fr in self.freshness]
        return {
            "n": len(vals),
            "p50_s": _pct(vals, 50),
            "p95_s": _pct(vals, 95),
            "mean_s": (round(float(np.mean(vals)), 4) if vals
                       else None),
            "max_s": (round(float(np.max(vals)), 4) if vals else None),
        }

    def spec_summary(self) -> Dict:
        return {
            "enabled": self.speculate,
            "predicted": self.spec_predicted,
            "hits": self.spec_hits,
            "cycles_with_speculation": self.spec_cycles,
            "hit_rate": (round(self.spec_hits / self.spec_predicted, 4)
                         if self.spec_predicted else None),
            "tracked_series": self.model.tracked(),
        }

    # -- startup ---------------------------------------------------------------

    def _startup_resume(self) -> None:
        """Adopt the world as a successor: seed the pending-delta map
        from the landed records, resume any pinned incomplete plan
        through ``run_refit`` (zero fit dispatches when the waves
        already landed), and only then reap stale cycle dirs."""
        from tsspark_tpu.data import plane

        active = self.registry.active_version()
        stamp = (0 if active is None
                 else self.registry.version_stamp(int(active)))
        self._head_version = active
        self._head_stamp = stamp
        for rec in plane.delta_records(self.data_dir):
            self._seq_seen = max(self._seq_seen, int(rec["seq"]))
            if rec["seq"] > stamp:
                self._pending.setdefault(
                    rec["seq"], float(rec.get("unix") or time.time())
                )
            if rec["seq"] > self.model.seen_seq():
                self.model.note_delta(
                    rec["seq"], float(rec.get("unix") or time.time()),
                    plane.delta_rows(self.data_dir, rec["seq"]),
                )
        plan = refit.read_refit_plan(self.scratch)
        if plan is not None and not plan.get("complete"):
            t0 = time.time()
            res = refit.run_refit(
                data_dir=self.data_dir, registry=self.registry,
                scratch=self.scratch, chunk=self.chunk,
                solver_config=self.solver_config,
                phase1_iters=self.phase1_iters,
                no_phase1_tune=self.no_phase1_tune,
                warm_start=self.warm_start, flip_fn=self._flip,
            )
            self._busy.append((t0, time.time()))
            if res.get("complete"):
                self.cycles += 1
                self.resumed_cycles += int(bool(res.get("resumed")))
                self._m_cycles.inc()
                # Advance the frontier BEFORE resolving freshness: a
                # stale head here would make the first tick re-detect
                # (and re-fit) the set this publish just covered, and
                # re-seed its pending seqs for a double-counted
                # freshness sample.
                self._head_version = int(res["version"])
                self._head_stamp = int(res["plan_stamp"])
                self._after_publish(int(res["version"]),
                                    int(res["plan_stamp"]))
            else:
                self._note_failure("resume")
        else:
            refit.reap_cycles(self.scratch)

    # -- the loop --------------------------------------------------------------

    def _frontier(self) -> int:
        """The stamp the NEXT detect diffs against: the last drafted
        cycle's plan_stamp (every delta at or below it is already owned
        by a cycle in flight or published)."""
        return int(self._head_stamp or 0)

    def _tick(self) -> None:
        from tsspark_tpu.data import plane

        if self._pub_thread is None and self._inflight is not None:
            # A previous publish failed: re-drive the stashed cycle
            # (the backoff already slept) before looking for new work.
            plan, fit_res = self._inflight
            self._spawn_publisher(plan, fit_res)
            if not self._join_publisher(block=True):
                self._note_failure("publish")
            return
        # Incremental poll: O(new deltas) per tick, not a full scan of
        # every historical visibility record (delta_seq_since walks up
        # from the highest seq this daemon has already observed).
        self._seq_seen = plane.delta_seq_since(self.data_dir,
                                               self._seq_seen)
        seq = self._seq_seen
        frontier = self._frontier()
        self._m_backlog.set(float(max(0, seq - frontier)))
        if seq <= frontier:
            if not self._join_publisher(block=False):
                # The overlapped publish failed while the loop idled:
                # back off, then the retry branch above re-drives it.
                self._note_failure("publish")
                return
            self._idle_tick()
            return
        lad = active_ladder(self.scratch)
        if lad is not None and not lad.allows("ingest"):
            # Ladder rung 3 (pause_ingest): the cycle's spill + fit
            # would grow scratch at the worst possible moment.  New
            # deltas stay pending (freshness pays, by design); the
            # idle tick keeps reaping, and relief resumes intake.
            self._idle_tick()
            return
        if self.debounce_s > 0:
            # Debounce: let a landing burst settle so one cycle owns
            # the whole batch instead of one cycle per delta.
            time.sleep(self.debounce_s)

        faults.inject("sched_detect")
        t_work0 = time.time()
        plan = refit.draft_plan(self.data_dir, frontier)
        self._note_deltas(frontier, plan["plan_stamp"])
        obs.record("sched.detect", t_work0, time.time() - t_work0,
                   n_changed=plan["n_changed"],
                   plan_stamp=plan["plan_stamp"])

        cache = None
        if plan["n_changed"]:
            try:
                # Overlapped stages: spill + warm-cache merge are mmap
                # reads; cycle N's publish may still be running.
                refit.ensure_spill(self.data_dir, plan, self.scratch)
                cache = self._warm_cache_for(plan)
            except StorageError:
                # A typed disk refusal (budget tripped between the
                # ladder gate and the spill, or a real ENOSPC/EIO) is
                # a cycle failure like any other: back off and retry —
                # the draft is idempotent — instead of crashing the
                # daemon.
                self._busy.append((t_work0, time.time()))
                self._note_failure("storage")
                return
        if not self._join_publisher(block=True):
            self._busy.append((t_work0, time.time()))
            self._note_failure("publish")
            return
        head = (self._head_version
                if self._head_version is not None
                else self.registry.active_version())
        if head is None:
            raise RuntimeError(
                "scheduler needs a published base version"
            )
        if self.registry.version_stamp(int(head)) \
                != plan["base_stamp"]:
            # The world moved under the draft (an out-of-band
            # publisher): drop it and re-detect against the new head.
            self._head_stamp = self.registry.version_stamp(int(head))
            self._busy.append((t_work0, time.time()))
            return
        plan = refit.pin_drafted(self.scratch, plan, int(head))

        fit_res = None
        if plan["n_changed"]:
            self._score_speculation(plan)
            fit_res = refit.fit_changed(
                self.data_dir, self.registry, plan, self.scratch,
                chunk=self.chunk, solver_config=self.solver_config,
                phase1_iters=self.phase1_iters,
                no_phase1_tune=self.no_phase1_tune,
                warm_start=self.warm_start, theta_cache=cache,
            )
            if not fit_res["complete"]:
                self._busy.append((t_work0, time.time()))
                self._note_failure("fit")
                return
        self._busy.append((t_work0, time.time()))
        self._spawn_publisher(plan, fit_res)
        self._head_stamp = int(plan["plan_stamp"])
        self._carry = self._carry_from(plan, fit_res)
        self._spec = None  # consumed (or stale) either way
        if not self.pipeline:
            self._join_publisher(block=True)

    def _idle_tick(self) -> None:
        """No new deltas: re-probe any stranded freshness, refresh the
        speculative warm prep, then sleep.  NEVER publishes — a
        zero-delta idle tick must not grow the registry, the snapshot
        dir, or RUNHISTORY (pinned by tests/test_sched.py, and by the
        ``sched-idle`` effect budget: no durable or raw write is
        reachable from here outside the declared spill/reap/re-probe
        cut points, so mispredicted speculation is free to abandon)."""
        if (self._pending and self._pub_thread is None
                and self._head_version is not None
                and min(self._pending) <= (self._head_stamp or 0)
                and time.monotonic() - self._last_reprobe >= 1.0):
            # A probe timeout left resolved-but-unconfirmed seqs
            # pending; without this, nothing re-probes until the NEXT
            # publish — which may never come on a paused stream.
            self._last_reprobe = time.monotonic()
            self._after_publish(int(self._head_version),
                                int(self._head_stamp or 0))
        if time.monotonic() - self._last_overdue_probe >= 1.0:
            # Data-liveness: series overdue by >k× their EWMA
            # inter-arrival (the alert stream reads the same model for
            # its data-liveness alert kind; the gauge is the fleet-wide
            # at-a-glance view).
            self._last_overdue_probe = time.monotonic()
            self._m_overdue.set(
                float(len(self.model.overdue_rows(time.time(),
                                                  k=OVERDUE_K)))
            )
        lad = active_ladder(self.scratch)
        if lad is not None and lad.should_reap():
            # Ladder rung 2 (reap): shrinking headroom — drop retained
            # cycle history down to the safety floor NOW instead of at
            # the next publish, sparing the in-flight plan's dir (its
            # spill is the publisher's input).
            keep = ()
            if self._inflight is not None:
                keep = (refit.cycle_paths(self.scratch,
                                          self._inflight[0])[0],)
            refit.reap_cycles(self.scratch, keep=keep)
        if (self.speculate and self._pub_thread is None
                and self.warm_start
                and (lad is None or lad.allows("speculate"))
                and time.monotonic() - self._last_spec
                >= self.spec_refresh_s):
            self._last_spec = time.monotonic()
            self._refresh_speculation()
        time.sleep(self.poll_s)

    # -- speculation -----------------------------------------------------------

    def _spec_budget(self) -> int:
        if self.spec_cap is not None:
            return int(self.spec_cap)
        recent = [n for n in self._recent_changed]
        base = int(np.mean(recent)) if recent else 0
        return max(32, 2 * base)

    def _refresh_speculation(self) -> None:
        """Pre-gather theta + pre-compact the claim set for the rows
        the arrival model predicts advance next.  Valid only against
        the CURRENT head stamp; a publish invalidates it (the cache is
        stamp-checked at consume time, so staleness is harmless)."""
        from tsspark_tpu.serve import snapplane

        head = self._head_version
        if head is None:
            return
        rows = self.model.predicted_rows(self._spec_budget())
        if not len(rows):
            return
        try:
            view = snapplane.attach(
                self.registry.version_dir(int(head)), verify=False
            )
        except (snapplane.SnapshotPlaneError, StorageError,
                OSError, ValueError):
            # No attachable plane (absent version dir, torn/partial
            # snapshot, classified disk fault): speculation is moot,
            # but count it — a version that NEVER attaches is a publish
            # bug this counter surfaces.
            self._m_spec_fail.inc()
            return
        t0 = time.time()
        theta = refit.warm_theta_gather(view.state.theta, rows)
        self._spec = {
            "base_stamp": int(self._head_stamp or 0),
            "rows": rows,
            "theta": np.asarray(theta, np.float32),
        }
        self._spec_rows = rows
        obs.record("sched.speculate", t0, time.time() - t0,
                   rows=int(len(rows)))

    def _score_speculation(self, plan: Dict) -> None:
        """Hit accounting: predicted ∩ actual over actual — the
        spec_hit_rate the SLO budgets.  Mispredicted rows cost only
        their pre-gather (discarded like a rejected draft token)."""
        if self._spec_rows is None:
            return
        changed = np.asarray(plan["changed_rows"], np.int64)
        hits = int(np.intersect1d(self._spec_rows, changed).size)
        self.spec_predicted += int(len(self._spec_rows))
        self.spec_hits += hits
        self.spec_cycles += 1
        self._m_spec_pred.inc(int(len(self._spec_rows)))
        self._m_spec_hit.inc(hits)
        self._spec_rows = None

    def _warm_cache_for(self, plan: Dict) -> Optional[Dict]:
        """Merge the carry buffer (cycle N's in-memory refit rows) and
        the speculative pre-gather into one theta cache for cycle N+1,
        both stamp-checked against the plan's base.  Rows covered by
        neither fall back to fit_changed's per-wave plane gather."""
        if not self.warm_start:
            return None
        parts: List[Tuple[np.ndarray, np.ndarray]] = []
        for src in (self._carry, self._spec):
            if (src is not None
                    and int(src["base_stamp"])
                    == int(plan["base_stamp"])
                    and len(src["rows"])):
                parts.append((np.asarray(src["rows"], np.int64),
                              np.asarray(src["theta"], np.float32)))
        if not parts:
            return None
        if len(parts) == 1:
            rows, theta = parts[0]
        else:
            # Carry wins on overlap: its rows are the just-refit ones,
            # bitwise what the new base plane will hold.
            rows_c, theta_c = parts[0]
            rows_s, theta_s = parts[1]
            keep = ~np.isin(rows_s, rows_c)
            rows = np.concatenate([rows_c, rows_s[keep]])
            theta = np.concatenate([theta_c, theta_s[keep]])
            order = np.argsort(rows, kind="stable")
            rows, theta = rows[order], theta[order]
        return {"base_stamp": int(plan["base_stamp"]),
                "rows": rows, "theta": theta}

    def _carry_from(self, plan: Dict,
                    fit_res: Optional[Dict]) -> Optional[Dict]:
        if fit_res is None or fit_res.get("state_sub") is None:
            return None
        theta = np.nan_to_num(
            np.asarray(fit_res["state_sub"].theta, np.float32)
        )
        return {"base_stamp": int(plan["plan_stamp"]),
                "rows": np.asarray(plan["changed_rows"], np.int64),
                "theta": theta}

    # -- publish / flip / freshness --------------------------------------------

    def _flip(self, version: int) -> None:
        faults.inject("sched_flip")
        if self.pool is not None:
            self.pool.activate(version, hot_series=self.hot_series,
                               horizons=self.horizons)
        elif self.flip_fn is not None:
            self.flip_fn(int(version))
        elif self.activate:
            self.registry.activate(int(version))

    def _spawn_publisher(self, plan: Dict,
                         fit_res: Optional[Dict]) -> None:
        assert self._pub_thread is None
        self._pub_result = None
        self._inflight = (plan, fit_res)
        state_sub = fit_res["state_sub"] if fit_res else None
        step_sub = fit_res["step_sub"] if fit_res else None

        def _publish_worker():
            t0 = time.time()
            try:
                pub = refit.publish_plan(
                    self.registry, plan, state_sub, step_sub,
                    self.scratch, flip_fn=self._flip, reap=False,
                    horizons=self.horizons,
                )
                self._pub_result = dict(pub, ok=True, plan=plan,
                                        t0=t0, t1=time.time())
            except BaseException as e:  # surfaced at join
                self._pub_result = {"ok": False, "error": e,
                                    "plan": plan, "t0": t0,
                                    "t1": time.time()}

        t = threading.Thread(target=_publish_worker,
                             name="sched-publish", daemon=True)
        self._pub_thread = t
        t.start()

    def _join_publisher(self, block: bool) -> bool:
        """Collect the publisher thread's outcome.  ``block=False``
        returns True while it is still running (nothing to collect
        yet); ``block=True`` waits.  False = the publish failed (the
        plan stays pinned for a resume)."""
        t = self._pub_thread
        if t is None:
            return True
        if not block and t.is_alive():
            return True
        t.join()
        self._pub_thread = None
        res = self._pub_result
        self._pub_result = None
        if res is None:
            return True
        self._busy.append((res["t0"], res["t1"]))
        if not res.get("ok"):
            err = res.get("error")
            obs.event("sched.publish_failed", error=repr(err))
            print(f"[sched] publish failed: {err!r}", file=sys.stderr)
            return False  # _inflight keeps the cycle for the retry
        self._inflight = None
        plan = res["plan"]
        self.cycles += 1
        self._m_cycles.inc()
        self._recent_changed.append(int(plan["n_changed"]))
        self._head_version = int(res["version"])
        self._after_publish(int(res["version"]),
                            int(plan["plan_stamp"]))
        # Reap ONLY the published cycle's dir: the next cycle's
        # prefetched spill may already exist beside it.
        cycle_dir, _d, _o = refit.cycle_paths(self.scratch, plan)
        shutil.rmtree(cycle_dir, ignore_errors=True)
        self._fail_streak = 0
        self._write_sched_state()
        return True

    def _after_publish(self, version: int, stamp: int) -> None:
        """Resolve freshness for every delta the new version covers:
        probe the serving side until a request is served at (or past)
        the version, then stamp land->served for each pending seq."""
        t_served: Optional[float] = None
        probe_src = "flip"
        if self.freshness_probe is not None:
            probe_src = "serve"
            deadline = time.monotonic() + self.probe_timeout_s
            while time.monotonic() < deadline:
                try:
                    served = self.freshness_probe(int(version))
                except Exception:  # broad by design: the probe
                    # invokes caller-supplied serve-side code (engine
                    # forecast, HTTP shim, test stubs) whose failure
                    # surface is unbounded; ANY probe failure means
                    # only "not confirmed yet" and is retried until the
                    # deadline, where the counted probe_failures path
                    # records the episode; probe_errors counts the raw
                    # raising attempts.
                    served = None
                    self.probe_errors += 1
                if served is not None:
                    # A served version going BACKWARDS (below one
                    # already confirmed) is the wrong-version signal
                    # the summary reports; an answer merely from
                    # before this flip settled is retried.
                    if int(served) < self._max_served:
                        self.wrong_version += 1
                    self._max_served = max(self._max_served,
                                           int(served))
                    if int(served) >= int(version):
                        t_served = time.time()
                        break
                time.sleep(0.02)
            if t_served is None:
                self.probe_failures += 1
                return  # seqs stay pending; idle ticks re-probe
        else:
            t_served = time.time()
        for seq in sorted(self._pending):
            if seq > int(stamp):
                continue
            fr = max(0.0, t_served - self._pending.pop(seq))
            self.freshness.append((seq, fr))
            self._m_fresh.set(fr)
            self._m_fresh_hist.observe(fr)
            obs.record("refit.freshness", t_served - fr, fr, seq=seq,
                       version=int(version), probe=probe_src)

    # -- bookkeeping -----------------------------------------------------------

    def _note_deltas(self, frontier: int, plan_stamp: int) -> None:
        from tsspark_tpu.data import plane

        for rec in plane.delta_records(self.data_dir):
            seq = rec["seq"]
            if frontier < seq <= plan_stamp:
                self._pending.setdefault(
                    seq, float(rec.get("unix") or time.time())
                )
            # Gate the patch read on the model's frontier: only NEW
            # seqs need their rows loaded (note_delta would drop an
            # already-seen seq anyway, but only after the zip read).
            if seq > self.model.seen_seq():
                self.model.note_delta(
                    seq, float(rec.get("unix") or time.time()),
                    plane.delta_rows(self.data_dir, seq),
                )

    def _note_failure(self, stage: str) -> None:
        self.failures += 1
        self._fail_streak += 1
        delay = min(self.backoff_base_s * (2 ** (self._fail_streak - 1)),
                    self.backoff_max_s)
        obs.event("sched.backoff", stage=stage,
                  streak=self._fail_streak, delay_s=round(delay, 3))
        print(f"[sched] {stage} failed (streak {self._fail_streak}); "
              f"backing off {delay:.1f}s", file=sys.stderr)
        self._write_sched_state()
        self._stop.wait(delay)

    def _write_sched_state(self, summary: Optional[Dict] = None) -> None:
        state = {
            "unix": round(time.time(), 3),
            "pid": os.getpid(),
            "cycles": self.cycles,
            "resumed_cycles": self.resumed_cycles,
            "failures": self.failures,
            "fail_streak": self._fail_streak,
            "head_version": self._head_version,
            "head_stamp": self._head_stamp,
            "pending_deltas": len(self._pending),
            "freshness": self.freshness_summary(),
            "spec": self.spec_summary(),
            "disk_ladder": current_state(self.scratch),
        }
        if summary is not None:
            state["last_summary"] = {
                k: v for k, v in summary.items() if k != "kind"
            }
        try:
            atomic_write(
                os.path.join(self.scratch, SCHED_STATE_FILE),
                lambda fh: json.dump(state, fh, indent=1), mode="w",
            )
        except StorageError:
            # Advisory observability, never fatal: under an exhausted
            # budget the daemon must keep running its ladder (reap,
            # pause) rather than die writing the file that REPORTS the
            # pressure.
            pass


def read_sched_state(scratch: str) -> Optional[Dict]:
    """The advisory scheduler state, or None (absent/torn)."""
    try:
        with open(os.path.join(scratch, SCHED_STATE_FILE)) as fh:
            d = json.load(fh)
        return d if isinstance(d, dict) else None
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# bench --freshness: the sustained churn stream
# ---------------------------------------------------------------------------

#: Default churn fraction per landed delta (the 1–10% band production
#: late-arriving data lives in).
DEFAULT_FRESHNESS_CHURN = 0.05

#: Deltas per measured stream.
DEFAULT_FRESHNESS_DELTAS = 6

#: Fraction of each delta drawn from a persistent hot pool (real
#: late-arriving data is cadenced — the same stores report daily — and
#: the hot bias is what gives the arrival model a learnable signal; the
#: rest stays uniform so mispredictions exist to discard).
HOT_BIAS = 0.7


def _hot_biased_rows(rng, n: int, k: int,
                     hot_pool: np.ndarray) -> np.ndarray:
    k = max(1, min(int(k), n))
    n_hot = min(int(round(HOT_BIAS * k)), len(hot_pool))
    hot = rng.choice(hot_pool, size=n_hot, replace=False) \
        if n_hot else np.empty(0, np.int64)
    rest = np.setdiff1d(np.arange(n, dtype=np.int64), hot,
                        assume_unique=False)
    cold = rng.choice(rest, size=max(0, k - n_hot), replace=False)
    return np.unique(np.concatenate([hot, cold]).astype(np.int64))


def _write_freshness_report(rep: Dict) -> str:
    path = (f"BENCH_freshness_{rep['rung']}_{rep['mode']}"
            f"_{int(rep['unix'])}.json")
    atomic_write(path, lambda fh: json.dump(rep, fh, indent=1),
                 mode="w")
    return path


def _freshness_report(rung, mode: str, churn: float, n_deltas: int,
                      interval_s: float, cold: Dict, summary: Dict,
                      wrong_version: int, cfg) -> Dict:
    import jax

    from tsspark_tpu.config import NUMERICS_REV
    from tsspark_tpu.obs.history import git_rev
    from tsspark_tpu.utils import checkpoint as ckpt

    fresh = summary["freshness"]
    cold_wall = float(cold["fit_s"]) + float(cold["publish_s"])
    p95 = fresh.get("p95_s")
    spec = summary["spec"]
    return {
        "kind": "freshness-bench",
        "unix": round(time.time(), 3),
        "trace_id": obs.trace_id(),
        "numerics_rev": NUMERICS_REV,
        "git_rev": git_rev(),
        "config_fingerprint": ckpt.config_fingerprint(cfg),
        "device": str(jax.devices()[0]),
        "rung": rung.name,
        "series": rung.series,
        "timesteps": rung.timesteps,
        "mode": mode,
        "churn": churn,
        "deltas": n_deltas,
        "interval_s": round(interval_s, 3),
        "complete": bool(fresh["n"] >= n_deltas
                         and summary["failures"] == 0),
        "cold_fit_s": round(float(cold["fit_s"]), 3),
        "cold_publish_s": round(float(cold["publish_s"]), 3),
        "cold_wall_s": round(cold_wall, 3),
        "cold_reused": bool(cold.get("reused")),
        "freshness_n": fresh["n"],
        "freshness_p50_s": fresh["p50_s"],
        "freshness_p95_s": p95,
        "freshness_mean_s": fresh["mean_s"],
        "freshness_max_s": fresh["max_s"],
        "freshness_vs_cold_frac": (round(p95 / cold_wall, 4)
                                   if p95 is not None and cold_wall
                                   else None),
        "cycle_overhead_frac": summary["cycle_overhead_frac"],
        "cycles": summary["cycles"],
        "spec_hit_rate": spec["hit_rate"],
        "spec_predicted": spec["predicted"],
        "wrong_version": wrong_version,
        "probe_failures": summary["probe_failures"],
        "wall_s": summary["wall_s"],
    }


def run_freshness_bench(rung="smoke", *,
                        churn: float = DEFAULT_FRESHNESS_CHURN,
                        n_deltas: int = DEFAULT_FRESHNESS_DELTAS,
                        interval_s: Optional[float] = None,
                        modes: Sequence[str] = ("serialized",
                                                "pipelined"),
                        reuse_cold: Optional[str] = None,
                        scratch_root: Optional[str] = None,
                        sentinel: Optional[bool] = None) -> List[Dict]:
    """``bench --freshness``: a sustained churn stream through the
    always-on loop, measuring steady-state data-to-forecast freshness
    (land of a row's ``deltaok_`` sentinel -> first request SERVED from
    a version containing it, probed through a live in-process engine).

    Runs the same stream twice — serialized back-to-back cycles, then
    pipelined — so the report pair shows exactly what the overlap buys
    on p95 freshness.  Both modes share one cold base (the warm-base
    amortization ``--reuse-cold`` gives churn sweeps); the plane lives
    under a private root because deltas mutate landed rows.  One
    ``BENCH_freshness_*`` artifact per mode, each judged by the
    regression sentinel under ``[tool.tsspark.slo.freshness]``."""
    import tempfile

    from tsspark_tpu import bench_scale
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import plane
    from tsspark_tpu.serve.cache import ForecastCache
    from tsspark_tpu.serve.engine import PredictionEngine

    if isinstance(rung, str):
        rung = bench_scale.RUNGS[rung]
    cfg = bench_scale._config()
    solver = SolverConfig(max_iters=rung.max_iters)
    scratch = os.path.join(
        scratch_root or tempfile.gettempdir(),
        f"tsfresh_{rung.name}_{rung.series}x{rung.timesteps}"
        f"_{plane.dataset_fingerprint()}",
    )
    os.makedirs(scratch, exist_ok=True)
    # The freshness bench always amortizes its cold base (internally
    # when no --reuse-cold dir was named): the measurement is the
    # STREAM, the cold fit is only its denominator.
    base_dir = reuse_cold or os.path.join(scratch, "coldbase")
    os.makedirs(base_dir, exist_ok=True)
    prev_run = obs.start_run(os.path.join(scratch, "spans.jsonl"))
    reports: List[Dict] = []
    try:
        spec = plane.DatasetSpec(
            generator="demo_weekly", n_series=rung.series,
            n_timesteps=rung.timesteps, seed=2,
        )
        dset_dir = plane.ensure(spec, root=os.path.join(base_dir,
                                                        "plane"))
        ids = plane.series_ids(spec)
        pool_rng = np.random.default_rng(7)
        hot_pool = np.sort(pool_rng.choice(
            rung.series,
            size=max(2, int(round(2 * churn * rung.series))),
            replace=False,
        )).astype(np.int64)

        p95_by_mode: Dict[str, Optional[float]] = {}
        for mode in modes:
            run_dir = os.path.join(scratch,
                                   f"run_{int(time.time())}_{mode}")
            # Same reaper as the delta bench: the scratch is
            # deliberately persistent (coldbase amortization), so
            # without an age-gated sweep every invocation strands two
            # rung-sized registry trees forever.
            refit._sweep_stale_runs(scratch, keep=run_dir)
            registry, cold, _catchup = refit.prepare_cold_registry(
                rung, cfg, solver, run_dir, dset_dir, ids,
                reuse_cold=base_dir,
            )
            if registry is None:
                print("[freshness] cold fit incomplete; aborting",
                      file=sys.stderr)
                reports.append({"complete": False,
                                "stage": "cold-fit", "mode": mode})
                break
            cold_wall = float(cold["fit_s"]) + float(cold["publish_s"])
            gap = interval_s if interval_s is not None else \
                min(10.0, max(0.3, 0.15 * cold_wall))

            sample, _ = bench_scale._request_mix(rung, ids)
            hot = [str(s) for s in sample[:rung.hot]]
            engine = PredictionEngine(registry, cache=ForecastCache())
            engine.materialize(hot, bench_scale.HORIZONS)
            probe_sid = str(ids[int(hot_pool[0])])

            def flip_fn(v, _e=engine, _r=registry, _h=hot):
                _e.prefetch(v)
                _e.materialize(_h, bench_scale.HORIZONS, version=v)
                _r.activate(v)

            def probe(version, _e=engine, _sid=probe_sid):
                # The scheduler judges the answer (freshness AND the
                # backwards-version wrong_version signal).
                res = _e.forecast([_sid], bench_scale.HORIZONS[0])
                return res.version

            sched = RefitScheduler(
                dset_dir, registry,
                os.path.join(run_dir, "sched"),
                chunk=rung.chunk, solver_config=solver,
                warm_start=True, flip_fn=flip_fn,
                pipeline=(mode == "pipelined"), speculate=True,
                poll_s=0.02, debounce_s=0.05, spec_refresh_s=0.2,
                freshness_probe=probe,
            )
            seq0 = plane.delta_seq(dset_dir)
            target = seq0 + int(n_deltas)

            def _land_stream(_seq0=seq0, _gap=gap):
                rng = np.random.default_rng([11, _seq0])
                k = max(1, int(round(churn * rung.series)))
                for i in range(int(n_deltas)):
                    rows = _hot_biased_rows(rng, rung.series, k,
                                            hot_pool)
                    try:
                        plane.land_synthetic_delta(dset_dir, churn,
                                                   rows=rows)
                    except Exception as e:
                        print(f"[freshness] land failed: {e!r}",
                              file=sys.stderr)
                        return
                    time.sleep(_gap)

            lander = threading.Thread(target=_land_stream,
                                      name="freshness-lander",
                                      daemon=True)
            t_mode0 = time.time()
            lander.start()
            summary = sched.run(
                until_stamp=target,
                duration_s=max(60.0, n_deltas * gap + 20 * cold_wall),
            )
            lander.join(timeout=10.0)
            rep = _freshness_report(rung, mode, churn, int(n_deltas),
                                    gap, cold, summary,
                                    int(summary["wrong_version"]),
                                    cfg)
            rep["stream_wall_s"] = round(time.time() - t_mode0, 3)
            path = _write_freshness_report(rep)
            rep["path"] = path
            p95_by_mode[mode] = rep["freshness_p95_s"]
            print(json.dumps({
                "rung": rung.name, "mode": mode, "churn": churn,
                "deltas": n_deltas,
                "freshness_p50_s": rep["freshness_p50_s"],
                "freshness_p95_s": rep["freshness_p95_s"],
                "freshness_vs_cold_frac":
                    rep["freshness_vs_cold_frac"],
                "cycle_overhead_frac": rep["cycle_overhead_frac"],
                "spec_hit_rate": rep["spec_hit_rate"],
                "wrong_version": rep["wrong_version"],
                "report": path,
            }), flush=True)
            if sentinel is None:
                sentinel_on = (os.environ.get("TSSPARK_SENTINEL", "1")
                               != "0")
            else:
                sentinel_on = sentinel
            if sentinel_on:
                try:
                    from tsspark_tpu.obs import regress

                    verdict = regress.sentinel_report(rep, source=path)
                    if verdict is not None:
                        print(
                            f"[freshness] {regress.summarize(verdict)}",
                            file=sys.stderr,
                        )
                        rep["sentinel_ok"] = verdict["ok"]
                except Exception as e:  # never mask the report
                    print(f"[freshness] sentinel skipped: {e!r}",
                          file=sys.stderr)
            reports.append(rep)
        if len([m for m in p95_by_mode.values()
                if m is not None]) == 2:
            ser, pip = (p95_by_mode.get("serialized"),
                        p95_by_mode.get("pipelined"))
            print(json.dumps({
                "freshness_pipeline_gain":
                    (round(1.0 - pip / ser, 4) if ser else None),
                "serialized_p95_s": ser, "pipelined_p95_s": pip,
            }), flush=True)
        return reports
    finally:
        obs.end_run(prev_run)


# ---------------------------------------------------------------------------
# CLI (python -m tsspark_tpu.sched): the killable daemon
# ---------------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Run the always-on scheduler as its own process — the unit the
    loop-storm chaos class SIGKILLs at every stage.  Adopts the
    spawner's trace; prints ONE JSON summary line at exit."""
    from tsspark_tpu.resident import force_virtual_host_mesh

    force_virtual_host_mesh()
    ap = argparse.ArgumentParser(prog="python -m tsspark_tpu.sched")
    ap.add_argument("--data", help="plane dataset dir")
    ap.add_argument("--registry", help="serve registry root")
    ap.add_argument("--scratch", help="scheduler scratch dir")
    ap.add_argument("--chunk", type=int, default=512)
    ap.add_argument("--max-iters", type=int, default=20)
    ap.add_argument("--poll", type=float, default=0.05)
    ap.add_argument("--debounce", type=float, default=0.1)
    ap.add_argument("--duration", type=float, default=None,
                    help="exit after this many seconds (default: run "
                         "until killed)")
    ap.add_argument("--max-cycles", type=int, default=None)
    ap.add_argument("--until-stamp", type=int, default=None,
                    help="exit once a version covering this delta seq "
                         "has published")
    ap.add_argument("--serialized", action="store_true",
                    help="disable the cycle pipeline (back-to-back "
                         "cycles; the freshness bench's comparison arm)")
    ap.add_argument("--no-speculate", action="store_true",
                    help="disable idle-time speculative warm prep")
    ap.add_argument("--cold", action="store_true",
                    help="disable the warm start")
    ap.add_argument("--no-activate", action="store_true",
                    help="publish without flipping (a pool front owns "
                         "the flip)")
    ap.add_argument("--freshness-bench", default=None, metavar="RUNG",
                    help="run the freshness stream bench at a scale "
                         "rung instead of the daemon")
    ap.add_argument("--reuse-cold", default=None, metavar="DIR")
    args = ap.parse_args(argv)
    obs.adopt_env()
    if args.freshness_bench:
        reports = run_freshness_bench(args.freshness_bench,
                                      reuse_cold=args.reuse_cold)
        return 0 if refit.sweep_ok(reports) else 1
    if not (args.data and args.registry and args.scratch):
        ap.error("--data, --registry and --scratch are required for "
                 "the daemon")
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.serve.registry import ParamRegistry

    registry = ParamRegistry.open(args.registry)
    sched = RefitScheduler(
        args.data, registry, args.scratch, chunk=args.chunk,
        solver_config=SolverConfig(max_iters=args.max_iters),
        warm_start=not args.cold, activate=not args.no_activate,
        poll_s=args.poll, debounce_s=args.debounce,
        pipeline=not args.serialized,
        speculate=not args.no_speculate,
    )
    summary = sched.run(duration_s=args.duration,
                        max_cycles=args.max_cycles,
                        until_stamp=args.until_stamp)
    print(json.dumps(summary), flush=True)
    return 0 if summary.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
