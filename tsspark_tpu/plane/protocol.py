"""THE column-plane protocol library: spec-first, CRC-sentinel-last.

The package grew the same memmap column-plane protocol three times —
the data plane (``data/plane.py``), the snapshot plane
(``serve/snapplane.py``), and the delta patch stream — each with its
own copy of the shard math, the CRC helpers, the JSON probe, and the
spec-first / payload / sentinel-LAST write order.  This module is the
single implementation they all route through, built on the durable-I/O
layer (``tsspark_tpu.io``) so every plane — past and future — inherits
the same fault injection points, typed storage errors, and disk-budget
gate.

The write order is the protocol:

* ``write_spec``     — the identity record, FIRST.  A reader finding a
  spec without its sentinel treats the plane as absent/in-progress.
* ``write_column``   — one atomic ``.npy`` per column (payload).
  Column bytes are invisible until the sentinel certifies them.
* ``write_sentinel`` — the CRC sentinel, LAST: the unit of visibility.
  A reader trusts nothing this sentinel does not cover, so a torn or
  short-written column is rejected at attach, never served.

``publish_plane`` is the one generic writer emitting that order; the
``plane-protocol`` :class:`~tsspark_tpu.analysis.protomodel.ProtocolSpec`
verifies it statically (happens-before writer order + exhaustive
kill-point sweep), so every caller of ``publish_plane`` inherits a
machine-checked crash story.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tsspark_tpu.io import (
    atomic_write,
    attach_array,
    is_missing,
    link_or_copy,
    reraise_classified,
)

__all__ = [
    "shard_ranges", "shard_crcs", "read_json", "write_spec",
    "write_column", "write_sentinel", "publish_plane", "verify_crcs",
    "attach_column", "link_or_copy",
]


def shard_ranges(n: int, shard_rows: int) -> List[Tuple[int, int]]:
    """Row ranges of the CRC shards: ``[lo, hi)`` windows of
    ``shard_rows`` covering ``n`` rows.  Shards bound what one torn
    write can hide behind a stale CRC and give the chaos harness a
    named unit to tear."""
    return [(lo, min(lo + shard_rows, n))
            for lo in range(0, n, shard_rows)]


def shard_crcs(cols: Dict[str, np.ndarray],
               lo: Optional[int] = None,
               hi: Optional[int] = None) -> Dict[str, int]:
    """Per-column CRC32 over rows ``[lo, hi)`` (whole columns when no
    range is given) — the sentinel's payload and the attach-time
    verifier's recomputation, one definition for both sides."""
    if lo is None:
        return {k: zlib.crc32(np.ascontiguousarray(a).tobytes())
                for k, a in cols.items()}
    return {k: zlib.crc32(np.ascontiguousarray(a[lo:hi]).tobytes())
            for k, a in cols.items()}


def read_json(path: str) -> Optional[Dict]:
    """Probe a JSON protocol record: a dict, or None when the file is
    absent or torn (protocol-normal).  A real disk failure (EIO, EROFS)
    raises its typed storage error instead of reading as absence — the
    narrow-except discipline of the storage fault domain."""
    try:
        with open(path) as fh:
            d = json.load(fh)
        return d if isinstance(d, dict) else None
    except ValueError:
        return None  # torn/partial JSON: never landed, reads as absent
    except OSError as e:
        if is_missing(e):
            return None
        reraise_classified(e)


def write_spec(path: str, spec: Dict) -> None:
    """Land a plane's identity record (step 1: spec FIRST)."""
    atomic_write(path, lambda fh: json.dump(spec, fh, indent=1),
                 mode="w")


def write_column(path: str, arr: np.ndarray, *,
                 lo: Optional[int] = None,
                 hi: Optional[int] = None) -> None:
    """Land one column payload atomically (step 2).  ``lo``/``hi``
    scope series-targeted fault rules to the rows this column carries."""
    atomic_write(path, lambda fh: np.save(fh, arr), lo=lo, hi=hi)


def write_sentinel(path: str, sentinel: Dict) -> None:
    """Land the CRC sentinel (step 3: the gate, LAST — its presence is
    the unit of visibility for everything it certifies)."""
    atomic_write(path, lambda fh: json.dump(sentinel, fh), mode="w")


def publish_plane(dirpath: str, spec_name: str, spec: Dict,
                  columns: Dict[str, np.ndarray],
                  col_path: Callable[[str, str], str],
                  sentinel_name: str, sentinel: Dict) -> None:
    """The generic plane publish: spec first, every column payload,
    CRC sentinel LAST.  The ``plane-protocol`` ProtocolSpec statically
    verifies this writer's order and kill-points — a crash after any
    prefix leaves the plane invisible (no sentinel) or complete."""
    write_spec(os.path.join(dirpath, spec_name), spec)
    for name, arr in columns.items():
        write_column(col_path(dirpath, name), arr)
    write_sentinel(os.path.join(dirpath, sentinel_name), sentinel)


def verify_crcs(cols: Dict[str, np.ndarray],
                shards) -> Optional[Tuple[str, int, int]]:
    """Recompute every shard CRC against the sentinel's records.
    Returns None when all match, else ``(column, lo, hi)`` of the first
    mismatch — a torn, short-written, or silently corrupted column."""
    for entry in shards or ():
        lo, hi, crcs = int(entry[0]), int(entry[1]), entry[2]
        got = shard_crcs(cols, lo, hi)
        for name, want in crcs.items():
            if got.get(name) != int(want):
                return (name, lo, hi)
    return None


def attach_column(path: str):
    """Attach one column as a read-only memmap (via the durable-I/O
    layer's ``io_mmap`` fault point)."""
    return attach_array(path, mmap_mode="r")
