"""``tsspark_tpu.plane`` — the unified column-plane protocol library.

One implementation of the spec-first / CRC-sentinel-last memmap plane
protocol, extracted from its three historical copies (``data/plane.py``,
``serve/snapplane.py``, the delta patch stream) and built on the
durable-I/O layer (``tsspark_tpu.io``).  See ``plane.protocol`` and
docs/ANALYSIS.md § unified ProtocolSpec.
"""

from tsspark_tpu.plane.protocol import (
    attach_column,
    link_or_copy,
    publish_plane,
    read_json,
    shard_crcs,
    shard_ranges,
    verify_crcs,
    write_column,
    write_sentinel,
    write_spec,
)

__all__ = [
    "attach_column", "link_or_copy", "publish_plane", "read_json",
    "shard_crcs", "shard_ranges", "verify_crcs", "write_column",
    "write_sentinel", "write_spec",
]
