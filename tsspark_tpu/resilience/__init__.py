"""Resilience subsystem: unified retry policy, deterministic fault
injection, checkpoint integrity, and quarantine reporting.

The paper's pitch is Spark-matching scale; at millions of series,
single-series failures, flaky accelerator tunnels, and torn checkpoint
files are the steady state, not the exception.  This package is the one
place that behavior is defined:

  policy.py    — ``RetryPolicy``: max attempts, exponential backoff +
                 deterministic jitter, per-attempt deadlines, total
                 budget.  Replaces the ad-hoc sleep/retry constants that
                 used to be scattered through ``orchestrate.py`` and the
                 streaming poll loops.  ``CircuitBreaker``: closed/open/
                 half-open failure gate that remembers failures ACROSS
                 calls, so a dead dependency is shed fast instead of
                 retried to every caller's deadline (wired into the
                 streaming poll, the serve engine's dispatch, and its
                 registry polling).
  faults.py    — ``FaultPlan`` / ``inject``: env-driven, deterministic
                 fault injection at named points (worker spawn, device
                 probe, chunk save, chunk fit, streaming poll), so every
                 recovery path is unit-testable on CPU without a real
                 TPU failure.
  integrity.py — CRC32 payload checksums in every chunk/prep npz;
                 corrupt or torn files are quarantined (``*.corrupt``)
                 and their ranges re-queued instead of crashing or
                 silently loading garbage.
  report.py    — ``ResilienceReport`` attached to the ``FitState`` a
                 resilient fit returns: quarantined series + reasons,
                 integrity quarantines, CPU degradation, warnings.

See ``docs/RESILIENCE.md`` for the operator-facing walkthrough.
"""

from tsspark_tpu.resilience.faults import FaultInjected, FaultPlan, inject
from tsspark_tpu.resilience.integrity import ChunkIntegrityError
from tsspark_tpu.resilience.policy import (
    CircuitBreaker,
    CircuitOpen,
    RetryPolicy,
)
from tsspark_tpu.resilience.report import (
    QuarantineRecord,
    ResilienceReport,
    ResilienceWarning,
    STATUS_QUARANTINED,
    attach_report,
    get_report,
)

__all__ = [
    "ChunkIntegrityError",
    "CircuitBreaker",
    "CircuitOpen",
    "FaultInjected",
    "FaultPlan",
    "QuarantineRecord",
    "ResilienceReport",
    "ResilienceWarning",
    "RetryPolicy",
    "STATUS_QUARANTINED",
    "attach_report",
    "get_report",
    "inject",
]
