"""Deterministic fault injection: make every recovery path testable on CPU.

The orchestrator's retry/quarantine/integrity machinery exists for
failures (worker OOM, wedged tunnel, torn chunk file, poison series)
that cannot be provoked on demand without real hardware faults.  This
harness plants named injection points on those paths; a ``FaultPlan``
arms some of them, and the plan travels through the environment
(``TSSPARK_FAULTS``) so the orchestrator's CHILD PROCESSES see the same
plan the test armed in the parent.

Determinism & cross-process accounting: each armed rule carries a fixed
call window (``after`` skipped calls, then ``attempts`` firings).  Call
slots are claimed by atomic ``O_CREAT|O_EXCL`` file creation under the
plan's ``state_dir``, so the N-th matching call fires the same way no
matter which process makes it, and a respawned worker does not reset the
count — exactly how a real flaky environment behaves.

Named points currently wired (see docs/RESILIENCE.md):

  worker_spawn      parent, before launching a child       (flag/raise)
  device_probe      tunnel_preflight                       (flag)
  fit_worker_start  child entry                            (exit/raise)
  fit_chunk         child, before a chunk's fit dispatch   (exit/raise)
  fit_worker_chunk  child, after a chunk save              (exit/raise)
  chunk_save        after save_chunk_atomic's rename       (corrupt)
  prep_save         after save_prep_atomic's rename        (corrupt)
  backend_fit       TpuBackend.fit entry                   (raise)
  stream_poll       streaming source poll                  (raise)
  io_write          tsspark_tpu.io payload write           (enospc/eio/
                                                            shortwrite/sleep)
  io_rename         tsspark_tpu.io publish rename          (enospc/eio)
  io_fsync          tsspark_tpu.io durability barrier      (lost_fsync/eio)
  io_link           tsspark_tpu.io hardlink copy-forward   (enospc/eio)
  io_mmap           tsspark_tpu.io memmap attach           (eio/sleep)

Storage modes (the disk misbehaving, not the process):

  "enospc"/"eio"  — ``inject`` raises ``OSError`` with the real errno so
                    the site's error classification is exercised, not a
                    lookalike exception.
  "shortwrite"    — ``short_write`` returns a fraction; the durable-I/O
                    layer truncates the payload it just wrote to that
                    fraction and then REPORTS SUCCESS, the way an
                    unchecked ``write(2)`` return tears a file.  The
                    CRC-sentinel protocol must catch it at read time.
  "lost_fsync"    — ``lost_fsync`` snapshots the target's PRE-write
                    state; the write proceeds and the caller sees
                    success, but the next ``exit``-mode firing in the
                    same plan rolls the file back before dying — the
                    rename lived in the page cache and the crash lost
                    it.  Replay rides the same deterministic
                    call-window machinery as every other rule.

Rules may carry ``path=<substring>`` to scope a storage rule to one
artifact family (e.g. ``path="manifest.json"`` fires only on registry
manifest renames) — the io layer passes every call's target path.

Production safety: with ``TSSPARK_FAULTS`` unset, ``inject`` is a single
dict lookup returning immediately.
"""

from __future__ import annotations

import errno as _errno
import json
import os
import shutil
import tempfile
import time
from typing import Dict, List, Optional

ENV_VAR = "TSSPARK_FAULTS"

_MODES = ("raise", "exit", "flag", "corrupt", "sleep",
          "enospc", "eio", "shortwrite", "lost_fsync")

# Modes that never fire from the generic ``inject`` gate: each has a
# dedicated hook (``corrupt_file``, ``short_write``, ``lost_fsync``)
# because firing needs the artifact path, not just the point name.
_HOOK_MODES = ("corrupt", "shortwrite", "lost_fsync")

# Subdirectory of the plan's state_dir holding lost-fsync rollback
# records (pre-write snapshots awaiting replay at the next kill point).
_LOST_DIR = "lostfsync"

# Guard against a runaway call counter chewing the state dir: no test
# plan legitimately sees this many calls at one point.
_MAX_CALLS = 100_000


class FaultInjected(RuntimeError):
    """Raised by an armed ``raise``-mode injection point."""

    def __init__(self, point: str, rule_id: str):
        super().__init__(
            f"fault injected at {point!r} (rule {rule_id}); this error is "
            f"deliberate — a FaultPlan armed this point"
        )
        self.point = point
        self.rule_id = rule_id


class FaultPlan:
    """A seeded, serializable set of armed failure rules.

    Usage (tests)::

        plan = (FaultPlan(state_dir=tmp)
                .fail("fit_worker_chunk", after=1, attempts=2, mode="exit")
                .fail("chunk_save", series=40, mode="corrupt"))
        monkeypatch.setenv(faults.ENV_VAR, plan.to_env())

    ``fail(point, ...)``:
      attempts — how many matching calls fire (after the skip window).
      after    — matching calls to let through before firing (e.g. "kill
                 the worker after it lands 2 chunks").
      mode     — "raise" (FaultInjected), "exit" (``os._exit(rc)``,
                 simulates a worker death), "flag" (``inject`` returns
                 True; the site fails soft, e.g. a probe returning
                 False), "corrupt" (``corrupt_file`` flips bytes in the
                 file the site just wrote), "sleep" (``inject`` stalls
                 ``delay_s`` seconds, then lets the call proceed — a
                 slow-I/O / slow-dependency simulation, not a failure).
      series   — only fire when the call's ``(lo, hi)`` context covers
                 this series index (how a poison SERIES is simulated:
                 the chunk containing it dies wherever it lands).
      rc       — exit code for "exit" mode.
      delay_s  — stall duration for "sleep" mode.
    """

    def __init__(self, state_dir: Optional[str] = None):
        self.state_dir = state_dir or tempfile.mkdtemp(
            prefix="tsspark_faults_"
        )
        self.rules: List[dict] = []

    def fail(self, point: str, *, attempts: int = 1, after: int = 0,
             mode: str = "raise", series: Optional[int] = None,
             rc: int = 23, delay_s: float = 0.5,
             tag: Optional[str] = None, path: Optional[str] = None,
             fraction: float = 0.5) -> "FaultPlan":
        """``tag``: free-form class label stamped onto the observability
        event a firing emits (the chaos storm tags rules with their
        fault class so MTTR is readable off the span ledger).
        ``path``: substring scope — the rule only matches calls whose
        target path contains it (storage rules aim at one artifact
        family this way).  ``fraction``: surviving fraction of the
        payload for ``shortwrite`` mode."""
        if mode not in _MODES:
            raise ValueError(f"mode {mode!r} not in {_MODES}")
        if attempts < 1 or after < 0:
            raise ValueError("attempts must be >= 1 and after >= 0")
        if not (0.0 <= fraction < 1.0):
            raise ValueError("fraction must be in [0, 1)")
        self.rules.append({
            "id": f"r{len(self.rules)}_{point}",
            "point": point, "attempts": int(attempts), "after": int(after),
            "mode": mode, "series": series, "rc": int(rc),
            "delay_s": float(delay_s), "tag": tag, "path": path,
            "fraction": float(fraction),
        })
        return self

    def to_env(self) -> str:
        os.makedirs(self.state_dir, exist_ok=True)
        return json.dumps({"state_dir": self.state_dir, "rules": self.rules})

    def install(self, env: Optional[Dict[str, str]] = None) -> None:
        """Arm the plan for this process tree (``os.environ`` default)."""
        (os.environ if env is None else env)[ENV_VAR] = self.to_env()

    @classmethod
    def from_env(cls, spec: str) -> "FaultPlan":
        d = json.loads(spec)
        plan = cls(state_dir=d["state_dir"])
        plan.rules = list(d["rules"])
        return plan


_plan_cache: Dict[str, Optional[FaultPlan]] = {}


def _active_plan() -> Optional[FaultPlan]:
    spec = os.environ.get(ENV_VAR)
    if not spec:
        return None
    plan = _plan_cache.get(spec)
    if plan is None:
        try:
            plan = FaultPlan.from_env(spec)
        except (ValueError, KeyError, TypeError):
            plan = None  # malformed spec: fail open, never break prod
        _plan_cache[spec] = plan
    return plan


def _matches(rule: dict, lo: Optional[int], hi: Optional[int]) -> bool:
    s = rule.get("series")
    if s is None:
        return True
    if lo is None:
        return True  # series-targeted rule at a context-free call site
    return lo <= s < (hi if hi is not None else lo + 1)


def _matches_path(rule: dict, path: Optional[str]) -> bool:
    scope = rule.get("path")
    if scope is None:
        return True
    if path is None:
        return False  # path-scoped rule at a pathless call site
    return scope in os.path.abspath(path)


def _claim_call(state_dir: str, rule: dict) -> Optional[int]:
    """Atomically claim this call's global 0-based sequence number for
    ``rule`` (cross-process: first O_CREAT|O_EXCL success wins a slot)."""
    for n in range(_MAX_CALLS):
        path = os.path.join(state_dir, f"{rule['id']}.{n}")
        try:
            os.close(os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
            return n
        except FileExistsError:
            continue
        except OSError:
            return None  # unwritable state dir: fail open
    return None


def _armed_call(rule: dict, state_dir: str,
                lo: Optional[int], hi: Optional[int]) -> bool:
    """True when this call falls inside the rule's firing window."""
    if not _matches(rule, lo, hi):
        return False
    n = _claim_call(state_dir, rule)
    if n is None:
        return False
    return rule["after"] <= n < rule["after"] + rule["attempts"]


def _obs_fault(rule: dict, point: str,
               lo: Optional[int], hi: Optional[int],
               path: Optional[str] = None) -> None:
    """Span-ledger annotation for one firing: the moment a fault was
    injected becomes readable off the trace (MTTR from spans), not just
    off the claim files' mtimes.  Best-effort; never breaks the site."""
    try:
        from tsspark_tpu.obs import context as obs

        attrs = {"point": point, "rule": rule["id"],
                 "mode": rule["mode"], "tag": rule.get("tag")}
        if lo is not None:
            attrs["lo"], attrs["hi"] = lo, hi
        if path is not None:
            attrs["path"] = os.path.basename(path)
        obs.event("fault", **attrs)
    except Exception:
        pass


def _count_fault_metric(point: str, mode: str) -> None:
    """Best-effort ``io.*`` accounting of fired faults (chaos reports
    and RUNHISTORY read these)."""
    try:
        from tsspark_tpu.obs.metrics import DEFAULT as METRICS

        METRICS.counter("tsspark_io_faults_fired_total").inc()
        METRICS.counter(f"tsspark_io_fault_{mode}_total").inc()
    except Exception:
        pass


def inject(point: str, *, lo: Optional[int] = None,
           hi: Optional[int] = None,
           path: Optional[str] = None) -> bool:
    """Fault injection point.  No-op (False) unless a plan arms ``point``.

    ``lo``/``hi``: the series range this call is operating on, matched
    against series-targeted rules.  ``path``: the artifact path the call
    targets (io-layer sites pass it; path-scoped rules need it to
    match).  Returns True when a "flag"-mode rule fires (the caller
    fails soft); "raise" raises ``FaultInjected``; "exit" kills the
    process like a real worker death; "enospc"/"eio" raise ``OSError``
    with the real errno so the site's disk-failure classification runs.
    """
    plan = _active_plan()
    if plan is None:
        return False
    flagged = False
    for rule in plan.rules:
        if rule["point"] != point or rule["mode"] in _HOOK_MODES:
            continue
        if not _matches_path(rule, path):
            continue
        if not _armed_call(rule, plan.state_dir, lo, hi):
            continue
        _obs_fault(rule, point, lo, hi, path)
        _count_fault_metric(point, rule["mode"])
        if rule["mode"] == "exit":
            # A kill point is where un-fsynced renames die with the
            # process: replay any recorded lost-fsync rollbacks first so
            # the survivor observes the pre-crash on-disk truth.
            _replay_lost_fsyncs(plan.state_dir)
            os._exit(rule["rc"])
        if rule["mode"] == "raise":
            raise FaultInjected(point, rule["id"])
        if rule["mode"] == "enospc":
            raise OSError(
                _errno.ENOSPC,
                f"injected ENOSPC at {point!r} (rule {rule['id']}); "
                f"deliberate — a FaultPlan armed this point",
            )
        if rule["mode"] == "eio":
            raise OSError(
                _errno.EIO,
                f"injected EIO at {point!r} (rule {rule['id']}); "
                f"deliberate — a FaultPlan armed this point",
            )
        if rule["mode"] == "sleep":
            # A stall, not a failure: the call proceeds after the delay
            # (and the site is NOT flagged), so the only observable
            # effect is latency — exactly what slow media/IO looks like.
            time.sleep(float(rule.get("delay_s", 0.5)))
            continue
        flagged = True
    return flagged


def corrupt_file(point: str, path: str, *, lo: Optional[int] = None,
                 hi: Optional[int] = None) -> bool:
    """Corruption injection point: when a "corrupt"-mode rule at
    ``point`` fires, flip bytes in the middle of ``path`` (simulating
    silent media corruption of a just-written checkpoint).  Returns True
    when corruption was applied."""
    plan = _active_plan()
    if plan is None:
        return False
    hit = False
    for rule in plan.rules:
        if rule["point"] != point or rule["mode"] != "corrupt":
            continue
        if not _matches_path(rule, path):
            continue
        if not _armed_call(rule, plan.state_dir, lo, hi):
            continue
        _obs_fault(rule, point, lo, hi, path)
        try:
            size = os.path.getsize(path)
            with open(path, "r+b") as fh:
                # Flip 16 bytes at several offsets spread across the
                # file: a single mid-file flip can land entirely inside
                # npz/zip alignment padding that no loader ever parses,
                # which would make the "corruption" silently benign.
                for k in range(1, 8):
                    off = size * k // 8
                    fh.seek(off)
                    chunk = fh.read(16)
                    fh.seek(off)
                    fh.write(bytes(b ^ 0xFF for b in chunk))
            hit = True
        except OSError:
            pass
    return hit


def short_write(point: str, path: str, *, lo: Optional[int] = None,
                hi: Optional[int] = None) -> Optional[float]:
    """Short-write injection point: when a "shortwrite"-mode rule at
    ``point`` fires, return the fraction of the payload that should
    survive.  The durable-I/O layer truncates the temp it just filled to
    that fraction and then completes the publish normally — the torn
    artifact lands in place exactly as an unchecked ``write(2)`` return
    would leave it, and only the CRC-sentinel read path can catch it.
    Returns None when nothing fired."""
    plan = _active_plan()
    if plan is None:
        return None
    for rule in plan.rules:
        if rule["point"] != point or rule["mode"] != "shortwrite":
            continue
        if not _matches_path(rule, path):
            continue
        if not _armed_call(rule, plan.state_dir, lo, hi):
            continue
        _obs_fault(rule, point, lo, hi, path)
        _count_fault_metric(point, "shortwrite")
        return float(rule.get("fraction", 0.5))
    return None


def lost_fsync(point: str, path: str, *, lo: Optional[int] = None,
               hi: Optional[int] = None) -> bool:
    """Lost-fsync injection point, called by the durable-I/O layer just
    BEFORE it renames a finished temp over ``path``.  When a
    "lost_fsync"-mode rule fires, the target's current (pre-write) state
    — its bytes, or the fact it did not exist — is snapshotted into the
    plan's state dir.  The write then proceeds and the caller sees
    success; the snapshot is replayed (file rolled back) by the next
    ``exit``-mode firing in the same plan, before ``os._exit``.  That is
    the real failure being modeled: the rename was only in the page
    cache, and the crash lost it while the process kept running as if it
    were durable.  Returns True when a snapshot was recorded."""
    plan = _active_plan()
    if plan is None:
        return False
    hit = False
    for rule in plan.rules:
        if rule["point"] != point or rule["mode"] != "lost_fsync":
            continue
        if not _matches_path(rule, path):
            continue
        if not _armed_call(rule, plan.state_dir, lo, hi):
            continue
        _obs_fault(rule, point, lo, hi, path)
        _count_fault_metric(point, "lost_fsync")
        try:
            _record_lost_fsync(plan.state_dir, path)
            hit = True
        except OSError:
            pass  # unwritable state dir: fail open, like _claim_call
    return hit


def _record_lost_fsync(state_dir: str, path: str) -> None:
    """Snapshot ``path``'s pre-write state for later rollback.  Slot
    allocation reuses the O_CREAT|O_EXCL idiom so concurrent processes
    recording at once never clobber each other's record."""
    d = os.path.join(state_dir, _LOST_DIR)
    os.makedirs(d, exist_ok=True)
    target = os.path.abspath(path)
    for n in range(_MAX_CALLS):
        rec_path = os.path.join(d, f"rec.{n}.json")
        try:
            fd = os.open(rec_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            continue
        existed = os.path.exists(target)
        if existed:
            shutil.copy2(target, rec_path + ".bak")
        rec = {"path": target, "existed": existed}
        try:
            os.write(fd, json.dumps(rec).encode())
        finally:
            os.close(fd)
        return
    raise OSError("lost-fsync record slots exhausted")


def _replay_lost_fsyncs(state_dir: str) -> int:
    """Roll back every recorded-but-unreplayed lost fsync: restore the
    pre-write bytes (or remove the file that 'never landed').  Each
    record is consumed by renaming it to ``.done`` first — the claim is
    atomic, so two kill points racing the replay apply it once.  Returns
    the number of files rolled back."""
    d = os.path.join(state_dir, _LOST_DIR)
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return 0
    replayed = 0
    for name in names:
        if not (name.startswith("rec.") and name.endswith(".json")):
            continue
        rec_path = os.path.join(d, name)
        done_path = rec_path + ".done"
        try:
            os.rename(rec_path, done_path)
        except OSError:
            continue  # another process claimed this record
        try:
            with open(done_path) as fh:
                rec = json.load(fh)
            if rec.get("existed"):
                shutil.copy2(rec_path + ".bak", rec["path"])
            else:
                try:
                    os.remove(rec["path"])
                except FileNotFoundError:
                    pass
            replayed += 1
        except (OSError, ValueError, KeyError):
            continue  # torn record: skip, never break the kill path
    return replayed
