"""Resilience reporting: what a resilient fit survived, on the FitState.

A million-series resilient fit can complete while still having a story
to tell: series quarantined as poison, chunk files quarantined as
corrupt, a degradation to the CPU backend, semantic-switch warnings from
the resilient gate.  That story rides the returned ``FitState`` as a
``.resilience`` attribute (``get_report``/``attach_report``) — a plain
subclass trick: the annotated state IS a ``FitState`` (same tuple, same
pytree behavior), and the attribute is best-effort metadata that later
``jax.tree`` transformations are free to drop.

``STATUS_QUARANTINED`` extends the solver's per-series termination codes
(ops/lbfgs.STATUS_*, 0-4): a quarantined series carries NaN parameters,
``converged=False``, and this status, so downstream consumers can mask
it without parsing the report.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

#: Per-series status code for quarantined rows.  Deliberately far from
#: the solver's own 0-4 range (ops/lbfgs.STATUS_*): it marks a series
#: the solver never (successfully) ran on.
STATUS_QUARANTINED = 100


class ResilienceWarning(UserWarning):
    """Loud-but-nonfatal resilience events: CPU degradation, the
    resilient gate overriding rescue/length_buckets semantics."""


@dataclasses.dataclass(frozen=True)
class QuarantineRecord:
    """One quarantined series: its batch row index and why."""

    index: int
    reason: str


@dataclasses.dataclass(frozen=True)
class ResilienceReport:
    """What a resilient fit survived (attached via ``attach_report``)."""

    quarantined: Tuple[QuarantineRecord, ...] = ()
    corrupt_chunks: Tuple[Tuple[int, int], ...] = ()
    warnings: Tuple[str, ...] = ()
    degraded_to_cpu: bool = False
    retries: int = 0

    def with_warning(self, msg: str) -> "ResilienceReport":
        return dataclasses.replace(self, warnings=self.warnings + (msg,))

    @property
    def quarantined_indices(self) -> Tuple[int, ...]:
        return tuple(r.index for r in self.quarantined)


def annotate_state(state, attr: str, value):
    """Return ``state`` annotated with ``value`` as attribute ``attr``.

    The result is a dynamically-derived instance of ``type(state)`` —
    field-for-field the same tuple (NamedTuple subclasses stay valid
    pytrees and keep ``_replace``/``_fields``), plus the attribute.
    Tree transformations rebuild the base type and drop the attribute;
    callers who need it keep the original reference.

    The annotation machinery is shared: the resilience report rides as
    ``.resilience`` (``attach_report``) and the perf telemetry as
    ``.perf`` (``tsspark_tpu.perf.attach_perf``) on the SAME generated
    class, so attaching one never drops the other.
    """
    # Re-annotating an annotated state (add_warning on a fit_resilient
    # result, attach_perf on an annotated state) must reuse the SAME
    # generated class, never subclass it again — hence the
    # _resilience_base marker.
    base = getattr(type(state), "_resilience_base", type(state))
    annotated_cls = _annotated_types.get(base)
    if annotated_cls is None:
        annotated_cls = type(base.__name__, (base,), {
            "_resilience_base": base,
            # The generated class is not an importable module attribute,
            # so pickle must rebuild the BASE type (a Spark transfer or
            # multiprocessing queue of the state keeps working; the
            # report, like under jax.tree transforms, is dropped).
            "__reduce__": lambda self: (
                type(self)._resilience_base, tuple(self)
            ),
        })
        _annotated_types[base] = annotated_cls
    out = annotated_cls(*state)
    # Carry annotations already riding ``state`` forward so attaching a
    # second kind (perf after resilience, or vice versa) composes.
    for k, v in vars(state).items() if hasattr(state, "__dict__") else ():
        setattr(out, k, v)
    setattr(out, attr, value)
    return out


def attach_report(state, report: ResilienceReport):
    """Return ``state`` annotated with ``report`` (see annotate_state)."""
    return annotate_state(state, "resilience", report)


_annotated_types: dict = {}


def get_report(state) -> Optional[ResilienceReport]:
    """The ``ResilienceReport`` attached to ``state``, or None."""
    return getattr(state, "resilience", None)


def add_warning(state, msg: str):
    """Annotate ``state`` with one more warning (creating or extending
    its report)."""
    report = get_report(state) or ResilienceReport()
    return attach_report(state, report.with_warning(msg))
