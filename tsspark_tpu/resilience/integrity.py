"""Checkpoint integrity: CRC32 payload checksums + quarantine.

Chunk and prep files are written atomically (dotfile + rename), which
protects against a reader seeing a half-written file — but not against
silent media corruption, a torn write surviving a power loss, or a stale
tool rewriting a payload.  Every npz now carries a CRC32 of its payload
bytes (``integrity_crc``, computed over name/dtype/shape/bytes of every
array); loaders verify it and QUARANTINE failures — the file is renamed
``*.corrupt`` (kept for forensics, invisible to the resume globs) so its
range reappears in ``missing_ranges`` and is re-fit, instead of the run
crashing or silently assembling garbage into a million-series result.

Verification treats "unreadable" (torn zip, truncated file) and "reads
but mismatches" identically: both quarantine.  Files written by older
versions (no ``integrity_crc`` entry) pass — np.load's zip CRCs already
vouch for their payload bytes.
"""

from __future__ import annotations

import glob
import os
import zlib
from typing import Dict, List, Optional, Tuple

INTEGRITY_KEY = "integrity_crc"


class ChunkIntegrityError(RuntimeError):
    """Corrupt/torn chunk files were found and quarantined; the caller
    should re-queue the attached ranges (they are now missing)."""

    def __init__(self, out_dir: str, ranges: List[Tuple[int, int]]):
        super().__init__(
            f"{len(ranges)} corrupt chunk file(s) quarantined in "
            f"{out_dir}: {ranges} — ranges re-queued for refit"
        )
        self.out_dir = out_dir
        self.ranges = ranges


def payload_crc(arrays: Dict) -> int:
    """CRC32 over every array's name, dtype, shape, and raw bytes, in
    name-sorted order (dict insertion order must not matter)."""
    import numpy as np

    crc = 0
    for name in sorted(arrays):
        if name == INTEGRITY_KEY:
            continue
        a = np.ascontiguousarray(np.asarray(arrays[name]))
        for token in (name, str(a.dtype), str(a.shape)):
            crc = zlib.crc32(token.encode(), crc)
        crc = zlib.crc32(a.tobytes(), crc)
    return crc & 0xFFFFFFFF


def stamp(arrays: Dict) -> Dict:
    """Return ``arrays`` plus its ``integrity_crc`` entry (uint32)."""
    import numpy as np

    out = dict(arrays)
    out[INTEGRITY_KEY] = np.uint32(payload_crc(arrays))
    return out


def verify_arrays(z) -> bool:
    """Verify a loaded npz (or dict of arrays) against its stamp.
    Unstamped (legacy) payloads pass."""
    import numpy as np

    try:
        keys = list(getattr(z, "files", None) or z.keys())
        # Read the FULL payload before deciding anything: corruption can
        # mangle the zip central directory so the stamp entry vanishes
        # from the key list — an unstamped-looking file only passes as
        # "legacy" if every array in it is actually readable.
        arrays = {k: z[k] for k in keys if k != INTEGRITY_KEY}
        if INTEGRITY_KEY not in keys:
            return True
        return int(np.asarray(z[INTEGRITY_KEY])) == payload_crc(arrays)
    except Exception:
        return False  # a payload that cannot even be read is corrupt


def verify_file(path: str) -> bool:
    """True when ``path`` loads cleanly and matches its stamp."""
    import numpy as np

    try:
        with np.load(path) as z:
            return verify_arrays(z)
    except Exception:
        return False  # torn/truncated/garbage file


def quarantine(path: str) -> str:
    """Rename a corrupt file out of the resume globs (kept for
    forensics); returns the new path."""
    dest = path + ".corrupt"
    # A repeat offender at the same range overwrites the previous
    # quarantined copy — the latest evidence is the interesting one.
    os.replace(path, dest)
    return dest


def sweep_chunks(out_dir: str, pattern: str = "chunk_*.npz"
                 ) -> List[Tuple[int, int]]:
    """Verify every chunk file in ``out_dir``; quarantine failures.

    Returns the (lo, hi) ranges quarantined — each is now missing from
    coverage and will be re-fit by the normal retry machinery.  Called
    at fit-worker start (so a resume never trusts a corrupt chunk) and
    before final assembly in ``load_fit_state``.
    """
    bad: List[Tuple[int, int]] = []
    for path in sorted(glob.glob(os.path.join(out_dir, pattern))):
        if verify_file(path):
            continue
        quarantine(path)
        base = os.path.basename(path)
        stem = base[base.index("_") + 1:-len(".npz")]
        try:
            lo_s, hi_s = stem.split("_")
            bad.append((int(lo_s), int(hi_s)))
        except ValueError:
            continue  # foreign file name matched the glob; just renamed
    return sorted(bad)
