"""Unified retry policy: one dataclass for every retry loop in the repo.

Before this module, retry behavior lived as hard-coded constants spread
across ``orchestrate.py`` (fixed 10 s post-crash sleeps, a fixed
fruitless-retry cap of 8, the 5 s -> x1.5 -> 30 s probe backoff, the
30 + 15*consec <= 90 s probe-patience escalation) and the streaming
driver's poll loop.  ``RetryPolicy`` expresses all of those as data, so
call sites accept a policy and tests/operators tune recovery behavior
without editing control flow.  The module-level default policies below
reproduce the exact pre-existing schedules.

Jitter is DETERMINISTIC: it is derived from ``(seed, attempt)``, never
from global RNG state or wall-clock entropy, so a replayed run sleeps
the same intervals — the property the fault-injection harness
(faults.py) relies on to make recovery paths reproducible.
"""

from __future__ import annotations

import dataclasses
import itertools
import random
import time
from typing import Callable, Optional, Tuple, Type


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How many times to try, how long to wait, and when to give up.

    ``max_attempts``: total attempts allowed (``None`` = unbounded; the
    probe loop uses this — a wedged runtime recovers on its own
    schedule).  ``allows(n)`` answers "may attempt number ``n`` (0-based
    count of attempts already made) start?".

    ``base_delay_s`` / ``backoff`` / ``max_delay_s`` / ``jitter``: the
    sleep before retry ``k`` (0-based) is
    ``min(base * backoff**k, max_delay)``, scaled by a deterministic
    jitter factor in ``[1 - jitter, 1 + jitter]`` drawn from
    ``(seed, k)``.

    ``attempt_timeout_s`` (+ ``attempt_timeout_step_s``, capped at
    ``attempt_timeout_max_s``): per-attempt deadline, escalating with
    consecutive failures — a healthy-but-slow dependency must not fail
    every probe forever, so each failure buys the next attempt more
    patience.

    ``total_budget_s``: overall wall budget across all attempts
    (``deadline_from(start)`` converts it to an absolute deadline).
    """

    max_attempts: Optional[int] = 9
    base_delay_s: float = 10.0
    backoff: float = 1.0
    max_delay_s: float = 30.0
    jitter: float = 0.0
    attempt_timeout_s: Optional[float] = None
    attempt_timeout_step_s: float = 0.0
    attempt_timeout_max_s: Optional[float] = None
    total_budget_s: Optional[float] = None
    seed: int = 0

    def allows(self, attempts_made: int) -> bool:
        """True if another attempt may start after ``attempts_made``."""
        return self.max_attempts is None or attempts_made < self.max_attempts

    def delay_s(self, retry: int) -> float:
        """Sleep before 0-based retry number ``retry`` (deterministic)."""
        d = min(
            self.base_delay_s * (self.backoff ** max(0, retry)),
            self.max_delay_s,
        )
        if self.jitter:
            # String seeding: deterministic across processes and Python
            # versions (tuple seeds are hash-based and deprecated).
            u = random.Random(f"{self.seed}:{retry}").uniform(-1.0, 1.0)
            d *= 1.0 + self.jitter * u
        return max(0.0, d)

    def attempt_timeout(self, consecutive_failures: int = 0
                        ) -> Optional[float]:
        """Per-attempt deadline after ``consecutive_failures`` failures."""
        if self.attempt_timeout_s is None:
            return None
        t = (self.attempt_timeout_s
             + self.attempt_timeout_step_s * max(0, consecutive_failures))
        if self.attempt_timeout_max_s is not None:
            t = min(t, self.attempt_timeout_max_s)
        return t

    def deadline_from(self, start: float) -> Optional[float]:
        """Absolute deadline for the whole retry loop, or None."""
        if self.total_budget_s is None:
            return None
        return start + self.total_budget_s

    def sleep(self, retry: int, deadline: Optional[float] = None) -> float:
        """Sleep ``delay_s(retry)``, clamped to ``deadline``; returns the
        seconds actually slept."""
        d = self.delay_s(retry)
        if deadline is not None:
            d = max(0.0, min(d, deadline - time.time()))
        if d > 0:
            time.sleep(d)
        return d

    def call(self, fn: Callable, *,
             retry_on: Tuple[Type[BaseException], ...] = (Exception,),
             on_retry: Optional[Callable[[int, BaseException], None]] = None):
        """Run ``fn()`` under this policy: retry on ``retry_on`` with the
        backoff schedule until an attempt succeeds, the attempt budget is
        exhausted, or the total budget runs out — then re-raise the last
        error.  The streaming poll loops ride this helper."""
        deadline = self.deadline_from(time.time())
        for attempt in itertools.count():
            try:
                return fn()
            except retry_on as e:
                out_of_attempts = not self.allows(attempt + 1)
                out_of_budget = (
                    deadline is not None and time.time() >= deadline
                )
                if out_of_attempts or out_of_budget:
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                self.sleep(attempt, deadline)


# The pre-existing schedules, named.  Call sites default to these so the
# refactor preserves behavior exactly; callers override per run.

#: orchestrate.run_resilient's post-crash schedule: fixed 10 s sleep
#: between worker respawns, give up after 9 consecutive zero-progress
#: deaths (the old ``max_fruitless_retries=8`` semantics: raise when the
#: count EXCEEDS 8).
WORKER_RETRY = RetryPolicy(
    max_attempts=9, base_delay_s=10.0, backoff=1.0, max_delay_s=10.0,
)

#: The accelerator probe loop: 5 s sleeps escalating x1.5 to a 30 s cap
#: between failed probes (reset on success), per-probe patience
#: 30 + 15*consec capped at 90 s, never giving up (a wedged runtime
#: recovers on its own schedule).
PROBE = RetryPolicy(
    max_attempts=None, base_delay_s=5.0, backoff=1.5, max_delay_s=30.0,
    attempt_timeout_s=30.0, attempt_timeout_step_s=15.0,
    attempt_timeout_max_s=90.0,
)

#: Streaming micro-batch poll: transient source errors (broker hiccup,
#: network blip) retried with 1 s -> x2 -> 30 s backoff, five attempts.
STREAM_POLL = RetryPolicy(
    max_attempts=5, base_delay_s=1.0, backoff=2.0, max_delay_s=30.0,
)
