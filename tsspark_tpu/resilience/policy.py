"""Unified retry policy: one dataclass for every retry loop in the repo.

Before this module, retry behavior lived as hard-coded constants spread
across ``orchestrate.py`` (fixed 10 s post-crash sleeps, a fixed
fruitless-retry cap of 8, the 5 s -> x1.5 -> 30 s probe backoff, the
30 + 15*consec <= 90 s probe-patience escalation) and the streaming
driver's poll loop.  ``RetryPolicy`` expresses all of those as data, so
call sites accept a policy and tests/operators tune recovery behavior
without editing control flow.  The module-level default policies below
reproduce the exact pre-existing schedules.

Jitter is DETERMINISTIC: it is derived from ``(seed, attempt)``, never
from global RNG state or wall-clock entropy, so a replayed run sleeps
the same intervals — the property the fault-injection harness
(faults.py) relies on to make recovery paths reproducible.
"""

from __future__ import annotations

import dataclasses
import itertools
import random
import threading
import time
from typing import Callable, Optional, Tuple, Type


class CircuitOpen(RuntimeError):
    """Raised when a call is refused because its circuit breaker is open:
    the dependency has failed enough in a row that retrying it before the
    reset window elapses only burns the caller's deadline."""

    def __init__(self, name: str, retry_after_s: float):
        super().__init__(
            f"circuit for {name!r} is open; retry in {retry_after_s:.2f}s"
        )
        self.name = name
        self.retry_after_s = retry_after_s


class CircuitBreaker:
    """Closed / open / half-open failure gate for one dependency.

    A ``RetryPolicy`` bounds how long ONE call keeps trying; a breaker
    remembers failures ACROSS calls, so a dead dependency (wedged
    accelerator client, corrupt registry, downed broker) is shed fast —
    ``allow()`` returns False for ``reset_timeout_s`` after
    ``failure_threshold`` consecutive failures — instead of every caller
    independently retrying to its deadline.  After the window one
    half-open trial call probes the dependency: its success closes the
    circuit, its failure re-opens it for another window.

    Thread-safe (the serving engine's pump and a publisher thread may
    race it).  ``clock`` injects a fake time source for tests; state is
    derived from the clock on demand, so an idle breaker transitions
    open -> half-open without a background timer.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0, name: str = "dependency",
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._trial_inflight = False
        # Observability counters (chaos scorecards, engine stats).
        self.opens = 0
        self.fast_fails = 0

    def _state_locked(self) -> str:
        if self._opened_at is None:
            return self.CLOSED
        if self._clock() - self._opened_at >= self.reset_timeout_s:
            return self.HALF_OPEN
        return self.OPEN

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def allow(self) -> bool:
        """May a call proceed right now?  Half-open admits exactly one
        trial at a time; refusals are counted in ``fast_fails``."""
        with self._lock:
            st = self._state_locked()
            if st == self.CLOSED:
                return True
            if st == self.HALF_OPEN and not self._trial_inflight:
                self._trial_inflight = True
                return True
            self.fast_fails += 1
            return False

    def retry_after_s(self) -> float:
        """Seconds until the next half-open trial would be admitted."""
        with self._lock:
            if self._opened_at is None:
                return 0.0
            return max(
                0.0,
                self.reset_timeout_s - (self._clock() - self._opened_at),
            )

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._trial_inflight = False

    def record_failure(self) -> None:
        with self._lock:
            st = self._state_locked()
            self._failures += 1
            if st == self.HALF_OPEN or (
                st == self.CLOSED
                and self._failures >= self.failure_threshold
            ):
                self._opened_at = self._clock()
                self._trial_inflight = False
                self.opens += 1

    def snapshot(self) -> dict:
        """JSON-able state for reports/scorecards."""
        with self._lock:
            return {
                "name": self.name,
                "state": self._state_locked(),
                "failures": self._failures,
                "opens": self.opens,
                "fast_fails": self.fast_fails,
            }


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How many times to try, how long to wait, and when to give up.

    ``max_attempts``: total attempts allowed (``None`` = unbounded; the
    probe loop uses this — a wedged runtime recovers on its own
    schedule).  ``allows(n)`` answers "may attempt number ``n`` (0-based
    count of attempts already made) start?".

    ``base_delay_s`` / ``backoff`` / ``max_delay_s`` / ``jitter``: the
    sleep before retry ``k`` (0-based) is
    ``min(base * backoff**k, max_delay)``, scaled by a deterministic
    jitter factor in ``[1 - jitter, 1 + jitter]`` drawn from
    ``(seed, k)``.

    ``attempt_timeout_s`` (+ ``attempt_timeout_step_s``, capped at
    ``attempt_timeout_max_s``): per-attempt deadline, escalating with
    consecutive failures — a healthy-but-slow dependency must not fail
    every probe forever, so each failure buys the next attempt more
    patience.

    ``total_budget_s``: overall wall budget across all attempts
    (``deadline_from(start)`` converts it to an absolute deadline).
    """

    max_attempts: Optional[int] = 9
    base_delay_s: float = 10.0
    backoff: float = 1.0
    max_delay_s: float = 30.0
    jitter: float = 0.0
    attempt_timeout_s: Optional[float] = None
    attempt_timeout_step_s: float = 0.0
    attempt_timeout_max_s: Optional[float] = None
    total_budget_s: Optional[float] = None
    seed: int = 0

    def allows(self, attempts_made: int) -> bool:
        """True if another attempt may start after ``attempts_made``."""
        return self.max_attempts is None or attempts_made < self.max_attempts

    def delay_s(self, retry: int) -> float:
        """Sleep before 0-based retry number ``retry`` (deterministic)."""
        d = min(
            self.base_delay_s * (self.backoff ** max(0, retry)),
            self.max_delay_s,
        )
        if self.jitter:
            # String seeding: deterministic across processes and Python
            # versions (tuple seeds are hash-based and deprecated).
            u = random.Random(f"{self.seed}:{retry}").uniform(-1.0, 1.0)
            d *= 1.0 + self.jitter * u
        return max(0.0, d)

    def attempt_timeout(self, consecutive_failures: int = 0
                        ) -> Optional[float]:
        """Per-attempt deadline after ``consecutive_failures`` failures."""
        if self.attempt_timeout_s is None:
            return None
        t = (self.attempt_timeout_s
             + self.attempt_timeout_step_s * max(0, consecutive_failures))
        if self.attempt_timeout_max_s is not None:
            t = min(t, self.attempt_timeout_max_s)
        return t

    def deadline_from(self, start: float) -> Optional[float]:
        """Absolute deadline for the whole retry loop, or None."""
        if self.total_budget_s is None:
            return None
        return start + self.total_budget_s

    def sleep(self, retry: int, deadline: Optional[float] = None) -> float:
        """Sleep ``delay_s(retry)``, clamped to ``deadline``; returns the
        seconds actually slept."""
        d = self.delay_s(retry)
        if deadline is not None:
            d = max(0.0, min(d, deadline - time.time()))
        if d > 0:
            time.sleep(d)
        return d

    def call(self, fn: Callable, *,
             retry_on: Tuple[Type[BaseException], ...] = (Exception,),
             on_retry: Optional[Callable[[int, BaseException], None]] = None,
             breaker: Optional["CircuitBreaker"] = None):
        """Run ``fn()`` under this policy: retry on ``retry_on`` with the
        backoff schedule until an attempt succeeds, the attempt budget is
        exhausted, or the total budget runs out — then re-raise the last
        error.  The streaming poll loops ride this helper.

        ``breaker``: a ``CircuitBreaker`` consulted before EVERY attempt
        and fed every outcome.  A call that starts against an open
        breaker raises ``CircuitOpen`` without attempting anything — a
        dependency that has been failing across calls is shed fast
        instead of retried to the deadline.  A breaker that OPENS
        mid-call stops the retry loop but re-raises the underlying
        error (the real failure must not be masked by the gate that
        merely reacted to it)."""
        deadline = self.deadline_from(time.time())
        for attempt in itertools.count():
            if breaker is not None and not breaker.allow():
                raise CircuitOpen(breaker.name, breaker.retry_after_s())
            try:
                result = fn()
            except retry_on as e:
                if breaker is not None:
                    breaker.record_failure()
                out_of_attempts = not self.allows(attempt + 1)
                out_of_budget = (
                    deadline is not None and time.time() >= deadline
                )
                breaker_tripped = (
                    breaker is not None
                    and breaker.state != CircuitBreaker.CLOSED
                )
                if out_of_attempts or out_of_budget or breaker_tripped:
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                self.sleep(attempt, deadline)
            except BaseException:
                # Non-retryable escape (caller bug, KeyboardInterrupt):
                # the attempt still has to resolve the breaker's
                # half-open trial slot, or the breaker wedges with the
                # trial marked in flight and never admits another call.
                if breaker is not None:
                    breaker.record_failure()
                raise
            else:
                if breaker is not None:
                    breaker.record_success()
                return result


# The pre-existing schedules, named.  Call sites default to these so the
# refactor preserves behavior exactly; callers override per run.

#: orchestrate.run_resilient's post-crash schedule: fixed 10 s sleep
#: between worker respawns, give up after 9 consecutive zero-progress
#: deaths (the old ``max_fruitless_retries=8`` semantics: raise when the
#: count EXCEEDS 8).
WORKER_RETRY = RetryPolicy(
    max_attempts=9, base_delay_s=10.0, backoff=1.0, max_delay_s=10.0,
)

#: The accelerator probe loop: 5 s sleeps escalating x1.5 to a 30 s cap
#: between failed probes (reset on success), per-probe patience
#: 30 + 15*consec capped at 90 s, never giving up (a wedged runtime
#: recovers on its own schedule).
PROBE = RetryPolicy(
    max_attempts=None, base_delay_s=5.0, backoff=1.5, max_delay_s=30.0,
    attempt_timeout_s=30.0, attempt_timeout_step_s=15.0,
    attempt_timeout_max_s=90.0,
)

#: Streaming micro-batch poll: transient source errors (broker hiccup,
#: network blip) retried with 1 s -> x2 -> 30 s backoff, five attempts.
STREAM_POLL = RetryPolicy(
    max_attempts=5, base_delay_s=1.0, backoff=2.0, max_delay_s=30.0,
)
