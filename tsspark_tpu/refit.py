"""Incremental delta-refit engine: refit cost scales with CHANGED series.

PR 12's 1M rung pays ~52 s of resident fit per refresh even when 1% of
the fleet gained a row — every refit today is a cold full-fleet fit.
This module closes ROADMAP item 4's perf core: an always-on loop where
each cycle touches only the series whose DATA actually advanced.

One ``run_refit`` cycle:

1. **detect** — the data plane's row-advance accounting
   (``data.plane.advanced_since``): the active registry version records
   the delta coverage stamp it was fitted at
   (``ParamRegistry.version_stamp``), and the changed set is exactly
   the rows of every delta landed after it.  The set is pinned in an
   atomic ``refit_plan.json`` so a killed cycle's successor refits the
   SAME plan instead of racing fresh deltas mid-flight.
2. **plan + fit** — the changed rows are compacted into a dense
   ``[0, n_changed)`` claim space and run through the PR 11
   mesh-resident path (``tsspark_tpu.resident``) over a gathered spill:
   the SAME ``plan_chunks``/lease/chunk-file machinery, so 10% churn
   produces ~10% of the waves and a SIGKILLed cycle resumes from its
   landed flushes.  Waves are **warm-started** from the active
   snapshot's theta, mmap-gathered per wave off the snapshot plane
   (``warm_theta_gather`` — only the touched pages are read), under the
   recorded PR 11 parity constraints: no buffer donation under
   pipelined overlap, >=2 rows/shard sub-mesh rule, ``use_theta0`` as a
   dynamic arg so warm and cold waves share one compiled program.
   ``warm_start=False`` is bitwise the cold resident path.
3. **delta publish** — ``ParamRegistry.publish_delta`` /
   ``snapplane.write_plane_delta``: the new version's plane
   copy-forwards unchanged rows from the active plane (vectorized
   scatter of the refit rows into a sequential copy; a column no
   changed row lands in — and EVERY column on a zero-delta cycle — is
   hardlinked wholesale, zero new snapshot bytes).
4. **flip** — through the PR 10 materialize/drain path
   (``ReplicaPool.activate`` when a pool is attached, or the engine's
   prefetch/materialize/activate analog), with partial cache
   invalidation: unchanged series' forecast-cache entries carry
   forward to the new version (``ForecastCache.carry_forward``).

``run_delta_bench`` (``bench --delta``) sweeps churn fractions at the
scale-ladder rungs and stamps ``delta_series_per_s`` +
``delta_wall_frac`` (delta cycle wall over the same run's measured cold
fit+publish wall) into bench-family reports the regression sentinel
baselines under ``+delta<churn>``-scoped workload keys.

See docs/PERF.md "Delta refit" for engage rules and reading guidance.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from tsspark_tpu import orchestrate
from tsspark_tpu.obs import context as obs
from tsspark_tpu.io import atomic_write

#: The cycle's pinned plan: base version, coverage stamps, the changed
#: row set — replaced atomically, so a successor after a mid-cycle kill
#: resumes exactly this claim set (never a fresh detect that would race
#: deltas landed after the kill).
REFIT_PLAN_FILE = "refit_plan.json"

#: Spill-set visibility marker inside a cycle dir: each spill column is
#: individually atomic but the SET is not — a kill between columns
#: would leave ds.npy without mask.npy, and a presence check would
#: resume against half a gather.  The marker (atomic, written LAST) is
#: the unit of visibility; re-spilling before it lands is safe because
#: no chunk file can exist until the fit stage starts.
SPILL_OK_FILE = "spillok.json"

#: Reused cold-reference record (``bench --delta --reuse-cold`` /
#: ``bench --freshness``): the measured cold fit+publish walls plus the
#: shape/fingerprint identity that makes reuse safe.
COLD_META_FILE = "cold_meta.json"


def warm_theta_gather(theta, idx):
    """Warm-start gather: rows ``idx`` of the active snapshot's theta,
    float32, NaN/inf scrubbed (a warm INIT must never smuggle a poison
    value into the solver — correctness never depends on init quality).

    Host arrays (the snapshot plane's memmap) take the numpy path —
    fancy indexing reads only the touched pages, which is what makes
    the per-wave gather O(wave), not O(fleet).  Traced values take the
    jnp path; the analysis gate's kernel-contract matrix traces this
    function under ``enable_x64`` so an f64 leak in the gather (the
    classic un-pinned-dtype drift) surfaces statically."""
    if isinstance(theta, np.ndarray):
        rows = np.take(np.asarray(theta), np.asarray(idx, np.int64),
                       axis=0)
        return np.nan_to_num(rows).astype(np.float32)
    import jax.numpy as jnp

    rows = jnp.take(jnp.asarray(theta), jnp.asarray(idx), axis=0)
    return jnp.nan_to_num(rows).astype(jnp.float32)


def read_refit_plan(scratch: str) -> Optional[Dict]:
    """The pinned plan in ``scratch``, or None (absent/torn)."""
    try:
        with open(os.path.join(scratch, REFIT_PLAN_FILE)) as fh:
            d = json.load(fh)
        return d if isinstance(d, dict) else None
    except (OSError, ValueError):
        return None


def _write_refit_plan(scratch: str, plan: Dict) -> None:
    atomic_write(
        os.path.join(scratch, REFIT_PLAN_FILE),
        lambda fh: json.dump(plan, fh), mode="w",
    )


# ---------------------------------------------------------------------------
# cycle stages (the scheduler pipelines these; run_refit composes them)
# ---------------------------------------------------------------------------


def draft_plan(data_dir: str, base_stamp: int,
               base_version: Optional[int] = None) -> Dict:
    """An IN-MEMORY cycle plan against a coverage stamp.  The pipelined
    scheduler drafts cycle N+1's plan against cycle N's ``plan_stamp``
    while N is still publishing — N's version number does not exist
    yet, so ``base_version`` stays None until :func:`pin_drafted`
    adopts it at fit time."""
    from tsspark_tpu.data import plane

    plan_stamp = plane.delta_seq(data_dir)
    changed = plane.advanced_since(data_dir, int(base_stamp))
    return {
        "base_version": (None if base_version is None
                         else int(base_version)),
        "base_stamp": int(base_stamp),
        "plan_stamp": int(plan_stamp),
        "n_changed": int(len(changed)),
        "changed_rows": [int(r) for r in changed.tolist()],
        "complete": False,
    }


def pin_drafted(scratch: str, plan: Dict, base_version: int) -> Dict:
    """Adopt a drafted plan's base version and pin it to disk — the
    point a speculative draft becomes THE cycle a successor resumes."""
    plan = dict(plan, base_version=int(base_version))
    _write_refit_plan(scratch, plan)
    return plan


def resolve_plan(data_dir: str, registry, scratch: str,
                 base_version: int) -> tuple:
    """(plan, resumed): resume the pinned plan when it is incomplete
    and its base is usable — the active version, a draft pinned before
    its base version number existed (matched by base STAMP — the
    pipelined scheduler's prefetch), or a PUBLISHED but not-yet-active
    version (a front elsewhere owns the flip and the publisher died
    after publish, before the flip: the plan must be resumed against
    its own base, never re-detected from the stale active pointer —
    that fresh detect racing deltas landed after the kill is exactly
    what the pin exists to prevent).  The published-base resume is
    gated on the plan covering at least the ACTIVE version's data
    stamp, so a plan orphaned behind a newer out-of-band flip can
    never publish a version that would regress coverage.  Else pin a
    fresh detect against ``base_version`` (the active version)."""
    plan = read_refit_plan(scratch)
    active_stamp = int(registry.version_stamp(int(base_version)))
    if plan is not None and not plan.get("complete"):
        pv = plan.get("base_version")
        if pv == int(base_version):
            return plan, True
        if pv is None and plan.get("base_stamp") == active_stamp:
            return pin_drafted(scratch, plan, base_version), True
        if pv is not None:
            try:
                pv_stamp = int(registry.version_stamp(int(pv)))
            except Exception:
                pv_stamp = None  # base vanished: fall through to detect
            if (pv_stamp == plan.get("base_stamp")
                    and int(plan.get("plan_stamp", -1))
                    >= active_stamp):
                return plan, True
    plan = draft_plan(data_dir, active_stamp,
                      base_version=int(base_version))
    _write_refit_plan(scratch, plan)
    return plan, False


def cycle_paths(scratch: str, plan: Dict) -> tuple:
    """(cycle_dir, spill data dir, fit out dir) for a plan.  Keyed by
    the STAMP pair, not the base version: a draft's paths must not move
    when :func:`pin_drafted` later fills the version in, or a prefetched
    spill would be orphaned."""
    cycle_dir = os.path.join(
        scratch,
        f"cycle_b{plan['base_stamp']:06d}_s{plan['plan_stamp']:06d}",
    )
    return (cycle_dir, os.path.join(cycle_dir, "delta_data"),
            os.path.join(cycle_dir, "out"))


def ensure_spill(data_dir: str, plan: Dict, scratch: str) -> str:
    """Gather the plan's changed rows into the cycle's spill dir
    (idempotent — the ``SPILL_OK_FILE`` marker is the unit of
    visibility for the spill SET; see its docstring).  Pure mmap reads:
    this is the stage the scheduler overlaps with the previous cycle's
    publish.  Returns the spill dir."""
    from tsspark_tpu.data import plane

    cycle_dir, ddir, _out = cycle_paths(scratch, plan)
    marker = os.path.join(cycle_dir, SPILL_OK_FILE)
    if os.path.exists(marker):
        return ddir
    os.makedirs(cycle_dir, exist_ok=True)
    changed = np.asarray(plan["changed_rows"], np.int64)
    batch = plane.open_batch(data_dir)
    sub = lambda a: (None if a is None
                     else np.ascontiguousarray(a[changed]))
    orchestrate.spill_data(
        ddir, np.asarray(batch.ds), sub(batch.y),
        mask=sub(batch.mask), regressors=sub(batch.regressors),
        cap=sub(batch.cap),
    )
    atomic_write(
        marker,
        lambda fh: json.dump({"n_changed": int(plan["n_changed"]),
                              "unix": round(time.time(), 3)}, fh),
        mode="w",
    )
    return ddir


def fit_changed(
    data_dir: str,
    registry,
    plan: Dict,
    scratch: str,
    *,
    chunk: int,
    solver_config,
    phase1_iters: int = 0,
    no_phase1_tune: bool = True,
    warm_start: bool = True,
    theta_cache: Optional[Dict] = None,
    deadline: Optional[float] = None,
) -> Dict:
    """The exclusive stage: spill (if not prefetched), warm-gather, and
    run the changed set through the resident path.  Returns a dict with
    ``complete``, ``fit_s``, ``fit_dispatches``, ``fit_path``,
    ``state_sub``, ``step_sub``, and ``warm_cache_hits``.

    ``theta_cache``: pre-gathered warm-init rows (the scheduler's
    speculative/carry-forward prep) — ``{"base_stamp": int, "rows":
    sorted int64 array, "theta": float32 (k, P)}``.  Consulted only
    when its ``base_stamp`` matches the plan's (a cache gathered
    against an older plane is stale); rows it covers skip the plane
    gather entirely, rows it misses fall back to the per-wave mmap
    gather.  Cache values are bitwise what the base plane holds for
    those rows, so a hit changes no numerics — it only saves the page
    reads."""
    from tsspark_tpu.serve import snapplane

    changed = np.asarray(plan["changed_rows"], np.int64)
    n_changed = int(plan["n_changed"])
    ddir = ensure_spill(data_dir, plan, scratch)
    _cycle_dir, _ddir, out_dir = cycle_paths(scratch, plan)
    os.makedirs(out_dir, exist_ok=True)
    orchestrate.save_run_config(out_dir, registry.config, solver_config)

    cache_rows = cache_theta = None
    if (warm_start and theta_cache is not None
            and int(theta_cache.get("base_stamp", -1))
            == int(plan["base_stamp"])
            and len(theta_cache.get("rows", ()))):
        cache_rows = np.asarray(theta_cache["rows"], np.int64)
        cache_theta = np.asarray(theta_cache["theta"], np.float32)
    hits = {"n": 0}

    theta0_fn = None
    base_view = None
    theta_mm = None
    if warm_start:
        base_vdir = registry.version_dir(int(plan["base_version"]))
        try:
            # verify=False: the registry CRC-swept this plane when it
            # was loaded for serving; a warm INIT cannot affect
            # correctness (warm_theta_gather scrubs non-finite values),
            # so the refit skips a second full sweep.
            base_view = snapplane.attach(base_vdir, verify=False)
            theta_mm = base_view.state.theta
        except snapplane.SnapshotPlaneError:
            import warnings

            warnings.warn(
                f"refit: base version {plan['base_version']} has no "
                "readable snapshot plane; warm start disabled for "
                "this cycle (cold ridge init — results stay "
                "correct, the warm-start perf lever is lost)",
                RuntimeWarning,
            )
    if theta_mm is not None:
        def theta0_fn(lo, hi):
            # Per-wave gather: base rows of this wave's slice of the
            # compacted changed set — cache rows from memory, the rest
            # as touched-pages-only mmap reads.
            rows = changed[lo:hi]
            if cache_rows is None:
                return warm_theta_gather(theta_mm, rows)
            pos = np.minimum(np.searchsorted(cache_rows, rows),
                             len(cache_rows) - 1)
            hit = cache_rows[pos] == rows
            if not hit.any():
                return warm_theta_gather(theta_mm, rows)
            out = np.empty((len(rows), cache_theta.shape[1]),
                           np.float32)
            out[hit] = cache_theta[pos[hit]]
            if not hit.all():
                out[~hit] = warm_theta_gather(theta_mm, rows[~hit])
            hits["n"] += int(hit.sum())
            return np.nan_to_num(out)

    from tsspark_tpu import resident

    chunks_before = len(orchestrate.completed_ranges(out_dir))
    t0 = time.time()
    fit_state = resident.run_resident(
        data_dir=ddir, out_dir=out_dir, series=n_changed,
        chunk=int(chunk), phase1_iters=phase1_iters,
        no_phase1_tune=no_phase1_tune, autotune=False,
        deadline=deadline, theta0_fn=theta0_fn,
    )
    out: Dict = {
        "complete": bool(fit_state.get("complete")),
        "fit_s": round(time.time() - t0, 3),
        "fit_path": fit_state.get("fit_path"),
        "fit_dispatches": (len(orchestrate.completed_ranges(out_dir))
                           - chunks_before),
        "warm_cache_hits": hits["n"],
        "state_sub": None,
        "step_sub": None,
    }
    if not out["complete"]:
        return out
    out["state_sub"] = orchestrate.load_fit_state(out_dir, n_changed)
    if base_view is not None and "step" in base_view.extras:
        out["step_sub"] = np.asarray(
            base_view.extras["step"][changed], np.float64
        )
    return out


def reap_cycles(scratch: str, keep: Sequence[str] = ()) -> None:
    """Remove completed cycle dirs (dead weight once their plan is
    done), sparing any in-flight dirs the pipelined scheduler names."""
    keep_abs = {os.path.abspath(k) for k in keep}
    try:
        names = os.listdir(scratch)
    except OSError:
        return
    for name in names:
        d = os.path.join(scratch, name)
        if (name.startswith("cycle_")
                and os.path.abspath(d) not in keep_abs):
            shutil.rmtree(d, ignore_errors=True)


def _advance_posterior(registry, plan, state_sub, changed, scratch,
                       v_new) -> bool:
    """Delta-cycle ADVI posterior advance: re-fit the changed rows'
    variational posteriors (warm-started from the cycle's fresh MAP
    theta, over the cycle's already-spilled data) and copy the rest
    forward from the base version's posterior.  Without this, a delta
    flip would silently drop the fleet from the ADVI tier to MAP —
    intervals would change meaning across a routine refresh.

    Returns True when a posterior landed in ``v_new``'s version dir.
    Skips (False) when the base has no posterior (fleet never advanced
    past MAP) or the config is outside the ADVI family."""
    from tsspark_tpu.uncertainty import advi as advi_mod
    from tsspark_tpu.uncertainty import qplane

    base_loaded = advi_mod.load_posterior(
        registry.version_dir(int(plan["base_version"])))
    if base_loaded is None:
        return False
    base_post, header = base_loaded
    config = registry.config
    if not qplane._advi_eligible(config):
        return False
    n_base = int(np.asarray(base_post.mu).shape[0])
    if len(changed) and int(changed.max()) >= n_base:
        # Fleet grew past the posterior's row space — a scatter would
        # mis-index; qplane re-gates n vs the snapshot at publish time.
        return False

    _cycle_dir, ddir, _out = cycle_paths(scratch, plan)
    load = lambda name: (np.load(os.path.join(ddir, name))
                         if os.path.exists(os.path.join(ddir, name))
                         else None)
    ds, y = np.load(os.path.join(ddir, "ds.npy")), load("y.npy")
    from tsspark_tpu.models.prophet.design import prepare_fit_data

    data, _meta = prepare_fit_data(
        ds, y, config, mask=load("mask.npy"), cap=load("cap.npy"),
    )
    import jax

    seed = int(header.get("seed", 0))
    num_steps = int(header.get("num_steps", 0)) or None
    from tsspark_tpu.config import AdviConfig

    advi_cfg = (AdviConfig(num_steps=num_steps) if num_steps
                else AdviConfig())
    # Key on (seed, new version): deterministic per cycle, decorrelated
    # across cycles.
    key = jax.random.fold_in(jax.random.PRNGKey(seed), int(v_new))
    sub = advi_mod.fit_advi(
        np.asarray(state_sub.theta, np.float32), data, key, config,
        advi_cfg,
    )
    mu = np.array(base_post.mu, np.float32)
    rho = np.array(base_post.rho, np.float32)
    elbo = np.array(base_post.elbo, np.float32)
    mu[changed] = np.asarray(sub.mu, np.float32)
    rho[changed] = np.asarray(sub.rho, np.float32)
    elbo[changed] = np.asarray(sub.elbo, np.float32)
    advi_mod.save_posterior(
        registry.version_dir(int(v_new)),
        advi_mod.AdviPosterior(mu=mu, rho=rho, elbo=elbo),
        seed=seed, num_steps=advi_cfg.num_steps,
    )
    return True


def publish_plan(
    registry,
    plan: Dict,
    state_sub,
    step_sub,
    scratch: str,
    *,
    pool=None,
    flip_fn: Optional[Callable[[int], None]] = None,
    activate: bool = True,
    hot_series: Optional[Sequence[str]] = None,
    horizons: Sequence[int] = (7, 14),
    reap: bool = True,
) -> Dict:
    """Copy-forward delta publish + flip + mark the plan complete.
    Everything here is mmap reads and atomic writes — the stage the
    scheduler overlaps with the NEXT cycle's detect and spill.  Returns
    ``{"version", "publish_s", "flip_s", "flipped"}``."""
    changed = np.asarray(plan["changed_rows"], np.int64)
    t0 = time.time()
    v_new = registry.publish_delta(
        state_sub, changed, base_version=int(plan["base_version"]),
        step_sub=step_sub, data_stamp=plan["plan_stamp"],
        activate=False,
    )
    # Forecast-plane copy-forward BEFORE the flip: unchanged series'
    # columns hardlink/scatter from the base plane, only the refit rows
    # recompute, and the replicas that refresh onto v_new adopt the
    # plane immediately — hot reads stay zero-dispatch across a delta
    # flip.  Best-effort by contract (fplane.maybe_publish sheds under
    # disk pressure and a base without a plane publishes full); it must
    # never fail the publish stage.
    from tsspark_tpu.serve import fplane

    try:
        fpub = fplane.maybe_publish(registry, int(v_new),
                                    horizons=tuple(horizons))
    except Exception as e:
        fpub = None
        obs.event("fplane.publish_failed", version=int(v_new),
                  error=repr(e))

    # Uncertainty tier rides the same contract: advance the ADVI
    # posterior for the refit rows (copy-forward the rest), then
    # delta-publish the quantile plane.  Best-effort — a failure sheds
    # to the MAP/compute interval path, never fails the flip.
    from tsspark_tpu.uncertainty import qplane

    qpub = None
    try:
        _advance_posterior(registry, plan, state_sub, changed, scratch,
                           int(v_new))
        qpub = qplane.maybe_publish(registry, int(v_new),
                                    horizons=tuple(horizons))
    except Exception as e:
        obs.event("qplane.publish_failed", version=int(v_new),
                  error=repr(e))
    publish_s = round(time.time() - t0, 3)

    t0 = time.time()
    if pool is not None:
        pool.activate(v_new, hot_series=list(hot_series or ()),
                      horizons=tuple(horizons))
    elif flip_fn is not None:
        flip_fn(int(v_new))
    elif activate:
        registry.activate(int(v_new))
    flip_s = round(time.time() - t0, 3)

    _write_refit_plan(scratch, dict(plan, complete=True,
                                    published_version=int(v_new)))
    if reap:
        reap_cycles(scratch)
    return {
        "version": int(v_new),
        "publish_s": publish_s,
        "flip_s": flip_s,
        "flipped": bool(pool is not None or flip_fn is not None
                        or activate),
        "fplane": None if fpub is None else fpub.get("status"),
        "qplane": None if qpub is None else qpub.get("status"),
    }


def run_refit(
    *,
    data_dir: str,
    registry,
    scratch: str,
    chunk: int = 512,
    solver_config=None,
    phase1_iters: int = 0,
    no_phase1_tune: bool = True,
    warm_start: bool = True,
    pool=None,
    hot_series: Optional[Sequence[str]] = None,
    horizons: Sequence[int] = (7, 14),
    activate: bool = True,
    flip_fn: Optional[Callable[[int], None]] = None,
    deadline: Optional[float] = None,
    theta_cache: Optional[Dict] = None,
) -> Dict:
    """One delta-refit cycle: detect -> warm resident fit over the
    changed set -> copy-forward delta publish -> flip.  Returns the
    cycle's metrics dict (versions, per-stage walls, dispatch count).

    ``registry`` is an attached ``ParamRegistry`` with an ACTIVE
    version whose snapshot plane exists (the warm-start source and the
    copy-forward base).  ``scratch`` persists across cycles: the
    current plan plus a per-(stamp pair) cycle dir whose chunk files
    make a killed cycle resumable.  The flip goes through
    ``pool.activate`` (the PR 10 materialize/drain path) when a pool is
    attached, else ``flip_fn`` when given, else ``registry.activate``;
    ``activate=False`` publishes without flipping (the chaos child —
    the harness's front owns the flip).  ``theta_cache``: pre-gathered
    warm-init rows (see :func:`fit_changed` — the scheduler's
    speculative prep; a plain cycle never needs it).

    Zero-delta fast path: no advanced series -> zero fit dispatches,
    a fully-hardlinked version (zero new snapshot bytes), and the
    serving side keeps returning bitwise-identical forecasts.

    The stages are the module-level :func:`resolve_plan` /
    :func:`ensure_spill` / :func:`fit_changed` / :func:`publish_plan`
    — the always-on scheduler (``tsspark_tpu.sched``) pipelines those
    directly so cycle N+1's detect and spill overlap cycle N's publish
    and flip; this function is their serial composition, ONE cycle as
    one call (the CLI/chaos/bench unit).
    """
    from tsspark_tpu.config import SolverConfig

    t_cycle0 = time.time()
    os.makedirs(scratch, exist_ok=True)
    if solver_config is None:
        solver_config = SolverConfig()
    base_version = registry.active_version()
    if base_version is None:
        from tsspark_tpu.serve.registry import RegistryError

        raise RegistryError("no-active-version",
                            "delta refit needs an active base version")

    # ---- detect: pin (or resume) the plan ---------------------------
    t0 = time.time()
    plan, resumed = resolve_plan(data_dir, registry, scratch,
                                 int(base_version))
    n_changed = int(plan["n_changed"])
    detect_s = time.time() - t0
    obs.record("refit.detect", t0, detect_s, n_changed=n_changed,
               base_version=int(base_version), resumed=resumed)

    result: Dict = {
        # The plan's base, not the active pointer: a resumed plan whose
        # publish landed but whose flip did not may legitimately base
        # on a published, not-yet-active version (see resolve_plan).
        "base_version": int(plan["base_version"]),
        "base_stamp": plan["base_stamp"],
        "plan_stamp": plan["plan_stamp"],
        "n_changed": n_changed,
        "resumed": resumed,
        "warm_start": bool(warm_start),
        "detect_s": round(detect_s, 3),
        "fit_dispatches": 0,
        "fit_s": 0.0,
    }

    state_sub = None
    step_sub = None
    if n_changed:
        # ---- fit: compacted claim space through the resident path ---
        fit_res = fit_changed(
            data_dir, registry, plan, scratch, chunk=int(chunk),
            solver_config=solver_config, phase1_iters=phase1_iters,
            no_phase1_tune=no_phase1_tune, warm_start=warm_start,
            theta_cache=theta_cache, deadline=deadline,
        )
        result["fit_s"] = fit_res["fit_s"]
        result["fit_path"] = fit_res["fit_path"]
        result["fit_dispatches"] = fit_res["fit_dispatches"]
        if fit_res["warm_cache_hits"]:
            result["warm_cache_hits"] = fit_res["warm_cache_hits"]
        if not fit_res["complete"]:
            result["complete"] = False
            result["wall_s"] = round(time.time() - t_cycle0, 3)
            return result
        state_sub = fit_res["state_sub"]
        step_sub = fit_res["step_sub"]

    # ---- delta publish + flip (copy-forward; PR 10 drain path) ------
    pub = publish_plan(
        registry, plan, state_sub, step_sub, scratch,
        pool=pool, flip_fn=flip_fn, activate=activate,
        hot_series=hot_series, horizons=horizons,
    )
    result.update(pub)
    result["complete"] = True
    result["wall_s"] = round(time.time() - t_cycle0, 3)
    obs.record("refit.cycle", t_cycle0, result["wall_s"],
               n_changed=n_changed, version=result.get("version"),
               warm_start=bool(warm_start))
    return result


# ---------------------------------------------------------------------------
# reusable cold reference (bench --delta/--freshness --reuse-cold)
# ---------------------------------------------------------------------------


def load_cold_meta(base_dir: str, rung) -> Optional[Dict]:
    """The recorded cold fit+publish reference under ``base_dir``, or
    None when absent or not reusable for this rung (shape or data
    fingerprint mismatch, or the cold out dir lost its coverage)."""
    from tsspark_tpu.data import plane

    try:
        with open(os.path.join(base_dir, COLD_META_FILE)) as fh:
            meta = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(meta, dict):
        return None
    if (meta.get("series") != rung.series
            or meta.get("timesteps") != rung.timesteps
            or meta.get("fingerprint") != plane.dataset_fingerprint()):
        return None
    out_dir = os.path.join(base_dir, "cold_out")
    done = sum(hi - lo for lo, hi
               in orchestrate.completed_ranges(out_dir))
    if done < rung.series:
        return None
    return dict(meta, out_dir=out_dir)


def save_cold_meta(base_dir: str, meta: Dict) -> None:
    atomic_write(
        os.path.join(base_dir, COLD_META_FILE),
        lambda fh: json.dump(meta, fh, indent=1), mode="w",
    )


def cold_base(rung, cfg, solver, run_dir: str, dset_dir: str,
              reuse_cold: Optional[str] = None) -> Dict:
    """The sweep's cold reference: a complete resident fit of the rung
    plus the measured fit wall.  With ``reuse_cold`` pointing at a
    prior run's base dir, the recorded measurement (and the fitted
    chunk files) are reused instead of re-fitting the whole rung on
    every invocation — the amortization churn sweeps and the freshness
    bench ride.  Returns ``{"out_dir", "fit_s", "publish_s" (None when
    the caller must measure its own publish), "fit_path", "reused"}``.
    """
    from tsspark_tpu import resident
    from tsspark_tpu.data import plane

    if reuse_cold:
        meta = load_cold_meta(reuse_cold, rung)
        if meta is not None:
            return {"out_dir": meta["out_dir"],
                    "fit_s": float(meta["fit_s"]),
                    "publish_s": float(meta["publish_s"]),
                    "fit_path": meta.get("fit_path"),
                    "data_stamp": int(meta.get("data_stamp") or 0),
                    "reused": True}
    base_dir = reuse_cold or run_dir
    out_dir = os.path.join(base_dir, "cold_out")
    # No (valid) meta means whatever lives in cold_out is NOT a
    # reusable fit for THIS rung/dataset — a different shape, or a
    # rotated data fingerprint.  Clear it: run_resident resumes from
    # completed chunk files, so stale coverage would silently publish
    # parameters fit against different data AND record a near-zero
    # "cold" wall into the meta (poisoning every *_vs_cold metric).
    shutil.rmtree(out_dir, ignore_errors=True)
    os.makedirs(out_dir, exist_ok=True)
    orchestrate.save_run_config(out_dir, cfg, solver)
    data_stamp = plane.delta_seq(dset_dir)
    t0 = time.time()
    cold_state = resident.run_resident(
        data_dir=dset_dir, out_dir=out_dir, series=rung.series,
        chunk=rung.chunk, phase1_iters=0, no_phase1_tune=True,
    )
    fit_s = time.time() - t0
    if not cold_state.get("complete"):
        return {"out_dir": out_dir, "fit_s": fit_s, "publish_s": None,
                "fit_path": cold_state.get("fit_path"),
                "data_stamp": data_stamp,
                "reused": False, "complete": False}
    return {"out_dir": out_dir, "fit_s": fit_s, "publish_s": None,
            "fit_path": cold_state.get("fit_path"),
            "data_stamp": data_stamp, "reused": False,
            "complete": True}


# ---------------------------------------------------------------------------
# bench --delta: the churn-fraction sweep
# ---------------------------------------------------------------------------

#: Churn fractions ``bench --delta`` sweeps by default.
DEFAULT_CHURNS = (0.01, 0.1, 0.3)


def parse_churns(spec: Optional[str]):
    """Churn fractions from a ``--churns`` CLI string (None -> the
    defaults).  ONE parser for both entry points (bench.py --delta and
    python -m tsspark_tpu.refit --delta-bench)."""
    if not spec:
        return DEFAULT_CHURNS
    return tuple(float(c) for c in spec.split(","))


def sweep_ok(reports: Sequence[Dict]) -> bool:
    """The sweep's pass/fail contract — every cycle complete AND
    sentinel-green — reduced in ONE place so the two entry points'
    exit codes can never diverge.  Success reports are bench-shaped
    (``complete`` lives under ``extra``); failure records carry it at
    the top level — accept both, and an EMPTY sweep is a failure."""
    if not reports:
        return False
    return all(
        bool(r.get("complete", (r.get("extra") or {}).get("complete")))
        and r.get("sentinel_ok", True)
        for r in reports
    )


#: A delta-bench run tree untouched this long is reaped on the next
#: sweep: each invocation keys a fresh ``run_<unix>`` dir (the cold
#: fit must be a real measurement, never a warm resume), so without an
#: age gate repeated sweeps accumulate rung-sized chunk/registry trees
#: forever.
STALE_RUN_S = 6 * 3600.0


def _sweep_stale_runs(scratch: str, keep: str,
                      max_age_s: float = STALE_RUN_S) -> int:
    removed = 0
    try:
        names = os.listdir(scratch)
    except OSError:
        return 0
    for name in names:
        d = os.path.join(scratch, name)
        if (not name.startswith("run_") or not os.path.isdir(d)
                or os.path.abspath(d) == os.path.abspath(keep)):
            continue
        try:
            import glob as glob_mod

            newest = max(
                (os.path.getmtime(p) for p in
                 glob_mod.glob(os.path.join(d, "**"), recursive=True)),
                default=os.path.getmtime(d),
            )
        except OSError:
            continue
        if time.time() - newest > max_age_s:
            shutil.rmtree(d, ignore_errors=True)
            removed += 1
    return removed


def _delta_report(rung, churn: float, cold: Dict, res: Dict,
                  serve_stats: Dict, cfg) -> Dict:
    """One bench-family report per (rung, churn): the regression
    sentinel keys its workload ``...+delta<churn>`` (obs.history), so
    delta cycles are never baselined against cold fits."""
    import jax

    from tsspark_tpu.config import NUMERICS_REV
    from tsspark_tpu.obs.history import git_rev
    from tsspark_tpu.utils import checkpoint as ckpt

    n_changed = res["n_changed"]
    fit_s = res.get("fit_s") or 0.0
    wall = res["wall_s"]
    cold_wall = cold["fit_s"] + cold["publish_s"]
    extra = {
        "trace_id": obs.trace_id(),
        "numerics_rev": NUMERICS_REV,
        "git_rev": git_rev(),
        "config_fingerprint": ckpt.config_fingerprint(cfg),
        "device": str(jax.devices()[0]),
        "complete": bool(res.get("complete")),
        "fit_path": res.get("fit_path", "resident"),
        "warm_start": res.get("warm_start"),
        "delta_churn": churn,
        "n_changed": n_changed,
        "series_done": n_changed,
        "series_total": rung.series,
        "delta_series_per_s": (round(n_changed / fit_s, 2)
                               if fit_s and n_changed else None),
        "delta_wall_frac": (round(wall / cold_wall, 4)
                            if cold_wall else None),
        "cold_fit_s": round(cold["fit_s"], 3),
        "cold_publish_s": round(cold["publish_s"], 3),
        "cold_wall_s": round(cold_wall, 3),
        "cold_reused": bool(cold.get("reused")),
        "detect_s": res.get("detect_s"),
        "fit_s": round(fit_s, 3),
        "publish_s": res.get("publish_s"),
        "flip_s": res.get("flip_s"),
        "fit_dispatches": res.get("fit_dispatches"),
        "version": res.get("version"),
        **serve_stats,
    }
    return {
        "metric": (f"delta_{rung.name}_{rung.series}x{rung.timesteps}"
                   "_refit_wall"),
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": 0.0,
        "unix": round(time.time(), 3),
        "extra": extra,
    }


def prepare_cold_registry(rung, cfg, solver, run_dir: str,
                          dset_dir: str, ids,
                          reuse_cold: Optional[str] = None) -> tuple:
    """(registry, cold, catchup) shared by ``bench --delta`` and
    ``bench --freshness``: the cold reference via :func:`cold_base`
    (measured fresh, or reused from ``reuse_cold``), published into a
    fresh registry under ``run_dir``.  When the reused base predates
    deltas already landed on the plane, one UNTIMED warm catch-up
    cycle brings the registry current so the measured sweep starts
    from a warm, current base — the reuse must amortize the cold fit,
    never skew the measured cycles with a backlog."""
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import plane
    from tsspark_tpu.serve.registry import ParamRegistry

    cold = cold_base(rung, cfg, solver, run_dir, dset_dir,
                     reuse_cold=reuse_cold)
    if cold.get("complete") is False:
        return None, cold, None
    registry = ParamRegistry(os.path.join(run_dir, "registry"), cfg)
    t0 = time.time()
    orchestrate.publish_fit_state(
        registry, cold["out_dir"], ids,
        data_stamp=int(cold.get("data_stamp") or 0),
    )
    publish_s = time.time() - t0
    if cold["publish_s"] is None:
        cold["publish_s"] = publish_s
        if reuse_cold:
            save_cold_meta(reuse_cold, {
                "rung": rung.name, "series": rung.series,
                "timesteps": rung.timesteps,
                "fingerprint": plane.dataset_fingerprint(),
                "fit_s": round(cold["fit_s"], 3),
                "publish_s": round(cold["publish_s"], 3),
                "fit_path": cold.get("fit_path"),
                "data_stamp": int(cold.get("data_stamp") or 0),
                "unix": round(time.time(), 3),
            })
    catchup = None
    if plane.delta_seq(dset_dir) > int(cold.get("data_stamp") or 0):
        # Prior sweeps' deltas: refit them untimed so measured cycles
        # see only their own churn.
        catchup = run_refit(
            data_dir=dset_dir, registry=registry,
            scratch=os.path.join(run_dir, "catchup"),
            chunk=rung.chunk,
            solver_config=SolverConfig(max_iters=rung.max_iters),
            warm_start=True,
        )
    return registry, cold, catchup


def run_delta_bench(rung="smoke",
                    churns: Sequence[float] = DEFAULT_CHURNS,
                    data_root: Optional[str] = None,
                    scratch_root: Optional[str] = None,
                    sentinel: Optional[bool] = None,
                    reuse_cold: Optional[str] = None) -> List[Dict]:
    """``bench --delta``: cold-fit one scale-ladder rung, then sweep
    ``churns`` — land a synthetic advance, run one warm delta-refit
    cycle (detect -> fit -> delta publish -> engine-materialized flip),
    and measure the flip-window cache carry-forward.  One bench-family
    ``BENCH_delta_*`` artifact per churn, each judged by the regression
    sentinel.

    The rung's plane dataset lives under a PRIVATE data root (deltas
    mutate landed rows in place; the shared cache's bytes must stay
    bitwise-stable for every other bench).  The cold fit runs in a
    fresh out dir each invocation so ``cold_wall`` is always a real
    measured fit, never a warm resume — UNLESS ``reuse_cold`` names a
    base dir, in which case the recorded cold measurement (and warm
    base) is reused so repeated churn sweeps amortize the cold fit."""
    import tempfile

    from tsspark_tpu import bench_scale
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import plane
    from tsspark_tpu.serve.cache import ForecastCache
    from tsspark_tpu.serve.engine import PredictionEngine

    if isinstance(rung, str):
        rung = bench_scale.RUNGS[rung]
    cfg = bench_scale._config()
    solver = SolverConfig(max_iters=rung.max_iters)
    scratch = os.path.join(
        scratch_root or tempfile.gettempdir(),
        f"tsdelta_{rung.name}_{rung.series}x{rung.timesteps}"
        f"_{plane.dataset_fingerprint()}",
    )
    os.makedirs(scratch, exist_ok=True)
    prev_run = obs.start_run(os.path.join(scratch, "spans.jsonl"))
    reports: List[Dict] = []
    try:
        droot = data_root or (os.path.join(reuse_cold, "plane")
                              if reuse_cold
                              else os.path.join(scratch, "plane"))
        spec = plane.DatasetSpec(
            generator="demo_weekly", n_series=rung.series,
            n_timesteps=rung.timesteps, seed=2,
        )
        dset_dir = plane.ensure(spec, root=droot)
        ids = plane.series_ids(spec)

        # ---- cold reference: resident fit + publish (or reuse) ------
        run_dir = os.path.join(scratch, f"run_{int(time.time())}")
        _sweep_stale_runs(scratch, keep=run_dir)
        registry, cold, _catchup = prepare_cold_registry(
            rung, cfg, solver, run_dir, dset_dir, ids,
            reuse_cold=reuse_cold,
        )
        if registry is None:
            print("[delta] cold fit incomplete; aborting the sweep",
                  file=sys.stderr)
            return [{"complete": False, "stage": "cold-fit"}]
        print(json.dumps({"delta_bench": rung.name,
                          "cold_fit_s": round(cold["fit_s"], 3),
                          "cold_publish_s": round(cold["publish_s"], 3),
                          "cold_reused": bool(cold.get("reused")),
                          "fit_path": cold["fit_path"]}), flush=True)

        # ---- serving side: in-process engine, warm hot set ----------
        sample, _ = bench_scale._request_mix(rung, ids)
        hot = [str(s) for s in sample[:rung.hot]]
        engine = PredictionEngine(registry, cache=ForecastCache())
        engine.materialize(hot, bench_scale.HORIZONS)

        for churn in churns:
            t0 = time.time()
            delta_rec = plane.land_synthetic_delta(dset_dir, churn)
            land_s = time.time() - t0
            # Idempotent re-warm: the flip-window stats must start from
            # a warm steady state, not a cold cache.
            engine.materialize(hot, bench_scale.HORIZONS)
            stats0 = engine.cache.stats()

            def flip_fn(v):
                # The engine analog of the pool's materialize/drain
                # flip: prefetch (plane CRC sweep = page warming),
                # materialize the hot set into the warm window, flip.
                engine.prefetch(v)
                engine.materialize(hot, bench_scale.HORIZONS, version=v)
                registry.activate(v)

            res = run_refit(
                data_dir=dset_dir, registry=registry,
                scratch=os.path.join(run_dir, "refit"),
                chunk=rung.chunk, solver_config=solver,
                warm_start=True, flip_fn=flip_fn,
                horizons=bench_scale.HORIZONS,
            )
            if not res.get("complete"):
                # Same graceful failure as the cold-fit path: record
                # the incomplete cycle instead of crashing the sweep.
                print(f"[delta] churn {churn}: refit cycle incomplete; "
                      f"stopping the sweep", file=sys.stderr)
                reports.append({"complete": False, "stage": "refit",
                                "churn": churn, **res})
                break
            # Flip-window loadgen over the hot set: carried entries
            # serve unchanged series without a dispatch — the hit-rate
            # win partial invalidation buys.
            changed_ids = set(
                (registry.delta_info(res["version"]) or {})
                .get("changed_ids") or ()
            )
            n_req = 0
            for sid in hot:
                engine.forecast([sid], bench_scale.HORIZONS[0])
                n_req += 1
            stats1 = engine.cache.stats()
            d_hits = stats1["hits"] - stats0["hits"]
            d_total = (stats1["hits"] + stats1["misses"]
                       - stats0["hits"] - stats0["misses"])
            serve_stats = {
                "land_s": round(land_s, 3),
                "delta_seq": delta_rec["seq"],
                "cache_carried": stats1["carried"] - stats0["carried"],
                "flip_requests": n_req,
                "flip_hit_rate": (round(d_hits / d_total, 4)
                                  if d_total else None),
                "hot_changed": sum(1 for s in hot if s in changed_ids),
            }
            rep = _delta_report(rung, churn, cold, res, serve_stats,
                                cfg)
            path = (f"BENCH_delta_{rung.name}_c{int(churn * 1000):04d}"
                    f"_{int(rep['unix'])}.json")
            atomic_write(path,
                         lambda fh: json.dump(rep, fh, indent=1),
                         mode="w")
            rep["path"] = path
            print(json.dumps({
                "rung": rung.name, "churn": churn,
                "n_changed": res["n_changed"],
                "delta_wall_s": res["wall_s"],
                "delta_wall_frac": rep["extra"]["delta_wall_frac"],
                "delta_series_per_s":
                    rep["extra"]["delta_series_per_s"],
                "cache_carried": serve_stats["cache_carried"],
                "flip_hit_rate": serve_stats["flip_hit_rate"],
                "report": path,
            }), flush=True)
            if sentinel is None:
                sentinel_on = (os.environ.get("TSSPARK_SENTINEL", "1")
                               != "0")
            else:
                sentinel_on = sentinel
            if sentinel_on:
                try:
                    from tsspark_tpu.obs import regress

                    verdict = regress.sentinel_report(
                        rep, source=path
                    )
                    if verdict is not None:
                        print(f"[delta] {regress.summarize(verdict)}",
                              file=sys.stderr)
                        rep["sentinel_ok"] = verdict["ok"]
                except Exception as e:  # never mask the report
                    print(f"[delta] sentinel skipped: {e!r}",
                          file=sys.stderr)
            reports.append(rep)
        return reports
    finally:
        obs.end_run(prev_run)


# ---------------------------------------------------------------------------
# CLI (python -m tsspark_tpu.refit): one cycle as a killable process
# ---------------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Run one delta-refit cycle (or the churn-sweep bench) as its own
    process — the fault-isolatable unit the refit-kill chaos class
    SIGKILLs mid delta-publish.  Adopts the spawner's trace."""
    from tsspark_tpu.resident import force_virtual_host_mesh

    force_virtual_host_mesh()
    ap = argparse.ArgumentParser(prog="python -m tsspark_tpu.refit")
    ap.add_argument("--data", help="plane dataset dir")
    ap.add_argument("--registry", help="serve registry root")
    ap.add_argument("--scratch", help="refit scratch dir")
    ap.add_argument("--chunk", type=int, default=512)
    ap.add_argument("--max-iters", type=int, default=20)
    ap.add_argument("--phase1-iters", type=int, default=0)
    ap.add_argument("--cold", action="store_true",
                    help="disable the warm start (bitwise the cold "
                         "resident path over the changed set)")
    ap.add_argument("--no-activate", action="store_true",
                    help="publish without flipping (a pool front owns "
                         "the flip)")
    ap.add_argument("--delta-bench", default=None, metavar="RUNG",
                    help="run the churn-fraction sweep at a scale "
                         "rung instead of one cycle")
    ap.add_argument("--churns", default=None,
                    help="comma-separated churn fractions for "
                         "--delta-bench")
    ap.add_argument("--reuse-cold", default=None, metavar="DIR",
                    help="reuse (or record) the cold fit+publish "
                         "reference under DIR so repeated sweeps "
                         "amortize the cold fit")
    args = ap.parse_args(argv)
    obs.adopt_env()
    if args.delta_bench:
        reports = run_delta_bench(args.delta_bench,
                                  churns=parse_churns(args.churns),
                                  reuse_cold=args.reuse_cold)
        return 0 if sweep_ok(reports) else 1
    if not (args.data and args.registry and args.scratch):
        ap.error("--data, --registry and --scratch are required for a "
                 "refit cycle")
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.serve.registry import ParamRegistry

    registry = ParamRegistry.open(args.registry)
    res = run_refit(
        data_dir=args.data, registry=registry, scratch=args.scratch,
        chunk=args.chunk,
        solver_config=SolverConfig(max_iters=args.max_iters),
        phase1_iters=args.phase1_iters,
        warm_start=not args.cold,
        activate=not args.no_activate,
    )
    print(json.dumps(res), flush=True)
    return 0 if res.get("complete") else 1


if __name__ == "__main__":
    sys.exit(main())
