"""Incremental delta-refit engine: refit cost scales with CHANGED series.

PR 12's 1M rung pays ~52 s of resident fit per refresh even when 1% of
the fleet gained a row — every refit today is a cold full-fleet fit.
This module closes ROADMAP item 4's perf core: an always-on loop where
each cycle touches only the series whose DATA actually advanced.

One ``run_refit`` cycle:

1. **detect** — the data plane's row-advance accounting
   (``data.plane.advanced_since``): the active registry version records
   the delta coverage stamp it was fitted at
   (``ParamRegistry.version_stamp``), and the changed set is exactly
   the rows of every delta landed after it.  The set is pinned in an
   atomic ``refit_plan.json`` so a killed cycle's successor refits the
   SAME plan instead of racing fresh deltas mid-flight.
2. **plan + fit** — the changed rows are compacted into a dense
   ``[0, n_changed)`` claim space and run through the PR 11
   mesh-resident path (``tsspark_tpu.resident``) over a gathered spill:
   the SAME ``plan_chunks``/lease/chunk-file machinery, so 10% churn
   produces ~10% of the waves and a SIGKILLed cycle resumes from its
   landed flushes.  Waves are **warm-started** from the active
   snapshot's theta, mmap-gathered per wave off the snapshot plane
   (``warm_theta_gather`` — only the touched pages are read), under the
   recorded PR 11 parity constraints: no buffer donation under
   pipelined overlap, >=2 rows/shard sub-mesh rule, ``use_theta0`` as a
   dynamic arg so warm and cold waves share one compiled program.
   ``warm_start=False`` is bitwise the cold resident path.
3. **delta publish** — ``ParamRegistry.publish_delta`` /
   ``snapplane.write_plane_delta``: the new version's plane
   copy-forwards unchanged rows from the active plane (vectorized
   scatter of the refit rows into a sequential copy; a column no
   changed row lands in — and EVERY column on a zero-delta cycle — is
   hardlinked wholesale, zero new snapshot bytes).
4. **flip** — through the PR 10 materialize/drain path
   (``ReplicaPool.activate`` when a pool is attached, or the engine's
   prefetch/materialize/activate analog), with partial cache
   invalidation: unchanged series' forecast-cache entries carry
   forward to the new version (``ForecastCache.carry_forward``).

``run_delta_bench`` (``bench --delta``) sweeps churn fractions at the
scale-ladder rungs and stamps ``delta_series_per_s`` +
``delta_wall_frac`` (delta cycle wall over the same run's measured cold
fit+publish wall) into bench-family reports the regression sentinel
baselines under ``+delta<churn>``-scoped workload keys.

See docs/PERF.md "Delta refit" for engage rules and reading guidance.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from tsspark_tpu import orchestrate
from tsspark_tpu.obs import context as obs
from tsspark_tpu.utils.atomic import atomic_write

#: The cycle's pinned plan: base version, coverage stamps, the changed
#: row set — replaced atomically, so a successor after a mid-cycle kill
#: resumes exactly this claim set (never a fresh detect that would race
#: deltas landed after the kill).
REFIT_PLAN_FILE = "refit_plan.json"


def warm_theta_gather(theta, idx):
    """Warm-start gather: rows ``idx`` of the active snapshot's theta,
    float32, NaN/inf scrubbed (a warm INIT must never smuggle a poison
    value into the solver — correctness never depends on init quality).

    Host arrays (the snapshot plane's memmap) take the numpy path —
    fancy indexing reads only the touched pages, which is what makes
    the per-wave gather O(wave), not O(fleet).  Traced values take the
    jnp path; the analysis gate's kernel-contract matrix traces this
    function under ``enable_x64`` so an f64 leak in the gather (the
    classic un-pinned-dtype drift) surfaces statically."""
    if isinstance(theta, np.ndarray):
        rows = np.take(np.asarray(theta), np.asarray(idx, np.int64),
                       axis=0)
        return np.nan_to_num(rows).astype(np.float32)
    import jax.numpy as jnp

    rows = jnp.take(jnp.asarray(theta), jnp.asarray(idx), axis=0)
    return jnp.nan_to_num(rows).astype(jnp.float32)


def read_refit_plan(scratch: str) -> Optional[Dict]:
    """The pinned plan in ``scratch``, or None (absent/torn)."""
    try:
        with open(os.path.join(scratch, REFIT_PLAN_FILE)) as fh:
            d = json.load(fh)
        return d if isinstance(d, dict) else None
    except (OSError, ValueError):
        return None


def _write_refit_plan(scratch: str, plan: Dict) -> None:
    atomic_write(
        os.path.join(scratch, REFIT_PLAN_FILE),
        lambda fh: json.dump(plan, fh), mode="w",
    )


def run_refit(
    *,
    data_dir: str,
    registry,
    scratch: str,
    chunk: int = 512,
    solver_config=None,
    phase1_iters: int = 0,
    no_phase1_tune: bool = True,
    warm_start: bool = True,
    pool=None,
    hot_series: Optional[Sequence[str]] = None,
    horizons: Sequence[int] = (7, 14),
    activate: bool = True,
    flip_fn: Optional[Callable[[int], None]] = None,
    deadline: Optional[float] = None,
) -> Dict:
    """One delta-refit cycle: detect -> warm resident fit over the
    changed set -> copy-forward delta publish -> flip.  Returns the
    cycle's metrics dict (versions, per-stage walls, dispatch count).

    ``registry`` is an attached ``ParamRegistry`` with an ACTIVE
    version whose snapshot plane exists (the warm-start source and the
    copy-forward base).  ``scratch`` persists across cycles: the
    current plan plus a per-(base-version, stamp) cycle dir whose chunk
    files make a killed cycle resumable.  The flip goes through
    ``pool.activate`` (the PR 10 materialize/drain path) when a pool is
    attached, else ``flip_fn`` when given, else ``registry.activate``;
    ``activate=False`` publishes without flipping (the chaos child —
    the harness's front owns the flip).

    Zero-delta fast path: no advanced series -> zero fit dispatches,
    a fully-hardlinked version (zero new snapshot bytes), and the
    serving side keeps returning bitwise-identical forecasts.
    """
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import plane
    from tsspark_tpu.serve import snapplane

    t_cycle0 = time.time()
    os.makedirs(scratch, exist_ok=True)
    if solver_config is None:
        solver_config = SolverConfig()
    base_version = registry.active_version()
    if base_version is None:
        from tsspark_tpu.serve.registry import RegistryError

        raise RegistryError("no-active-version",
                            "delta refit needs an active base version")

    # ---- detect: pin (or resume) the plan ---------------------------
    t0 = time.time()
    plan = read_refit_plan(scratch)
    resumed = bool(plan is not None and not plan.get("complete")
                   and plan.get("base_version") == int(base_version))
    if not resumed:
        base_stamp = registry.version_stamp(int(base_version))
        plan_stamp = plane.delta_seq(data_dir)
        changed = plane.advanced_since(data_dir, base_stamp)
        plan = {
            "base_version": int(base_version),
            "base_stamp": int(base_stamp),
            "plan_stamp": int(plan_stamp),
            "n_changed": int(len(changed)),
            "changed_rows": [int(r) for r in changed.tolist()],
            "complete": False,
        }
        _write_refit_plan(scratch, plan)
    changed = np.asarray(plan["changed_rows"], np.int64)
    n_changed = int(plan["n_changed"])
    detect_s = time.time() - t0
    obs.record("refit.detect", t0, detect_s, n_changed=n_changed,
               base_version=int(base_version), resumed=resumed)

    cycle_dir = os.path.join(
        scratch,
        f"cycle_v{plan['base_version']:06d}_s{plan['plan_stamp']:06d}",
    )
    result: Dict = {
        "base_version": int(base_version),
        "base_stamp": plan["base_stamp"],
        "plan_stamp": plan["plan_stamp"],
        "n_changed": n_changed,
        "resumed": resumed,
        "warm_start": bool(warm_start),
        "detect_s": round(detect_s, 3),
        "fit_dispatches": 0,
        "fit_s": 0.0,
    }

    state_sub = None
    step_sub = None
    if n_changed:
        # ---- fit: compacted claim space through the resident path ---
        ddir = os.path.join(cycle_dir, "delta_data")
        out_dir = os.path.join(cycle_dir, "out")
        os.makedirs(out_dir, exist_ok=True)
        # Gate on the PLAN's spilled flag, not file presence: each spill
        # file is individually atomic but the set is not — a kill
        # between columns would leave ds.npy without mask.npy, and a
        # presence check would resume against half a gather.  Re-spilling
        # before the flag is safe (no chunk file can exist yet).
        if not plan.get("spilled"):
            batch = plane.open_batch(data_dir)
            sub = lambda a: (None if a is None
                             else np.ascontiguousarray(a[changed]))
            orchestrate.spill_data(
                ddir, np.asarray(batch.ds), sub(batch.y),
                mask=sub(batch.mask), regressors=sub(batch.regressors),
                cap=sub(batch.cap),
            )
            plan = dict(plan, spilled=True)
            _write_refit_plan(scratch, plan)
        orchestrate.save_run_config(out_dir, registry.config,
                                    solver_config)

        theta0_fn = None
        base_view = None
        base_vdir = registry.version_dir(int(base_version))
        if warm_start:
            try:
                # verify=False: the registry CRC-swept this plane when
                # it was loaded for serving; a warm INIT cannot affect
                # correctness (warm_theta_gather scrubs non-finite
                # values), so the refit skips a second full sweep.
                base_view = snapplane.attach(base_vdir, verify=False)
            except snapplane.SnapshotPlaneError:
                import warnings

                warnings.warn(
                    f"refit: base version {base_version} has no "
                    "readable snapshot plane; warm start disabled for "
                    "this cycle (cold ridge init — results stay "
                    "correct, the warm-start perf lever is lost)",
                    RuntimeWarning,
                )
        if base_view is not None:
            theta_mm = base_view.state.theta

            def theta0_fn(lo, hi):
                # Per-wave mmap gather: base rows of this wave's slice
                # of the compacted changed set — touched pages only.
                return warm_theta_gather(theta_mm, changed[lo:hi])

        from tsspark_tpu import resident

        chunks_before = len(orchestrate.completed_ranges(out_dir))
        t0 = time.time()
        fit_state = resident.run_resident(
            data_dir=ddir, out_dir=out_dir, series=n_changed,
            chunk=int(chunk), phase1_iters=phase1_iters,
            no_phase1_tune=no_phase1_tune, autotune=False,
            deadline=deadline, theta0_fn=theta0_fn,
        )
        result["fit_s"] = round(time.time() - t0, 3)
        result["fit_path"] = fit_state.get("fit_path")
        result["fit_dispatches"] = (
            len(orchestrate.completed_ranges(out_dir)) - chunks_before
        )
        if not fit_state.get("complete"):
            result["complete"] = False
            result["wall_s"] = round(time.time() - t_cycle0, 3)
            return result
        state_sub = orchestrate.load_fit_state(out_dir, n_changed)
        if base_view is not None and "step" in base_view.extras:
            step_sub = np.asarray(
                base_view.extras["step"][changed], np.float64
            )

    # ---- delta publish: copy-forward + scatter ----------------------
    t0 = time.time()
    v_new = registry.publish_delta(
        state_sub, changed, base_version=int(base_version),
        step_sub=step_sub, data_stamp=plan["plan_stamp"],
        activate=False,
    )
    result["version"] = int(v_new)
    result["publish_s"] = round(time.time() - t0, 3)

    # ---- flip: PR 10 materialize/drain ------------------------------
    t0 = time.time()
    if pool is not None:
        pool.activate(v_new, hot_series=list(hot_series or ()),
                      horizons=tuple(horizons))
    elif flip_fn is not None:
        flip_fn(int(v_new))
    elif activate:
        registry.activate(int(v_new))
    result["flip_s"] = round(time.time() - t0, 3)
    result["flipped"] = bool(pool is not None or flip_fn is not None
                             or activate)

    plan = dict(plan, complete=True, published_version=int(v_new))
    _write_refit_plan(scratch, plan)
    # Completed cycle dirs are dead weight (the plan is done); reap
    # every cycle dir, including this one — the next cycle keys a new
    # one off its own (base version, stamp).
    for name in os.listdir(scratch):
        if name.startswith("cycle_"):
            shutil.rmtree(os.path.join(scratch, name),
                          ignore_errors=True)
    result["complete"] = True
    result["wall_s"] = round(time.time() - t_cycle0, 3)
    obs.record("refit.cycle", t_cycle0, result["wall_s"],
               n_changed=n_changed, version=result.get("version"),
               warm_start=bool(warm_start))
    return result


# ---------------------------------------------------------------------------
# bench --delta: the churn-fraction sweep
# ---------------------------------------------------------------------------

#: Churn fractions ``bench --delta`` sweeps by default.
DEFAULT_CHURNS = (0.01, 0.1, 0.3)


def parse_churns(spec: Optional[str]):
    """Churn fractions from a ``--churns`` CLI string (None -> the
    defaults).  ONE parser for both entry points (bench.py --delta and
    python -m tsspark_tpu.refit --delta-bench)."""
    if not spec:
        return DEFAULT_CHURNS
    return tuple(float(c) for c in spec.split(","))


def sweep_ok(reports: Sequence[Dict]) -> bool:
    """The sweep's pass/fail contract — every cycle complete AND
    sentinel-green — reduced in ONE place so the two entry points'
    exit codes can never diverge.  Success reports are bench-shaped
    (``complete`` lives under ``extra``); failure records carry it at
    the top level — accept both, and an EMPTY sweep is a failure."""
    if not reports:
        return False
    return all(
        bool(r.get("complete", (r.get("extra") or {}).get("complete")))
        and r.get("sentinel_ok", True)
        for r in reports
    )


#: A delta-bench run tree untouched this long is reaped on the next
#: sweep: each invocation keys a fresh ``run_<unix>`` dir (the cold
#: fit must be a real measurement, never a warm resume), so without an
#: age gate repeated sweeps accumulate rung-sized chunk/registry trees
#: forever.
STALE_RUN_S = 6 * 3600.0


def _sweep_stale_runs(scratch: str, keep: str,
                      max_age_s: float = STALE_RUN_S) -> int:
    removed = 0
    try:
        names = os.listdir(scratch)
    except OSError:
        return 0
    for name in names:
        d = os.path.join(scratch, name)
        if (not name.startswith("run_") or not os.path.isdir(d)
                or os.path.abspath(d) == os.path.abspath(keep)):
            continue
        try:
            import glob as glob_mod

            newest = max(
                (os.path.getmtime(p) for p in
                 glob_mod.glob(os.path.join(d, "**"), recursive=True)),
                default=os.path.getmtime(d),
            )
        except OSError:
            continue
        if time.time() - newest > max_age_s:
            shutil.rmtree(d, ignore_errors=True)
            removed += 1
    return removed


def _delta_report(rung, churn: float, cold: Dict, res: Dict,
                  serve_stats: Dict, cfg) -> Dict:
    """One bench-family report per (rung, churn): the regression
    sentinel keys its workload ``...+delta<churn>`` (obs.history), so
    delta cycles are never baselined against cold fits."""
    import jax

    from tsspark_tpu.config import NUMERICS_REV
    from tsspark_tpu.obs.history import git_rev
    from tsspark_tpu.utils import checkpoint as ckpt

    n_changed = res["n_changed"]
    fit_s = res.get("fit_s") or 0.0
    wall = res["wall_s"]
    cold_wall = cold["fit_s"] + cold["publish_s"]
    extra = {
        "trace_id": obs.trace_id(),
        "numerics_rev": NUMERICS_REV,
        "git_rev": git_rev(),
        "config_fingerprint": ckpt.config_fingerprint(cfg),
        "device": str(jax.devices()[0]),
        "complete": bool(res.get("complete")),
        "fit_path": res.get("fit_path", "resident"),
        "warm_start": res.get("warm_start"),
        "delta_churn": churn,
        "n_changed": n_changed,
        "series_done": n_changed,
        "series_total": rung.series,
        "delta_series_per_s": (round(n_changed / fit_s, 2)
                               if fit_s and n_changed else None),
        "delta_wall_frac": (round(wall / cold_wall, 4)
                            if cold_wall else None),
        "cold_fit_s": round(cold["fit_s"], 3),
        "cold_publish_s": round(cold["publish_s"], 3),
        "cold_wall_s": round(cold_wall, 3),
        "detect_s": res.get("detect_s"),
        "fit_s": round(fit_s, 3),
        "publish_s": res.get("publish_s"),
        "flip_s": res.get("flip_s"),
        "fit_dispatches": res.get("fit_dispatches"),
        "version": res.get("version"),
        **serve_stats,
    }
    return {
        "metric": (f"delta_{rung.name}_{rung.series}x{rung.timesteps}"
                   "_refit_wall"),
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": 0.0,
        "unix": round(time.time(), 3),
        "extra": extra,
    }


def run_delta_bench(rung="smoke",
                    churns: Sequence[float] = DEFAULT_CHURNS,
                    data_root: Optional[str] = None,
                    scratch_root: Optional[str] = None,
                    sentinel: Optional[bool] = None) -> List[Dict]:
    """``bench --delta``: cold-fit one scale-ladder rung, then sweep
    ``churns`` — land a synthetic advance, run one warm delta-refit
    cycle (detect -> fit -> delta publish -> engine-materialized flip),
    and measure the flip-window cache carry-forward.  One bench-family
    ``BENCH_delta_*`` artifact per churn, each judged by the regression
    sentinel.

    The rung's plane dataset lives under a PRIVATE data root (deltas
    mutate landed rows in place; the shared cache's bytes must stay
    bitwise-stable for every other bench).  The cold fit runs in a
    fresh out dir each invocation so ``cold_wall`` is always a real
    measured fit, never a warm resume."""
    import tempfile

    from tsspark_tpu import bench_scale, resident
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import plane
    from tsspark_tpu.serve.cache import ForecastCache
    from tsspark_tpu.serve.engine import PredictionEngine
    from tsspark_tpu.serve.registry import ParamRegistry

    if isinstance(rung, str):
        rung = bench_scale.RUNGS[rung]
    cfg = bench_scale._config()
    solver = SolverConfig(max_iters=rung.max_iters)
    scratch = os.path.join(
        scratch_root or tempfile.gettempdir(),
        f"tsdelta_{rung.name}_{rung.series}x{rung.timesteps}"
        f"_{plane.dataset_fingerprint()}",
    )
    os.makedirs(scratch, exist_ok=True)
    prev_run = obs.start_run(os.path.join(scratch, "spans.jsonl"))
    reports: List[Dict] = []
    try:
        droot = data_root or os.path.join(scratch, "plane")
        spec = plane.DatasetSpec(
            generator="demo_weekly", n_series=rung.series,
            n_timesteps=rung.timesteps, seed=2,
        )
        dset_dir = plane.ensure(spec, root=droot)
        ids = plane.series_ids(spec)

        # ---- cold reference: resident fit + publish, fresh out dir --
        run_dir = os.path.join(scratch, f"run_{int(time.time())}")
        _sweep_stale_runs(scratch, keep=run_dir)
        out_dir = os.path.join(run_dir, "cold_out")
        os.makedirs(out_dir, exist_ok=True)
        orchestrate.save_run_config(out_dir, cfg, solver)
        t0 = time.time()
        cold_state = resident.run_resident(
            data_dir=dset_dir, out_dir=out_dir, series=rung.series,
            chunk=rung.chunk, phase1_iters=0, no_phase1_tune=True,
        )
        cold_fit_s = time.time() - t0
        if not cold_state.get("complete"):
            print("[delta] cold fit incomplete; aborting the sweep",
                  file=sys.stderr)
            return [{"complete": False, "stage": "cold-fit"}]
        registry = ParamRegistry(os.path.join(run_dir, "registry"), cfg)
        t0 = time.time()
        orchestrate.publish_fit_state(
            registry, out_dir, ids,
            data_stamp=plane.delta_seq(dset_dir),
        )
        cold = {"fit_s": cold_fit_s, "publish_s": time.time() - t0,
                "fit_path": cold_state.get("fit_path")}
        print(json.dumps({"delta_bench": rung.name,
                          "cold_fit_s": round(cold_fit_s, 3),
                          "cold_publish_s": round(cold["publish_s"], 3),
                          "fit_path": cold["fit_path"]}), flush=True)

        # ---- serving side: in-process engine, warm hot set ----------
        sample, _ = bench_scale._request_mix(rung, ids)
        hot = [str(s) for s in sample[:rung.hot]]
        engine = PredictionEngine(registry, cache=ForecastCache())
        engine.materialize(hot, bench_scale.HORIZONS)

        for churn in churns:
            t0 = time.time()
            delta_rec = plane.land_synthetic_delta(dset_dir, churn)
            land_s = time.time() - t0
            # Idempotent re-warm: the flip-window stats must start from
            # a warm steady state, not a cold cache.
            engine.materialize(hot, bench_scale.HORIZONS)
            stats0 = engine.cache.stats()

            def flip_fn(v):
                # The engine analog of the pool's materialize/drain
                # flip: prefetch (plane CRC sweep = page warming),
                # materialize the hot set into the warm window, flip.
                engine.prefetch(v)
                engine.materialize(hot, bench_scale.HORIZONS, version=v)
                registry.activate(v)

            res = run_refit(
                data_dir=dset_dir, registry=registry,
                scratch=os.path.join(run_dir, "refit"),
                chunk=rung.chunk, solver_config=solver,
                warm_start=True, flip_fn=flip_fn,
                horizons=bench_scale.HORIZONS,
            )
            if not res.get("complete"):
                # Same graceful failure as the cold-fit path: record
                # the incomplete cycle instead of crashing the sweep.
                print(f"[delta] churn {churn}: refit cycle incomplete; "
                      f"stopping the sweep", file=sys.stderr)
                reports.append({"complete": False, "stage": "refit",
                                "churn": churn, **res})
                break
            # Flip-window loadgen over the hot set: carried entries
            # serve unchanged series without a dispatch — the hit-rate
            # win partial invalidation buys.
            changed_ids = set(
                (registry.delta_info(res["version"]) or {})
                .get("changed_ids") or ()
            )
            n_req = 0
            for sid in hot:
                engine.forecast([sid], bench_scale.HORIZONS[0])
                n_req += 1
            stats1 = engine.cache.stats()
            d_hits = stats1["hits"] - stats0["hits"]
            d_total = (stats1["hits"] + stats1["misses"]
                       - stats0["hits"] - stats0["misses"])
            serve_stats = {
                "land_s": round(land_s, 3),
                "delta_seq": delta_rec["seq"],
                "cache_carried": stats1["carried"] - stats0["carried"],
                "flip_requests": n_req,
                "flip_hit_rate": (round(d_hits / d_total, 4)
                                  if d_total else None),
                "hot_changed": sum(1 for s in hot if s in changed_ids),
            }
            rep = _delta_report(rung, churn, cold, res, serve_stats,
                                cfg)
            path = (f"BENCH_delta_{rung.name}_c{int(churn * 1000):04d}"
                    f"_{int(rep['unix'])}.json")
            atomic_write(path,
                         lambda fh: json.dump(rep, fh, indent=1),
                         mode="w")
            rep["path"] = path
            print(json.dumps({
                "rung": rung.name, "churn": churn,
                "n_changed": res["n_changed"],
                "delta_wall_s": res["wall_s"],
                "delta_wall_frac": rep["extra"]["delta_wall_frac"],
                "delta_series_per_s":
                    rep["extra"]["delta_series_per_s"],
                "cache_carried": serve_stats["cache_carried"],
                "flip_hit_rate": serve_stats["flip_hit_rate"],
                "report": path,
            }), flush=True)
            if sentinel is None:
                sentinel_on = (os.environ.get("TSSPARK_SENTINEL", "1")
                               != "0")
            else:
                sentinel_on = sentinel
            if sentinel_on:
                try:
                    from tsspark_tpu.obs import regress

                    verdict = regress.sentinel_report(
                        rep, source=path
                    )
                    if verdict is not None:
                        print(f"[delta] {regress.summarize(verdict)}",
                              file=sys.stderr)
                        rep["sentinel_ok"] = verdict["ok"]
                except Exception as e:  # never mask the report
                    print(f"[delta] sentinel skipped: {e!r}",
                          file=sys.stderr)
            reports.append(rep)
        return reports
    finally:
        obs.end_run(prev_run)


# ---------------------------------------------------------------------------
# CLI (python -m tsspark_tpu.refit): one cycle as a killable process
# ---------------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Run one delta-refit cycle (or the churn-sweep bench) as its own
    process — the fault-isolatable unit the refit-kill chaos class
    SIGKILLs mid delta-publish.  Adopts the spawner's trace."""
    from tsspark_tpu.resident import force_virtual_host_mesh

    force_virtual_host_mesh()
    ap = argparse.ArgumentParser(prog="python -m tsspark_tpu.refit")
    ap.add_argument("--data", help="plane dataset dir")
    ap.add_argument("--registry", help="serve registry root")
    ap.add_argument("--scratch", help="refit scratch dir")
    ap.add_argument("--chunk", type=int, default=512)
    ap.add_argument("--max-iters", type=int, default=20)
    ap.add_argument("--phase1-iters", type=int, default=0)
    ap.add_argument("--cold", action="store_true",
                    help="disable the warm start (bitwise the cold "
                         "resident path over the changed set)")
    ap.add_argument("--no-activate", action="store_true",
                    help="publish without flipping (a pool front owns "
                         "the flip)")
    ap.add_argument("--delta-bench", default=None, metavar="RUNG",
                    help="run the churn-fraction sweep at a scale "
                         "rung instead of one cycle")
    ap.add_argument("--churns", default=None,
                    help="comma-separated churn fractions for "
                         "--delta-bench")
    args = ap.parse_args(argv)
    obs.adopt_env()
    if args.delta_bench:
        reports = run_delta_bench(args.delta_bench,
                                  churns=parse_churns(args.churns))
        return 0 if sweep_ok(reports) else 1
    if not (args.data and args.registry and args.scratch):
        ap.error("--data, --registry and --scratch are required for a "
                 "refit cycle")
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.serve.registry import ParamRegistry

    registry = ParamRegistry.open(args.registry)
    res = run_refit(
        data_dir=args.data, registry=registry, scratch=args.scratch,
        chunk=args.chunk,
        solver_config=SolverConfig(max_iters=args.max_iters),
        phase1_iters=args.phase1_iters,
        warm_start=not args.cold,
        activate=not args.no_activate,
    )
    print(json.dumps(res), flush=True)
    return 0 if res.get("complete") else 1


if __name__ == "__main__":
    sys.exit(main())
