"""Configuration dataclasses for the TPU-native Prophet-family framework.

Design notes
------------
All configs are frozen dataclasses so they can be used as static (hashable)
arguments to ``jax.jit``.  Anything that changes array *shapes* (number of
changepoints, Fourier orders, regressor count, horizon) lives here and is
static; anything that is a *value* (prior scales, caps) is carried as data so
re-fits with different regularization do not trigger recompilation.

Reference parity: mirrors the knobs of the reference's ``tsspark.fit.prophet``
module as described by the driver north star (BASELINE.json:5 — changepoint
prior regularization, holiday/external regressors, logistic growth caps,
additive and multiplicative seasonality).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

#: THE fit-numerics revision, shared by every consumer that must refuse
#: to mix parameters fitted under different numerics regimes: bench.py's
#: resumable scratch fingerprint and the serve registry's manifest guard
#: (serve/registry.py) both read this one constant, so the two can never
#: drift apart.  Bump when a model/solver/backend change alters fit
#: NUMERICS (solver args, phase policy, data handling); orchestration-
#: only changes (probing, retries, logging) must NOT bump it — resume
#: state and published registries survive them by design.
#: rev 7: the online chunk autotuner varies chunk widths mid-run, which
#: changes the chunk the adaptive phase-1 depth observes.
NUMERICS_REV = 7


@dataclasses.dataclass(frozen=True)
class SeasonalityConfig:
    """One Fourier seasonality block (e.g. yearly / weekly / daily).

    period is in days (Prophet convention); fourier_order K produces 2K
    feature columns sin(2*pi*n*t/period), cos(2*pi*n*t/period) for n=1..K.
    """

    name: str
    period: float
    fourier_order: int
    prior_scale: float = 10.0
    mode: str = "additive"  # "additive" | "multiplicative"
    # Conditional seasonality (Prophet's condition_name): the block's feature
    # columns are zeroed on rows where the named boolean condition is False,
    # so the component only acts (and is only fit) where the condition holds
    # (e.g. "on_season", "is_weekend").  Condition values are per-(series,
    # timestamp) data supplied at fit/predict time.
    condition_name: Optional[str] = None

    def __post_init__(self):
        if self.fourier_order < 1:
            raise ValueError(f"fourier_order must be >= 1, got {self.fourier_order}")
        if self.period <= 0:
            raise ValueError(f"period must be > 0, got {self.period}")
        if self.mode not in ("additive", "multiplicative"):
            raise ValueError(f"mode must be additive|multiplicative, got {self.mode}")

    @property
    def num_features(self) -> int:
        return 2 * self.fourier_order


@dataclasses.dataclass(frozen=True)
class RegressorConfig:
    """An external regressor column (includes holiday indicator columns)."""

    name: str
    prior_scale: float = 10.0
    standardize: bool = True
    mode: str = "additive"

    def __post_init__(self):
        if self.mode not in ("additive", "multiplicative"):
            raise ValueError(f"mode must be additive|multiplicative, got {self.mode}")


# Default seasonalities mirroring Prophet's auto-seasonality choices.
YEARLY = SeasonalityConfig("yearly", period=365.25, fourier_order=10)
WEEKLY = SeasonalityConfig("weekly", period=7.0, fourier_order=3)
DAILY = SeasonalityConfig("daily", period=1.0, fourier_order=4)


@dataclasses.dataclass(frozen=True)
class ProphetConfig:
    """Full model specification for a (batch of) Prophet-style fit(s).

    One config describes every series in a batch; per-series data (caps,
    changepoint locations, masks) is carried in the design matrices.
    """

    growth: str = "linear"  # "linear" | "logistic" | "flat"
    n_changepoints: int = 25
    changepoint_range: float = 0.8
    changepoint_prior_scale: float = 0.05
    # "uniform": even grid over the observed span (identical to quantiles on
    # regular grids, zero gathers).  "quantile": observed-timestamp order
    # statistics per series (Prophet's placement) — use for irregular grids.
    changepoint_placement: str = "uniform"
    # Explicit changepoint locations in absolute days (Prophet's
    # ``changepoints=`` constructor arg; Forecaster converts datetimes).
    # When set, overrides placement and n_changepoints (forced to its
    # length); locations are shared across the batch in absolute time and
    # land at per-series scaled positions via each series' own span.
    changepoints: Optional[Tuple[float, ...]] = None
    seasonalities: Tuple[SeasonalityConfig, ...] = (YEARLY, WEEKLY)
    regressors: Tuple[RegressorConfig, ...] = ()
    seasonality_mode: str = "additive"  # default mode for seasonalities
    interval_width: float = 0.8
    uncertainty_samples: int = 256
    # Prior scales for the base trend params (Prophet uses 5.0 in its Stan model).
    k_prior_scale: float = 5.0
    m_prior_scale: float = 5.0
    sigma_prior_scale: float = 0.5  # half-normal scale on observation noise

    def __post_init__(self):
        if self.changepoints is not None:
            cps = tuple(sorted(float(c) for c in self.changepoints))
            object.__setattr__(self, "changepoints", cps)
            object.__setattr__(self, "n_changepoints", len(cps))
        if self.growth not in ("linear", "logistic", "flat"):
            raise ValueError(f"growth must be linear|logistic|flat, got {self.growth}")
        if self.changepoint_placement not in ("uniform", "quantile"):
            raise ValueError(
                "changepoint_placement must be uniform|quantile, "
                f"got {self.changepoint_placement}"
            )
        if not 0.0 < self.changepoint_range <= 1.0:
            raise ValueError("changepoint_range must be in (0, 1]")
        if self.n_changepoints < 0:
            raise ValueError("n_changepoints must be >= 0")
        if self.seasonality_mode not in ("additive", "multiplicative"):
            raise ValueError("seasonality_mode must be additive|multiplicative")
        names = [s.name for s in self.seasonalities] + [r.name for r in self.regressors]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate seasonality/regressor names: {names}")

    # ---- chainable builders (Prophet's add_seasonality/add_regressor) --------

    def with_seasonality(
        self,
        name: str,
        period: float,
        fourier_order: int,
        prior_scale: float = 10.0,
        mode: Optional[str] = None,
        condition_name: Optional[str] = None,
    ) -> "ProphetConfig":
        """Config with one more seasonality — the immutable counterpart of
        Prophet's ``m.add_seasonality(...)``.  ``mode=None`` inherits
        ``seasonality_mode``.  Chainable; duplicate names raise via
        __post_init__."""
        s = SeasonalityConfig(
            name, period, fourier_order, prior_scale=prior_scale,
            mode=mode or self.seasonality_mode,
            condition_name=condition_name,
        )
        return dataclasses.replace(
            self, seasonalities=self.seasonalities + (s,)
        )

    def with_regressor(
        self,
        name: str,
        prior_scale: float = 10.0,
        standardize: bool = True,
        mode: str = "additive",
    ) -> "ProphetConfig":
        """Config with one more external regressor (Prophet's
        ``m.add_regressor(...)``).  Chainable."""
        r = RegressorConfig(
            name, prior_scale=prior_scale, standardize=standardize, mode=mode
        )
        return dataclasses.replace(self, regressors=self.regressors + (r,))

    # ---- static shape helpers -------------------------------------------------

    @property
    def condition_names(self) -> Tuple[str, ...]:
        """Unique condition names used by conditional seasonalities, in
        first-appearance order."""
        seen = []
        for s in self.seasonalities:
            if s.condition_name and s.condition_name not in seen:
                seen.append(s.condition_name)
        return tuple(seen)

    @property
    def num_seasonal_features(self) -> int:
        return sum(s.num_features for s in self.seasonalities)

    @property
    def num_regressors(self) -> int:
        return len(self.regressors)

    @property
    def num_features(self) -> int:
        """Total beta dimension: seasonal Fourier columns + regressor columns."""
        return self.num_seasonal_features + self.num_regressors

    def feature_modes(self) -> Tuple[bool, ...]:
        """Per-feature flag: True if the column is multiplicative."""
        modes = []
        for s in self.seasonalities:
            modes.extend([s.mode == "multiplicative"] * s.num_features)
        for r in self.regressors:
            modes.append(r.mode == "multiplicative")
        return tuple(modes)

    def feature_prior_scales(self) -> Tuple[float, ...]:
        scales = []
        for s in self.seasonalities:
            scales.extend([s.prior_scale] * s.num_features)
        for r in self.regressors:
            scales.append(r.prior_scale)
        return tuple(scales)

    @property
    def num_params(self) -> int:
        """Flat parameter vector length: k, m, log_sigma, delta, beta."""
        return 3 + self.n_changepoints + self.num_features


@dataclasses.dataclass(frozen=True)
class SolverConfig:
    """Batched L-BFGS MAP solver settings (see ops/lbfgs.py)."""

    max_iters: int = 200
    history: int = 10  # L-BFGS memory
    tol: float = 2e-9  # relative objective-decrease tolerance (scipy's ftol)
    # Consecutive sub-tol iterations required before ftol ends a series: a
    # single microscopic accepted step is indistinguishable from a stuck
    # line search (measured on eval config 3: every holdout-tail outlier
    # was a single-shot ftol exit at 2-3 iterations, up to 5.5 nats above
    # the oracle's optimum — see ops/lbfgs.py).
    ftol_patience: int = 2
    gtol: float = 1e-6  # gradient-inf-norm convergence tolerance
    ls_max_steps: int = 20  # line-search step-ladder size (one fan eval)
    ls_shrink: float = 0.5
    ls_armijo_c1: float = 1e-4
    ls_seed_prev: bool = True  # seed each ladder from the last accepted step
    init_step: float = 1.0
    # Float32 noise-floor detection: a series whose accepted relative
    # objective decrease stays below floor_ulps machine epsilons for
    # floor_patience consecutive iterations is stationary in this precision
    # (gtol may be unreachable for it) and is marked converged with
    # status=STATUS_FLOOR instead of burning the remaining budget.
    floor_ulps: float = 8.0
    floor_patience: int = 3
    # Warm start: "ridge" solves the batched masked normal equations in
    # closed form (models/prophet/init.py) so L-BFGS starts next to the
    # optimum; "heuristic" is Prophet's endpoint initializer.
    init: str = "ridge"
    # Initial L-BFGS metric: "gn_diag" preconditions with the inverse
    # Gauss-Newton diagonal at theta0 (models/prophet/init.curvature_diag).
    # "auto" (default) currently resolves to "gn_diag" for every growth
    # mode on full-depth solves — measured round 4 on the M5 eval config
    # (609 series vs the scipy oracle): GN-primary + rescue cuts the
    # holdout-parity tail p99 0.86 -> 0.58 sMAPE at equal wall, and on
    # logistic growth the plain metric loses ~1 nat/series at the same
    # depth (mean gap +0.52 -> -0.95 after the switch).  The one place the
    # plain metric still wins is SHORT-depth lockstep passes (GN roughly
    # halves the fraction converged by iteration 12 on the well-ridge-
    # initialized majority), which is why the two-phase bench pins its
    # phase-1 to the plain metric and phase-2 to "gn_diag" via the traced
    # solver switches rather than relying on this default.
    precond: str = "auto"

    def __post_init__(self):
        if self.init not in ("ridge", "heuristic"):
            raise ValueError(f"init must be ridge|heuristic, got {self.init}")
        if self.precond not in ("gn_diag", "none", "auto"):
            raise ValueError(
                f"precond must be gn_diag|none|auto, got {self.precond}"
            )

    def resolved_precond(self, growth: str) -> str:
        """Concrete initial-metric choice for a model's growth mode."""
        if self.precond != "auto":
            return self.precond
        del growth  # measured best for every growth mode (see above)
        return "gn_diag"


@dataclasses.dataclass(frozen=True)
class McmcConfig:
    """Batched HMC full-posterior sampling settings (see ops/hmc.py).

    The TPU analog of upstream Prophet's ``mcmc_samples=N`` Stan/NUTS path:
    one chain per series, all chains advanced in lockstep.
    """

    num_samples: int = 300
    num_warmup: int = 300
    num_leapfrog: int = 24
    # 0.9 (vs Stan's 0.8 default): the observation-noise tail has funnel-like
    # curvature, and with thousands of lockstep chains the frozen post-warmup
    # step must leave headroom or a few chains land stuck in divergence
    # regions.  The smaller step is cheap for these low-dim posteriors.
    target_accept: float = 0.9
    init_step_size: float = 0.1
    step_jitter: float = 0.2       # multiplicative leapfrog step-size jitter
    init_jitter: float = 0.01      # N(0, .) jitter on the MAP init per chain
    divergence_threshold: float = 1000.0  # energy error treated as divergent

    def __post_init__(self):
        if self.num_samples < 1 or self.num_warmup < 2:
            raise ValueError("num_samples >= 1 and num_warmup >= 2 required")
        if not 0.0 < self.target_accept < 1.0:
            raise ValueError("target_accept must be in (0, 1)")
        if self.num_leapfrog < 1:
            raise ValueError("num_leapfrog must be >= 1")


@dataclasses.dataclass(frozen=True)
class AdviConfig:
    """Batched mean-field ADVI settings (see uncertainty/advi.py).

    The cheap member of the uncertainty ladder (MAP < ADVI < NUTS): a
    diagonal-Gaussian posterior per series, fitted by maximizing a
    reparameterized ELBO over the same padded (n_series, n_timesteps)
    design tensors the L-BFGS MAP solve runs on, all series in
    lockstep.  "Going NUTS with ADVI" (PAPERS.md) measures ADVI
    intervals at NUTS quality for this model family at a fraction of
    the cost, which is why it is the default served tier and NUTS is
    the sampled gold audit.
    """

    num_steps: int = 200
    num_elbo_samples: int = 4      # MC samples per ELBO gradient step
    learning_rate: float = 0.05    # Adam step size
    init_rho: float = -3.0         # initial log-stddev (softplus-free)
    adam_b1: float = 0.9
    adam_b2: float = 0.999
    adam_eps: float = 1e-8

    def __post_init__(self):
        if self.num_steps < 1:
            raise ValueError("num_steps must be >= 1")
        if self.num_elbo_samples < 1:
            raise ValueError("num_elbo_samples must be >= 1")
        if self.learning_rate <= 0.0:
            raise ValueError("learning_rate must be > 0")
        if not 0.0 <= self.adam_b1 < 1.0 or not 0.0 <= self.adam_b2 < 1.0:
            raise ValueError("adam betas must be in [0, 1)")


@dataclasses.dataclass(frozen=True)
class ShardingConfig:
    """How a fit batch is laid out over a jax.sharding.Mesh.

    series_axis shards the embarrassingly-parallel series batch (the analog of
    the reference's Spark ``mapPartitions`` fan-out); time_axis optionally
    shards long series over chips (sequence parallelism: loss/grad reductions
    over time become psums over the time axis).
    """

    series_axis: str = "series"
    time_axis: Optional[str] = None
    donate_params: bool = True
