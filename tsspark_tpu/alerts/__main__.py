"""CLI: ``python -m tsspark_tpu.alerts`` — the killable alert scorer.

Two modes:

* ``--bench RUNG`` — the land→alert freshness bench
  (:mod:`tsspark_tpu.alerts.bench`).
* drive mode (``--data/--registry/--alerts-dir``) — run the scorer as
  its own process over an existing plane dataset + registry: the unit
  the alerts chaos classes SIGKILL mid-publish and mid-delivery.
  ``--poll-once`` runs exactly one cycle and exits (the chaos child);
  otherwise the loop polls until ``--duration`` elapses or it is
  killed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional, Sequence

from tsspark_tpu.obs import context as obs


def main(argv: Optional[Sequence[str]] = None) -> int:
    from tsspark_tpu.resident import force_virtual_host_mesh

    force_virtual_host_mesh()
    ap = argparse.ArgumentParser(prog="python -m tsspark_tpu.alerts")
    ap.add_argument("--bench", default=None, metavar="RUNG",
                    help="run the land→alert freshness bench at a "
                         "scale rung instead of the scorer")
    ap.add_argument("--reuse-cold", default=None, metavar="DIR")
    ap.add_argument("--churn", type=float, default=None)
    ap.add_argument("--deltas", type=int, default=None)
    ap.add_argument("--data", help="plane dataset dir")
    ap.add_argument("--registry", help="serve registry root")
    ap.add_argument("--alerts-dir", help="durable alert log dir")
    ap.add_argument("--sink", default=None,
                    help="sink spec (jsonl:<path>); defaults to "
                         "$TSSPARK_ALERTS_SINK")
    ap.add_argument("--horizon", type=int, default=1)
    ap.add_argument("--z", type=float, default=None,
                    help="z-score threshold override (fallback mode)")
    ap.add_argument("--overdue-k", type=float, default=None,
                    help="data-liveness overdue multiple of the EWMA "
                         "inter-arrival (default sched.OVERDUE_K)")
    ap.add_argument("--poll-once", action="store_true",
                    help="run one score/deliver cycle and exit")
    ap.add_argument("--poll", type=float, default=0.1)
    ap.add_argument("--duration", type=float, default=None)
    args = ap.parse_args(argv)
    obs.adopt_env()
    if args.bench:
        from tsspark_tpu import refit
        from tsspark_tpu.alerts import bench

        kw = {}
        if args.churn is not None:
            kw["churn"] = args.churn
        if args.deltas is not None:
            kw["n_deltas"] = args.deltas
        reports = bench.run_alerts_bench(args.bench,
                                         reuse_cold=args.reuse_cold,
                                         **kw)
        return 0 if refit.sweep_ok(reports) else 1

    if not (args.data and args.registry and args.alerts_dir):
        ap.error("--data, --registry and --alerts-dir are required "
                 "for the scorer")
    sink_spec = args.sink or os.environ.get("TSSPARK_ALERTS_SINK")
    if not sink_spec:
        ap.error("--sink (or TSSPARK_ALERTS_SINK) is required")
    from tsspark_tpu import sched
    from tsspark_tpu.alerts.sink import build_sink
    from tsspark_tpu.alerts.stream import AlertStream
    from tsspark_tpu.serve.cache import ForecastCache
    from tsspark_tpu.serve.engine import PredictionEngine
    from tsspark_tpu.serve.registry import ParamRegistry

    registry = ParamRegistry.open(args.registry)
    engine = PredictionEngine(registry, cache=ForecastCache(256))
    stream = AlertStream(
        args.alerts_dir, args.data, engine, build_sink(sink_spec),
        horizon=args.horizon, z=args.z,
        overdue_k=(sched.OVERDUE_K if args.overdue_k is None
                   else args.overdue_k),
    )
    if args.poll_once:
        res = stream.poll_once()
        res["snapshot"] = stream.snapshot()
        print(json.dumps(res), flush=True)
        return 0 if not res["stalled"] else 1
    t_end = None if args.duration is None else \
        time.monotonic() + args.duration
    last = {}
    while t_end is None or time.monotonic() < t_end:
        last = stream.poll_once()
        time.sleep(args.poll)
    last["snapshot"] = stream.snapshot()
    print(json.dumps(last), flush=True)
    return 0 if not last.get("stalled") else 1


if __name__ == "__main__":
    sys.exit(main())
