"""``python -m tsspark_tpu.alerts --bench RUNG``: the land→alert
freshness stream.

A churn lander feeds synthetic deltas into the plane while an
:class:`~tsspark_tpu.alerts.stream.AlertStream` scores and delivers
against the rung's cold-published version; the measurement is the
land→sink-ack latency per delta (the ``alerts.freshness`` span
stream), summarized as p50/p95 and judged by the regression sentinel
under ``[tool.tsspark.slo.alerts]``.  The cold fit is only the
denominator and is amortized exactly like the freshness bench
(``--reuse-cold`` / internal coldbase).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from tsspark_tpu.alerts.sink import JsonlSink
from tsspark_tpu.alerts.stream import AlertStream
from tsspark_tpu.io import atomic_write
from tsspark_tpu.obs import context as obs

#: Default churn fraction / delta count of the alert stream bench.
DEFAULT_ALERTS_CHURN = 0.05
DEFAULT_ALERTS_DELTAS = 6


def _write_alerts_report(rep: Dict) -> str:
    path = f"BENCH_alerts_{rep['rung']}_{int(rep['unix'])}.json"
    atomic_write(path, lambda fh: json.dump(rep, fh, indent=1),
                 mode="w")
    return path


def _alerts_report(rung, churn: float, n_deltas: int, gap: float,
                   cold: Dict, stream: AlertStream, seq0: int,
                   totals: Dict, wall_s: float, cfg) -> Dict:
    import jax

    from tsspark_tpu.config import NUMERICS_REV
    from tsspark_tpu.obs.history import git_rev
    from tsspark_tpu.utils import checkpoint as ckpt

    fresh = stream.freshness_summary()
    cold_wall = float(cold["fit_s"]) + float(cold["publish_s"])
    delivered_seqs = max(0, stream.delivered_seq() - int(seq0))
    last = stream.record_ok(stream.scored_seq()) \
        if stream.scored_seq() else None
    return {
        "kind": "alerts-bench",
        "unix": round(time.time(), 3),
        "trace_id": obs.trace_id(),
        "numerics_rev": NUMERICS_REV,
        "git_rev": git_rev(),
        "config_fingerprint": ckpt.config_fingerprint(cfg),
        "device": str(jax.devices()[0]),
        "rung": rung.name,
        "series": rung.series,
        "timesteps": rung.timesteps,
        # The scoring mode the stream actually ran in (interval when
        # the version publishes a quantile plane, zscore fallback
        # otherwise) — the workload key includes it, so the sentinel
        # never compares interval runs against fallback runs.
        "mode": (last or {}).get("mode", "unknown"),
        "degraded": bool((last or {}).get("degraded", True)),
        "churn": churn,
        "deltas": int(n_deltas),
        "interval_s": round(gap, 3),
        "complete": bool(delivered_seqs >= int(n_deltas)
                         and fresh["n"] >= int(n_deltas)),
        "cold_fit_s": round(float(cold["fit_s"]), 3),
        "cold_publish_s": round(float(cold["publish_s"]), 3),
        "cold_wall_s": round(cold_wall, 3),
        "cold_reused": bool(cold.get("reused")),
        "alerts_n": fresh["n"],
        "alerts_p50_s": fresh["p50_s"],
        "alerts_p95_s": fresh["p95_s"],
        "alerts_mean_s": fresh["mean_s"],
        "alerts_max_s": fresh["max_s"],
        "fired": int(totals["fired"]),
        "suppressed": int(totals["suppressed"]),
        "delivered": int(totals["delivered"]),
        "deduped": int(totals["deduped"]),
        "queued": int(totals["queued"]),
        "delivered_frac": (round(delivered_seqs / int(n_deltas), 4)
                           if n_deltas else None),
        "breaker_opens": int(stream.breaker.snapshot()["opens"]),
        "wall_s": round(wall_s, 3),
    }


def run_alerts_bench(rung="smoke", *,
                     churn: float = DEFAULT_ALERTS_CHURN,
                     n_deltas: int = DEFAULT_ALERTS_DELTAS,
                     interval_s: Optional[float] = None,
                     reuse_cold: Optional[str] = None,
                     scratch_root: Optional[str] = None,
                     sentinel: Optional[bool] = None) -> List[Dict]:
    """Land a churn stream and measure land→alert-ack freshness through
    a live AlertStream + JSONL sink.  One ``BENCH_alerts_*`` artifact,
    ingested into RUNHISTORY as the ``alerts`` family."""
    import tempfile

    from tsspark_tpu import bench_scale, refit
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import plane
    from tsspark_tpu.serve.cache import ForecastCache
    from tsspark_tpu.serve.engine import PredictionEngine

    if isinstance(rung, str):
        rung = bench_scale.RUNGS[rung]
    cfg = bench_scale._config()
    solver = SolverConfig(max_iters=rung.max_iters)
    scratch = os.path.join(
        scratch_root or tempfile.gettempdir(),
        f"tsalerts_{rung.name}_{rung.series}x{rung.timesteps}"
        f"_{plane.dataset_fingerprint()}",
    )
    os.makedirs(scratch, exist_ok=True)
    base_dir = reuse_cold or os.path.join(scratch, "coldbase")
    os.makedirs(base_dir, exist_ok=True)
    prev_run = obs.start_run(os.path.join(scratch, "spans.jsonl"))
    reports: List[Dict] = []
    try:
        spec = plane.DatasetSpec(
            generator="demo_weekly", n_series=rung.series,
            n_timesteps=rung.timesteps, seed=2,
        )
        dset_dir = plane.ensure(spec, root=os.path.join(base_dir,
                                                        "plane"))
        ids = plane.series_ids(spec)
        run_dir = os.path.join(scratch, f"run_{int(time.time())}")
        refit._sweep_stale_runs(scratch, keep=run_dir)
        registry, cold, _catchup = refit.prepare_cold_registry(
            rung, cfg, solver, run_dir, dset_dir, ids,
            reuse_cold=base_dir,
        )
        if registry is None:
            print("[alerts] cold fit incomplete; aborting",
                  file=sys.stderr)
            return [{"complete": False, "stage": "cold-fit"}]
        cold_wall = float(cold["fit_s"]) + float(cold["publish_s"])
        gap = interval_s if interval_s is not None else \
            min(5.0, max(0.2, 0.05 * cold_wall))

        engine = PredictionEngine(registry, cache=ForecastCache())
        stream = AlertStream(
            os.path.join(run_dir, "alerts"), dset_dir, engine,
            JsonlSink(os.path.join(run_dir, "alerts_sink.jsonl")),
            horizon=1,
        )
        seq0 = plane.delta_seq(dset_dir)
        target = seq0 + int(n_deltas)
        rng = np.random.default_rng([13, seq0])
        k = max(1, int(round(churn * rung.series)))

        def _land_stream():
            for _i in range(int(n_deltas)):
                rows = np.sort(rng.choice(rung.series, size=k,
                                          replace=False)).astype(
                    np.int64
                )
                try:
                    plane.land_synthetic_delta(dset_dir, churn,
                                               rows=rows)
                except Exception as e:
                    print(f"[alerts] land failed: {e!r}",
                          file=sys.stderr)
                    return
                time.sleep(gap)

        lander = threading.Thread(target=_land_stream,
                                  name="alerts-lander", daemon=True)
        totals = {"fired": 0, "suppressed": 0, "delivered": 0,
                  "deduped": 0, "queued": 0}
        t0 = time.time()
        lander.start()
        deadline = t0 + max(60.0, n_deltas * gap + 20 * cold_wall)
        while time.time() < deadline:
            res = stream.poll_once()
            totals["delivered"] += res["delivered"]
            totals["deduped"] += res["deduped"]
            totals["queued"] = res["queued"]
            if stream.delivered_seq() >= target:
                break
            time.sleep(0.05)
        lander.join(timeout=10.0)
        for s in range(seq0 + 1, stream.scored_seq() + 1):
            rec = stream.record_ok(s)
            if rec is not None:
                totals["fired"] += int(rec["n_fired"])
                totals["suppressed"] += int(rec["n_suppressed"])
        rep = _alerts_report(rung, churn, int(n_deltas), gap, cold,
                             stream, seq0, totals,
                             time.time() - t0, cfg)
        path = _write_alerts_report(rep)
        rep["path"] = path
        print(json.dumps({
            "rung": rung.name, "mode": rep["mode"], "churn": churn,
            "deltas": n_deltas,
            "alerts_p50_s": rep["alerts_p50_s"],
            "alerts_p95_s": rep["alerts_p95_s"],
            "fired": rep["fired"], "suppressed": rep["suppressed"],
            "delivered_frac": rep["delivered_frac"],
            "report": path,
        }), flush=True)
        if sentinel is None:
            sentinel_on = (os.environ.get("TSSPARK_SENTINEL", "1")
                           != "0")
        else:
            sentinel_on = sentinel
        if sentinel_on:
            try:
                from tsspark_tpu.obs import regress

                verdict = regress.sentinel_report(rep, source=path)
                if verdict is not None:
                    print(f"[alerts] {regress.summarize(verdict)}",
                          file=sys.stderr)
                    rep["sentinel_ok"] = verdict["ok"]
            except Exception as e:  # never mask the report
                print(f"[alerts] sentinel skipped: {e!r}",
                      file=sys.stderr)
        reports.append(rep)
        return reports
    finally:
        obs.end_run(prev_run)
