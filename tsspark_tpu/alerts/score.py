"""Deterministic anomaly scoring of landed deltas.

After each delta lands, the advanced rows' NEWEST observations are
scored against the active version's *served* forecast:

* **interval mode** — the version publishes a quantile plane
  (``uncertainty.qplane``): an observation outside the served
  ``[q_lo, q_hi]`` interval fires, severity = distance outside the
  interval in interval widths.  Served through ``engine.quantiles``,
  whose compute fallback bitwise-reproduces plane cells, so the mode
  decision keys off the VERSION (plane published or not), never off
  transient attach state.
* **z-score fallback** — no quantile plane for the version: residual
  z-score of the observation against the served point forecast, with
  the scale estimated from the patch window's first differences.  The
  degradation is recorded on every alert it produced (``degraded``).

Scoring is a pure function of (delta patch bytes, served forecast,
config thresholds) — all deterministic per (series, delta_seq,
version) — so a re-score after any crash converges bitwise to the
original record: :func:`canonical_bytes` of the record dict is the
unit the alert log's CRC sentinel certifies.

The decision core (:func:`score_rows`) is plain NumPy over served
arrays: the ``alerts-score`` effect budget (pyproject) pins that no
jax compile/dispatch, raw filesystem write, or spawn is reachable from
it — scoring can never stall the refit loop on a compile or write
outside the durable layer.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

#: Alert record schema (bumped on any canonical-layout change: the CRC
#: sentinel certifies bytes, so layout drift must be explicit).
SCHEMA = 1

#: z-score threshold of the fallback mode (|z| above this fires).
DEFAULT_Z = 3.0

#: Served interval of the primary mode: (lo, hi) quantiles.
DEFAULT_QUANTILES = (0.1, 0.9)

#: Floor for interval widths / residual scales — a constant series must
#: not turn every exact repeat into a division blow-up.
_EPS = 1e-9


def default_z() -> float:
    """The fallback-mode threshold, overridable via ``TSSPARK_ALERTS_Z``
    (a bad value falls back to the default rather than killing the
    scorer: thresholds are policy, not protocol)."""
    raw = os.environ.get("TSSPARK_ALERTS_Z")
    if raw is None:
        return DEFAULT_Z
    try:
        z = float(raw)
    except ValueError:
        return DEFAULT_Z
    return z if z > 0 else DEFAULT_Z


# ---------------------------------------------------------------------------
# the decision core (effect-budget root: pure NumPy, no IO, no JAX)
# ---------------------------------------------------------------------------


def score_rows(
    y: np.ndarray,
    *,
    lo: Optional[np.ndarray] = None,
    hi: Optional[np.ndarray] = None,
    yhat: Optional[np.ndarray] = None,
    sigma: Optional[np.ndarray] = None,
    z: float = DEFAULT_Z,
) -> Tuple[np.ndarray, np.ndarray, str]:
    """Vectorized breach decision for a batch of observations.

    Interval mode when ``lo``/``hi`` are given (fired = outside the
    interval; severity = distance outside in interval widths), else
    z-score mode against ``yhat``/``sigma`` (fired = |z| > threshold;
    severity = excess |z| in threshold units).  Returns
    ``(fired bool[k], severity float64[k], mode)``.  Pure and
    deterministic: same inputs, same bits."""
    y = np.asarray(y, np.float64)
    if lo is not None and hi is not None:
        lo = np.asarray(lo, np.float64)
        hi = np.asarray(hi, np.float64)
        width = np.maximum(hi - lo, _EPS)
        below = y < lo
        above = y > hi
        fired = below | above
        dist = np.where(below, lo - y, np.where(above, y - hi, 0.0))
        return fired, dist / width, "interval"
    if yhat is None or sigma is None:
        raise ValueError("score_rows needs lo/hi or yhat/sigma")
    yhat = np.asarray(yhat, np.float64)
    sigma = np.maximum(np.asarray(sigma, np.float64), _EPS)
    zs = np.abs(y - yhat) / sigma
    fired = zs > float(z)
    sev = np.maximum(zs - float(z), 0.0) / float(z)
    return fired, sev, "zscore"


def residual_scale(y: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """Per-row local noise scale for the z-score fallback: std of the
    observed window's first differences (floored).  Uses only the
    delta patch itself, so the fallback needs nothing beyond the point
    forecast — deterministic by construction."""
    y = np.asarray(y, np.float64)
    m = np.asarray(mask, np.float64) > 0
    out = np.empty(y.shape[0], np.float64)
    for i in range(y.shape[0]):
        vals = y[i][m[i]]
        d = np.diff(vals)
        out[i] = float(np.std(d)) if d.size else 0.0
    return np.maximum(out, _EPS)


def newest_observations(patch: Dict) -> Tuple[np.ndarray, np.ndarray]:
    """(values, window positions) of each patched row's newest OBSERVED
    sample — the observation the delta just delivered, the thing the
    served forecast is judged against."""
    y = np.asarray(patch["y"], np.float64)
    m = np.asarray(patch["mask"], np.float64) > 0
    w = m.shape[1]
    # Index of the last observed column (rows are never landed fully
    # unobserved; an all-hole row degrades to the last column).
    pos = np.where(m.any(axis=1),
                   w - 1 - np.argmax(m[:, ::-1], axis=1), w - 1)
    vals = y[np.arange(y.shape[0]), pos]
    return vals, pos.astype(np.int64)


# ---------------------------------------------------------------------------
# canonical record bytes (what the CRC sentinel certifies)
# ---------------------------------------------------------------------------


def canonical_bytes(record: Dict) -> bytes:
    """The record's one true serialization: sorted keys, no whitespace,
    shortest-round-trip floats.  Bitwise re-score = byte-equal output
    of this function — the property the torn-record chaos class pins."""
    return json.dumps(record, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def record_crc(record: Dict) -> int:
    return zlib.crc32(canonical_bytes(record)) & 0xFFFFFFFF


def _r(x: float) -> float:
    """Canonical float rounding for record payloads: deterministic and
    readable; 6 decimals is far inside float64's round-trip band."""
    return round(float(x), 6)


# ---------------------------------------------------------------------------
# one delta -> one alert record
# ---------------------------------------------------------------------------


def alert_key(kind: str, series: str, seq: int) -> str:
    """The exactly-once dedup key: (kind, series, delta_seq).  Delivery
    may repeat after a kill; a sink consumer deduping on this key sees
    each alert exactly once."""
    return f"{kind}:{series}:{int(seq)}"


def score_delta(engine, dset_dir: str, seq: int, *,
                horizon: int = 1,
                quantiles: Sequence[float] = DEFAULT_QUANTILES,
                z: Optional[float] = None) -> Dict:
    """Score one landed delta's advanced rows against the active
    version's served forecast; returns the canonical alert record
    (sorted by plane row, floats canonically rounded — ready for
    :func:`canonical_bytes`).

    Raises ``ValueError`` when the delta's patch is unreadable (a torn
    patch is data-plane corruption, not an empty alert set)."""
    from tsspark_tpu.data import plane
    from tsspark_tpu.uncertainty import qplane

    z = default_z() if z is None else float(z)
    patch = plane.delta_patch(dset_dir, int(seq))
    if patch is None:
        raise ValueError(f"delta {seq} has no readable patch in "
                         f"{dset_dir!r}")
    spec_rec = plane.read_spec(dset_dir)
    if spec_rec is None:
        raise ValueError(f"{dset_dir!r} is not a plane dataset")
    all_ids = plane.series_ids(plane.DatasetSpec.from_dict(spec_rec))
    rows = np.asarray(patch["rows"], np.int64)
    order = np.argsort(rows, kind="stable")
    rows = rows[order]
    y_new, _pos = newest_observations(patch)
    y_new = y_new[order]
    sids = [str(all_ids[int(r)]) for r in rows]

    h = int(horizon)
    qlo, qhi = float(min(quantiles)), float(max(quantiles))

    def _covered(v: Optional[int]) -> bool:
        # Mode keys off the VERSION's published quantile plane, a
        # durable property — never off transient attach state, so a
        # successor's re-score makes the same decision.  The compute
        # fallback under engine.quantiles reproduces plane cells
        # bitwise, so interval numbers are identical either way.
        return v is not None and qplane.has_qplane(
            engine.registry.version_dir(int(v))
        )

    version = engine.served_version()
    if version is None:
        version = engine.registry.active_version()
    covered = _covered(version)
    for _attempt in range(2):
        if covered:
            res = engine.quantiles(sids, h, quantiles=(qlo, qhi))
            version = int(res.version)
            if not _covered(version) and _attempt == 0:
                covered = False   # flip landed mid-call: redo once
                continue
            lo = res.values[f"q{qplane.permille(qlo):03d}"][:, h - 1]
            hi = res.values[f"q{qplane.permille(qhi):03d}"][:, h - 1]
            fired, sev, mode = score_rows(y_new, lo=lo, hi=hi)
            bounds = [(_r(a), _r(b)) for a, b in zip(lo, hi)]
        else:
            fres = engine.forecast(sids, h)
            version = int(fres.version)
            if _covered(version) and _attempt == 0:
                covered = True
                continue
            yhat = fres.values["yhat"][:, h - 1]
            sigma = residual_scale(patch["y"], patch["mask"])[order]
            fired, sev, mode = score_rows(y_new, yhat=yhat,
                                          sigma=sigma, z=z)
            bounds = [(_r(a), _r(b)) for a, b in zip(yhat, sigma)]
        break

    alerts = []
    for i in range(len(rows)):
        if not fired[i]:
            continue
        a = {
            "key": alert_key("anomaly", sids[i], seq),
            "kind": "anomaly",
            "series": sids[i],
            "row": int(rows[i]),
            "seq": int(seq),
            "version": version,
            "mode": mode,
            "degraded": not covered,
            "y": _r(y_new[i]),
            "severity": _r(sev[i]),
        }
        if mode == "interval":
            a["lo"], a["hi"] = bounds[i]
        else:
            a["yhat"], a["sigma"] = bounds[i]
            a["z"] = _r(z)
        alerts.append(a)
    return {
        "kind": "alert-record",
        "schema": SCHEMA,
        "seq": int(seq),
        "version": version,
        "mode": mode,
        "degraded": not covered,
        "horizon": h,
        "quantiles": [_r(qlo), _r(qhi)],
        "z": _r(z),
        "n_scored": int(len(rows)),
        "n_fired": int(len(alerts)),
        "n_suppressed": int(len(rows) - len(alerts)),
        "alerts": alerts,
    }
