"""Pluggable alert delivery sinks.

A sink is the boundary where alerts leave the process — the one stage
of the pipeline whose failures the scorer cannot roll back.  The
contract that makes exactly-once composable on top of at-least-once
delivery:

* ``emit(alert)`` either raises (nothing may be assumed delivered) or
  returns (the alert is durably acked by the sink).  The stream only
  advances its watermark after every alert of a record returned.
* ``keys()`` is the sink's own delivered-key set — the dedup side of
  the exactly-once argument.  A redelivery after a kill consults it,
  so a consumer reading the sink sees each (kind, series, delta_seq)
  key exactly once even though the stream only guarantees
  at-least-once emission attempts.
* ``recover()`` repairs any torn state a kill mid-emit left behind
  (for the JSONL sink: a trailing line without its newline, which
  would otherwise corrupt the NEXT append by concatenation).

The stream wraps every emit in ``RetryPolicy`` + ``CircuitBreaker``
(``resilience.policy``); the sink itself stays dumb and replayable.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Set

from tsspark_tpu.io import append_line


class SinkError(RuntimeError):
    """A sink refused or failed an emit: the alert is NOT acked.  The
    retry policy treats it (and OSError) as retryable; anything else a
    sink raises is a bug and propagates."""


class AlertSink:
    """Interface. ``name`` labels breaker/metrics output."""

    name = "null"

    def emit(self, alert: Dict) -> None:
        raise NotImplementedError

    def keys(self) -> Set[str]:
        """Delivered alert keys (the dedup set).  May re-read durable
        state; called on resume paths, not per emit."""
        raise NotImplementedError

    def recover(self) -> None:
        """Repair torn sink state after a crash (idempotent)."""


class JsonlSink(AlertSink):
    """Append-only JSONL file sink — the durable reference sink.

    One alert per line through the durable append path (single
    ``O_APPEND`` write per line, classified errors, ``io_write`` fault
    point).  Readers tolerate a torn last line; :meth:`recover`
    terminates one so later appends never concatenate onto it.  The
    torn fragment itself stays in the file (forensics) — its alert was
    never acked, so redelivery appends it whole."""

    name = "jsonl"

    def __init__(self, path: str):
        self.path = str(path)

    def emit(self, alert: Dict) -> None:
        append_line(self.path, json.dumps(alert, sort_keys=True))

    def _lines(self) -> List[str]:
        try:
            with open(self.path, "rb") as fh:
                raw = fh.read()
        except OSError:
            return []
        return raw.decode("utf-8", errors="replace").split("\n")

    def keys(self) -> Set[str]:
        out: Set[str] = set()
        for line in self._lines():
            if not line.strip():
                continue
            try:
                d = json.loads(line)
            except ValueError:
                continue  # torn/garbage line: never acked
            if isinstance(d, dict) and d.get("key"):
                out.add(str(d["key"]))
        return out

    def alerts(self) -> List[Dict]:
        """Every parseable delivered alert, in delivery order (the
        invariant checker's consumer view)."""
        out = []
        for line in self._lines():
            if not line.strip():
                continue
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if isinstance(d, dict) and d.get("key"):
                out.append(d)
        return out

    def recover(self) -> None:
        try:
            with open(self.path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                size = fh.tell()
                if size == 0:
                    return
                fh.seek(-1, os.SEEK_END)
                last = fh.read(1)
        except OSError:
            return  # absent: nothing to repair
        if last != b"\n":
            # Terminate the torn line so the next append starts clean.
            append_line(self.path, "")


class FlakySink(AlertSink):
    """Deterministic failure wrapper for tests and the chaos brownout:
    the first ``fail_n`` emits raise :class:`SinkError` (a timeout/
    brownout look-alike), then the inner sink takes over.  Failures
    never ack — exactly the window the breaker + durable queue must
    cover."""

    name = "flaky"

    def __init__(self, inner: AlertSink, fail_n: int):
        self.inner = inner
        self.fail_n = int(fail_n)
        self.attempts = 0
        self.failures = 0

    def emit(self, alert: Dict) -> None:
        self.attempts += 1
        if self.failures < self.fail_n:
            self.failures += 1
            raise SinkError(
                f"injected sink brownout ({self.failures}/{self.fail_n})"
            )
        self.inner.emit(alert)

    def keys(self) -> Set[str]:
        return self.inner.keys()

    def recover(self) -> None:
        self.inner.recover()


def build_sink(spec: str) -> AlertSink:
    """CLI sink factory: ``jsonl:<path>`` (or a bare path, which means
    the same).  Unknown schemes raise — a misrouted alert sink must
    fail loudly at startup, not drop alerts quietly."""
    if ":" in spec:
        scheme, _, rest = spec.partition(":")
        if scheme != "jsonl":
            raise ValueError(f"unknown sink scheme {scheme!r} "
                             "(known: jsonl)")
        return JsonlSink(rest)
    return JsonlSink(spec)
