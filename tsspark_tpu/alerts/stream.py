"""The durable alert log and its exactly-once delivery watermark.

Layout (one directory per scored dataset, unified plane-protocol
discipline):

* ``alerts_spec.json``      — identity record, spec FIRST.
* ``alertrec_<seq>.json``   — one canonical alert record per delta,
  landed atomically through the durable layer (``io.atomic_write``).
* ``alertok_<seq>.json``    — CRC sentinel LAST: certifies the record's
  canonical bytes.  A record without its sentinel (killed scorer) or
  failing its CRC (torn bytes) reads as UNSCORED and is re-scored —
  bitwise the original, by the determinism contract of
  ``alerts.score``.
* ``alerts_watermark.json`` — delivery watermark: the highest seq whose
  alerts the sink has ALL acked, replaced atomically only after the
  acks.  A torn/absent watermark reads as 0 — redelivery is always
  safe because every alert carries its (kind, series, delta_seq) key
  and the sink's key set dedups it.
* ``alerts_queue.jsonl``    — durable overflow queue for loose
  (non-record) alerts an open breaker refused; drained on recovery,
  deduped by key.

The exactly-once argument (docs/ALERTS.md): scoring is resumable
(sentinel gate), delivery is at-least-once (watermark advances only
after sink ack), and every alert is keyed — at-least-once + keyed
dedup = exactly-once effect.  The ``alerts_exactly_once`` chaos
invariant checks the composition end to end across kills.

Fault points (``resilience.faults``): ``alert_publish`` brackets every
step of the record protocol (the chaos storm SIGKILLs each window);
``alert_deliver`` fires before every sink emit attempt (kill
mid-delivery, brownout).
"""

from __future__ import annotations

import collections
import json
import os
import time
import zlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from tsspark_tpu.alerts.score import (
    DEFAULT_QUANTILES,
    alert_key,
    canonical_bytes,
    score_delta,
)
from tsspark_tpu.alerts.sink import AlertSink, SinkError
from tsspark_tpu.io import (
    StorageError,
    append_line,
    atomic_write,
    current_state,
    is_missing,
    reraise_classified,
)
from tsspark_tpu.obs import context as obs
from tsspark_tpu.obs.metrics import DEFAULT as METRICS
from tsspark_tpu.plane import protocol
from tsspark_tpu.resilience import faults
from tsspark_tpu.resilience.policy import (
    CircuitBreaker,
    CircuitOpen,
    RetryPolicy,
)

#: Fault point bracketing each step of the alert-record publish.
ALERT_PUBLISH = "alert_publish"
#: Fault point before every sink emit attempt.
ALERT_DELIVER = "alert_deliver"

SPEC_FILE = "alerts_spec.json"
WATERMARK_FILE = "alerts_watermark.json"
QUEUE_FILE = "alerts_queue.jsonl"
REC_PREFIX = "alertrec_"
OK_PREFIX = "alertok_"

#: Bounded land->alert freshness sample window (daemon runs forever).
FRESHNESS_WINDOW = 4096

#: Alert fields that survive disk-ladder detail shedding: identity and
#: routing only.  Alerts are NEVER dropped by the ladder — only their
#: scoring context is shed.
_CORE_FIELDS = ("key", "kind", "series", "row", "seq", "version",
                "mode", "severity")

#: Ladder states at which delivery sheds scoring detail (rung 2+: the
#: disk is the thing under pressure, and alert context is the cheapest
#: payload to shrink before anything load-bearing degrades).
_SHED_STATES = ("reap", "pause_ingest", "stale_serve")


def _default_retry() -> RetryPolicy:
    # Tight by default: an alert pipeline must shed to the durable
    # queue quickly, not stall the scorer behind 10 s sink sleeps.
    return RetryPolicy(max_attempts=3, base_delay_s=0.05, backoff=2.0,
                       max_delay_s=0.5)


class AlertStream:
    """One alert log + delivery pipeline over (dataset, engine, sink).

    Crash recovery is a NEW instance over the same ``alerts_dir``: the
    constructor repairs sink state, and :meth:`poll_once` re-scores any
    delta without a valid sentinel and re-delivers everything past the
    watermark (deduped by the sink's key set)."""

    def __init__(self, alerts_dir: str, dset_dir: str, engine,
                 sink: AlertSink, *,
                 horizon: int = 1,
                 quantiles: Sequence[float] = DEFAULT_QUANTILES,
                 z: Optional[float] = None,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 overdue_k: float = 3.0,
                 clock=time.time):
        self.dir = str(alerts_dir)
        self.dset_dir = str(dset_dir)
        self.engine = engine
        self.sink = sink
        self.horizon = int(horizon)
        self.quantiles = tuple(float(q) for q in quantiles)
        self.z = z
        self.overdue_k = float(overdue_k)
        self.retry = retry if retry is not None else _default_retry()
        self.breaker = breaker if breaker is not None else \
            CircuitBreaker(failure_threshold=3, reset_timeout_s=1.0,
                           name="alert-sink")
        self._clock = clock
        os.makedirs(self.dir, exist_ok=True)
        self.sink.recover()

        self._records: Dict[int, Dict] = {}   # sentinel-verified cache
        self._land_unix: Dict[int, float] = {}
        self._row_last_seq: Dict[int, int] = {}
        self._arrivals = None                 # lazy sched.ArrivalModel
        self._ids: Optional[np.ndarray] = None
        self.freshness: "collections.deque" = collections.deque(
            maxlen=FRESHNESS_WINDOW
        )
        self._m_fired = METRICS.counter("tsspark_alerts_fired_total")
        self._m_supp = METRICS.counter(
            "tsspark_alerts_suppressed_total"
        )
        self._m_delivered = METRICS.counter(
            "tsspark_alerts_delivered_total"
        )
        self._m_dedup = METRICS.counter("tsspark_alerts_dedup_total")
        self._m_liveness = METRICS.counter(
            "tsspark_alerts_liveness_total"
        )
        self._m_queued = METRICS.gauge("tsspark_alerts_queued")
        self._m_breaker = METRICS.gauge("tsspark_alerts_breaker_open")
        self._m_watermark = METRICS.gauge(
            "tsspark_alerts_watermark_seq"
        )
        self._m_fresh = METRICS.gauge(
            "tsspark_alerts_freshness_last_seconds"
        )
        self._m_fresh_hist = METRICS.histogram(
            "tsspark_alerts_freshness_seconds"
        )

    # -- paths (readers; write sites build literals inline) --------------------

    def _spec_path(self) -> str:
        return os.path.join(self.dir, SPEC_FILE)

    def _rec_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"{REC_PREFIX}{int(seq):06d}.json")

    def _ok_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"{OK_PREFIX}{int(seq):06d}.json")

    def _queue_path(self) -> str:
        return os.path.join(self.dir, QUEUE_FILE)

    # -- the durable record protocol -------------------------------------------

    def record_ok(self, seq: int) -> Optional[Dict]:
        """The sentinel-certified record for ``seq``, or None when the
        record is absent, unsentineled, or fails its CRC — all of which
        read as UNSCORED (the re-score converges bitwise).  A real disk
        failure raises its typed storage error."""
        seq = int(seq)
        cached = self._records.get(seq)
        if cached is not None:
            return cached
        ok = protocol.read_json(self._ok_path(seq))
        if ok is None or not isinstance(ok.get("crc"), int):
            return None
        try:
            with open(self._rec_path(seq), "rb") as fh:
                raw = fh.read()
        except OSError as e:
            if is_missing(e):
                return None
            reraise_classified(e)
        if (zlib.crc32(raw) & 0xFFFFFFFF) != int(ok["crc"]):
            return None  # torn/corrupt record: sentinel rejects it
        try:
            rec = json.loads(raw.decode("utf-8"))
        except ValueError:
            return None
        if not isinstance(rec, dict) or int(rec.get("seq", -1)) != seq:
            return None
        self._records[seq] = rec
        return rec

    def _ensure_spec(self) -> None:
        if protocol.read_json(self._spec_path()) is not None:
            return
        protocol.write_spec(self._spec_path(), {
            "kind": "alerts-spec",
            "schema": 1,
            "dataset": os.path.basename(self.dset_dir.rstrip(os.sep)),
            "horizon": self.horizon,
            "quantiles": list(self.quantiles),
            "sink": self.sink.name,
        })

    def score_seq(self, seq: int) -> Dict:
        """Score delta ``seq`` and publish its alert record under the
        plane-protocol discipline: spec FIRST, atomic record payload,
        CRC sentinel LAST.  Idempotent — a re-publish lands byte-equal
        files (the ``alert-record`` ProtocolSpec statically sweeps the
        kill-points of this writer)."""
        seq = int(seq)
        self._ensure_spec()
        record = score_delta(self.engine, self.dset_dir, seq,
                             horizon=self.horizon,
                             quantiles=self.quantiles, z=self.z)
        payload = canonical_bytes(record)
        rec_path = os.path.join(self.dir,
                                f"alertrec_{seq:06d}.json")
        faults.inject(ALERT_PUBLISH, path=rec_path)
        atomic_write(rec_path, lambda fh: fh.write(payload))
        ok_path = os.path.join(self.dir, f"alertok_{seq:06d}.json")
        faults.inject(ALERT_PUBLISH, path=ok_path)
        protocol.write_sentinel(ok_path, {
            "kind": "alert-record-ok",
            "seq": seq,
            "crc": zlib.crc32(payload) & 0xFFFFFFFF,
            "n_alerts": int(record["n_fired"]),
        })
        faults.inject(ALERT_PUBLISH, path=ok_path)
        self._m_fired.inc(int(record["n_fired"]))
        self._m_supp.inc(int(record["n_suppressed"]))
        self._records[seq] = record
        return record

    def scored_seq(self) -> int:
        """Highest CONTIGUOUSLY certified record seq (resume frontier:
        the first gap or torn record is where re-scoring starts)."""
        seq = 0
        while self.record_ok(seq + 1) is not None:
            seq += 1
        return seq

    # -- the delivery watermark ------------------------------------------------

    def delivered_seq(self) -> int:
        """The delivery watermark: every alert of every record at or
        below it has been acked by the sink.  Torn/absent reads as 0 —
        redelivery is deduped, so the watermark is a fast-forward
        pointer, never a correctness input."""
        wm = protocol.read_json(os.path.join(self.dir, WATERMARK_FILE))
        if wm is None or not isinstance(wm.get("seq"), int):
            return 0
        return int(wm["seq"])

    def _advance_watermark(self, seq: int) -> None:
        atomic_write(
            os.path.join(self.dir, "alerts_watermark.json"),
            lambda fh: json.dump({
                "kind": "alert-watermark",
                "seq": int(seq),
                "unix": round(float(self._clock()), 3),
            }, fh),
            mode="w",
        )
        self._m_watermark.set(float(seq))

    # -- delivery ---------------------------------------------------------------

    def _shed(self, alert: Dict) -> Dict:
        st = current_state(self.dir)
        if st not in _SHED_STATES:
            return alert
        kept = {k: alert[k] for k in _CORE_FIELDS if k in alert}
        kept["shed"] = st
        return kept

    def _emit(self, alert: Dict) -> None:
        """One at-least-once emit under retry + breaker.  Raises
        ``CircuitOpen`` / ``SinkError`` / storage errors when the sink
        stays down — the caller leaves the alert durably queued."""
        payload = self._shed(alert)

        def attempt():
            faults.inject(ALERT_DELIVER, path=self.sink.name)
            self.sink.emit(payload)

        self.retry.call(attempt, retry_on=(SinkError, OSError),
                        breaker=self.breaker)
        self._m_delivered.inc()

    def deliver_pending(self) -> Dict:
        """Deliver every certified record past the watermark, in seq
        order, deduping against the sink's key set; advance the
        watermark only after a record's alerts ALL acked.  Stops (and
        leaves the rest durably queued in the record log) when the
        sink stays down."""
        wm = self.delivered_seq()
        known = self.sink.keys()
        out = {"delivered": 0, "deduped": 0, "records": 0,
               "stalled": False}
        seq = wm + 1
        while True:
            rec = self.record_ok(seq)
            if rec is None:
                break  # frontier: not yet scored (or torn -> re-score)
            try:
                for alert in rec["alerts"]:
                    if alert["key"] in known:
                        self._m_dedup.inc()
                        out["deduped"] += 1
                        continue
                    self._emit(alert)
                    known.add(alert["key"])
                    out["delivered"] += 1
            except (CircuitOpen, SinkError, StorageError, OSError) as e:
                obs.event("alerts.delivery_stalled", seq=seq,
                          error=repr(e),
                          breaker=self.breaker.state)
                out["stalled"] = True
                break
            self._advance_watermark(seq)
            out["records"] += 1
            self._note_freshness(seq, rec)
            seq += 1
        self._m_breaker.set(
            0.0 if self.breaker.state == CircuitBreaker.CLOSED else 1.0
        )
        return out

    def _note_freshness(self, seq: int, rec: Dict) -> None:
        t_land = self._land_unix.get(int(seq))
        if t_land is None:
            return  # resumed before poll learned the land time
        fr = max(0.0, float(self._clock()) - float(t_land))
        self.freshness.append((int(seq), fr))
        self._m_fresh.set(fr)
        self._m_fresh_hist.observe(fr)
        obs.record("alerts.freshness", t_land, fr, seq=int(seq),
                   version=int(rec["version"]),
                   n_alerts=int(rec["n_fired"]), mode=rec["mode"])

    # -- loose alerts (data-liveness) + the durable overflow queue -------------

    def _series_id(self, row: int) -> str:
        if self._ids is None:
            from tsspark_tpu.data import plane

            spec_rec = plane.read_spec(self.dset_dir)
            if spec_rec is None:
                return str(row)
            self._ids = plane.series_ids(
                plane.DatasetSpec.from_dict(spec_rec)
            )
        if self._ids is None or row >= len(self._ids):
            return str(row)
        return str(self._ids[int(row)])

    def _note_arrivals(self, seq: int, unix: float, rows) -> None:
        if rows is None:
            return
        if self._arrivals is None:
            from tsspark_tpu.sched import ArrivalModel

            self._arrivals = ArrivalModel()
        self._arrivals.note_delta(seq, unix, rows)
        for r in np.asarray(rows, np.int64).tolist():
            self._row_last_seq[int(r)] = int(seq)

    def liveness_alerts(self, now: Optional[float] = None) -> List[Dict]:
        """Data-liveness alerts off the arrival model: series whose
        learned cadence says a delta is overdue by more than
        ``overdue_k``x its EWMA inter-arrival.  Keyed by the series'
        LAST seen delta seq, so an overdue episode fires once and
        re-arms only when the series advances again."""
        if self._arrivals is None:
            return []
        now = float(self._clock()) if now is None else float(now)
        out = []
        overdue = self._arrivals.overdue_rows(now, k=self.overdue_k)
        for row in sorted(overdue):
            last_seq = self._row_last_seq.get(int(row))
            if last_seq is None:
                continue
            sid = self._series_id(int(row))
            out.append({
                "key": alert_key("data-liveness", sid, last_seq),
                "kind": "data-liveness",
                "series": sid,
                "row": int(row),
                "seq": int(last_seq),
                "mode": "liveness",
                "overdue_s": round(float(overdue[row]), 3),
            })
        return out

    def _queue_lines(self) -> List[Dict]:
        try:
            with open(self._queue_path(), "rb") as fh:
                raw = fh.read()
        except OSError as e:
            if is_missing(e):
                return []
            reraise_classified(e)
        out = []
        for line in raw.decode("utf-8", errors="replace").split("\n"):
            if not line.strip():
                continue
            try:
                d = json.loads(line)
            except ValueError:
                continue  # torn last line: its alert was re-queued or
                # re-derived; the fragment is inert
            if isinstance(d, dict) and d.get("key"):
                out.append(d)
        return out

    def _rewrite_queue(self, remaining: List[Dict]) -> None:
        if not remaining and not os.path.exists(self._queue_path()):
            return
        body = "".join(json.dumps(a, sort_keys=True) + "\n"
                       for a in remaining)
        atomic_write(self._queue_path(),
                     lambda fh: fh.write(body), mode="w")
        self._m_queued.set(float(len(remaining)))

    def deliver_loose(self, alerts: List[Dict]) -> Dict:
        """Deliver non-record alerts (liveness) plus whatever the
        durable queue holds: dedup by key, emit under retry/breaker,
        queue durably anything the sink refuses, drain on recovery.
        Exactly-once by the same argument as records — the queue file
        is the durable at-least-once side, the key set the dedup."""
        known = self.sink.keys()
        pending: List[Dict] = []
        seen: Set[str] = set()
        for a in self._queue_lines() + list(alerts):
            if a["key"] in known or a["key"] in seen:
                continue
            seen.add(a["key"])
            pending.append(a)
        delivered = 0
        remaining: List[Dict] = []
        stalled = False
        for i, a in enumerate(pending):
            if stalled:
                remaining.append(a)
                continue
            try:
                self._emit(a)
                if a["kind"] == "data-liveness":
                    self._m_liveness.inc()
                delivered += 1
            except (CircuitOpen, SinkError, StorageError, OSError) as e:
                obs.event("alerts.queue_stalled", error=repr(e),
                          breaker=self.breaker.state)
                stalled = True
                remaining.append(a)
        self._rewrite_queue(remaining)
        self._m_breaker.set(
            0.0 if self.breaker.state == CircuitBreaker.CLOSED else 1.0
        )
        return {"delivered": delivered, "queued": len(remaining),
                "stalled": stalled}

    # -- the poll loop ----------------------------------------------------------

    def poll_once(self, now: Optional[float] = None) -> Dict:
        """One cycle: fold new deltas into the arrival model, score
        every delta without a certified record (resume + fresh work in
        one motion), deliver past the watermark, then the liveness/
        queue path.  Safe to call from a fresh process at any time —
        this IS the crash recovery.

        (Named ``poll_once``, not ``poll``: the effect-budget checker's
        call graph joins by simple callee name, and ``poll`` is
        ``Popen.poll`` all over the serve tier — a collision would drag
        the scorer's engine closure into the serve-threads budget.)"""
        from tsspark_tpu.data import plane

        now = float(self._clock()) if now is None else float(now)
        scored = 0
        for rec in plane.delta_records(self.dset_dir):
            seq = int(rec["seq"])
            unix = float(rec.get("unix") or now)
            self._land_unix.setdefault(seq, unix)
            if self._arrivals is None \
                    or seq > self._arrivals.seen_seq():
                self._note_arrivals(
                    seq, unix, plane.delta_rows(self.dset_dir, seq)
                )
            if self.record_ok(seq) is None:
                # Crash-safe open/close pair, not a context span: the
                # chaos scorer-kill lands INSIDE score_seq, and the
                # engine spans it already emitted must still resolve
                # their parent in the ledger after the process dies.
                t_sp = time.time()
                sid = obs.open_span("alerts.score", seq=seq)
                try:
                    self.score_seq(seq)
                finally:
                    obs.close_span(sid, "alerts.score", t_sp, seq=seq)
                scored += 1
        dres = self.deliver_pending()
        lres = self.deliver_loose(self.liveness_alerts(now))
        return {
            "scored": scored,
            "delivered": dres["delivered"] + lres["delivered"],
            "deduped": dres["deduped"],
            "records": dres["records"],
            "queued": lres["queued"],
            "stalled": dres["stalled"] or lres["stalled"],
            "watermark": self.delivered_seq(),
        }

    # -- telemetry ---------------------------------------------------------------

    def freshness_summary(self) -> Dict:
        vals = [fr for _seq, fr in self.freshness]
        arr = np.asarray(vals, np.float64)
        return {
            "n": len(vals),
            "p50_s": (round(float(np.percentile(arr, 50)), 4)
                      if vals else None),
            "p95_s": (round(float(np.percentile(arr, 95)), 4)
                      if vals else None),
            "mean_s": (round(float(arr.mean()), 4) if vals else None),
            "max_s": (round(float(arr.max()), 4) if vals else None),
        }

    def snapshot(self) -> Dict:
        return {
            "scored_seq": self.scored_seq(),
            "delivered_seq": self.delivered_seq(),
            "queued": len(self._queue_lines()),
            "breaker": self.breaker.snapshot(),
            "freshness": self.freshness_summary(),
            "disk_ladder": current_state(self.dir),
        }
