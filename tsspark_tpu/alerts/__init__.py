"""Exactly-once anomaly alert stream off the refit loop.

The last open half of the forecasting-*service* story (ROADMAP item
5(ii)): the scheduler already watches every landed delta and the
uncertainty tier serves calibrated quantiles — exactly the thresholds
an online anomaly scorer needs.  This package turns them into alerts a
consumer can page on, with **exactly-once delivery** as the headline
invariant:

* ``score``  — deterministic vectorized scoring of each landed delta's
  new observations against the active version's *served* forecast:
  quantile-interval breach when the version publishes a quantile plane,
  residual z-score fallback otherwise (degradation recorded per alert).
  A re-score of (series, delta_seq, version) is bitwise the original.
* ``stream`` — the durable alert log under the unified plane-protocol
  discipline (spec FIRST, atomic per-cycle record, CRC sentinel LAST)
  plus the delivery watermark: a scorer killed at ANY point resumes
  from the watermark, and at-least-once delivery + keyed dedup
  composes to an exactly-once effect.
* ``sink``   — pluggable delivery sinks (JSONL first) behind
  ``RetryPolicy`` + ``CircuitBreaker``; an open breaker queues alerts
  durably and drains on recovery without duplicates; disk-ladder aware
  (scoring detail is shed before any alert is dropped).
* ``bench``  — ``python -m tsspark_tpu.alerts --bench RUNG``: the
  land→alert freshness stream, judged under
  ``[tool.tsspark.slo.alerts]``.

The chaos storm's ``alerts`` stage (``tsspark_tpu.chaos``) SIGKILLs
the scorer mid-publish and mid-delivery, browns out the sink, and
tears a landed record; the ``alerts_exactly_once`` invariant proves
zero dropped and zero duplicate alerts across every kill/resume.
See docs/ALERTS.md for the scoring rules and the runbook.
"""

from tsspark_tpu.alerts.score import (  # noqa: F401
    DEFAULT_Z,
    canonical_bytes,
    record_crc,
    score_delta,
    score_rows,
)
from tsspark_tpu.alerts.sink import (  # noqa: F401
    AlertSink,
    FlakySink,
    JsonlSink,
    SinkError,
    build_sink,
)
from tsspark_tpu.alerts.stream import (  # noqa: F401
    ALERT_DELIVER,
    ALERT_PUBLISH,
    AlertStream,
)
