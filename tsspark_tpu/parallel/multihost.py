"""Multi-host execution: distributed runtime init + host-local data feeding.

The reference scales across machines through Spark's executor fan-out; the
TPU-native equivalent is one SPMD program over a multi-host mesh, with
per-host processes that each hold only their slice of the series batch:

  1. every process calls :func:`initialize` (JAX distributed runtime — the
     coordination layer under multi-host DCN collectives),
  2. every process loads/prepares only ITS series rows (host-local numpy),
  3. :func:`global_batch` assembles the per-host rows into global sharded
     ``jax.Array``s addressable by the whole mesh, and the usual
     ``sharding.fit_sharded`` program runs unchanged — XLA routes
     collectives over ICI within a host and DCN across hosts.

Single-process meshes degrade gracefully: ``global_batch`` is then just a
device_put onto the mesh sharding.  The REAL multi-process path (two OS
processes joined via jax.distributed, each holding half the batch,
assembled with jax.make_array_from_process_local_data and solved over a
4-device mesh) is exercised by tests/test_multihost.py, which checks every
addressable result shard against a single-device reference solve.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from tsspark_tpu.config import ShardingConfig
from tsspark_tpu.models.prophet.design import FitData
from tsspark_tpu.parallel.sharding import data_shardings


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> None:
    """Start the JAX distributed runtime (multi-host DCN coordination).

    Call once per host process before building meshes.  On single-host
    setups (and TPU pods with automatic environment discovery) all
    arguments may be omitted.  Thin passthrough to
    ``jax.distributed.initialize`` so callers depend on this package's
    API rather than JAX internals.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )


def global_batch(
    data: FitData,
    mesh: Mesh,
    config: ShardingConfig = ShardingConfig(),
) -> FitData:
    """Assemble per-host FitData rows into globally-sharded jax.Arrays.

    Each process passes the rows of the series batch IT loaded (equal row
    counts per process; pad with inert mask-0 rows if needed).  The result
    is a FitData of global arrays laid out per ``data_shardings`` — series
    axis split across the mesh — ready for ``fit_sharded``/``fit_core``
    without any host ever materializing the full batch.
    """
    specs = data_shardings(mesh, data, config)

    def put(x, spec):
        sh = NamedSharding(mesh, spec)
        if jax.process_count() == 1:
            # Degenerate mode: device_put reshards device arrays directly —
            # no host round trip.
            return jax.device_put(x, sh)
        # Multi-process contract: x is this host's local numpy rows.
        return jax.make_array_from_process_local_data(sh, np.asarray(x))

    # data's leaves are arrays, so tree.map takes each corresponding spec
    # subtree (a PartitionSpec) whole — no is_leaf needed.
    return jax.tree.map(put, data, specs)
