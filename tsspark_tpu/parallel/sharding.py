"""Sharded batched fitting: collect -> shard -> fit -> scatter.

The whole MAP solve is ONE jitted XLA program with sharding annotations on
its inputs/outputs; XLA partitions the batched L-BFGS automatically:

  * series axis — every per-series quantity ((B, T) data, (B, P) params,
    (M, B, P) solver history) is partitioned on its B dim; all solver math
    is elementwise or reduces over P/T, so shards run independently.  The
    only cross-shard traffic is the scalar all-reduce hidden in the
    ``while_loop`` convergence test (``all(converged)``) — one bit per
    iteration over ICI.
  * time axis (optional sequence parallelism) — (B, T) data is additionally
    partitioned on T; loss/gradient reductions over T become psums that XLA
    inserts.  This is the long-series regime; the shared (T, F) seasonal
    matrix is partitioned on T as well so the seasonal matmul stays local.

This file replaces the reference's Spark driver path (mapPartitions over CPU
executors, BASELINE.json:5) with sharding annotations — there is no
scheduler code to write, which is precisely the TPU-first design win.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tsspark_tpu.config import ProphetConfig, ShardingConfig, SolverConfig
from tsspark_tpu.models.prophet.design import FitData
from tsspark_tpu.models.prophet.init import curvature_diag, initial_theta
from tsspark_tpu.models.prophet.loss import (
    fan_value_closed_form,
    has_closed_form_fan,
    value_and_grad_batch,
    value_batch,
)
from tsspark_tpu.ops import lbfgs


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def data_shardings(
    mesh: Mesh, data: FitData, config: ShardingConfig
) -> FitData:
    """PartitionSpecs for each FitData leaf (shaped like the pytree)."""
    s_ax = config.series_axis
    # Time axis: the config's declared name wins; otherwise fall back to
    # the first mesh axis that is NOT the series axis.  Taking
    # axis_names[1] positionally put the SERIES axis on the time
    # dimension for a mesh declared ("time", "series") (ADVICE r4).
    t_ax = config.time_axis
    if t_ax is None:
        rest = [n for n in mesh.axis_names if n != s_ax]
        t_ax = rest[0] if rest else None
    bt = P(s_ax, t_ax)
    return FitData(
        t=bt,
        y=bt,
        mask=bt,
        s=P(s_ax, None),
        cap=bt,
        X_season=P(t_ax, None) if data.X_season.ndim == 2 else P(s_ax, t_ax, None),
        X_reg=P(s_ax, t_ax, None),
        prior_scales=P(None),
        mult_mask=P(None),
    )


@functools.partial(
    jax.jit, static_argnames=("config", "solver_config", "mesh", "shard_cfg")
)
def _fit_sharded_core(data, theta0, config, solver_config, mesh, shard_cfg):
    specs = data_shardings(mesh, data, shard_cfg)
    s_ax = shard_cfg.series_axis
    data = jax.lax.with_sharding_constraint(
        data, jax.tree.map(lambda sp: NamedSharding(mesh, sp), specs,
                           is_leaf=lambda x: isinstance(x, P))
    )
    if theta0 is None:
        theta0 = initial_theta(data, config, solver_config)
    theta0 = jax.lax.with_sharding_constraint(
        theta0, NamedSharding(mesh, P(s_ax, None))
    )
    precond = (
        curvature_diag(data, config, theta0)
        if solver_config.resolved_precond(config.growth) == "gn_diag"
        else None
    )
    fun = lambda th: value_and_grad_batch(th, data, config)
    fval = lambda th: value_batch(th, data, config)
    fan = (lambda th, d, s: fan_value_closed_form(th, d, s, data, config)) \
        if has_closed_form_fan(config) else None
    return lbfgs.minimize(fun, theta0, solver_config, fun_value=fval,
                          precond=precond, fan_value=fan)


def fit_sharded(
    data: FitData,
    theta0: Optional[jnp.ndarray],
    config: ProphetConfig,
    solver_config: SolverConfig,
    mesh: Mesh,
    shard_cfg: ShardingConfig = ShardingConfig(),
) -> lbfgs.LbfgsResult:
    """Fit a batch across the mesh; pads B to the series-shard count.

    ``theta0=None`` computes the warm start inside the sharded program.
    Returns per-series results for the ORIGINAL (unpadded) batch.
    """
    b = data.y.shape[0]
    n_series_shards = mesh.shape[shard_cfg.series_axis]
    b_pad = pad_to_multiple(b, n_series_shards)
    if b_pad != b:
        pad_b = lambda a: jnp.pad(
            a, [(0, b_pad - b)] + [(0, 0)] * (a.ndim - 1)
        )
        data = FitData(
            t=pad_b(data.t),
            y=pad_b(data.y),
            mask=pad_b(data.mask),  # zero mask -> inert dummy series
            s=pad_b(data.s),
            cap=jnp.concatenate(
                [data.cap, jnp.ones((b_pad - b,) + data.cap.shape[1:],
                                    data.cap.dtype)]
            ),
            X_season=data.X_season if data.X_season.ndim == 2
            else pad_b(data.X_season),
            X_reg=pad_b(data.X_reg),
            prior_scales=data.prior_scales,
            mult_mask=data.mult_mask,
        )
        if theta0 is not None:
            theta0 = pad_b(theta0)

    res = _fit_sharded_core(data, theta0, config, solver_config, mesh, shard_cfg)
    if b_pad != b:
        res = jax.tree.map(lambda a: a[:b], res)
    return res
