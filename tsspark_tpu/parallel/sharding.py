"""Sharded batched fitting: collect -> shard -> fit -> scatter.

The whole MAP solve is ONE jitted XLA program with sharding annotations on
its inputs/outputs; XLA partitions the batched L-BFGS automatically:

  * series axis — every per-series quantity ((B, T) data, (B, P) params,
    (M, B, P) solver history) is partitioned on its B dim; all solver math
    is elementwise or reduces over P/T, so shards run independently.  The
    only cross-shard traffic is the scalar all-reduce hidden in the
    ``while_loop`` convergence test (``all(converged)``) — one bit per
    iteration over ICI.
  * time axis (optional sequence parallelism) — (B, T) data is additionally
    partitioned on T; loss/gradient reductions over T become psums that XLA
    inserts.  This is the long-series regime; the shared (T, F) seasonal
    matrix is partitioned on T as well so the seasonal matmul stays local.

This file replaces the reference's Spark driver path (mapPartitions over CPU
executors, BASELINE.json:5) with sharding annotations — there is no
scheduler code to write, which is precisely the TPU-first design win.
"""

from __future__ import annotations

import functools
import re
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tsspark_tpu.config import ProphetConfig, ShardingConfig, SolverConfig
from tsspark_tpu.models.prophet.design import FitData
from tsspark_tpu.models.prophet.init import curvature_diag, initial_theta
from tsspark_tpu.models.prophet.loss import (
    fan_value_closed_form,
    has_closed_form_fan,
    value_and_grad_batch,
    value_batch,
)
from tsspark_tpu.ops import lbfgs


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def next_pow2(n: int) -> int:
    """THE pow-2 ladder primitive shared by the chunk padding
    (``TpuBackend``) and the compaction width policy below, so the two
    can never walk different ladders.  (``perf.autotune`` keeps a
    stdlib-local copy: importing this module would pull JAX into the
    perf package's deliberately light import chain.)"""
    p = 1
    while p < n:
        p *= 2
    return p


def compacted_width(n_live: int, floor: int = 32, multiple: int = 1) -> int:
    """Padded batch width for a compacted live set.

    The segment scheduler (``ProphetModel._fit_prepared``) shrinks the
    lockstep batch to its unconverged set between solver segments; this
    is THE width policy it shrinks to:

    * next power of two — widths walk the same ladder the backend's
      chunk padding uses (``TpuBackend._fit_padded``), so shrunk widths
      re-hit already-compiled programs instead of compiling a program
      per live-set size;
    * floored (default 32, the backend's tiny-batch floor) — below it
      per-dispatch overhead dominates and the inert rows are free;
    * rounded up to a ``multiple`` — the series-axis shard count when a
      mesh is in play, so a compacted width still divides evenly across
      the series shards (``fit_sharded``'s own padding contract).
    """
    w = next_pow2(max(int(n_live), 1))
    return pad_to_multiple(max(w, int(floor)), max(int(multiple), 1))


def _resolve_time_axis(mesh: Mesh, config: ShardingConfig):
    """Time axis for a layout: the config's declared name wins; otherwise
    an axis literally named "time" (the convention TpuBackend's default
    layout honors — on a 3-axis mesh like ("series", "x", "time") the
    first-non-series fallback would lay time-major leaves on "x" and
    leave the declared "time" axis unused, ADVICE r5); otherwise the
    first mesh axis that is NOT the series axis.  Taking axis_names[1]
    positionally put the SERIES axis on the time dimension for a mesh
    declared ("time", "series") (ADVICE r4).  Shared by the plain and
    packed spec builders so the two feeds can never resolve different
    time axes for the same mesh."""
    t_ax = config.time_axis
    if t_ax is None:
        rest = [n for n in mesh.axis_names if n != config.series_axis]
        if "time" in rest:
            return "time"
        t_ax = rest[0] if rest else None
    return t_ax


def data_shardings(
    mesh: Mesh, data: FitData, config: ShardingConfig
) -> FitData:
    """PartitionSpecs for each FitData leaf (shaped like the pytree)."""
    s_ax = config.series_axis
    t_ax = _resolve_time_axis(mesh, config)
    bt = P(s_ax, t_ax)
    return FitData(
        t=bt,
        y=bt,
        mask=bt,
        s=P(s_ax, None),
        cap=bt,
        X_season=P(t_ax, None) if data.X_season.ndim == 2 else P(s_ax, t_ax, None),
        X_reg=P(s_ax, t_ax, None),
        prior_scales=P(None),
        mult_mask=P(None),
    )


def packed_shardings(
    mesh: Mesh, packed, config: ShardingConfig
):
    """PartitionSpecs for each PackedFitData leaf (design.PackedFitData).

    Mirrors ``data_shardings`` for the transfer-optimized form: per-series
    leaves shard on the series axis, time-major leaves additionally on the
    time axis.  ``X_reg_bits`` is the one exception — its time axis is
    bit-packed 8 steps per byte, so a time shard boundary would land
    mid-byte unless every shard length were a multiple of 8; the column is
    u8 (32x smaller than its f32 expansion) so replicating it across time
    shards costs less than the alignment bookkeeping would."""
    from tsspark_tpu.models.prophet.design import PackedFitData

    s_ax = config.series_axis
    t_ax = _resolve_time_axis(mesh, config)
    return PackedFitData(
        y=P(s_ax, t_ax),
        ds_rel=P(t_ax),
        t_off=P(s_ax),
        t_inv_span=P(s_ax),
        s=P(s_ax, None),
        cap=P(s_ax, None) if packed.cap.shape[-1] == 1 else P(s_ax, t_ax),
        X_season=(
            P(t_ax, None) if packed.X_season.ndim == 2
            else P(s_ax, t_ax, None)
        ),
        X_reg=P(s_ax, t_ax, None),
        X_reg_bits=P(s_ax, None, None),
        prior_scales=P(None),
        mult_mask=P(None),
    )


# ---------------------------------------------------------------------------
# partition rules + shard/gather fns (the mesh-resident feed machinery)
# ---------------------------------------------------------------------------

def match_partition_rules(rules: Sequence[Tuple[str, P]], tree):
    """PartitionSpec pytree for a NamedTuple batch: each field name is
    matched against ``rules`` (ordered ``(regex, PartitionSpec)`` pairs,
    first match wins) — the rule-driven analog of writing a spec per
    leaf by hand, so a new payload field inherits a layout from its
    name instead of silently defaulting to replicated.  Scalar/0-d
    leaves never partition.  Raises on an unmatched name: a field
    without a rule is a layout decision nobody made."""
    import numpy as np

    def spec_for(name: str, leaf):
        if np.ndim(leaf) == 0:
            return P()
        for pattern, spec in rules:
            if re.search(pattern, name) is not None:
                # Trim trailing axes the leaf does not have (a rank-1
                # leaf under a (series, None) rule shards its one axis).
                return P(*spec[: np.ndim(leaf)])
        raise ValueError(f"no partition rule matches field {name!r}")

    return type(tree)(**{
        name: spec_for(name, getattr(tree, name))
        for name in tree._fields
    })


def resident_partition_rules(series_axis: str,
                             x_season_per_series: bool
                             ) -> Tuple[Tuple[str, P], ...]:
    """THE partition rules of the mesh-resident fit feed
    (``tsspark_tpu.resident``): per-series leaves shard on the series
    axis, shared design tensors replicate.  Time is deliberately NOT
    sharded — per-series math must stay shard-local so the resident
    program is bitwise the single-device program per row
    (tests/test_resident.py pins exactly that)."""
    shared = r"^(ds_rel|prior_scales|mult_mask)$" \
        if x_season_per_series else r"^(ds_rel|prior_scales|mult_mask|X_season)$"
    return (
        (shared, P()),
        (r".*", P(series_axis, None, None)),
    )


def pad_packed_rows(packed, k: int):
    """``packed`` with ``k`` inert series rows appended (host numpy):
    all-NaN ``y`` (the packed encoding of an all-masked series — the
    NaN-fold recovers mask == 0 on device), zeroed time encoding,
    positive logistic cap.  THE padding rule shared by
    ``fit_sharded_packed`` and the resident feed, so a shard-count pad
    can never encode inert rows two different ways."""
    import numpy as np

    if k <= 0:
        return packed

    def pad_rows(a, fill):
        a = np.asarray(a)
        return np.concatenate(
            [a, np.full((k,) + a.shape[1:], fill, a.dtype)]
        )

    return packed._replace(
        y=pad_rows(packed.y, np.nan),   # all-masked -> inert series
        # t_inv_span=0, t_off=0 -> reconstructed t == 0 everywhere,
        # the same inert-row t encoding fit_sharded's zero-padding
        # produces (a 1.0 fill would make t the raw day offsets).
        t_off=pad_rows(packed.t_off, 0.0),
        t_inv_span=pad_rows(packed.t_inv_span, 0.0),
        s=pad_rows(packed.s, 0.0),
        cap=pad_rows(packed.cap, 1.0),  # keep logistic cap positive
        X_reg=pad_rows(packed.X_reg, 0.0),
        X_reg_bits=pad_rows(packed.X_reg_bits, 0),
        X_season=(
            packed.X_season if packed.X_season.ndim == 2
            else pad_rows(packed.X_season, 0.0)
        ),
    )


def make_shard_and_gather_fns(mesh: Mesh, specs):
    """(shard_fns, gather_fns) pytrees from a PartitionSpec pytree.

    ``shard_fns`` place host arrays as sharded device arrays (one
    ``device_put`` per leaf under its NamedSharding — each device
    receives only its shard's bytes); ``gather_fns`` pull a sharded
    leaf back to host numpy.  Apply with ``jax.tree.map(lambda f, x:
    f(x), fns, tree)``; specs are leaves here (``is_leaf`` on
    PartitionSpec), matching the SNIPPETS-style rule machinery."""
    import numpy as np

    def make_shard(spec):
        sharding = NamedSharding(mesh, spec)
        return lambda a: jax.device_put(a, sharding)

    def make_gather(_spec):
        return lambda a: np.asarray(a)

    is_spec = lambda x: isinstance(x, P)
    return (
        jax.tree.map(make_shard, specs, is_leaf=is_spec),
        jax.tree.map(make_gather, specs, is_leaf=is_spec),
    )


@functools.partial(
    jax.jit,
    static_argnames=("config", "solver_config", "reg_u8_cols"),
)
def fit_resident_core(
    packed,
    theta0: jnp.ndarray,
    config,
    solver_config,
    reg_u8_cols: Tuple[int, ...] = (),
    max_iters_dynamic=None,
    gn_precond_dynamic=None,
    use_theta0_dynamic=None,
):
    """The mesh-resident fit program (``tsspark_tpu.resident``).

    Computation-follows-data: the caller ``device_put``s ``packed`` and
    ``theta0`` under the resident partition rules' NamedShardings and
    GSPMD partitions the program from those input shardings — there is
    no ``with_sharding_constraint`` here because the traced body must
    stay EXACTLY ``fit_core_packed``'s (same jaxpr, same traced phase
    controls), which is what makes per-series results bitwise equal to
    the file-protocol chunk workers' (the resident/fileproto parity
    gate).

    Deliberately NOT donated: donating ``theta0`` measurably corrupted
    results under the resident pipeline's ASYNC overlap — with two
    waves in flight on the forced-host multi-device CPU backend, the
    donated-buffer aliasing changed (repeatably, fresh buffers per wave
    included) the bits of whole shards, while serialized dispatches and
    undonated pipelined dispatches both stayed bitwise-identical to the
    single-device program.  The buffer saved is one (B, P) warm start
    (~200 KB at B=1024); the bitwise-parity gate is worth more.  Do not
    re-add donation without re-running tests/test_resident.py's parity
    suite with ``pipeline_depth >= 1`` on the virtual mesh."""
    from tsspark_tpu.models.prophet.model import fit_core_packed

    return fit_core_packed(
        packed, theta0, config, solver_config, reg_u8_cols=reg_u8_cols,
        max_iters_dynamic=max_iters_dynamic,
        gn_precond_dynamic=gn_precond_dynamic,
        use_theta0_dynamic=use_theta0_dynamic,
    )


def _constrained_solve(data, theta0, config, solver_config, mesh, shard_cfg):
    """Shared sharded-solve tail (traced): anchor the FitData/theta
    shardings, build the warm start + preconditioner inside the program,
    run the batched L-BFGS.  Called from both jitted entry points (plain
    and packed-transit)."""
    specs = data_shardings(mesh, data, shard_cfg)
    s_ax = shard_cfg.series_axis
    data = jax.lax.with_sharding_constraint(
        data, jax.tree.map(lambda sp: NamedSharding(mesh, sp), specs,
                           is_leaf=lambda x: isinstance(x, P))
    )
    if theta0 is None:
        theta0 = initial_theta(data, config, solver_config)
    theta0 = jax.lax.with_sharding_constraint(
        theta0, NamedSharding(mesh, P(s_ax, None))
    )
    precond = (
        curvature_diag(data, config, theta0)
        if solver_config.resolved_precond(config.growth) == "gn_diag"
        else None
    )
    fun = lambda th: value_and_grad_batch(th, data, config)
    fval = lambda th: value_batch(th, data, config)
    fan = (lambda th, d, s: fan_value_closed_form(th, d, s, data, config)) \
        if has_closed_form_fan(config) else None
    return lbfgs.minimize(fun, theta0, solver_config, fun_value=fval,
                          precond=precond, fan_value=fan)


@functools.partial(
    jax.jit, static_argnames=("config", "solver_config", "mesh", "shard_cfg")
)
def _fit_sharded_core(data, theta0, config, solver_config, mesh, shard_cfg):
    return _constrained_solve(
        data, theta0, config, solver_config, mesh, shard_cfg
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "config", "solver_config", "mesh", "shard_cfg", "reg_u8_cols"
    ),
)
def _fit_sharded_packed_core(
    packed, theta0, config, solver_config, mesh, shard_cfg, reg_u8_cols
):
    from tsspark_tpu.models.prophet.design import unpack_fit_data

    pspecs = packed_shardings(mesh, packed, shard_cfg)
    packed = jax.lax.with_sharding_constraint(
        packed, jax.tree.map(lambda sp: NamedSharding(mesh, sp), pspecs,
                             is_leaf=lambda x: isinstance(x, P))
    )
    # The unpack (elementwise: NaN-fold mask recovery, bit expansion, t
    # reconstruction) is traced INTO the sharded program, so the expanded
    # (B, T) tensors exist only as device shards — the host->device feed
    # ships the packed bytes.  _constrained_solve then re-anchors the
    # unpacked leaves on the plain FitData shardings.
    data = unpack_fit_data(packed, reg_u8_cols)
    return _constrained_solve(
        data, theta0, config, solver_config, mesh, shard_cfg
    )


def fit_sharded(
    data: FitData,
    theta0: Optional[jnp.ndarray],
    config: ProphetConfig,
    solver_config: SolverConfig,
    mesh: Mesh,
    shard_cfg: ShardingConfig = ShardingConfig(),
) -> lbfgs.LbfgsResult:
    """Fit a batch across the mesh; pads B to the series-shard count.

    ``theta0=None`` computes the warm start inside the sharded program.
    Returns per-series results for the ORIGINAL (unpadded) batch.
    """
    b = data.y.shape[0]
    n_series_shards = mesh.shape[shard_cfg.series_axis]
    b_pad = pad_to_multiple(b, n_series_shards)
    if b_pad != b:
        pad_b = lambda a: jnp.pad(
            a, [(0, b_pad - b)] + [(0, 0)] * (a.ndim - 1)
        )
        data = FitData(
            t=pad_b(data.t),
            y=pad_b(data.y),
            mask=pad_b(data.mask),  # zero mask -> inert dummy series
            s=pad_b(data.s),
            cap=jnp.concatenate(
                [data.cap, jnp.ones((b_pad - b,) + data.cap.shape[1:],
                                    data.cap.dtype)]
            ),
            X_season=data.X_season if data.X_season.ndim == 2
            else pad_b(data.X_season),
            X_reg=pad_b(data.X_reg),
            prior_scales=data.prior_scales,
            mult_mask=data.mult_mask,
        )
        if theta0 is not None:
            theta0 = pad_b(theta0)

    res = _fit_sharded_core(data, theta0, config, solver_config, mesh, shard_cfg)
    if b_pad != b:
        res = jax.tree.map(lambda a: a[:b], res)
    return res


def fit_sharded_packed(
    packed,
    reg_u8_cols: Tuple[int, ...],
    theta0: Optional[jnp.ndarray],
    config: ProphetConfig,
    solver_config: SolverConfig,
    mesh: Mesh,
    shard_cfg: ShardingConfig = ShardingConfig(),
) -> lbfgs.LbfgsResult:
    """Packed-transit analog of ``fit_sharded``.

    The multi-chip host->device feed ships the PackedFitData bytes (~1/3
    of the plain form — NaN-folded mask, bit-packed indicators, on-device
    t reconstruction, design.PackedFitData) and each device receives ONLY
    its shard: leaves are ``device_put`` with their NamedShardings before
    the program runs, so no device ever materializes the full batch.  On
    a real v5e-8 this is the same transfer bottleneck the single-chip
    packed path exists for, 8x wider.

    Padding rows are all-NaN ``y`` (the packed encoding of an all-masked
    inert series — the NaN-fold recovers mask == 0 on device).
    """
    import numpy as np

    b = packed.y.shape[0]
    n_series_shards = mesh.shape[shard_cfg.series_axis]
    b_pad = pad_to_multiple(b, n_series_shards)
    if b_pad != b:
        k = b_pad - b
        packed = pad_packed_rows(packed, k)
        if theta0 is not None:
            theta0 = np.concatenate([
                np.asarray(theta0),
                np.zeros((k,) + np.asarray(theta0).shape[1:],
                         np.asarray(theta0).dtype),
            ])

    pspecs = packed_shardings(mesh, packed, shard_cfg)
    packed = jax.device_put(
        packed,
        jax.tree.map(lambda sp: NamedSharding(mesh, sp), pspecs,
                     is_leaf=lambda x: isinstance(x, P)),
    )
    if theta0 is not None:
        theta0 = jax.device_put(
            jnp.asarray(theta0),
            NamedSharding(mesh, P(shard_cfg.series_axis, None)),
        )
    res = _fit_sharded_packed_core(
        packed, theta0, config, solver_config, mesh, shard_cfg,
        tuple(reg_u8_cols),
    )
    if b_pad != b:
        res = jax.tree.map(lambda a: a[:b], res)
    return res
