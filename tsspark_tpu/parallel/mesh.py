"""Device-mesh construction for sharded batch fitting.

The fit workload is data-parallel over series (the TPU-native analog of the
reference's Spark partition fan-out, BASELINE.json:5) with optional
sequence parallelism over the time axis for very long series: a 2-D
``(series, time)`` mesh.  Collectives ride ICI within a host and DCN across
hosts — XLA inserts them from the sharding annotations; nothing here issues
explicit collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from tsspark_tpu.config import ShardingConfig


def make_mesh(
    n_series_shards: Optional[int] = None,
    n_time_shards: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    config: ShardingConfig = ShardingConfig(),
) -> Mesh:
    """Build a (series, time) mesh over the available devices.

    Defaults put every device on the series axis — the right layout for the
    M5-style many-short-series regime.  ``n_time_shards > 1`` trades series
    parallelism for sequence parallelism (long-series regime).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n_series_shards is None:
        if n % n_time_shards:
            raise ValueError(f"{n} devices not divisible by time={n_time_shards}")
        n_series_shards = n // n_time_shards
    if n_series_shards * n_time_shards != n:
        raise ValueError(
            f"mesh {n_series_shards}x{n_time_shards} != {n} devices"
        )
    arr = np.asarray(devices).reshape(n_series_shards, n_time_shards)
    time_axis = config.time_axis or "time"
    return Mesh(arr, (config.series_axis, time_axis))
