"""Perf telemetry: where the milliseconds of a batched fit actually go.

The ROADMAP demands every PR make a hot path measurably faster — which
is only checkable when runs REPORT their hot-path shape.  This module is
the lightweight recorder every fit path can thread through:

  * per-dispatch wall time (one ``SegmentRecord`` per XLA dispatch:
    a solver segment, a packed chunk fit, or one fused full solve);
  * compile-vs-execute attribution via compile-cache miss detection
    (``CompileWatch`` samples the jit caches of the registered fit
    kernels around each dispatch — a cache-size increase means the
    dispatch paid an XLA compile, so its wall time is compile-tainted);
  * the live-set width trajectory (the compaction scheduler shrinks the
    batch as series converge; ``width`` is the dispatched batch width,
    ``live`` the series still unconverged inside it);
  * series/s throughput once a caller supplies the completed count.

The report rides the returned ``FitState`` exactly like
``ResilienceReport`` does (``attach_perf``/``get_perf`` — the same
best-effort annotation machinery, ``resilience.report.annotate_state``),
is folded into ``BENCH_*.json`` extras by ``bench.py``
(``summarize_times``), and prints via ``python -m tsspark_tpu.perf``.

Host-side only: nothing here runs under a trace, and recording a
segment costs two ``time.perf_counter`` calls plus a cache-size read.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class SegmentRecord:
    """One recorded XLA dispatch."""

    index: int          # arrival order within the recorder
    kind: str           # "segment" | "chunk" | "fit"
    width: int          # dispatched (padded) batch width
    live: int           # series still unconverged in the dispatch
    wall_s: float       # host wall time around the blocking dispatch
    compile_miss: bool  # a watched jit cache grew during the dispatch

    def to_dict(self) -> Dict:
        return {
            "i": self.index, "kind": self.kind, "width": self.width,
            "live": self.live, "wall_s": round(self.wall_s, 4),
            "compile_miss": self.compile_miss,
        }


class CompileWatch:
    """Compile-cache miss detector over a set of jitted callables.

    ``jax.jit`` functions expose ``_cache_size()``; a dispatch that grew
    any watched cache compiled a new executable.  Unknown/missing
    attributes degrade to "no miss observed" rather than failing — the
    recorder must never take a fit down.
    """

    def __init__(self, fns: Sequence = ()):
        self._fns = tuple(fns)

    @classmethod
    def default(cls) -> "CompileWatch":
        """Watch the fit kernels every backend path dispatches through."""
        from tsspark_tpu.models.prophet import model as model_mod

        return cls((
            model_mod.fit_core,
            model_mod.fit_core_packed,
            model_mod.fit_init_core,
            model_mod.fit_segment_core,
        ))

    def size(self) -> int:
        total = 0
        for fn in self._fns:
            probe = getattr(fn, "_cache_size", None)
            if probe is None:
                continue
            try:
                total += int(probe())
            except Exception:
                pass
        return total


@dataclasses.dataclass(frozen=True)
class PerfReport:
    """Aggregated telemetry for one fit (or one recorder lifetime)."""

    segments: Tuple[SegmentRecord, ...] = ()

    @property
    def total_s(self) -> float:
        return sum(s.wall_s for s in self.segments)

    @property
    def compile_s(self) -> float:
        """Wall time of compile-tainted dispatches (upper bound on the
        compile share: the dispatch's execute time is inside it too)."""
        return sum(s.wall_s for s in self.segments if s.compile_miss)

    @property
    def execute_s(self) -> float:
        return sum(s.wall_s for s in self.segments if not s.compile_miss)

    @property
    def compile_misses(self) -> int:
        return sum(1 for s in self.segments if s.compile_miss)

    @property
    def widths(self) -> Tuple[int, ...]:
        """Dispatched width trajectory (the compaction ladder, in order)."""
        return tuple(s.width for s in self.segments)

    def series_per_s(self, n_series: int) -> float:
        t = self.total_s
        return n_series / t if t > 0 else 0.0

    def to_dict(self, n_series: Optional[int] = None) -> Dict:
        d = {
            "segments": [s.to_dict() for s in self.segments],
            "n_dispatches": len(self.segments),
            "total_s": round(self.total_s, 4),
            "compile_s": round(self.compile_s, 4),
            "execute_s": round(self.execute_s, 4),
            "compile_misses": self.compile_misses,
            "width_min": min(self.widths) if self.segments else 0,
            "width_max": max(self.widths) if self.segments else 0,
        }
        if n_series is not None:
            d["series_per_s"] = round(self.series_per_s(n_series), 2)
        return d


class PerfRecorder:
    """Accumulates SegmentRecords across dispatches (and across chunks:
    one recorder on a backend sees every chunk of every fit it serves)."""

    def __init__(self, watch: Optional[CompileWatch] = None):
        self._watch = watch if watch is not None else CompileWatch.default()
        self._segments: List[SegmentRecord] = []

    @contextlib.contextmanager
    def dispatch(self, width: int, live: Optional[int] = None,
                 kind: str = "segment") -> Iterator[None]:
        """Time one blocking XLA dispatch (the body must block_until_ready
        or the wall time measures only the async enqueue)."""
        snap = self._watch.size()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            wall = time.perf_counter() - t0
            self._segments.append(SegmentRecord(
                index=len(self._segments), kind=kind, width=int(width),
                live=int(width if live is None else live),
                wall_s=wall, compile_miss=self._watch.size() > snap,
            ))

    def record(self, width: int, wall_s: float, live: Optional[int] = None,
               kind: str = "segment", compile_miss: bool = False) -> None:
        """Append a pre-timed record (for callers that already own the
        clock, e.g. the orchestrator's per-chunk timing)."""
        self._segments.append(SegmentRecord(
            index=len(self._segments), kind=kind, width=int(width),
            live=int(width if live is None else live),
            wall_s=float(wall_s), compile_miss=bool(compile_miss),
        ))

    def report(self) -> PerfReport:
        return PerfReport(segments=tuple(self._segments))


# ---------------------------------------------------------------------------
# FitState annotation (the ResilienceReport pattern)
# ---------------------------------------------------------------------------

def attach_perf(state, report: PerfReport):
    """Annotate ``state`` with ``report`` as a ``.perf`` attribute (same
    derived-class trick as ``resilience.report.attach_report``; composes
    with an attached resilience report — both attributes survive)."""
    from tsspark_tpu.resilience.report import annotate_state

    return annotate_state(state, "perf", report)


def get_perf(state) -> Optional[PerfReport]:
    """The ``PerfReport`` attached to ``state``, or None."""
    return getattr(state, "perf", None)


# ---------------------------------------------------------------------------
# times.jsonl -> BENCH extras summarization (bench.py + __main__)
# ---------------------------------------------------------------------------

def summarize_times(times: Sequence[Dict],
                    autotune: Optional[Dict] = None) -> Dict:
    """The ``extra.perf`` block of a BENCH summary, from the orchestrate
    worker's ``times.jsonl`` rows (tolerates rows from older workers that
    lack the telemetry fields).

    ``autotune``: the persisted ``autotune.json`` payload, embedded
    verbatim so a committed BENCH artifact carries the learned chunk
    size alongside the throughput it bought.
    """
    chunks = [t for t in times if "fit_s" in t]
    per_size: Dict[int, List[float]] = {}
    for t in chunks:
        size = int(t.get("width", t.get("chunk", 0)) or 0)
        sps = t.get("series_per_s")
        if sps is None and t.get("fit_s"):
            sps = (t["hi"] - t["lo"]) / t["fit_s"]
        if size and sps:
            per_size.setdefault(size, []).append(float(sps))
    out = {
        "n_chunks": len(chunks),
        "first_flush_s": next(
            (round(float(t["t"]), 2) for t in chunks if "t" in t), None
        ),
        "compile_misses": sum(
            1 for t in chunks if t.get("compile_miss")
        ),
        "chunk_sizes": sorted(per_size),
        "series_per_s_by_size": {
            str(k): round(sum(v) / len(v), 2)
            for k, v in sorted(per_size.items())
        },
        "segments": [
            {k: t[k] for k in
             ("lo", "hi", "width", "live", "fit_s", "series_per_s",
              "compile_miss", "t") if k in t}
            for t in chunks
        ],
    }
    if autotune:
        out["autotune"] = autotune
    return out
