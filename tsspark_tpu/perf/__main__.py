"""``python -m tsspark_tpu.perf`` — print a fit's perf telemetry.

Accepts a BENCH summary JSON (``bench.py``'s one-line output, e.g. a
committed ``BENCH_*.json`` — reads ``extra.perf``), an orchestrate
scratch/out directory (reads ``times.jsonl`` + ``autotune.json``
directly), or a ``RUNLEDGER_*.json`` run ledger (tsspark_tpu.obs —
reads its embedded ``perf`` block).  Device-free: never imports JAX.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tsspark_tpu.perf.recorder import summarize_times


def _load(target: str) -> dict:
    if os.path.isdir(target):
        times = []
        tpath = os.path.join(target, "times.jsonl")
        if os.path.exists(tpath):
            with open(tpath) as fh:
                for line in fh:
                    if line.strip():
                        try:
                            times.append(json.loads(line))
                        except ValueError:
                            pass  # torn tail line of a killed worker
        autotune = None
        apath = os.path.join(target, "autotune.json")
        if os.path.exists(apath):
            try:
                with open(apath) as fh:
                    autotune = json.load(fh)
            except ValueError:
                pass
        return summarize_times(times, autotune)
    with open(target) as fh:
        summary = json.load(fh)
    if summary.get("kind") == "run-ledger":
        perf = summary.get("perf")
        if perf is None:
            raise SystemExit(
                f"{target}: run ledger carries no perf block (no "
                "times.jsonl rows were found when it was built)"
            )
        return perf
    perf = summary.get("extra", {}).get("perf")
    if perf is None:
        raise SystemExit(
            f"{target}: no extra.perf block (pre-telemetry BENCH artifact?)"
        )
    return perf


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tsspark_tpu.perf",
        description="perf telemetry summary (docs/PERF.md)",
    )
    ap.add_argument("target",
                    help="BENCH summary JSON file or orchestrate out dir")
    ap.add_argument("--json", action="store_true",
                    help="print the raw perf dict instead of the table")
    args = ap.parse_args(argv)
    perf = _load(args.target)
    if args.json:
        print(json.dumps(perf, indent=2))
        return 0

    print(f"chunks fitted:     {perf.get('n_chunks', 0)}")
    ff = perf.get("first_flush_s")
    print(f"first chunk flush: {ff if ff is not None else 'n/a'} s")
    print(f"compile misses:    {perf.get('compile_misses', 0)}")
    by_size = perf.get("series_per_s_by_size", {})
    if by_size:
        print("series/s by chunk size:")
        for size, sps in by_size.items():
            print(f"  {size:>6}: {sps}")
    at = perf.get("autotune")
    if at:
        print(f"autotuned chunk:   {at.get('chunk')}")
    segs = perf.get("segments", [])
    if segs:
        print(f"dispatches ({len(segs)}):")
        for s in segs[:40]:
            width = s.get("width", s.get("chunk", "?"))
            live = s.get("live", "")
            live_txt = f" live={live}" if live != "" else ""
            miss = " [compile]" if s.get("compile_miss") else ""
            sps = s.get("series_per_s")
            sps_txt = f" {sps} series/s" if sps is not None else ""
            print(f"  [{s.get('lo', '?')}:{s.get('hi', '?')}] "
                  f"w={width}{live_txt} {s.get('fit_s', '?')}s"
                  f"{sps_txt}{miss}")
        if len(segs) > 40:
            print(f"  ... {len(segs) - 40} more")
    return 0


if __name__ == "__main__":
    sys.exit(main())
