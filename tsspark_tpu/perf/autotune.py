"""Online chunk-size autotuner: find the throughput-optimal batch width.

BENCH_r05 finished 0 of 30,490 series in 875 s: the fixed 1024-series
chunk meant the very first dispatch had to compile and run a huge
program before ANYTHING flushed, and on a degraded (tunnel-down, CPU)
runtime that first dispatch alone outlived the stall watchdog.  The
right chunk size is a property of the RUNTIME (one the parent cannot
observe up front), so it is learned online:

  * start SMALL (``floor``, default 128) so the first chunk file lands
    within seconds — the run demonstrates liveness and banks progress
    immediately, whatever the hardware turns out to be;
  * after each chunk, record series/s for its size and hill-climb along
    the power-of-2 ladder: explore the next size up once the current
    one has a warm (compile-free) measurement, move toward whichever
    neighbor measures better, stay put at a local optimum;
  * compile-tainted samples never drive a decision — a fresh width's
    first dispatch pays its XLA compile, and judging the width by that
    sample would brand every new size slow;
  * persist the learned state (``autotune.json``, atomic) next to the
    run's chunk files so a resumed run — or the streaming driver via
    ``load_learned_chunk`` — starts at the learned width instead of
    re-walking the ladder.

Numerics: chunk width only changes how series are GROUPED into lockstep
programs; every per-series trajectory is row-local (the compaction
parity tests pin this), so tuning is throughput-only.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from tsspark_tpu.utils.atomic import atomic_write_text

# A neighbor must beat the incumbent by this factor to pull the tuner
# over: chunk-to-chunk throughput noise (data-dependent convergence,
# host jitter) is well above 1%, and oscillating between two near-equal
# sizes would pay gratuitous compile churn on any new runtime.
_HYSTERESIS = 1.05
# Per-size sample window for the throughput estimate: recent samples
# only, so a one-off slow chunk (GC pause, probe overlap) ages out.
_WINDOW = 4


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class ChunkAutotuner:
    """Hill-climbing pow-2 chunk-size tuner (see module docstring).

    ``cap`` is the largest size the caller trusts (the old fixed chunk:
    1024 is the largest that survives the TPU tunnel's crash envelope);
    ``floor`` the smallest worth dispatching.  ``state_path=None`` keeps
    the tuner in-memory (tests, streaming).

    ``multiple``: every size the tuner emits is at least this and (for
    a power-of-two multiple) divisible by it — the SHARD-WIDTH hook for
    the mesh-resident path (``tsspark_tpu.resident``), which tunes the
    per-wave width over an ``n_shards``-device mesh: a wave must divide
    evenly across the series shards or each dispatch pays inert pad
    rows on every device.  The ladder stays pow-2 (compiled-program
    reuse), so a pow-2 ``multiple`` composes exactly; a non-pow-2 one
    only floors the ladder (the resident feed pads the remainder).
    """

    def __init__(self, cap: int, floor: int = 128,
                 state_path: Optional[str] = None,
                 start: Optional[int] = None,
                 multiple: int = 1):
        self.cap = max(1, int(cap))
        self.multiple = max(1, int(multiple))
        self.floor = max(1, min(max(int(floor), self.multiple), self.cap))
        self.state_path = state_path
        self._samples: Dict[int, List[float]] = {}
        size = self.floor if start is None else int(start)
        self._cur = min(max(_next_pow2(size), self.floor), self.cap)

    # -- persistence -------------------------------------------------------

    @classmethod
    def load(cls, state_path: str, cap: int,
             floor: int = 128, multiple: int = 1) -> "ChunkAutotuner":
        """Tuner warm-started from a persisted state file (fresh tuner
        when the file is absent/corrupt — the state is pure cache)."""
        start = None
        samples: Dict[int, List[float]] = {}
        try:
            with open(state_path) as fh:
                d = json.load(fh)
            # AttributeError covers valid-JSON-but-not-a-dict payloads
            # (d.get on a list/str): the state is pure cache, and ANY
            # unreadable form must yield a fresh tuner, never a
            # crash-looping fit worker.
            # A resumed tuner continues from the exploration CURSOR when
            # recorded (older files carry only the measured-best
            # "chunk", which is the right fallback).
            start = int(d.get("cursor", 0) or d.get("chunk", 0)) or None
            samples = {
                int(k): [float(x) for x in v][-_WINDOW:]
                for k, v in d.get("series_per_s", {}).items()
            }
        except (OSError, ValueError, TypeError, AttributeError):
            pass
        tuner = cls(cap=cap, floor=floor, state_path=state_path,
                    start=start, multiple=multiple)
        tuner._samples = {
            k: v for k, v in samples.items()
            if tuner.floor <= k <= tuner.cap
        }
        return tuner

    def save(self) -> None:
        if self.state_path is None:
            return
        payload = json.dumps({
            # "chunk" is the MEASURED-BEST width — what every external
            # consumer (streaming warm start, bench prep sizing,
            # load_learned_chunk) wants; "cursor" is the hill-climber's
            # own next-dispatch position, which may be an unexplored
            # rung mid-exploration.
            "chunk": self.best_size,
            "cursor": self._cur,
            "series_per_s": {
                str(k): [round(x, 3) for x in v]
                for k, v in sorted(self._samples.items())
            },
            "updated": time.time(),
        })
        try:
            atomic_write_text(self.state_path, payload + "\n")
        except OSError:
            pass  # the state is cache; a full disk must not kill the fit

    # -- the online loop ---------------------------------------------------

    def next_size(self) -> int:
        """The chunk size the next dispatch should use."""
        return self._cur

    def throughput(self, size: int) -> Optional[float]:
        """Mean warm series/s for ``size`` (None until warm-sampled)."""
        v = self._samples.get(size)
        return sum(v) / len(v) if v else None

    @property
    def best_size(self) -> int:
        """Highest-throughput warm-sampled size (current size when none
        is warm yet) — what phase-2 style followers should dispatch at."""
        if not self._samples:
            return self._cur
        return max(self._samples, key=lambda k: self.throughput(k) or 0.0)

    def record(self, size: int, n_series: int, wall_s: float,
               compile_miss: bool = False) -> None:
        """Fold one chunk's measurement in and re-decide the next size."""
        size = int(size)
        if wall_s <= 0 or n_series <= 0:
            return
        if not compile_miss:
            window = self._samples.setdefault(size, [])
            window.append(n_series / wall_s)
            del window[:-_WINDOW]
            self._decide()
        self.save()

    def _decide(self) -> None:
        cur_tp = self.throughput(self._cur)
        if cur_tp is None:
            return  # no warm sample at the current size yet: hold
        up, down = self._cur * 2, self._cur // 2
        up_tp = self.throughput(up) if up <= self.cap else None
        down_tp = self.throughput(down) if down >= self.floor else None
        if (up <= self.cap and up_tp is None
                and (down_tp is None or cur_tp >= down_tp)):
            # Explore upward while the climb is still paying: the ladder
            # starts at the floor, so the unexplored direction with
            # headroom is always up — but a size that already measures
            # worse than its lower neighbor must not climb further.
            self._cur = up
        elif up_tp is not None and up_tp > cur_tp * _HYSTERESIS:
            self._cur = up
        elif down_tp is not None and down_tp > cur_tp * _HYSTERESIS:
            self._cur = down


def load_learned_chunk(state_path: str) -> Optional[int]:
    """The persisted learned chunk size, or None (absent/corrupt file).
    The streaming driver's warm start: a driver pointed at a completed
    run's ``autotune.json`` sizes its refit chunks from measured
    throughput instead of a static default."""
    try:
        with open(state_path) as fh:
            return int(json.load(fh)["chunk"]) or None
    except (OSError, ValueError, TypeError, KeyError):
        return None
