"""Perf telemetry + online tuning (``docs/PERF.md``).

* ``recorder`` — per-dispatch telemetry (wall, width, compile-miss,
  series/s) attached to ``FitState`` like the resilience report.
* ``autotune`` — online pow-2 chunk-size hill climber with persisted
  state, consumed by ``orchestrate.fit_worker`` and the streaming
  driver's warm start.
* ``python -m tsspark_tpu.perf`` — summary printer over a BENCH JSON
  or an orchestrate scratch dir.

Importing this package stays light (stdlib only); JAX loads only when
``CompileWatch.default()`` resolves the fit kernels.
"""

from tsspark_tpu.perf.autotune import ChunkAutotuner, load_learned_chunk
from tsspark_tpu.perf.recorder import (
    CompileWatch,
    PerfRecorder,
    PerfReport,
    SegmentRecord,
    attach_perf,
    get_perf,
    summarize_times,
)

__all__ = [
    "ChunkAutotuner",
    "CompileWatch",
    "PerfRecorder",
    "PerfReport",
    "SegmentRecord",
    "attach_perf",
    "get_perf",
    "load_learned_chunk",
    "summarize_times",
]
