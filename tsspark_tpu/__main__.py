"""Command-line interface: fit / predict / backtest over CSV or Parquet.

The reference's user entry points are programmatic (DataFrame in, forecast
out); this CLI wraps the same Forecaster surface for shell pipelines:

  python -m tsspark_tpu fit      --input sales.csv --model model.npz
  python -m tsspark_tpu predict  --model model.npz --horizon 28 --output fc.csv
  python -m tsspark_tpu forecast --input sales.csv --horizon 28 --output fc.csv
  python -m tsspark_tpu backtest --input sales.csv --horizon 14 --output pm.csv

Input is a long frame (series_id, ds, y [, regressors...]).  Model files are
portable .npz checkpoints (utils/checkpoint.py).
"""

from __future__ import annotations

import argparse
import json
import sys


def _read_frame(path: str):
    import pandas as pd

    if path.endswith((".parquet", ".pq")):
        return pd.read_parquet(path)
    return pd.read_csv(path, parse_dates=["ds"])


def _write_frame(df, path: str) -> None:
    if path == "-":
        df.to_csv(sys.stdout, index=False)
    elif path.endswith((".parquet", ".pq")):
        df.to_parquet(path, index=False)
    else:
        df.to_csv(path, index=False)


def _build_forecaster(args, df=None):
    from tsspark_tpu import (
        DAILY,
        Forecaster,
        ProphetConfig,
        SeasonalityConfig,
        SolverConfig,
        WEEKLY,
        YEARLY,
        country_holidays,
    )

    named = {"yearly": YEARLY, "weekly": WEEKLY, "daily": DAILY}
    seas = []
    for spec in args.seasonality:
        if spec in named:
            seas.append(named[spec])
        else:  # name:period:order
            name, period, order = spec.split(":")
            seas.append(SeasonalityConfig(name, float(period), int(order)))
    holidays = ()
    if args.country_holidays:
        import pandas as pd

        if df is not None:
            years = range(
                pd.to_datetime(df["ds"]).dt.year.min(),
                pd.to_datetime(df["ds"]).dt.year.max() + 2,
            )
        else:
            years = range(2015, 2031)
        holidays = country_holidays(args.country_holidays, years=years)
    cfg = ProphetConfig(
        growth=args.growth,
        n_changepoints=args.n_changepoints,
        changepoint_prior_scale=args.changepoint_prior_scale,
        seasonalities=tuple(seas),
        seasonality_mode=args.seasonality_mode,
        interval_width=args.interval_width,
    )
    return Forecaster(
        cfg,
        backend=args.backend,
        holidays=holidays,
        regressor_cols=tuple(args.regressor),
        cap_col="cap" if args.growth == "logistic" else None,
        solver_config=SolverConfig(max_iters=args.max_iters),
        auto_seasonality=args.auto_seasonality,
    )


def _add_model_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--backend", default="tpu", help="forecast backend name")
    p.add_argument("--growth", default="linear",
                   choices=["linear", "logistic", "flat"])
    p.add_argument("--seasonality", action="append",
                   default=None, metavar="NAME[:PERIOD:ORDER]",
                   help="repeatable; yearly/weekly/daily or custom "
                        "name:period_days:fourier_order")
    p.add_argument("--seasonality-mode", default="additive",
                   choices=["additive", "multiplicative"])
    p.add_argument("--n-changepoints", type=int, default=25)
    p.add_argument("--changepoint-prior-scale", type=float, default=0.05)
    p.add_argument("--interval-width", type=float, default=0.8)
    p.add_argument("--regressor", action="append", default=[],
                   help="repeatable external regressor column name")
    p.add_argument("--country-holidays", default=None, metavar="CC",
                   help="ISO country code for a computed holiday calendar")
    p.add_argument("--auto-seasonality", action="store_true",
                   help="choose yearly/weekly/daily from the observed "
                        "calendar at fit time (overrides --seasonality)")
    p.add_argument("--max-iters", type=int, default=200)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tsspark_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_fit = sub.add_parser("fit", help="fit and save a model checkpoint")
    p_fit.add_argument("--input", required=True)
    p_fit.add_argument("--model", required=True, help="output .npz path")
    _add_model_args(p_fit)

    p_pred = sub.add_parser("predict", help="forecast from a checkpoint")
    p_pred.add_argument("--model", required=True)
    p_pred.add_argument("--horizon", type=int, required=True)
    p_pred.add_argument("--output", default="-")
    p_pred.add_argument("--include-history", action="store_true")

    p_fc = sub.add_parser("forecast", help="fit + predict in one go")
    p_fc.add_argument("--input", required=True)
    p_fc.add_argument("--horizon", type=int, required=True)
    p_fc.add_argument("--output", default="-")
    p_fc.add_argument("--include-history", action="store_true")
    p_fc.add_argument("--future", default=None,
                      help="future frame with ds + regressor/cap columns")
    _add_model_args(p_fc)

    p_bt = sub.add_parser("backtest",
                          help="rolling-origin CV + performance metrics")
    p_bt.add_argument("--input", required=True)
    p_bt.add_argument("--horizon", type=float, required=True)
    p_bt.add_argument("--period", type=float, default=None)
    p_bt.add_argument("--initial", type=float, default=None)
    p_bt.add_argument("--output", default="-",
                      help="performance-metrics table destination")
    p_bt.add_argument("--cv-output", default=None,
                      help="optionally also write the raw CV frame")
    _add_model_args(p_bt)

    args = ap.parse_args(argv)
    if getattr(args, "seasonality", None) is None:
        args.seasonality = ["yearly", "weekly"]

    if args.cmd == "fit":
        from tsspark_tpu.utils import checkpoint

        df = _read_frame(args.input)
        fc = _build_forecaster(args, df)
        fc.fit(df)
        checkpoint.save_forecaster(args.model, fc)
        print(json.dumps({"saved": args.model,
                          "n_series": len(fc.series_ids)}))
        return 0

    if args.cmd == "predict":
        from tsspark_tpu.utils import checkpoint

        fc = checkpoint.load_forecaster(args.model)
        out = fc.predict(horizon=args.horizon,
                         include_history=args.include_history)
        _write_frame(out, args.output)
        return 0

    if args.cmd == "forecast":
        df = _read_frame(args.input)
        fc = _build_forecaster(args, df)
        fc.fit(df)
        future = _read_frame(args.future) if args.future else None
        out = fc.predict(horizon=args.horizon, future_df=future,
                         include_history=args.include_history)
        _write_frame(out, args.output)
        return 0

    if args.cmd == "backtest":
        from tsspark_tpu.eval import diagnostics

        df = _read_frame(args.input)
        fc = _build_forecaster(args, df)
        cv = diagnostics.cross_validation(
            fc, df, horizon=args.horizon,
            period=args.period, initial=args.initial,
        )
        if args.cv_output:
            _write_frame(cv, args.cv_output)
        _write_frame(diagnostics.performance_metrics(cv), args.output)
        return 0

    return 1


if __name__ == "__main__":
    sys.exit(main())
