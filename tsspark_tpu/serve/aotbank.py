"""AOT program bank: the serve tier's compile wall paid at publish.

A cold replica's first request used to pay the full ``forecast_jit``
compile ladder (SCALE_1m measured ``time_to_first_request_s`` = 7.466s,
almost all compiles).  This module moves that cost to PUBLISH time: the
flip orchestrator calls :func:`build_bank`, which walks the known
(width, horizon-bucket) shape ladder the engine's pow-2 discipline
produces and ``jax.jit(...).lower(...).compile()``s each program with
the persistent JAX compilation cache armed at a shared directory.  A
replica that arms the same directory (:func:`arm_from_env` — the
``$TSSPARK_AOT_CACHE_DIR`` contract, inherited by pool children) then
LOADS its first-request programs from the cache instead of compiling
them, so cold start stops paying the wall.

The bank is recorded in an ``aot_bank.json`` manifest (atomic write)
keyed by config fingerprint + ladder, which makes :func:`build_bank`
idempotent across flips of the same model shape — rebuilds happen only
when the fingerprint or the ladder changes.

The bank is an ACCELERATOR, never a correctness dependency: a missing
or stale cache dir just means the replica compiles as before.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tsspark_tpu.io import atomic_write
from tsspark_tpu.obs import context as obs
from tsspark_tpu.parallel.sharding import next_pow2

__all__ = [
    "AOT_CACHE_ENV", "AOT_MANIFEST", "DEFAULT_WIDTHS",
    "cache_dir_from_env", "arm", "arm_from_env", "shape_ladder",
    "build_bank", "read_manifest",
]

#: The shared compilation-cache directory contract: set it and every
#: process (publisher, front, replicas — children inherit the env)
#: compiles into / loads from the same persistent cache.
AOT_CACHE_ENV = "TSSPARK_AOT_CACHE_DIR"

#: Bank manifest (written into the cache dir, atomically).
AOT_MANIFEST = "aot_bank.json"

#: Dispatch widths the engine's compaction ladder actually produces for
#: hot traffic (``compacted_width`` floor .. a typical materialize
#: chunk).  Widths above the snapshot's row count are skipped.
DEFAULT_WIDTHS = (8, 16, 32, 64, 128, 256)


def cache_dir_from_env() -> Optional[str]:
    """The configured AOT cache directory, or None when unset."""
    return os.environ.get(AOT_CACHE_ENV) or None


def arm(dirpath: str) -> None:
    """Point JAX's persistent compilation cache at ``dirpath`` with a
    zero min-compile-time floor, so even the small serve programs
    persist (the default 1s floor would skip exactly the programs a
    replica's cold start pays for).

    The cache singleton initializes LAZILY at the process's first
    compile and then ignores config updates — a publisher that already
    dispatched anything (e.g. the fit that produced the version) would
    silently write nothing — so arming resets it when the configured
    dir actually changed."""
    import jax

    os.makedirs(dirpath, exist_ok=True)
    rearm = jax.config.jax_compilation_cache_dir != dirpath
    jax.config.update("jax_compilation_cache_dir", dirpath)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    if rearm:
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.reset_cache()
        except Exception:
            pass  # older jax: the lazy init may still pick the dir up


def arm_from_env() -> Optional[str]:
    """Arm the cache from ``$TSSPARK_AOT_CACHE_DIR`` when set (the
    replica/daemon entry hook).  Returns the armed dir or None."""
    d = cache_dir_from_env()
    if d:
        arm(d)
    return d


def shape_ladder(n_series: int,
                 horizons: Sequence[int],
                 widths: Sequence[int] = DEFAULT_WIDTHS
                 ) -> List[Tuple[int, int]]:
    """The (width, horizon-bucket) pairs worth pre-compiling: the
    engine pads widths up ``compacted_width``'s pow-2 ladder and
    horizons up ``max(8, next_pow2(h))``, so this finite grid IS the
    serve tier's hot program set."""
    from tsspark_tpu.serve.fplane import bucket_ladder

    cap = next_pow2(max(int(n_series), 1))
    ws = sorted({int(w) for w in widths if int(w) <= cap} or {cap})
    return [(w, hb) for w in ws for hb in bucket_ladder(horizons)]


def read_manifest(dirpath: str) -> Optional[Dict]:
    """The bank manifest in ``dirpath``, or None (absent/torn)."""
    try:
        with open(os.path.join(dirpath, AOT_MANIFEST)) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def build_bank(snap, backend, *, dirpath: Optional[str] = None,
               horizons: Sequence[int] = (7, 14, 28),
               widths: Sequence[int] = DEFAULT_WIDTHS,
               fingerprint: Optional[str] = None) -> Optional[Dict]:
    """AOT-compile the serve program ladder against ``snap``'s
    parameter shapes and persist the executables via the JAX
    compilation cache in ``dirpath`` (default: the env contract; no
    dir configured -> no-op, returns None).

    Each (width, horizon-bucket) entry traces the engine's exact
    dispatch: a ``width``-row gather, the float64 future grid, and the
    deterministic ``forecast_jit`` program at num_samples=0 —
    ``jit.lower(...).compile()``, so the trace happens here and the
    replica's first request is a cache load.  Idempotent per
    (fingerprint, ladder): an up-to-date manifest short-circuits."""
    dirpath = dirpath or cache_dir_from_env()
    if not dirpath:
        return None
    ladder = shape_ladder(
        int(np.asarray(snap.state.theta).shape[0]), horizons, widths
    )
    want = {"fingerprint": fingerprint,
            "ladder": [[w, hb] for w, hb in ladder]}
    have = read_manifest(dirpath)
    if have is not None \
            and {k: have.get(k) for k in want} == want:
        return dict(have, status="present")
    arm(dirpath)
    import jax

    from tsspark_tpu.models.prophet import predict as predict_mod
    from tsspark_tpu.serve.fplane import future_grid

    model = getattr(backend, "_model", None)
    if model is None:
        return None  # non-prophet backend: nothing to pre-compile
    entries = []
    t_bank0 = time.time()
    for width, hb in ladder:
        idx = np.arange(min(width, len(np.asarray(snap.step))))
        if width > len(idx):
            idx = np.concatenate(
                [idx, np.repeat(idx[:1], width - len(idx))]
            )
        state, step = snap.take(idx)
        grid = future_grid(state, step, hb)
        data = predict_mod.prepare_predict_data(
            grid, state.meta, model.config
        )
        t0 = time.time()
        lowered = predict_mod.forecast_jit.lower(
            state.theta, data, state.meta, model.config,
            key=jax.random.PRNGKey(0), num_samples=0,
            return_samples=False,
        )
        lowered.compile()
        entries.append({"width": int(width), "horizon_bucket": int(hb),
                        "compile_s": round(time.time() - t0, 3)})
    manifest = dict(
        want,
        entries=entries,
        built_s=round(time.time() - t_bank0, 3),
        unix=round(time.time(), 3),
        jax=jax.__version__,
    )
    atomic_write(os.path.join(dirpath, AOT_MANIFEST),
                 lambda fh: json.dump(manifest, fh, indent=1),
                 mode="w")
    obs.event("aotbank.built", dir=dirpath, n=len(entries),
              built_s=manifest["built_s"])
    return dict(manifest, status="built")
