"""Engine replica pool: the serving read path scaled out horizontally.

One ``PredictionEngine`` process is a single failure domain — its death
takes every forecast consumer with it (ROADMAP item 4).  This module
puts N engine REPLICA PROCESSES behind a single front:

* **Replicas** (``run_replica``, spawned as ``python -m
  tsspark_tpu.serve.replica``) each own a full engine over the shared
  ``ParamRegistry`` and serve the daemon's JSONL envelope over a
  unix-domain socket.  Every replica holds a **lease on its slot**
  (the orchestrate chunk-lease machinery reused on ``[slot, slot+1)``),
  renews it from its heartbeat thread, and **fences every response** on
  still holding it: a zombie replica revived after its slot was stolen
  answers ``fenced`` errors, never data — the split-brain guarantee
  that a stale parameter version cannot be served by a replaced
  process.
* **The front** (``ReplicaPool``) shards requests by series key
  (``shard_of`` — stable CRC32 of the first series id), health-checks
  replicas via heartbeat files, wraps each replica in its own
  ``CircuitBreaker``, and **fails over** a request to the next sibling
  slot when a replica dies mid-request, its breaker is open, or it
  answers ``fenced`` — transport failures are retried on siblings, so a
  single replica kill costs zero non-shed requests.  Dead or wedged
  replicas are respawned under ``RetryPolicy`` backoff; the replacement
  process claims the slot lease itself, so the lease (not the front's
  opinion) arbitrates which process owns a slot.
* **Version discipline**: the front stamps ``expect_version`` into
  every routed request; replicas refresh on mismatch and answer a
  structured ``version-mismatch`` error rather than serving a version
  the front did not expect — closing the stale-read window between an
  activation and a replica's refresh.  ``ReplicaPool.activate`` flips a
  version by first **materializing** hot forecasts for the new version
  into every replica's version-keyed cache (``PredictionEngine.
  materialize`` — ahead-of-time compute, the speculative-decoding bet),
  then flipping the registry pointer, then draining replicas one at a
  time through an explicit refresh — p99 stays flat through the flip
  because the first post-flip requests are cache hits on a prefetched
  snapshot.
* **Front crash tolerance**: the pool's state (slot → socket/pid/gen)
  is persisted in ``pool.json``; ``ReplicaPool.attach`` rebuilds a
  front over the LIVE replicas of a dead one without restarting them.

The wire protocol is the serve daemon's JSONL envelope plus control
commands (``ping`` / ``stats`` / ``metrics`` / ``warm`` / ``refresh`` /
``quit``) and two extra response fields: ``replica`` (the answering
slot) and the structured ``fenced`` / ``version-mismatch`` errors.
``docs/SERVING.md`` ("Replica pool & failure domains") is the operator
walkthrough; the pool-scale chaos classes (``replica-kill``,
``split-brain-activation``, ``front-crash``) drive all of this under
storm in ``tsspark_tpu.chaos``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import select
import signal
import socket
import subprocess
import sys
import threading
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from tsspark_tpu.obs import context as obs
from tsspark_tpu.obs.metrics import DEFAULT as METRICS
from tsspark_tpu.resilience.policy import CircuitBreaker, RetryPolicy
from tsspark_tpu.serve.engine import ServeError
from tsspark_tpu.io import atomic_write, current_state, stale_serving

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
))

#: Replica exit codes (the spawner reads them off ``Popen.poll``).
RC_LEASE_HELD = 3      # slot lease is live under another process
RC_FENCED = 4          # lease lost while serving (replaced); clean exit

_POOL_STATE = "pool.json"


class PoolError(ServeError):
    """Base of the pool front's structured errors."""

    reason = "pool-error"


class NoReplicaAvailable(PoolError):
    """Every candidate replica for a request's shard order was dead,
    fenced, or breaker-open — the request could not be placed."""

    reason = "no-replica"


class ReplicaFenced(ServeError):
    """The answering replica no longer holds its slot lease (it was
    replaced while stalled); it refuses to serve data."""

    reason = "fenced"


class VersionMismatch(ServeError):
    """The replica's served version differs from the version the front
    stamped into the request, even after a forced refresh."""

    reason = "version-mismatch"

    def __init__(self, served, expected):
        self.served = served
        self.expected = expected
        super().__init__(
            f"replica serves version {served}, front expected {expected}"
        )

    def to_dict(self) -> Dict:
        d = super().to_dict()
        d["served"] = self.served
        d["expected"] = self.expected
        return d


def shard_of(series_id, n_shards: int) -> int:
    """Stable shard of a series key: CRC32 of the id string.  Requests
    route to the shard of their FIRST series id; the failover order for
    shard ``s`` is ``s, s+1, ... (mod n)``."""
    return zlib.crc32(str(series_id).encode()) % max(1, int(n_shards))


def _slot_token(slot: int) -> str:
    return f"pool{slot}.{os.getpid()}.{int(time.time() * 1e3)}"


def _hb_path(pool_dir: str, slot: int) -> str:
    return os.path.join(pool_dir, f"poolhb_{slot}")


def _send_line(sock: socket.socket, obj: Dict) -> None:
    sock.sendall((json.dumps(obj) + "\n").encode())


class _LineReader:
    """Newline-framed reads over a socket with manual buffering.

    ``socket.makefile()`` is documented-unsafe under a timeout (a
    timeout mid-read leaves the buffered file object in an inconsistent
    state); manual ``recv`` buffering keeps partial lines intact across
    timeouts, which the replica's poll-for-stop read loop hits
    constantly on idle connections."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = b""

    def readline(self, poll_s: Optional[float] = None) -> Optional[bytes]:
        """One full line (without the newline), or None on EOF.
        ``socket.timeout`` propagates with the partial line preserved.

        ``poll_s``: wait for readability via ``select`` instead of the
        socket timeout — the server side keeps its accepted sockets
        BLOCKING (a shared socket timeout would also cap ``sendall``,
        and a response stream larger than the socket buffer would then
        tear the connection whenever the peer drains another socket
        first) and polls reads here."""
        while b"\n" not in self.buf:
            if poll_s is not None:
                ready, _, _ = select.select([self.sock], [], [], poll_s)
                if not ready:
                    raise socket.timeout()
            chunk = self.sock.recv(65536)
            if not chunk:
                return None
            self.buf += chunk
        line, self.buf = self.buf.split(b"\n", 1)
        return line


def _pid_alive(pid: Optional[int]) -> bool:
    if not pid or pid <= 0:
        return False
    try:
        os.kill(int(pid), 0)
        return True
    except OSError:
        return False


# ---------------------------------------------------------------------------
# the replica process
# ---------------------------------------------------------------------------


class _Replica:
    """One replica's server loop: engine + UDS JSONL + lease fencing.

    Runs inside its own process (``run_replica``).  The slot lease is
    claimed before the engine attaches and renewed from the heartbeat
    thread; ``fenced`` flips the moment a renewal finds the lease under
    a foreign token, after which every forecast response is the
    structured ``fenced`` error and the process exits after a short
    grace window (long enough for probes to observe the refusal)."""

    def __init__(self, pool_dir: str, slot: int, registry_root: str,
                 socket_path: str, *, gen: int = 1,
                 heartbeat_s: float = 0.25, lease_ttl_s: float = 1.5,
                 max_queue: int = 4096, max_batch: int = 128,
                 cache_capacity: Optional[int] = None,
                 fence_grace_s: float = 8.0):
        self.pool_dir = pool_dir
        self.slot = int(slot)
        self.gen = int(gen)
        self.registry_root = registry_root
        self.socket_path = socket_path
        self.heartbeat_s = float(heartbeat_s)
        self.lease_ttl_s = float(lease_ttl_s)
        self.max_queue = int(max_queue)
        self.max_batch = int(max_batch)
        self.cache_capacity = (None if cache_capacity is None
                               else int(cache_capacity))
        self.fence_grace_s = float(fence_grace_s)
        self.token = _slot_token(self.slot)
        self.fenced = threading.Event()
        self.stop = threading.Event()
        self.engine = None
        self.registry = None

    # -- lease fencing ---------------------------------------------------------

    def _claim_slot(self) -> bool:
        from tsspark_tpu import orchestrate

        return orchestrate.claim_lease(
            self.pool_dir, self.slot, self.slot + 1, self.token,
            ttl_s=self.lease_ttl_s,
        )

    def _holds_slot(self) -> bool:
        from tsspark_tpu import orchestrate

        return orchestrate.holds_lease(
            self.pool_dir, self.slot, self.slot + 1, self.token
        )

    def _heartbeat(self) -> None:
        # Root of the `serve-threads` effect budget: this thread may
        # touch the filesystem and the lease, but never the device —
        # no jax-dispatch/compile anywhere in its reach.
        hb = _hb_path(self.pool_dir, self.slot)
        m_shed = METRICS.gauge("tsspark_pool_replica_shed",
                               replica=str(self.slot))
        m_q = METRICS.gauge("tsspark_pool_replica_queue",
                            replica=str(self.slot))
        try:
            while not self.stop.is_set():
                try:
                    os.utime(hb)
                except OSError:
                    pass
                if not self._claim_slot():
                    # Renewal refused: a replacement owns the slot.
                    # Flip to fenced and let the grace timer end the
                    # process — in-flight probes must observe the
                    # structured refusal.
                    self.fenced.set()
                    obs.event("replica.fenced", slot=self.slot,
                              pid=os.getpid())
                    threading.Timer(self.fence_grace_s,  # lint-ok[thread-join]: one-shot grace timer whose only action is stop.set; run() exits (and the process with it) when it fires
                                    self.stop.set).start()
                    return
                if self.engine is not None:
                    m_shed.set(float(self.engine.stats.shed))
                    m_q.set(float(self.engine.stats.submitted
                                  - self.engine.stats.completed
                                  - self.engine.stats.shed
                                  - self.engine.stats.failed))
                self.stop.wait(self.heartbeat_s)
        except Exception as e:
            # A dying heartbeat must take the replica down VISIBLY:
            # with renewals stopped the lease lapses and the front
            # respawns the slot — but only if this process also stops
            # serving instead of running on silently past its fence
            # (the publisher-join bug class the concurrency gate
            # exists to catch).
            obs.event("replica.heartbeat_failed", slot=self.slot,
                      pid=os.getpid(), error=repr(e))
            self.stop.set()

    # -- request handling ------------------------------------------------------

    def _error(self, rid, err: Dict) -> Dict:
        return {"ok": False, "id": rid, "replica": self.slot,
                "error": err}

    def _handle_cmd(self, msg: Dict) -> Dict:
        rid = msg.get("id")
        cmd = msg["cmd"]
        if cmd == "ping":
            return {"ok": True, "id": rid, "replica": self.slot,
                    "pid": os.getpid(), "gen": self.gen,
                    "fenced": self.fenced.is_set(),
                    "version": self.engine.served_version()}
        if cmd == "stats":
            return {"ok": True, "id": rid, "replica": self.slot,
                    "pid": os.getpid(), "gen": self.gen,
                    "stats": self.engine.stats.snapshot(),
                    "cache": self.engine.cache.stats(),
                    "version": self.engine.served_version()}
        if cmd == "metrics":
            return {"ok": True, "id": rid, "replica": self.slot,
                    "prometheus": METRICS.to_prometheus()}
        if cmd == "warm":
            warmed = self.engine.materialize(
                msg.get("series_ids") or (),
                msg.get("horizons") or (7,),
                version=msg.get("version"),
            )
            return {"ok": True, "id": rid, "replica": self.slot,
                    "warmed": warmed, "version": msg.get("version")}
        if cmd == "refresh":
            target = msg.get("version")
            if target is not None:
                self.engine.ensure_version(int(target))
            else:
                self.engine.ensure_version(-1)  # any flip: force reload
            return {"ok": True, "id": rid, "replica": self.slot,
                    "version": self.engine.served_version()}
        if cmd == "quantiles":
            # Interval read: synchronous on the engine (plane gather or
            # row-local compute fallback — no dispatch pump involved).
            import numpy as np

            if self.fenced.is_set():
                return self._error(rid, ReplicaFenced(
                    f"slot {self.slot} lease lost"
                ).to_dict())
            try:
                res = self.engine.quantiles(
                    msg["series_ids"], int(msg["horizon"]),
                    quantiles=msg.get("quantiles"),
                )
            except ServeError as e:
                return self._error(rid, e.to_dict())
            except (KeyError, TypeError, ValueError) as e:
                return self._error(rid, {"type": "BadRequest",
                                         "detail": str(e)})
            return {
                "ok": True, "id": rid, "replica": self.slot,
                "version": res.version,
                "latency_ms": round(res.latency_s * 1e3, 3),
                "from_cache": res.from_cache,
                "series_ids": list(res.series_ids),
                "ds": np.asarray(res.ds).tolist(),
                **{k: np.asarray(v).tolist()
                   for k, v in res.values.items()},
            }
        if cmd == "quit":
            self.stop.set()
            return {"ok": True, "id": rid, "replica": self.slot}
        return self._error(rid, {"type": "BadRequest",
                                 "detail": f"unknown cmd {cmd!r}"})

    def _respond_forecast(self, rid, expect, pend) -> Dict:
        """Resolve one pending forecast into a response line, enforcing
        the lease fence and the front's version expectation AT RESPOND
        TIME (the analog of the fit worker's save-time fence).

        This is a root of the ``serve-respond`` effect budget
        (pyproject ``[tool.tsspark.analysis.effects]``): nothing
        reachable from here may compile, touch durable storage, or
        spawn — the gate proves it on every commit."""
        import numpy as np

        from tsspark_tpu.serve.registry import RegistryError

        try:
            res = pend.result(timeout=60.0)
        except ServeError as e:
            return self._error(rid, e.to_dict())
        except RegistryError as e:
            return self._error(rid, {"type": "RegistryError",
                                     "reason": e.reason,
                                     "detail": str(e)})
        except Exception as e:  # engine bug / timeout: structured out
            return self._error(rid, {"type": type(e).__name__,
                                     "reason": "internal",
                                     "detail": str(e)})
        if expect is not None and res.version != expect:
            # The stamp and the served version disagree.  Serving a
            # version that IS the registry's current active pointer is
            # legitimate (the stamp simply predates a flip that landed
            # mid-flight); anything else is the stale-read window the
            # stamping protocol exists to close — reject it.
            try:
                active = self.registry.active_version()
            except Exception:
                active = None
            if res.version != active:
                return self._error(
                    rid, VersionMismatch(res.version, expect).to_dict()
                )
        if self.fenced.is_set() or not self._holds_slot():
            self.fenced.set()
            return self._error(rid, ReplicaFenced(
                f"slot {self.slot} lease lost (pid {os.getpid()})"
            ).to_dict())
        return {
            "ok": True, "id": rid, "replica": self.slot,
            "version": res.version,
            "latency_ms": round(res.latency_s * 1e3, 3),
            "from_cache": res.from_cache,
            "series_ids": list(res.series_ids),
            "ds": np.asarray(res.ds).tolist(),
            **{k: np.asarray(v).tolist()
               for k, v in res.values.items()},
        }

    def _serve_conn(self, conn: socket.socket) -> None:
        from tsspark_tpu.serve.engine import (
            EngineOverloaded,
            ForecastRequest,
        )

        # Blocking socket: writes must never share a read-poll timeout
        # (see _LineReader.readline) — the reader polls via select.
        conn.settimeout(None)
        rfile = _LineReader(conn)
        wlock = threading.Lock()
        pending = []  # (rid, expect_version, PendingForecast)
        cond = threading.Condition()
        done = threading.Event()

        def write(obj: Dict) -> bool:
            try:
                with wlock:
                    _send_line(conn, obj)
                return True
            except OSError:
                done.set()
                return False

        def writer() -> None:
            while True:
                with cond:
                    while not pending and not done.is_set():
                        cond.wait(0.2)
                    if not pending:
                        if done.is_set():
                            return
                        continue
                    rid, expect, pend = pending.pop(0)
                try:
                    resp = self._respond_forecast(rid, expect, pend)
                except Exception as e:
                    # The writer must answer EVERY submitted request: a
                    # dead writer wedges the client on this connection
                    # until its timeout, then the whole group fails
                    # over — one escaped response must not cost that.
                    resp = self._error(rid, {"type": type(e).__name__,
                                             "reason": "internal",
                                             "detail": str(e)})
                write(resp)

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        try:
            while not self.stop.is_set():
                try:
                    line = rfile.readline(poll_s=0.5)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if line is None:
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except ValueError as e:
                    write(self._error(None, {"type": "BadRequest",
                                             "detail": str(e)}))
                    continue
                if msg.get("cmd"):
                    try:
                        write(self._handle_cmd(msg))
                    except Exception as e:
                        write(self._error(msg.get("id"),
                                          {"type": type(e).__name__,
                                           "reason": "internal",
                                           "detail": str(e)}))
                    continue
                rid = msg.get("id")
                if self.fenced.is_set():
                    write(self._error(rid, ReplicaFenced(
                        f"slot {self.slot} lease lost"
                    ).to_dict()))
                    continue
                expect = msg.get("expect_version")
                expect = None if expect is None else int(expect)
                if (expect is not None
                        and self.engine.served_version() != expect):
                    # Submit-time refresh: don't dispatch a whole batch
                    # at a version the front already moved past.
                    self.engine.ensure_version(expect)
                deadline_ms = msg.get("deadline_ms")
                try:
                    req = ForecastRequest.make(
                        msg["series_ids"], int(msg["horizon"]),
                        num_samples=int(msg.get("num_samples", 0)),
                        seed=int(msg.get("seed", 0)),
                        deadline_in_s=(None if deadline_ms is None
                                       else float(deadline_ms) / 1e3),
                    )
                    pend = self.engine.submit(req)
                except EngineOverloaded as e:
                    write(self._error(rid, e.to_dict()))
                    continue
                except (KeyError, TypeError, ValueError) as e:
                    write(self._error(rid, {"type": "BadRequest",
                                            "detail": str(e)}))
                    continue
                with cond:
                    pending.append((rid, expect, pend))
                    cond.notify()
        finally:
            done.set()
            with cond:
                cond.notify()
            wt.join(timeout=2.0)
            try:
                conn.close()
            except OSError:
                pass

    # -- the process body ------------------------------------------------------

    def run(self) -> int:
        from tsspark_tpu.serve.engine import PredictionEngine
        from tsspark_tpu.serve.registry import ParamRegistry

        os.makedirs(self.pool_dir, exist_ok=True)
        if not self._claim_slot():
            return RC_LEASE_HELD
        hb = _hb_path(self.pool_dir, self.slot)
        open(hb, "a").close()
        from tsspark_tpu.serve.cache import ForecastCache

        self.registry = ParamRegistry.open(self.registry_root)
        self.engine = PredictionEngine(
            self.registry,
            max_queue=self.max_queue, max_batch=self.max_batch,
            cache=ForecastCache(capacity=self.cache_capacity),
            retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.01,
                                     backoff=2.0, max_delay_s=0.1),
            breaker=CircuitBreaker(failure_threshold=3,
                                   reset_timeout_s=0.5,
                                   name=f"replica{self.slot}-backend"),
        )
        self.engine.start(poll_s=0.002)
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(self.socket_path)
        srv.listen(64)
        srv.settimeout(0.25)
        hb_t = threading.Thread(target=self._heartbeat, daemon=True)
        hb_t.start()
        obs.event("replica.start", slot=self.slot, pid=os.getpid(),
                  gen=self.gen)
        try:
            while not self.stop.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(target=self._serve_conn, args=(conn,),  # lint-ok[thread-join]: per-connection daemon threads bounded by the connection lifetime; stop closes the listener, engine.stop resolves their pends, and each closes its conn in a finally — the client observes EOF and fails over
                                 daemon=True).start()
        finally:
            self.stop.set()
            try:
                srv.close()
            except OSError:
                pass
            self.engine.stop()
            hb_t.join(timeout=2.0)
            if self.fenced.is_set():
                return RC_FENCED
            # Clean shutdown releases the slot for an instant successor.
            from tsspark_tpu import orchestrate

            orchestrate.release_lease(self.pool_dir, self.slot,
                                      self.slot + 1, self.token)
        return 0


def run_replica(pool_dir: str, slot: int, registry_root: str,
                socket_path: str, **kwargs) -> int:
    """Entry point for one replica process (see ``_Replica``)."""
    return _Replica(pool_dir, slot, registry_root, socket_path,
                    **kwargs).run()


# ---------------------------------------------------------------------------
# the front
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ReplicaInfo:
    """Front-side view of one slot."""

    slot: int
    gen: int = 0
    socket_path: str = ""
    pid: Optional[int] = None
    proc: Optional[subprocess.Popen] = None
    draining: bool = False
    fail_streak: int = 0
    next_respawn: float = 0.0
    breaker: Optional[CircuitBreaker] = None


class _Conn:
    """One persistent client connection to a replica socket.

    Responses are matched by REQUEST ID, never by arrival order: a
    connection that still has another pipelined wave's responses in
    flight (the failover path re-routes individual requests onto a
    sibling mid-wave) must not hand those bytes to the wrong caller.
    Unclaimed responses are stashed for their own reader; the stash is
    bounded — an abandoned response (its request was re-routed after a
    timeout) ages out instead of leaking."""

    _STASH_CAP = 4096

    def __init__(self, path: str, gen: int, timeout_s: float):
        self.path = path
        self.gen = gen
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(timeout_s)
        self.sock.connect(path)
        self.rfile = _LineReader(self.sock)
        self.stash: Dict[str, Dict] = {}

    def send(self, obj: Dict) -> None:
        _send_line(self.sock, obj)

    def _recv_raw(self) -> Dict:
        line = self.rfile.readline()
        if line is None:
            raise ConnectionError(f"replica at {self.path} closed")
        return json.loads(line)

    def recv_for(self, rid) -> Dict:
        """The response whose ``id`` matches ``rid`` (stashing any
        other wave's responses that arrive first)."""
        rid = str(rid)
        if rid in self.stash:
            return self.stash.pop(rid)
        while True:
            resp = self._recv_raw()
            got = str(resp.get("id"))
            if got == rid:
                return resp
            while len(self.stash) >= self._STASH_CAP:
                self.stash.pop(next(iter(self.stash)))
            self.stash[got] = resp

    def request(self, obj: Dict) -> Dict:
        self.send(obj)
        return self.recv_for(obj.get("id"))

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class ReplicaPool:
    """The pool front: spawn/attach, shard-route, fail over, respawn.

    Thread-safe for concurrent request threads (each thread keeps its
    own socket connections; breakers, routing state, and counters are
    shared).  ``ensure_alive`` is the health step — call it from a
    watch thread (``start_watch``) or inline between request waves."""

    def __init__(self, pool_dir: str, registry_root: str,
                 n_replicas: int = 2, *,
                 heartbeat_s: float = 0.25,
                 stale_after_s: Optional[float] = None,
                 lease_ttl_s: Optional[float] = None,
                 request_timeout_s: float = 60.0,
                 spawn_timeout_s: float = 120.0,
                 respawn_policy: Optional[RetryPolicy] = None,
                 breaker_threshold: int = 3,
                 breaker_reset_s: float = 0.5,
                 max_queue: int = 4096, max_batch: int = 128,
                 cache_capacity: Optional[int] = None,
                 hot_horizons: Sequence[int] = (7, 14, 28)):
        from tsspark_tpu.serve.registry import ParamRegistry

        self.pool_dir = os.path.abspath(pool_dir)
        self.registry_root = os.path.abspath(registry_root)
        self.n_replicas = int(n_replicas)
        self.heartbeat_s = float(heartbeat_s)
        self.stale_after_s = (float(stale_after_s)
                              if stale_after_s is not None
                              else 5.0 * self.heartbeat_s)
        self.lease_ttl_s = (float(lease_ttl_s)
                            if lease_ttl_s is not None
                            else 8.0 * self.heartbeat_s)
        self.request_timeout_s = float(request_timeout_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.respawn_policy = respawn_policy or RetryPolicy(
            max_attempts=None, base_delay_s=0.2, backoff=2.0,
            max_delay_s=2.0,
        )
        self.max_queue = int(max_queue)
        self.max_batch = int(max_batch)
        self.cache_capacity = (None if cache_capacity is None
                               else int(cache_capacity))
        self.hot_horizons = tuple(int(h) for h in hot_horizons)
        os.makedirs(self.pool_dir, exist_ok=True)
        self.registry = ParamRegistry.open(self.registry_root)
        self.expected_version = self.registry.active_version()
        self.replicas: Dict[int, ReplicaInfo] = {
            k: ReplicaInfo(
                slot=k,
                breaker=CircuitBreaker(
                    failure_threshold=int(breaker_threshold),
                    reset_timeout_s=float(breaker_reset_s),
                    name=f"replica{k}",
                ),
            )
            for k in range(self.n_replicas)
        }
        # _lock serializes lifecycle passes (spawn/ensure_alive) ONLY —
        # the request path must never wait behind a multi-second
        # respawn, so it uses the dedicated locks below.
        self._lock = threading.RLock()
        self._activate_lock = threading.Lock()
        self._local = threading.local()
        self._watch: Optional[threading.Thread] = None
        self._watch_stop = threading.Event()
        self._rid = 0
        self._rid_lock = threading.Lock()
        # Pool counters (also mirrored into the obs metrics registry —
        # the "pool gauges" the SLO watcher and loadgen report read).
        # Guarded by _count_lock: `+= 1` from concurrent client threads
        # is load/add/store bytecode, and wrong_version in particular
        # is an invariant pinned at exactly zero — a lost increment
        # would hide a real stale read.
        self._count_lock = threading.Lock()
        self.failovers = 0
        self.respawns = 0
        self.wrong_version = 0
        self.fenced_seen = 0
        self._m_alive = METRICS.gauge("tsspark_pool_replicas_alive")
        self._m_failovers = METRICS.counter("tsspark_pool_failovers_total")
        self._m_respawns = METRICS.counter("tsspark_pool_respawns_total")
        self._m_wrongv = METRICS.counter(
            "tsspark_pool_wrong_version_total"
        )

    # -- lifecycle -------------------------------------------------------------

    def _spawn_cmd(self, info: ReplicaInfo) -> List[str]:
        cmd = [
            sys.executable, "-m", "tsspark_tpu.serve.replica",
            "--pool-dir", self.pool_dir,
            "--slot", str(info.slot),
            "--registry", self.registry_root,
            "--socket", info.socket_path,
            "--gen", str(info.gen),
            "--heartbeat-s", str(self.heartbeat_s),
            "--lease-ttl-s", str(self.lease_ttl_s),
            "--max-queue", str(self.max_queue),
            "--max-batch", str(self.max_batch),
        ]
        if self.cache_capacity is not None:
            cmd += ["--cache-capacity", str(self.cache_capacity)]
        return cmd

    def _child_env(self) -> Dict[str, str]:
        env = dict(os.environ)
        parts = [_REPO_ROOT] + (
            [env["PYTHONPATH"]] if env.get("PYTHONPATH") else []
        )
        env["PYTHONPATH"] = os.pathsep.join(parts)
        # Replicas share the parent's persistent compile cache so a
        # respawn re-serves in seconds, not a compile round.
        if "TSSPARK_JAX_CACHE" not in env:
            try:
                import jax

                cache_dir = jax.config.jax_compilation_cache_dir
                if cache_dir:
                    env["TSSPARK_JAX_CACHE"] = cache_dir
            except Exception:
                pass
        obs.inject_env(env)
        return env

    def _spawn(self, slot: int) -> bool:
        """Start (or restart) the replica for ``slot``; True when it
        answers ping before ``spawn_timeout_s``.  The child claims the
        slot lease itself — a spawn against a LIVE lease exits
        ``RC_LEASE_HELD`` and this returns False (the backoff loop in
        ``ensure_alive`` retries after the lease expires)."""
        info = self.replicas[slot]
        info.gen += 1
        info.socket_path = os.path.join(
            self.pool_dir, f"replica_{slot}.g{info.gen}.sock"
        )
        info.proc = subprocess.Popen(
            self._spawn_cmd(info), env=self._child_env(),
            stdout=sys.stderr, stderr=sys.stderr,
        )
        info.pid = info.proc.pid
        t0 = time.monotonic()
        while time.monotonic() - t0 < self.spawn_timeout_s:
            if info.proc.poll() is not None:
                return False
            try:
                conn = _Conn(info.socket_path, info.gen, 5.0)
                try:
                    resp = conn.request({"cmd": "ping"})
                finally:
                    conn.close()
                if resp.get("ok"):
                    info.breaker.record_success()
                    self._write_state()
                    return True
            except (OSError, ValueError, ConnectionError):
                time.sleep(0.05)
        return False

    def start(self) -> "ReplicaPool":
        """Spawn every replica and wait until each answers ping."""
        with self._lock:  # lint-ok[lock-blocking]: lifecycle RLock held across spawn waits BY DESIGN — it serializes spawn/health/stop passes only; the request path uses the dedicated rid/count/activate locks and never contends (PR 10)
            for slot in range(self.n_replicas):
                deadline = time.monotonic() + self.spawn_timeout_s
                while not self._spawn(slot):
                    if time.monotonic() > deadline:
                        raise PoolError(
                            f"replica {slot} failed to start within "
                            f"{self.spawn_timeout_s}s"
                        )
                    time.sleep(0.2)
            self._m_alive.set(float(self.n_replicas))
        return self

    @classmethod
    def attach(cls, pool_dir: str, **kwargs) -> "ReplicaPool":
        """Rebuild a front over an existing pool (front-crash recovery):
        live replicas are adopted as-is (their leases and engines keep
        serving), dead slots are respawned."""
        with open(os.path.join(pool_dir, _POOL_STATE)) as fh:
            state = json.load(fh)
        pool = cls(pool_dir, state["registry"],
                   n_replicas=int(state["n_replicas"]), **kwargs)
        with pool._lock:
            for key, rec in (state.get("replicas") or {}).items():
                slot = int(key)
                if slot not in pool.replicas:
                    continue
                info = pool.replicas[slot]
                info.gen = int(rec.get("gen", 1))
                info.socket_path = rec.get("socket", "")
                info.pid = rec.get("pid")
                info.proc = None  # not our child; liveness via pid/hb
            for slot in range(pool.n_replicas):
                if pool.ping(slot) is None:
                    pool._spawn(slot)
        return pool

    def _write_state(self) -> None:
        state = {
            "n_replicas": self.n_replicas,
            "registry": self.registry_root,
            "expected_version": self.expected_version,
            "replicas": {
                str(k): {"socket": i.socket_path, "pid": i.pid,
                         "gen": i.gen}
                for k, i in self.replicas.items()
            },
        }
        atomic_write(
            os.path.join(self.pool_dir, _POOL_STATE),
            lambda fh: json.dump(state, fh, indent=1), mode="w",
        )

    def stop(self) -> None:
        self.stop_watch()
        with self._lock:  # lint-ok[lock-blocking]: lifecycle RLock across replica terminate/wait — teardown must exclude a concurrent health pass respawning what it just killed; request threads never take this lock
            for info in self.replicas.values():
                try:
                    self._request_slot(info.slot, {"cmd": "quit"},
                                       timeout_s=2.0)
                except Exception:
                    pass
                if info.proc is not None:
                    try:
                        info.proc.terminate()
                        info.proc.wait(timeout=5.0)
                    except Exception:
                        try:
                            info.proc.kill()
                        except OSError:
                            pass
                elif _pid_alive(info.pid):
                    try:
                        os.kill(int(info.pid), signal.SIGTERM)
                    except OSError:
                        pass
        self.close_front()

    def close_front(self) -> None:
        """Drop this thread's connections (front teardown; replicas keep
        running — ``attach`` builds the successor front)."""
        conns = getattr(self._local, "conns", None) or {}
        for c in conns.values():
            c.close()
        self._local.conns = {}

    # -- health ----------------------------------------------------------------

    def ping(self, slot: int) -> Optional[Dict]:
        try:
            resp = self._request_slot(slot, {"cmd": "ping"},
                                      timeout_s=2.0)
            return resp if resp.get("ok") else None
        except (OSError, ValueError, ConnectionError, PoolError):
            return None

    def alive_count(self) -> int:
        return sum(1 for k in self.replicas if self.ping(k) is not None)

    def _slot_unhealthy(self, info: ReplicaInfo) -> Optional[str]:
        if info.proc is not None and info.proc.poll() is not None:
            return f"process exited rc={info.proc.poll()}"
        if info.proc is None and not _pid_alive(info.pid):
            return "attached pid is gone"
        try:
            age = time.time() - os.path.getmtime(
                _hb_path(self.pool_dir, info.slot)
            )
        except OSError:
            age = float("inf")
        if age > self.stale_after_s:
            return f"heartbeat stale ({age:.2f}s)"
        return None

    def ensure_alive(self) -> List[int]:
        """One health pass: respawn dead/wedged slots (under the
        respawn policy's backoff).  Returns the slots respawned."""
        respawned: List[int] = []
        with self._lock:  # lint-ok[lock-blocking]: lifecycle RLock across the kill/respawn pass — exactly the PR 10 design: one health pass at a time, while the request path routes on breakers/leases without ever taking this lock
            alive = 0
            for slot, info in self.replicas.items():
                why = self._slot_unhealthy(info)
                if why is None:
                    alive += 1
                    continue
                if time.time() < info.next_respawn:
                    continue
                if info.proc is not None and info.proc.poll() is None:
                    # Wedged (stale heartbeat, process alive): kill it;
                    # the lease decides whether the replacement may
                    # actually take over.
                    try:
                        info.proc.kill()
                        info.proc.wait(timeout=5.0)
                    except Exception:
                        pass
                self._bump("respawns")
                self._m_respawns.inc()
                obs.event("pool.respawn", slot=slot, reason=why)
                if self._spawn(slot):
                    info.fail_streak = 0
                    info.next_respawn = 0.0
                    respawned.append(slot)
                    alive += 1
                else:
                    info.fail_streak += 1
                    info.next_respawn = (
                        time.time()
                        + self.respawn_policy.delay_s(info.fail_streak)
                    )
            self._m_alive.set(float(alive))
        return respawned

    def start_watch(self, interval_s: float = 0.3) -> None:
        if self._watch is not None:
            return
        self._watch_stop.clear()

        def loop():
            while not self._watch_stop.is_set():
                try:
                    self.ensure_alive()
                except Exception:
                    pass
                self._watch_stop.wait(interval_s)

        self._watch = threading.Thread(target=loop, name="pool-watch",
                                       daemon=True)
        self._watch.start()

    def stop_watch(self) -> None:
        if self._watch is None:
            return
        self._watch_stop.set()
        self._watch.join(timeout=5.0)
        self._watch = None

    # -- request path ----------------------------------------------------------

    def _conn(self, slot: int) -> _Conn:
        conns: Dict[int, _Conn] = getattr(self._local, "conns", None)
        if conns is None:
            conns = {}
            self._local.conns = conns
        info = self.replicas[slot]
        cur = conns.get(slot)
        if cur is not None and cur.gen == info.gen:
            return cur
        if cur is not None:
            cur.close()
        conn = _Conn(info.socket_path, info.gen,
                     self.request_timeout_s)
        conns[slot] = conn
        return conn

    def _drop_conn(self, slot: int) -> None:
        conns = getattr(self._local, "conns", None) or {}
        cur = conns.pop(slot, None)
        if cur is not None:
            cur.close()

    def _request_slot(self, slot: int, payload: Dict,
                      timeout_s: Optional[float] = None) -> Dict:
        conn = self._conn(slot)
        if timeout_s is not None:
            conn.sock.settimeout(timeout_s)
        try:
            return conn.request(payload)
        finally:
            if timeout_s is not None:
                conn.sock.settimeout(self.request_timeout_s)

    def _bump(self, name: str, n: int = 1) -> None:
        if not n:
            return
        with self._count_lock:
            setattr(self, name, getattr(self, name) + n)

    def _note_served_version(self, resp: Dict,
                             stamped: Optional[int]) -> None:
        """Judge an OK response's version against its own stamp.  A
        response OLDER than the stamp is normally the stale-read window
        (counted in ``wrong_version`` — the chaos invariant pins it at
        zero) — unless the registry's active pointer itself moved back
        (a ROLLBACK landed): then the replica is correct and the front
        adopts the new pointer instead of flagging every response
        forever."""
        version = resp.get("version")
        if stamped is None or version is None or version >= stamped:
            return
        active = self.registry.active_version()
        if version == active:
            self.expected_version = active  # lint-ok[lock-guard]: single reference store (GIL-atomic) adopting the registry's active pointer; every writer converges to active_version(), so last-write-wins is idempotent — locking would park the request path behind a multi-second activate
            return
        self._bump("wrong_version")
        self._m_wrongv.inc()

    def _next_rid(self) -> str:
        with self._rid_lock:
            self._rid += 1
            return f"q{self._rid}"

    def shard_order(self, series_ids: Sequence) -> List[int]:
        home = shard_of(series_ids[0], self.n_replicas)
        return [(home + off) % self.n_replicas
                for off in range(self.n_replicas)]

    def forecast(self, series_ids: Sequence, horizon: int,
                 num_samples: int = 0, seed: int = 0,
                 deadline_ms: Optional[float] = None) -> Dict:
        """Route one request; returns the replica's raw response dict
        (``ok`` true with arrays, or a structured error the caller
        inspects).  Transport failures / fenced replicas fail over to
        siblings; only ``NoReplicaAvailable`` raises."""
        payload = {
            "id": self._next_rid(),
            "series_ids": [str(s) for s in series_ids],
            "horizon": int(horizon),
            "num_samples": int(num_samples), "seed": int(seed),
        }
        if deadline_ms is not None:
            payload["deadline_ms"] = float(deadline_ms)
        resp = self._route(payload)
        if isinstance(resp, dict) and stale_serving(self.registry_root):
            # Ladder rung 4 (stale_serve): the registry root is out of
            # disk, refits are paused, and this answer may be computed
            # from a version older than the landed data.  Keep serving
            # — recency honesty beats an outage — but say so.
            resp["stale"] = True
            resp["disk_ladder"] = current_state(self.registry_root)
        return resp

    def quantiles(self, series_ids: Sequence, horizon: int,
                  quantiles: Optional[Sequence[float]] = None) -> Dict:
        """Route one interval read to the home replica: quantile-plane
        mmap gather on the replica when covered, row-local compute
        fallback otherwise.  Same failover and staleness marking as
        :meth:`forecast`."""
        payload = {
            "id": self._next_rid(), "cmd": "quantiles",
            "series_ids": [str(s) for s in series_ids],
            "horizon": int(horizon),
        }
        if quantiles is not None:
            payload["quantiles"] = [float(q) for q in quantiles]
        resp = self._route(payload)
        if isinstance(resp, dict) and stale_serving(self.registry_root):
            resp["stale"] = True
            resp["disk_ladder"] = current_state(self.registry_root)
        return resp

    def _route(self, payload: Dict,
               skip_slot: Optional[int] = None) -> Dict:
        """``skip_slot``: a slot the caller just observed failing (the
        wave fallback) — excluded so the re-route neither re-sends to a
        known-bad replica nor double-counts its failure."""
        last_detail = "no replica admitted the request"
        for slot in self.shard_order(payload["series_ids"]):
            if slot == skip_slot:
                continue
            resp = self._try_slot(slot, payload)
            if resp is not None:
                return resp
            last_detail = f"slot {slot} unavailable"
        raise NoReplicaAvailable(last_detail)

    def _try_slot(self, slot: int, payload: Dict,
                  _retried: bool = False) -> Optional[Dict]:
        """One attempt at one slot; None means 'fail over'."""
        info = self.replicas[slot]
        if info.draining or not info.breaker.allow():
            return None
        payload = dict(payload, expect_version=self.expected_version)
        try:
            resp = self._request_slot(slot, payload)
        except (OSError, ValueError, ConnectionError):
            info.breaker.record_failure()
            self._drop_conn(slot)
            self._bump("failovers")
            self._m_failovers.inc()
            return None
        stamped = payload.get("expect_version")
        if resp.get("ok"):
            info.breaker.record_success()
            self._note_served_version(resp, stamped)
            return resp
        err = resp.get("error") or {}
        reason = err.get("reason")
        if reason == "fenced":
            info.breaker.record_failure()
            self._bump("fenced_seen")
            self._bump("failovers")
            self._m_failovers.inc()
            self._drop_conn(slot)
            return None
        if reason == "version-mismatch" and not _retried:
            # The registry may have flipped under the front (another
            # publisher activated): adopt the current active pointer
            # and retry this slot once before failing over.  The
            # replica ANSWERED — record the success first, or a
            # half-open breaker's single trial slot would be consumed
            # by this attempt and never resolved (the retry's allow()
            # would then refuse the healthy replica forever).
            info.breaker.record_success()
            active = self.registry.active_version()
            if active != self.expected_version:
                self.expected_version = active  # lint-ok[lock-guard]: same single-store adoption as _note_served_version — GIL-atomic, idempotent, request path must not wait on the activate lock
            return self._try_slot(slot, payload, _retried=True)
        if reason == "version-mismatch":
            info.breaker.record_failure()
            self._bump("failovers")
            self._m_failovers.inc()
            return None
        # Structured terminal error (shed, unknown series, overloaded,
        # backend breaker): the replica answered — not a failover case.
        info.breaker.record_success()
        return resp

    # -- pipelined waves (the loadgen's hot path) ------------------------------

    def submit_wave(self, requests: List[Dict]) -> Dict[str, Dict]:
        """Send many requests pipelined (grouped per owning replica),
        collect all responses.  Requests left unanswered by a dying
        replica are re-routed individually through the failover path.
        Each request dict needs ``id`` and ``series_ids`` (+ forecast
        fields); returns ``{id: response}``."""
        groups: Dict[int, List[Dict]] = {}
        out: Dict[str, Dict] = {}
        for req in requests:
            placed = False
            for slot in self.shard_order(req["series_ids"]):
                info = self.replicas[slot]
                if info.draining or not info.breaker.allow():
                    continue
                groups.setdefault(slot, []).append(req)
                placed = True
                break
            if not placed:
                out[req["id"]] = {
                    "ok": False, "id": req["id"],
                    "error": NoReplicaAvailable("all slots down")
                    .to_dict(),
                }
        # Two phases: send EVERY slot's group first, then collect — so
        # all replicas compute concurrently instead of each waiting for
        # the previous slot's batch to drain.
        sent: Dict[int, List[Dict]] = {}
        stamps: Dict[str, Optional[int]] = {}
        for slot, group in groups.items():
            try:
                conn = self._conn(slot)
                for req in group:
                    stamp = self.expected_version
                    stamps[str(req["id"])] = stamp
                    conn.send(dict(req, expect_version=stamp))
                sent[slot] = group
            except (OSError, ValueError, ConnectionError):
                self.replicas[slot].breaker.record_failure()
                self._drop_conn(slot)
        for slot, group in groups.items():
            info = self.replicas[slot]
            answered: Dict[str, Dict] = {}
            if slot in sent:
                try:
                    conn = self._conn(slot)
                    for req in group:
                        rid = str(req["id"])
                        answered[rid] = conn.recv_for(rid)
                except (OSError, ValueError, ConnectionError):
                    info.breaker.record_failure()
                    self._drop_conn(slot)
            if slot in sent and answered:
                # One breaker outcome for the slot's whole group: a
                # fenced answer steers future routing away; a clean
                # group (mismatch included — the replica is healthy,
                # the front's stamp just lagged a flip) counts as up.
                if any((r.get("error") or {}).get("reason") == "fenced"
                       for r in answered.values()
                       if not r.get("ok")):
                    info.breaker.record_failure()
                else:
                    info.breaker.record_success()
            for req in group:
                rid = str(req["id"])
                resp = answered.get(rid)
                err = ((resp.get("error") or {})
                       if resp is not None and not resp.get("ok")
                       else {})
                if resp is None or err.get("reason") in (
                    "fenced", "version-mismatch"
                ):
                    if resp is not None:
                        self._bump("fenced_seen",
                                   err.get("reason") == "fenced")
                    self._bump("failovers")
                    self._m_failovers.inc()
                    try:
                        # skip_slot: never re-send to the slot that
                        # just failed this request (and never count its
                        # failure twice).
                        resp = self._route(dict(req), skip_slot=slot)
                    except NoReplicaAvailable as e:
                        resp = {"ok": False, "id": rid,
                                "error": e.to_dict()}
                elif resp.get("ok"):
                    self._note_served_version(resp, stamps.get(rid))
                out[rid] = resp
        return out

    # -- version flips ---------------------------------------------------------

    def activate(self, version: int,
                 hot_series: Optional[Sequence] = None,
                 horizons: Optional[Sequence[int]] = None) -> None:
        """Flip the pool to ``version`` with a flat p99: materialize
        hot forecasts for the NEW version into every replica's cache
        (ahead-of-time compute against a prefetched snapshot), flip the
        registry pointer, then drain replicas one at a time through an
        explicit refresh (siblings own each drained slot's traffic for
        the moment its engine swaps snapshots)."""
        version = int(version)
        horizons = tuple(horizons or self.hot_horizons)
        hot = [str(s) for s in (hot_series or ())]
        with self._activate_lock:
            t0 = time.time()
            # Materialize the serve artifacts for the NEW version
            # before any replica refreshes onto it: the forecast plane
            # (replicas adopt it at warm/refresh and answer hot reads
            # with zero JAX dispatch) and the AOT program bank (a
            # respawned replica loads its first-request programs from
            # the shared compilation cache).  Both are best-effort
            # accelerators — a shed or failed publish leaves the
            # compute path serving, never blocks the flip.
            arts = self._publish_serve_artifacts(version, horizons)
            warmed = {}
            for slot in self.replicas:
                try:
                    resp = self._request_slot(slot, {
                        "cmd": "warm", "version": version,
                        "series_ids": hot, "horizons": list(horizons),
                    })
                    warmed[slot] = (resp.get("warmed")
                                    if resp.get("ok") else None)
                except (OSError, ValueError, ConnectionError):
                    warmed[slot] = None  # dead replica warms at respawn
            self.registry.activate(version)
            self.expected_version = version
            for slot, info in self.replicas.items():
                info.draining = True
                try:
                    deadline = time.monotonic() + 10.0
                    while time.monotonic() < deadline:
                        resp = self._request_slot(slot, {
                            "cmd": "refresh", "version": version,
                        })
                        if (resp.get("ok")
                                and resp.get("version") == version):
                            break
                        time.sleep(0.02)
                except (OSError, ValueError, ConnectionError):
                    pass  # dead replica adopts the flip at respawn
                finally:
                    info.draining = False
            self._write_state()
            obs.record("pool.activate", t0, time.time() - t0,
                       version=version, warmed=warmed,
                       hot=len(hot), fplane=arts.get("fplane"),
                       aot=arts.get("aot"))

    def _publish_serve_artifacts(self, version: int,
                                 horizons: Sequence[int]) -> Dict:
        """Best-effort forecast plane + AOT program bank for the flip
        target (both idempotent; see ``fplane.maybe_publish`` /
        ``aotbank.build_bank``).  Failures degrade to an event — the
        flip itself must never hinge on speculative precompute."""
        out: Dict = {"fplane": None, "qplane": None, "aot": None}
        try:
            from tsspark_tpu.serve import aotbank, fplane
            from tsspark_tpu.uncertainty import qplane

            pub = fplane.maybe_publish(self.registry, version,
                                       horizons=horizons)
            out["fplane"] = None if pub is None else pub.get("status")
            qpub = qplane.maybe_publish(self.registry, version,
                                        horizons=horizons)
            out["qplane"] = None if qpub is None else qpub.get("status")
            bank_dir = aotbank.cache_dir_from_env()
            if bank_dir:
                from tsspark_tpu.backends.registry import get_backend
                from tsspark_tpu.config import SolverConfig

                snap = self.registry.load(int(version), fallback=False)
                bank = aotbank.build_bank(
                    snap,
                    get_backend("tpu", self.registry.config,
                                SolverConfig()),
                    dirpath=bank_dir, horizons=horizons,
                )
                out["aot"] = None if bank is None else bank.get("status")
        except Exception as e:
            obs.event("pool.serve_artifacts_failed",
                      version=int(version), error=repr(e))
        return out

    # -- aggregation -----------------------------------------------------------

    def stats(self) -> Dict:
        """Pool + per-replica stats (per-replica shed counts are the
        per-failure-domain saturation signal)."""
        per: Dict[str, Dict] = {}
        for slot in self.replicas:
            try:
                resp = self._request_slot(slot, {"cmd": "stats"},
                                          timeout_s=5.0)
            except (OSError, ValueError, ConnectionError):
                resp = None
            if resp is not None and resp.get("ok"):
                from tsspark_tpu.utils.procmem import (
                    mapped_file_mem,
                    proc_mem,
                )

                st = resp["stats"]
                per[str(slot)] = {
                    "pid": resp.get("pid"), "gen": resp.get("gen"),
                    "version": resp.get("version"),
                    "submitted": st.get("submitted"),
                    "completed": st.get("completed"),
                    "shed": st.get("shed"),
                    "failed": st.get("failed"),
                    "rejected": st.get("rejected"),
                    "fast_failed": st.get("fast_failed"),
                    "latency_ms": st.get("latency_ms"),
                    "plane_hits": st.get("plane_hits"),
                    "plane_hit_rate": st.get("plane_hit_rate"),
                    "cache": resp.get("cache"),
                    # Sharing-aware memory (utils.procmem): rss_anon is
                    # the private heap an npz snapshot would live in;
                    # the snap_* fields are the replica's resident cost
                    # in the mmap snapshot plane's shared columns.
                    "mem": {
                        **proc_mem(resp.get("pid")),
                        "snap": mapped_file_mem(resp.get("pid")),
                    },
                }
            else:
                per[str(slot)] = {"down": True}
        return {
            "n_replicas": self.n_replicas,
            "expected_version": self.expected_version,
            "failovers": self.failovers,
            "respawns": self.respawns,
            "wrong_version": self.wrong_version,
            "fenced_seen": self.fenced_seen,
            "breakers": {str(k): i.breaker.snapshot()
                         for k, i in self.replicas.items()},
            "disk_ladder": current_state(self.registry_root),
            "stale_serve": stale_serving(self.registry_root),
            "replicas": per,
        }

    def prometheus(self) -> str:
        """Aggregated Prometheus text: the front's own pool gauges plus
        each live replica's metrics under a ``# replica <k>`` banner
        (per-replica shed counts ride the labeled
        ``tsspark_pool_replica_shed`` gauge each replica exports)."""
        parts = ["# pool front", METRICS.to_prometheus()]
        for slot in self.replicas:
            try:
                resp = self._request_slot(slot, {"cmd": "metrics"},
                                          timeout_s=5.0)
            except (OSError, ValueError, ConnectionError):
                continue
            if resp.get("ok"):
                parts.append(f"# replica {slot}")
                parts.append(resp.get("prometheus", ""))
        return "\n".join(parts)


# The replica CLI lives in tsspark_tpu/serve/replica.py (a module this
# package's __init__ does NOT import, so ``python -m`` runs it without
# the runpy double-import warning).
