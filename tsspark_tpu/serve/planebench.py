"""``bench --serveplane`` — the forecast plane's economics, measured.

One run, three questions (docs/SERVING.md "Forecast plane"):

1. **Hot-read throughput** — the same deterministic hot mix (point
   forecasts at the pool's hot horizons, caches DISABLED so every
   request pays its real path) replayed through two engines over the
   same registry: one serving from the materialized plane, one forced
   onto the compute path.  The ratio is the plane's whole claim.
2. **Zero-dispatch read latency** — per-request walls on the plane
   engine; p99 feeds the ``plane_read_p99_ms`` SLO budget.
3. **Replica cold start** — TTFR of a 1-replica pool against a fresh
   compilation cache (cold: the first request pays the compile wall)
   vs one warmed by the AOT program bank (``serve/aotbank.py``): the
   warm replica LOADS its first-request program instead of compiling.

The report is a ``BENCH_serveplane_<unix>.json`` artifact (kind
``serve-loadgen`` plus a ``plane`` section) ingested into RUNHISTORY
under a ``serveplane_``-prefixed workload key and judged by the
regression sentinel against ``[tool.tsspark.slo.serve]``.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

HOT_HORIZONS = (7, 14, 28)


def _percentiles(walls_s: Sequence[float]) -> Dict[str, Optional[float]]:
    if not walls_s:
        return {"p50": None, "p95": None, "p99": None}
    a = np.asarray(walls_s, np.float64) * 1e3
    return {k: round(float(np.percentile(a, q)), 3)
            for k, q in (("p50", 50), ("p95", 95), ("p99", 99))}


def _hot_mix(rng, snap, n: int) -> List[Dict]:
    """The deterministic hot-read mix: 1-8 Zipf-picked series per
    request, hot horizons only, num_samples=0 — exactly the traffic
    the plane exists for (sampled intervals stay on compute and are
    measured by the ordinary loadgen)."""
    n_series = len(snap.series_ids)
    w = 1.0 / (1.0 + np.arange(n_series))
    w = w / w.sum()
    reqs = []
    for _ in range(n):
        k = int(rng.integers(1, min(9, n_series + 1)))
        pick = rng.choice(n_series, size=k, replace=False, p=w)
        reqs.append({
            "series_ids": [snap.series_ids[i] for i in pick],
            "horizon": int(rng.choice(HOT_HORIZONS)),
        })
    return reqs


def _replay(engine, reqs: Sequence[Dict],
            record_walls: bool = False):
    """Replay ``reqs`` synchronously; returns (wall_s, per-request
    walls).  Synchronous on purpose: the throughput under test is the
    read path itself, not queue coalescing."""
    walls: List[float] = []
    t0 = time.perf_counter()
    for r in reqs:
        t1 = time.perf_counter()
        engine.forecast(r["series_ids"], r["horizon"], num_samples=0,
                        seed=0, deadline_in_s=None)
        if record_walls:
            walls.append(time.perf_counter() - t1)
    return time.perf_counter() - t0, walls


@contextlib.contextmanager
def _env(overrides: Dict[str, Optional[str]]):
    """Temporarily set/unset env vars (None = unset) — the TTFR pools
    read their cache contract from the environment they inherit."""
    old = {k: os.environ.get(k) for k in overrides}
    try:
        for k, v in overrides.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _ttfr(pool_dir: str, registry_root: str, sid) -> Dict:
    """Spawn a 1-replica pool and time its path to first service:
    ``spawn_s`` (start() wall: fork + imports + lease + socket),
    ``first_request_s`` (one request per hot horizon bucket — the
    whole first-contact program ladder, where the compile wall lives
    when the cache is cold), and their sum ``ttfr_s`` (the scale
    bench's time_to_first_request_s analog)."""
    from tsspark_tpu.serve.pool import ReplicaPool

    pool = ReplicaPool(pool_dir, registry_root, n_replicas=1)
    t0 = time.perf_counter()
    pool.start()
    t_ready = time.perf_counter()
    try:
        resp = pool.submit_wave([
            {"id": f"ttfr-{h}", "series_ids": [sid], "horizon": int(h),
             "num_samples": 0, "seed": 0, "deadline_ms": 300_000.0}
            for h in HOT_HORIZONS
        ])
        t_done = time.perf_counter()
        ok = all(r.get("ok") for r in resp.values()) and \
            len(resp) == len(HOT_HORIZONS)
    finally:
        pool.stop()
    return {
        "ok": ok,
        "spawn_s": round(t_ready - t0, 3),
        "first_request_s": round(t_done - t_ready, 3),
        "ttfr_s": round(t_done - t0, 3),
    }


def run_serveplane_bench(args) -> int:
    """The ``bench --serveplane`` runner (argparse namespace from
    bench.py: series/requests/seed/dir/report/data_root)."""
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS
    from tsspark_tpu.io import atomic_write
    from tsspark_tpu.serve import aotbank, fplane
    from tsspark_tpu.serve.__main__ import (
        _build_demo_registry, _report_identity, _sentinel_gate,
    )
    from tsspark_tpu.serve.cache import ForecastCache
    from tsspark_tpu.serve.engine import PredictionEngine

    t_start = time.perf_counter()
    scratch = os.path.join(args.dir or ".", "serveplane_scratch")
    obs.start_run(os.path.join(scratch, "spans.jsonl"))
    METRICS.reset()
    registry = _build_demo_registry(
        os.path.join(scratch, "registry"), args.series, args.seed,
        data_root=args.data_root,
    )
    snap = registry.load()
    v = int(registry.active_version())
    setup_s = round(time.perf_counter() - t_start, 3)

    # -- replica cold start: fresh compile cache vs the AOT bank ------------
    # Measured BEFORE the plane publish so the first request actually
    # exercises the compute path's compile wall (a plane-covered read
    # needs no program at all — that is the tentpole, not this probe).
    sid0 = snap.series_ids[0]
    with _env({"TSSPARK_JAX_CACHE": os.path.join(scratch, "cold_cache"),
               "TSSPARK_AOT_CACHE_DIR": None}):
        os.makedirs(os.environ["TSSPARK_JAX_CACHE"], exist_ok=True)
        ttfr_cold = _ttfr(os.path.join(scratch, "pool_cold"),
                          registry.root, sid0)

    aot_dir = os.path.join(scratch, "aot_bank")
    from tsspark_tpu.backends.registry import get_backend
    from tsspark_tpu.config import SolverConfig

    backend = get_backend("tpu", registry.config, SolverConfig())
    t0 = time.perf_counter()
    bank = aotbank.build_bank(snap, backend, dirpath=aot_dir,
                              horizons=HOT_HORIZONS)
    bank_s = round(time.perf_counter() - t0, 3)
    with _env({"TSSPARK_JAX_CACHE": os.path.join(scratch, "warm_seed"),
               "TSSPARK_AOT_CACHE_DIR": aot_dir}):
        os.makedirs(os.environ["TSSPARK_JAX_CACHE"], exist_ok=True)
        ttfr_warm = _ttfr(os.path.join(scratch, "pool_warm"),
                          registry.root, sid0)

    # -- plane publish ------------------------------------------------------
    fpub = fplane.maybe_publish(registry, v, backend,
                                horizons=HOT_HORIZONS)
    if fpub is None or fpub.get("status") == "present":
        fpub = dict(fpub or {}, status=(fpub or {}).get("status"))

    # -- hot-read throughput: plane vs forced compute path ------------------
    # Caches disabled (capacity=0) on BOTH engines: every request pays
    # its real path, so the ratio is plane-vs-dispatch, not LRU-vs-LRU.
    rng = np.random.default_rng(args.seed)
    reqs_plane = _hot_mix(rng, snap, args.requests)
    reqs_disp = _hot_mix(np.random.default_rng(args.seed),
                         snap, max(1, args.requests // 8))

    eng_plane = PredictionEngine(registry, cache=ForecastCache(0))
    eng_plane.refresh()
    _replay(eng_plane, reqs_plane[:16])  # warm pages / settle
    plane_wall, walls = _replay(eng_plane, reqs_plane,
                                record_walls=True)
    stats_plane = eng_plane.stats.snapshot()

    eng_disp = PredictionEngine(registry, cache=ForecastCache(0))
    eng_disp.refresh()
    eng_disp._planes = {v: None}     # force the compute path
    _replay(eng_disp, reqs_disp[:8])  # pay compiles outside the clock
    disp_wall, _ = _replay(eng_disp, reqs_disp)
    stats_disp = eng_disp.stats.snapshot()

    plane_rps = round(len(reqs_plane) / plane_wall, 1)
    disp_rps = round(len(reqs_disp) / disp_wall, 1)
    read_lat = _percentiles(walls)

    METRICS.export(os.path.join(scratch, "metrics_serveplane.json"),
                   trace_id=obs.trace_id())
    report = {
        **_report_identity(registry),
        "n_requests": len(reqs_plane),
        "n_series": len(snap.series_ids),
        "mix": {"horizons": list(HOT_HORIZONS), "sampled_fraction": 0.0,
                "series_per_request": [1, 8], "zipf": True,
                "seed": args.seed, "cache_capacity": 0},
        "setup_s": setup_s,
        "wall_s": round(plane_wall, 3),
        "requests_per_s": plane_rps,
        "engine": stats_plane,
        "cache": eng_plane.cache.stats(),
        "plane": {
            "status": fpub.get("status"),
            "publish_s": fpub.get("publish_s"),
            "nbytes": fpub.get("nbytes"),
            "buckets": fpub.get("buckets"),
            "plane_hit_rate": stats_plane.get("plane_hit_rate"),
            "read_latency_ms": read_lat,
            "hot_read": {
                "plane_rps": plane_rps,
                "dispatch_rps": disp_rps,
                "speedup": (round(plane_rps / disp_rps, 2)
                            if disp_rps else None),
                "n_plane": len(reqs_plane),
                "n_dispatch": len(reqs_disp),
                "dispatch_engine": {
                    k: stats_disp.get(k)
                    for k in ("dispatches", "plane_hits", "completed")
                },
            },
            "ttfr": {
                "cold_s": ttfr_cold["ttfr_s"],
                "aot_warm_s": ttfr_warm["ttfr_s"],
                "cold": ttfr_cold,
                "aot_warm": ttfr_warm,
            },
            "aot": {
                "dir": aot_dir,
                "built_s": (bank or {}).get("built_s"),
                "entries": len((bank or {}).get("entries") or ()),
                "bank_wall_s": bank_s,
            },
        },
        "active_version": v,
    }
    out = args.report or f"BENCH_serveplane_{int(time.time())}.json"
    atomic_write(out, lambda fh: json.dump(report, fh, indent=1),
                 mode="w")
    print(
        f"serveplane: plane {plane_rps}/s vs dispatch {disp_rps}/s "
        f"({report['plane']['hot_read']['speedup']}x) | plane read "
        f"p50={read_lat['p50']} p99={read_lat['p99']} ms | plane hit "
        f"rate {report['plane']['plane_hit_rate']} | publish "
        f"{fpub.get('publish_s')}s ({fpub.get('nbytes')} B) | TTFR "
        f"cold {ttfr_cold['ttfr_s']}s (first req "
        f"{ttfr_cold['first_request_s']}s) -> AOT-warm "
        f"{ttfr_warm['ttfr_s']}s (first req "
        f"{ttfr_warm['first_request_s']}s) | report -> {out}"
    )
    return _sentinel_gate(report, out)
