"""Micro-batched low-latency prediction engine.

The serving read path as a real subsystem: requests enter a BOUNDED
queue (admission control — a full queue rejects at submit instead of
building invisible backlog), are coalesced into shape-bucketed batches,
gathered out of the active registry snapshot, and dispatched through
``backend.predict`` as ONE program per bucket.

Shape discipline is what keeps the jit cache small under arbitrary
request mixes: batch widths walk the same pow-2 ladder the fit path's
compaction scheduler uses (``parallel.sharding.compacted_width``), and
horizons are padded up a pow-2 ladder too (each series' future grid
just extends at its own cadence; rows/steps are sliced back per
request).  Padding is bitwise-invisible on the deterministic path —
every predict op is row- and timestep-local — so an engine-batched
forecast equals a direct ``backend.predict`` for the same series bit
for bit (pinned in tests/test_serve.py).  Sampled intervals draw from a
batch-shaped key, so a series' draws depend on the width and row order
of whichever miss-set batch first computed them: repeated identical
requests return the same cached values, but the draws themselves are
statistically exchangeable across traffic patterns rather than a pure
function of the request.

Deadline-expired requests are SHED with a structured error before the
batch dispatches — one slow client must not hold a coalesced batch
hostage.  Transient backend failures retry under a
``resilience.RetryPolicy`` when one is attached.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import queue
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tsspark_tpu.backends.registry import ForecastBackend, get_backend
from tsspark_tpu.config import SolverConfig
from tsspark_tpu.obs import context as obs
from tsspark_tpu.obs.metrics import DEFAULT as METRICS
from tsspark_tpu.parallel.sharding import compacted_width, next_pow2
from tsspark_tpu.resilience import faults
from tsspark_tpu.resilience.policy import CircuitBreaker
from tsspark_tpu.serve import fplane
from tsspark_tpu.serve.cache import ForecastCache
from tsspark_tpu.serve.registry import (
    ParamRegistry,
    RegistryError,
    Snapshot,
)


# ---------------------------------------------------------------------------
# requests + structured errors
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ForecastRequest:
    """One prediction request (possibly many series, one horizon)."""

    series_ids: Tuple[str, ...]
    horizon: int
    num_samples: int = 0
    seed: int = 0
    deadline_s: Optional[float] = None   # absolute time.monotonic()

    @classmethod
    def make(cls, series_ids: Sequence, horizon: int,
             num_samples: int = 0, seed: int = 0,
             deadline_in_s: Optional[float] = None) -> "ForecastRequest":
        if int(horizon) < 1:
            raise ValueError(f"horizon must be >= 1, got {horizon}")
        if not series_ids:
            raise ValueError("series_ids must be non-empty")
        return cls(
            series_ids=tuple(str(s) for s in series_ids),
            horizon=int(horizon),
            num_samples=int(num_samples),
            # The seed only reaches the program when sampling; folding
            # it to 0 otherwise lets deterministic requests that differ
            # only in seed share one cache entry and one dispatch row.
            seed=int(seed) if num_samples else 0,
            deadline_s=(None if deadline_in_s is None
                        else time.monotonic() + float(deadline_in_s)),
        )


class ServeError(RuntimeError):
    """Base of the engine's structured errors (all JSON-able)."""

    reason = "serve-error"

    def to_dict(self) -> Dict:
        return {"type": type(self).__name__, "reason": self.reason,
                "detail": str(self)}


class RequestShed(ServeError):
    """Deadline expired before dispatch; the request was dropped from
    its batch instead of blocking it."""

    reason = "deadline-exceeded"

    def __init__(self, deadline_s: float, now_s: float):
        self.deadline_s = deadline_s
        self.now_s = now_s
        super().__init__(
            f"deadline expired {now_s - deadline_s:.3f}s before dispatch"
        )

    def to_dict(self) -> Dict:
        d = super().to_dict()
        d["late_s"] = round(self.now_s - self.deadline_s, 4)
        return d


class UnknownSeries(ServeError):
    """The active snapshot has no parameters for some requested ids."""

    reason = "unknown-series"

    def __init__(self, missing: Sequence[str], version: int):
        self.missing = tuple(missing)
        self.version = version
        super().__init__(
            f"version {version} has no params for {list(missing)[:5]}"
        )


class EngineOverloaded(ServeError):
    """The bounded request queue is full (admission control)."""

    reason = "overloaded"


class BackendUnavailable(ServeError):
    """The dispatch circuit breaker is open: the backend has failed
    enough consecutive dispatches that requests are shed fast instead of
    each burning its deadline on doomed retries."""

    reason = "circuit-open"

    def __init__(self, name: str, retry_after_s: float):
        self.retry_after_s = retry_after_s
        super().__init__(
            f"{name} circuit open; retry in {retry_after_s:.2f}s"
        )

    def to_dict(self) -> Dict:
        d = super().to_dict()
        d["retry_after_s"] = round(self.retry_after_s, 3)
        return d


class PendingForecast:
    """Handle returned by ``submit``; resolves to a ForecastResult."""

    def __init__(self, request: ForecastRequest):
        self.request = request
        self.submitted_s = time.monotonic()
        # Wall-clock twin of submitted_s: span records join across
        # processes on wall time; latency math stays on the monotonic.
        self.submitted_unix = time.time()
        self._event = threading.Event()
        self._result: Optional["ForecastResult"] = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def _complete(self, result: "ForecastResult") -> None:
        self._result = result
        self._event.set()

    def _fail(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    def result(self, timeout: Optional[float] = None) -> "ForecastResult":
        if not self._event.wait(timeout):
            raise TimeoutError("forecast still pending")
        if self._error is not None:
            raise self._error
        return self._result


@dataclasses.dataclass(frozen=True)
class ForecastResult:
    """Per-request output: (B, H) arrays in request series order."""

    series_ids: Tuple[str, ...]
    ds: np.ndarray                    # (B, H) float64 future grid
    values: Dict[str, np.ndarray]     # each (B, H)
    version: int
    latency_s: float
    from_cache: int                   # series rows served without a
                                      # fresh dispatch: LRU hits plus
                                      # plane-gathered rows (the plane
                                      # is the shared materialized
                                      # cache — cache.plane_hits)


#: Rolling-window sizes for the per-request/per-dispatch samples below:
#: a serving daemon runs indefinitely, so unbounded lists would be a
#: slow leak and make every stats call scan the full history.  100k
#: request latencies ≈ the last minute at the loadgen's measured rate.
_LATENCY_WINDOW = 100_000
_OCCUPANCY_WINDOW = 10_000


@dataclasses.dataclass
class EngineStats:
    submitted: int = 0
    completed: int = 0
    shed: int = 0
    failed: int = 0
    rejected: int = 0
    pumps: int = 0
    dispatches: int = 0
    # Breaker-open fast failures (BackendUnavailable) + the last
    # retry-after hint handed to a caller: the per-failure-domain
    # saturation signal the SERVE report and metrics surface.
    fast_failed: int = 0
    last_retry_after_s: Optional[float] = None
    # Series-rows served straight from the materialized forecast plane
    # (zero JAX dispatch) vs through ``backend.predict``: the split the
    # serveplane bench's hit-rate SLO rides.
    plane_hits: int = 0
    plane_misses: int = 0
    # Same split for interval reads against the quantile plane
    # (uncertainty/qplane.py): rows answered by the mmap gather vs
    # through the row-local compute fallback.
    qplane_hits: int = 0
    qplane_misses: int = 0
    latencies_s: "collections.deque" = dataclasses.field(
        default_factory=lambda: collections.deque(maxlen=_LATENCY_WINDOW)
    )
    # One (live, width, n_requests) triple per dispatched bucket.
    occupancy: "collections.deque" = dataclasses.field(
        default_factory=lambda: collections.deque(
            maxlen=_OCCUPANCY_WINDOW
        )
    )

    def snapshot(self) -> Dict:
        lat = np.asarray(self.latencies_s, np.float64)
        pct = (lambda q: round(float(np.percentile(lat, q)) * 1e3, 3)) \
            if lat.size else (lambda q: None)
        fill = [n / w for n, w, _ in self.occupancy if w]
        reqs = [r for _, _, r in self.occupancy]
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "shed": self.shed,
            "failed": self.failed,
            "rejected": self.rejected,
            "pumps": self.pumps,
            "dispatches": self.dispatches,
            "fast_failed": self.fast_failed,
            "retry_after_s": self.last_retry_after_s,
            "plane_hits": self.plane_hits,
            "plane_misses": self.plane_misses,
            "plane_hit_rate": (
                round(self.plane_hits
                      / (self.plane_hits + self.plane_misses), 4)
                if (self.plane_hits + self.plane_misses) else None
            ),
            "qplane_hits": self.qplane_hits,
            "qplane_misses": self.qplane_misses,
            "qplane_hit_rate": (
                round(self.qplane_hits
                      / (self.qplane_hits + self.qplane_misses), 4)
                if (self.qplane_hits + self.qplane_misses) else None
            ),
            "latency_ms": {
                "p50": pct(50), "p95": pct(95), "p99": pct(99),
                "mean": (round(float(lat.mean()) * 1e3, 3)
                         if lat.size else None),
                "max": (round(float(lat.max()) * 1e3, 3)
                        if lat.size else None),
            },
            "batch_occupancy": {
                "mean_fill": (round(float(np.mean(fill)), 4)
                              if fill else None),
                "mean_requests_per_dispatch": (
                    round(float(np.mean(reqs)), 2) if reqs else None
                ),
                "mean_requests_per_pump": (
                    round(self.completed / self.pumps, 2)
                    if self.pumps else None
                ),
            },
        }


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


class PredictionEngine:
    """Coalescing, cached, deadline-aware forecast server over a
    registry.

    ``pump`` drains and serves queued requests synchronously (the unit
    the daemon, the loadgen, and the tests drive); ``start``/``stop``
    run the same pump on a background thread for fully async use.
    """

    def __init__(
        self,
        registry: ParamRegistry,
        backend: Optional[ForecastBackend] = None,
        max_queue: int = 1024,
        max_batch: int = 256,
        width_floor: int = 8,
        horizon_floor: int = 8,
        cache: Optional[ForecastCache] = None,
        recorder=None,
        retry_policy=None,
        retry_on: Tuple = (Exception,),
        breaker: Optional[CircuitBreaker] = None,
        registry_breaker: Optional[CircuitBreaker] = None,
    ):
        """``breaker``: circuit breaker over backend dispatch — when a
        dead backend has failed it open, requests fail fast with the
        structured ``BackendUnavailable`` instead of retrying to their
        deadlines.  ``registry_breaker``: same gate over registry
        snapshot loads; while it is open the engine keeps serving the
        snapshot it already holds (stale beats down)."""
        self.registry = registry
        self.backend = backend if backend is not None else get_backend(
            "tpu", registry.config, SolverConfig()
        )
        self.max_batch = int(max_batch)
        self.width_floor = int(width_floor)
        self.horizon_floor = int(horizon_floor)
        self.cache = cache if cache is not None else ForecastCache()
        self.recorder = recorder
        self.retry_policy = retry_policy
        self.retry_on = retry_on
        self.breaker = breaker
        self.registry_breaker = registry_breaker
        self.stats = EngineStats()
        self._queue: "queue.Queue[PendingForecast]" = queue.Queue(
            maxsize=int(max_queue)
        )
        self._snapshot: Optional[Snapshot] = None
        self._manifest_key: Optional[Tuple[int, ...]] = None
        self._active_seen: Optional[int] = None
        # A snapshot loaded ahead of its activation (``prefetch``): the
        # next refresh that finds it matching the active pointer swaps
        # it in without a disk load — the flip-window latency saver the
        # pool's ahead-of-time materializer rides.
        self._prefetched: Optional[Snapshot] = None
        # Attached forecast planes, version-keyed (None memoizes a
        # failed/absent attach so a plane-less version costs one probe,
        # not one per pump).  Bounded: the engine only ever serves the
        # active version plus a prefetched successor.
        self._planes: Dict[int, Optional[fplane.FPlaneView]] = {}
        # Attached quantile planes (uncertainty/qplane.py), same
        # memoization discipline — a rejected/absent attach is cached
        # so interval reads on a plane-less version cost one probe.
        self._qplanes: Dict[int, Optional[object]] = {}
        self._pump_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Metric handles resolved once (docs/OBSERVABILITY.md naming):
        # the hot path pays one int add per outcome, no dict lookups.
        self._m_req = {
            r: METRICS.counter("tsspark_serve_requests_total", result=r)
            for r in ("completed", "shed", "failed", "rejected")
        }
        self._m_latency = METRICS.histogram(
            "tsspark_serve_request_seconds"
        )
        self._m_dispatches = METRICS.counter(
            "tsspark_serve_dispatches_total"
        )
        # Hot reads answered straight from the materialized forecast
        # plane (zero JAX dispatch) vs sent to backend.predict.
        self._m_plane = {
            r: METRICS.counter("tsspark_serve_plane_reads_total",
                               result=r)
            for r in ("hit", "miss")
        }
        self._m_queue = METRICS.gauge("tsspark_serve_queue_depth")
        # Live breaker state for the SLO watcher (obs.watch): 0 closed,
        # 1 open/half-open — updated at every dispatch outcome.
        self._m_breaker = METRICS.gauge("tsspark_serve_breaker_open")
        # Seconds until the open dispatch breaker admits a trial (0 when
        # closed) — the retry-after hint, scrapeable per failure domain.
        self._m_retry_after = METRICS.gauge(
            "tsspark_serve_retry_after_seconds"
        )
        # In-process activations invalidate immediately; refresh() also
        # polls the manifest so cross-process flips are picked up.
        registry.subscribe(self._on_activate)

    def _obs_request(self, pend: PendingForecast, status: str,
                     **attrs) -> None:
        """One ``serve.request`` span per resolved request: admission
        (submit) -> completion, the engine-side latency the SERVE_*
        report percentiles summarize — same clock, same value."""
        if not obs.active():
            return
        dur = time.monotonic() - pend.submitted_s
        req = pend.request
        obs.record("serve.request", pend.submitted_unix, dur,
                   status=status, n_series=len(req.series_ids),
                   horizon=req.horizon, **attrs)

    # -- snapshot lifecycle ----------------------------------------------------

    def _on_activate(self, version: Optional[int]) -> None:
        if version is not None:
            self._carry_cache_forward(version)
        self.cache.invalidate(version)
        self._snapshot = None  # lint-ok[lock-guard]: publisher-thread callback; a single store to None is GIL-atomic and refresh() reads the slot through a local (see its docstring) — taking the pump lock here would stall every flip behind an in-flight batch dispatch

    def _carry_cache_forward(self, new_version: int) -> None:
        """Partial cache invalidation on a DELTA flip: when the version
        being activated is a delta publish, unchanged series'
        parameters are bitwise the base version's, so their cached
        forecasts migrate to the new version instead of being dropped
        with the rest (``ForecastCache.carry_forward``).  Runs before
        the ``invalidate`` that settles the flip; a full publish (no
        delta metadata) keeps the drop-everything behavior."""
        try:
            info = self.registry.delta_info(int(new_version))
        except Exception:
            return  # torn/racing manifest: fall back to the full drop
        if not info or info.get("base_version") is None:
            return
        self.cache.carry_forward(
            info["base_version"], int(new_version),
            set(info.get("changed_ids") or ()),
        )

    def refresh(self) -> Snapshot:
        """The current active snapshot, reloading on version flips.

        Runs once per pump, so the steady state must stay off the
        manifest JSON: an unchanged stat key (mtime_ns, size) proves the
        active pointer cannot have moved — cross-process flips are
        caught by the key changing, in-process ones by the subscribe
        hook clearing ``_snapshot``.

        Reloads compare the ACTIVE pointer, not the loaded snapshot's
        version: when the registry fell back to the last good version
        under a corrupt active snapshot, the served version legitimately
        differs from the active one and must not trigger a reload every
        pump."""
        # One local read of the shared slot: _on_activate (a publisher
        # thread) may null self._snapshot at any point — the local keeps
        # this pump on a coherent snapshot (at worst one batch serves
        # the version from just before the flip; the version-keyed
        # cache makes that harmless) instead of racing into None.
        key = self.registry.manifest_key()
        snap = self._snapshot
        if snap is not None and key == self._manifest_key:
            return snap
        active = self.registry.active_version()
        if snap is None or active != self._active_seen:
            pre = self._prefetched
            if pre is not None and active == pre.version:
                # The flip was prefetched (pool warm / materialize):
                # swap it in without touching the disk.
                loaded: Optional[Snapshot] = pre
                self._prefetched = None
            else:
                loaded = self._load_active()
            if loaded is None:
                # Registry breaker open: serve the held snapshot but do
                # NOT advance the seen markers — the flip has not been
                # loaded yet, and marking it seen would pin this engine
                # to the stale snapshot forever once the breaker's
                # window elapses.  The next pump retries (the breaker
                # gate keeps retries cheap while it stays open).
                return snap
            self._carry_cache_forward(loaded.version)
            self.cache.invalidate(loaded.version)
            self._snapshot = loaded
            self._active_seen = active
            snap = loaded
            # Probe the new version's forecast + quantile planes at the
            # flip (the attach CRC sweep doubles as page warming); a
            # torn or absent plane memoizes None and compute serves.
            self._plane_for(loaded.version)
            self._qplane_for(loaded.version)
        self._manifest_key = key
        return snap

    def _load_active(self) -> Optional[Snapshot]:
        """Registry load guarded by ``registry_breaker``.  Returns None
        while the breaker refuses the load AND a held snapshot exists
        (serving one version behind beats serving nothing — the caller
        must then leave its staleness markers untouched so the load is
        retried after the window); with nothing held the failure
        surfaces as a structured RegistryError."""
        br = self.registry_breaker
        if br is not None and not br.allow():
            if self._snapshot is not None:
                return None
            raise RegistryError(
                "circuit-open",
                f"registry load suppressed by open breaker; retry in "
                f"{br.retry_after_s():.2f}s",
            )
        try:
            snap = self.registry.load()
        except BaseException:
            # BaseException: a half-open trial slot must be resolved
            # even on KeyboardInterrupt, or the breaker wedges with the
            # trial marked in flight forever.
            if br is not None:
                br.record_failure()
            raise
        if br is not None:
            br.record_success()
        return snap

    # -- forecast plane (zero-dispatch hot reads) ------------------------------

    def _plane_for(self, version: int
                   ) -> Optional[fplane.FPlaneView]:
        """The attached forecast plane for ``version``, or None.

        First probe per version attaches (CRC sweep = page warming);
        the outcome — including a rejected torn plane — is memoized, so
        a plane-less or corrupt version costs one probe and the engine
        serves it through the compute path with ONE structured event,
        never an outage (the torn-forecast-plane chaos contract)."""
        version = int(version)
        if version in self._planes:
            return self._planes[version]
        view: Optional[fplane.FPlaneView] = None
        try:
            vdir = self.registry.version_dir(version)
            if fplane.has_plane(vdir):
                view = fplane.attach(vdir)
        except fplane.ForecastPlaneError as e:
            obs.event("fplane.rejected", version=version,
                      reason=e.reason, detail=str(e))
        except Exception as e:
            obs.event("fplane.attach_failed", version=version,
                      error=repr(e))
        self._planes[version] = view
        while len(self._planes) > 4:
            self._planes.pop(next(iter(self._planes)))
        return view

    def attach_plane(self, version: int) -> bool:
        """Re-probe ``version``'s forecast plane, dropping any memoized
        failure first — the pool's warm/retry hook: after a torn
        publish is retried, the replica picks the fresh plane up here
        instead of staying memoized on the tear."""
        self._planes.pop(int(version), None)
        return self._plane_for(version) is not None

    # -- quantile plane (zero-dispatch interval reads) -------------------------

    def _qplane_for(self, version: int):
        """The attached quantile plane for ``version``, or None —
        ``_plane_for``'s discipline applied to the interval tier:
        first probe attaches (CRC sweep = page warming), every outcome
        including a rejected torn plane is memoized, and a corrupt
        plane degrades interval reads to the compute fallback with ONE
        structured event."""
        from tsspark_tpu.uncertainty import qplane

        version = int(version)
        if version in self._qplanes:
            return self._qplanes[version]
        view = None
        try:
            vdir = self.registry.version_dir(version)
            if qplane.has_qplane(vdir):
                view = qplane.attach(vdir)
        except qplane.QuantilePlaneError as e:
            obs.event("qplane.rejected", version=version,
                      reason=e.reason, detail=str(e))
        except Exception as e:
            obs.event("qplane.attach_failed", version=version,
                      error=repr(e))
        self._qplanes[version] = view
        while len(self._qplanes) > 4:
            self._qplanes.pop(next(iter(self._qplanes)))
        return view

    def attach_qplane(self, version: int) -> bool:
        """Re-probe ``version``'s quantile plane, dropping any memoized
        failure first (the post-retry pickup hook, like
        ``attach_plane``)."""
        self._qplanes.pop(int(version), None)
        return self._qplane_for(version) is not None

    def quantiles(self, series_ids: Sequence, horizon: int,
                  quantiles: Optional[Sequence[float]] = None
                  ) -> ForecastResult:
        """Interval forecast: per-series quantile rows, served from the
        version's quantile plane when it covers every requested
        (bucket, quantile) pair — a vectorized memmap gather, zero JAX
        dispatch — else through the row-local compute fallback
        (``uncertainty.qplane.compute_rows``), which reproduces
        plane-covered cells bit for bit by construction.

        Synchronous by design: the gather path does no device work to
        coalesce, and the fallback is host-side sampling — neither
        belongs in the dispatch pump's batch economics.  ``quantiles``
        defaults to the plane's published set (or
        ``DEFAULT_QUANTILES`` with no plane); a long-tail quantile the
        plane does not carry routes the whole request to compute.

        Returns a :class:`ForecastResult` whose values are keyed
        ``"q<permille>"`` (``q100``/``q500``/``q900`` by default);
        ``from_cache`` counts plane-served rows."""
        from tsspark_tpu.uncertainty import advi as advi_mod
        from tsspark_tpu.uncertainty import qplane

        t0 = time.monotonic()
        sids = [str(s) for s in series_ids]
        if not sids:
            raise ValueError("series_ids must be non-empty")
        with self._pump_lock:
            snap = self.refresh()
        version = snap.version
        idx, missing = snap.rows(sids)
        if missing:
            raise UnknownSeries(missing, version)
        idx = np.asarray(idx, np.int64)
        h = int(horizon)
        hb = max(self.horizon_floor, next_pow2(h))
        view = self._qplane_for(version)
        qs = (tuple(float(q) for q in quantiles)
              if quantiles is not None
              else (view.quantiles if view is not None
                    else qplane.DEFAULT_QUANTILES))
        if view is not None and view.covers(hb, qs):
            grid, gathered = qplane.quantile_batch(view, snap, idx, hb)
            values = {f"q{qplane.permille(q):03d}":
                      gathered[qplane.permille(q)][:, :h] for q in qs}
            ds = grid[:, :h]
            self.stats.qplane_hits += len(sids)
            cached = len(sids)
        else:
            self.stats.qplane_misses += len(sids)
            draws = view.draws if view is not None else \
                qplane.DEFAULT_DRAWS
            seed = view.seed if view is not None else \
                qplane.DEFAULT_SEED
            posterior = None
            if view is not None and view.mode == "advi":
                loaded = advi_mod.load_posterior(
                    self.registry.version_dir(version)
                )
                if loaded is not None:
                    posterior = loaded[0]
            cols = qplane.compute_rows(
                snap, self.registry.config, self.backend, idx, hb,
                quantiles=qs, draws=draws, seed=seed,
                posterior=posterior,
            )
            meta = snap.state.meta
            last = (np.asarray(meta.ds_start, np.float64)[idx]
                    + np.asarray(meta.ds_span, np.float64)[idx])
            step = np.asarray(snap.step, np.float64)[idx]
            grid = last[:, None] + step[:, None] * np.arange(1, hb + 1)
            values = {f"q{qplane.permille(q):03d}":
                      cols[qplane.permille(q)][:, :h] for q in qs}
            ds = grid[:, :h]
            cached = 0
        return ForecastResult(
            series_ids=sids, ds=ds, values=values, version=version,
            latency_s=time.monotonic() - t0, from_cache=cached,
        )

    # -- version discipline (pool support) -------------------------------------

    def served_version(self) -> Optional[int]:
        """The version the engine is currently serving (None before the
        first refresh)."""
        snap = self._snapshot
        return None if snap is None else snap.version

    def prefetch(self, version: int) -> Snapshot:
        """Load ``version`` ahead of its activation and stash it: the
        refresh that later finds the active pointer at this version
        swaps it in with zero disk I/O.  Explicit version — no
        fallback substitution."""
        snap = self.registry.load(int(version), fallback=False)
        self._prefetched = snap  # lint-ok[lock-guard]: single reference store; a refresh racing this at worst drops the stash and pays one disk load on the next flip — never a torn snapshot (the loaded object is immutable)
        return snap

    def ensure_version(self, version: int) -> bool:
        """Force the engine onto ``version`` if the registry's active
        pointer agrees: drops the cached staleness markers and reloads
        when the served version differs.  Returns True when the engine
        now serves exactly ``version`` (False when the registry's
        active pointer is elsewhere — the caller decides whether that
        is a mismatch error).  Serialized against the pump."""
        version = int(version)
        with self._pump_lock:
            try:
                snap = self.refresh()
            except Exception:
                snap = None
            if snap is not None and snap.version == version:
                return True
            self._snapshot = None
            self._manifest_key = None
            self._active_seen = None
            try:
                snap = self.refresh()
            except Exception:
                return False
            return snap is not None and snap.version == version

    def materialize(self, series_ids: Sequence, horizons: Sequence[int],
                    version: Optional[int] = None, num_samples: int = 0,
                    seed: int = 0, max_width: int = 256) -> int:
        """Ahead-of-time forecast materialization: compute forecasts
        for ``series_ids`` x ``horizons`` into the version-keyed cache
        — against ``version`` (prefetching its snapshot) or the active
        one.  Used by the pool's activate path so a version flip lands
        on a warm cache; idempotent (already-cached rows are skipped).
        Returns the number of series-rows computed."""
        if version is None:
            with self._pump_lock:
                snap = self.refresh()
        else:
            pre = self._prefetched
            snap = (pre if pre is not None
                    and pre.version == int(version)
                    else self.prefetch(version))
        self.cache.allow_version(snap.version)
        # Delta flip: migrate unchanged series' cached rows into the
        # warm window FIRST — the materialization loop below then
        # computes only what carry-forward cannot cover (the refit
        # series), which is the whole point of a delta publish.  Gated
        # on warming a version this engine is NOT yet serving: once the
        # flip settles, re-materializing the (delta) active version
        # must not re-read the delta manifest and rescan the cache
        # under its lock per call.  A full publish is a no-op either
        # way.
        if snap.version != self.served_version():
            self._carry_cache_forward(snap.version)
        ids = list(dict.fromkeys(str(s) for s in series_ids))
        _, missing = snap.rows(ids)
        absent = set(missing)
        ids = [s for s in ids if s not in absent]
        # Fresh plane probe (not the memoized outcome): warming runs at
        # flip/retry time, exactly when a just-published plane — or a
        # retried one replacing a torn publish — should be adopted.
        self.attach_plane(snap.version)
        view = self._plane_for(snap.version)
        warmed = 0
        for h in horizons:
            hb = max(self.horizon_floor, next_pow2(int(h)))
            if view is not None and view.covers(hb, num_samples):
                # Plane-covered bucket: every replica already reads it
                # from the shared pages — duplicating rows into this
                # process's LRU would cost heap for no hit-rate.
                continue
            todo = [
                s for s in ids
                if self.cache.peek((snap.version, s, hb, num_samples,
                                    seed)) is None
            ]
            for i in range(0, len(todo), int(max_width)):
                part = todo[i:i + int(max_width)]
                fresh = self._dispatch(snap, part, hb, num_samples,
                                       seed, n_requests=0)
                for sid, row in fresh.items():
                    self.cache.put(
                        (snap.version, sid, hb, num_samples, seed), row
                    )
                    warmed += 1
        return warmed

    # -- request intake --------------------------------------------------------

    def submit(self, request: ForecastRequest) -> PendingForecast:
        pend = PendingForecast(request)
        try:
            self._queue.put_nowait(pend)
        except queue.Full:
            self.stats.rejected += 1
            self._m_req["rejected"].inc()
            self._obs_request(pend, "err", reason="overloaded")
            raise EngineOverloaded(
                f"request queue full ({self._queue.maxsize})"
            )
        self.stats.submitted += 1
        return pend

    def forecast(self, series_ids: Sequence, horizon: int,
                 num_samples: int = 0, seed: int = 0,
                 deadline_in_s: Optional[float] = None,
                 timeout_s: Optional[float] = 60.0) -> ForecastResult:
        """Synchronous convenience: submit + serve (pumping inline when
        no background worker is running)."""
        pend = self.submit(ForecastRequest.make(
            series_ids, horizon, num_samples=num_samples, seed=seed,
            deadline_in_s=deadline_in_s,
        ))
        if self._thread is None:
            while not pend.done():
                self.pump(block_s=0.0)
        return pend.result(timeout=timeout_s)

    # -- the batch loop --------------------------------------------------------

    def pump(self, max_batch: Optional[int] = None,
             block_s: float = 0.0) -> int:
        """Drain up to one batch of queued requests and serve it.
        Returns the number of requests resolved (served, shed, or
        failed).  ``block_s``: how long to wait for the FIRST request
        (coalescing window); once one arrives, everything already
        queued joins its batch."""
        with self._pump_lock:
            batch: List[PendingForecast] = []
            cap = self.max_batch if max_batch is None else int(max_batch)
            try:
                batch.append(self._queue.get(
                    block=block_s > 0, timeout=block_s or None
                ))
            except queue.Empty:
                return 0
            while len(batch) < cap:
                try:
                    batch.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            self.stats.pumps += 1
            self._m_queue.set(self._queue.qsize())
            try:
                snap = self.refresh()
            except Exception as e:
                for pend in batch:
                    pend._fail(e)
                    self._m_req["failed"].inc()
                    self._obs_request(pend, "err", reason="refresh")
                self.stats.failed += len(batch)
                return len(batch)
            now = time.monotonic()
            groups: Dict[Tuple[int, int, int], List[PendingForecast]] = {}
            resolved = 0
            for pend in batch:
                req = pend.request
                if req.deadline_s is not None and now > req.deadline_s:
                    pend._fail(RequestShed(req.deadline_s, now))
                    self.stats.shed += 1
                    self._m_req["shed"].inc()
                    self._obs_request(pend, "err", reason="shed")
                    resolved += 1
                    continue
                hb = max(self.horizon_floor, next_pow2(req.horizon))
                groups.setdefault(
                    (hb, req.num_samples, req.seed), []
                ).append(pend)
            for (hb, n_s, seed), pends in groups.items():
                try:
                    resolved += self._dispatch_group(snap, hb, n_s,
                                                     seed, pends)
                except Exception as e:
                    # A group whose dispatch escapes (engine bug, OOM)
                    # must fail ITS OWN pends — abandoning them would
                    # leave submitters blocked to their timeouts, and
                    # the remaining groups of this pump unserved.
                    for pend in pends:
                        if not pend.done():
                            pend._fail(e)
                            self.stats.failed += 1
                            self._m_req["failed"].inc()
                            self._obs_request(pend, "err",
                                              reason="pump-escape")
                            resolved += 1
            return resolved

    def _dispatch_group(self, snap: Snapshot, hb: int, num_samples: int,
                        seed: int, pends: List[PendingForecast]) -> int:
        """Serve one (horizon-bucket, num_samples, seed) group: resolve
        the cache, dispatch ONE padded predict for the misses, scatter,
        assemble per request."""
        version = snap.version
        rows: Dict[str, Dict] = {}      # sid -> per-series row dict
        hits: Dict[str, bool] = {}
        needed: List[str] = []          # unique cache misses, in order
        needed_set = set()
        row_idx: Dict[str, int] = {}    # miss sid -> snapshot row
        live: List[PendingForecast] = []
        for pend in pends:
            if not pend.request.series_ids:
                # Direct ForecastRequest construction bypasses make()'s
                # validation; an empty request must fail alone, not
                # crash the batch it was coalesced into.
                pend._fail(ValueError("series_ids must be non-empty"))
                self.stats.failed += 1
                self._m_req["failed"].inc()
                self._obs_request(pend, "err", reason="empty-request")
                continue
            idx, missing = snap.rows(pend.request.series_ids)
            if missing:
                pend._fail(UnknownSeries(missing, version))
                self.stats.failed += 1
                self._m_req["failed"].inc()
                self._obs_request(pend, "err", reason="unknown-series",
                                  version=version)
                continue
            live.append(pend)
            # With missing empty, rows() returns one index per input id
            # in input order on both the dict and the sorted-mmap path,
            # so this zip lines up — the plane gather below reuses these
            # indices instead of paying a second id resolution.
            for sid, r in zip(pend.request.series_ids, idx):
                if sid in rows or sid in needed_set:
                    continue
                val = self.cache.get((version, sid, hb, num_samples,
                                      seed))
                if val is None:
                    needed.append(sid)
                    needed_set.add(sid)
                    row_idx[sid] = r
                else:
                    rows[sid] = val
                    hits[sid] = True
        batch = None                    # (grid, gathered, pos) fast path
        if needed:
            # Materialized-forecast-plane fast path: a deterministic
            # group whose bucket the active plane covers is answered by
            # a vectorized memmap gather — zero JAX dispatch, one
            # page-cache copy shared by every replica.  Not inserted
            # into the cache: the plane IS the shared cache, and
            # duplicating its rows into per-process LRUs would undo the
            # one-copy memory story.  Long-tail buckets and sampled
            # requests fall through to the compute path below.
            view = self._plane_for(version)
            if view is not None and view.covers(hb, num_samples):
                idx = np.fromiter((row_idx[s] for s in needed),
                                  np.int64, len(needed))
                if not rows:
                    # Every series of every live request is a cache
                    # miss answered by this ONE gather, so the batch
                    # arrays serve the group whole — fancy-indexing a
                    # gathered column is bitwise np.stack over its
                    # rows, minus the per-series dict scatter and the
                    # restack.
                    grid, gathered = fplane.plane_batch(view, snap,
                                                        idx, hb)
                    batch = (grid, gathered,
                             {s: i for i, s in enumerate(needed)})
                else:
                    served = fplane.plane_rows(view, snap, idx, hb)
                    for sid, row in zip(needed, served):
                        rows[sid] = row
                        hits[sid] = True
                self.stats.plane_hits += len(needed)
                self._m_plane["hit"].inc(len(needed))
                self.cache.note_plane_hits(len(needed))
                needed = []
            else:
                self.stats.plane_misses += len(needed)
                self._m_plane["miss"].inc(len(needed))
        if needed:
            try:
                fresh = self._dispatch(snap, needed, hb, num_samples,
                                       seed, n_requests=len(live))
            except Exception as e:
                reason = (e.reason if isinstance(e, ServeError)
                          else type(e).__name__)
                for pend in live:
                    pend._fail(e)
                    self._m_req["failed"].inc()
                    self._obs_request(pend, "err", reason=reason,
                                      version=version)
                self.stats.failed += len(live)
                return len(pends)
            # Activation-race note: if an activation lands while the
            # dispatch runs, its listener invalidates the cache — and
            # the cache's version gate (ForecastCache.put, atomic under
            # the cache lock) drops these late inserts for the retired
            # version instead of pinning them.  The results still serve
            # this batch's requests either way.
            for sid, row in fresh.items():
                rows[sid] = row
                self.cache.put((version, sid, hb, num_samples, seed),
                               row)
        done_s = time.monotonic()
        for pend in live:
            req = pend.request
            h = req.horizon
            sids = req.series_ids
            if batch is not None:
                grid, gathered, pos = batch
                sel = [pos[s] for s in sids]
                ds = grid[sel][:, :h]
                values = {k: v[sel][:, :h]
                          for k, v in gathered.items()}
                cached = len(sids)
            else:
                values = {
                    k: np.stack([rows[s][k] for s in sids])[:, :h]
                    for k in rows[sids[0]] if k != "ds"
                }
                ds = np.stack([rows[s]["ds"] for s in sids])[:, :h]
                cached = sum(1 for s in sids if hits.get(s))
            pend._complete(ForecastResult(
                series_ids=sids,
                ds=ds,
                values=values,
                version=version,
                latency_s=done_s - pend.submitted_s,
                from_cache=cached,
            ))
            self.stats.completed += 1
            self.stats.latencies_s.append(done_s - pend.submitted_s)
            self._m_req["completed"].inc()
            self._m_latency.observe(done_s - pend.submitted_s)
            if obs.active():
                obs.record(
                    "serve.request", pend.submitted_unix,
                    done_s - pend.submitted_s, version=version,
                    n_series=len(sids), horizon=h, cached=cached,
                )
        return len(pends)

    def _dispatch(self, snap: Snapshot, sids: List[str], hb: int,
                  num_samples: int, seed: int,
                  n_requests: int) -> Dict[str, Dict]:
        """One padded ``backend.predict`` over the missing series."""
        idx, _ = snap.rows(sids)
        n = len(sids)
        width = compacted_width(n, floor=self.width_floor, multiple=1)
        if width > n:
            idx = np.concatenate([idx, np.repeat(idx[:1], width - n)])
        state, step = snap.take(idx)
        # Each series continues its own calendar at its recorded
        # cadence: one float64 broadcast, no history scans.
        last = np.asarray(state.meta.ds_start + state.meta.ds_span,
                          np.float64)
        grid = last[:, None] + step[:, None] * np.arange(1, hb + 1)

        def run():
            faults.inject("serve_predict")
            out = self.backend.predict(
                state, grid, num_samples=num_samples, seed=seed
            )
            # Pull to host INSIDE the timed scope: the jitted forecast
            # returns async device arrays, and an un-blocked dispatch
            # would time only the enqueue (perf.PerfRecorder contract).
            return {k: np.asarray(v) for k, v in out.items()}

        # Dispatch circuit breaker: a backend that has been failing
        # across dispatches sheds this one fast (structured error, no
        # retries burned); each dispatch counts as ONE breaker outcome
        # even when the retry policy makes several attempts inside it.
        if self.breaker is not None and not self.breaker.allow():
            retry_after = self.breaker.retry_after_s()
            self.stats.fast_failed += 1
            self.stats.last_retry_after_s = round(retry_after, 3)
            self._m_breaker.set(1.0)
            self._m_retry_after.set(retry_after)
            raise BackendUnavailable(self.breaker.name, retry_after)
        ctx = (self.recorder.dispatch(width, live=n, kind="predict")
               if self.recorder is not None else contextlib.nullcontext())
        # ok-flag + finally (not except Exception): even a BaseException
        # escape must resolve the breaker's half-open trial slot, or the
        # breaker wedges with the trial marked in flight forever.
        ok = False
        t_disp0 = time.time()
        m_disp0 = time.monotonic()
        try:
            with ctx:
                if self.retry_policy is not None:
                    out = self.retry_policy.call(run,
                                                 retry_on=self.retry_on)
                else:
                    out = run()
            ok = True
        finally:
            if self.breaker is not None:
                (self.breaker.record_success if ok
                 else self.breaker.record_failure)()
                closed = self.breaker.state == CircuitBreaker.CLOSED
                self._m_breaker.set(0.0 if closed else 1.0)
                self._m_retry_after.set(
                    0.0 if closed else self.breaker.retry_after_s()
                )
            if obs.active():
                obs.record("serve.dispatch", t_disp0,
                           time.monotonic() - m_disp0,
                           status="ok" if ok else "err",
                           width=width, live=n, horizon=hb,
                           version=snap.version)
        self.stats.dispatches += 1
        self._m_dispatches.inc()
        self.stats.occupancy.append((n, width, n_requests))
        result: Dict[str, Dict] = {}
        for i, sid in enumerate(sids):
            row = {k: v[i] for k, v in out.items()}
            row["ds"] = grid[i]
            result[sid] = row
        return result

    # -- background worker -----------------------------------------------------

    def start(self, poll_s: float = 0.02) -> None:
        """Run ``pump`` on a daemon thread until ``stop``."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            import traceback

            while not self._stop.is_set():
                try:
                    self.pump(block_s=poll_s)
                except Exception:
                    # pump() resolves per-request failures itself; an
                    # escape here is a bug, but it must not kill the
                    # worker and leave every later submit hanging.
                    # Loud on stderr: a silent swallow here cost a
                    # debugging session (requests timing out with no
                    # trace of why).
                    traceback.print_exc()
                    time.sleep(poll_s)

        self._thread = threading.Thread(
            target=loop, name="serve-pump", daemon=True
        )
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout_s)
        self._thread = None
