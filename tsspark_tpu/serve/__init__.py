"""Forecast serving subsystem (``docs/SERVING.md``).

The read path as a first-class subsystem — the fit side's mirror image:

  registry.py — versioned, atomic parameter registry over
                ``utils.checkpoint`` + ``utils.atomic``: publish /
                activate / rollback of fitted ``FitState`` snapshots,
                manifest validated at load (format, config fingerprint,
                NUMERICS_REV), per-series row lookup.
  engine.py   — micro-batched prediction engine: bounded-queue
                admission, request coalescing into pow-2 shape buckets
                (the fit path's ``compacted_width`` ladder, so the jit
                cache stays small), deadline shedding with structured
                errors, ``RetryPolicy``-wrapped dispatch.
  snapplane.py — memmap snapshot column plane: every registry version
                as spec-first / CRC-sentinel-last ``.npy`` columns the
                engine and every pool replica attach read-only, so N
                processes map ONE page-cache copy of the active
                version (the npz stays the archival fallback; the two
                formats serve bitwise-equal predictions).
  cache.py    — version-keyed per-series forecast LRU, BOUNDED with
                strict eviction + an eviction counter, invalidated on
                registry activation, with hit/miss counters.
  __main__.py — ``python -m tsspark_tpu.serve``: a stdin/stdout JSONL
                daemon, plus ``--loadgen`` which replays a synthetic
                request mix and emits a ``SERVE_*.json`` latency report
                (p50/p95/p99, batch occupancy, cache hit rate).

  pool.py     — engine replica pool: N replica processes (each a full
                engine over the shared registry) behind one sharding
                front — lease-fenced replica identity, heartbeat health
                checks, per-replica circuit breakers, failover to
                sibling shard owners, respawn under RetryPolicy
                backoff, and version flips drained one replica at a
                time behind an ahead-of-time forecast materializer.

Producers publish: ``orchestrate.publish_fit_state`` (chunked fleet
runs) and ``streaming.ParamStore.publish`` / ``StreamingForecaster.
publish`` (the micro-batch refit loop).  ``StreamingForecaster`` with
an attached engine routes its ``forecast`` through this subsystem, so
streaming and serving share one batched read path.
"""

from tsspark_tpu.serve.cache import ForecastCache
from tsspark_tpu.serve.engine import (
    BackendUnavailable,
    EngineOverloaded,
    EngineStats,
    ForecastRequest,
    ForecastResult,
    PendingForecast,
    PredictionEngine,
    RequestShed,
    ServeError,
    UnknownSeries,
)
from tsspark_tpu.serve.pool import (
    NoReplicaAvailable,
    PoolError,
    ReplicaFenced,
    ReplicaPool,
    VersionMismatch,
    shard_of,
)
from tsspark_tpu.serve.registry import (
    NUMERICS_REV,
    ParamRegistry,
    RegistryError,
    Snapshot,
    take_fitstate,
)

__all__ = [
    "BackendUnavailable",
    "EngineOverloaded",
    "EngineStats",
    "ForecastCache",
    "ForecastRequest",
    "ForecastResult",
    "NUMERICS_REV",
    "NoReplicaAvailable",
    "ParamRegistry",
    "PendingForecast",
    "PoolError",
    "PredictionEngine",
    "RegistryError",
    "ReplicaFenced",
    "ReplicaPool",
    "RequestShed",
    "ServeError",
    "Snapshot",
    "UnknownSeries",
    "VersionMismatch",
    "shard_of",
    "take_fitstate",
]
