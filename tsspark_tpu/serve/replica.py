"""``python -m tsspark_tpu.serve.replica`` — one pool replica process.

Spawned by ``serve.pool.ReplicaPool`` (not an operator entry point):
claims its slot lease, attaches a full ``PredictionEngine`` over the
shared registry, and serves the JSONL envelope on its unix socket until
killed, told to quit, or fenced out of its lease.  Lives outside
``serve/__init__`` imports so runpy executes it without the
found-in-sys.modules double-import warning.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    # Same device pinning as `python -m tsspark_tpu.serve`: a replica
    # must never block on a wedged accelerator tunnel.
    if os.environ.get("TSSPARK_SERVE_DEVICE", "cpu") == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax

        jax.config.update("jax_platforms", "cpu")
    cache_dir = os.environ.get("TSSPARK_JAX_CACHE")
    if cache_dir:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.5
        )
    # The AOT program bank's cache wins when configured: first-request
    # programs were compiled at publish time into this directory, so a
    # cold replica LOADS them instead of paying the compile wall
    # (docs/SERVING.md, "AOT program bank").
    from tsspark_tpu.serve import aotbank

    aotbank.arm_from_env()

    ap = argparse.ArgumentParser(
        prog="python -m tsspark_tpu.serve.replica",
        description="serve replica-pool worker (docs/SERVING.md, "
                    "'Replica pool & failure domains')",
    )
    ap.add_argument("--pool-dir", required=True)
    ap.add_argument("--slot", type=int, required=True)
    ap.add_argument("--registry", required=True)
    ap.add_argument("--socket", required=True)
    ap.add_argument("--gen", type=int, default=1)
    ap.add_argument("--heartbeat-s", type=float, default=0.25)
    ap.add_argument("--lease-ttl-s", type=float, default=1.5)
    ap.add_argument("--max-queue", type=int, default=4096)
    ap.add_argument("--max-batch", type=int, default=128)
    # None defers to the configured default
    # ($TSSPARK_SERVE_CACHE_CAPACITY -> serve.cache.default_capacity).
    ap.add_argument("--cache-capacity", type=int, default=None)
    args = ap.parse_args(argv)

    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.serve.pool import run_replica

    obs.adopt_env()
    return run_replica(
        args.pool_dir, args.slot, args.registry, args.socket,
        gen=args.gen, heartbeat_s=args.heartbeat_s,
        lease_ttl_s=args.lease_ttl_s, max_queue=args.max_queue,
        max_batch=args.max_batch, cache_capacity=args.cache_capacity,
    )


if __name__ == "__main__":
    sys.exit(main())
