"""Materialized forecast plane: point forecasts as shared mmap pages.

The serving read path's last compute dependency removed (ROADMAP item
1): at version-flip time the publisher batch-computes the full
(series x horizon-bucket) point-forecast table for the new version and
lands it in the version dir as a memmap column plane under the same
spec-first / atomic-columns / CRC-sentinel-last protocol the snapshot
plane uses (``plane/protocol.py``) —

* ``fplane_spec.json`` — identity record (bucket ladder, column
  dtypes/shapes, config fingerprint, NUMERICS_REV), written FIRST;
* ``fcol_h<bucket>_<key>.npy`` — one plain npy per (horizon bucket,
  output key): ``yhat`` / ``trend`` / ``additive`` / ``multiplicative``,
  each ``(n_series, bucket)`` in the exact dtype ``backend.predict``
  returns — a plane row IS the engine's dispatch output, bit for bit;
* ``fplaneok.json`` — the CRC sentinel, written LAST: per-shard CRC32
  of every column's rows.  A torn publish (killed mid-column) fails the
  sentinel and is REJECTED at attach; the engine then keeps serving
  through its compute path — never a wrong number, never an outage.

Every replica that attaches answers hot point-forecast reads with a
vectorized memmap gather out of ONE page-cache copy — zero JAX dispatch
on the read path (:func:`plane_batch` roots the ``serve-plane-read``
effect budget with ``jax-dispatch`` forbidden, so "mmap only" is a
machine-checked gate failure, not a benchmark claim).  The ``ds`` grid
is NOT stored: it is recomputed at read time with the engine's exact
float64 formula over the snapshot plane's cadence columns, which is
bitwise identical and saves one float64 column per bucket.

Delta versions copy-forward unchanged series' columns exactly like
``serve/snapplane.py``: hardlink when nothing in a column changed,
else one sequential base read + a vectorized scatter of the refit
rows' freshly computed forecasts, with CRCs recomputed only for the
shards a changed row lands in.

Publishing is SPECULATIVE work: :func:`maybe_publish` refuses under the
disk-pressure ladder's ``shed_spec`` state and degrades (returns None)
on a disk-budget refusal instead of failing the flip — the plane is an
accelerator, the compute path is the contract.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tsspark_tpu.io import (
    BackpressureError,
    DiskFullError,
    active_ladder,
    link_or_copy,
)
from tsspark_tpu.obs import context as obs
from tsspark_tpu.parallel.sharding import next_pow2
from tsspark_tpu.plane.protocol import (
    attach_column,
    read_json,
    shard_crcs,
    shard_ranges,
    verify_crcs,
    write_column,
    write_sentinel,
    write_spec,
)
from tsspark_tpu.resilience import faults

__all__ = [
    "FPLANE_FORMAT", "FPLANE_SPEC", "FPLANE_OK", "FCOL_PREFIX",
    "POINT_KEYS", "DEFAULT_HOT_HORIZONS", "DEFAULT_SHARD_ROWS",
    "ForecastPlaneError", "FPlaneView", "bucket_ladder", "future_grid",
    "write_plane", "write_plane_delta", "attach", "has_plane",
    "verify_plane", "plane_batch", "plane_rows", "maybe_publish",
    "plane_nbytes",
]

#: Plane format revision (bump on incompatible layout change; the
#: reader refuses unknown revisions instead of misparsing them).
FPLANE_FORMAT = 1

FPLANE_SPEC = "fplane_spec.json"
FPLANE_OK = "fplaneok.json"
FCOL_PREFIX = "fcol_"

#: The deterministic (num_samples=0) predict output keys — the engine's
#: per-series row dict minus the recomputed ``ds`` grid.
POINT_KEYS = ("yhat", "trend", "additive", "multiplicative")

#: Horizons the plane covers by default — the pool's hot-horizon set;
#: the bucket ladder they induce is {8, 16, 32}.
DEFAULT_HOT_HORIZONS = (7, 14, 28)

#: CRC shard width (rows) — same bound as the snapshot plane's: what
#: one torn write can hide behind a stale CRC.
DEFAULT_SHARD_ROWS = 65536

#: Engine horizon floor (PredictionEngine.horizon_floor's default):
#: buckets below it never reach a dispatch, so the plane never needs
#: them either.
_HORIZON_FLOOR = 8

#: Publish-time batch width for the full-table compute.
_PUBLISH_CHUNK = 256


class ForecastPlaneError(RuntimeError):
    """Structured plane failure.  ``reason`` is ``"absent"`` (no plane
    was ever published here — serve through the compute path silently)
    or ``"corrupt"`` (a plane exists but fails its sentinel — torn
    publish; the reader must refuse it)."""

    def __init__(self, reason: str, detail: str):
        super().__init__(f"{reason}: {detail}")
        self.reason = reason


def bucket_ladder(horizons: Sequence[int],
                  floor: int = _HORIZON_FLOOR) -> Tuple[int, ...]:
    """The pow-2 horizon buckets ``horizons`` land in — the engine's
    grouping ladder (``max(floor, next_pow2(h))``), deduplicated."""
    return tuple(sorted({max(int(floor), next_pow2(int(h)))
                         for h in horizons}))


def _col_name(hb: int, key: str) -> str:
    return f"h{int(hb)}_{key}"


def _col_path(vdir: str, name: str) -> str:
    return os.path.join(vdir, f"{FCOL_PREFIX}{name}.npy")


def future_grid(state, step: np.ndarray, hb: int) -> np.ndarray:
    """The engine's future time grid, verbatim (``PredictionEngine.
    _dispatch``): each series continues its own calendar at its
    recorded cadence — float64 throughout, so a plane-time grid and a
    request-time grid over the same rows are bitwise identical."""
    last = np.asarray(state.meta.ds_start + state.meta.ds_span,
                      np.float64)
    return last[:, None] + np.asarray(step, np.float64)[:, None] \
        * np.arange(1, int(hb) + 1)


def _predict_rows(snap, backend, idx: np.ndarray, hb: int,
                  chunk: int = _PUBLISH_CHUNK) -> Dict[str, np.ndarray]:
    """Deterministic point forecasts for snapshot rows ``idx`` at
    bucket ``hb``: the engine's dispatch math (gather -> grid ->
    ``backend.predict`` at num_samples=0) in publish-width chunks.
    Every predict op is row-local, so the chunking is bitwise-invisible
    (the engine-parity contract tests/test_serve.py pins)."""
    from tsspark_tpu.parallel.sharding import compacted_width

    idx = np.asarray(idx, np.int64)
    outs: List[Dict[str, np.ndarray]] = []
    for lo in range(0, len(idx), int(chunk)):
        part = idx[lo:lo + int(chunk)]
        n_part = len(part)
        # Pad up the engine's pow-2 width ladder (width_floor=8,
        # repeat-first-row padding): publish-time programs then share
        # the serve tier's compile shapes — the AOT bank covers both,
        # and a publisher never mints one-off widths.
        width = compacted_width(n_part, floor=_HORIZON_FLOOR,
                                multiple=1)
        if width > n_part:
            part = np.concatenate(
                [part, np.repeat(part[:1], width - n_part)]
            )
        state, step = snap.take(part)
        grid = future_grid(state, step, hb)
        out = backend.predict(state, grid, num_samples=0, seed=0)
        outs.append({k: np.asarray(out[k])[:n_part]
                     for k in POINT_KEYS})
    if not outs:
        return {k: np.empty((0, int(hb)), np.float32)
                for k in POINT_KEYS}
    return {k: np.ascontiguousarray(
                np.concatenate([o[k] for o in outs], axis=0))
            for k in POINT_KEYS}


def write_plane(vdir: str, snap, backend, *,
                horizons: Sequence[int] = DEFAULT_HOT_HORIZONS,
                fingerprint: Optional[str] = None,
                numerics_rev: Optional[int] = None,
                shard_rows: int = DEFAULT_SHARD_ROWS,
                chunk: int = _PUBLISH_CHUNK) -> Dict:
    """Land the full forecast plane for ``snap`` in ``vdir``: spec
    first, columns (each itself atomic), CRC sentinel LAST.  The
    ``fplane_publish`` fault point is armed per column so the chaos
    harness can kill a publisher mid-plane and prove the sentinel
    rejects the tear.  Returns the spec."""
    n = int(np.asarray(snap.state.theta).shape[0])
    buckets = bucket_ladder(horizons)
    cols: Dict[str, np.ndarray] = {}
    for hb in buckets:
        fresh = _predict_rows(snap, backend, np.arange(n), hb,
                              chunk=chunk)
        for key in POINT_KEYS:
            cols[_col_name(hb, key)] = fresh[key]
    spec = {
        "format": FPLANE_FORMAT,
        "n_series": n,
        "shard_rows": int(shard_rows),
        "buckets": [int(b) for b in buckets],
        "keys": list(POINT_KEYS),
        "horizons": [int(h) for h in horizons],
        "fingerprint": fingerprint,
        "numerics_rev": numerics_rev,
        "columns": {k: {"dtype": a.dtype.str, "shape": list(a.shape)}
                    for k, a in cols.items()},
    }
    write_spec(os.path.join(vdir, FPLANE_SPEC), spec)
    for name, arr in cols.items():
        faults.inject("fplane_publish")
        write_column(_col_path(vdir, name), arr)
    sentinel = {
        "format": FPLANE_FORMAT,
        "n_series": n,
        "shard_rows": int(shard_rows),
        "unix": round(time.time(), 3),
        "shards": [[lo, hi, shard_crcs(cols, lo, hi)]
                   for lo, hi in shard_ranges(n, shard_rows)],
    }
    write_sentinel(os.path.join(vdir, FPLANE_OK), sentinel)
    return spec


def write_plane_delta(vdir: str, base_vdir: str, changed_rows,
                      snap, backend, *,
                      fingerprint: Optional[str] = None,
                      numerics_rev: Optional[int] = None,
                      base_version: Optional[int] = None) -> Dict:
    """Copy-forward delta publish: land the NEW version's forecast
    plane in ``vdir`` from the base version's in ``base_vdir`` plus a
    fresh compute over only ``changed_rows`` (``snap`` is the NEW
    version's snapshot — unchanged rows' parameters are bitwise the
    base's, so their base-plane forecasts are exactly what this
    version would compute).

    Per column: the zero-delta fast path HARDLINKS wholesale (zero new
    bytes, base CRCs reused verbatim); otherwise one sequential base
    read, a vectorized scatter of the recomputed changed rows, one
    atomic save — with CRCs recomputed only for the shards a changed
    row lands in.  Protocol order is ``write_plane``'s: spec first,
    columns, sentinel LAST; the ``fplane_publish`` fault point is
    armed per column."""
    base_spec = read_json(os.path.join(base_vdir, FPLANE_SPEC))
    base_ok = read_json(os.path.join(base_vdir, FPLANE_OK))
    if base_spec is None or base_ok is None:
        raise ForecastPlaneError(
            "absent", f"{base_vdir}: delta publish needs the base "
            "version's forecast plane (spec + sentinel)"
        )
    n = int(base_spec.get("n_series", -1))
    shard_rows = int(base_spec.get("shard_rows", DEFAULT_SHARD_ROWS))
    buckets = tuple(int(b) for b in base_spec.get("buckets") or ())
    changed = np.unique(np.asarray(changed_rows, np.int64))
    if len(changed) and (changed[0] < 0 or changed[-1] >= n):
        raise ValueError(f"changed rows outside [0, {n})")
    fresh: Dict[int, Dict[str, np.ndarray]] = {}
    if len(changed):
        for hb in buckets:
            fresh[hb] = _predict_rows(snap, backend, changed, hb)
    spec = dict(base_spec, fingerprint=fingerprint,
                numerics_rev=numerics_rev,
                delta_from=base_version, n_changed=int(len(changed)))
    write_spec(os.path.join(vdir, FPLANE_SPEC), spec)
    scattered: Dict[str, np.ndarray] = {}
    for name in base_spec["columns"]:
        src = _col_path(base_vdir, name)
        dst = _col_path(vdir, name)
        faults.inject("fplane_publish")
        if not len(changed):
            link_or_copy(src, dst)
            continue
        hb, key = name.split("_", 1)
        base_mm = attach_column(src)
        out = np.array(base_mm)        # copy-forward: one sequential read
        del base_mm
        out[changed] = np.asarray(fresh[int(hb[1:])][key], out.dtype)
        write_column(dst, out)
        scattered[name] = out
    touched = set(np.unique(changed // shard_rows).tolist())
    shards = []
    for entry in base_ok.get("shards") or ():
        lo, hi, crcs = int(entry[0]), int(entry[1]), dict(entry[2])
        if lo // shard_rows in touched:
            crcs.update(shard_crcs(scattered, lo, hi))
        shards.append([lo, hi, crcs])
    sentinel = dict(base_ok, unix=round(time.time(), 3), shards=shards)
    write_sentinel(os.path.join(vdir, FPLANE_OK), sentinel)
    return spec


@dataclasses.dataclass(frozen=True)
class FPlaneView:
    """One attached (memmap) forecast plane."""

    n_series: int
    buckets: Tuple[int, ...]
    keys: Tuple[str, ...]
    #: bucket -> key -> (n_series, bucket) read-only memmap.
    columns: Dict[int, Dict[str, np.ndarray]]
    fingerprint: Optional[str]
    numerics_rev: Optional[int]

    def covers(self, hb: int, num_samples: int) -> bool:
        """Whether a (horizon-bucket, num_samples) group can be served
        from this plane: deterministic requests only — sampled
        intervals stay on the compute path."""
        return num_samples == 0 and int(hb) in self.columns


def attach(vdir: str, *, verify: bool = True,
           expected_n: Optional[int] = None) -> FPlaneView:
    """Attach the forecast plane in ``vdir`` as memmap views.

    ``verify`` recomputes every shard CRC against the sentinel before
    any column is trusted — a sequential read of the shared pages that
    doubles as page warming for the first post-flip hot reads.  Raises
    ``ForecastPlaneError("absent")`` when no plane was published here,
    ``("corrupt")`` for anything torn, truncated, or mismatched."""
    sentinel = read_json(os.path.join(vdir, FPLANE_OK))
    spec = read_json(os.path.join(vdir, FPLANE_SPEC))
    if sentinel is None and spec is None:
        raise ForecastPlaneError(
            "absent", f"no forecast plane under {vdir}"
        )
    if spec is None or sentinel is None:
        raise ForecastPlaneError(
            "corrupt",
            f"{vdir}: forecast plane is half-published "
            f"(spec={'ok' if spec else 'missing'}, "
            f"sentinel={'ok' if sentinel else 'missing'})",
        )
    if spec.get("format") != FPLANE_FORMAT \
            or sentinel.get("format") != FPLANE_FORMAT:
        raise ForecastPlaneError(
            "corrupt",
            f"{vdir}: plane format {spec.get('format')} != "
            f"{FPLANE_FORMAT}",
        )
    n = int(spec.get("n_series", -1))
    if expected_n is not None and n != int(expected_n):
        raise ForecastPlaneError(
            "corrupt",
            f"{vdir}: plane carries {n} series, snapshot says "
            f"{expected_n}",
        )
    buckets = tuple(int(b) for b in spec.get("buckets") or ())
    keys = tuple(spec.get("keys") or POINT_KEYS)
    flat: Dict[str, np.ndarray] = {}
    for name, meta in (spec.get("columns") or {}).items():
        path = _col_path(vdir, name)
        try:
            mm = attach_column(path)
        except Exception as e:
            # Any unreadable column IS a corrupt plane (a header torn
            # mid-byte surfaces as SyntaxError out of numpy).
            raise ForecastPlaneError("corrupt", f"{path}: {e}")
        if (mm.dtype.str != meta.get("dtype")
                or list(mm.shape) != meta.get("shape")):
            raise ForecastPlaneError(
                "corrupt",
                f"{path}: on-disk {mm.dtype.str}{list(mm.shape)} != "
                f"spec {meta.get('dtype')}{meta.get('shape')}",
            )
        flat[name] = mm
    for hb in buckets:
        for key in keys:
            if _col_name(hb, key) not in flat:
                raise ForecastPlaneError(
                    "corrupt",
                    f"{vdir}: plane is missing column "
                    f"{_col_name(hb, key)!r}",
                )
    if verify:
        bad = verify_crcs(flat, sentinel.get("shards"))
        if bad is not None:
            name, lo, hi = bad
            raise ForecastPlaneError(
                "corrupt",
                f"{_col_path(vdir, name)}: shard [{lo}, {hi}) CRC "
                "mismatch (torn or silently corrupted forecast column)",
            )
    columns: Dict[int, Dict[str, np.ndarray]] = {
        hb: {key: flat[_col_name(hb, key)] for key in keys}
        for hb in buckets
    }
    return FPlaneView(
        n_series=n, buckets=buckets, keys=keys, columns=columns,
        fingerprint=spec.get("fingerprint"),
        numerics_rev=spec.get("numerics_rev"),
    )


def has_plane(vdir: str) -> bool:
    """Cheap presence probe (no CRC sweep)."""
    return os.path.exists(os.path.join(vdir, FPLANE_OK))


def verify_plane(vdir: str) -> bool:
    """Deep integrity check: True when the plane attaches AND every
    shard CRC matches (the chaos harness's torn-plane probe)."""
    try:
        attach(vdir, verify=True)
        return True
    except ForecastPlaneError:
        return False


def plane_nbytes(vdir: str) -> Optional[int]:
    """Total column bytes of the plane in ``vdir``; None when no plane
    is published."""
    spec = read_json(os.path.join(vdir, FPLANE_SPEC))
    if spec is None:
        return None
    total = 0
    for meta in (spec.get("columns") or {}).values():
        n = 1
        for d in meta.get("shape") or ():
            n *= int(d)
        total += n * int(np.dtype(meta["dtype"]).itemsize)
    return total


# ---------------------------------------------------------------------------
# the zero-dispatch read path
# ---------------------------------------------------------------------------


def plane_batch(view: FPlaneView, snap, idx: np.ndarray,
                hb: int) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
    """Serve snapshot rows ``idx`` at bucket ``hb`` straight from the
    plane, batched: one vectorized memmap gather per output key plus
    the recomputed float64 ``ds`` grid.  Returns ``(grid, gathered)``
    with ``grid`` shaped ``(len(idx), hb)`` and each ``gathered[key]``
    the matching ``(len(idx), hb)`` column slice.

    This is the plane read root of the ``serve-plane-read`` effect
    budget (pyproject ``[tool.tsspark.analysis.effects]``): nothing
    reachable from here may dispatch or compile a JAX program, touch
    durable storage, or spawn — page-cache reads and host numpy only,
    so N replicas serve hot reads out of ONE physical copy of the
    table.

    The grid math mirrors ``PredictionEngine._dispatch`` exactly —
    elementwise float64 ops commute with the row gather, so a
    plane-served ``ds`` row equals a dispatch-computed one bit for
    bit."""
    idx = np.asarray(idx, np.int64)
    meta = snap.state.meta
    last = (np.asarray(meta.ds_start, np.float64)[idx]
            + np.asarray(meta.ds_span, np.float64)[idx])
    step = np.asarray(snap.step, np.float64)[idx]
    grid = last[:, None] + step[:, None] * np.arange(1, int(hb) + 1)
    cols = view.columns[int(hb)]
    return grid, {key: np.asarray(mm[idx]) for key, mm in cols.items()}


def plane_rows(view: FPlaneView, snap, idx: np.ndarray,
               hb: int) -> List[Dict[str, np.ndarray]]:
    """Per-series form of :func:`plane_batch`: one row dict per index,
    the engine's cache-scatter unit — used when a group mixes plane
    rows with LRU hits and the batch arrays can't serve it whole."""
    grid, gathered = plane_batch(view, snap, idx, hb)
    out: List[Dict[str, np.ndarray]] = []
    for i in range(len(grid)):
        row = {key: v[i] for key, v in gathered.items()}
        row["ds"] = grid[i]
        out.append(row)
    return out


# ---------------------------------------------------------------------------
# publish orchestration
# ---------------------------------------------------------------------------


def maybe_publish(registry, version: int, backend=None, *,
                  horizons: Sequence[int] = DEFAULT_HOT_HORIZONS,
                  force: bool = False) -> Optional[Dict]:
    """Best-effort forecast-plane publish for ``version``: the flip
    orchestration hook (``refit.publish_plan``, ``ReplicaPool.
    activate``, the serve bench).  Idempotent — a version that already
    has a plane returns immediately.

    Publishing is speculative precompute, so it bows to the PR-16
    disk-pressure ladder: at ``shed_spec`` or worse it refuses
    outright, and a ``DiskFullError``/``BackpressureError`` mid-write
    degrades to None (one structured event, no plane) instead of
    failing the flip — the compute path serves until storage recovers.
    A kill switch (``$TSSPARK_FPLANE=0``) disables publishing for
    deployments that prefer pure compute serving.

    Returns ``{"status", "version", "publish_s", ...}`` or None when
    publishing was shed/refused."""
    if os.environ.get("TSSPARK_FPLANE", "1") == "0":
        return None
    version = int(version)
    vdir = registry.version_dir(version)
    if has_plane(vdir) and not force:
        return {"status": "present", "version": version}
    lad = active_ladder(registry.root)
    if lad is not None and not lad.allows("speculate"):
        obs.event("fplane.shed", version=version,
                  state=lad.state(), reason="disk-pressure")
        return None
    if backend is None:
        from tsspark_tpu.backends.registry import get_backend
        from tsspark_tpu.config import SolverConfig

        backend = get_backend("tpu", registry.config, SolverConfig())
    t0 = time.time()
    try:
        snap = registry.load(version, fallback=False)
        info = None
        try:
            info = registry.delta_info(version)
        except Exception:
            info = None  # torn/racing manifest: publish full
        base_v = None if not info else info.get("base_version")
        if base_v is not None \
                and has_plane(registry.version_dir(int(base_v))):
            spec = write_plane_delta(
                vdir, registry.version_dir(int(base_v)),
                info.get("changed_rows") or (), snap, backend,
                base_version=int(base_v),
            )
            status = "published-delta"
        else:
            spec = write_plane(vdir, snap, backend, horizons=horizons)
            status = "published"
    except (DiskFullError, BackpressureError) as e:
        obs.event("fplane.refused", version=version, error=repr(e))
        return None
    publish_s = round(time.time() - t0, 3)
    out = {"status": status, "version": version,
           "publish_s": publish_s,
           "n_series": int(spec.get("n_series", 0)),
           "buckets": list(spec.get("buckets") or ()),
           "nbytes": plane_nbytes(vdir)}
    obs.event("fplane.published", **out)
    return out
