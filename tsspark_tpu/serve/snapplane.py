"""Memmap snapshot column plane: registry versions as shared pages.

``ParamRegistry.load`` used to materialize every snapshot npz into the
loading process's PRIVATE heap — N pool replicas therefore held N full
copies of the active version, the measured reason 4 replicas aggregate
less throughput than one engine on a one-core box (ROADMAP item 5
stretch).  This module publishes each registry version the way the data
plane (``data/plane.py``) publishes datasets: one ``.npy`` column file
per FitState leaf plus the id->row index, under the same
spec-first / sentinel-last visibility protocol —

* ``snap_spec.json``  — identity record (column dtypes/shapes,
  n_series, config fingerprint, NUMERICS_REV), written FIRST;
* ``snapcol_<name>.npy`` — one plain npy per column: ``theta``, the
  solver diagnostics, every ``meta_*`` ScalingMeta leaf (host float64),
  ``extra_*`` side arrays (per-series cadence), plus the id index
  triple ``ids`` / ``ids_sorted`` / ``id_order`` (see below);
* ``snapok.json``     — the CRC sentinel, written LAST: per-shard CRC32
  of every column's rows.  A reader trusts nothing this sentinel does
  not cover, so a torn or silently corrupted column is REJECTED at
  attach instead of being assembled into forecasts (the exact contract
  ``resilience.integrity`` gives the npz format).

Readers attach with ``np.load(..., mmap_mode="r")``: the engine and
every pool replica then map ONE page-cache copy of the active version
instead of each parsing a private npz heap — per-replica incremental
RSS is O(1) in snapshot size.  The attach-time CRC sweep doubles as
``madvise``-style page warming: it walks every column sequentially, so
an activation prefetch (``PredictionEngine.prefetch`` -> ``registry.
load``) leaves the pages hot for the first post-flip requests, and the
second and later replicas to attach find them already resident.

Row lookup without an O(n_series) Python pass: the publisher writes the
id column alongside ``ids_sorted`` (the ids in lexicographic order) and
``id_order`` (the original row of each sorted position), so
``Snapshot.rows`` resolves a request with one vectorized
``np.searchsorted`` against the sorted memmap — no per-series dict
build at load time, no million-entry Python dict in any replica.

The npz (``utils.checkpoint.save_state``) stays the archival/fallback
format: ``ParamRegistry._load_version`` prefers the plane, degrades to
the same version's npz when the plane is torn, and only then walks the
active->previous fallback chain.  Predictions served from the two
formats are pinned bitwise equal (tests/test_snapshot_plane.py).

``serve/fplane.py`` extends the same protocol one level up the read
path: where this plane shares the model PARAMETERS as pages, the
forecast plane shares the hot-horizon forecast OUTPUTS themselves
(``fcol_*`` columns under ``fplane_spec.json``/``fplaneok.json``), so a
hot point-forecast read needs neither a parameter gather nor a JAX
dispatch.  Its delta copy-forward and CRC-sentinel rejection semantics
mirror ``write_plane_delta``/``verify_plane`` here column for column.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, Optional

import numpy as np

from tsspark_tpu.io import atomic_write, link_or_copy
from tsspark_tpu.models.prophet.design import ScalingMeta
from tsspark_tpu.models.prophet.model import FitState
from tsspark_tpu.plane.protocol import (
    attach_column,
    publish_plane,
    read_json,
    shard_crcs,
    shard_ranges,
    verify_crcs,
    write_column,
    write_sentinel,
    write_spec,
)

__all__ = [
    "SNAP_FORMAT", "SNAP_SPEC", "SNAP_OK", "COL_PREFIX",
    "DELTA_MANIFEST", "DEFAULT_SHARD_ROWS", "SnapshotPlaneError",
    "PlaneView", "shard_ranges", "state_columns", "write_plane",
    "write_plane_delta", "read_delta_manifest", "attach", "has_plane",
    "verify_plane", "snapshot_nbytes",
]

#: Plane format revision (bump on incompatible layout change; the
#: reader refuses unknown revisions instead of misparsing them).
SNAP_FORMAT = 1

SNAP_SPEC = "snap_spec.json"
SNAP_OK = "snapok.json"
COL_PREFIX = "snapcol_"

#: Delta-publish metadata (``write_plane_delta``): base version, the
#: changed-row/id set, and the data-plane coverage stamp — what the
#: serving side reads to carry unchanged series' cache entries forward
#: across a delta flip instead of dropping the whole version's cache.
DELTA_MANIFEST = "delta_manifest.json"

#: CRC shard width (rows).  Shards bound what one torn write can hide
#: behind a stale CRC and give the chaos harness a named unit to tear;
#: 64k rows keeps the sentinel a few entries even at 1M series.
DEFAULT_SHARD_ROWS = 65536


class SnapshotPlaneError(RuntimeError):
    """Structured plane failure.  ``reason`` is ``"absent"`` (no plane
    was ever published in this version dir — fall back to the npz
    silently) or ``"corrupt"`` (a plane exists but fails its sentinel —
    the caller must treat the version as torn)."""

    def __init__(self, reason: str, detail: str):
        super().__init__(f"{reason}: {detail}")
        self.reason = reason


def _col_path(vdir: str, name: str) -> str:
    return os.path.join(vdir, f"{COL_PREFIX}{name}.npy")


def state_columns(state: FitState,
                  extras: Optional[Dict[str, np.ndarray]] = None
                  ) -> Dict[str, np.ndarray]:
    """FitState -> host-numpy column dict, the exact key set
    ``utils.checkpoint.save_state`` puts in the npz (minus the
    integrity stamp) — one leaf naming scheme for both formats, so the
    bitwise-parity contract is checkable key by key."""
    cols = {
        "theta": np.asarray(state.theta),
        "loss": np.asarray(state.loss),
        "grad_norm": np.asarray(state.grad_norm),
        "converged": np.asarray(state.converged),
        "n_iters": np.asarray(state.n_iters),
    }
    if state.status is not None:
        cols["status"] = np.asarray(state.status)
    cols.update(
        {f"meta_{k}": np.asarray(v)
         for k, v in state.meta._asdict().items()}
    )
    cols.update(
        {f"extra_{k}": np.asarray(v)
         for k, v in (extras or {}).items()}
    )
    return cols


def write_plane(vdir: str, state: FitState, ids: np.ndarray,
                extras: Optional[Dict[str, np.ndarray]] = None, *,
                fingerprint: Optional[str] = None,
                numerics_rev: Optional[int] = None,
                shard_rows: int = DEFAULT_SHARD_ROWS) -> None:
    """Land one version's column plane in ``vdir``: spec first, columns
    (each itself atomic), CRC sentinel last.  The version dir is
    publisher-private until the registry manifest references it, so a
    publisher killed mid-plane leaves an orphan dir the version
    allocator skips — never a half-visible snapshot."""
    ids = np.asarray(ids)
    if ids.dtype.kind not in ("U", "S"):
        ids = ids.astype(np.str_)
    cols = state_columns(state, extras)
    n = int(cols["theta"].shape[0])
    if len(ids) != n:
        raise ValueError(f"{len(ids)} ids for {n} state rows")
    # The searchsorted row index, PRECOMPUTED at publish: readers mmap
    # the sorted view directly instead of paying an O(n log n) sort (or
    # an O(n) dict build) on every snapshot load.
    order = np.argsort(ids, kind="stable").astype(np.int64)
    cols["ids"] = ids
    cols["ids_sorted"] = ids[order]
    cols["id_order"] = order
    spec = {
        "format": SNAP_FORMAT,
        "n_series": n,
        "shard_rows": int(shard_rows),
        "fingerprint": fingerprint,
        "numerics_rev": numerics_rev,
        "columns": {k: {"dtype": a.dtype.str, "shape": list(a.shape)}
                    for k, a in cols.items()},
    }
    sentinel = {
        "format": SNAP_FORMAT,
        "n_series": n,
        "shard_rows": int(shard_rows),
        "unix": round(time.time(), 3),
        "shards": [[lo, hi, shard_crcs(cols, lo, hi)]
                   for lo, hi in shard_ranges(n, shard_rows)],
    }
    publish_plane(vdir, SNAP_SPEC, spec, cols, _col_path,
                  SNAP_OK, sentinel)


def write_plane_delta(vdir: str, base_vdir: str, changed_rows,
                      sub_state: Optional[FitState], *,
                      extras_sub: Optional[Dict[str, np.ndarray]] = None,
                      base_version: Optional[int] = None,
                      data_stamp: Optional[int] = None,
                      fingerprint: Optional[str] = None,
                      numerics_rev: Optional[int] = None) -> Dict:
    """Copy-forward delta publish: land a NEW version's plane in
    ``vdir`` from the base version's plane in ``base_vdir`` plus a
    refit over only ``changed_rows`` (``sub_state`` has one row per
    changed series, base row order).

    Per column:

    * a column whose rows cannot have changed (the id index triple, an
      extra the caller did not refit) — or ANY column when the changed
      set is empty (the zero-delta fast path) — is HARDLINKED
      wholesale: zero new snapshot bytes, and the base sentinel's CRCs
      are reused verbatim;
    * a refit column is copy-forwarded: one sequential read of the base
      memmap into a fresh buffer, a vectorized scatter of the changed
      rows, one atomic save.  Unchanged rows are therefore BITWISE the
      base version's — the delta-publish parity contract the refit-kill
      chaos invariant checks — and CRCs are recomputed only for shards
      a changed row actually lands in (untouched shards reuse the base
      CRC: copy-forward preserved their bytes).

    Protocol order is ``write_plane``'s: spec first, columns, sentinel
    LAST, then the delta manifest (pure metadata — the registry
    manifest referencing ``vdir`` is the real visibility gate).  The
    ``delta_publish`` fault point is armed per column so the chaos
    harness can kill a publisher mid-plane.  Returns the delta
    manifest record."""
    from tsspark_tpu.resilience import faults

    base_spec = read_json(os.path.join(base_vdir, SNAP_SPEC))
    base_ok = read_json(os.path.join(base_vdir, SNAP_OK))
    if base_spec is None or base_ok is None:
        raise SnapshotPlaneError(
            "absent", f"{base_vdir}: delta publish needs the base "
            "version's snapshot plane (spec + sentinel)"
        )
    n = int(base_spec.get("n_series", -1))
    shard_rows = int(base_spec.get("shard_rows", DEFAULT_SHARD_ROWS))
    changed = np.unique(np.asarray(changed_rows, np.int64))
    if len(changed) and (changed[0] < 0 or changed[-1] >= n):
        raise ValueError(f"changed rows outside [0, {n})")
    sub_cols: Dict[str, np.ndarray] = {}
    if len(changed):
        if sub_state is None:
            raise ValueError("sub_state required for a non-empty delta")
        sub_cols = state_columns(sub_state, extras_sub)
        for name in ("ids", "ids_sorted", "id_order"):
            sub_cols.pop(name, None)
        unknown = sorted(set(sub_cols) - set(base_spec["columns"]))
        if unknown:
            raise ValueError(
                f"refit columns {unknown} not in the base plane — the "
                "two versions' FitState layouts drifted; publish a full "
                "snapshot instead"
            )
        for name, sub in sub_cols.items():
            if sub.shape[0] != len(changed):
                raise ValueError(
                    f"column {name}: {sub.shape[0]} refit rows for "
                    f"{len(changed)} changed series"
                )
    spec = dict(base_spec, fingerprint=fingerprint,
                numerics_rev=numerics_rev,
                delta_from=base_version, n_changed=int(len(changed)))
    write_spec(os.path.join(vdir, SNAP_SPEC), spec)
    scattered: Dict[str, np.ndarray] = {}
    for name in base_spec["columns"]:
        src = _col_path(base_vdir, name)
        dst = _col_path(vdir, name)
        faults.inject("delta_publish")
        if name not in sub_cols:
            link_or_copy(src, dst)
            continue
        base_mm = attach_column(src)
        out = np.array(base_mm)        # copy-forward: one sequential read
        del base_mm
        out[changed] = np.asarray(sub_cols[name], out.dtype)
        write_column(dst, out)
        scattered[name] = out
    # Sentinel: recompute only (scattered column x touched shard) CRCs.
    touched = set(np.unique(changed // shard_rows).tolist())
    shards = []
    for entry in base_ok.get("shards") or ():
        lo, hi, crcs = int(entry[0]), int(entry[1]), dict(entry[2])
        if lo // shard_rows in touched:
            crcs.update(shard_crcs(scattered, lo, hi))
        shards.append([lo, hi, crcs])
    sentinel = dict(base_ok, unix=round(time.time(), 3), shards=shards)
    write_sentinel(os.path.join(vdir, SNAP_OK), sentinel)
    ids_mm = attach_column(_col_path(base_vdir, "ids"))
    manifest = {
        "base_version": base_version,
        "n_changed": int(len(changed)),
        "changed_rows": [int(r) for r in changed.tolist()],
        "changed_ids": [str(s) for s in ids_mm[changed]],
        "data_stamp": data_stamp,
        "unix": round(time.time(), 3),
    }
    del ids_mm
    atomic_write(os.path.join(vdir, DELTA_MANIFEST),
                 lambda fh: json.dump(manifest, fh), mode="w")
    return manifest


def read_delta_manifest(vdir: str) -> Optional[Dict]:
    """The version's delta-publish metadata, or None for a full
    (non-delta) version."""
    return read_json(os.path.join(vdir, DELTA_MANIFEST))


@dataclasses.dataclass(frozen=True)
class PlaneView:
    """One attached (memmap) snapshot plane."""

    n_series: int
    state: FitState                # leaves are read-only memmaps
    ids: np.ndarray                # (n,) memmap, original row order
    ids_sorted: np.ndarray         # (n,) memmap, lexicographic
    id_order: np.ndarray           # (n,) int64 memmap, sorted pos -> row
    extras: Dict[str, np.ndarray]
    fingerprint: Optional[str]
    numerics_rev: Optional[int]


def attach(vdir: str, *, verify: bool = True,
           expected_n: Optional[int] = None) -> PlaneView:
    """Attach the plane in ``vdir`` as memmap views.

    ``verify`` recomputes every shard CRC against the sentinel before
    any column is trusted — a sequential read of the shared pages that
    doubles as the activation prefetch's page warming (the pages stay
    in cache for every other process mapping this version).  Raises
    ``SnapshotPlaneError("absent")`` when no plane was published here,
    ``("corrupt")`` for anything torn, truncated, or mismatched.
    """
    sentinel = read_json(os.path.join(vdir, SNAP_OK))
    spec = read_json(os.path.join(vdir, SNAP_SPEC))
    if sentinel is None and spec is None:
        raise SnapshotPlaneError(
            "absent", f"no snapshot plane under {vdir}"
        )
    if spec is None or sentinel is None:
        raise SnapshotPlaneError(
            "corrupt",
            f"{vdir}: plane is half-published "
            f"(spec={'ok' if spec else 'missing'}, "
            f"sentinel={'ok' if sentinel else 'missing'})",
        )
    if spec.get("format") != SNAP_FORMAT \
            or sentinel.get("format") != SNAP_FORMAT:
        raise SnapshotPlaneError(
            "corrupt",
            f"{vdir}: plane format {spec.get('format')} != {SNAP_FORMAT}",
        )
    n = int(spec.get("n_series", -1))
    if expected_n is not None and n != int(expected_n):
        raise SnapshotPlaneError(
            "corrupt",
            f"{vdir}: plane carries {n} series, manifest says "
            f"{expected_n}",
        )
    cols: Dict[str, np.ndarray] = {}
    for name, meta in (spec.get("columns") or {}).items():
        path = _col_path(vdir, name)
        try:
            mm = attach_column(path)
        except Exception as e:
            # Not just OSError/ValueError: a header torn mid-byte
            # surfaces as SyntaxError out of numpy's literal_eval — any
            # unreadable column IS a corrupt plane.
            raise SnapshotPlaneError("corrupt", f"{path}: {e}")
        if (mm.dtype.str != meta.get("dtype")
                or list(mm.shape) != meta.get("shape")):
            raise SnapshotPlaneError(
                "corrupt",
                f"{path}: on-disk {mm.dtype.str}{list(mm.shape)} != "
                f"spec {meta.get('dtype')}{meta.get('shape')}",
            )
        cols[name] = mm
    for req in ("theta", "ids", "ids_sorted", "id_order"):
        if req not in cols:
            raise SnapshotPlaneError(
                "corrupt", f"{vdir}: plane is missing column {req!r}"
            )
    if verify:
        bad = verify_crcs(cols, sentinel.get("shards"))
        if bad is not None:
            name, lo, hi = bad
            raise SnapshotPlaneError(
                "corrupt",
                f"{_col_path(vdir, name)}: shard [{lo}, {hi}) "
                "CRC mismatch (torn or silently corrupted "
                "snapshot column)",
            )
    meta_fields = {
        k[len("meta_"):]: np.asarray(cols[k], np.float64)
        for k in cols if k.startswith("meta_")
    }
    state = FitState(
        theta=cols["theta"],
        meta=ScalingMeta(**meta_fields),
        loss=cols["loss"],
        grad_norm=cols["grad_norm"],
        converged=cols["converged"],
        n_iters=cols["n_iters"],
        status=cols.get("status"),
    )
    return PlaneView(
        n_series=n,
        state=state,
        ids=cols["ids"],
        ids_sorted=cols["ids_sorted"],
        id_order=cols["id_order"],
        extras={k[len("extra_"):]: v for k, v in cols.items()
                if k.startswith("extra_")},
        fingerprint=spec.get("fingerprint"),
        numerics_rev=spec.get("numerics_rev"),
    )


def has_plane(vdir: str) -> bool:
    """Cheap presence probe (no CRC sweep)."""
    return os.path.exists(os.path.join(vdir, SNAP_OK))


def verify_plane(vdir: str) -> bool:
    """Deep integrity check: True when the plane attaches AND every
    shard CRC matches (the chaos harness's torn-shard probe)."""
    try:
        attach(vdir, verify=True)
        return True
    except SnapshotPlaneError:
        return False


def snapshot_nbytes(vdir: str) -> Optional[int]:
    """Total column bytes of the plane in ``vdir`` (the denominator of
    the scale ladder's one-physical-copy RSS accounting); None when no
    plane is published."""
    spec = read_json(os.path.join(vdir, SNAP_SPEC))
    if spec is None:
        return None
    total = 0
    for meta in (spec.get("columns") or {}).values():
        n = 1
        for d in meta.get("shape") or ():
            n *= int(d)
        total += n * int(np.dtype(meta["dtype"]).itemsize)
    return total
