"""``python -m tsspark_tpu.serve`` — serve forecasts, or load-test the
serving stack.

Daemon mode (default): attach to a registry and answer stdin JSONL::

    {"series_ids": ["a", "b"], "horizon": 14, "num_samples": 0,
     "deadline_ms": 250, "id": "req-1"}

one response line per request (``ok``/``error`` + (B, H) arrays), plus
``{"cmd": "stats"}`` / ``{"cmd": "activate", "version": N}`` /
``{"cmd": "rollback"}`` control lines.

Loadgen mode (``--loadgen N``): build a synthetic registry (or reuse
``--registry``), replay a deterministic Zipf-ish request mix of N
requests through the engine, and emit a ``SERVE_<unix>.json`` report —
p50/p95/p99 latency, batch occupancy, cache hit rate, per-dispatch
telemetry via ``perf.PerfRecorder`` — the serving analog of
``BENCH_*.json``.

Like the analysis gate, the entry point pins JAX to CPU unless told
otherwise: a serving smoke run must never block on a wedged TPU tunnel.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _build_demo_registry(root: str, n_series: int, seed: int,
                         data_root: str = None):
    """Fit the shared demo dataset and publish it as version 1.

    The batch comes from the columnar data plane (generator
    ``demo_weekly``, docs/DATA.md) — the same cache bench.py and the
    streaming replay source read — so the loadgen has no private
    datagen path and a repeated loadgen is a pure memmap read."""
    import numpy as np
    import jax.numpy as jnp

    from tsspark_tpu.backends.registry import get_backend
    from tsspark_tpu.config import (
        ProphetConfig, SeasonalityConfig, SolverConfig,
    )
    from tsspark_tpu.data import plane
    from tsspark_tpu.serve.registry import ParamRegistry

    config = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=3,
    )
    spec = plane.DatasetSpec(
        generator="demo_weekly", n_series=n_series, n_timesteps=180,
        seed=seed,
    )
    batch = plane.open_batch(plane.ensure(spec, root=data_root))
    backend = get_backend("tpu", config, SolverConfig(max_iters=25))
    state = backend.fit(
        jnp.asarray(np.asarray(batch.ds, np.float64)),
        jnp.asarray(np.asarray(batch.y)),
    )
    registry = ParamRegistry(root, config)
    registry.publish(state, np.asarray(batch.series_ids),
                     step=np.ones(n_series))
    return registry


def _zipf_weights(n: int):
    import numpy as np

    w = 1.0 / (1.0 + np.arange(n))
    return w / w.sum()


def _loadgen(args) -> int:
    import numpy as np

    from tsspark_tpu.models.prophet import predict as predict_mod
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS
    from tsspark_tpu.perf import CompileWatch, PerfRecorder
    from tsspark_tpu.resilience import RetryPolicy
    from tsspark_tpu.serve.cache import ForecastCache
    from tsspark_tpu.serve.engine import (
        EngineOverloaded, ForecastRequest, PredictionEngine,
    )
    from tsspark_tpu.serve.registry import ParamRegistry
    from tsspark_tpu.utils.atomic import atomic_write

    t_start = time.perf_counter()
    # One trace per loadgen run: engine request/dispatch spans land in
    # the scratch's spans.jsonl, and the SERVE report is stamped with
    # the trace id so the run ledger joins the two.
    scratch_root = os.path.join(args.dir or ".", "serve_scratch")
    obs.start_run(os.path.join(scratch_root, "spans.jsonl"))
    METRICS.reset()  # this run's snapshot describes this run only
    if args.registry and os.path.exists(
        os.path.join(args.registry, "manifest.json")
    ):
        registry = ParamRegistry.open(args.registry)
    else:
        root = args.registry or os.path.join(
            args.dir or ".", "serve_scratch", "registry"
        )
        registry = _build_demo_registry(root, args.series, args.seed,
                                        data_root=args.data_root)
    recorder = PerfRecorder(
        watch=CompileWatch((predict_mod.forecast_jit,))
    )
    engine = PredictionEngine(
        registry,
        max_queue=args.max_queue,
        max_batch=args.max_batch,
        cache=ForecastCache(capacity=args.cache_capacity),
        recorder=recorder,
        retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.01,
                                 backoff=2.0, max_delay_s=0.1),
    )
    snap = engine.refresh()
    n_series = len(snap.series_ids)

    rng = np.random.default_rng(args.seed)
    weights = _zipf_weights(n_series)
    horizons = (7, 14, 28)
    n = args.loadgen
    pending = []
    wave = max(1, args.max_batch // 2)
    submitted = 0
    t0 = time.perf_counter()
    while submitted < n:
        k = min(wave, n - submitted)
        for _ in range(k):
            k_sids = rng.integers(1, min(9, n_series + 1))
            sids = rng.choice(n_series, size=k_sids, replace=False,
                              p=weights)
            sampled = rng.random() < 0.1
            req = ForecastRequest.make(
                [snap.series_ids[i] for i in sids],
                horizon=int(rng.choice(horizons)),
                num_samples=20 if sampled else 0,
                seed=args.seed,
                # ~2% arrive already hopeless: exercise the shedding
                # path under load, not just in unit tests.
                deadline_in_s=(0.0 if rng.random() < 0.02 else 30.0),
            )
            try:
                pending.append(engine.submit(req))
            except EngineOverloaded:
                pass  # counted in engine.stats.rejected
            submitted += 1
        while engine.pump() > 0:
            pass
    wall_s = time.perf_counter() - t0

    stats = engine.stats.snapshot()
    METRICS.export(os.path.join(scratch_root, "metrics_loadgen.json"),
                   trace_id=obs.trace_id())
    import jax

    from tsspark_tpu.config import NUMERICS_REV
    from tsspark_tpu.obs.history import git_rev
    from tsspark_tpu.utils import checkpoint as ckpt

    report = {
        "kind": "serve-loadgen",
        "unix": round(time.time(), 3),
        "trace_id": obs.trace_id(),
        # Cross-run identity (obs.history): the sentinel baselines
        # latency/shed/hit-rate only across matching numerics revs and
        # device classes — a TPU loadgen must never gate a CPU one.
        "numerics_rev": NUMERICS_REV,
        "git_rev": git_rev(),
        "device": str(jax.devices()[0]),
        "config_fingerprint": ckpt.config_fingerprint(registry.config),
        "n_requests": n,
        "n_series": n_series,
        "mix": {
            "horizons": list(horizons),
            "sampled_fraction": 0.1,
            "hopeless_deadline_fraction": 0.02,
            "series_per_request": [1, 8],
            "zipf": True,
            "seed": args.seed,
        },
        "wall_s": round(wall_s, 3),
        "setup_s": round(t0 - t_start, 3),
        "requests_per_s": round(n / wall_s, 1) if wall_s > 0 else None,
        "engine": stats,
        "cache": engine.cache.stats(),
        "dispatch": recorder.report().to_dict(),
        "active_version": registry.active_version(),
    }
    out = args.report or f"SERVE_{int(time.time())}.json"
    atomic_write(out, lambda fh: json.dump(report, fh, indent=1),
                 mode="w")
    lat = stats["latency_ms"]
    print(
        f"serve loadgen: {n} requests in {wall_s:.2f}s "
        f"({report['requests_per_s']}/s) | latency p50={lat['p50']} "
        f"p95={lat['p95']} p99={lat['p99']} ms | cache hit rate "
        f"{report['cache']['hit_rate']} | shed {stats['shed']} | "
        f"report -> {out}"
    )
    # Regression sentinel post-step: the report joins RUNHISTORY.jsonl
    # and a p50/p99/shed/hit-rate breach vs the rolling baseline makes
    # the loadgen exit nonzero (docs/OBSERVABILITY.md).
    if os.environ.get("TSSPARK_SENTINEL", "1") != "0":
        try:
            from tsspark_tpu.obs import regress

            verdict = regress.sentinel_report(report, source=out)
            if verdict is not None:
                print(regress.summarize(verdict))
                if not verdict["ok"]:
                    return 1
        except Exception as e:
            print(f"sentinel skipped: {e!r}", file=sys.stderr)
    return 0


def _daemon(args) -> int:
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.serve.engine import PredictionEngine
    from tsspark_tpu.serve.registry import ParamRegistry

    registry = ParamRegistry.open(args.registry)
    # Daemon spans live next to the registry it serves; a request line
    # may carry a ``trace`` envelope ({"trace_id", "parent_span_id"})
    # and its engine spans then join the CALLER's trace.
    obs.start_run(os.path.join(args.registry, "spans.jsonl"))
    engine = PredictionEngine(
        registry, max_queue=args.max_queue, max_batch=args.max_batch,
    )

    def emit(obj):
        sys.stdout.write(json.dumps(obj) + "\n")
        sys.stdout.flush()

    try:
        return _serve_lines(registry, engine, emit,
                            metrics_every=args.metrics_every,
                            metrics_dir=args.registry)
    except BrokenPipeError:
        return 0  # client went away; nothing left to answer


def _serve_lines(registry, engine, emit, lines=None,
                 metrics_every=None, metrics_dir=None) -> int:
    """The daemon's request loop (``lines`` defaults to stdin; tests
    pass a list).  ``metrics_every``: export an atomic
    ``metrics_daemon.json`` snapshot next to the registry at most every
    N seconds (checked per request line — the export rides traffic, so
    an idle daemon leaves its last snapshot in place), which is what
    lets ``python -m tsspark_tpu.obs watch <registry>`` observe a live
    engine without a signal channel."""
    import contextlib

    import numpy as np

    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS
    from tsspark_tpu.serve.engine import ServeError
    from tsspark_tpu.serve.registry import RegistryError

    def export_metrics():
        METRICS.export(
            os.path.join(metrics_dir or ".", "metrics_daemon.json"),
            trace_id=obs.trace_id(),
        )

    last_export = 0.0
    if metrics_every is not None:
        export_metrics()  # a watcher sees a snapshot before traffic
        last_export = time.monotonic()
    for line in (lines if lines is not None else sys.stdin):
        line = line.strip()
        if not line:
            continue
        if (metrics_every is not None
                and time.monotonic() - last_export >= metrics_every):
            export_metrics()
            last_export = time.monotonic()
        try:
            msg = json.loads(line)
        except ValueError as e:
            emit({"ok": False,
                  "error": {"type": "BadRequest", "detail": str(e)}})
            continue
        rid = msg.get("id")
        try:
            cmd = msg.get("cmd")
            if cmd == "stats":
                emit({"ok": True, "id": rid,
                      "stats": engine.stats.snapshot(),
                      "cache": engine.cache.stats(),
                      "active_version": registry.active_version()})
                continue
            if cmd == "metrics":
                # Prometheus text snapshot over the request channel —
                # scrape-style consumers need no side file.
                emit({"ok": True, "id": rid,
                      "prometheus": METRICS.to_prometheus()})
                continue
            if cmd == "activate":
                registry.activate(int(msg["version"]))
                emit({"ok": True, "id": rid,
                      "active_version": registry.active_version()})
                continue
            if cmd == "rollback":
                v = registry.rollback()
                emit({"ok": True, "id": rid, "active_version": v})
                continue
            deadline_ms = msg.get("deadline_ms")
            tr = msg.get("trace") or {}
            ctx = (obs.remote_context(tr.get("trace_id"),
                                      tr.get("parent_span_id"))
                   if tr else contextlib.nullcontext())
            with ctx:
                res = engine.forecast(
                    msg["series_ids"], int(msg["horizon"]),
                    num_samples=int(msg.get("num_samples", 0)),
                    seed=int(msg.get("seed", 0)),
                    deadline_in_s=(None if deadline_ms is None
                                   else float(deadline_ms) / 1e3),
                )
            emit({
                "ok": True, "id": rid, "version": res.version,
                "series_ids": list(res.series_ids),
                "latency_ms": round(res.latency_s * 1e3, 3),
                "ds": np.asarray(res.ds).tolist(),
                **{k: np.asarray(v).tolist()
                   for k, v in res.values.items()},
            })
        except (ServeError, RegistryError) as e:
            err = (e.to_dict() if isinstance(e, ServeError)
                   else {"type": "RegistryError", "reason": e.reason,
                         "detail": str(e)})
            emit({"ok": False, "id": rid, "error": err})
        except (KeyError, TypeError, ValueError) as e:
            emit({"ok": False, "id": rid,
                  "error": {"type": "BadRequest", "detail": str(e)}})
    return 0


def main(argv=None) -> int:
    # Pin the backend at the CONFIG level, not just the env var:
    # ``python -m tsspark_tpu.serve`` imports the package (and thus jax)
    # before this line runs, so JAX_PLATFORMS is already captured — the
    # config update is what actually keeps a smoke/CI run off a
    # (possibly wedged) accelerator tunnel.  Same defense as
    # ``python -m tsspark_tpu.analysis`` and tests/conftest.py.
    if os.environ.get("TSSPARK_SERVE_DEVICE", "cpu") == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax

        jax.config.update("jax_platforms", "cpu")

    ap = argparse.ArgumentParser(
        prog="python -m tsspark_tpu.serve",
        description="forecast serving daemon / load generator "
                    "(docs/SERVING.md)",
    )
    ap.add_argument("--registry", default=None,
                    help="registry root (daemon: required; loadgen: "
                    "reused when it exists, else built synthetic)")
    ap.add_argument("--loadgen", type=int, default=None, metavar="N",
                    help="replay a synthetic mix of N requests and "
                    "emit a SERVE_*.json report")
    ap.add_argument("--dir", default=None,
                    help="loadgen scratch root (default: cwd)")
    ap.add_argument("--report", default=None,
                    help="loadgen report path (default: SERVE_<unix>.json)")
    ap.add_argument("--series", type=int, default=48,
                    help="loadgen synthetic series count")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--data-root", default=None,
                    help="columnar data-plane root the loadgen demo "
                    "dataset is cached under (default: the shared "
                    "plane root, tsspark_tpu.data.plane.default_root)")
    ap.add_argument("--max-queue", type=int, default=4096)
    ap.add_argument("--max-batch", type=int, default=128)
    ap.add_argument("--cache-capacity", type=int, default=8192)
    ap.add_argument("--metrics-every", type=float, default=None,
                    metavar="N",
                    help="daemon: export an atomic metrics_daemon.json "
                    "snapshot next to the registry at most every N "
                    "seconds (enables `python -m tsspark_tpu.obs "
                    "watch <registry>` against a live engine)")
    args = ap.parse_args(argv)

    if args.loadgen is not None:
        return _loadgen(args)
    if not args.registry:
        ap.error("daemon mode needs --registry (or pass --loadgen N)")
    return _daemon(args)


if __name__ == "__main__":
    sys.exit(main())
