"""``python -m tsspark_tpu.serve`` — serve forecasts, or load-test the
serving stack.

Daemon mode (default): attach to a registry and answer stdin JSONL::

    {"series_ids": ["a", "b"], "horizon": 14, "num_samples": 0,
     "deadline_ms": 250, "id": "req-1"}

one response line per request (``ok``/``error`` + (B, H) arrays), plus
``{"cmd": "stats"}`` / ``{"cmd": "activate", "version": N}`` /
``{"cmd": "rollback"}`` control lines.

Loadgen mode (``--loadgen N``): build a synthetic registry (or reuse
``--registry``), replay a deterministic Zipf-ish request mix of N
requests through the engine, and emit a ``SERVE_<unix>.json`` report —
p50/p95/p99 latency, batch occupancy, cache hit rate, per-dispatch
telemetry via ``perf.PerfRecorder`` — the serving analog of
``BENCH_*.json``.  With ``--pool R`` the same mix is replayed against
R replica PROCESSES behind the sharding pool front
(``serve.pool.ReplicaPool``) in pipelined waves with one mid-run
version flip through the ahead-of-time materializer; the report gains
a ``pool`` section (aggregate req/s, failovers, per-replica shed
counts, flip-window p99).

Like the analysis gate, the entry point pins JAX to CPU unless told
otherwise: a serving smoke run must never block on a wedged TPU tunnel.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _build_demo_registry(root: str, n_series: int, seed: int,
                         data_root: str = None):
    """Fit the shared demo dataset and publish it as version 1.

    The batch comes from the columnar data plane (generator
    ``demo_weekly``, docs/DATA.md) — the same cache bench.py and the
    streaming replay source read — so the loadgen has no private
    datagen path and a repeated loadgen is a pure memmap read."""
    import numpy as np
    import jax.numpy as jnp

    from tsspark_tpu.backends.registry import get_backend
    from tsspark_tpu.config import (
        ProphetConfig, SeasonalityConfig, SolverConfig,
    )
    from tsspark_tpu.data import plane
    from tsspark_tpu.serve.registry import ParamRegistry

    config = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=3,
    )
    spec = plane.DatasetSpec(
        generator="demo_weekly", n_series=n_series, n_timesteps=180,
        seed=seed,
    )
    batch = plane.open_batch(plane.ensure(spec, root=data_root))
    backend = get_backend("tpu", config, SolverConfig(max_iters=25))
    state = backend.fit(
        jnp.asarray(np.asarray(batch.ds, np.float64)),
        jnp.asarray(np.asarray(batch.y)),
    )
    registry = ParamRegistry(root, config)
    registry.publish(state, np.asarray(batch.series_ids),
                     step=np.ones(n_series))
    return registry


def _zipf_weights(n: int):
    import numpy as np

    w = 1.0 / (1.0 + np.arange(n))
    return w / w.sum()


def _loadgen_setup(args):
    """Shared loadgen scaffolding: bind the run's trace, reset the
    metrics registry, and open (or demo-build) the registry.  Returns
    ``(scratch_root, registry)``."""
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS
    from tsspark_tpu.serve.registry import ParamRegistry

    # One trace per loadgen run: engine request/dispatch spans land in
    # the scratch's spans.jsonl, and the SERVE report is stamped with
    # the trace id so the run ledger joins the two.
    scratch_root = os.path.join(args.dir or ".", "serve_scratch")
    obs.start_run(os.path.join(scratch_root, "spans.jsonl"))
    METRICS.reset()  # this run's snapshot describes this run only
    if args.registry and os.path.exists(
        os.path.join(args.registry, "manifest.json")
    ):
        registry = ParamRegistry.open(args.registry)
    else:
        root = args.registry or os.path.join(scratch_root, "registry")
        registry = _build_demo_registry(root, args.series, args.seed,
                                        data_root=args.data_root)
    return scratch_root, registry


def _report_identity(registry) -> dict:
    """The cross-run identity block every SERVE report carries
    (obs.history): the sentinel baselines latency/shed/hit-rate only
    across matching numerics revs and device classes — a TPU loadgen
    must never gate a CPU one."""
    import jax

    from tsspark_tpu.config import NUMERICS_REV
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs.history import git_rev
    from tsspark_tpu.utils import checkpoint as ckpt

    return {
        "kind": "serve-loadgen",
        "unix": round(time.time(), 3),
        "trace_id": obs.trace_id(),
        "numerics_rev": NUMERICS_REV,
        "git_rev": git_rev(),
        "device": str(jax.devices()[0]),
        "config_fingerprint": ckpt.config_fingerprint(registry.config),
    }


def _write_report(report, args) -> str:
    """Persist the SERVE report atomically; returns its path."""
    from tsspark_tpu.io import atomic_write

    out = args.report or f"SERVE_{int(time.time())}.json"
    atomic_write(out, lambda fh: json.dump(report, fh, indent=1),
                 mode="w")
    return out


def _sentinel_gate(report, out) -> int:
    """Regression sentinel post-step: the report joins RUNHISTORY.jsonl
    and a breach vs the rolling baseline makes the loadgen exit nonzero
    (docs/OBSERVABILITY.md).  Returns the exit code."""
    if os.environ.get("TSSPARK_SENTINEL", "1") != "0":
        try:
            from tsspark_tpu.obs import regress

            verdict = regress.sentinel_report(report, source=out)
            if verdict is not None:
                print(regress.summarize(verdict))
                if not verdict["ok"]:
                    return 1
        except Exception as e:
            print(f"sentinel skipped: {e!r}", file=sys.stderr)
    return 0


def _loadgen(args) -> int:
    import numpy as np

    from tsspark_tpu.models.prophet import predict as predict_mod
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS
    from tsspark_tpu.perf import CompileWatch, PerfRecorder
    from tsspark_tpu.resilience import RetryPolicy
    from tsspark_tpu.serve.cache import ForecastCache
    from tsspark_tpu.serve.engine import (
        EngineOverloaded, ForecastRequest, PredictionEngine,
    )

    t_start = time.perf_counter()
    scratch_root, registry = _loadgen_setup(args)
    recorder = PerfRecorder(
        watch=CompileWatch((predict_mod.forecast_jit,))
    )
    engine = PredictionEngine(
        registry,
        max_queue=args.max_queue,
        max_batch=args.max_batch,
        cache=ForecastCache(capacity=args.cache_capacity),
        recorder=recorder,
        retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.01,
                                 backoff=2.0, max_delay_s=0.1),
    )
    snap = engine.refresh()
    n_series = len(snap.series_ids)

    rng = np.random.default_rng(args.seed)
    weights = _zipf_weights(n_series)
    horizons = (7, 14, 28)
    n = args.loadgen
    pending = []
    wave = max(1, args.max_batch // 2)
    submitted = 0
    t0 = time.perf_counter()
    while submitted < n:
        k = min(wave, n - submitted)
        for _ in range(k):
            k_sids = rng.integers(1, min(9, n_series + 1))
            sids = rng.choice(n_series, size=k_sids, replace=False,
                              p=weights)
            sampled = rng.random() < 0.1
            req = ForecastRequest.make(
                [snap.series_ids[i] for i in sids],
                horizon=int(rng.choice(horizons)),
                num_samples=20 if sampled else 0,
                seed=args.seed,
                # ~2% arrive already hopeless: exercise the shedding
                # path under load, not just in unit tests.
                deadline_in_s=(0.0 if rng.random() < 0.02 else 30.0),
            )
            try:
                pending.append(engine.submit(req))
            except EngineOverloaded:
                pass  # counted in engine.stats.rejected
            submitted += 1
        while engine.pump() > 0:
            pass
    wall_s = time.perf_counter() - t0

    stats = engine.stats.snapshot()
    METRICS.export(os.path.join(scratch_root, "metrics_loadgen.json"),
                   trace_id=obs.trace_id())
    report = {
        **_report_identity(registry),
        "n_requests": n,
        "n_series": n_series,
        "mix": {
            "horizons": list(horizons),
            "sampled_fraction": 0.1,
            "hopeless_deadline_fraction": 0.02,
            "series_per_request": [1, 8],
            "zipf": True,
            "seed": args.seed,
        },
        "wall_s": round(wall_s, 3),
        "setup_s": round(t0 - t_start, 3),
        "requests_per_s": round(n / wall_s, 1) if wall_s > 0 else None,
        "engine": stats,
        "cache": engine.cache.stats(),
        "dispatch": recorder.report().to_dict(),
        "active_version": registry.active_version(),
    }
    out = _write_report(report, args)
    lat = stats["latency_ms"]
    print(
        f"serve loadgen: {n} requests in {wall_s:.2f}s "
        f"({report['requests_per_s']}/s) | latency p50={lat['p50']} "
        f"p95={lat['p95']} p99={lat['p99']} ms | cache hit rate "
        f"{report['cache']['hit_rate']} | shed {stats['shed']} | "
        f"report -> {out}"
    )
    return _sentinel_gate(report, out)


def _pool_loadgen(args) -> int:
    """Loadgen against a replica pool (``--loadgen N --pool R``): R
    replica processes behind the sharding front, T client threads
    replaying the same deterministic Zipf mix in pipelined waves, one
    mid-run version flip through the ahead-of-time materializer — the
    SERVE report gains a ``pool`` section (aggregate req/s, failovers,
    per-replica shed counts, flip-window p99)."""
    import threading

    import numpy as np

    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS
    from tsspark_tpu.serve.pool import ReplicaPool

    t_start = time.perf_counter()
    scratch_root, registry = _loadgen_setup(args)
    snap = registry.load()
    sids_all = list(snap.series_ids)
    n_series = len(sids_all)
    # The mid-run flip target, published ahead of the replay.
    v_next = registry.publish(
        snap.state._replace(theta=np.asarray(snap.state.theta) * 1.01),
        sids_all, step=np.asarray(snap.step), activate=False,
    )

    weights = _zipf_weights(n_series)
    horizons = (7, 14, 28)
    pool = ReplicaPool(
        os.path.join(scratch_root, "pool"), registry.root,
        n_replicas=args.pool, max_queue=args.max_queue,
        max_batch=args.max_batch, cache_capacity=args.cache_capacity,
    )
    pool.start()
    pool.start_watch(0.3)
    # Ahead-of-time materialization before the clock starts: the pool's
    # steady state (and a production flip) serves pre-computed hot
    # forecasts, so the replay measures serving, not each replica
    # independently cold-filling its deterministic working set.
    active_v = registry.active_version()
    for slot in range(args.pool):
        try:
            pool._request_slot(slot, {
                "cmd": "warm", "version": active_v,
                "series_ids": sids_all[:256], "horizons": list(horizons),
            }, timeout_s=300.0)
        except Exception:
            pass  # cold replicas warm in-run instead

    n = args.loadgen
    n_threads = args.pool_clients or min(8, 2 * args.pool)
    wave = max(1, args.max_batch // 2)
    lock = threading.Lock()
    completed = [0]
    outcomes = {"ok": 0, "shed": 0, "rejected": 0, "failed": 0}
    latencies: list = []   # (t_done_monotonic, latency_s)

    def client(tid: int, share: int) -> None:
        rng = np.random.default_rng(args.seed * 1009 + tid)
        sent = 0
        while sent < share:
            k = min(wave, share - sent)
            reqs = []
            for j in range(k):
                k_sids = int(rng.integers(1, min(9, n_series + 1)))
                pick = rng.choice(n_series, size=k_sids, replace=False,
                                  p=weights)
                sampled = rng.random() < 0.1
                reqs.append({
                    "id": f"t{tid}-{sent + j}",
                    "series_ids": [sids_all[i] for i in pick],
                    "horizon": int(rng.choice(horizons)),
                    "num_samples": 20 if sampled else 0,
                    "seed": args.seed,
                    "deadline_ms": (0.0 if rng.random() < 0.02
                                    else 30_000.0),
                })
            t0 = time.monotonic()
            resp = pool.submit_wave(reqs)
            t1 = time.monotonic()
            with lock:
                for r in resp.values():
                    if r.get("ok"):
                        outcomes["ok"] += 1
                        latencies.append((t1, t1 - t0))
                    else:
                        reason = (r.get("error") or {}).get("reason")
                        if reason == "deadline-exceeded":
                            outcomes["shed"] += 1
                        elif reason == "overloaded":
                            outcomes["rejected"] += 1
                        else:
                            outcomes["failed"] += 1
                completed[0] += len(resp)
            sent += k

    shares = [n // n_threads + (1 if t < n % n_threads else 0)
              for t in range(n_threads)]
    threads = [threading.Thread(target=client, args=(t, shares[t]),
                                daemon=True)
               for t in range(n_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    # Mid-run version flip behind the materializer: warm the hottest
    # series for v_next on every replica, flip, drain one at a time.
    flip: dict = {}
    while completed[0] < n // 2 and any(t.is_alive() for t in threads):
        time.sleep(0.02)
    hot = [sids_all[i] for i in np.argsort(-weights)[:16]]
    t_f0 = time.monotonic()
    pool.activate(v_next, hot_series=hot, horizons=horizons)
    t_f1 = time.monotonic()
    flip = {"version": v_next, "t0": t_f0, "t1": t_f1,
            "wall_s": round(t_f1 - t_f0, 3)}
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0

    stats = pool.stats()
    # Flip-window p99: client-observed latency of requests completing
    # from warm-start to one second past the drain.
    win = [lat for (done, lat) in latencies
           if t_f0 <= done <= t_f1 + 1.0]
    flip["n_in_window"] = len(win)
    flip["p99_ms"] = (round(float(np.percentile(win, 99)) * 1e3, 3)
                      if win else None)
    lat_all = np.asarray([lat for _, lat in latencies], np.float64)
    pct = (lambda q: round(float(np.percentile(lat_all, q)) * 1e3, 3)) \
        if lat_all.size else (lambda q: None)
    METRICS.export(os.path.join(scratch_root, "metrics_loadgen.json"),
                   trace_id=obs.trace_id())
    report = {
        **_report_identity(registry),
        "n_requests": n,
        "n_series": n_series,
        "mix": {
            "horizons": list(horizons),
            "sampled_fraction": 0.1,
            "hopeless_deadline_fraction": 0.02,
            "series_per_request": [1, 8],
            "zipf": True,
            "seed": args.seed,
            "clients": n_threads,
            "wave": wave,
        },
        "wall_s": round(wall_s, 3),
        "setup_s": round(t0 - t_start, 3),
        "requests_per_s": round(n / wall_s, 1) if wall_s > 0 else None,
        "engine": {
            "submitted": n,
            "completed": outcomes["ok"],
            "shed": outcomes["shed"],
            "rejected": outcomes["rejected"],
            "failed": outcomes["failed"],
            "latency_ms": {"p50": pct(50), "p95": pct(95),
                           "p99": pct(99),
                           "mean": (round(float(lat_all.mean()) * 1e3,
                                          3) if lat_all.size else None),
                           "max": (round(float(lat_all.max()) * 1e3, 3)
                                   if lat_all.size else None)},
        },
        "pool": {
            "replicas": args.pool,
            "clients": n_threads,
            "failovers": stats["failovers"],
            "respawns": stats["respawns"],
            "wrong_version": stats["wrong_version"],
            "fenced_seen": stats["fenced_seen"],
            "per_replica": stats["replicas"],
            "flip": flip,
        },
        "active_version": registry.active_version(),
    }
    pool.stop()
    out = _write_report(report, args)
    lat = report["engine"]["latency_ms"]
    shed_pr = {k: (v or {}).get("shed")
               for k, v in stats["replicas"].items()}
    print(
        f"pool loadgen: {n} requests x {args.pool} replicas in "
        f"{wall_s:.2f}s ({report['requests_per_s']}/s aggregate) | "
        f"client p50={lat['p50']} p99={lat['p99']} ms | flip p99="
        f"{flip['p99_ms']} ms over {flip['n_in_window']} | failovers "
        f"{stats['failovers']} | wrong-version {stats['wrong_version']}"
        f" | shed/replica {shed_pr} | report -> {out}"
    )
    return _sentinel_gate(report, out)


def _daemon(args) -> int:
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.serve.engine import PredictionEngine
    from tsspark_tpu.serve.registry import ParamRegistry

    registry = ParamRegistry.open(args.registry)
    # Daemon spans live next to the registry it serves; a request line
    # may carry a ``trace`` envelope ({"trace_id", "parent_span_id"})
    # and its engine spans then join the CALLER's trace.
    obs.start_run(os.path.join(args.registry, "spans.jsonl"))
    engine = PredictionEngine(
        registry, max_queue=args.max_queue, max_batch=args.max_batch,
    )

    def emit(obj):
        sys.stdout.write(json.dumps(obj) + "\n")
        sys.stdout.flush()

    try:
        return _serve_lines(registry, engine, emit,
                            metrics_every=args.metrics_every,
                            metrics_dir=args.registry)
    except BrokenPipeError:
        return 0  # client went away; nothing left to answer


def _serve_lines(registry, engine, emit, lines=None,
                 metrics_every=None, metrics_dir=None) -> int:
    """The daemon's request loop (``lines`` defaults to stdin; tests
    pass a list).  ``metrics_every``: export an atomic
    ``metrics_daemon.json`` snapshot next to the registry at most every
    N seconds (checked per request line — the export rides traffic, so
    an idle daemon leaves its last snapshot in place), which is what
    lets ``python -m tsspark_tpu.obs watch <registry>`` observe a live
    engine without a signal channel."""
    import contextlib

    import numpy as np

    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS
    from tsspark_tpu.serve.engine import ServeError
    from tsspark_tpu.serve.registry import RegistryError

    def export_metrics():
        METRICS.export(
            os.path.join(metrics_dir or ".", "metrics_daemon.json"),
            trace_id=obs.trace_id(),
        )

    last_export = 0.0
    if metrics_every is not None:
        export_metrics()  # a watcher sees a snapshot before traffic
        last_export = time.monotonic()
    for line in (lines if lines is not None else sys.stdin):
        line = line.strip()
        if not line:
            continue
        if (metrics_every is not None
                and time.monotonic() - last_export >= metrics_every):
            export_metrics()
            last_export = time.monotonic()
        try:
            msg = json.loads(line)
        except ValueError as e:
            emit({"ok": False,
                  "error": {"type": "BadRequest", "detail": str(e)}})
            continue
        rid = msg.get("id")
        try:
            cmd = msg.get("cmd")
            if cmd == "stats":
                emit({"ok": True, "id": rid,
                      "stats": engine.stats.snapshot(),
                      "cache": engine.cache.stats(),
                      "active_version": registry.active_version()})
                continue
            if cmd == "metrics":
                # Prometheus text snapshot over the request channel —
                # scrape-style consumers need no side file.
                emit({"ok": True, "id": rid,
                      "prometheus": METRICS.to_prometheus()})
                continue
            if cmd == "activate":
                registry.activate(int(msg["version"]))
                emit({"ok": True, "id": rid,
                      "active_version": registry.active_version()})
                continue
            if cmd == "rollback":
                v = registry.rollback()
                emit({"ok": True, "id": rid, "active_version": v})
                continue
            deadline_ms = msg.get("deadline_ms")
            tr = msg.get("trace") or {}
            ctx = (obs.remote_context(tr.get("trace_id"),
                                      tr.get("parent_span_id"))
                   if tr else contextlib.nullcontext())
            with ctx:
                res = engine.forecast(
                    msg["series_ids"], int(msg["horizon"]),
                    num_samples=int(msg.get("num_samples", 0)),
                    seed=int(msg.get("seed", 0)),
                    deadline_in_s=(None if deadline_ms is None
                                   else float(deadline_ms) / 1e3),
                )
            emit({
                "ok": True, "id": rid, "version": res.version,
                "series_ids": list(res.series_ids),
                "latency_ms": round(res.latency_s * 1e3, 3),
                "ds": np.asarray(res.ds).tolist(),
                **{k: np.asarray(v).tolist()
                   for k, v in res.values.items()},
            })
        except (ServeError, RegistryError) as e:
            err = (e.to_dict() if isinstance(e, ServeError)
                   else {"type": "RegistryError", "reason": e.reason,
                         "detail": str(e)})
            emit({"ok": False, "id": rid, "error": err})
        except (KeyError, TypeError, ValueError) as e:
            emit({"ok": False, "id": rid,
                  "error": {"type": "BadRequest", "detail": str(e)}})
    return 0


def main(argv=None) -> int:
    # Pin the backend at the CONFIG level, not just the env var:
    # ``python -m tsspark_tpu.serve`` imports the package (and thus jax)
    # before this line runs, so JAX_PLATFORMS is already captured — the
    # config update is what actually keeps a smoke/CI run off a
    # (possibly wedged) accelerator tunnel.  Same defense as
    # ``python -m tsspark_tpu.analysis`` and tests/conftest.py.
    if os.environ.get("TSSPARK_SERVE_DEVICE", "cpu") == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax

        jax.config.update("jax_platforms", "cpu")
    # Persistent compile cache, same keying as the chaos CLI: the
    # loadgen re-jits a small ladder of predict shapes, and pool
    # replicas inherit this directory (ReplicaPool passes it through
    # TSSPARK_JAX_CACHE) — without it every replica cold-compiles the
    # whole bucket ladder on its own.
    import jax

    from tsspark_tpu.utils.platform import host_cpu_tag

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("TSSPARK_JAX_CACHE") or os.path.join(
            repo_root, f".jax_cache_{host_cpu_tag()}"
        ),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    ap = argparse.ArgumentParser(
        prog="python -m tsspark_tpu.serve",
        description="forecast serving daemon / load generator "
                    "(docs/SERVING.md)",
    )
    ap.add_argument("--registry", default=None,
                    help="registry root (daemon: required; loadgen: "
                    "reused when it exists, else built synthetic)")
    ap.add_argument("--loadgen", type=int, default=None, metavar="N",
                    help="replay a synthetic mix of N requests and "
                    "emit a SERVE_*.json report")
    ap.add_argument("--pool", type=int, default=None, metavar="R",
                    help="loadgen: drive R replica processes behind "
                    "the sharding pool front instead of one in-process "
                    "engine (docs/SERVING.md, 'Replica pool')")
    ap.add_argument("--pool-clients", type=int, default=None,
                    metavar="T",
                    help="pool loadgen: client threads (default: "
                    "min(8, 2*R))")
    ap.add_argument("--dir", default=None,
                    help="loadgen scratch root (default: cwd)")
    ap.add_argument("--report", default=None,
                    help="loadgen report path (default: SERVE_<unix>.json)")
    ap.add_argument("--series", type=int, default=48,
                    help="loadgen synthetic series count")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--data-root", default=None,
                    help="columnar data-plane root the loadgen demo "
                    "dataset is cached under (default: the shared "
                    "plane root, tsspark_tpu.data.plane.default_root)")
    ap.add_argument("--max-queue", type=int, default=4096)
    ap.add_argument("--max-batch", type=int, default=128)
    ap.add_argument("--cache-capacity", type=int, default=None,
                    help="forecast-cache entries per engine (default: "
                    "$TSSPARK_SERVE_CACHE_CAPACITY, else 8192)")
    ap.add_argument("--metrics-every", type=float, default=None,
                    metavar="N",
                    help="daemon: export an atomic metrics_daemon.json "
                    "snapshot next to the registry at most every N "
                    "seconds (enables `python -m tsspark_tpu.obs "
                    "watch <registry>` against a live engine)")
    args = ap.parse_args(argv)

    if args.loadgen is not None:
        if args.pool:
            return _pool_loadgen(args)
        return _loadgen(args)
    if not args.registry:
        ap.error("daemon mode needs --registry (or pass --loadgen N)")
    return _daemon(args)


if __name__ == "__main__":
    sys.exit(main())
