"""Version-keyed forecast LRU.

Keys carry the registry version, so a stale entry can never satisfy a
request against a newer activation even without explicit invalidation;
the explicit ``invalidate`` (wired to ``ParamRegistry.subscribe``)
exists to free the memory and to make the flip observable in the
hit/miss counters.

Entries are PER SERIES, not per request: a request for (a, b, c) that
follows one for (b, c, d) re-dispatches only ``a`` — series-level reuse
is where a heavy-traffic mix actually overlaps.

The cache is BOUNDED (strict LRU eviction at ``capacity``): at
million-series scale an unbounded version-keyed cache is a slow OOM —
every distinct (series, horizon-bucket) pair a long-lived engine ever
serves would stay pinned until the next version flip.  Evictions are
counted (``stats()["evicted"]`` and the ``tsspark_serve_cache_evicted``
metric) so an undersized cache shows up in the SERVE report and the
SLO watch instead of as silent hit-rate decay.  The default capacity
comes from ``$TSSPARK_SERVE_CACHE_CAPACITY`` so operators size it per
deployment without touching call sites.
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Dict, Hashable, Optional

#: Fallback capacity when neither the constructor nor the environment
#: picks one (entries are (H,)-row dicts — 8192 is a few tens of MB at
#: serving horizons).
FALLBACK_CAPACITY = 8192


def default_capacity() -> int:
    """Configured default: ``$TSSPARK_SERVE_CACHE_CAPACITY`` or the
    module fallback (pool replicas inherit the env, so one knob sizes
    every engine in a deployment)."""
    try:
        return int(os.environ.get("TSSPARK_SERVE_CACHE_CAPACITY", ""))
    except ValueError:
        return FALLBACK_CAPACITY


class ForecastCache:
    """Thread-safe LRU of per-series forecast rows.

    Key: ``(version, series_id, horizon_bucket, num_samples, seed)``.
    Value: dict of ``(H,)`` arrays (plus the ds row) — whatever the
    engine scatters per series.
    """

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = (default_capacity() if capacity is None
                         else int(capacity))
        self._data: "collections.OrderedDict[Hashable, Dict]" = (
            collections.OrderedDict()
        )
        self._lock = threading.Lock()
        # Version gate (set by invalidate): once an activation has
        # declared a current version, put() drops entries keyed to any
        # other version UNDER THIS LOCK — an engine dispatch racing the
        # activation (snapshot read before the flip, insert after the
        # invalidation sweep) can therefore never pin a retired-version
        # entry.  None = no activation seen yet, accept everything.
        self._accept_version: Optional[Hashable] = None
        # One additional version inserts are accepted for even while a
        # different version is active: the pre-activation warm window
        # (``allow_version``) — the pool's ahead-of-time materializer
        # fills the NEXT version's entries before the flip, and the
        # version gate must not drop them as stale.
        self._warm_version: Optional[Hashable] = None
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.evicted = 0
        self.carried = 0
        # Misses the engine answered from the materialized forecast
        # plane instead of a dispatch: those rows are deliberately NOT
        # inserted here (the plane's shared pages are the cache), so
        # without this counter a plane-dominated workload would read as
        # a 0% hit rate when it is actually 100% dispatch-free.
        self.plane_hits = 0

    def __len__(self) -> int:
        return len(self._data)

    def get(self, key: Hashable) -> Optional[Dict]:
        with self._lock:
            val = self._data.get(key)
            if val is None:
                self.misses += 1
                return None
            self._data.move_to_end(key)
            self.hits += 1
            return val

    def peek(self, key: Hashable) -> Optional[Dict]:
        """Presence probe without touching the hit/miss counters or the
        LRU order (the materializer's idempotency check must not skew
        the serving hit rate)."""
        with self._lock:
            return self._data.get(key)

    def allow_version(self, version: Hashable) -> None:
        """Open the warm window for ``version``: inserts keyed to it
        are accepted alongside the active version's until the next
        ``invalidate`` (i.e. until an activation settles the question)."""
        with self._lock:
            self._warm_version = version

    def put(self, key: Hashable, value: Dict) -> None:
        if self.capacity <= 0:
            return
        evictions = 0
        with self._lock:
            if (self._accept_version is not None
                    and isinstance(key, tuple) and key
                    and key[0] != self._accept_version
                    and key[0] != self._warm_version):
                return  # keyed to a retired version: never pin it
            self._data[key] = value
            self._data.move_to_end(key)
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)
                evictions += 1
            self.evicted += evictions
        if evictions:
            # Metric resolved outside the cache lock (and per event, so
            # a METRICS.reset() between loadgen runs never strands a
            # stale handle).
            from tsspark_tpu.obs.metrics import DEFAULT as METRICS

            METRICS.counter("tsspark_serve_cache_evicted").inc(
                evictions
            )

    def carry_forward(self, old_version, new_version,
                      changed_ids) -> int:
        """Delta-flip cache migration: re-key ``old_version``'s entries
        for series NOT in ``changed_ids`` to ``new_version``.  A delta
        publish copy-forwards unchanged series' parameters bitwise, so
        their cached forecasts are exactly what the new version would
        compute — dropping them (the full-flip behavior) would turn a
        1%-churn flip into a 100% cold cache.  Changed series are left
        to miss and recompute.  Must run BEFORE ``invalidate`` settles
        the flip (the engine's refresh hook orders the two); counted in
        ``stats()["carried"]``.  The capacity bound holds through the
        warm window: migrated entries evict LRU exactly like ``put``
        (at worst the base version's coldest entries go first — they
        are about to be invalidated anyway).  Returns the entries
        migrated."""
        if self.capacity <= 0:
            return 0
        moved = evictions = 0
        with self._lock:
            for key in list(self._data):
                if not (isinstance(key, tuple) and key
                        and key[0] == old_version):
                    continue
                if key[1] in changed_ids:
                    continue
                new_key = (new_version,) + key[1:]
                if new_key not in self._data:
                    self._data[new_key] = self._data[key]
                    moved += 1
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)
                evictions += 1
            self.carried += moved
            self.evicted += evictions
        # Metrics resolved outside the cache lock, per event (same
        # discipline as put's eviction counter): carried is how obs
        # watch sees carry-forward health during a delta flip without
        # polling engine internals.
        from tsspark_tpu.obs.metrics import DEFAULT as METRICS

        if moved:
            METRICS.counter("tsspark_serve_cache_carried").inc(moved)
        if evictions:
            METRICS.counter("tsspark_serve_cache_evicted").inc(
                evictions
            )
        return moved

    def invalidate(self, version: Optional[int] = None) -> int:
        """Drop entries for versions OTHER than ``version`` (``None``
        drops everything and clears the version gate).  Returns the
        count dropped.  Called on registry activation: entries for the
        newly active version are the only ones a future request can
        still hit — and the gate makes in-flight dispatches' late
        inserts for the retired version no-ops (see ``put``)."""
        with self._lock:
            self._accept_version = version
            self._warm_version = None  # the flip settles the window
            if version is None:
                dropped = len(self._data)
                self._data.clear()
            else:
                stale = [k for k in self._data if k[0] != version]
                for k in stale:
                    del self._data[k]
                dropped = len(stale)
            self.invalidations += dropped
            return dropped

    def key_versions(self) -> list:
        """Sorted distinct registry versions present in the cache keys —
        the chaos harness's staleness probe: after an activation settles,
        every key should carry the active version (a foreign version
        here is an entry pinned by the activation/insert race)."""
        with self._lock:
            return sorted({k[0] for k in self._data})

    def note_plane_hits(self, n: int) -> None:
        """Record ``n`` misses that the forecast plane absorbed (the
        engine's zero-dispatch read path)."""
        with self._lock:
            self.plane_hits += int(n)

    def stats(self) -> Dict:
        total = self.hits + self.misses
        return {
            "size": len(self._data),
            "capacity": self.capacity,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / total, 4) if total else 0.0,
            "plane_hits": self.plane_hits,
            # Requests served WITHOUT a backend dispatch: LRU hits plus
            # plane-absorbed misses over all lookups.
            "hot_rate": (round((self.hits + self.plane_hits) / total, 4)
                         if total else 0.0),
            "invalidations": self.invalidations,
            "evicted": self.evicted,
            "carried": self.carried,
        }
