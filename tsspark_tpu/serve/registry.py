"""Versioned, atomic parameter registry: the serving side's source of truth.

A fit (orchestrate run, streaming driver, plain backend fit) PUBLISHES a
``FitState`` snapshot; the serving engine READS whichever version is
ACTIVE.  The two sides never share mutable state — activation is one
atomic manifest rename, so a prediction daemon mid-request sees the old
version or the new one, never a mix, and a bad deploy rolls back with
one more rename.

Layout under the registry root::

    manifest.json            # atomic index: active/previous + catalog
    v000001/state.npz|.json  # one utils.checkpoint snapshot per version
    v000002/...

Write protocol (crash-safe by ordering): the snapshot files land first
(each itself atomic via utils.checkpoint -> utils.atomic), the manifest
referencing them is replaced last.  A manifest can therefore never name
files that do not fully exist.

Versions are monotonically increasing integers; the manifest also pins
the config fingerprint (utils.checkpoint.config_fingerprint) and the
serve ``NUMERICS_REV``, so a reader refuses snapshots fitted under an
incompatible parameter layout or numerics regime instead of silently
serving garbage.
"""

from __future__ import annotations

import contextlib
import dataclasses
import fcntl
import json
import os
import time
import warnings
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from tsspark_tpu.config import NUMERICS_REV, ProphetConfig
from tsspark_tpu.models.prophet.model import FitState
from tsspark_tpu.obs import context as obs
from tsspark_tpu.resilience import integrity
from tsspark_tpu.serve import snapplane
from tsspark_tpu.utils import checkpoint as ckpt
from tsspark_tpu.io import atomic_write, sweep_stale_temps

_MANIFEST = "manifest.json"
_FORMAT = 1

#: Snapshot formats a registry publishes/reads.  "both" (default) lands
#: the memmap column plane AND the archival npz per version; "mmap"
#: skips the npz (bulk publishes at million-series scale); "npz" pins
#: the legacy private-heap format (the scale ladder's RSS comparison
#: arm forces it via TSSPARK_SNAPSHOT_FORMAT).
SNAPSHOT_FORMATS = ("both", "mmap", "npz")


class RegistryError(RuntimeError):
    """Structured registry failure: corrupt manifest, incompatible
    snapshot, unknown version.  ``reason`` is a stable machine-readable
    tag; the message carries the human detail."""

    def __init__(self, reason: str, detail: str):
        super().__init__(f"{reason}: {detail}")
        self.reason = reason


class SnapshotAbsent(Exception):
    """Internal control flow: the version dir has no snapshot plane
    (pre-plane publish) — fall through to the npz, no warning."""


def take_fitstate(state: FitState, idx: np.ndarray) -> FitState:
    """Row-gather of a FitState — the read-path analog of the compaction
    gathers (``ops.lbfgs.take_state`` / ``design.take_fit_data``): every
    per-series leaf is taken on axis 0, host float64 meta leaves stay
    host float64 (a jnp gather would silently quantize ``ds_start``).
    """
    idx = np.asarray(idx, np.int64)

    def take(a):
        if isinstance(a, np.ndarray):
            return np.take(a, idx, axis=0)
        return jnp.take(jnp.asarray(a), jnp.asarray(idx), axis=0)

    return jax.tree.map(take, state)


def _normalize_step(step: Optional[np.ndarray], n: int) -> np.ndarray:
    if step is None:
        step = np.ones(n)
    return np.where(np.asarray(step, np.float64) > 0, step, 1.0)


@dataclasses.dataclass(frozen=True)
class Snapshot:
    """One loaded registry version: the batch FitState plus the id->row
    index and per-series cadence the read path needs.

    Two sources, one read API:

    * ``source="npz"`` — the archival checkpoint, fully materialized in
      this process's heap; ``row_of`` is an eager id->row dict.
    * ``source="mmap"`` — a lazy view over the version's snapshot plane
      (``serve.snapplane``): every FitState leaf and the id index are
      read-only memmaps, so ``rows``/``take`` touch only the pages a
      request actually gathers and N processes share ONE page-cache
      copy.  ``row_of`` is None; lookup is a vectorized
      ``np.searchsorted`` against the publish-time sorted index — no
      O(n_series) Python pass anywhere on the load path.

    ``fallback_from``: set when this snapshot was served because the
    ACTIVE version failed its integrity/load check (see
    ``ParamRegistry.load``) — the version that could not be loaded."""

    version: int
    state: FitState
    series_ids: Tuple[str, ...]           # or (n,) unicode memmap
    step: np.ndarray                      # (B,) median cadence, days
    row_of: Optional[Dict[str, int]] = None
    fallback_from: Optional[int] = None
    source: str = "npz"
    ids_sorted: Optional[np.ndarray] = None   # mmap: lexicographic ids
    id_order: Optional[np.ndarray] = None     # mmap: sorted pos -> row

    @classmethod
    def build(cls, version: int, state: FitState, series_ids,
              step: Optional[np.ndarray]) -> "Snapshot":
        # C-level id normalization + C-iterated dict build: this runs on
        # every npz snapshot load, and the former per-series Python
        # passes (`str(s) for s in ids`, an enumerate dict
        # comprehension) were the registry's O(n_series) interpreter
        # cost at million-series scale (ROADMAP item 2; micro-benched in
        # tests/test_resident.py).
        from tsspark_tpu.orchestrate import normalize_series_ids

        ids = tuple(normalize_series_ids(series_ids).tolist())
        n = len(ids)
        return cls(version=version, state=state, series_ids=ids,
                   step=_normalize_step(step, n),
                   row_of=dict(zip(ids, range(n))))

    @classmethod
    def attach(cls, version: int, view: "snapplane.PlaneView"
               ) -> "Snapshot":
        """Lazy mmap snapshot over an attached plane view."""
        return cls(
            version=version, state=view.state, series_ids=view.ids,
            step=_normalize_step(view.extras.get("step"),
                                 view.n_series),
            row_of=None, source="mmap",
            ids_sorted=view.ids_sorted, id_order=view.id_order,
        )

    def rows(self, series_ids) -> Tuple[np.ndarray, List[str]]:
        """Row indices for ``series_ids`` + the ids this version lacks."""
        if self.row_of is not None:
            idx, missing = [], []
            for s in series_ids:
                i = self.row_of.get(str(s))
                (missing.append(str(s)) if i is None
                 else idx.append(i))
            return np.asarray(idx, np.int64), missing
        from tsspark_tpu.orchestrate import normalize_series_ids

        q = normalize_series_ids(series_ids)
        n = len(self.ids_sorted)
        if len(q) == 0 or n == 0:
            return np.empty(0, np.int64), [str(s) for s in q]
        pos = np.minimum(np.searchsorted(self.ids_sorted, q), n - 1)
        found = self.ids_sorted[pos] == q
        idx = np.asarray(self.id_order[pos[found]], np.int64)
        missing = [str(s) for s in q[~found]]
        return idx, missing

    def take(self, idx: np.ndarray) -> Tuple[FitState, np.ndarray]:
        """(gathered FitState, gathered cadence) for row indices — on
        an mmap snapshot the gather reads only the touched pages."""
        return take_fitstate(self.state, idx), np.take(self.step, idx)


class ParamRegistry:
    """Publish / activate / rollback fitted-parameter versions."""

    def __init__(self, root: str, config: ProphetConfig,
                 numerics_rev: int = NUMERICS_REV, strict: bool = True,
                 snapshot_format: Optional[str] = None):
        """``snapshot_format``: "both" (default) / "mmap" / "npz" —
        which snapshot representation ``publish`` lands and ``load``
        prefers (see ``SNAPSHOT_FORMATS``).  Defaults from
        ``$TSSPARK_SNAPSHOT_FORMAT`` so pool replica processes inherit
        the front's choice without wire-protocol plumbing."""
        self.root = root
        self.config = config
        self.numerics_rev = int(numerics_rev)
        self.strict = strict
        fmt = (snapshot_format
               or os.environ.get("TSSPARK_SNAPSHOT_FORMAT") or "both")
        if fmt not in SNAPSHOT_FORMATS:
            raise ValueError(
                f"snapshot_format {fmt!r} not in {SNAPSHOT_FORMATS}"
            )
        self.snapshot_format = fmt
        self._listeners: List[Callable[[Optional[int]], None]] = []
        os.makedirs(root, exist_ok=True)
        # A publisher SIGKILLed mid-snapshot orphans a pid-suffixed
        # atomic-write temp inside its version dir; reap dead writers'
        # orphans here the way the fit workers sweep their scratch.
        sweep_stale_temps(root, recursive=True)
        self._read_manifest()  # validate eagerly: fail at attach time

    @classmethod
    def open(cls, root: str, **kwargs) -> "ParamRegistry":
        """Attach to an existing registry, rebuilding the model config
        from the manifest — a serving daemon needs no side-channel
        config file."""
        path = os.path.join(root, _MANIFEST)
        try:
            with open(path) as fh:
                m = json.load(fh)
        except OSError:
            raise RegistryError("missing-manifest",
                                f"no registry at {root!r}")
        except ValueError as e:
            raise RegistryError("corrupt-manifest", f"{path}: {e}")
        if not isinstance(m, dict) or "config" not in m:
            raise RegistryError(
                "corrupt-manifest", f"{path}: no embedded model config"
            )
        config = ckpt._config_from_dict(m["config"])
        return cls(root, config, **kwargs)

    # -- manifest I/O ----------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.root, _MANIFEST)

    def _fresh_manifest(self) -> Dict:
        return {
            "format": _FORMAT,
            "fingerprint": ckpt.config_fingerprint(self.config),
            "numerics_rev": self.numerics_rev,
            "config": dataclasses.asdict(self.config),
            "active_version": None,
            "previous_version": None,
            "versions": {},
        }

    def _read_manifest(self) -> Dict:
        path = self._manifest_path()
        if not os.path.exists(path):
            return self._fresh_manifest()
        try:
            with open(path) as fh:
                m = json.load(fh)
        except ValueError as e:
            raise RegistryError("corrupt-manifest", f"{path}: {e}")
        if not isinstance(m, dict) or m.get("format") != _FORMAT:
            raise RegistryError(
                "corrupt-manifest",
                f"{path}: format {m.get('format') if isinstance(m, dict) else '?'}"
                f" != {_FORMAT}",
            )
        if self.strict:
            fp = ckpt.config_fingerprint(self.config)
            if m.get("fingerprint") != fp:
                raise RegistryError(
                    "fingerprint-mismatch",
                    f"registry was published under config fingerprint "
                    f"{m.get('fingerprint')}, reader has {fp}; pass "
                    "strict=False to force-attach",
                )
            if m.get("numerics_rev") != self.numerics_rev:
                raise RegistryError(
                    "numerics-rev-mismatch",
                    f"registry numerics_rev {m.get('numerics_rev')} != "
                    f"reader {self.numerics_rev}: parameters fitted under "
                    "a different numerics regime must be republished",
                )
        active = m.get("active_version")
        if active is not None and str(active) not in m.get("versions", {}):
            raise RegistryError(
                "corrupt-manifest",
                f"active_version {active} is not in the version catalog",
            )
        return m

    def _write_manifest(self, m: Dict) -> None:
        atomic_write(
            self._manifest_path(),
            lambda fh: json.dump(m, fh, indent=1),
            mode="w",
        )

    @contextlib.contextmanager
    def _locked(self):
        """Advisory exclusive lock serializing manifest
        read-modify-writes: two concurrent publishers must not allocate
        the same version number or drop each other's catalog entry.
        The lock file itself is never read (flock works on the open
        file description, not the contents); readers stay lock-free —
        the atomic manifest replace already gives them old-or-new."""
        fh = open(os.path.join(self.root, ".manifest.lock"), "a")
        try:
            fcntl.flock(fh, fcntl.LOCK_EX)
            yield
        finally:
            fcntl.flock(fh, fcntl.LOCK_UN)
            fh.close()

    # -- queries ---------------------------------------------------------------
    # Roots of the `registry-read` effect budget: nothing reachable
    # from these may write (publish/activate/rollback are the only
    # writers) — a reader polling versions must never mutate the store.

    def manifest_key(self) -> Optional[Tuple[int, int, int]]:
        """Cheap change detector for the manifest ((ino, mtime_ns,
        size), or None when no manifest exists yet): every manifest
        replace is an ``os.replace`` of a freshly created temp file, so
        the inode changes even when two flips land inside one
        filesystem-timestamp granule at identical size (activate ->
        rollback swapping two same-width integers).  Hot read paths
        stat this instead of re-parsing the JSON per batch."""
        try:
            st = os.stat(self._manifest_path())
        except OSError:
            return None
        return (st.st_ino, st.st_mtime_ns, st.st_size)

    def versions(self) -> Tuple[int, ...]:
        return tuple(sorted(
            int(v) for v in self._read_manifest()["versions"]
        ))

    def version_dir(self, version: int) -> str:
        """Absolute directory of a published version's snapshot files
        (the manifest's ``path`` field resolved against the root) —
        the supported way for out-of-registry readers (the delta-refit
        engine's warm-start gather, the chaos bitwise probes) to reach
        a version's plane without manifest-layout knowledge."""
        entry = self._read_manifest()["versions"].get(str(int(version)))
        if entry is None:
            raise RegistryError(
                "unknown-version",
                f"version {version} was never published",
            )
        return os.path.join(self.root, entry["path"])

    def active_version(self) -> Optional[int]:
        return self._read_manifest()["active_version"]

    # -- writes ----------------------------------------------------------------

    def publish(self, state: FitState, series_ids,
                step: Optional[np.ndarray] = None,
                activate: bool = True,
                snapshot_format: Optional[str] = None,
                data_stamp: Optional[int] = None) -> int:
        """Persist one snapshot as the next version (snapshot files
        first, manifest last); optionally activate it.  Returns the new
        version number.  Concurrent publishers serialize on the
        manifest lock (``_locked``).

        The snapshot lands as the memmap column plane
        (``serve.snapplane``) plus the archival npz, per
        ``snapshot_format`` (default: the registry's) — the plane is
        what the engine and pool replicas map as one shared page-cache
        copy; the npz is the per-version fallback when a plane shard
        tears.

        ``data_stamp``: the data plane's delta coverage stamp
        (``data.plane.delta_seq``) this snapshot was fitted at —
        recorded in the manifest entry so the delta-refit engine can
        later ask ``advanced_since(stamp)`` for exactly the series this
        version is stale for."""
        t_pub0 = time.time()
        fmt = snapshot_format or self.snapshot_format
        if fmt not in SNAPSHOT_FORMATS:
            raise ValueError(
                f"snapshot_format {fmt!r} not in {SNAPSHOT_FORMATS}"
            )
        from tsspark_tpu.orchestrate import normalize_series_ids

        ids = normalize_series_ids(series_ids)
        if len(ids) != int(np.asarray(state.theta).shape[0]):
            raise ValueError(
                f"{len(ids)} series ids for "
                f"{np.asarray(state.theta).shape[0]} state rows"
            )
        extras = {}
        if step is not None:
            extras["step"] = np.asarray(step, np.float64)
        # Lock only the version ALLOCATION and the manifest update, not
        # the (potentially tens-of-MB) snapshot serialization between
        # them — an activate/rollback must never stall behind a bulk
        # publish.  The claimed directory makes allocation crash-safe:
        # a publisher that dies mid-write leaves an orphan dir the
        # existence check skips, never a reused version number.
        with self._locked():
            m = self._read_manifest()
            version = max((int(v) for v in m["versions"]), default=0) + 1
            while os.path.exists(os.path.join(self.root,
                                              f"v{version:06d}")):
                version += 1
            vdir = f"v{version:06d}"
            os.makedirs(os.path.join(self.root, vdir))
        if fmt != "mmap":
            ckpt.save_state(
                os.path.join(self.root, vdir, "state"), state,
                self.config, series_ids=ids, extras=extras,
            )
        if fmt != "npz":
            snapplane.write_plane(
                os.path.join(self.root, vdir), state, ids,
                extras=extras,
                fingerprint=ckpt.config_fingerprint(self.config),
                numerics_rev=self.numerics_rev,
            )
        with self._locked():
            m = self._read_manifest()
            m["versions"][str(version)] = {
                "path": vdir,
                "n_series": int(len(ids)),
                "published_unix": round(time.time(), 3),
                "formats": sorted(
                    ({"both": ("mmap", "npz")}.get(fmt, (fmt,)))
                ),
                **({"data_stamp": int(data_stamp)}
                   if data_stamp is not None else {}),
            }
            if activate:
                m["previous_version"] = m["active_version"]
                m["active_version"] = version
            self._write_manifest(m)
        obs.record("registry.publish", t_pub0, time.time() - t_pub0,
                   version=version, n_series=int(len(ids)),
                   activated=bool(activate))
        if activate:
            self._notify(version)
        return version

    def publish_delta(self, sub_state: Optional[FitState], changed_rows,
                      *, base_version: Optional[int] = None,
                      step_sub: Optional[np.ndarray] = None,
                      data_stamp: Optional[int] = None,
                      activate: bool = True) -> int:
        """Delta publish: the next version as a COPY-FORWARD of
        ``base_version`` (default: the active one) with only
        ``changed_rows`` replaced by ``sub_state``'s refit rows —
        ``serve.snapplane.write_plane_delta``: unchanged rows are
        bitwise the base plane's (untouched columns hardlink wholesale;
        a zero-row delta hardlinks EVERYTHING — zero new snapshot
        bytes).  Delta versions are plane-only (no archival npz: the
        npz would re-serialize the whole fleet, defeating the delta);
        a torn delta plane degrades down the active->previous chain
        like any plane-only version.  Returns the new version."""
        t_pub0 = time.time()
        with self._locked():
            m = self._read_manifest()
            if base_version is None:
                base_version = m["active_version"]
            if base_version is None:
                raise RegistryError(
                    "no-active-version",
                    "delta publish needs a base version",
                )
            base_entry = m["versions"].get(str(int(base_version)))
            if base_entry is None:
                raise RegistryError(
                    "unknown-version",
                    f"delta base {base_version} was never published",
                )
            version = max((int(v) for v in m["versions"]), default=0) + 1
            while os.path.exists(os.path.join(self.root,
                                              f"v{version:06d}")):
                version += 1
            vdir = f"v{version:06d}"
            os.makedirs(os.path.join(self.root, vdir))
        base_vdir = os.path.join(self.root, base_entry["path"])
        if not snapplane.has_plane(base_vdir):
            raise RegistryError(
                "delta-base-missing-plane",
                f"version {base_version} has no snapshot plane; delta "
                "publish copy-forwards plane columns — republish the "
                "base with snapshot_format 'both' or 'mmap' first",
            )
        changed = np.unique(np.asarray(changed_rows, np.int64))
        extras_sub = None
        if step_sub is not None and len(changed):
            extras_sub = {"step": np.asarray(step_sub, np.float64)}
        snapplane.write_plane_delta(
            os.path.join(self.root, vdir), base_vdir, changed,
            sub_state, extras_sub=extras_sub,
            base_version=int(base_version), data_stamp=data_stamp,
            fingerprint=ckpt.config_fingerprint(self.config),
            numerics_rev=self.numerics_rev,
        )
        with self._locked():
            m = self._read_manifest()
            m["versions"][str(version)] = {
                "path": vdir,
                "n_series": int(base_entry["n_series"]),
                "published_unix": round(time.time(), 3),
                "formats": ["mmap"],
                "delta_from": int(base_version),
                "n_changed": int(len(changed)),
                **({"data_stamp": int(data_stamp)}
                   if data_stamp is not None else {}),
            }
            if activate:
                m["previous_version"] = m["active_version"]
                m["active_version"] = version
            self._write_manifest(m)
        obs.record("registry.publish_delta", t_pub0,
                   time.time() - t_pub0, version=version,
                   base_version=int(base_version),
                   n_changed=int(len(changed)),
                   activated=bool(activate))
        if activate:
            self._notify(version)
        return version

    def delta_info(self, version: int) -> Optional[Dict]:
        """Delta-publish metadata of ``version`` (base version + the
        changed-id set), or None for a full publish.  What the engine's
        cache carry-forward reads on a delta flip."""
        m = self._read_manifest()
        entry = m["versions"].get(str(int(version)))
        if entry is None or entry.get("delta_from") is None:
            return None
        manifest = snapplane.read_delta_manifest(
            os.path.join(self.root, entry["path"])
        )
        if manifest is None:
            return None
        return dict(manifest, version=int(version))

    def version_stamp(self, version: int) -> int:
        """The data-plane delta coverage stamp ``version`` was fitted
        at (0 for pre-delta publishes — everything ever advanced is
        then considered new)."""
        entry = self._read_manifest()["versions"].get(str(int(version)))
        if entry is None:
            raise RegistryError(
                "unknown-version",
                f"version {version} was never published",
            )
        return int(entry.get("data_stamp") or 0)

    def activate(self, version: int) -> None:
        """Flip the active pointer to an already-published version."""
        t_act0 = time.time()
        with self._locked():
            m = self._read_manifest()
            if str(int(version)) not in m["versions"]:
                raise RegistryError(
                    "unknown-version",
                    f"version {version} was never published",
                )
            flipped = m["active_version"] != int(version)
            if flipped:
                m["previous_version"] = m["active_version"]
                m["active_version"] = int(version)
                self._write_manifest(m)
        if flipped:
            obs.record("registry.activate", t_act0,
                       time.time() - t_act0, version=int(version))
            self._notify(int(version))

    def rollback(self) -> int:
        """Re-activate the previously active version (one level deep —
        the bad-deploy escape hatch).  Returns the version restored."""
        m = self._read_manifest()
        prev = m["previous_version"]
        if prev is None:
            raise RegistryError("no-rollback-target",
                                "no previously active version recorded")
        self.activate(prev)
        return prev

    # -- reads -----------------------------------------------------------------

    def load(self, version: Optional[int] = None,
             fallback: bool = True) -> Snapshot:
        """Load a version (default: the active one) as a Snapshot.

        The snapshot npz must pass its payload-CRC check
        (resilience.integrity — stamped by utils.checkpoint.save_state)
        before it is parsed: a torn OR silently corrupted file raises
        ``corrupt-snapshot`` instead of being assembled into forecasts.

        When the ACTIVE version (``version=None``) fails that check and
        ``fallback`` is on, the previously active version — then the
        rest of the catalog, newest first — is tried instead: a corrupt
        active snapshot must degrade the read path to the last GOOD
        version (with a loud warning and ``Snapshot.fallback_from``
        set), never take it down.  An explicitly requested version
        always raises."""
        t_load0 = time.time()
        m = self._read_manifest()
        requested = version
        if version is None:
            version = m["active_version"]
            if version is None:
                raise RegistryError("no-active-version",
                                    "nothing has been activated yet")
        try:
            snap = self._load_version(m, int(version))
            obs.record("registry.load", t_load0, time.time() - t_load0,
                       version=int(version))
            return snap
        except RegistryError as e:
            if (requested is not None or not fallback
                    or e.reason != "corrupt-snapshot"):
                raise
            for v in self._fallback_candidates(m, int(version)):
                try:
                    snap = self._load_version(m, v)
                except RegistryError:
                    continue
                warnings.warn(
                    f"active registry version {version} failed its "
                    f"integrity/load check ({e}); serving last good "
                    f"version {v} — republish or rollback to clear",
                    RuntimeWarning,
                )
                obs.record("registry.load", t_load0,
                           time.time() - t_load0, version=v,
                           fallback_from=int(version))
                return dataclasses.replace(snap,
                                           fallback_from=int(version))
            raise

    def _fallback_candidates(self, m: Dict, bad: int) -> List[int]:
        """Versions to try when the active snapshot is corrupt: the
        previously active one first (the rollback target — most likely
        known-good), then the remaining catalog newest-first."""
        out: List[int] = []
        prev = m.get("previous_version")
        if prev is not None and int(prev) != bad:
            out.append(int(prev))
        for v in sorted((int(x) for x in m["versions"]), reverse=True):
            if v != bad and v not in out:
                out.append(v)
        return out

    def _load_version(self, m: Dict, version: int) -> Snapshot:
        entry = m["versions"].get(str(int(version)))
        if entry is None:
            raise RegistryError("unknown-version",
                                f"version {version} was never published")
        vdir = os.path.join(self.root, entry["path"])
        if self.snapshot_format != "npz":
            try:
                return self._load_plane(vdir, int(version), entry)
            except SnapshotAbsent:
                pass  # version predates the plane: npz is the format
            except RegistryError as e:
                # Plane torn: the SAME version's archival npz is the
                # first fallback, BEFORE the active->previous chain —
                # only when it too is missing/corrupt does the caller
                # degrade to an older version.  One verification pass:
                # _load_npz does the CRC check itself; a failure there
                # re-raises the PLANE error (the root cause).
                try:
                    snap = self._load_npz(vdir, int(version), entry)
                except RegistryError:
                    raise e
                warnings.warn(
                    f"registry version {version}: snapshot plane failed "
                    f"its CRC sentinel ({e}); serving the archival npz "
                    "for this version — republish to restore the "
                    "one-copy mmap path",
                    RuntimeWarning,
                )
                return snap
        return self._load_npz(vdir, int(version), entry)

    def _load_plane(self, vdir: str, version: int,
                    entry: Dict) -> Snapshot:
        """Attach the version's memmap column plane as a lazy Snapshot.
        The CRC sweep inside ``snapplane.attach`` is the torn-shard
        gate AND the page warming (one sequential pass; pages stay
        shared for every other mapping process)."""
        try:
            view = snapplane.attach(
                vdir, verify=True, expected_n=int(entry["n_series"])
            )
        except snapplane.SnapshotPlaneError as e:
            if e.reason == "absent":
                raise SnapshotAbsent(str(e))
            raise RegistryError(
                "corrupt-snapshot", f"version {version}: {e}"
            )
        if self.strict and view.fingerprint is not None \
                and view.fingerprint != ckpt.config_fingerprint(
                    self.config):
            raise RegistryError(
                "corrupt-snapshot",
                f"version {version}: plane was published under config "
                f"fingerprint {view.fingerprint}, reader has "
                f"{ckpt.config_fingerprint(self.config)}",
            )
        return Snapshot.attach(version, view)

    def _load_npz(self, vdir: str, version: int,
                  entry: Dict) -> Snapshot:
        base = os.path.join(vdir, "state")
        if not integrity.verify_file(base + ".npz"):
            raise RegistryError(
                "corrupt-snapshot",
                f"version {version} at {base}.npz: payload CRC mismatch "
                "(torn or silently corrupted snapshot)",
            )
        try:
            state, ids, extras = ckpt.load_state(
                base, self.config, strict=self.strict, return_extras=True,
            )
        except (OSError, ValueError, KeyError) as e:
            raise RegistryError(
                "corrupt-snapshot", f"version {version} at {base}: {e}"
            )
        if ids is None or len(ids) != int(entry["n_series"]):
            raise RegistryError(
                "corrupt-snapshot",
                f"version {version}: snapshot carries "
                f"{0 if ids is None else len(ids)} series ids, manifest "
                f"says {entry['n_series']}",
            )
        return Snapshot.build(int(version), state, ids,
                              extras.get("step"))

    # -- invalidation fan-out --------------------------------------------------

    def subscribe(self, fn: Callable[[Optional[int]], None]) -> None:
        """Call ``fn(new_active_version)`` after every in-process
        activation (engines invalidate their caches through this)."""
        self._listeners.append(fn)

    def _notify(self, version: Optional[int]) -> None:
        for fn in self._listeners:
            fn(version)
