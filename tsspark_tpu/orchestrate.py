"""Resilient multi-process fit orchestration (elastic recovery).

The reference got task-level retry, straggler re-dispatch, and crash
recovery for free from Spark's scheduler (SURVEY.md §2.5); this module is
the TPU-native equivalent, as a LIBRARY capability rather than benchmark
plumbing (it previously lived inside ``bench.py`` — round-4 verdict,
Weak #3).  The design splits a large batched fit into processes:

  parent (no JAX)   — spawns fit workers over the remaining series range,
                      watches per-dispatch heartbeats and chunk-file
                      progress, kills wedged workers, probes a wedged
                      accelerator runtime until it heals, retries crashed
                      ranges (halving the chunk only when an attempt made
                      zero progress), and resumes from completed per-chunk
                      result files across invocations.
  fit child (JAX)   — phase 1: every chunk at a short lockstep depth,
                      saved atomically as it lands; phase 2: the
                      unconverged tail across ALL chunks compacted into
                      one batch, finished at full depth with the
                      GN-diagonal metric (device-resident gather when the
                      phase-1 payloads are still on device), chunk files
                      patched in place (idempotent, crash-resumable).
  prep child (CPU)  — pre-packs pending chunk payloads while the
                      accelerator is down, so recovery converts into
                      fitted chunks immediately.

The phase-1/phase-2 NUMERICS are the same traced-dispatch policy
``TpuBackend.fit_twophase`` uses — both read their phase triples from
``backends.tpu.phase1_dynamic_args`` / ``phase2_dynamic_args``, and
``tests/test_orchestrate.py`` pins the end-to-end equality.

Public surface:

  fit_resilient(config, solver_config, ds, y, ...) -> FitState
      Process-isolated, resumable fit.  ``Forecaster`` exposes it as
      ``Forecaster(cfg, backend="tpu", resilient=True)``.

  run_resilient(...)    -- the parent loop, for callers that manage their
                           own scratch/data spill (bench.py).
  fit_worker / prep_worker -- child entry points
                           (``python -m tsspark_tpu.orchestrate --_fit``).
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import pickle
import subprocess
import sys
import time
import warnings
from typing import Callable, List, Optional, Tuple

# Resilience layer: policies/faults/report are stdlib-only at import
# time and integrity defers numpy, so the parent process stays as light
# as before (children import the heavy stack themselves).
# obs.context is stdlib-only too: with no trace bound (TSSPARK_TRACE
# unset, no start_run) every call below is a single None check.
from tsspark_tpu.obs import context as obs
from tsspark_tpu.resilience import faults, integrity
from tsspark_tpu.resilience.integrity import ChunkIntegrityError
from tsspark_tpu.resilience.policy import (
    PROBE as PROBE_POLICY,
    WORKER_RETRY as WORKER_RETRY_POLICY,
    RetryPolicy,
)
from tsspark_tpu.resilience.report import (
    QuarantineRecord,
    ResilienceReport,
    ResilienceWarning,
    STATUS_QUARANTINED,
    attach_report,
)
from tsspark_tpu.io import (
    atomic_write,
    atomic_write_text,
    sweep_stale_temps,
)

MIN_CHUNK = 512


class WorkerCrashLoopError(RuntimeError):
    """The fit worker died with zero progress too many consecutive times
    (a deterministic failure, not a wedge).  Carries the still-missing
    ranges so ``fit_resilient`` can bisect them for poison series."""

    def __init__(self, msg: str, missing: List[Tuple[int, int]], rc: int):
        super().__init__(msg)
        self.missing = missing
        self.rc = rc
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Live worker subprocesses: a caller's signal handler must kill them or an
# orphan fit child keeps holding the accelerator runtime after the parent
# is gone (bench.py's SIGTERM handler consumes this).
_CHILDREN: set = set()


def kill_children() -> None:
    for proc in list(_CHILDREN):
        try:
            proc.kill()
        except OSError:
            pass


def _setup_jax_child():
    """Child-process JAX config: persistent compile cache (keyed by host
    CPU tag so executables compiled for different hosts never mix)."""
    import jax

    from tsspark_tpu.utils.platform import honor_env_platforms, host_cpu_tag

    honor_env_platforms()
    cache = os.environ.get("TSSPARK_JAX_CACHE") or os.path.join(
        _REPO_ROOT, f".jax_cache_{host_cpu_tag()}"
    )
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return jax


# --------------------------------------------------------------------------
# run config + data spill: how child processes learn what to fit
# --------------------------------------------------------------------------

def save_run_config(out_dir: str, model_config, solver_config) -> None:
    """Serialize the model/solver configs for the child workers (frozen
    dataclasses of primitives — pickle round-trips them exactly).  Written
    atomically so a child racing the parent never reads a torn file."""
    os.makedirs(out_dir, exist_ok=True)
    atomic_write(
        os.path.join(out_dir, "runcfg.pkl"),
        lambda fh: pickle.dump(
            {"model": model_config, "solver": solver_config}, fh
        ),
    )


def load_run_config(out_dir: str):
    with open(os.path.join(out_dir, "runcfg.pkl"), "rb") as fh:
        d = pickle.load(fh)
    return d["model"], d["solver"]


_DATA_FIELDS = ("y", "mask", "reg", "cap", "floor")


def spill_data(data_dir: str, ds, y, mask=None, regressors=None, cap=None,
               floor=None) -> None:
    """Write the batch to .npy files the child processes mmap.  float32
    on disk (the fit path's working dtype); ``ds`` keeps its dtype (the
    shared calendar grid must stay float64 until the packer's relative
    subtraction)."""
    import numpy as np

    os.makedirs(data_dir, exist_ok=True)
    atomic_write(os.path.join(data_dir, "ds.npy"),
                 lambda fh: np.save(fh, np.asarray(ds)))
    arrs = dict(y=y, mask=mask, reg=regressors, cap=cap, floor=floor)
    for name in _DATA_FIELDS:
        a = arrs[name]
        if a is not None:
            atomic_write(
                os.path.join(data_dir, f"{name}.npy"),
                lambda fh, a=a: np.save(fh, np.asarray(a, np.float32)),
            )


def _load_data(data_dir: str):
    """(ds, {field: mmap-or-None}) for the child workers."""
    import numpy as np

    ds = np.load(os.path.join(data_dir, "ds.npy"))
    out = {}
    for name in _DATA_FIELDS:
        p = os.path.join(data_dir, f"{name}.npy")
        out[name] = np.load(p, mmap_mode="r") if os.path.exists(p) else None
    return ds, out


# --------------------------------------------------------------------------
# chunk-result and prep-payload files (atomic, resumable)
# --------------------------------------------------------------------------

def _chunk_path(out_dir: str, lo: int, hi: int) -> str:
    return os.path.join(out_dir, f"chunk_{lo:06d}_{hi:06d}.npz")


def _prep_path(out_dir: str, lo: int, hi: int) -> str:
    return os.path.join(out_dir, f"prep_{lo:06d}_{hi:06d}.npz")


def save_chunk_atomic(out_dir, lo, hi, state, extra_arrays=None) -> bool:
    """One chunk's FitState -> chunk_<lo>_<hi>.npz.  Dotfile prefix + an
    atomic rename so a half-written file can never match the resume/eval
    glob; a payload CRC32 (resilience.integrity) so silent corruption is
    caught at load time and quarantined instead of assembled.  Returns
    whether an armed fault plan corrupted the file post-save (the
    observability land-span must not count a deliberately-torn save as
    a healthy recovery signal)."""
    import numpy as np

    arrays = dict(
        theta=np.asarray(state.theta),
        loss=np.asarray(state.loss),
        grad_norm=np.asarray(state.grad_norm),
        converged=np.asarray(state.converged),
        n_iters=np.asarray(state.n_iters),
        status=np.asarray(state.status) if state.status is not None
        else np.zeros(len(np.asarray(state.converged)), np.int32),
        y_scale=np.asarray(state.meta.y_scale),
        floor=np.asarray(state.meta.floor),
        ds_start=np.asarray(state.meta.ds_start),
        ds_span=np.asarray(state.meta.ds_span),
        reg_mean=np.asarray(state.meta.reg_mean),
        reg_std=np.asarray(state.meta.reg_std),
        changepoints=np.asarray(state.meta.changepoints),
    )
    arrays.update(extra_arrays or {})
    path = _chunk_path(out_dir, lo, hi)
    stamped = integrity.stamp(arrays)
    atomic_write(path, lambda fh: np.savez(fh, **stamped))
    return faults.corrupt_file("chunk_save", path, lo=lo, hi=hi)


def _state_from_chunk(z):
    """FitState view of one loaded chunk file."""
    from tsspark_tpu.models.prophet.design import ScalingMeta
    from tsspark_tpu.models.prophet.model import FitState

    return FitState(
        theta=z["theta"], loss=z["loss"], grad_norm=z["grad_norm"],
        converged=z["converged"], n_iters=z["n_iters"], status=z["status"],
        meta=ScalingMeta(
            y_scale=z["y_scale"], floor=z["floor"],
            ds_start=z["ds_start"], ds_span=z["ds_span"],
            reg_mean=z["reg_mean"], reg_std=z["reg_std"],
            changepoints=z["changepoints"],
        ),
    )


def load_fit_state(out_dir: str, n_series: int):
    """Assemble the full-batch FitState from completed chunk files.
    Raises if coverage is incomplete (callers gate on completed_ranges)."""
    import jax
    import numpy as np

    # Integrity gate: a corrupt/torn chunk is quarantined (*.corrupt)
    # and its range re-queued via missing_ranges — NEVER silently
    # concatenated into the full-batch result.
    bad = integrity.sweep_chunks(out_dir)
    if bad:
        raise ChunkIntegrityError(out_dir, bad)
    done = completed_ranges(out_dir)
    if missing_ranges(done, n_series):
        raise RuntimeError(
            f"incomplete chunk coverage in {out_dir}: "
            f"{missing_ranges(done, n_series)}"
        )
    states = [
        _state_from_chunk(dict(np.load(_chunk_path(out_dir, lo, hi))))
        for lo, hi in done
    ]
    cat = lambda *xs: np.concatenate(xs, axis=0)[:n_series]
    return jax.tree.map(cat, *states) if len(states) > 1 else jax.tree.map(
        lambda a: np.asarray(a)[:n_series], states[0]
    )


def publish_fit_state(registry, out_dir: str, series_ids,
                      step=None, activate: bool = True,
                      data_stamp=None) -> int:
    """Assemble a completed run's chunk coverage and publish it as one
    serve-registry version (tsspark_tpu.serve.registry.ParamRegistry).

    ``series_ids`` are the run's ids in batch-row order (chunk files
    carry ranges, not ids — the caller that planned the run owns the
    mapping).  ``step`` is the per-series cadence in days, same order;
    omitting it publishes the DAILY default, and the serving engine
    will then step every future grid by 1.0 — pass the real cadence for
    any sub-daily/weekly workload.  Integrity/coverage gates are
    ``load_fit_state``'s: a torn or incomplete run raises instead of
    publishing a partial version.  Returns the published version.

    ``data_stamp``: the data plane's delta coverage stamp this run was
    fitted at (``data.plane.delta_seq``) — recorded in the registry
    manifest so the delta-refit engine (``tsspark_tpu.refit``) can
    later claim exactly the series that advanced past this version.
    """
    ids = normalize_series_ids(series_ids)
    state = load_fit_state(out_dir, len(ids))
    return registry.publish(state, ids, step=step, activate=activate,
                            data_stamp=data_stamp)


def normalize_series_ids(series_ids):
    """Series ids as a numpy unicode array — C-level conversion, no
    per-series Python pass.  The publish path used to run
    ``[str(s) for s in ids]`` per publish, a 1M-element Python loop on
    the registry's critical path (ROADMAP item 2); every publish-side
    consumer (here, ``serve.registry``) now normalizes through this one
    helper, and ``tests/test_resident.py`` micro-benches it at scale."""
    import numpy as np

    ids = np.asarray(series_ids)
    if ids.ndim == 0:
        # A sized-less iterable (a generator — or a bare string, which
        # the old per-element loop also exploded into characters) lands
        # as a 0-d array; materialize by iteration, exactly like the
        # old ``[str(s) for s in ids]`` — a public-API input type must
        # not silently narrow.
        ids = np.asarray(list(series_ids))
    if ids.dtype.kind not in ("U", "S"):
        ids = ids.astype(np.str_)
    return ids


def save_prep_atomic(out_dir, lo, hi, b_real, packed, meta,
                     u8_cols=()) -> None:
    """Persist one chunk's packed device payload (host numpy) so a CPU
    prep worker can build it while the accelerator is wedged and the fit
    worker can later skip its own prep.

    ``u8_cols``: the regressor indicator-column split the payload was
    packed under — a STATIC argument of the compiled fit program, so it
    rides in the file and ``load_prep`` rejects a mismatch (during
    overlapped ingestion the prep and fit workers may decide the split
    from different landed coverage)."""
    import numpy as np

    arrays = {"b_real": np.asarray(b_real),
              "u8_cols": np.asarray(tuple(u8_cols), np.int32)}
    for k, v in packed._asdict().items():
        arrays[f"packed_{k}"] = np.asarray(v)
    for k, v in meta._asdict().items():
        arrays[f"meta_{k}"] = np.asarray(v)
    path = _prep_path(out_dir, lo, hi)
    stamped = integrity.stamp(arrays)
    atomic_write(path, lambda fh: np.savez(fh, **stamped))
    faults.corrupt_file("prep_save", path, lo=lo, hi=hi)


def load_prep(out_dir, lo, hi, chunk=None, u8_cols=None):
    """(b_real, PackedFitData, ScalingMeta) or None if absent/corrupt.

    ``chunk``: reject payloads whose padded batch width differs — a tail
    range keeps its (lo, hi) name across a chunk-halving retry, and
    serving the old wider payload would re-dispatch exactly the program
    size that just crashed the worker.

    ``u8_cols``: reject payloads packed under a DIFFERENT regressor
    indicator split — the split is a static argument of the compiled
    program, and feeding a payload packed under another one would
    mis-reassemble X_reg (files without the recorded split count as a
    mismatch; prep files are pure cache, so the worker re-preps)."""
    import numpy as np

    from tsspark_tpu.models.prophet.design import PackedFitData, ScalingMeta

    path = _prep_path(out_dir, lo, hi)
    if not os.path.exists(path):
        return None
    try:
        z = np.load(path)
        if not integrity.verify_arrays(z):
            # A corrupt prep cache must not feed the fit; drop it so the
            # worker re-preps locally (prep files are pure cache).
            z.close()
            os.remove(path)
            return None
        if u8_cols is not None:
            if "u8_cols" not in z.files:
                return None
            if tuple(int(j) for j in z["u8_cols"]) != tuple(u8_cols):
                return None
        packed = PackedFitData(**{
            k: z[f"packed_{k}"] for k in PackedFitData._fields
        })
        meta = ScalingMeta(**{
            k: z[f"meta_{k}"] for k in ScalingMeta._fields
        })
        if chunk is not None and packed.y.shape[0] != chunk:
            return None
        return int(z["b_real"]), packed, meta
    except Exception:
        return None


# --------------------------------------------------------------------------
# lease-fenced range claims
# --------------------------------------------------------------------------
#
# plan_chunks keeps claims disjoint WITHIN one worker's view, but a
# watchdog-killed worker's half-finished range used to be reclaimable the
# instant the parent respawned — and a predecessor that was stalled (not
# dead) when the watchdog gave up on it could still flush its result
# later, double-landing the range.  Leases close that window: a worker
# claims ``lease_<lo>_<hi>.json`` (atomic O_EXCL create) before fitting a
# range, may steal only a STALE lease (owner pid dead, or expiry passed
# — the watchdog's kill is SIGKILL, so dead-pid reclaim is immediate),
# and re-checks that it still holds the lease token immediately before
# saving the chunk: a worker whose lease was stolen discards its result
# instead of racing the thief's save (fencing).  A torn lease file (its
# writer died inside the O_EXCL create) reads as stale and is stolen
# atomically via os.replace.

#: A lease outlives any healthy chunk fit (the stall watchdog kills a
#: silent worker long before this), but a crashed owner is reclaimed
#: immediately via the dead-pid check — expiry only backstops the
#: pid-reuse corner.
LEASE_TTL_S = 600.0


def _lease_path(out_dir: str, lo: int, hi: int) -> str:
    return os.path.join(out_dir, f"lease_{lo:06d}_{hi:06d}.json")


def read_lease(out_dir: str, lo: int, hi: int) -> Optional[dict]:
    """The current lease record, or None when absent/torn (both mean
    claimable)."""
    try:
        with open(_lease_path(out_dir, lo, hi)) as fh:
            d = json.load(fh)
        return d if isinstance(d, dict) else None
    except (OSError, ValueError):
        return None


def _lease_stale(lease: dict) -> bool:
    if time.time() >= float(lease.get("expires_unix", 0.0)):
        return True
    pid = int(lease.get("pid", -1))
    if pid > 0 and pid != os.getpid():
        try:
            os.kill(pid, 0)  # liveness probe only (signal 0 sends nothing)
        except OSError:
            return True  # owner process is gone; its lease is dead
    return False


def _live_overlapping_lease(out_dir: str, lo: int, hi: int,
                            token: str) -> bool:
    """True when another worker's LIVE lease overlaps ``[lo, hi)`` on a
    DIFFERENT range name.  Lease files are keyed by exact range, but
    claim grids differ across workers (tuner-sized claims, the parent's
    chunk halving) — without this scan two workers could hold
    non-conflicting lease files over overlapping series and double-land
    them.

    A STALE overlapping lease does not block — and is REMOVED here:
    claiming over it must fence its (dead or expired) owner, whose
    save-time ``holds_lease`` checks its own exact file, not ours."""
    for p in glob.glob(os.path.join(out_dir, "lease_*.json")):
        stem = os.path.basename(p)[len("lease_"):-len(".json")]
        try:
            l2, h2 = (int(x) for x in stem.split("_"))
        except ValueError:
            continue  # foreign file name matched the glob
        if (l2, h2) == (lo, hi) or not (l2 < hi and lo < h2):
            continue
        try:
            with open(p) as fh:
                cur = json.load(fh)
        except ValueError:
            cur = None  # torn record reads as stale
        except OSError:
            continue  # already gone
        if isinstance(cur, dict) and cur.get("token") == token:
            continue  # our own coverage at another width
        if isinstance(cur, dict) and not _lease_stale(cur):
            return True
        try:
            os.remove(p)  # fence the stale owner out of its save
        except OSError:
            pass
    return False


def claim_lease(out_dir: str, lo: int, hi: int, token: str,
                ttl_s: float = LEASE_TTL_S,
                span_id: Optional[str] = None) -> bool:
    """Claim the fit lease on range ``[lo, hi)``.

    Returns True when this ``token`` now holds the lease (fresh claim,
    renewal of its own lease, or steal of a stale one); False when a
    LIVE lease belongs to another worker — on this exact range OR any
    overlapping one (claim grids differ across workers).  The
    fresh-claim path is an atomic ``O_CREAT|O_EXCL``; steals/renewals
    replace the file atomically (utils.atomic), so a concurrent reader
    sees the old record or the new one, never a torn mix.

    ``span_id``: the claimant's observability claim-span id, carried IN
    the lease record — the cross-process trace propagation of the chunk
    protocol.  A thief that steals this lease reads it back and links
    its own claim span to the stolen one (``stolen_from``), so a
    reclaimed range's spans parent correctly across the worker death."""
    if _live_overlapping_lease(out_dir, lo, hi, token):
        return False
    path = _lease_path(out_dir, lo, hi)
    payload = json.dumps({
        "token": token, "pid": os.getpid(),
        "expires_unix": round(time.time() + ttl_s, 3),
        "span": span_id,
    })
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        with os.fdopen(fd, "w") as fh:
            fh.write(payload)
        return True
    except FileExistsError:
        pass
    except OSError:
        return False  # unwritable out dir: claim fails soft
    cur = read_lease(out_dir, lo, hi)
    if cur is not None and cur.get("token") != token \
            and not _lease_stale(cur):
        return False
    # Own lease (renew), stale lease (steal), or torn record: replace
    # whole.  Two racers both seeing "stale" both replace — last rename
    # wins whole, and the loser is fenced out at save time by
    # holds_lease, so the range still lands exactly once.
    atomic_write(path, lambda fh: fh.write(payload), mode="w")
    return True


def holds_lease(out_dir: str, lo: int, hi: int, token: str) -> bool:
    """Fencing check: does ``token`` still own the range?  Run
    immediately before a chunk save — a worker whose lease was stolen
    (it stalled past reclaim) must discard its result, not race the
    thief's save."""
    cur = read_lease(out_dir, lo, hi)
    return cur is not None and cur.get("token") == token


def release_lease(out_dir: str, lo: int, hi: int, token: str) -> None:
    """Drop the lease after its chunk landed (only the holder's token
    may release — a thief's lease is never yanked by the fenced loser)."""
    if holds_lease(out_dir, lo, hi, token):
        try:
            os.remove(_lease_path(out_dir, lo, hi))
        except OSError:
            pass


def completed_ranges(out_dir: str):
    done = []
    for f in glob.glob(os.path.join(out_dir, "chunk_*.npz")):
        base = os.path.basename(f)[len("chunk_"):-len(".npz")]
        lo, hi = base.split("_")
        done.append((int(lo), int(hi)))
    # NUMERIC sort, never filename sort: past 999,999 series the lo field
    # grows to 7 digits and sorts lexicographically BEFORE 6-digit names
    # (chunk_1000448_* < chunk_999936_*), which would let load_fit_state
    # concatenate chunks out of order and silently assign results to the
    # wrong series rows (ADVICE r5).
    return sorted(done)


def missing_ranges(done, total):
    missing, cur = [], 0
    for lo, hi in sorted(done):
        if lo > cur:
            missing.append((cur, lo))
        cur = max(cur, hi)
    if cur < total:
        missing.append((cur, total))
    return missing


def plan_chunks(done, lo, hi, chunk):
    """The fit worker's range claims: the still-MISSING coverage inside
    [lo, hi), each gap walked on its own chunk grid.

    COVERAGE, not exact file names: after a poison-series bisection (or a
    chunk-size change) a region may be covered by differently-named
    sub-range files, and a name-based check would refit it — worse, the
    refit would write a chunk file OVERLAPPING the existing ones, and
    load_fit_state's concatenation would then duplicate rows.

    This is THE claim function of the chunk-file protocol: every range a
    fit worker writes comes out of it, so its invariants (claims pairwise
    disjoint, inside [lo, hi), never overlapping ``done`` coverage) are
    what keeps two workers' files from assembling duplicated series rows.
    ``tsspark_tpu.analysis.fileproto`` model-checks exactly these
    invariants over enumerated small states.
    """
    todo = []
    for m_lo, m_hi in missing_ranges(done, hi):
        m_lo = max(m_lo, lo)
        for c_lo in range(m_lo, min(m_hi, hi), chunk):
            todo.append((c_lo, min(c_lo + chunk, m_hi, hi)))
    return todo


def _pad_chunk_rows(a, lo, hi, chunk, fill=0.0):
    """Rows [lo:hi] of ``a`` zero/fill-padded to the chunk width (inert
    all-masked rows, same convention as TpuBackend._fit_padded).  ONE
    definition shared by the fit and prep workers: the prep cache is
    pinned bit-identical to the inline prep, so the two must never
    drift."""
    import numpy as np

    if a is None:
        return None
    out = np.full((chunk,) + a.shape[1:], fill, np.float32)
    out[:hi - lo] = a[lo:hi]
    return out


def _chunk_mask(y_c, mask, lo, hi, chunk):
    """The chunk's (chunk, T) mask: the user's rows when given, else
    derived from the chunk's own y — with the PAD region forced to zero
    either way.  Without the explicit derivation, prepare's isfinite
    fallback would see the zero-filled pad rows as fully-OBSERVED
    constant-zero series and spend real lockstep solver work on them."""
    import numpy as np

    if mask is not None:
        return _pad_chunk_rows(mask, lo, hi, chunk)
    m = np.zeros(y_c.shape, np.float32)
    m[:hi - lo] = np.isfinite(y_c[:hi - lo])
    return m


# --------------------------------------------------------------------------
# fit worker (accelerator child)
# --------------------------------------------------------------------------

def decide_u8_split(data_dir: str, reg, series: int,
                    heartbeat=None, stall_s: float = 30.0):
    """The regressor indicator-column split, decided ONCE per run on
    LANDED plane coverage only (unlanded memmap rows are preallocation
    zeros and would mark every column an indicator — then blow up the
    moment a real continuous row lands).  Blocks for the first shard of
    an overlapped ingest, self-producing past the stall allowance so a
    dead driver never deadlocks the decision; ``heartbeat`` keeps an
    external watchdog calm while waiting.  ONE definition shared by the
    chunk-file fit worker and the mesh-resident path
    (``tsspark_tpu.resident``) — the split is a static argument of the
    compiled fit program, so the two paths deciding differently would
    break their bitwise-parity contract."""
    from tsspark_tpu.data import plane as data_plane
    from tsspark_tpu.models.prophet.design import _indicator_reg_cols

    if reg is None:
        return ()
    ready = data_plane.ready_coverage(data_dir, series)
    if ready is None:
        return _indicator_reg_cols(reg)
    waited = 0.0
    while not ready:
        if heartbeat is not None:
            heartbeat()
        time.sleep(0.5)
        waited += 0.5
        if waited >= stall_s:
            waited = 0.0
            if not data_plane.produce_next_missing(data_dir):
                # Nothing landed and nothing self-producible (a crashed
                # import, a fingerprint-rotated dir): stop waiting — the
                # claim loop hits the same wall and exits cleanly.
                break
        ready = data_plane.ready_coverage(data_dir, series)
    return (_indicator_reg_cols(reg[ready[0][0]:ready[0][1]])
            if ready else ())


def _metrics_chunk(live: int, fit_s: float) -> None:
    """Per-chunk metrics (docs/OBSERVABILITY.md naming convention);
    called only on the traced path — untraced fits skip even the
    registry lookups."""
    from tsspark_tpu.obs.metrics import DEFAULT

    DEFAULT.counter("tsspark_fit_chunks_total").inc()
    DEFAULT.counter("tsspark_fit_series_total").inc(live)
    DEFAULT.histogram("tsspark_fit_chunk_seconds").observe(fit_s)

def fit_worker(args) -> int:
    """Phase 1: every chunk at a short lockstep depth (phase1 iters), saved
    as it lands.  Phase 2 (once no chunk is missing over the whole range):
    gather the unconverged tail across ALL chunks into one compacted batch,
    finish it at full depth warm-started from phase-1 parameters, and patch
    the chunk files in place (idempotent; resumable after any crash).

    Rationale: the batched solver is lockstep, so pre-compaction every chunk
    paid max_iters for its slowest series while the measured mean iterations
    to converge is ~3 (VERDICT round 2).  TpuBackend.fit_twophase is the
    same logic as an in-memory API; both phases' traced-dispatch triples
    come from backends.tpu.phase{1,2}_dynamic_args so the two
    implementations cannot drift.

    Observability: when the spawner propagated a trace (TSSPARK_TRACE),
    the worker adopts it, writes a crash-safe ``open`` record for its
    own span FIRST (a SIGKILLed worker's chunk spans then still have a
    parent in the ledger), records claim/fit/land spans per chunk into
    the shared ``spans.jsonl``, and exports its metrics snapshot at
    clean exit.  With no trace bound, all of it is a None check.
    """
    obs.adopt_env()
    t_w0 = time.time()
    wspan = obs.open_span("fit.worker", make_current=True,
                          lo=args.lo, hi=args.hi, chunk=args.chunk)
    try:
        rc = _fit_worker_body(args)
    except BaseException:
        obs.close_span(wspan, "fit.worker", t_w0, status="err")
        raise
    obs.close_span(wspan, "fit.worker", t_w0, rc=rc)
    if obs.active():
        from tsspark_tpu.obs.metrics import DEFAULT

        try:
            DEFAULT.export(
                os.path.join(args.out,
                             f"metrics_fit_{os.getpid()}.json"),
                trace_id=obs.trace_id(),
            )
        except OSError:
            pass
    return rc


def _fit_worker_body(args) -> int:
    jax = _setup_jax_child()
    import numpy as np

    from tsspark_tpu.backends.registry import get_backend
    from tsspark_tpu.backends.tpu import (
        difficulty_order,
        patch_state,
        phase1_dynamic_args,
        phase2_dynamic_args,
    )
    from tsspark_tpu.models.prophet.design import (
        ScalingMeta, pack_fit_data,
    )
    from tsspark_tpu.models.prophet.model import (
        FitState, fit_core_packed, fitstate_from_packed,
    )

    faults.inject("fit_worker_start")
    t_worker0 = time.time()
    # Resume never trusts a corrupt chunk: quarantine torn/mismatched
    # files NOW so their ranges land back in this worker's todo list and
    # phase 2 can never np.load garbage.  Predecessors killed mid-write
    # (the watchdog's SIGKILL) left pid-suffixed temp orphans — sweep
    # them so a crash-looping run's scratch usage stays bounded.
    sweep_stale_temps(args.out)
    integrity.sweep_chunks(args.out)
    model_config, solver_config = load_run_config(args.out)
    ds, d = _load_data(args.data)
    y, mask, reg = d["y"], d["mask"], d["reg"]
    cap, floor = d["cap"], d["floor"]
    # Overlapped ingestion (docs/DATA.md): when --data is a plane
    # dataset still being produced, claims — and every other read of
    # the column memmaps — are gated on the shard coverage that has
    # LANDED, so the fit starts on the first shards while the ingest
    # pool writes the rest.  Plain spill dirs and complete datasets
    # gate nothing (ready_coverage returns None).
    from tsspark_tpu.data import plane as data_plane

    ingest_stall_s = float(os.environ.get("TSSPARK_INGEST_STALL_S", "30"))

    # Liveness for the parent's stall watchdog: every completed solver
    # dispatch touches this file, so long legitimate work (a fresh compile,
    # the chunk-less phase-2 straggler fit) is distinguishable from a
    # wedged runtime without any new chunk result appearing.
    hb_path = os.path.join(args.out, "heartbeat")

    def heartbeat():
        # Atomic like every other artifact: the parent's watchdog reads
        # the file's mtime AND workers racing a respawned sibling must
        # never leave a torn timestamp behind.
        atomic_write_text(hb_path, str(time.time()))

    backend = get_backend(
        "tpu", model_config, solver_config,
        chunk_size=args.chunk, iter_segment=args.segment or None,
        on_segment=heartbeat,
    )
    max_iters = solver_config.max_iters
    # phase1 depth >= full depth degenerates to a single-phase run.
    two_phase = 0 < args.phase1_iters < max_iters
    phase1 = backend._phase1(args.phase1_iters) if two_phase else backend

    from concurrent.futures import ThreadPoolExecutor

    # The packed mode drives ONE compiled program for both phases: the
    # static solver carries the full depth, while the per-phase differences
    # (solve depth, GN-metric switch, warm-start-vs-ridge-init) are TRACED
    # scalars (fit_core's *_dynamic args).
    model = backend._model
    n_params = model.config.num_params
    collapse_cap = model.config.growth != "logistic"
    # Per-width ridge-init placeholder cache: the autotuner dispatches
    # several pow-2 widths over one run.
    _zeros_theta: dict = {}

    def theta_zeros(width: int):
        if width not in _zeros_theta:
            _zeros_theta[width] = np.zeros((width, n_params), np.float32)
        return _zeros_theta[width]

    # Online chunk autotuner (tsspark_tpu.perf.autotune): start the
    # ladder SMALL so the first chunk file flushes within seconds
    # (BENCH_r05 flushed nothing in 875 s behind one huge first
    # dispatch), then hill-climb the pow-2 ladder toward the measured
    # series/s optimum.  The learned state persists next to the chunk
    # files so resumes — and the streaming driver's warm start — skip
    # the walk.  Chunk width only regroups series into lockstep
    # programs (row-local math; tests/test_compaction.py), so tuning
    # is throughput-only.
    from tsspark_tpu.perf import ChunkAutotuner, CompileWatch

    compile_watch = CompileWatch.default()
    tuner = None
    if getattr(args, "autotune", False):
        tuner = ChunkAutotuner.load(
            os.path.join(args.out, "autotune.json"),
            cap=args.chunk, floor=min(args.chunk, 128),
        )

    # Segmented mode (--segment < phase-1 depth) keeps the FitData path:
    # per-segment dispatches with a heartbeat after each, for runs where
    # bounding single-dispatch time matters more than transfer bytes.
    segmented = bool(
        phase1.iter_segment
        and phase1.iter_segment < phase1._model.solver_config.max_iters
    )
    # Indicator-column split for the packed path, decided ONCE on the full
    # dataset: per-chunk auto-detection would let a chunk whose continuous
    # column is coincidentally all-0/1 flip the static argument and
    # silently recompile mid-run.  The decision (landed-coverage gating,
    # stall-bounded wait, self-produce) is decide_u8_split — shared with
    # the mesh-resident path.
    u8_cols = decide_u8_split(args.data, reg, args.series,
                              heartbeat=heartbeat, stall_s=ingest_stall_s)

    def prep(lo: int, hi: int, width: int):
        if not segmented:
            # A CPU prep worker may have pre-packed this chunk while the
            # runtime was down (same prepare/pack code path, so numerics
            # are identical); corrupt/absent files fall through to local
            # prep.  Width-mismatched payloads (the prep worker packs at
            # the requested cap, the tuner may dispatch smaller) are
            # rejected by load_prep and re-prepped locally, as are
            # payloads packed under a different u8 indicator split.
            cached = load_prep(args.out, lo, hi, chunk=width,
                               u8_cols=u8_cols)
            if cached is not None:
                return lo, hi, width, cached[0], cached[1], cached[2]
        b_real = hi - lo
        rows = lambda a, fill=0.0: _pad_chunk_rows(a, lo, hi, width, fill)
        # as_numpy: a prep thread must not issue device transfers — they
        # would queue behind the in-flight fit program and re-serialize
        # the pipeline the prefetch exists to overlap.
        y_c = rows(y)
        data, meta = model.prepare(
            ds, y_c, mask=_chunk_mask(y_c, mask, lo, hi, width),
            regressors=rows(reg), cap=rows(cap, fill=1.0),
            floor=rows(floor), as_numpy=True,
        )
        if segmented:
            return lo, hi, width, b_real, data, meta
        packed, _ = pack_fit_data(data, meta, ds, reg_u8_cols=u8_cols,
                                  collapse_cap=collapse_cap)
        return lo, hi, width, b_real, packed, meta

    # Range claims come from plan_chunks (coverage-based, never file
    # names) — see its docstring for the overlap invariants it carries.
    # NOTE: tsspark_tpu.resident's claim loop mirrors next_claim below
    # (same plan/lease/ready-coverage/self-produce invariants); a change
    # to the claim logic here must land there too.
    # With the tuner each claim is sized at submit time, so the claim
    # grid follows the learned chunk size mid-run; locally-claimed
    # ranges count as covered because the writer thread may not have
    # flushed their files yet.  Every claim is additionally LEASED
    # (claim_lease): a range a live sibling holds is skipped, a dead
    # predecessor's range is stolen, and the save path re-checks the
    # lease token so a stalled worker whose range was reclaimed can
    # never double-land it.
    claimed: List[Tuple[int, int]] = []
    lease_token = f"{os.getpid()}.{int(t_worker0 * 1e3)}"
    # Per-range observability claim spans: the span id travels IN the
    # lease file, so a thief that steals a dead predecessor's range can
    # link its claim to the stolen one (cross-process span parentage
    # through the chunk protocol itself).
    claim_spans: dict = {}

    def next_claim(block: bool = True):
        waited = 0.0
        while True:
            width = tuner.next_size() if tuner is not None else args.chunk
            ready = data_plane.ready_coverage(args.data, args.series)
            todo2 = plan_chunks(
                completed_ranges(args.out) + claimed, args.lo, args.hi,
                width,
            )
            if ready is not None:
                todo2 = [(l2, h2) for l2, h2 in todo2
                         if data_plane.covers(ready, l2, h2)]
            for lo2, hi2 in todo2:
                prior = read_lease(args.out, lo2, hi2) if obs.active() \
                    else None
                claim_sid = obs.new_id() if obs.active() else None
                if not claim_lease(args.out, lo2, hi2, lease_token,
                                   span_id=claim_sid):
                    continue  # a LIVE sibling owns this range; leave it
                claimed.append((lo2, hi2))
                if claim_sid is not None:
                    claim_spans[(lo2, hi2)] = claim_sid
                    stolen = (prior.get("span")
                              if prior and prior.get("token") != lease_token
                              else None)
                    extra = {"stolen_from": stolen} if stolen else {}
                    obs.record("chunk.claim", time.time(), 0.0,
                               span_id=claim_sid, lo=lo2, hi=hi2,
                               width=width, **extra)
                return lo2, hi2, width
            if ready is None or not data_plane.ingest_pending(
                args.data, args.series
            ):
                return None  # coverage exhausted for real
            if not block:
                return None  # caller has in-flight work; don't stall it
            # Data still being produced: wait for the next shard to
            # land (heartbeats keep the parent's stall watchdog calm),
            # and past the stall allowance SELF-PRODUCE the first
            # missing shard — generation is deterministic, so a dead
            # ingest driver never deadlocks the fit.
            heartbeat()
            time.sleep(0.5)
            waited += 0.5
            if waited >= ingest_stall_s:
                waited = 0.0
                if not data_plane.produce_next_missing(args.data):
                    return None

    prefetch_depth = 3
    # Adaptive phase-1 depth: depth is a TRACED value of the one compiled
    # program, so it can change per chunk for free.  One adjustment after
    # chunk 0 keeps runs predictable.  The deepen branch fires only on a
    # PATHOLOGICAL first chunk (a quarter still progressing): measured on
    # the M5 shape, the unconverged set is depth-FLAT — it is the
    # ill-conditioned tail that needs phase 2's GN metric, not more plain
    # lockstep iterations.  If virtually everything converges early,
    # shallow out.
    depth = {"v": args.phase1_iters if two_phase else max_iters,
             "tuned": not two_phase or bool(args.no_phase1_tune)}

    def tune_depth(state, b_real):
        if depth["tuned"]:
            return
        depth["tuned"] = True
        frac_unconv = float(
            (~np.asarray(state.converged)[:b_real]).mean()
        )
        # THE depth policy (backends.tpu.tune_phase1_depth), shared with
        # the mesh-resident path so the two cannot drift.
        from tsspark_tpu.backends.tpu import tune_phase1_depth

        depth["v"] = tune_phase1_depth(depth["v"], frac_unconv, max_iters)

    def save_and_log(lo, hi, state, fit_s, t_wait, t_put, t_dev, t1,
                     width, compiled):
        """Chunk save + prep-file cleanup + one times.jsonl row (shared by
        the packed writer path and the segmented inline path).  The row
        doubles as the per-chunk perf telemetry (docs/PERF.md): padded
        width, live series, series/s, compile-miss, and the wall offset
        of the flush — what bench.py folds into BENCH extras via
        ``perf.summarize_times``."""
        if not holds_lease(args.out, lo, hi, lease_token):
            # Fenced: this worker stalled long enough for its lease to
            # be reclaimed — the range belongs to the thief now, and
            # saving here would double-land it (or clobber the thief's
            # freshly saved result with a stale one).
            print(
                f"[orchestrate] lease on [{lo}, {hi}) lost; discarding "
                f"this worker's result (fenced)", file=sys.stderr,
            )
            obs.event("fenced", lo=lo, hi=hi)
            return
        t_save0 = time.time()
        corrupted = save_chunk_atomic(args.out, lo, hi, state)
        release_lease(args.out, lo, hi, lease_token)
        if obs.active():
            # claim -> fit -> land chain, timed off the clocks this
            # function already owns (the PerfRecorder-shaped telemetry
            # in times.jsonl and these spans are one measurement).
            fit_sid = obs.record(
                "chunk.fit", t_save0 - fit_s, fit_s,
                parent_id=claim_spans.get((lo, hi)),
                lo=lo, hi=hi, width=width, live=hi - lo,
                compile_miss=bool(compiled),
            )
            obs.record("chunk.land", t_save0, time.time() - t_save0,
                       parent_id=fit_sid, lo=lo, hi=hi,
                       **({"corrupted": True} if corrupted else {}))
            _metrics_chunk(hi - lo, fit_s)
        try:  # prep payload served its purpose; bound scratch disk
            os.remove(_prep_path(args.out, lo, hi))
        except OSError:
            pass
        with open(os.path.join(args.out, "times.jsonl"), "a") as fh:
            fh.write(json.dumps({
                "lo": lo, "hi": hi, "fit_s": round(fit_s, 3),
                "wait_s": round(t_wait, 3), "put_s": round(t_put, 3),
                "dev_s": round(t_dev, 3),
                "read_s": round(time.time() - t1, 3),
                "chunk": args.chunk, "width": width, "live": hi - lo,
                "series_per_s": round((hi - lo) / fit_s, 2) if fit_s else 0,
                "compile_miss": bool(compiled),
                "t": round(time.time() - t_worker0, 2),
                "device": str(jax.devices()[0]),
            }) + "\n")

    # Post-fit host work (device->host readback of the small result
    # buffers, FitState assembly, chunk-file save) rides a single writer
    # thread so the main thread's next device_put starts immediately after
    # the fit dispatch completes.  ``fit_s`` is captured on the MAIN
    # thread at hand-off so it measures the chunk's actual wall
    # (wait+put+dev); read_s alone reflects writer-side readback, which
    # may overlap the next chunk's upload.
    def finish_chunk(lo, hi, b_real, theta, stats, meta, fit_s, t_wait,
                     t_put, t_dev, width, compiled):
        t1 = time.time()
        state = fitstate_from_packed(
            np.asarray(theta)[:b_real],
            np.asarray(stats)[:, :b_real],
            jax.tree.map(lambda a: np.asarray(a)[:b_real], meta),
        )
        save_and_log(lo, hi, state, fit_s, t_wait, t_put, t_dev, t1,
                     width, compiled)
        return state

    # Device-resident chunk payloads: phase 1 keeps every uploaded packed
    # payload alive on device so phase 2 can gather its straggler rows ON
    # DEVICE instead of re-prepping and re-uploading them.  Falls back to
    # the host path whenever coverage is partial (resume, chunk-halving
    # retries).  Retained bytes are CAPPED (ADVICE r4): HBM cost is
    # linear in series count; past the budget we stop inserting and the
    # partial-coverage check routes phase 2 to the host path.
    resident = {}
    resident_bytes = 0
    resident_budget = int(
        os.environ.get("TSSPARK_RESIDENT_MB",
                       os.environ.get("BENCH_RESIDENT_MB", "4096"))
    ) * (1 << 20)
    # Test/chaos hook: crash the worker after N chunk saves to prove the
    # parent's retry + resume path (tests/test_orchestrate.py).
    crash_after = int(os.environ.get("TSSPARK_TEST_CRASH_AFTER", "0"))
    from collections import deque

    with ThreadPoolExecutor(max_workers=2) as pool, \
            ThreadPoolExecutor(max_workers=1) as writer:
        write_futs = []
        pending: deque = deque()

        def submit_next(block: bool = False) -> bool:
            c = next_claim(block=block)
            if c is None:
                return False
            lo2, hi2, w2 = c
            pending.append(pool.submit(prep, lo2, hi2, w2))
            return True

        # First claim may BLOCK on the opening shard of an overlapped
        # ingest (a fresh plane dataset has zero coverage for the first
        # seconds); once work is in flight, refills never stall it —
        # the pipeline drains and the outer loop blocks instead.
        for i in range(prefetch_depth):
            if not submit_next(block=(i == 0)):
                break
        n_fitted = 0
        while pending:
            t0 = time.time()
            lo, hi, width, b_real, payload, meta = pending.popleft().result()
            faults.inject("fit_chunk", lo=lo, hi=hi)
            t_wait = time.time() - t0
            submit_next()
            t1 = time.time()
            # One device_put call for the whole pytree (not per-leaf
            # tree.map): the runtime can batch the per-buffer dispatches.
            payload = jax.device_put(payload)
            jax.block_until_ready(jax.tree.leaves(payload))
            t_put = time.time() - t1
            t1 = time.time()
            snap = compile_watch.size()
            if segmented:
                # Compaction on: the segment scheduler shrinks each
                # chunk's lockstep batch to its unconverged set between
                # dispatches (bitwise-identical; heartbeats still fire
                # per dispatch).
                state = phase1._model._fit_prepared(
                    payload, meta, None, phase1.iter_segment,
                    on_segment=heartbeat, compact=True,
                )
                jax.block_until_ready(state.theta)
                t_dev = time.time() - t1
                compiled = compile_watch.size() > snap
                if tuner is not None and hi - lo == width:
                    # Full chunks only: a padded tail claim costs
                    # full-width wall for a short real-row count and
                    # would drag the size's estimate off the optimum.
                    tuner.record(width, hi - lo, time.time() - t0,
                                 compile_miss=compiled)
                t1 = time.time()
                state = jax.tree.map(
                    lambda a: np.asarray(a)[:b_real], state
                )
                save_and_log(lo, hi, state, time.time() - t0,
                             t_wait, t_put, t_dev, t1, width, compiled)
            else:
                theta, stats = fit_core_packed(
                    payload, theta_zeros(width), model.config,
                    solver_config, reg_u8_cols=u8_cols,
                    **phase1_dynamic_args(depth["v"], False, packed=True),
                )
                jax.block_until_ready(theta)
                heartbeat()
                compiled = compile_watch.size() > snap
                if two_phase and not os.environ.get("BENCH_NO_RESIDENT"):
                    # Real [lo, hi) recorded: rows past hi - lo are inert
                    # padding that phase 2 must never gather (a padding
                    # row "converges" instantly and would silently patch
                    # garbage into a real series' slot).
                    nb = sum(
                        a.nbytes for a in jax.tree.leaves(payload)
                    )
                    if resident_bytes + nb <= resident_budget:
                        resident[lo] = (hi, payload)
                        resident_bytes += nb
                t_dev = time.time() - t1
                fit_s = time.time() - t0
                if tuner is not None and hi - lo == width:
                    # Full chunks only (see the segmented branch above).
                    tuner.record(width, hi - lo, fit_s,
                                 compile_miss=compiled)
                if not depth["tuned"]:
                    # Depth must settle before chunk 1 dispatches, so
                    # chunk 0 finalizes inline.
                    state = finish_chunk(lo, hi, b_real, theta, stats,
                                         meta, fit_s, t_wait, t_put, t_dev,
                                         width, compiled)
                    tune_depth(state, b_real)
                else:
                    write_futs.append(writer.submit(
                        finish_chunk, lo, hi, b_real, theta, stats, meta,
                        fit_s, t_wait, t_put, t_dev, width, compiled,
                    ))
            n_fitted += 1
            if crash_after and n_fitted >= crash_after:
                for f in write_futs:
                    f.result()
                os._exit(17)  # simulated mid-run worker death
            if os.environ.get(faults.ENV_VAR):
                # Flush pending writer-thread saves first so an "exit"
                # fault kills the worker with exactly the chunks the
                # plan's call count says are on disk (no-op without an
                # armed plan, so production keeps the save pipeline).
                for f in write_futs:
                    f.result()
                write_futs.clear()
                faults.inject("fit_worker_chunk", lo=lo, hi=hi)
            if not pending:
                # Pipeline drained with ingestion still landing shards:
                # NOW a blocking claim is free wall (nothing in flight
                # to stall) — wait for the next shard instead of dying
                # and paying a full respawn + compile warmup.
                submit_next(block=True)
        for f in write_futs:
            f.result()  # surface writer-thread failures before phase 2

    # ---- phase 2: compacted straggler pass over the whole series range ----
    marker = os.path.join(args.out, "phase2_done")
    # Quarantine anything corrupted DURING this worker's own phase 1 (a
    # torn save or media fault the start-of-run sweep could not have
    # seen): phase 2 np.loads every chunk file — a corrupt one used to
    # kill the worker that had just fit it (found by the chaos harness)
    # — and the single-phase marker below must never certify coverage
    # that includes a corrupt file.
    if integrity.sweep_chunks(args.out):
        return 0  # ranges re-queued; the parent's rescan refits them
    if not two_phase:
        # Single-phase run (phase1_iters == 0 OR >= full depth): there is
        # no phase-2 work, but the parent's pending check only knows
        # phase1_iters, not the solver's depth — write the marker once
        # coverage is complete so the two predicates cannot deadlock the
        # retry loop (a worker that never writes it would be respawned
        # forever when phase1_iters >= max_iters).
        if not missing_ranges(completed_ranges(args.out), args.series):
            atomic_write_text(marker, "ok\n")
            obs.record("phase2.done", time.time(), 0.0)
        return 0
    done = completed_ranges(args.out)
    if missing_ranges(done, args.series):
        return 0  # another worker attempt still owes phase-1 chunks
    if os.path.exists(marker):
        return 0

    t0 = time.time()
    straggler_idx, straggler_theta, straggler_gn = [], [], []
    files = {}
    for lo, hi in done:
        z = dict(np.load(_chunk_path(args.out, lo, hi)))
        files[(lo, hi)] = z
        # Already-patched chunks (resume after a phase-2 crash) are final.
        if z.get("phase2") is not None:
            continue
        # Unconverged only: fit_twophase's straggler selection (stuck
        # exits are the rescue pass's job — see TpuBackend.fit_twophase
        # for the measured rationale).  Quarantined placeholder rows are
        # never gathered: their data is exactly what killed a worker.
        bad = np.flatnonzero(
            ~z["converged"] & (z["status"] != STATUS_QUARANTINED)
        )
        straggler_idx.extend(int(lo + i) for i in bad)
        straggler_theta.append(z["theta"][bad])
        straggler_gn.append(z["grad_norm"][bad])
    phase2_mode = "none"
    if straggler_idx:
        heartbeat()  # phase 2 starts: reset the stall clock
        idx = np.asarray(straggler_idx)
        # Difficulty-sorted compaction (backends.tpu.difficulty_order;
        # the chunk-file patch below indexes by idx, so order is free).
        order = difficulty_order(np.concatenate(straggler_gn))
        idx = idx[order]
        theta_cat = np.concatenate(straggler_theta, axis=0)[order]
        # Stragglers get the GN-diagonal initial metric and the full
        # solve depth, through THE SAME compiled program as phase 1: the
        # batch is padded to the fixed phase-1 chunk size (inert
        # all-masked rows) and the phase differences ride the traced
        # *_dynamic args (phase2_dynamic_args — the triple fit_twophase
        # uses), so no second program is ever compiled or warmed.
        n_s = len(straggler_idx)
        # Phase-2 pad width: the tuner's best-throughput (warm-compiled)
        # size when autotuning, else the requested chunk — either way the
        # deep refit re-dispatches a program shape phase 1 already ran.
        p2_chunk = tuner.best_size if tuner is not None else args.chunk
        pad = (-n_s) % p2_chunk
        pad_rows = lambda a: np.concatenate(
            [a, np.zeros((pad,) + a.shape[1:], a.dtype)]
        ) if pad else a

        def host_gather():
            """(y, mask, reg, cap, floor, init) rows for the host-side
            phase-2 paths (copies the device-resident path never makes).
            The isfinite fallback mask is derived from the GATHERED rows
            only — materializing it over the whole (possibly mmap'd)
            dataset to read back a few hundred stragglers would force
            the full y into memory."""
            g = lambda a: None if a is None else pad_rows(
                np.ascontiguousarray(a[idx], np.float32)
            )
            y_rows = g(y)
            if mask is not None:
                m_rows = g(mask)
            else:
                m_rows = np.zeros_like(y_rows)
                m_rows[:idx.size] = np.isfinite(y_rows[:idx.size])
            return (
                y_rows, m_rows, g(reg), g(cap), g(floor),
                pad_rows(theta_cat.astype(np.float32)),
            )

        if segmented:
            phase2_mode = "segmented"
            y_s, m_s, r_s, c_s, f_s, init_s = host_gather()
            # Bounded-dispatch mode: phase 2 keeps --segment's short
            # per-segment dispatches (the reason segmented mode exists),
            # via the static straggler backend.
            state2 = backend._straggler_backend().fit(
                ds, y_s, mask=m_s, regressors=r_s, cap=c_s, floor=f_s,
                init=init_s,
            )
            state2 = jax.tree.map(lambda a: np.asarray(a)[:n_s], state2)
            jax.block_until_ready(jax.tree.leaves(state2)[0])
        elif resident and all(
            any(l2 <= int(g) < h2 for l2, (h2, _) in resident.items())
            for g in idx
        ):
            phase2_mode = "resident"
            # Device-resident gather: every straggler's chunk payload is
            # still on device from phase 1, so the deep refit gathers its
            # rows there — per sub-chunk the link carries only a (c,)
            # index vector and a (c, P) warm-start instead of a re-packed
            # payload, and no host re-prep runs at all.  Only the ~n_s
            # straggler rows are ever concatenated (per-chunk takes
            # first, each chunk freed as it is consumed), so peak HBM
            # stays near phase-1 levels.
            import jax.numpy as jnp

            from tsspark_tpu.models.prophet.design import (
                PACKED_PER_SERIES_FIELDS,
            )

            def map_batch(p, fn):
                upd = {
                    k: fn(getattr(p, k)) for k in PACKED_PER_SERIES_FIELDS
                }
                if p.X_season.ndim == 3:  # per-series (conditional seas.)
                    upd["X_season"] = fn(p.X_season)
                return p._replace(**upd)

            smalls, grouped, gather_ranges = [], [], []
            for l2 in sorted(resident):
                h2, payload2 = resident[l2]
                sel = idx[(idx >= l2) & (idx < h2)]
                if sel.size:
                    local = jnp.asarray((sel - l2).astype(np.int32))
                    smalls.append(map_batch(
                        payload2,
                        lambda a: jnp.take(a, local, axis=0),
                    ))
                    grouped.extend(int(g) for g in sel)
                    gather_ranges.append((l2, h2))
                del resident[l2]
            cat_fields = PACKED_PER_SERIES_FIELDS + (
                ("X_season",) if smalls[0].X_season.ndim == 3 else ()
            )
            strag = smalls[0]._replace(**{
                k: jnp.concatenate(
                    [getattr(s, k) for s in smalls], axis=0
                ) for k in cat_fields
            })
            del smalls
            pos_of = {g: i for i, g in enumerate(grouped)}
            row_idx = np.asarray(
                [pos_of[int(g)] for g in idx], np.int32
            )

            def gather_fit(ix, th):
                # Eager device-side row gathers (a few small dispatches),
                # then THE SAME compiled fit program as phase 1 — the
                # gathered payload has phase 1's exact shapes/dtypes, so
                # no new executable is ever compiled for phase 2.
                packed_g = map_batch(
                    strag, lambda a: jnp.take(a, ix, axis=0)
                )
                return fit_core_packed(
                    packed_g, th, model.config, solver_config,
                    reg_u8_cols=u8_cols,
                    **phase2_dynamic_args(solver_config, packed=True),
                )
            th_parts, st_parts = [], []
            for lo2 in range(0, n_s, p2_chunk):
                hi2 = min(lo2 + p2_chunk, n_s)
                ix = row_idx[lo2:hi2]
                th = theta_cat[lo2:hi2].astype(np.float32)
                if hi2 - lo2 < p2_chunk:
                    # Pad by repeating the first row: a duplicate of a row
                    # already being solved adds no lockstep depth (unlike
                    # arbitrary data) and its result is sliced away.
                    rep = p2_chunk - (hi2 - lo2)
                    ix = np.concatenate([ix, np.repeat(ix[:1], rep)])
                    th = np.concatenate(
                        [th, np.repeat(th[:1], rep, axis=0)]
                    )
                th2, st2 = gather_fit(jnp.asarray(ix), jnp.asarray(th))
                jax.block_until_ready(th2)
                heartbeat()
                th_parts.append(np.asarray(th2)[:hi2 - lo2])
                st_parts.append(np.asarray(st2)[:, :hi2 - lo2])
            del strag
            # Scaling meta for the straggler rows comes from the chunk
            # files — deterministic per series, so these are the exact
            # values a host re-prep would recompute.  Rows are selected
            # inside each file via its own (lo, hi) (no full-dataset
            # concatenation, no positional-alignment assumption), in
            # grouped order, then mapped back to difficulty order with
            # the same row_idx the solves used.
            meta_keys = ("y_scale", "floor", "ds_start", "ds_span",
                         "reg_mean", "reg_std", "changepoints")
            meta_grouped = {
                k: np.concatenate([
                    files[(l2, h2)][k][idx[(idx >= l2) & (idx < h2)] - l2]
                    for (l2, h2) in gather_ranges
                ]) for k in meta_keys
            }
            state2 = fitstate_from_packed(
                np.concatenate(th_parts, axis=0),
                np.concatenate(st_parts, axis=1),
                ScalingMeta(**{
                    k: v[row_idx[:n_s]] for k, v in meta_grouped.items()
                }),
            )
        else:
            # Straggler sub-chunk prep (numpy design build + packing)
            # prefetched on threads so it overlaps the deep device solves,
            # same pattern as the phase-1 loop.
            # NOTE: tsspark_tpu.resident's phase 2 mirrors this branch
            # (serial, sharded dispatch) and the two are pinned BITWISE
            # equal by tests/test_resident.py — a change to the straggler
            # gather/pad/patch logic here must land there too.
            phase2_mode = "host"
            # Partial-coverage fallback: the retained payloads serve no
            # purpose here — release them before the deep solves raise
            # peak memory.
            resident.clear()
            y_s, m_s, r_s, c_s, f_s, init_s = host_gather()
            lows = list(range(0, n_s + pad, p2_chunk))

            def prep2(lo2):
                hi2 = lo2 + p2_chunk
                sl = lambda a: None if a is None else a[lo2:hi2]
                data2, meta2 = model.prepare(
                    ds, y_s[lo2:hi2], mask=sl(m_s), regressors=sl(r_s),
                    cap=sl(c_s), floor=sl(f_s), as_numpy=True,
                )
                packed2, _ = pack_fit_data(
                    data2, meta2, ds, reg_u8_cols=u8_cols,
                    collapse_cap=collapse_cap,
                )
                return packed2, meta2

            subs = []
            with ThreadPoolExecutor(max_workers=2) as pool2:
                futs2 = {
                    j: pool2.submit(prep2, lows[j])
                    for j in range(min(prefetch_depth, len(lows)))
                }
                for j, lo2 in enumerate(lows):
                    packed2, meta2 = futs2.pop(j).result()
                    nxt = j + prefetch_depth
                    if nxt < len(lows):
                        futs2[nxt] = pool2.submit(prep2, lows[nxt])
                    # Warm continuation only: phase 2's set is series
                    # still PROGRESSING at the phase-1 cap (stuck exits
                    # carry status FLOOR/STALLED and are the rescue
                    # path's job, not phase 2's).
                    th2, st2 = fit_core_packed(
                        packed2, init_s[lo2:lo2 + p2_chunk],
                        model.config, solver_config,
                        reg_u8_cols=u8_cols,
                        **phase2_dynamic_args(solver_config, packed=True),
                    )
                    jax.block_until_ready(th2)
                    heartbeat()
                    subs.append(fitstate_from_packed(
                        np.asarray(th2), st2, meta2
                    ))
            state2 = jax.tree.map(
                lambda *xs: np.concatenate(xs, axis=0)[:n_s], *subs
            )
        for (lo, hi), z in files.items():
            if z.get("phase2") is not None:
                continue
            in_chunk = np.flatnonzero((idx >= lo) & (idx < hi))
            local = idx[in_chunk] - lo
            state = _state_from_chunk(z)
            sub = jax.tree.map(lambda a: np.asarray(a)[in_chunk], state2)
            patched = patch_state(state, local, sub)
            t_patch0 = time.time()
            corrupted = save_chunk_atomic(
                args.out, lo, hi, patched,
                extra_arrays={"phase2": np.asarray(1)},
            )
            # The patch rewrites the chunk file (new mtime): without
            # this land record the span ledger and the on-disk recovery
            # signals would disagree about when the range last landed.
            obs.record("chunk.land", t_patch0, time.time() - t_patch0,
                       lo=lo, hi=hi, phase2=True,
                       **({"corrupted": True} if corrupted else {}))
    with open(os.path.join(args.out, "times.jsonl"), "a") as fh:
        fh.write(json.dumps({
            "phase2_s": round(time.time() - t0, 3),
            "stragglers": len(straggler_idx),
            "phase2_mode": phase2_mode,
        }) + "\n")
    atomic_write_text(marker, "ok\n")
    obs.record("fit.phase2", t0, time.time() - t0,
               stragglers=len(straggler_idx), mode=phase2_mode)
    obs.record("phase2.done", time.time(), 0.0)
    return 0


# --------------------------------------------------------------------------
# prep worker (CPU child)
# --------------------------------------------------------------------------

def prep_worker(args) -> int:
    """CPU-side chunk prep: build the packed device payloads for up to
    ``--max-ahead`` pending chunks and save them next to the chunk results.

    Runs overlapped with the parent's probe loop (JAX_PLATFORMS=cpu, so a
    wedged accelerator cannot block it): when the runtime recovers, the
    fit worker finds its first chunks pre-packed and goes straight to
    device work instead of paying host prep on the critical path."""
    obs.adopt_env()  # prep-side fault events join the run's trace
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _setup_jax_child()
    import numpy as np

    from tsspark_tpu.models.prophet.design import (
        _indicator_reg_cols, pack_fit_data,
    )
    from tsspark_tpu.models.prophet.model import ProphetModel

    model_config, solver_config = load_run_config(args.out)
    ds, d = _load_data(args.data)
    y, mask, reg = d["y"], d["mask"], d["reg"]
    cap, floor = d["cap"], d["floor"]
    model = ProphetModel(model_config, solver_config)
    # Overlapped ingestion: pre-pack only rows whose plane shards have
    # landed (prep is pure cache — self-producing data is the fit
    # worker's prerogative, not the prep child's), and decide the u8
    # indicator split from LANDED rows only, exactly like the fit
    # worker: unlanded memmap rows are preallocation zeros and would
    # mark every column an indicator.  The split rides in each payload
    # (save_prep_atomic) so a fit worker that decided differently
    # rejects the file instead of mis-reassembling X_reg.
    from tsspark_tpu.data import plane as data_plane

    ready = data_plane.ready_coverage(args.data, args.series)
    if reg is None:
        u8_cols = ()
    elif ready is None:
        u8_cols = _indicator_reg_cols(reg)
    elif ready:
        u8_cols = _indicator_reg_cols(reg[ready[0][0]:ready[0][1]])
    else:
        return 0  # nothing landed yet; nothing worth pre-packing
    collapse_cap = model_config.growth != "logistic"

    # Completed COVERAGE, not exact chunk-file names: after a mid-run
    # chunk halving, regions fitted under the old wider grid have no file
    # at the new (lo, hi) spacing, and pre-packing them would burn the
    # bounded --max-ahead budget on payloads no fit worker will read.
    done = completed_ranges(args.out)

    def _covered(lo: int, hi: int) -> bool:
        cur = lo
        for dlo, dhi in done:
            if dhi <= cur:
                continue
            if dlo > cur:
                return False
            cur = dhi
            if cur >= hi:
                return True
        return cur >= hi

    def rows(a, lo, hi, fill=0.0):
        return _pad_chunk_rows(a, lo, hi, args.chunk, fill)

    made = 0
    for lo in range(0, args.series, args.chunk):
        if made >= args.max_ahead:
            break
        hi = min(lo + args.chunk, args.series)
        if ready is not None and not data_plane.covers(ready, lo, hi):
            continue
        if _covered(lo, hi) or os.path.exists(_prep_path(args.out, lo, hi)):
            continue
        y_c = rows(y, lo, hi)
        data, meta = model.prepare(
            ds, y_c, mask=_chunk_mask(y_c, mask, lo, hi, args.chunk),
            regressors=rows(reg, lo, hi), cap=rows(cap, lo, hi, fill=1.0),
            floor=rows(floor, lo, hi), as_numpy=True,
        )
        packed, _ = pack_fit_data(data, meta, ds, reg_u8_cols=u8_cols,
                                  collapse_cap=collapse_cap)
        save_prep_atomic(args.out, lo, hi, hi - lo, packed, meta,
                         u8_cols=u8_cols)
        made += 1
    return 0


# --------------------------------------------------------------------------
# parent: probe / spawn / watchdog / retry loop
# --------------------------------------------------------------------------

def tunnel_preflight(timeout: float = 90.0) -> bool:
    """Client-creation watchdog: a wedged accelerator runtime can block
    ``jax.devices()`` forever (observed repeatedly on the tunneled dev
    chip).  Probe it in a disposable subprocess so the decision takes
    <= ``timeout`` seconds instead of a fit-worker stall cycle."""
    if faults.inject("device_probe"):
        return False  # injected wedge: the probe loop's test hook
    code = (
        "import jax, jax.numpy as jnp\n"
        "jax.devices()\n"
        "x = jnp.ones((128, 128))\n"
        "(x @ x).block_until_ready()\n"
        "print('tunnel-ok', flush=True)\n"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return False
    return "tunnel-ok" in (r.stdout or "")


def _child_env(force_cpu: bool = False) -> dict:
    """Child env: the package's parent dir prepended to PYTHONPATH (the
    ``-m`` entry must resolve tsspark_tpu) WITHOUT clobbering existing
    entries — the TPU plugin may live on PYTHONPATH too.

    Seeding from ``dict(os.environ)`` is load-bearing: the effect
    gate's env-propagation rule requires every spawn site to forward
    the inherited ``EnvSpec`` variables (``TSSPARK_FAULTS``,
    ``TSSPARK_DISK_BUDGET_*``, ``TSSPARK_TRACE``, ...), and recognizes
    this builder by exactly that seed."""
    env = dict(os.environ)
    parts = [_REPO_ROOT] + (
        [env["PYTHONPATH"]] if env.get("PYTHONPATH") else []
    )
    env["PYTHONPATH"] = os.pathsep.join(parts)
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    return env


def spawn_worker(mode: str, data_dir: str, out_dir: str, extra: list,
                 timeout: Optional[float] = None,
                 progress_timeout: Optional[float] = None,
                 log_stream=None,
                 policy: Optional[RetryPolicy] = None,
                 force_cpu: bool = False) -> int:
    """Run a child worker; kill it on overall timeout OR when no new chunk
    result / heartbeat has appeared for ``progress_timeout`` seconds (a
    wedged runtime blocks client creation forever — stalling is
    indistinguishable from working except by watching the output dir).

    ``policy``: the policy's per-attempt deadline (``attempt_timeout_s``,
    when set) caps this spawn's ``timeout`` — how a RetryPolicy bounds
    each worker attempt independently of the run's overall budget.

    ``force_cpu`` pins the child to the CPU backend (prep workers
    always; fit workers after the parent's probe budget declares the
    accelerator path dead — see run_resilient's probe_budget_s).

    Observability: each spawn is one ``worker.attempt`` span; its id is
    injected into the child's environment as the cross-process parent,
    so the child's ``fit.worker`` span (and everything under it)
    parents to this attempt in the run ledger."""
    t_spawn0 = time.time()
    # Open-first, like fit.worker: the attempt's open record must be on
    # disk BEFORE the child starts parenting spans to it — a parent
    # killed mid-wait must not orphan the whole child subtree.
    attempt_sid = obs.open_span("worker.attempt", mode=mode) \
        if obs.active() else None

    def finish(rc: int) -> int:
        if attempt_sid is not None:
            obs.close_span(attempt_sid, "worker.attempt", t_spawn0,
                           mode=mode, rc=rc,
                           status="ok" if rc == 0 else "err")
        return rc

    if faults.inject("worker_spawn"):
        return finish(-9)  # injected spawn failure (same rc as killed)
    if policy is not None:
        per_attempt = policy.attempt_timeout(0)
        if per_attempt is not None:
            timeout = (per_attempt if timeout is None
                       else min(timeout, per_attempt))
    cmd = [sys.executable, "-m", "tsspark_tpu.orchestrate", mode,
           "--data", data_dir, "--out", out_dir] + extra
    env = _child_env(force_cpu=force_cpu or (mode == "--_prep"))
    obs.inject_env(env, parent_id=attempt_sid)
    proc = subprocess.Popen(
        cmd, stdout=log_stream or sys.stderr, env=env,
    )
    _CHILDREN.add(proc)
    start = time.time()
    last_progress = start
    n_chunks = len(completed_ranges(out_dir))
    hb_path = os.path.join(out_dir, "heartbeat")
    hb_last = os.path.getmtime(hb_path) if os.path.exists(hb_path) else 0.0
    any_progress = False
    try:
        while True:
            try:
                return finish(proc.wait(timeout=10.0))
            except subprocess.TimeoutExpired:
                pass
            now = time.time()
            n_now = len(completed_ranges(out_dir))
            if n_now > n_chunks:
                n_chunks, last_progress = n_now, now
                any_progress = True
            # Per-dispatch heartbeats also count: the phase-2 straggler
            # pass rewrites existing chunks (no new files), and a fresh
            # compile shows nothing for minutes — both are liveness.
            hb_now = os.path.getmtime(hb_path) if os.path.exists(hb_path) \
                else 0.0
            if hb_now > hb_last:
                hb_last, last_progress = hb_now, now
                any_progress = True
            timed_out = timeout is not None and now - start > timeout
            # Until THIS worker shows its first sign of life it may be
            # cold-compiling its first dispatch — give it triple the
            # steady allowance, but no more.
            allowance = (progress_timeout if any_progress
                         else None if progress_timeout is None
                         else 3.0 * progress_timeout)
            stalled = (allowance is not None
                       and now - last_progress > allowance)
            if timed_out or stalled:
                why = "timed out" if timed_out else "stalled"
                print(
                    f"[orchestrate] worker {why} after "
                    f"{round(now - start)}s", file=sys.stderr,
                )
                proc.kill()
                proc.wait()
                return finish(-9)
    finally:
        _CHILDREN.discard(proc)


def run_resilient(
    *,
    data_dir: str,
    out_dir: str,
    series: int,
    chunk: int = 1024,
    min_chunk: int = MIN_CHUNK,
    segment: int = 0,
    phase1_iters: int = 12,
    no_phase1_tune: bool = False,
    autotune: bool = False,
    deadline: Optional[float] = None,
    reserve: Callable[[], float] = lambda: 25.0,
    on_idle: Optional[Callable[[], None]] = None,
    progress_timeout: float = 90.0,
    state: Optional[dict] = None,
    probe_accelerator: Optional[bool] = None,
    probe_budget_s: Optional[float] = None,
    max_fruitless_retries: Optional[int] = 8,
    retry_policy: Optional[RetryPolicy] = None,
    probe_policy: Optional[RetryPolicy] = None,
) -> dict:
    """Parent loop: drive fit workers until the series range is complete
    (phase 2 included) or the deadline's reserve is reached.

    ``state`` (mutable, updated in place so a caller's signal handler can
    read live values): {"chunk", "retries", "probes": {n, fails, last_t,
    consec}}.  ``on_idle`` fires while waiting out a wedged runtime —
    callers hang CPU-side work there (bench.py pre-packs chunks and runs
    its eval).  ``deadline=None`` means run until complete: a wedged
    runtime is probed forever because it recovers on its own schedule.
    ``probe_accelerator=None`` auto-detects (probing is pointless when
    JAX is pinned to CPU).  Returns ``state`` plus {"complete": bool}.

    ``max_fruitless_retries`` bounds CONSECUTIVE zero-progress worker
    deaths: a wedged accelerator shows up as failed probes (waited out
    forever), but a child that starts, runs, and dies without landing a
    single chunk every time is a deterministic failure (bad input the
    eligibility gate missed, a poisoned chunk, a broken install) — with
    no deadline it would otherwise respawn in an infinite loop instead
    of surfacing the error the in-process path raises immediately.
    ``None`` disables the cap (deadline-bounded callers like bench.py
    prefer the budget to decide).

    ``retry_policy`` / ``probe_policy`` (resilience.policy.RetryPolicy):
    the post-crash respawn schedule and the accelerator-probe schedule.
    Defaults reproduce the historical behavior exactly — a fixed 10 s
    respawn sleep with ``max_fruitless_retries + 1`` consecutive
    zero-progress attempts, and 5 s x1.5-backoff probe sleeps (30 s cap)
    with 30 + 15*consec <= 90 s per-probe patience.  An explicit
    ``retry_policy`` overrides ``max_fruitless_retries``.

    ``autotune`` turns on the fit workers' online chunk-size tuner
    (tsspark_tpu.perf.ChunkAutotuner): the chunk ladder starts small so
    the first result file flushes within seconds, then hill-climbs
    toward the measured series/s optimum; the learned size persists in
    ``<out_dir>/autotune.json`` so resumes start warm.  ``chunk`` then
    acts as the tuner's CAP rather than the fixed size.

    ``probe_budget_s`` bounds the accelerator probe/backoff phase: once
    that much wall time has passed with failed probes and ZERO chunks
    landed, the parent stops probing and spawns fit workers pinned to
    the CPU backend (loud stderr note, ``state["degraded_cpu"]``) —
    slow beats a run that spends its whole budget probing a dead tunnel
    and reports nothing (BENCH_r05).  ``None`` keeps the historical
    probe-forever behavior.
    """
    if retry_policy is None:
        retry_policy = dataclasses.replace(
            WORKER_RETRY_POLICY,
            max_attempts=(None if max_fruitless_retries is None
                          else max_fruitless_retries + 1),
            # Crash-loop tests fault on purpose; don't make them wait
            # out the production respawn sleep.
            base_delay_s=(
                2.0 if os.environ.get("TSSPARK_TEST_CRASH_AFTER")
                else WORKER_RETRY_POLICY.base_delay_s
            ),
        )
    if probe_policy is None:
        probe_policy = PROBE_POLICY
    if state is None:
        state = {}
    state.setdefault("chunk", chunk)
    state.setdefault("retries", 0)
    probes = state.setdefault(
        "probes", {"n": 0, "fails": 0, "last_t": 0.0}
    )
    t0 = time.time()

    def _probe_log(ok: bool, dur: float) -> None:
        probes["n"] += 1
        probes["fails"] += 0 if ok else 1
        probes["last_t"] = round(time.time() - t0, 1)
        try:
            with open(os.path.join(out_dir, "probes.jsonl"), "a") as fh:
                fh.write(json.dumps({
                    "t": probes["last_t"], "ok": ok,
                    "dur_s": round(dur, 1),
                }) + "\n")
        except OSError:
            pass

    # CPU degradation survives re-entry: a caller re-running rounds
    # (fit_resilient after a bisection) passes the same state dict, and
    # a tunnel already declared dead must not be re-probed from scratch.
    force_cpu = bool(state.get("degraded_cpu"))
    check_tunnel = (
        not force_cpu
        and (probe_accelerator if probe_accelerator is not None
             else os.environ.get("JAX_PLATFORMS", "") not in ("cpu",))
    )
    # Probe-budget accounting: ``spent`` accumulates ONLY time inside
    # the failed-probe/backoff branch (probe wall + backoff sleep) — a
    # slow compile or a long healthy fit must never count against the
    # probe budget.  It resets whenever a new chunk lands THIS run, so
    # the budget bounds the current outage; a resumed run with a dead
    # tunnel still degrades instead of re-probing its whole budget away
    # on top of run 1's banked chunks (the BENCH_r05 shape).
    probe_phase = {"spent": 0.0, "n": len(completed_ranges(out_dir))}
    two_phase = phase1_iters > 0
    while True:
        missing = missing_ranges(completed_ranges(out_dir), series)
        phase2_pending = two_phase and not os.path.exists(
            os.path.join(out_dir, "phase2_done")
        )
        if not missing and not phase2_pending:
            state["complete"] = True
            return state
        remaining = (deadline - time.time()) if deadline else float("inf")
        if remaining < reserve():
            state["complete"] = False
            return state
        # Client-creation watchdog: don't hand the range to a fit worker
        # that will hang in jax.devices() for the whole stall allowance.
        # A wedged runtime recovers on its own schedule, so probing NEVER
        # gives up while budget remains — cheap probes loop until
        # deadline - reserve, the wait overlapped by on_idle work.
        if check_tunnel:
            t_probe = time.time()
            # Escalating per-probe patience (probe_policy.attempt_timeout:
            # 30 + 15*consec <= 90 s by default): cheap probes while
            # wedged, but a healthy runtime whose client creation is
            # merely SLOW must not fail every probe forever — each
            # consecutive failure buys the next probe more patience.
            patience = probe_policy.attempt_timeout(
                probes.get("consec", 0)
            ) or 90.0
            if deadline:
                patience = min(
                    patience, max(10.0, remaining - reserve())
                )
            ok = tunnel_preflight(timeout=patience)
            probes["consec"] = 0 if ok else probes.get("consec", 0) + 1
            _probe_log(ok, time.time() - t_probe)
            if not ok:
                print(
                    f"[orchestrate] accelerator probe failed "
                    f"({probes['fails']}/{probes['n']} failed)",
                    file=sys.stderr,
                )
                n_now = len(completed_ranges(out_dir))
                if n_now > probe_phase["n"]:
                    # New chunks landed since the last outage: this is a
                    # fresh outage, give it a fresh probe budget.
                    probe_phase.update(n=n_now, spent=0.0)
                probe_phase["spent"] += time.time() - t_probe
                if (probe_budget_s is not None
                        and probe_phase["spent"] > probe_budget_s):
                    # The probe/backoff phase spent its bounded share of
                    # the budget with nothing NEW landed this run: stop
                    # probing and pin the fit workers to CPU — a slow
                    # run that flushes chunks beats one that probes a
                    # dead tunnel to the deadline and reports zero new
                    # series (BENCH_r05).
                    print(
                        f"[orchestrate] probe budget "
                        f"({probe_budget_s:.0f}s) exhausted with no new "
                        f"chunks landed; degrading fit workers to CPU",
                        file=sys.stderr,
                    )
                    state["degraded_cpu"] = True
                    force_cpu = True
                    check_tunnel = False
                    continue
                if on_idle is not None:
                    on_idle()
                # Backoff between failed probes (probe_policy.delay_s:
                # 5 s x1.5 capped at 30 s by default, reset on success
                # since the retry index is the consec-failure count).
                probe_sleep = probe_policy.delay_s(
                    max(0, probes["consec"] - 1)
                )
                sleep_cap = (
                    max(0.0, deadline - time.time() - reserve())
                    if deadline else probe_sleep
                )
                time.sleep(min(probe_sleep, sleep_cap))
                # Backoff sleeps are probe-phase time too (on_idle work
                # overlaps them, but the accelerator made no progress).
                probe_phase["spent"] += min(probe_sleep, sleep_cap)
                continue
            check_tunnel = False
        remaining = (deadline - time.time()) if deadline else None
        budget = (
            max(60.0, remaining - reserve()) if remaining is not None
            else None
        )
        before = len(completed_ranges(out_dir))
        lo = missing[0][0] if missing else 0
        hi = missing[-1][1] if missing else series
        rc = spawn_worker("--_fit", data_dir, out_dir, [
            "--lo", str(lo), "--hi", str(hi),
            "--chunk", str(state["chunk"]),
            "--segment", str(segment),
            "--series", str(series),
            "--phase1-iters", str(phase1_iters),
        ] + (["--no-phase1-tune"] if no_phase1_tune else [])
          + (["--autotune"] if autotune else []),
            timeout=budget, progress_timeout=progress_timeout,
            policy=retry_policy, force_cpu=force_cpu)
        if rc == 0:
            state["fruitless"] = 0
            continue  # re-scan; loop exits when nothing is missing
        state["retries"] += 1
        made_progress = len(completed_ranges(out_dir)) > before
        fruitless = 0 if made_progress else state.get("fruitless", 0) + 1
        state["fruitless"] = fruitless
        if not retry_policy.allows(fruitless):
            raise WorkerCrashLoopError(
                f"fit worker died {fruitless} consecutive times with zero "
                f"progress (last rc={rc}); giving up — check the worker "
                f"log on stderr for the underlying error (scratch kept in "
                f"{out_dir})",
                missing=missing_ranges(completed_ranges(out_dir), series),
                rc=rc,
            )
        # A death with zero progress puts the runtime itself under
        # suspicion (unless the accelerator path is already declared
        # dead — CPU-pinned workers have no tunnel to probe).
        check_tunnel = (
            not made_progress
            and not force_cpu
            and (probe_accelerator if probe_accelerator is not None
                 else os.environ.get("JAX_PLATFORMS", "") not in ("cpu",))
        )
        # Halve the chunk only when a PHASE-1 attempt made no progress at
        # all — halving targets too-big-program crashes.  A straggler
        # crash mid-run keeps the size that was evidently working, and a
        # death in the phase-2 pass (all chunks already exist) says
        # nothing about chunk size.
        old = state["chunk"]
        state["chunk"] = old if (made_progress or not missing) \
            else max(old // 2, min_chunk)
        print(
            f"[orchestrate] fit worker died (rc={rc}), chunk {old} -> "
            f"{state['chunk']}, retry {state['retries']}", file=sys.stderr,
        )
        # A crash loop that keeps LANDING chunks is re-probed and retried
        # until the deadline's reserve; only the retry policy's attempt
        # budget on consecutive zero-progress deaths cuts it short.  The
        # sleep lets a crashed accelerator worker restart; its retry
        # index is the consecutive-fruitless count so a backoff>1 policy
        # escalates exactly when nothing is landing.
        retry_policy.sleep(fruitless)


# --------------------------------------------------------------------------
# poison-batch quarantine: bisect / placeholder rows / CPU degradation
# --------------------------------------------------------------------------

def _write_quarantine_placeholders(out_dir: str, indices, reason: str,
                                   report: ResilienceReport
                                   ) -> ResilienceReport:
    """Cover each quarantined series with a 1-row placeholder chunk:
    NaN parameters, ``converged=False``, ``status=STATUS_QUARANTINED``,
    inert scaling meta — so ``load_fit_state`` assembles a complete
    batch and downstream consumers can mask the row.  Shapes/dtypes are
    taken from an existing healthy chunk (the caller guarantees one)."""
    import numpy as np

    from tsspark_tpu.models.prophet.design import ScalingMeta
    from tsspark_tpu.models.prophet.model import FitState

    done = completed_ranges(out_dir)
    tmpl = dict(np.load(_chunk_path(out_dir, *done[0])))

    def row(key, fill):
        a = tmpl[key]
        return np.full((1,) + a.shape[1:], fill, a.dtype)

    for q in sorted(indices):
        state = FitState(
            theta=row("theta", np.nan),
            loss=row("loss", np.nan),
            grad_norm=row("grad_norm", np.nan),
            converged=row("converged", False),
            n_iters=row("n_iters", 0),
            status=np.full((1,), STATUS_QUARANTINED, np.int32),
            meta=ScalingMeta(
                y_scale=row("y_scale", 1.0),
                floor=row("floor", 0.0),
                ds_start=row("ds_start", 0.0),
                ds_span=row("ds_span", 1.0),
                reg_mean=row("reg_mean", 0.0),
                reg_std=row("reg_std", 1.0),
                changepoints=row("changepoints", 0.0),
            ),
        )
        # phase2=1: the straggler pass must never gather this row — its
        # data is exactly what killed a worker.
        save_chunk_atomic(out_dir, q, q + 1, state,
                          extra_arrays={"phase2": np.asarray(1),
                                        "quarantined": np.asarray(1)})
        report = dataclasses.replace(
            report,
            quarantined=report.quarantined + (
                QuarantineRecord(int(q), reason),
            ),
        )
    return report


_CPU_FILL_CHUNK = 256  # bound the scipy loop's per-call batch


def _cpu_fill(out_dir: str, data_dir: str, series: int,
              model_config, solver_config,
              deadline: Optional[float] = None) -> None:
    """Graceful degradation: fit every still-missing range in-process on
    the CPU reference backend and persist normal chunk files.  Slow, but
    it finishes the run when the accelerator path's retry budget is
    exhausted — the loud-warning alternative to raising.  A caller's
    ``deadline`` (fit_resilient's budget_s) still bounds it: landed
    chunks persist, so a resumed call continues the fill."""
    import numpy as np

    from tsspark_tpu.backends.registry import degraded_backend

    ds, d = _load_data(data_dir)
    backend = degraded_backend(model_config, solver_config)
    for lo, hi in missing_ranges(completed_ranges(out_dir), series):
        for lo2 in range(lo, hi, _CPU_FILL_CHUNK):
            if deadline is not None and time.time() > deadline:
                raise TimeoutError(
                    f"budget exhausted during CPU degradation fill; "
                    f"partial chunks kept in {out_dir}"
                )
            hi2 = min(lo2 + _CPU_FILL_CHUNK, hi)
            sl = lambda a: None if a is None else np.asarray(a[lo2:hi2])
            state = backend.fit(
                ds, np.asarray(d["y"][lo2:hi2]), mask=sl(d["mask"]),
                regressors=sl(d["reg"]), cap=sl(d["cap"]),
                floor=sl(d["floor"]),
            )
            # phase2=1: the CPU oracle runs at full depth; there is no
            # straggler pass owed for these rows.
            save_chunk_atomic(out_dir, lo2, hi2, state,
                              extra_arrays={"phase2": np.asarray(1)})
    marker = os.path.join(out_dir, "phase2_done")
    if not os.path.exists(marker):
        # The accelerator path is gone; nothing will come back to run a
        # straggler pass, so close the run out (phase-1-depth rows in
        # pre-existing chunks keep their honest converged=False flags).
        atomic_write_text(marker, "degraded-to-cpu\n")


def _bisect_quarantine(
    *, data_dir: str, out_dir: str, series: int, chunk: int, segment: int,
    phase1_iters: int, no_phase1_tune: bool, progress_timeout: float,
    retry_policy: RetryPolicy, report: ResilienceReport,
    model_config, solver_config, max_quarantine: int,
    degrade_to_cpu: bool, deadline: Optional[float],
    force_cpu: bool = False,
) -> ResilienceReport:
    """A chunk kept killing the worker: bisect the failing ranges down to
    single series, quarantine the isolated poison, and fit the survivors
    through the normal worker path (their sub-range chunk files count as
    ordinary coverage).  When the failures look environmental instead of
    data-bound — more than ``max_quarantine`` series "poisoned", or no
    chunk has EVER landed — degrade the remaining ranges to the CPU
    backend (loud warning) rather than quarantining the world.
    """

    def extra(lo: int, hi: int) -> list:
        return ([
            "--lo", str(lo), "--hi", str(hi), "--chunk", str(chunk),
            "--segment", str(segment), "--series", str(series),
            "--phase1-iters", str(phase1_iters),
        ] + (["--no-phase1-tune"] if no_phase1_tune else []))

    def covered(lo: int, hi: int) -> bool:
        holes = missing_ranges(completed_ranges(out_dir), series)
        return not any(h_lo < hi and h_hi > lo for h_lo, h_hi in holes)

    def probe(lo: int, hi: int) -> bool:
        for attempt in range(2):
            try:
                # A run already degraded to CPU keeps its probes there:
                # an accelerator-bound probe would hang in client
                # creation for the whole attempt timeout and make every
                # data-bound crash look environmental.
                spawn_worker(
                    "--_fit", data_dir, out_dir, extra(lo, hi),
                    timeout=retry_policy.attempt_timeout(attempt),
                    progress_timeout=progress_timeout,
                    force_cpu=force_cpu,
                )
            except faults.FaultInjected:
                pass  # an injected spawn failure is still a failure
            if covered(lo, hi):
                return True
            time.sleep(min(1.0, retry_policy.delay_s(attempt)))
        return False

    quarantined: list = []
    degrade = False
    stack = list(missing_ranges(completed_ranges(out_dir), series))
    while stack:
        if deadline is not None and time.time() > deadline:
            raise TimeoutError(
                f"budget exhausted while bisecting poison ranges; "
                f"partial chunks kept in {out_dir}"
            )
        lo, hi = stack.pop(0)
        if probe(lo, hi):
            continue
        if hi - lo <= 1:
            quarantined.append(lo)
            if len(quarantined) > max_quarantine:
                degrade = True
                break
            continue
        mid = (lo + hi) // 2
        stack[:0] = [(lo, mid), (mid, hi)]

    if degrade or (quarantined and not completed_ranges(out_dir)):
        if not degrade_to_cpu:
            raise WorkerCrashLoopError(
                f"worker crash loop looks environmental ("
                f"{len(quarantined)} single-series probes failed, cap "
                f"{max_quarantine}) and degrade_to_cpu is disabled",
                missing=missing_ranges(completed_ranges(out_dir), series),
                rc=-9,
            )
        msg = (
            f"accelerator-path retry budget exhausted "
            f"({len(quarantined)} single-series probes failed — an "
            f"environmental fault, not poison data); DEGRADING the "
            f"remaining ranges to the CPU backend.  This completes the "
            f"fit but is orders of magnitude slower; phase-1-depth rows "
            f"in already-completed chunks keep converged=False."
        )
        warnings.warn(msg, ResilienceWarning, stacklevel=3)
        _cpu_fill(out_dir, data_dir, series, model_config, solver_config,
                  deadline=deadline)
        return dataclasses.replace(
            report, degraded_to_cpu=True, warnings=report.warnings + (msg,)
        )
    if quarantined:
        report = _write_quarantine_placeholders(
            out_dir, quarantined,
            "worker died repeatedly fitting this series (isolated by "
            "bisection); poison-series quarantine",
            report,
        )
        warnings.warn(
            f"quarantined {len(quarantined)} poison series "
            f"{sorted(quarantined)[:8]}{'...' if len(quarantined) > 8 else ''}"
            f" after bisection; their rows carry NaN parameters and "
            f"status=STATUS_QUARANTINED (see FitState's resilience report)",
            ResilienceWarning, stacklevel=3,
        )
    return report


# --------------------------------------------------------------------------
# public in-memory API
# --------------------------------------------------------------------------

def _call_fingerprint(config, solver_config, arrays: dict,
                      params: dict) -> str:
    """Hash of everything that determines a resilient run's results:
    configs, run params, and the spilled data itself.  Guards scratch_dir
    resume — without it a second call with different data/config would
    silently mix old chunk results with new ones (bench.py keys its
    scratch on a code fingerprint for the same reason)."""
    import hashlib

    import numpy as np

    h = hashlib.md5()
    h.update(pickle.dumps(
        {"model": config, "solver": solver_config, "params": params}
    ))
    for name in sorted(arrays):
        a = arrays[name]
        h.update(name.encode())
        if a is None:
            h.update(b"<none>")
            continue
        b = np.ascontiguousarray(a)
        h.update(str(b.shape).encode())
        h.update(str(b.dtype).encode())
        h.update(b)
    return h.hexdigest()


def fit_resilient(
    config,
    solver_config,
    ds,
    y,
    mask=None,
    regressors=None,
    cap=None,
    floor=None,
    *,
    chunk: int = 1024,
    phase1_iters: int = 12,
    segment: int = 0,
    no_phase1_tune: bool = False,
    autotune: bool = False,
    probe_budget_s: Optional[float] = None,
    budget_s: Optional[float] = None,
    scratch_dir: Optional[str] = None,
    keep_scratch: bool = False,
    progress_timeout: float = 90.0,
    retry_policy: Optional[RetryPolicy] = None,
    probe_policy: Optional[RetryPolicy] = None,
    quarantine: bool = True,
    max_quarantine: int = 32,
    degrade_to_cpu: bool = True,
):
    """Process-isolated, crash-resumable batched fit.

    Semantics of ``TpuBackend.fit_twophase`` (same phase policy, same
    traced dispatches) with the elastic-recovery properties the in-memory
    path cannot give: a worker OOM/crash/wedge kills only a child process;
    completed chunks persist in ``scratch_dir`` and the fit resumes from
    them — within this call (automatic retry) and across calls (pass the
    same ``scratch_dir``).

    Requires the packed-path batch shape: a shared 1-D ``ds`` grid, and an
    exact 0/1 mask if given.  ``conditions`` / per-series grids are not
    supported here — use the in-memory backend for those.

    ``budget_s=None`` runs until complete (a wedged accelerator is probed
    indefinitely); with a budget, raises TimeoutError when it ends with
    coverage incomplete.  Returns the full-batch FitState, annotated with
    a ``resilience`` report (resilience.report.get_report).

    Robustness semantics (docs/RESILIENCE.md):

    * The finite-observed-y contract (``isfinite(y)`` wherever
      ``mask == 1``) is validated HERE, before any data is spilled or a
      worker spawned: with ``quarantine=False`` the contract error is
      raised immediately (the in-process path's behavior) instead of
      crash-looping through ~9 child spawns; with ``quarantine=True``
      (default) the offending series are quarantined up front and the
      survivors fit normally.
    * A chunk that kills the worker repeatedly is bisected down to the
      poison series (``quarantine=True``): survivors are fit, the poison
      rows return NaN parameters with ``status=STATUS_QUARANTINED`` and
      are listed in the report — one bad series cannot stall a
      million-series run.  More than ``max_quarantine`` "poison" series
      is read as an environmental fault instead: the remaining ranges
      degrade to the CPU backend with a loud ``ResilienceWarning``
      (``degrade_to_cpu=False`` raises instead).
    * Chunk files carry payload CRCs; corrupt/torn ones are quarantined
      (``*.corrupt``) and re-fit automatically before assembly.

    ``retry_policy``/``probe_policy`` tune the respawn and accelerator
    probe schedules (resilience.policy.RetryPolicy).

    ``autotune`` / ``probe_budget_s``: the workers' online chunk-size
    tuner and the probe-phase budget (see ``run_resilient``).  With
    ``autotune=True``, ``chunk`` is the tuner's cap and the learned
    size persists in the scratch dir for resumes.
    """
    import shutil
    import tempfile

    import numpy as np

    if np.asarray(ds).ndim != 1:
        raise ValueError(
            "fit_resilient requires a shared 1-D ds grid (the packed "
            "chunk-worker path); per-series grids need the in-memory "
            "backend"
        )
    y = np.asarray(y)
    series = y.shape[0]

    # Finite-observed-y pre-validation (the pack_fit_data contract): a
    # violating batch would kill EVERY worker at pack time with zero
    # progress, so the parent used to crash-loop through the whole
    # fruitless-retry budget before surfacing the error the in-process
    # path raises immediately (ADVICE r5).
    mask_spill = mask
    poisoned: list = []
    if mask is not None:
        m = np.asarray(mask)
        bad_rows = np.flatnonzero(
            ((m > 0) & ~np.isfinite(y)).any(axis=tuple(range(1, y.ndim)))
        )
        if bad_rows.size:
            if not quarantine:
                raise ValueError(
                    f"fit_resilient requires finite y wherever mask == 1 "
                    f"(the packed chunk-worker contract); series "
                    f"{bad_rows[:8].tolist()} violate it.  Fix the data, "
                    f"drop the mask (NaN then counts as missing), or pass "
                    f"quarantine=True to fit the survivors."
                )
            poisoned = [int(i) for i in bad_rows]
            mask_spill = m.copy()
            mask_spill[bad_rows] = 0.0  # inert rows; overwritten below
    own_scratch = scratch_dir is None
    scratch = scratch_dir or tempfile.mkdtemp(prefix="tsspark_resilient_")
    data_dir = os.path.join(scratch, "data")
    out_dir = os.path.join(scratch, "out")
    os.makedirs(out_dir, exist_ok=True)
    # Clamp BEFORE deriving min_chunk: min_chunk from the unclamped
    # request could exceed the effective chunk, making a zero-progress
    # "halving" retry GROW the program that just crashed.
    chunk = min(chunk, max(32, series))
    # Resume guard: a scratch_dir may only be reused by the SAME call
    # (same configs, params, and data bytes) — otherwise old chunk files
    # would silently mix into the new run's results.
    fp = _call_fingerprint(
        config, solver_config,
        {"ds": ds, "y": y, "mask": mask, "reg": regressors, "cap": cap,
         "floor": floor},
        {"series": series, "chunk": chunk, "phase1_iters": phase1_iters,
         "segment": segment, "no_phase1_tune": no_phase1_tune,
         "quarantine": quarantine,
         # autotune changes which chunk widths the adaptive phase-1
         # depth sees, so its results may differ from a fixed-chunk run
         # — a different fingerprint keeps the two from sharing scratch.
         "autotune": autotune},
    )
    fp_path = os.path.join(out_dir, "run_fingerprint")
    if os.path.exists(fp_path):
        with open(fp_path) as fh:
            if fh.read().strip() != fp:
                raise ValueError(
                    f"scratch_dir {scratch!r} holds a DIFFERENT resilient "
                    "run (config, data, or run params changed since its "
                    "chunks were written); pass a fresh scratch_dir or "
                    "delete it"
                )
        fresh = False
    else:
        if completed_ranges(out_dir):
            raise ValueError(
                f"scratch_dir {scratch!r} has chunk results but no run "
                "fingerprint; refusing to resume from unidentifiable state"
            )
        fresh = True
    if fresh or not os.path.exists(os.path.join(data_dir, "ds.npy")):
        spill_data(data_dir, ds, y, mask=mask_spill, regressors=regressors,
                   cap=cap, floor=floor)
    save_run_config(out_dir, config, solver_config)
    if fresh:
        atomic_write_text(fp_path, fp)
    deadline = (time.time() + budget_s) if budget_s else None
    report = ResilienceReport(quarantined=tuple(
        QuarantineRecord(
            i, "non-finite observed y (mask == 1 on a non-finite cell); "
               "contract violation quarantined before fitting",
        ) for i in poisoned
    ))
    run_kwargs = dict(
        data_dir=data_dir,
        out_dir=out_dir,
        series=series,
        chunk=chunk,
        min_chunk=min(MIN_CHUNK, chunk),
        segment=segment,
        phase1_iters=phase1_iters,
        no_phase1_tune=no_phase1_tune,
        autotune=autotune,
        probe_budget_s=probe_budget_s,
        deadline=deadline,
        progress_timeout=progress_timeout,
        retry_policy=retry_policy,
        probe_policy=probe_policy,
    )
    # Outer recovery loop: each round either completes coverage, turns a
    # crash loop into quarantines/degradation (quarantine=True), or
    # re-queues ranges whose chunk files failed the integrity check.
    # Bounded: a persistent corruptor or crash source must not spin the
    # parent forever.
    crash_rounds = integrity_rounds = 0
    run_state: dict = {}
    while True:
        try:
            run_state = run_resilient(state=run_state, **run_kwargs)
        except WorkerCrashLoopError:
            if not quarantine:
                raise
            crash_rounds += 1
            if crash_rounds > 3:
                raise
            report = _bisect_quarantine(
                data_dir=data_dir, out_dir=out_dir, series=series,
                chunk=chunk, segment=segment, phase1_iters=phase1_iters,
                no_phase1_tune=no_phase1_tune,
                progress_timeout=progress_timeout,
                retry_policy=run_kwargs["retry_policy"] or WORKER_RETRY_POLICY,
                report=report, model_config=config,
                solver_config=solver_config, max_quarantine=max_quarantine,
                degrade_to_cpu=degrade_to_cpu, deadline=deadline,
                force_cpu=bool(run_state.get("degraded_cpu")),
            )
            # Fresh round state, but the learned "accelerator is dead"
            # fact survives: wiping it would send the next round back
            # to probing the tunnel its predecessor already gave up on.
            run_state = {"degraded_cpu": run_state.get("degraded_cpu",
                                                       False)}
            continue  # re-enter for the phase-2 pass / remaining ranges
        if not run_state.get("complete"):
            raise TimeoutError(
                f"fit_resilient budget exhausted with incomplete coverage; "
                f"partial chunks kept in {scratch} (pass scratch_dir="
                f"{scratch!r} to resume)"
            )
        try:
            result = load_fit_state(out_dir, series)
            break
        except ChunkIntegrityError as e:
            # The corrupt chunks are already quarantined (*.corrupt) and
            # their ranges missing again; drop the phase-2 marker so the
            # refit chunks get their straggler pass too, then go again.
            integrity_rounds += 1
            report = dataclasses.replace(
                report,
                corrupt_chunks=report.corrupt_chunks + tuple(e.ranges),
            )
            if integrity_rounds > 3:
                raise
            marker = os.path.join(out_dir, "phase2_done")
            if os.path.exists(marker):
                os.remove(marker)
            run_state = {"degraded_cpu": run_state.get("degraded_cpu",
                                                       False)}
    report = dataclasses.replace(
        report, retries=int(run_state.get("retries", 0))
    )
    if run_state.get("degraded_cpu") and not report.degraded_to_cpu:
        report = dataclasses.replace(
            report, degraded_to_cpu=True,
            warnings=report.warnings + (
                "accelerator probe budget exhausted with no new chunks; "
                "fit workers were pinned to the CPU backend",
            ),
        )
    if report.quarantined:
        result = _mark_quarantined_rows(result, report.quarantined_indices)
    if own_scratch and not keep_scratch:
        shutil.rmtree(scratch, ignore_errors=True)
    return attach_report(result, report)


def _mark_quarantined_rows(state, indices):
    """NaN out quarantined rows in the assembled FitState (their chunk
    slots were fit as inert all-masked rows or placeholders): theta/loss
    NaN, converged False, status STATUS_QUARANTINED."""
    import numpy as np

    idx = np.asarray(sorted(indices), np.int64)
    theta = np.asarray(state.theta).copy()
    loss = np.asarray(state.loss).copy()
    grad = np.asarray(state.grad_norm).copy()
    conv = np.asarray(state.converged).copy()
    theta[idx] = np.nan
    loss[idx] = np.nan
    grad[idx] = np.nan
    conv[idx] = False
    status = (np.asarray(state.status).copy() if state.status is not None
              else np.zeros(conv.shape[0], np.int32))
    status[idx] = STATUS_QUARANTINED
    return state._replace(theta=theta, loss=loss, grad_norm=grad,
                          converged=conv, status=status)


# --------------------------------------------------------------------------
# child CLI
# --------------------------------------------------------------------------

def _worker_main(argv) -> int:
    import argparse

    mode = argv.pop(0)
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--lo", type=int, default=0)
    ap.add_argument("--hi", type=int, default=0)
    ap.add_argument("--chunk", type=int, default=1024)
    ap.add_argument("--segment", type=int, default=0)
    ap.add_argument("--series", type=int, default=0)
    ap.add_argument("--phase1-iters", type=int, default=0)
    ap.add_argument("--no-phase1-tune", action="store_true")
    ap.add_argument("--autotune", action="store_true")
    ap.add_argument("--max-ahead", type=int, default=6)
    a = ap.parse_args(argv)
    if mode == "--_resident":
        # Mesh-resident single-program mode (tsspark_tpu.resident): the
        # whole fit as sharded in-process dispatches, chunk files landed
        # through the same save_chunk_atomic/lease protocol — so the
        # chaos harness can SIGKILL/fault this child and resume it
        # exactly like a chunk-file fit worker.
        from tsspark_tpu import resident

        return resident.resident_worker(a)
    return {"--_fit": fit_worker, "--_prep": prep_worker}[mode](a)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] in ("--_fit", "--_prep",
                                             "--_resident"):
        sys.exit(_worker_main(sys.argv[1:]))
    raise SystemExit(
        "tsspark_tpu.orchestrate is a worker/launcher module; use "
        "fit_resilient() or bench.py"
    )
