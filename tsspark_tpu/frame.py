"""Long-format DataFrame front-end: the user-facing fit/predict API.

The reference exposes a Spark-DataFrame API (long format: series id, ``ds``
timestamp, ``y`` value) whose TPU path collapses to collect -> shard -> fit ->
scatter (BASELINE.json:5).  This module is that collapse: pivot the long
frame onto a shared calendar grid (collect), hand padded arrays to a
``ForecastBackend`` (shard+fit happens inside), and explode results back to
long format (scatter).

Timestamps are converted to float days since the Unix epoch; any pandas
datetime64 resolution or plain numeric "days" column works.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Dict, NamedTuple, Optional, Sequence

import numpy as np
import pandas as pd
import jax.numpy as jnp

from tsspark_tpu import native
from tsspark_tpu.backends.registry import ForecastBackend, get_backend
from tsspark_tpu.config import McmcConfig, ProphetConfig, SolverConfig
from tsspark_tpu.models import holidays as holidays_mod
from tsspark_tpu.models.prophet.model import FitState, ProphetModel

_SECONDS_PER_DAY = 86400.0


def _ds_to_days(ds: pd.Series) -> np.ndarray:
    if np.issubdtype(ds.dtype, np.number):
        return ds.to_numpy(np.float64)
    # Resolution-agnostic (pandas >= 2 may store datetime64 in s/ms/us/ns).
    ts = pd.to_datetime(ds)
    delta = ts - pd.Timestamp("1970-01-01")
    return (delta / pd.Timedelta(days=1)).to_numpy(np.float64)


def _days_to_ts(days: np.ndarray) -> pd.Series:
    return pd.Timestamp("1970-01-01") + pd.to_timedelta(
        np.round(days * _SECONDS_PER_DAY * 1e3).astype("int64"), unit="ms"
    )


class PivotedBatch(NamedTuple):
    ds: np.ndarray             # (T,) shared grid in days
    y: np.ndarray              # (B, T) with NaN holes
    series_ids: np.ndarray     # (B,)
    cap: Optional[np.ndarray]
    floor: Optional[np.ndarray]       # (B,)
    regressors: Optional[np.ndarray]  # (B, T, R)


def pivot_long(
    df: pd.DataFrame,
    id_col: str = "series_id",
    ds_col: str = "ds",
    y_col: str = "y",
    cap_col: Optional[str] = None,
    floor_col: Optional[str] = None,
    regressor_cols: Sequence[str] = (),
) -> PivotedBatch:
    """Collect: long frame -> padded (B, T) arrays on the union calendar grid.

    The scatter runs through the native threaded pivot engine
    (tsspark_tpu.native) when the compiled library is available; semantics
    (last row wins on duplicate (series, ds)) are identical either way.
    """
    days = _ds_to_days(df[ds_col])
    grid, cols = np.unique(days, return_inverse=True)
    rows, ids = pd.factorize(df[id_col], sort=False)
    if (rows < 0).any():  # factorize marks null ids with -1
        raise ValueError(f"null values in id column {id_col!r}")
    ids = np.asarray(ids)
    b, t_len = len(ids), len(grid)

    def scatter(col, fill=np.nan):
        out = native.bulk_pivot(
            rows, cols, df[col].to_numpy(np.float64), b, t_len
        )
        if not np.isnan(fill):
            out = np.where(np.isnan(out), fill, out)
        return out

    y = scatter(y_col)
    cap = scatter(cap_col) if cap_col else None
    if floor_col:
        # First *observed* floor per series (a series may have no row at the
        # earliest union-grid timestamp, so column 0 is not safe).
        floor_grid = scatter(floor_col)
        first_obs = np.argmax(np.isfinite(floor_grid), axis=1)
        floor = np.nan_to_num(floor_grid[np.arange(b), first_obs])
    else:
        floor = None
    reg = None
    if regressor_cols:
        reg = np.stack([np.nan_to_num(scatter(c)) for c in regressor_cols], axis=-1)
    return PivotedBatch(
        ds=grid, y=y, series_ids=ids, cap=cap, floor=floor, regressors=reg
    )


class Forecaster:
    """High-level fit/predict over long DataFrames, backed by a plugin backend.

    Example:
      fc = Forecaster(config, backend="tpu")
      fc.fit(train_df)
      out = fc.predict(horizon=28)   # long frame: series_id, ds, yhat, bounds
    """

    def __init__(
        self,
        config: ProphetConfig = ProphetConfig(),
        solver_config: SolverConfig = SolverConfig(),
        backend: str = "tpu",
        id_col: str = "series_id",
        ds_col: str = "ds",
        y_col: str = "y",
        cap_col: Optional[str] = None,
        floor_col: Optional[str] = None,
        regressor_cols: Sequence[str] = (),
        holidays: Sequence[holidays_mod.Holiday] = (),
        changepoints: Optional[Sequence] = None,
        mcmc_samples: int = 0,
        mcmc_config: Optional[McmcConfig] = None,
        auto_seasonality: bool = False,
        **backend_kwargs,
    ):
        """``mcmc_samples > 0`` switches fitting to the full-posterior HMC
        path (the upstream Prophet ``mcmc_samples`` knob): predict intervals
        then carry seasonality/regressor uncertainty from the posterior
        draws instead of the MAP trend simulation.  MCMC runs unchunked —
        intended for batches that fit on one device."""
        # Explicit changepoint dates (Prophet's ``changepoints=``):
        # datetimes/strings/numbers accepted, converted to absolute days.
        # Numeric covers numpy scalars too — np.int64 is not an `int`, and
        # routing it through pd.to_datetime would read it as NANOSECONDS
        # since epoch (a silently inert changepoint at day ~0).
        if changepoints is not None:
            cps = list(changepoints)
            numeric = all(
                isinstance(c, (int, float, np.integer, np.floating))
                for c in cps
            )
            days = _ds_to_days(
                pd.Series(cps if numeric else pd.to_datetime(cps))
            )
            config = dataclasses.replace(
                config, changepoints=tuple(float(d) for d in days)
            )
        # Prophet's add_regressor implies the input column is named after
        # the regressor: when the config declares regressors and no
        # explicit column mapping is given, default to the declared names
        # (previously an error demanding regressor_cols).
        if not regressor_cols and config.regressors:
            regressor_cols = tuple(r.name for r in config.regressors)
        # Holidays are sugar over the regressor path: each (holiday, offset)
        # appends an unstandardized indicator column after the user's
        # regressor columns; the indicator values are computed from the
        # calendar grid at fit/predict time (no future_df needed for them).
        self.holidays = tuple(holidays)
        if self.holidays:
            config = holidays_mod.add_holidays(config, self.holidays)
        self.config = config
        # auto_seasonality defers the seasonality choice to fit time, where
        # Prophet's span/frequency rule is applied to the observed calendar
        # (seasonality.auto_seasonalities) and the backend is rebuilt with
        # the resolved config.  Explicit `seasonalities` are then ignored.
        self.auto_seasonality = auto_seasonality
        self._backend_ctor = (backend, solver_config, dict(backend_kwargs))
        self.backend: ForecastBackend = get_backend(
            backend, config, solver_config, **backend_kwargs
        )
        self.id_col, self.ds_col, self.y_col = id_col, ds_col, y_col
        self.cap_col, self.floor_col = cap_col, floor_col
        self.regressor_cols = tuple(regressor_cols)
        self._was_datetime = False
        self.state: Optional[FitState] = None
        self.series_ids: Optional[np.ndarray] = None
        self._train_ds: Optional[np.ndarray] = None
        self._freq_days: Optional[float] = None
        # An explicit mcmc_config enables MCMC by itself; mcmc_samples is
        # shorthand for the default config.  Conflicting values would
        # silently surprise either way, so they must agree.
        if (mcmc_config is not None and mcmc_samples > 0
                and mcmc_samples != mcmc_config.num_samples):
            raise ValueError(
                f"mcmc_samples={mcmc_samples} conflicts with "
                f"mcmc_config.num_samples={mcmc_config.num_samples}; "
                "give one or make them agree"
            )
        if mcmc_config is None and mcmc_samples > 0:
            mcmc_config = McmcConfig(num_samples=mcmc_samples)
        self.mcmc_config = mcmc_config
        self.mcmc_state = None

    def _combined_regressors(
        self, grid: np.ndarray, reg: Optional[np.ndarray], b: int
    ) -> Optional[np.ndarray]:
        """User regressor columns ++ holiday indicator columns, (B, T, R+H)."""
        if not self.holidays:
            return reg
        # A holiday whose enumerated dates stop before the forecast grid ends
        # would silently contribute zero effect exactly where the user expects
        # it most — warn so they extend the calendar
        # (country_holidays(years=…)).  "Stops before" must respect the
        # holiday's own recurrence: warn only when at least one *expected*
        # occurrence (last date + observed recurrence spacing) falls inside
        # the grid uncovered.  This keeps e.g. Thanksgiving quiet on a fit
        # through Dec 31 while still flagging a calendar that genuinely runs
        # out mid-horizon.  Single-date holidays have no observed spacing and
        # warn as soon as the grid passes them.
        grid_end = np.max(grid)

        def _runs_out(h) -> bool:
            if not h.dates:
                return False
            dates = np.sort(np.asarray(h.dates, dtype=np.float64))
            spacing = float(np.median(np.diff(dates))) if dates.size > 1 else 0.0
            return dates[-1] + h.upper_window + spacing < grid_end

        stale = [h.name for h in self.holidays if _runs_out(h)]
        if stale:
            warnings.warn(
                f"forecast grid extends past the last enumerated date of "
                f"holiday(s) {stale}; their effect will be zero there — "
                f"extend the holiday dates to cover the horizon",
                stacklevel=3,
            )
        hol = holidays_mod.holiday_features(grid, self.holidays)  # (T, H)
        hol_b = np.broadcast_to(hol, (b,) + hol.shape)
        return hol_b if reg is None else np.concatenate([reg, hol_b], axis=-1)

    # -- fit -------------------------------------------------------------------

    def fit(self, df: pd.DataFrame, init: Optional[jnp.ndarray] = None
            ) -> "Forecaster":
        self._was_datetime = not np.issubdtype(df[self.ds_col].dtype, np.number)
        cond_names = self.config.condition_names
        batch = pivot_long(
            df, self.id_col, self.ds_col, self.y_col,
            self.cap_col, self.floor_col,
            tuple(self.regressor_cols) + cond_names,
        )
        self.series_ids = batch.series_ids
        self._train_ds = batch.ds
        diffs = np.diff(batch.ds)
        self._freq_days = float(np.median(diffs)) if len(diffs) else 1.0
        self._resolve_auto_seasonality(batch.ds)
        reg, conditions = self._split_conditions(batch.regressors, cond_names)
        reg = self._combined_regressors(
            batch.ds, reg, len(batch.series_ids)
        )
        fit_kw = dict(
            cap=None if batch.cap is None else jnp.asarray(np.nan_to_num(batch.cap)),
            floor=None if batch.floor is None else jnp.asarray(batch.floor),
            regressors=None if reg is None else jnp.asarray(reg),
            conditions=conditions,
        )
        if self.mcmc_config is not None:
            # Full-posterior path: backend-independent model math (MAP init
            # + lockstep HMC chains), unchunked.
            model = ProphetModel(self.config, self.backend.solver_config)
            self.mcmc_state = model.fit_mcmc(
                jnp.asarray(batch.ds), jnp.asarray(batch.y),
                mcmc_config=self.mcmc_config, init=init, **fit_kw,
            )
            self.state = self.mcmc_state.map_state
        else:
            self.state = self.backend.fit(
                jnp.asarray(batch.ds), jnp.asarray(batch.y), init=init,
                **fit_kw,
            )
        return self

    def _resolve_auto_seasonality(self, ds_days) -> None:
        """Apply Prophet's auto-seasonality rule to the observed calendar
        and rebuild the backend with the resolved config.  Called by fit()
        AND by eval.diagnostics.cross_validation (which fits per-cutoff
        models from the config directly) so the flag means the same model
        everywhere."""
        if not self.auto_seasonality:
            return
        import dataclasses as _dc

        from tsspark_tpu.models.prophet import seasonality as seas_mod

        self.config = _dc.replace(
            self.config,
            seasonalities=seas_mod.auto_seasonalities(ds_days),
        )
        name, solver, kwargs = self._backend_ctor
        self.backend = get_backend(name, self.config, solver, **kwargs)

    def _split_conditions(self, reg, cond_names):
        """Separate pivoted condition columns (appended after the user's
        regressor columns) back into the conditions dict."""
        if not cond_names:
            return reg, None
        n_r = len(self.regressor_cols)
        conditions = {
            c: reg[:, :, n_r + i] for i, c in enumerate(cond_names)
        }
        reg = reg[:, :, :n_r] if n_r else None
        return reg, conditions

    # -- predict ---------------------------------------------------------------

    def mcmc_diagnostics(self) -> pd.DataFrame:
        """Per-series sampler health: worst split-R-hat, smallest bulk ESS,
        acceptance rate, divergence count (the Stan-summary convergence gate
        for the ``mcmc_samples`` path).  R-hat above ~1.05 or tiny ESS means
        the chain has not converged — lengthen warmup/samples."""
        if self.mcmc_state is None:
            raise RuntimeError(
                "no MCMC fit: construct with mcmc_samples=N (or mcmc_config) "
                "and call fit first"
            )
        ms = self.mcmc_state
        rhat = np.asarray(ms.rhat)
        ess = np.asarray(ms.ess)
        return pd.DataFrame({
            "series_id": list(self.series_ids),
            "rhat_max": rhat.max(axis=-1),
            "ess_min": ess.min(axis=-1),
            "ess_mean": ess.mean(axis=-1),
            "accept_rate": np.asarray(ms.accept_rate),
            "divergences": np.asarray(ms.divergences),
        })

    def regressor_coefficients(self) -> pd.DataFrame:
        """Fitted external-regressor effects in interpretable units (the
        Prophet ``regressor_coefficients`` utility).

        Returns one row per (series, regressor): ``coef`` is the change in
        yhat per unit change of the RAW regressor value — additive effects
        in data units (beta rescaled by y_scale and the standardization
        std), multiplicative effects as a relative fraction of the trend.
        """
        if self.state is None:
            raise RuntimeError("fit before regressor_coefficients")
        regs = self.config.regressors
        if not regs:
            raise ValueError("model has no external regressors")
        from tsspark_tpu.models.prophet.params import unpack

        p = unpack(np.asarray(self.state.theta), self.config)
        beta = np.asarray(p.beta)[:, self.config.num_seasonal_features:]
        meta = self.state.meta
        rows = []
        for j, rc in enumerate(regs):
            raw = beta[:, j] / np.asarray(meta.reg_std)[:, j]
            coef = raw if rc.mode == "multiplicative" \
                else raw * np.asarray(meta.y_scale)
            rows.append(pd.DataFrame({
                self.id_col: list(self.series_ids),
                "regressor": rc.name,
                "mode": rc.mode,
                "coef": coef,
            }))
        return pd.concat(rows, ignore_index=True)

    def changepoints_df(self, series_id=None) -> pd.DataFrame:
        """Fit-time changepoints for one series: ds (data units), the
        fitted rate adjustment ``delta`` (scaled units, the scale
        Prophet's 0.01 significance threshold applies to), and
        ``abs_delta``.  Feeds plot.add_changepoints_to_plot."""
        if self.state is None:
            raise RuntimeError("fit before changepoints_df")
        from tsspark_tpu.models.prophet.params import unpack

        sid = series_id if series_id is not None else self.series_ids[0]
        order = {s: i for i, s in enumerate(self.series_ids)}
        if sid not in order:
            raise ValueError(f"series {sid!r} was not fitted")
        i = order[sid]
        meta = self.state.meta
        s = np.asarray(meta.changepoints, np.float64)[i]
        days = s * np.asarray(meta.ds_span)[i] + np.asarray(meta.ds_start)[i]
        delta = np.asarray(
            unpack(np.asarray(self.state.theta), self.config).delta
        )[i]
        return pd.DataFrame({
            self.id_col: sid,
            "ds": _days_to_ts(days) if self._was_datetime else days,
            "delta": delta,
            "abs_delta": np.abs(delta),
        })

    def make_future_grid(self, horizon: int, include_history: bool = False
                         ) -> np.ndarray:
        if self._train_ds is None:
            raise RuntimeError("fit before predict")
        last = self._train_ds[-1]
        fut = last + self._freq_days * np.arange(1, horizon + 1)
        return np.concatenate([self._train_ds, fut]) if include_history else fut

    def make_future_frame(
        self, horizon: int, include_history: bool = False
    ) -> pd.DataFrame:
        """Long (series_id, ds) frame continuing the training calendar —
        Prophet's ``make_future_dataframe`` for the batched case.

        The intended edit-then-predict loop for models that need future
        covariates: add cap/regressor/condition columns to the returned
        frame, then call ``predict(future_df=...)``.
        """
        grid = self.make_future_grid(horizon, include_history)
        ds_rep = np.tile(grid, len(self.series_ids))
        return pd.DataFrame({
            self.id_col: np.repeat(list(self.series_ids), len(grid)),
            self.ds_col: _days_to_ts(ds_rep) if self._was_datetime
            else ds_rep,
        })

    def predict(
        self,
        horizon: Optional[int] = None,
        future_df: Optional[pd.DataFrame] = None,
        include_history: bool = False,
        seed: int = 0,
        num_samples: Optional[int] = None,
    ) -> pd.DataFrame:
        """Scatter: forecast back to a long frame.

        Either give ``horizon`` (regular grid continuing the training
        frequency; only valid without external regressors) or ``future_df``
        (long frame carrying ds plus cap/regressor columns per series).
        """
        if self.state is None:
            raise RuntimeError("fit before predict")
        grid, cap, reg, conditions = self._resolve_future(
            horizon, future_df, include_history
        )
        reg = self._combined_regressors(grid, reg, len(self.series_ids))
        cap_j = None if cap is None else jnp.asarray(np.nan_to_num(cap))
        reg_j = None if reg is None else jnp.asarray(reg)
        if self.mcmc_state is not None:
            model = ProphetModel(self.config, self.backend.solver_config)
            fc = model.predict_mcmc(
                self.mcmc_state, jnp.asarray(grid), cap=cap_j,
                regressors=reg_j, seed=seed, max_draws=num_samples,
                conditions=conditions,
            )
        else:
            fc = self.backend.predict(
                self.state, jnp.asarray(grid), cap=cap_j, regressors=reg_j,
                seed=seed, num_samples=num_samples, conditions=conditions,
            )
        return self._to_long(grid, fc)

    def _resolve_future(
        self,
        horizon: Optional[int],
        future_df: Optional[pd.DataFrame],
        include_history: bool,
    ):
        """Shared grid/cap/regressor/condition resolution for every
        forecast-shaped entry point (predict, predictive_samples)."""
        if horizon is not None and not isinstance(
            horizon, (int, np.integer)
        ):
            # A DataFrame passed positionally lands here and would
            # otherwise surface as an inscrutable pandas error downstream.
            raise TypeError(
                f"horizon must be an int, got {type(horizon).__name__}; "
                "pass a frame as future_df=..."
            )
        if future_df is not None:
            return self._align_future(future_df)
        if horizon is None:
            raise ValueError("give horizon or future_df")
        if self.regressor_cols:
            raise ValueError(
                "models with external regressors need future_df with "
                "future regressor values"
            )
        if self.config.condition_names:
            raise ValueError(
                "models with conditional seasonalities need future_df "
                "with future condition values"
            )
        if self.cap_col is not None:
            raise ValueError("logistic models need future_df with cap")
        grid = self.make_future_grid(horizon, include_history)
        return grid, None, None, None

    def predictive_samples(
        self,
        horizon: Optional[int] = None,
        future_df: Optional[pd.DataFrame] = None,
        include_history: bool = False,
        seed: int = 0,
        num_samples: Optional[int] = None,
    ) -> Dict[str, np.ndarray]:
        """Raw posterior-predictive draws (Prophet's ``predictive_samples``).

        Returns {"series_ids": (B,), "ds": (T,) grid,
        "yhat_samples": (S, B, T) in data units}.  Runs UNCHUNKED — the
        draws tensor is the product of samples x series x grid points;
        budget ``num_samples`` accordingly for large batches.

        MAP fits simulate future-changepoint trend paths + observation
        noise (S = ``num_samples`` or ``config.uncertainty_samples``);
        MCMC fits emit one trajectory per retained posterior draw
        (``num_samples`` thins the chain), so seasonality/regressor
        uncertainty rides along too.
        """
        if self.state is None:
            raise RuntimeError("fit before predictive_samples")
        if self.mcmc_state is None:
            n_s = (
                self.config.uncertainty_samples if num_samples is None
                else num_samples
            )
            if not n_s:
                raise ValueError(
                    "predictive_samples needs uncertainty_samples > 0 "
                    "(config) or num_samples > 0"
                )
        grid, cap, reg, conditions = self._resolve_future(
            horizon, future_df, include_history
        )
        reg = self._combined_regressors(grid, reg, len(self.series_ids))
        # Backend-independent: sampling needs only the model layer and the
        # fitted state (self.backend may be any registered backend).
        model = ProphetModel(self.config, self.backend.solver_config)
        cap_j = None if cap is None else jnp.asarray(np.nan_to_num(cap))
        reg_j = None if reg is None else jnp.asarray(reg)
        if self.mcmc_state is not None:
            # One draw trajectory per retained posterior sample — the
            # sample count is the (possibly thinned) chain length.
            fc = model.predict_mcmc(
                self.mcmc_state, jnp.asarray(grid), cap=cap_j,
                regressors=reg_j, seed=seed, max_draws=num_samples,
                conditions=conditions, return_samples=True,
            )
        else:
            fc = model.predict(
                self.state, jnp.asarray(grid), cap=cap_j,
                regressors=reg_j, seed=seed, num_samples=num_samples,
                conditions=conditions, return_samples=True,
            )
        ds_out = _days_to_ts(grid) if self._was_datetime else grid
        return {
            "series_ids": np.asarray(self.series_ids),
            "ds": np.asarray(ds_out),
            "yhat_samples": np.asarray(fc["yhat_samples"]),
        }

    def _align_future(self, future_df: pd.DataFrame):
        """Pivot a future frame and align its series order with training."""
        cond_names = self.config.condition_names
        batch = pivot_long(
            future_df, self.id_col, self.ds_col,
            y_col=self.ds_col,  # y unused at predict; reuse ds column
            cap_col=self.cap_col, floor_col=self.floor_col,
            regressor_cols=tuple(self.regressor_cols) + cond_names,
        )
        order = {s: i for i, s in enumerate(batch.series_ids)}
        missing = [s for s in self.series_ids if s not in order]
        if missing:
            raise ValueError(
                f"future frame is missing {len(missing)} training series "
                f"(e.g. {missing[:5]}); every fitted series needs future "
                f"rows, or pass horizon= to auto-extend the calendar"
            )
        perm = np.asarray([order[s] for s in self.series_ids])
        cap = None if batch.cap is None else batch.cap[perm]
        reg = None if batch.regressors is None else batch.regressors[perm]
        reg, conditions = self._split_conditions(reg, cond_names)
        return batch.ds, cap, reg, conditions

    def components(
        self,
        horizon: Optional[int] = None,
        future_df: Optional[pd.DataFrame] = None,
        include_history: bool = True,
    ):
        """Per-block component arrays for plotting / inspection.

        Returns (ds_grid, components) where components maps each seasonality
        and regressor name to a (B, T) array in data units (multiplicative
        blocks in relative units), matching the training series order.
        """
        if self.state is None:
            raise RuntimeError("fit before components")
        if future_df is not None:
            grid, cap, reg, conditions = self._align_future(future_df)
        else:
            if self.regressor_cols or self.cap_col or \
                    self.config.condition_names:
                raise ValueError(
                    "models with regressors, caps, or conditional "
                    "seasonalities need future_df for components"
                )
            grid = self.make_future_grid(
                horizon or 0, include_history=include_history
            )
            if grid.size == 0:
                raise ValueError(
                    "components with horizon=0 and include_history=False "
                    "selects no timestamps"
                )
            cap = reg = conditions = None
        reg = self._combined_regressors(grid, reg, len(self.series_ids))
        comps = self.backend.components(
            self.state, jnp.asarray(grid),
            cap=None if cap is None else jnp.asarray(np.nan_to_num(cap)),
            regressors=None if reg is None else jnp.asarray(reg),
            conditions=conditions,
        )
        ds_out = _days_to_ts(grid) if self._was_datetime else grid
        return ds_out, {k: np.asarray(v) for k, v in comps.items()}

    def _to_long(self, grid: np.ndarray, fc: Dict[str, jnp.ndarray]
                 ) -> pd.DataFrame:
        b, t_len = len(self.series_ids), len(grid)
        ds_rep = np.tile(grid, b)
        out = {
            self.id_col: np.repeat(self.series_ids, t_len),
            self.ds_col: _days_to_ts(ds_rep) if self._was_datetime else ds_rep,
        }
        for k, v in fc.items():
            out[k] = np.asarray(v).reshape(-1)
        return pd.DataFrame(out)
