"""Holiday calendars and holiday-indicator feature expansion.

The reference supports "holiday / external regressors" in its Prophet fit
(BASELINE.json:5).  In this framework a holiday is sugar over the external
regressor path: every (holiday, day-offset) pair expands to one 0/1 indicator
column appended to the regressor block, with ``standardize=False`` and the
holiday's own prior scale — exactly how upstream Prophet lowers its
``holidays`` frame into the design matrix.  The expansion happens *outside*
jit (plain numpy on the calendar grid), so holiday sets of any size never
change the compiled program beyond the static regressor count.

Country calendars are computed arithmetically (nth-weekday rules + the
Gregorian Easter computus) — this machine has zero egress, so nothing is
looked up.  Supported: US, CA, GB/UK, DE, FR, IT, ES, BR, JP, IN (the
``_COUNTRIES`` registry below is the source of truth).
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
from typing import Iterable, Sequence, Tuple

import numpy as np

from tsspark_tpu.config import ProphetConfig, RegressorConfig

_EPOCH = _dt.date(1970, 1, 1)


def _date_to_days(d: _dt.date) -> float:
    return float((d - _EPOCH).days)


def to_days(dates: Iterable) -> np.ndarray:
    """Absolute float days since the epoch from dates/strings/numbers."""
    out = []
    for d in dates:
        if isinstance(d, (int, float, np.integer, np.floating)):
            out.append(float(d))
        elif isinstance(d, _dt.datetime):
            out.append(_date_to_days(d.date()))
        elif isinstance(d, _dt.date):
            out.append(_date_to_days(d))
        else:  # ISO string / numpy datetime64 / pandas Timestamp
            d64 = np.datetime64(str(d), "D")
            out.append(float(d64.astype("datetime64[D]").astype(np.int64)))
    return np.asarray(out, np.float64)


@dataclasses.dataclass(frozen=True)
class Holiday:
    """One named holiday: its occurrence dates plus an effect window.

    ``lower_window``/``upper_window`` extend the effect to days before/after
    each occurrence (Prophet convention: lower_window=-1 covers the eve).
    Each distinct offset gets its own indicator column and coefficient.
    """

    name: str
    dates: Tuple[float, ...]  # absolute days since epoch
    lower_window: int = 0
    upper_window: int = 0
    prior_scale: float = 10.0
    mode: str = "additive"

    def __post_init__(self):
        if self.lower_window > 0:
            raise ValueError("lower_window must be <= 0 (days before)")
        if self.upper_window < 0:
            raise ValueError("upper_window must be >= 0 (days after)")
        if self.mode not in ("additive", "multiplicative"):
            raise ValueError(f"mode must be additive|multiplicative, got {self.mode}")

    @staticmethod
    def from_dates(name: str, dates: Iterable, **kwargs) -> "Holiday":
        return Holiday(name=name, dates=tuple(to_days(dates)), **kwargs)

    @property
    def offsets(self) -> Tuple[int, ...]:
        return tuple(range(self.lower_window, self.upper_window + 1))


def holidays_from_df(df, prior_scale: float = 10.0) -> Tuple[Holiday, ...]:
    """Prophet-style holidays frame -> Holiday specs.

    Expects columns ``holiday`` and ``ds``; optional ``lower_window``,
    ``upper_window``, ``prior_scale`` (constant per holiday name).
    """
    specs = []
    for name, grp in df.groupby("holiday", sort=True):
        lw = int(grp["lower_window"].iloc[0]) if "lower_window" in grp else 0
        uw = int(grp["upper_window"].iloc[0]) if "upper_window" in grp else 0
        ps = float(grp["prior_scale"].iloc[0]) if "prior_scale" in grp else prior_scale
        specs.append(
            Holiday.from_dates(
                str(name), grp["ds"], lower_window=lw, upper_window=uw,
                prior_scale=ps,
            )
        )
    return tuple(specs)


# ---------------------------------------------------------------------------
# Computed country calendars
# ---------------------------------------------------------------------------


def _nth_weekday(year: int, month: int, weekday: int, n: int) -> _dt.date:
    """n-th (1-based) given weekday (Mon=0) of a month."""
    d = _dt.date(year, month, 1)
    shift = (weekday - d.weekday()) % 7 + 7 * (n - 1)
    return d + _dt.timedelta(days=shift)


def _last_weekday(year: int, month: int, weekday: int) -> _dt.date:
    d = (
        _dt.date(year + 1, 1, 1)
        if month == 12
        else _dt.date(year, month + 1, 1)
    ) - _dt.timedelta(days=1)
    return d - _dt.timedelta(days=(d.weekday() - weekday) % 7)


def _easter(year: int) -> _dt.date:
    """Gregorian Easter Sunday (anonymous computus)."""
    a = year % 19
    b, c = divmod(year, 100)
    d, e = divmod(b, 4)
    g = (8 * b + 13) // 25
    h = (19 * a + b - d - g + 15) % 30
    i, k = divmod(c, 4)
    l = (32 + 2 * e + 2 * i - h - k) % 7
    m = (a + 11 * h + 22 * l) // 451
    month = (h + l - 7 * m + 114) // 31
    day = (h + l - 7 * m + 114) % 31 + 1
    return _dt.date(year, month, day)


def _us(year: int):
    yield "New Year's Day", _dt.date(year, 1, 1)
    yield "Martin Luther King Jr. Day", _nth_weekday(year, 1, 0, 3)
    yield "Washington's Birthday", _nth_weekday(year, 2, 0, 3)
    yield "Memorial Day", _last_weekday(year, 5, 0)
    if year >= 2021:
        yield "Juneteenth", _dt.date(year, 6, 19)
    yield "Independence Day", _dt.date(year, 7, 4)
    yield "Labor Day", _nth_weekday(year, 9, 0, 1)
    yield "Columbus Day", _nth_weekday(year, 10, 0, 2)
    yield "Veterans Day", _dt.date(year, 11, 11)
    yield "Thanksgiving", _nth_weekday(year, 11, 3, 4)
    yield "Christmas Day", _dt.date(year, 12, 25)


def _ca(year: int):
    easter = _easter(year)
    yield "New Year's Day", _dt.date(year, 1, 1)
    yield "Good Friday", easter - _dt.timedelta(days=2)
    # Victoria Day: the Monday on or before May 24.
    may24 = _dt.date(year, 5, 24)
    yield "Victoria Day", may24 - _dt.timedelta(days=may24.weekday() % 7)
    yield "Canada Day", _dt.date(year, 7, 1)
    yield "Labour Day", _nth_weekday(year, 9, 0, 1)
    yield "Thanksgiving", _nth_weekday(year, 10, 0, 2)
    yield "Christmas Day", _dt.date(year, 12, 25)
    yield "Boxing Day", _dt.date(year, 12, 26)


def _gb(year: int):
    easter = _easter(year)
    yield "New Year's Day", _dt.date(year, 1, 1)
    yield "Good Friday", easter - _dt.timedelta(days=2)
    yield "Easter Monday", easter + _dt.timedelta(days=1)
    yield "Early May Bank Holiday", _nth_weekday(year, 5, 0, 1)
    yield "Spring Bank Holiday", _last_weekday(year, 5, 0)
    yield "Summer Bank Holiday", _last_weekday(year, 8, 0)
    yield "Christmas Day", _dt.date(year, 12, 25)
    yield "Boxing Day", _dt.date(year, 12, 26)


def _de(year: int):
    easter = _easter(year)
    yield "Neujahr", _dt.date(year, 1, 1)
    yield "Karfreitag", easter - _dt.timedelta(days=2)
    yield "Ostermontag", easter + _dt.timedelta(days=1)
    yield "Tag der Arbeit", _dt.date(year, 5, 1)
    yield "Christi Himmelfahrt", easter + _dt.timedelta(days=39)
    yield "Pfingstmontag", easter + _dt.timedelta(days=50)
    yield "Tag der Deutschen Einheit", _dt.date(year, 10, 3)
    yield "Erster Weihnachtstag", _dt.date(year, 12, 25)
    yield "Zweiter Weihnachtstag", _dt.date(year, 12, 26)


def _fr(year: int):
    easter = _easter(year)
    yield "Jour de l'an", _dt.date(year, 1, 1)
    yield "Lundi de Paques", easter + _dt.timedelta(days=1)
    yield "Fete du Travail", _dt.date(year, 5, 1)
    yield "Victoire 1945", _dt.date(year, 5, 8)
    yield "Ascension", easter + _dt.timedelta(days=39)
    yield "Lundi de Pentecote", easter + _dt.timedelta(days=50)
    yield "Fete nationale", _dt.date(year, 7, 14)
    yield "Assomption", _dt.date(year, 8, 15)
    yield "Toussaint", _dt.date(year, 11, 1)
    yield "Armistice 1918", _dt.date(year, 11, 11)
    yield "Noel", _dt.date(year, 12, 25)


def _it(year: int):
    easter = _easter(year)
    yield "Capodanno", _dt.date(year, 1, 1)
    yield "Epifania", _dt.date(year, 1, 6)
    yield "Lunedi dell'Angelo", easter + _dt.timedelta(days=1)
    yield "Festa della Liberazione", _dt.date(year, 4, 25)
    yield "Festa del Lavoro", _dt.date(year, 5, 1)
    yield "Festa della Repubblica", _dt.date(year, 6, 2)
    yield "Ferragosto", _dt.date(year, 8, 15)
    yield "Tutti i Santi", _dt.date(year, 11, 1)
    yield "Immacolata Concezione", _dt.date(year, 12, 8)
    yield "Natale", _dt.date(year, 12, 25)
    yield "Santo Stefano", _dt.date(year, 12, 26)


def _es(year: int):
    easter = _easter(year)
    yield "Ano Nuevo", _dt.date(year, 1, 1)
    yield "Epifania del Senor", _dt.date(year, 1, 6)
    yield "Viernes Santo", easter - _dt.timedelta(days=2)
    yield "Fiesta del Trabajo", _dt.date(year, 5, 1)
    yield "Asuncion de la Virgen", _dt.date(year, 8, 15)
    yield "Fiesta Nacional", _dt.date(year, 10, 12)
    yield "Todos los Santos", _dt.date(year, 11, 1)
    yield "Dia de la Constitucion", _dt.date(year, 12, 6)
    yield "Inmaculada Concepcion", _dt.date(year, 12, 8)
    yield "Navidad", _dt.date(year, 12, 25)


def _br(year: int):
    easter = _easter(year)
    yield "Confraternizacao Universal", _dt.date(year, 1, 1)
    yield "Carnaval", easter - _dt.timedelta(days=47)  # Shrove Tuesday
    yield "Sexta-feira Santa", easter - _dt.timedelta(days=2)
    yield "Tiradentes", _dt.date(year, 4, 21)
    yield "Dia do Trabalhador", _dt.date(year, 5, 1)
    yield "Corpus Christi", easter + _dt.timedelta(days=60)
    yield "Independencia", _dt.date(year, 9, 7)
    yield "Nossa Senhora Aparecida", _dt.date(year, 10, 12)
    yield "Finados", _dt.date(year, 11, 2)
    yield "Proclamacao da Republica", _dt.date(year, 11, 15)
    yield "Natal", _dt.date(year, 12, 25)


def _jp(year: int):
    # Fixed-date subset (equinox days and Happy-Monday shifts post-2000
    # are approximated by their statutory rules below).  Era-dependent
    # dates are year-gated: the Emperor's Birthday moved with the era
    # (Dec 23 under Heisei 1989-2018, Feb 23 under Reiwa from 2020; none
    # gazetted in the 2019 transition year), and the Apr 29 / May 4 pair
    # was relabeled in 2007 (Apr 29: Greenery Day -> Showa Day; May 4:
    # citizens' rest day -> Greenery Day).
    yield "New Year's Day", _dt.date(year, 1, 1)
    if year >= 2000:
        yield "Coming of Age Day", _nth_weekday(year, 1, 0, 2)
    yield "National Foundation Day", _dt.date(year, 2, 11)
    if year >= 2020:
        yield "Emperor's Birthday", _dt.date(year, 2, 23)
    if year >= 2007:
        yield "Showa Day", _dt.date(year, 4, 29)
        yield "Greenery Day", _dt.date(year, 5, 4)
    else:
        yield "Greenery Day", _dt.date(year, 4, 29)
    yield "Constitution Day", _dt.date(year, 5, 3)
    yield "Children's Day", _dt.date(year, 5, 5)
    if year >= 2003:
        yield "Marine Day", _nth_weekday(year, 7, 0, 3)
    if year >= 2016:
        yield "Mountain Day", _dt.date(year, 8, 11)
    if year >= 2003:
        yield "Respect for the Aged Day", _nth_weekday(year, 9, 0, 3)
    if year >= 2000:
        yield "Health and Sports Day", _nth_weekday(year, 10, 0, 2)
    yield "Culture Day", _dt.date(year, 11, 3)
    yield "Labour Thanksgiving Day", _dt.date(year, 11, 23)
    if 1989 <= year <= 2018:
        yield "Emperor's Birthday", _dt.date(year, 12, 23)


def _in(year: int):
    # Pan-India gazetted fixed-date holidays (movable religious holidays
    # follow lunar calendars and need an external table — pass them via
    # holidays_from_df / Holiday.from_dates).
    yield "Republic Day", _dt.date(year, 1, 26)
    yield "Independence Day", _dt.date(year, 8, 15)
    yield "Gandhi Jayanti", _dt.date(year, 10, 2)
    yield "Christmas Day", _dt.date(year, 12, 25)


_COUNTRIES = {
    "US": _us, "CA": _ca, "GB": _gb, "UK": _gb, "DE": _de,
    "FR": _fr, "IT": _it, "ES": _es, "BR": _br, "JP": _jp, "IN": _in,
}


def country_holidays(
    country: str,
    years: Sequence[int],
    lower_window: int = 0,
    upper_window: int = 0,
    prior_scale: float = 10.0,
    mode: str = "additive",
) -> Tuple[Holiday, ...]:
    """Computed holiday calendar for a country over the given years."""
    gen = _COUNTRIES.get(country.upper())
    if gen is None:
        raise ValueError(
            f"unknown country {country!r}; available: {sorted(set(_COUNTRIES))}"
        )
    by_name: dict = {}
    for year in years:
        for name, date in gen(year):
            by_name.setdefault(name, []).append(_date_to_days(date))
    return tuple(
        Holiday(
            name=name,
            dates=tuple(days),
            lower_window=lower_window,
            upper_window=upper_window,
            prior_scale=prior_scale,
            mode=mode,
        )
        for name, days in sorted(by_name.items())
    )


# ---------------------------------------------------------------------------
# Feature expansion
# ---------------------------------------------------------------------------


def holiday_column_configs(
    holidays: Sequence[Holiday],
) -> Tuple[RegressorConfig, ...]:
    """One RegressorConfig per (holiday, offset) indicator column."""
    cols = []
    for h in holidays:
        for off in h.offsets:
            suffix = "" if off == 0 else f"_{off:+d}"
            cols.append(
                RegressorConfig(
                    name=f"{h.name}{suffix}",
                    prior_scale=h.prior_scale,
                    standardize=False,
                    mode=h.mode,
                )
            )
    return tuple(cols)


def holiday_features(
    ds_days: np.ndarray, holidays: Sequence[Holiday]
) -> np.ndarray:
    """0/1 indicator matrix (T, H) on a calendar grid (absolute days).

    Grid timestamps match a holiday occurrence when they fall on the same
    calendar day (floor of the fractional day — so every hour of a sub-daily
    grid on Dec 25 matches Christmas), shifted by each window offset.
    """
    grid = np.floor(np.asarray(ds_days, np.float64)).astype(np.int64)
    cols = []
    for h in holidays:
        days = np.floor(np.asarray(h.dates, np.float64)).astype(np.int64)
        for off in h.offsets:
            cols.append(np.isin(grid, days + off).astype(np.float32))
    if not cols:
        return np.zeros((len(grid), 0), np.float32)
    return np.stack(cols, axis=-1)


def add_holidays(
    config: ProphetConfig, holidays: Sequence[Holiday]
) -> ProphetConfig:
    """Config with the holiday indicator columns appended as regressors."""
    return dataclasses.replace(
        config, regressors=config.regressors + holiday_column_configs(holidays)
    )
