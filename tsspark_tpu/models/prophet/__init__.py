"""Prophet-family model: batched TPU-native decomposable forecaster."""
