"""Forecasting: point predictions, component decomposition, and uncertainty.

Uncertainty follows the public Prophet recipe: the MAP fit is a point
estimate, so predictive intervals come from simulating future trend
changepoints (same frequency as history, delta magnitudes ~ Laplace with the
MLE scale of the fitted deltas) plus Gaussian observation noise, then taking
quantiles over samples.  All simulation is batched: one jitted program draws
``(S, B, T_future)`` trend paths with no Python loops over samples or series
(the reference runs this per-series inside its Spark UDF; BASELINE.json:5).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from tsspark_tpu.config import ProphetConfig
from tsspark_tpu.models.prophet import seasonality, trend
from tsspark_tpu.models.prophet.design import (
    FitData,
    ScalingMeta,
    _component,
    model_yhat,
    seasonal_split,
    trend_fn,
)
from tsspark_tpu.models.prophet.params import unpack


def prepare_predict_data(
    ds: jnp.ndarray,
    meta: ScalingMeta,
    config: ProphetConfig,
    cap: Optional[jnp.ndarray] = None,
    regressors: Optional[jnp.ndarray] = None,
    conditions=None,
    dtype: jnp.dtype = jnp.float32,
) -> FitData:
    """Assemble design tensors for a (future or in-sample) time grid.

    Scalings are the *training* scalings from ``meta`` — predictions must be
    produced in the same parameter space the model was fit in.  Time maps
    are computed host-side in float64 (absolute epoch days vs. float32's
    ~5-minute ulp; see ScalingMeta) before casting to the device dtype.
    """
    ds_np = np.asarray(ds, np.float64)
    b = meta.y_scale.shape[0]
    shared_grid = ds_np.ndim == 1
    ds_b = (np.broadcast_to(ds_np, (b,) + ds_np.shape[-1:])
            if shared_grid else ds_np)
    t_len = ds_b.shape[-1]
    ds_start = np.asarray(meta.ds_start, np.float64)
    ds_span = np.asarray(meta.ds_span, np.float64)
    t = jnp.asarray(
        (ds_b - ds_start[:, None]) / ds_span[:, None], dtype
    )

    y_scale = np.asarray(meta.y_scale, np.float64)
    floor = np.asarray(meta.floor, np.float64)
    if config.growth == "logistic":
        if cap is None:
            raise ValueError("logistic growth requires cap at predict time")
        cap_s = jnp.asarray(
            (np.asarray(cap, np.float64) - floor[:, None]) / y_scale[:, None],
            dtype,
        )
    else:
        cap_s = jnp.ones((b, t_len), dtype)

    x_season = seasonality.seasonal_feature_matrix(
        ds_np if shared_grid else ds_b, config.seasonalities
    ).astype(dtype)
    x_season = seasonality.apply_conditions(
        x_season, config.seasonalities, conditions, b
    )

    r = config.num_regressors
    if r:
        if regressors is None:
            raise ValueError(f"config declares {r} regressors but none given")
        reg = np.asarray(regressors, np.float64)
        x_reg = jnp.asarray(
            (reg - np.asarray(meta.reg_mean, np.float64)[:, None, :])
            / np.asarray(meta.reg_std, np.float64)[:, None, :],
            dtype,
        )
    else:
        x_reg = jnp.zeros((b, t_len, 0), dtype)

    # Fit-time changepoint locations from meta: prediction must evaluate the
    # trend on the SAME grid the parameters were fit against (quantile
    # placement makes the grid data-dependent; uniform round-trips too).
    s = jnp.asarray(meta.changepoints, dtype)
    return FitData(
        t=t,
        y=jnp.zeros((b, t_len), dtype),
        mask=jnp.zeros((b, t_len), dtype),
        s=s,
        cap=cap_s,
        X_season=x_season,
        X_reg=x_reg,
        prior_scales=jnp.asarray(config.feature_prior_scales(), dtype),
        mult_mask=jnp.asarray(
            [1.0 if m else 0.0 for m in config.feature_modes()], dtype
        ),
    )


def component_breakdown(
    theta: jnp.ndarray, data: FitData, meta: ScalingMeta, config: ProphetConfig
) -> Dict[str, jnp.ndarray]:
    """Per-block components in data units (additive) / relative units (mult)."""
    p = unpack(theta, config)
    out: Dict[str, jnp.ndarray] = {}
    offset = 0
    scale = meta.y_scale[:, None]
    out["trend"] = trend_fn(p, data, config) * scale + meta.floor[:, None]
    for s_cfg in config.seasonalities:
        nf = s_cfg.num_features
        beta_blk = jnp.zeros_like(p.beta).at[..., offset : offset + nf].set(
            p.beta[..., offset : offset + nf]
        )
        blk = _component(beta_blk[..., : config.num_seasonal_features], data.X_season)
        out[s_cfg.name] = blk * (1.0 if s_cfg.mode == "multiplicative" else scale)
        offset += nf
    for i, r_cfg in enumerate(config.regressors):
        col = p.beta[..., config.num_seasonal_features + i]
        blk = col[:, None] * data.X_reg[..., i]
        out[r_cfg.name] = blk * (1.0 if r_cfg.mode == "multiplicative" else scale)
    return out


def _simulate_trends(
    key: jax.Array,
    theta: jnp.ndarray,
    data: FitData,
    config: ProphetConfig,
    num_samples: int,
    det: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """(S, B, T) scaled trend sample paths with simulated future changepoints."""
    p = unpack(theta, config)
    b, t_len = data.t.shape
    future = (data.t > 1.0).astype(data.t.dtype)  # (B, T)

    # Mean spacing of future points (scaled units) -> per-step changepoint
    # probability matching the historical changepoint frequency (n_cp per
    # unit of scaled time).
    dt = jnp.diff(data.t, axis=-1, prepend=data.t[..., :1])
    mean_dt = (dt * future).sum(-1) / jnp.maximum(future.sum(-1), 1.0)
    cp_prob = jnp.clip(config.n_changepoints * mean_dt, 0.0, 1.0)  # (B,)

    # Laplace MLE scale of fitted deltas (Prophet's lambda), per series.
    if config.n_changepoints:
        lam = jnp.abs(p.delta).mean(-1)
    else:
        lam = jnp.zeros((b,), data.t.dtype)
    lam = jnp.maximum(lam, 1e-8)

    # Draw dtypes pinned to the design dtype: random.uniform/laplace
    # default to the x64-mode float, so an un-pinned draw silently
    # promotes every sample path to f64 under enable_x64 (the same
    # drift class the contract checker caught in ops/hmc.py).
    k_bern, k_lap = jax.random.split(key)
    ind = (
        jax.random.uniform(k_bern, (num_samples, b, t_len),
                           dtype=data.t.dtype) < cp_prob[None, :, None]
    ).astype(data.t.dtype) * future[None]
    lap = jax.random.laplace(k_lap, (num_samples, b, t_len),
                             dtype=data.t.dtype) * lam[None, :, None]
    new_delta = ind * lap  # (S, B, T)

    if det is None:
        det = trend_fn(p, data, config)  # (B, T) deterministic trend

    if config.growth == "linear":
        # Slope change delta_j at future grid point t_j adds
        # delta_j * (t - t_j) for t >= t_j:  t*cumsum(d) - cumsum(d*t).
        c = jnp.cumsum(new_delta, axis=-1)
        d = jnp.cumsum(new_delta * data.t[None], axis=-1)
        return det[None] + data.t[None] * c - d
    if config.growth == "logistic":
        # Full recompute with history + sampled future changepoints.  The
        # concatenated changepoint vector must stay sorted even when the
        # prediction grid includes in-sample times (t <= 1): in-sample
        # positions carry delta == 0 (the `future` mask above), so clamping
        # them to just past the history keeps the array sorted without
        # changing the trend.  History changepoints live in [0, 1).
        t_clamped = jnp.maximum(data.t, 1.0 + 1e-6)
        s_ext = jnp.concatenate(
            [jnp.broadcast_to(data.s, (num_samples,) + data.s.shape),
             jnp.broadcast_to(t_clamped[None], new_delta.shape)],
            axis=-1,
        )
        d_ext = jnp.concatenate(
            [jnp.broadcast_to(p.delta, (num_samples,) + p.delta.shape), new_delta],
            axis=-1,
        )
        sim = jax.vmap(
            lambda dd, ss: trend.logistic(data.t, data.cap, p.k, p.m, dd, ss)
        )(d_ext, s_ext)
        return sim
    return jnp.broadcast_to(det[None], (num_samples,) + det.shape)


def forecast_from_draws(
    samples: jnp.ndarray,
    data: FitData,
    meta: ScalingMeta,
    config: ProphetConfig,
    key: jax.Array,
    interval_width: Optional[float] = None,
    return_samples: bool = False,
) -> Dict[str, jnp.ndarray]:
    """Posterior-predictive forecast from (S, B, P) MCMC draws.

    Unlike the MAP path (:func:`forecast`), every component — trend,
    seasonality, regressors, observation noise — carries posterior
    uncertainty: each draw contributes one full trajectory (with its own
    simulated future changepoints), and intervals are quantiles across draws.
    ``yhat`` is the posterior-predictive mean.
    """
    s_draws = samples.shape[0]
    keys = jax.random.split(key, s_draws + 1)

    def one_draw(theta_s, k):
        k_tr, k_noise = jax.random.split(k)
        p = unpack(theta_s, config)
        add, mult = seasonal_split(theta_s, data, config)
        # Deterministic trajectory for the point forecast; simulated future
        # changepoints + observation noise only feed the quantile draws, so
        # yhat stays seed-independent posterior structure, not MC noise.
        det_tr = trend_fn(p, data, config)
        det_yhat = det_tr * (1.0 + mult) + add
        tr = _simulate_trends(
            k_tr, theta_s, data, config, num_samples=1, det=det_tr
        )[0]
        sigma = jnp.exp(p.log_sigma)[:, None]
        noise = jax.random.normal(k_noise, tr.shape) * sigma
        yhat = tr * (1.0 + mult) + add + noise
        return yhat, tr, det_yhat, det_tr, add, mult

    yhat_s, trend_s, det_yhat_s, det_trend_s, add_s, mult_s = jax.vmap(one_draw)(
        samples, keys[:s_draws]
    )

    scale = meta.y_scale[:, None]
    floor = meta.floor[:, None]
    width = config.interval_width if interval_width is None else interval_width
    lo_q = (1.0 - width) / 2.0
    hi_q = 1.0 - lo_q
    qs = jnp.quantile(yhat_s, jnp.asarray([lo_q, hi_q]), axis=0)
    t_qs = jnp.quantile(trend_s, jnp.asarray([lo_q, hi_q]), axis=0)
    return {
        "yhat": det_yhat_s.mean(0) * scale + floor,
        "trend": det_trend_s.mean(0) * scale + floor,
        "additive": add_s.mean(0) * scale,
        "multiplicative": mult_s.mean(0),
        "yhat_lower": qs[0] * scale + floor,
        "yhat_upper": qs[1] * scale + floor,
        "trend_lower": t_qs[0] * scale + floor,
        "trend_upper": t_qs[1] * scale + floor,
        **(
            {"yhat_samples": yhat_s * scale[None] + floor[None]}
            if return_samples else {}
        ),
    }


def forecast(
    theta: jnp.ndarray,
    data: FitData,
    meta: ScalingMeta,
    config: ProphetConfig,
    key: Optional[jax.Array] = None,
    num_samples: Optional[int] = None,
    return_samples: bool = False,
) -> Dict[str, jnp.ndarray]:
    """Point forecast + components + predictive intervals, in data units.

    Returns a dict with "yhat", "trend", "additive", "multiplicative",
    and (when sampling) "yhat_lower"/"yhat_upper"/"trend_lower"/"trend_upper",
    all (B, T).  ``return_samples`` additionally includes the raw
    posterior-predictive draws as "yhat_samples" (S, B, T) — Prophet's
    ``predictive_samples`` — sized S*B*T floats, the caller's memory to
    budget.
    """
    p = unpack(theta, config)
    yhat_s, trend_s = model_yhat(theta, data, config)
    scale = meta.y_scale[:, None]
    floor = meta.floor[:, None]
    out = {
        "yhat": yhat_s * scale + floor,
        "trend": trend_s * scale + floor,
    }
    add, mult = seasonal_split(theta, data, config)
    out["additive"] = add * scale
    out["multiplicative"] = mult

    n_s = config.uncertainty_samples if num_samples is None else num_samples
    if n_s and key is not None:
        k_tr, k_noise = jax.random.split(key)
        trends = _simulate_trends(k_tr, theta, data, config, n_s)  # (S, B, T)
        sigma = jnp.exp(p.log_sigma)[None, :, None]
        noise = jax.random.normal(k_noise, trends.shape,
                                  dtype=trends.dtype) * sigma
        samples = trends * (1.0 + mult[None]) + add[None] + noise
        lo_q = (1.0 - config.interval_width) / 2.0
        hi_q = 1.0 - lo_q
        # Quantile points carry the sample dtype: a bare float list is
        # f64 under x64 and would promote the interval outputs.
        q = jnp.asarray([lo_q, hi_q], samples.dtype)
        qs = jnp.quantile(samples, q, axis=0)
        out["yhat_lower"] = qs[0] * scale + floor
        out["yhat_upper"] = qs[1] * scale + floor
        t_qs = jnp.quantile(trends, q, axis=0)
        out["trend_lower"] = t_qs[0] * scale + floor
        out["trend_upper"] = t_qs[1] * scale + floor
        if return_samples:
            out["yhat_samples"] = samples * scale[None] + floor[None]
    return out


# One compiled program for the whole forecast (point pass + trend-path
# simulation + quantiles) instead of dozens of tiny eager dispatches.  On
# TPU this is the difference between one fused executable and an
# op-by-op dispatch stream over the tunnel; it also sidesteps an XLA:CPU
# JIT instability observed when a long-lived process (the test suite)
# compiles hundreds of small eager programs and then segfaults inside a
# trivial convert_element_type compile on this path.  config/num_samples/
# return_samples are static (compile-time); theta/data/meta/key are traced
# — meta's float64 host leaves are only used for the final y_scale/floor
# affine map here, where f32 is fine (the precision-critical ds math
# happens in prepare_predict_data, outside this program).
forecast_jit = jax.jit(
    forecast, static_argnames=("config", "num_samples", "return_samples")
)
