"""Trend functions: piecewise-linear, logistic-growth-with-cap, and flat.

TPU-first design: the changepoint sums are computed as FUSED
compare-multiply-reduce chains over the (small) changepoint axis,

    sum_j v_j * 1[t >= s_j]  ==  reduce_c((t[:, :, None] >= s[:, None, :]) * v)

which XLA loop-fuses so the (B, T, n_cp) comparison tensor never touches HBM
— the pass reads t (B, T) once and streams pure VPU work.  Two designs were
measured and rejected on real v5e hardware (profiled round 3, see
profiles/ and README "Performance notes"):

  * the classic Prophet indicator matmul ``A @ delta`` with a materialized
    (B, T, n_cp) matrix: hundreds of MB of HBM traffic per objective eval;
  * ``cumsum(delta)[searchsorted(s, t)]`` (a (B, n_cp) cumsum + (B, T)
    gather): O(B*T) HBM traffic on paper, but TPU gathers from per-row
    tables do not vectorize across lanes — measured 157 ms per trend eval
    at 1024x1941 vs 3.6 ms for the fused reduce, and it dominated the
    entire fit (the objective, its vjp, and the line-search fan each paid
    it).  Gradients through the fused form are reductions, not
    scatter-adds, which TPUs equally dislike.

Parity target: the trend family of the reference's ``tsspark.fit.prophet``
(piecewise-linear + logistic-growth caps, BASELINE.json:5).  The reference
source is unavailable (SURVEY.md §0), so semantics follow the public Prophet
model definition.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def changepoint_index(t: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
    """Number of changepoints at or before each time.

    Args:
      t: (B, T) scaled times.
      s: (B, n_cp) *sorted* changepoint locations in scaled time.

    Returns:
      (B, T) int32 index into [0, n_cp].
    """
    if s.shape[-1] == 0:
        return jnp.zeros(t.shape, dtype=jnp.int32)
    return jax.vmap(
        lambda tt, ss: jnp.searchsorted(ss, tt, side="right").astype(jnp.int32)
    )(t, s)


def step_weighted_sum(
    values: jnp.ndarray, t: jnp.ndarray, s: jnp.ndarray
) -> jnp.ndarray:
    """sum_j values_j * 1[t >= s_j] as one fused compare-multiply-reduce.

    values, s: (B, n_cp); t: (B, T) -> (B, T).  The boundary convention
    (changepoint active AT its own timestamp) matches
    ``searchsorted(side="right")``.  The (B, T, n_cp) comparison is
    loop-fused by XLA — nothing 3-D hits HBM.
    """
    if s.shape[-1] == 0:
        return jnp.zeros(t.shape, t.dtype)
    active = (t[..., :, None] >= s[..., None, :]).astype(t.dtype)
    return jnp.einsum("...tc,...c->...t", active, values,
                      precision=jax.lax.Precision.HIGHEST)


def piecewise_linear(
    t: jnp.ndarray,
    k: jnp.ndarray,
    m: jnp.ndarray,
    delta: jnp.ndarray,
    s: jnp.ndarray,
) -> jnp.ndarray:
    """g(t) = (k + sum_{j: s_j <= t} delta_j) * t + (m + sum gamma_j),
    gamma_j = -s_j * delta_j  (keeps the trend continuous at changepoints).

    Computed in the equivalent hinge-basis form

        g(t) = k*t + m + sum_j delta_j * relu(t - s_j)

    (expand relu(t - s_j) = (t - s_j) * 1[t >= s_j] and regroup), which is
    one fused compare-multiply-reduce — no gather, no 3-D intermediate.

    Shapes: t (B, T); k, m (B,); delta, s (B, n_cp).  Returns (B, T).
    """
    base = k[..., None] * t + m[..., None]
    if s.shape[-1] == 0:
        return base
    hinge = jnp.maximum(t[..., :, None] - s[..., None, :], 0.0)
    return base + jnp.einsum("...tc,...c->...t", hinge, delta,
                             precision=jax.lax.Precision.HIGHEST)


def _logistic_gamma(
    k: jnp.ndarray, m: jnp.ndarray, delta: jnp.ndarray, s: jnp.ndarray
) -> jnp.ndarray:
    """Offset adjustments keeping the logistic trend continuous.

    Sequential recursion over changepoints (public Prophet definition):
      gamma_j = (s_j - m - sum_{l<j} gamma_l) * (1 - k_{j-1} / k_j)
    with k_j = k + sum_{l<=j} delta_l.  n_cp is small (default 25) so a
    lax.scan over changepoints costs nothing; everything inside is batched
    over series.
    """
    eps = 1e-10

    def safe_div(a, b):
        return a / jnp.where(jnp.abs(b) < eps, jnp.where(b < 0, -eps, eps), b)

    k_cum = k[..., None] + jnp.concatenate(
        [jnp.zeros_like(delta[..., :1]), jnp.cumsum(delta, axis=-1)], axis=-1
    )  # (B, n_cp + 1)

    def step(gamma_sum, inputs):
        s_j, k_prev, k_next = inputs
        gamma_j = (s_j - m - gamma_sum) * (1.0 - safe_div(k_prev, k_next))
        return gamma_sum + gamma_j, gamma_j

    n_cp = delta.shape[-1]
    xs = (
        jnp.moveaxis(s, -1, 0),               # (n_cp, B)
        jnp.moveaxis(k_cum[..., :-1], -1, 0),  # k_{j-1}
        jnp.moveaxis(k_cum[..., 1:], -1, 0),   # k_j
    )
    _, gammas = jax.lax.scan(step, jnp.zeros_like(m), xs, length=n_cp)
    return jnp.moveaxis(gammas, 0, -1)  # (B, n_cp)


def logistic(
    t: jnp.ndarray,
    cap: jnp.ndarray,
    k: jnp.ndarray,
    m: jnp.ndarray,
    delta: jnp.ndarray,
    s: jnp.ndarray,
) -> jnp.ndarray:
    """Logistic growth trend with (possibly time-varying) capacity.

    g(t) = cap(t) / (1 + exp(-(k + A(t)delta) * (t - (m + A(t)gamma)))).

    Shapes: t, cap (B, T); k, m (B,); delta, s (B, n_cp).  Returns (B, T).
    """
    rate = k[..., None] + step_weighted_sum(delta, t, s)
    if delta.shape[-1] > 0:
        gamma = _logistic_gamma(k, m, delta, s)
        offset = m[..., None] + step_weighted_sum(gamma, t, s)
    else:
        offset = m[..., None] * jnp.ones_like(t)
    return cap * jax.nn.sigmoid(rate * (t - offset))


def flat(t: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """Constant trend g(t) = m."""
    return jnp.broadcast_to(m[..., None], t.shape).astype(t.dtype)


def uniform_changepoints(
    t_first: jnp.ndarray,
    t_last: jnp.ndarray,
    n_changepoints: int,
    changepoint_range: float,
) -> jnp.ndarray:
    """Per-series changepoint grid, uniform over the first
    ``changepoint_range`` fraction of each series' observed span.

    Prophet places changepoints at quantiles of observed timestamps; for
    regularly sampled series (the M4/M5 cases) a uniform grid over the
    observed span is identical up to sampling jitter, and it is batchable
    with zero gathers.

    Args:
      t_first, t_last: (B,) scaled time of first/last observation.
    Returns:
      (B, n_changepoints) sorted changepoints.
    """
    xp = np if isinstance(t_first, np.ndarray) else jnp
    if n_changepoints == 0:
        return xp.zeros(t_first.shape + (0,), t_first.dtype)
    span = (t_last - t_first) * changepoint_range
    # Fractions in (0, 1]: skip 0 so the first changepoint is strictly after
    # the first observation (a changepoint at t_first is unidentifiable).
    fracs = xp.arange(1, n_changepoints + 1, dtype=t_first.dtype) / n_changepoints
    return t_first[..., None] + span[..., None] * fracs[None, :]
