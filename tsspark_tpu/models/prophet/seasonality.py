"""Fourier seasonality features.

Seasonal features are a function of *absolute* time (days since a fixed
epoch), not per-series scaled time, so for a batch of series sharing one
calendar grid the feature matrix is a single shared (T, F) array — the
seasonal component of every series is then one (B, F) @ (F, T) matmul on the
MXU instead of B independent matvecs (the reference fans these out per-series
through Spark executors; see BASELINE.json:5).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from tsspark_tpu.config import ProphetConfig, SeasonalityConfig


def fourier_features(
    t_days: jnp.ndarray, period: float, order: int
) -> jnp.ndarray:
    """Fourier basis for one seasonality block.

    Args:
      t_days: (..., T) time in days since a fixed epoch.
      period: period in days.
      order:  number of harmonics K.

    Returns:
      (..., T, 2K) features [sin(2pi*1*t/p), cos(2pi*1*t/p), ..., sin(2pi*K*t/p),
      cos(2pi*K*t/p)].
    """
    # Fold t into [0, period) first so the trig arguments keep phase
    # precision even for large absolute day counts.  Host arrays fold in
    # float64 (epoch days ~2e4 quantize to ~5min in f32 — visible phase
    # error for sub-daily periods) and stay HOST numpy end-to-end: one eager
    # jnp op here costs a tiny XLA compile + a tunnel dispatch, and this
    # runs on the per-chunk critical path of the fit driver.
    host = isinstance(t_days, np.ndarray)
    xp = np if host else jnp
    t_mod = xp.mod(t_days.astype(np.float64), period) if host \
        else jnp.mod(t_days, period)
    n = xp.arange(1, order + 1, dtype=t_mod.dtype)
    angles = 2.0 * xp.pi * t_mod[..., None] * n / period
    feats = xp.stack([xp.sin(angles), xp.cos(angles)], axis=-1)
    feats = feats.reshape(feats.shape[:-2] + (2 * order,))
    return feats.astype(np.float32) if host else feats


def seasonal_feature_matrix(
    t_days: jnp.ndarray, seasonalities: Sequence[SeasonalityConfig]
) -> jnp.ndarray:
    """Concatenate all seasonality blocks into one (..., T, F_seasonal) matrix."""
    host = isinstance(t_days, np.ndarray)
    if not seasonalities:
        zeros = np.zeros if host else jnp.zeros
        return zeros(t_days.shape + (0,), jnp.float32)
    blocks = [
        fourier_features(t_days, s.period, s.fourier_order) for s in seasonalities
    ]
    return (np if host else jnp).concatenate(blocks, axis=-1)


def apply_conditions(
    x_season,
    seasonalities: Sequence[SeasonalityConfig],
    conditions,
    batch: int,
):
    """Gate conditional seasonality blocks by their per-row conditions.

    Args:
      x_season: (T, Fs) shared or (B, T, Fs) per-series feature matrix
        (numpy on the host prep path, jnp on traced paths).
      conditions: dict mapping condition_name -> (B, T) truthy array.
      batch: B (needed to broadcast a shared matrix per-series).

    Returns:
      (B, T, Fs): gated blocks are zero where their condition is False, so
      the gated component contributes nothing there and its betas are fit
      only against rows where the condition holds (Prophet's
      ``add_seasonality(..., condition_name=...)`` semantics).
    """
    cond_needed = [s.condition_name for s in seasonalities if s.condition_name]
    if not cond_needed:
        return x_season
    conditions = conditions or {}
    missing = [c for c in cond_needed if c not in conditions]
    if missing:
        raise ValueError(
            f"conditional seasonalities need condition values for {missing}"
        )
    host = isinstance(x_season, np.ndarray)
    xp = np if host else jnp
    t_len = x_season.shape[-2]
    if x_season.ndim == 2:
        x_season = xp.broadcast_to(
            x_season, (batch, t_len, x_season.shape[-1])
        )
    gated = []
    offset = 0
    for s in seasonalities:
        block = x_season[..., offset : offset + s.num_features]
        if s.condition_name:
            c = xp.asarray(conditions[s.condition_name])
            if c.shape != (batch, t_len):
                raise ValueError(
                    f"condition {s.condition_name!r} has shape {c.shape}, "
                    f"expected {(batch, t_len)}"
                )
            block = block * (c[..., None] != 0)
        gated.append(block)
        offset += s.num_features
    return xp.concatenate(gated, axis=-1)


def auto_seasonalities(
    ds_days, mask=None
) -> "Tuple[SeasonalityConfig, ...]":
    """Prophet's auto-seasonality rule from the observed calendar.

    yearly  — span >= 2 years (730 days);
    weekly  — span >= 2 weeks AND finest spacing < 7 days;
    daily   — span >= 2 days  AND finest spacing < 1 day.

    Args:
      ds_days: (T,) or (B, T) absolute days; NaN/masked entries ignored.
      mask: optional validity mask matching ds_days.
    Returns:
      tuple of the standard YEARLY / WEEKLY / DAILY configs that apply.
    """
    from tsspark_tpu.config import DAILY, WEEKLY, YEARLY

    ds = np.asarray(ds_days, np.float64).ravel()
    if mask is not None:
        ds = ds[np.asarray(mask).ravel() > 0]
    ds = np.unique(ds[np.isfinite(ds)])
    if ds.size < 2:
        return ()
    span = float(ds[-1] - ds[0])
    spacing = float(np.min(np.diff(ds)))
    out = []
    if span >= 730.0:
        out.append(YEARLY)
    if span >= 14.0 and spacing < 7.0:
        out.append(WEEKLY)
    if span >= 2.0 and spacing < 1.0:
        out.append(DAILY)
    return tuple(out)


def feature_matrix(
    t_days: jnp.ndarray,
    config: ProphetConfig,
    regressors: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Full design matrix: Fourier seasonal columns + external regressor columns.

    Args:
      t_days: (..., T) absolute days.
      regressors: (..., T, R) standardized external regressor values (holiday
        indicators and covariates), or None when config.regressors is empty.

    Returns:
      (..., T, F) with F == config.num_features, column order matching
      config.feature_prior_scales() / config.feature_modes().
    """
    x = seasonal_feature_matrix(t_days, config.seasonalities)
    r = config.num_regressors
    if r:
        if regressors is None:
            raise ValueError(
                f"config declares {r} regressors but no regressor values given"
            )
        if regressors.shape[-1] != r:
            raise ValueError(
                f"regressors last dim {regressors.shape[-1]} != {r} declared"
            )
        x = jnp.concatenate([x, regressors.astype(x.dtype)], axis=-1)
    elif regressors is not None and regressors.shape[-1] != 0:
        raise ValueError("regressor values given but config declares none")
    return x
