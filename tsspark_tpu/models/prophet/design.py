"""Fit-data assembly and the batched forward model.

This is the TPU-native replacement for the reference's per-series
design-matrix build in ``tsspark.fit.prophet`` (BASELINE.json:5): instead of
building one small design matrix per series inside a Spark ``mapPartitions``
UDF, we build *one* set of padded, batched tensors for the whole series batch
and evaluate the model as a handful of large fused ops:

  * seasonal component — ``(B, Fs) @ (Fs, T)`` matmul (MXU) when the batch
    shares a calendar grid, batched matmul otherwise;
  * regressor component — small batched einsum (per-series covariates);
  * trend — cumsum + gather (see trend.py), VPU-bound, O(B*T).

Everything is a NamedTuple of arrays so it jits, vmaps, and shards cleanly.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp

from tsspark_tpu.config import ProphetConfig
from tsspark_tpu.models.prophet import seasonality, trend
from tsspark_tpu.models.prophet.params import ProphetParams, unpack


class ScalingMeta(NamedTuple):
    """Per-series affine scalings needed to map predictions back to data units."""

    y_scale: jnp.ndarray        # (B,)
    floor: jnp.ndarray          # (B,)
    ds_start: jnp.ndarray       # (B,) absolute days of first observation
    ds_span: jnp.ndarray        # (B,) observed span in days (>= 1 step)
    reg_mean: jnp.ndarray       # (B, R) regressor standardization mean
    reg_std: jnp.ndarray        # (B, R) regressor standardization std


class FitData(NamedTuple):
    """Everything the batched loss needs, padded to (B, T).

    X_season may be (T, Fs) — shared calendar grid, the fast path — or
    (B, T, Fs).  X_reg is (B, T, R) (external features are per-series).
    """

    t: jnp.ndarray            # (B, T) per-series scaled time
    y: jnp.ndarray            # (B, T) scaled observations (0 where masked)
    mask: jnp.ndarray         # (B, T) 1.0 where observed
    s: jnp.ndarray            # (B, n_cp) changepoints in scaled time
    cap: jnp.ndarray          # (B, T) scaled capacity (ones unless logistic)
    X_season: jnp.ndarray     # (T, Fs) or (B, T, Fs)
    X_reg: jnp.ndarray        # (B, T, R)
    prior_scales: jnp.ndarray  # (F,) per-feature normal prior scale
    mult_mask: jnp.ndarray    # (F,) 1.0 where the feature is multiplicative


def _component(beta: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """beta (B, F) times features (T, F) or (B, T, F) -> (B, T)."""
    if x.shape[-1] == 0:
        return jnp.zeros(beta.shape[:-1] + x.shape[-2:-1], beta.dtype)
    if x.ndim == 2:
        return beta @ x.T
    return jnp.einsum("bf,btf->bt", beta, x)


def trend_fn(
    params: ProphetParams, data: FitData, config: ProphetConfig
) -> jnp.ndarray:
    if config.growth == "linear":
        return trend.piecewise_linear(data.t, params.k, params.m, params.delta, data.s)
    if config.growth == "logistic":
        return trend.logistic(
            data.t, data.cap, params.k, params.m, params.delta, data.s
        )
    return trend.flat(data.t, params.m)


def seasonal_split(
    theta: jnp.ndarray, data: FitData, config: ProphetConfig
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(additive_total, multiplicative_total) in scaled units, each (B, T)."""
    p = unpack(theta, config)
    fs = config.num_seasonal_features
    beta_season, beta_reg = p.beta[..., :fs], p.beta[..., fs:]
    mm_season, mm_reg = data.mult_mask[:fs], data.mult_mask[fs:]

    add = _component(beta_season * (1.0 - mm_season), data.X_season)
    add = add + _component(beta_reg * (1.0 - mm_reg), data.X_reg)
    mult = _component(beta_season * mm_season, data.X_season)
    mult = mult + _component(beta_reg * mm_reg, data.X_reg)
    return add, mult


def model_yhat(
    theta: jnp.ndarray, data: FitData, config: ProphetConfig
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Batched forward pass in scaled units.

    Returns (yhat, trend) each (B, T):
      yhat = trend * (1 + X_mult @ beta_mult) + X_add @ beta_add
    """
    p = unpack(theta, config)
    g = trend_fn(p, data, config)
    add, mult = seasonal_split(theta, data, config)
    return g * (1.0 + mult) + add, g


def prepare_fit_data(
    ds: jnp.ndarray,
    y: jnp.ndarray,
    config: ProphetConfig,
    mask: Optional[jnp.ndarray] = None,
    cap: Optional[jnp.ndarray] = None,
    floor: Optional[jnp.ndarray] = None,
    regressors: Optional[jnp.ndarray] = None,
    dtype: jnp.dtype = jnp.float32,
) -> Tuple[FitData, ScalingMeta]:
    """Scale, mask, and assemble a padded batch for fitting.

    Args:
      ds: (T,) shared calendar grid or (B, T) per-series grids, absolute days.
      y:  (B, T) raw observations; NaN marks missing (merged into mask).
      mask: optional (B, T) validity; default = finite(y).
      cap: (B, T) capacities, required for logistic growth (data units).
      floor: (B,) or (B, T) logistic floor, defaults to 0.
      regressors: (B, T, R) raw external regressor values.

    Returns:
      (FitData, ScalingMeta).
    """
    y = jnp.asarray(y, dtype)
    if y.ndim != 2:
        raise ValueError(f"y must be (B, T), got {y.shape}")
    b, t_len = y.shape
    ds = jnp.asarray(ds, dtype)
    ds_b = jnp.broadcast_to(ds, (b, t_len)) if ds.ndim == 1 else ds

    finite = jnp.isfinite(y)
    if mask is None:
        mask = finite.astype(dtype)
    else:
        mask = jnp.asarray(mask, dtype) * finite.astype(dtype)
    y = jnp.where(mask > 0, jnp.nan_to_num(y), 0.0)

    # Per-series observed span -> scaled time in [0, 1].  Fully-masked rows
    # (dummy padding series) fall back to the raw grid span so every
    # downstream quantity stays finite.
    any_obs = mask.sum(axis=-1) > 0
    big = jnp.where(mask > 0, ds_b, jnp.inf)
    small = jnp.where(mask > 0, ds_b, -jnp.inf)
    ds_start = jnp.where(any_obs, jnp.min(big, axis=-1), jnp.min(ds_b, axis=-1))
    ds_end = jnp.where(any_obs, jnp.max(small, axis=-1), jnp.max(ds_b, axis=-1))
    # Span floor = one grid step, so degenerate (single-observation) series
    # keep future scaled times O(1) instead of exploding.
    grid_span = jnp.max(ds_b, axis=-1) - jnp.min(ds_b, axis=-1)
    step = grid_span / jnp.maximum(t_len - 1, 1)
    ds_span = jnp.maximum(ds_end - ds_start, jnp.maximum(step, 1e-9))
    t = (ds_b - ds_start[:, None]) / ds_span[:, None]

    # Per-series y scaling (Prophet absmax scaling; floor only for logistic).
    if floor is None:
        floor_b = jnp.zeros((b,), dtype)
    else:
        floor_b = jnp.asarray(floor, dtype)
        if floor_b.ndim == 2:
            floor_b = floor_b[:, 0]
    y_shift = y - floor_b[:, None]
    y_scale = jnp.max(jnp.abs(y_shift) * mask, axis=-1)
    y_scale = jnp.maximum(y_scale, 1e-10)
    y_s = jnp.where(mask > 0, y_shift / y_scale[:, None], 0.0)

    if config.growth == "logistic":
        if cap is None:
            raise ValueError("logistic growth requires cap")
        cap_s = (jnp.asarray(cap, dtype) - floor_b[:, None]) / y_scale[:, None]
    else:
        cap_s = jnp.ones((b, t_len), dtype)

    # Changepoints: observed span maps to exactly [0, 1] in scaled time.
    s = trend.uniform_changepoints(
        jnp.zeros((b,), dtype),
        jnp.ones((b,), dtype),
        config.n_changepoints,
        config.changepoint_range,
    )

    # Seasonal features from absolute time; shared grid -> shared matrix.
    x_season = seasonality.seasonal_feature_matrix(
        ds if ds.ndim == 1 else ds_b, config.seasonalities
    ).astype(dtype)

    # External regressors: per-series standardization over observed window.
    r = config.num_regressors
    if r:
        if regressors is None:
            raise ValueError(f"config declares {r} regressors but none given")
        reg = jnp.asarray(regressors, dtype)
        if reg.shape != (b, t_len, r):
            raise ValueError(f"regressors shape {reg.shape} != {(b, t_len, r)}")
        n = jnp.maximum(mask.sum(-1), 1.0)[:, None]
        mean = (reg * mask[..., None]).sum(1) / n
        var = (((reg - mean[:, None, :]) ** 2) * mask[..., None]).sum(1) / n
        std = jnp.sqrt(jnp.maximum(var, 0.0))
        # Don't rescale columns the user opted out of, nor (near-)constant
        # or binary-indicator columns (Prophet's standardize='auto' rule).
        opt_out = jnp.asarray(
            [not rc.standardize for rc in config.regressors], bool
        )[None, :]
        skip = opt_out | jnp.all(
            (mask[..., None] == 0) | (reg == 0) | (reg == 1), axis=1
        ) | (std < 1e-8)
        std_eff = jnp.where(skip, 1.0, std)
        mean_eff = jnp.where(skip, 0.0, mean)
        x_reg = (reg - mean_eff[:, None, :]) / std_eff[:, None, :]
    else:
        x_reg = jnp.zeros((b, t_len, 0), dtype)
        mean_eff = jnp.zeros((b, 0), dtype)
        std_eff = jnp.ones((b, 0), dtype)

    data = FitData(
        t=t,
        y=y_s,
        mask=mask,
        s=s,
        cap=cap_s,
        X_season=x_season,
        X_reg=x_reg,
        prior_scales=jnp.asarray(config.feature_prior_scales(), dtype),
        mult_mask=jnp.asarray(
            [1.0 if m else 0.0 for m in config.feature_modes()], dtype
        ),
    )
    meta = ScalingMeta(
        y_scale=y_scale,
        floor=floor_b,
        ds_start=ds_start,
        ds_span=ds_span,
        reg_mean=mean_eff,
        reg_std=std_eff,
    )
    return data, meta
