"""Fit-data assembly and the batched forward model.

This is the TPU-native replacement for the reference's per-series
design-matrix build in ``tsspark.fit.prophet`` (BASELINE.json:5): instead of
building one small design matrix per series inside a Spark ``mapPartitions``
UDF, we build *one* set of padded, batched tensors for the whole series batch
and evaluate the model as a handful of large fused ops:

  * seasonal component — ``(B, Fs) @ (Fs, T)`` matmul (MXU) when the batch
    shares a calendar grid, batched matmul otherwise;
  * regressor component — small batched einsum (per-series covariates);
  * trend — fused compare-multiply-reduce over the changepoint axis (see
    trend.py; gather-free), VPU-bound, O(B*T) HBM traffic.

Everything is a NamedTuple of arrays so it jits, vmaps, and shards cleanly.
"""

from __future__ import annotations

import contextlib as _contextlib
from typing import NamedTuple, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from tsspark_tpu.config import ProphetConfig
from tsspark_tpu.models.prophet import seasonality, trend
from tsspark_tpu.models.prophet.params import ProphetParams, unpack


class ScalingMeta(NamedTuple):
    """Per-series affine scalings needed to map predictions back to data units.

    Fields are HOST numpy float64: ``ds_start`` is absolute epoch days
    (~2e4), where float32's ulp is ~5 minutes — computing the time maps
    (fit-time ``t``, predict-time ``t``, warm-start transfer) demands the
    subtraction happen in f64 *before* anything is cast to the device f32.
    """

    y_scale: np.ndarray        # (B,)
    floor: np.ndarray          # (B,)
    ds_start: np.ndarray       # (B,) absolute days of first observation
    ds_span: np.ndarray        # (B,) observed span in days (>= 1 step)
    reg_mean: np.ndarray       # (B, R) regressor standardization mean
    reg_std: np.ndarray        # (B, R) regressor standardization std
    changepoints: np.ndarray   # (B, n_cp) changepoint locations, scaled time


class FitData(NamedTuple):
    """Everything the batched loss needs, padded to (B, T).

    X_season may be (T, Fs) — shared calendar grid, the fast path — or
    (B, T, Fs).  X_reg is (B, T, R) (external features are per-series).
    """

    t: jnp.ndarray            # (B, T) per-series scaled time
    y: jnp.ndarray            # (B, T) scaled observations (0 where masked)
    mask: jnp.ndarray         # (B, T) 1.0 where observed
    s: jnp.ndarray            # (B, n_cp) changepoints in scaled time
    cap: jnp.ndarray          # (B, T) scaled capacity (ones unless logistic)
    X_season: jnp.ndarray     # (T, Fs) or (B, T, Fs)
    X_reg: jnp.ndarray        # (B, T, R)
    prior_scales: jnp.ndarray  # (F,) per-feature normal prior scale
    mult_mask: jnp.ndarray    # (F,) 1.0 where the feature is multiplicative


class PackedFitData(NamedTuple):
    """Transfer-optimized FitData for shared-calendar batches.

    On a tunneled single-chip runtime the host->device copy is the dominant
    per-chunk cost once the fit itself is fast (measured round 4 at chunk
    2048x1941: ~0.63 s of device solve vs 1-5 s of transfer).  This form
    ships the same information in a fraction of the bytes, bit-exactly:

      * the validity mask is not shipped at all — masked cells of ``y``
        travel as NaN and the device recovers ``mask = isfinite(y)`` and
        ``y = where(mask, y, 0)``, both bit-exact because prepare_fit_data
        zeroes masked observations and the packer requires an exact 0/1
        mask;
      * exact-0/1 indicator regressor columns (holidays, promos) are
        bit-packed 8 time steps per byte (``X_reg_bits``, 32x smaller than
        f32; unpacked on device with shifts);
      * ``t`` is not shipped — the (B, T) scaled-time grid is an affine
        map of the SHARED calendar, reconstructed on device from the (T,)
        relative grid and two (B,) per-series scalars (error ~1e-6 in
        [0, 1] scaled units, far below the daily grid spacing ~5e-4);
      * ``cap`` collapses to (B, 1) for non-logistic growth (it is all-ones
        and unused by the trend there).

    ``unpack_fit_data`` runs INSIDE the fit program, so the expansion costs
    no extra dispatch and the expanded tensors never cross the tunnel.
    """

    y: jnp.ndarray            # (B, T) f32 scaled observations; NaN = masked
    ds_rel: jnp.ndarray       # (T,) f32 shared grid minus grid[0]
    t_off: jnp.ndarray        # (B,) f32: (ds_start - grid[0]) / ds_span
    t_inv_span: jnp.ndarray   # (B,) f32: 1 / ds_span
    s: jnp.ndarray            # (B, n_cp) f32 changepoints (scaled time)
    cap: jnp.ndarray          # (B, 1) f32, or (B, T) f32 for logistic
    X_season: jnp.ndarray     # (T, Fs) or (B, T, Fs) f32
    X_reg: jnp.ndarray        # (B, T, R - K) f32 non-indicator columns
    X_reg_bits: jnp.ndarray   # (B, ceil(T/8), K) u8 bit-packed indicators
    prior_scales: jnp.ndarray
    mult_mask: jnp.ndarray


# PackedFitData fields that ALWAYS carry a leading per-series batch axis —
# the fields a row gather/concat over series must touch.  X_season is NOT
# here: it is (T, Fs) shared for plain seasonalities but (B, T, Fs) when
# conditional seasonalities make it per-series — consumers must branch on
# its ndim.  Kept next to the NamedTuple so a new per-series field gets
# added here in the same change (consumers: bench.py's device-resident
# phase-2 gather).
PACKED_PER_SERIES_FIELDS = (
    "y", "t_off", "t_inv_span", "s", "cap", "X_reg", "X_reg_bits",
)


def take_fit_data(data: FitData, idx: jnp.ndarray) -> FitData:
    """Gather a row subset of a FitData batch (series axis): the design-
    tensor half of the compaction primitive (``ops.lbfgs.take_state``).

    Shared leaves — a (T, Fs) calendar seasonal matrix, the prior
    vectors — are carried as-is; everything per-series is gathered on
    axis 0.  Gathered rows are bitwise copies, so a solve continued on
    the subset reproduces each selected series' full-width trajectory
    exactly.
    """
    idx = jnp.asarray(idx)
    take = lambda a: jnp.take(a, idx, axis=0)
    return FitData(
        t=take(data.t),
        y=take(data.y),
        mask=take(data.mask),
        s=take(data.s),
        cap=take(data.cap),
        X_season=(
            data.X_season if data.X_season.ndim == 2 else take(data.X_season)
        ),
        X_reg=take(data.X_reg),
        prior_scales=data.prior_scales,
        mult_mask=data.mult_mask,
    )


def _bitpack_time(a: np.ndarray) -> np.ndarray:
    """(B, T, K) exact-0/1 array -> (B, ceil(T/8), K) uint8, little-endian
    bits along the time axis (host side, numpy)."""
    b, t, k = a.shape
    tb = (t + 7) // 8
    if k == 0:
        return np.zeros((b, tb, 0), np.uint8)
    pad = tb * 8 - t
    u8 = np.asarray(a, np.uint8)
    if pad:
        u8 = np.concatenate([u8, np.zeros((b, pad, k), np.uint8)], axis=1)
    w = (1 << np.arange(8, dtype=np.uint16)).reshape(1, 1, 8, 1)
    return (
        (u8.reshape(b, tb, 8, k).astype(np.uint16) * w).sum(axis=2)
    ).astype(np.uint8)


def _bitunpack_time(p: jnp.ndarray, t: int) -> jnp.ndarray:
    """(B, ceil(T/8), K) uint8 -> (B, T, K) uint8 of 0/1 (traced; runs
    inside the fit program — a few elementwise u8 ops, fused by XLA)."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (p[:, :, None, :] >> shifts[None, None, :, None]) & jnp.uint8(1)
    return bits.reshape(p.shape[0], -1, p.shape[-1])[:, :t, :]


def _indicator_reg_cols(x_reg: np.ndarray) -> Tuple[int, ...]:
    """Columns of (B, T, R) whose every value is exactly 0.0 or 1.0 —
    holiday / promo style indicators that survive a uint8 round trip
    bit-for-bit (unstandardized: prepare_fit_data's auto rule never rescales
    binary columns, so post-prep values are still exact 0/1)."""
    return tuple(
        j for j in range(x_reg.shape[-1])
        if bool(np.all((x_reg[..., j] == 0.0) | (x_reg[..., j] == 1.0)))
    )


def packable_batch(ds, mask) -> bool:
    """THE packed-transit eligibility predicate: a shared (T,) calendar
    grid and an exact 0/1 mask (fractional observation weights need the
    plain FitData path).  One definition shared by ProphetModel.fit,
    TpuBackend's mesh routing, and the resilient-fit gate so the
    single-device, sharded, and orchestrated paths can never decide
    packability differently."""
    if np.asarray(ds).ndim != 1:
        return False
    if mask is None:
        return True  # prepare derives an isfinite mask, exactly 0/1
    m = np.asarray(mask)
    return bool(np.all((m == 0.0) | (m == 1.0)))


def pack_fit_data(
    data: FitData,
    meta: ScalingMeta,
    ds: np.ndarray,
    reg_u8_cols: Optional[Tuple[int, ...]] = None,
    collapse_cap: bool = False,
) -> Tuple[PackedFitData, Tuple[int, ...]]:
    """Host-side (numpy) packing of an ``as_numpy=True`` prepared batch.

    ``ds`` is the shared (T,) calendar grid in absolute days (float64: the
    ds - ds[0] subtraction must happen before the f32 cast, same rationale
    as ScalingMeta).  Requires a shared grid and an exact 0/1 mask (the
    NaN-fold transit only encodes observed/missing, so it would silently
    DROP fractionally-weighted observations instead of down-weighting
    them); batches violating either keep the plain FitData path.

    ``reg_u8_cols``: which X_reg columns travel bit-packed.  None
    auto-detects exact-0/1 columns — fine for a one-shot fit, but chunked
    pipelines must detect ONCE on the full dataset and pass the result
    here: the tuple is a static argument of the jitted consumer, and a
    chunk whose continuous column coincidentally lands in {0, 1} would
    otherwise flip it and silently recompile mid-run.

    Returns (packed, reg_u8_cols): pass the tuple to the jitted consumer
    as a static arg so ``unpack_fit_data`` can reassemble X_reg in its
    original column order.
    """
    ds64 = np.asarray(ds, np.float64)
    if ds64.ndim != 1:
        raise ValueError("pack_fit_data requires a shared (T,) grid")
    mask_np = np.asarray(data.mask)
    if not np.all((mask_np == 0.0) | (mask_np == 1.0)):
        raise ValueError(
            "pack_fit_data requires an exact 0/1 mask; fractional "
            "observation weights need the plain FitData path"
        )
    y_np = np.asarray(data.y)
    if not np.all(np.isfinite(y_np[mask_np > 0])):
        raise ValueError(
            "pack_fit_data requires finite y wherever mask == 1: the "
            "NaN-fold transit recovers the mask as isfinite(y), so a "
            "non-finite OBSERVED cell would silently become masked on "
            "device while the plain FitData path propagates it into the "
            "loss"
        )
    f32 = np.float32
    cap = np.asarray(data.cap)
    # Collapse is a STATIC (config-level) decision, not a data one: for
    # non-logistic growth cap is always all-ones, so callers pass
    # collapse_cap=True; deciding from chunk values would let one chunk
    # with a time-varying cap flip the compiled input shape mid-stream.
    if collapse_cap and cap.shape[-1] != 1:
        cap = cap[..., :1]
    x_reg = np.asarray(data.X_reg, f32)
    u8_cols = (
        _indicator_reg_cols(x_reg) if reg_u8_cols is None
        else tuple(reg_u8_cols)
    )
    if reg_u8_cols is not None:
        bad = [
            j for j in u8_cols
            if not np.all((x_reg[..., j] == 0.0) | (x_reg[..., j] == 1.0))
        ]
        if bad:
            raise ValueError(
                f"reg_u8_cols {bad} contain non-0/1 values in this batch; "
                "the bit-packed transit would corrupt them"
            )
    f32_cols = tuple(j for j in range(x_reg.shape[-1]) if j not in u8_cols)
    # Mask folded into y as NaN: bit-exact because prepare_fit_data zeroes
    # masked cells (y is "0 where masked" by the FitData contract), so the
    # device-side where(isfinite(y), y, 0) reproduces data.y exactly and
    # isfinite(y) reproduces the exact 0/1 mask checked above.
    y_nan = np.where(
        mask_np > 0, np.asarray(data.y, f32), np.float32(np.nan)
    ).astype(f32)
    packed = PackedFitData(
        y=y_nan,
        ds_rel=(ds64 - ds64[0]).astype(f32),
        t_off=((meta.ds_start - ds64[0]) / meta.ds_span).astype(f32),
        t_inv_span=(1.0 / meta.ds_span).astype(f32),
        s=np.asarray(data.s, f32),
        cap=cap.astype(f32),
        X_season=np.asarray(data.X_season, f32),
        X_reg=np.ascontiguousarray(x_reg[..., f32_cols]),
        X_reg_bits=_bitpack_time(
            np.ascontiguousarray(x_reg[..., u8_cols])
        ),
        prior_scales=np.asarray(data.prior_scales, f32),
        mult_mask=np.asarray(data.mult_mask, f32),
    )
    return packed, u8_cols


def unpack_fit_data(
    packed: PackedFitData, reg_u8_cols: Tuple[int, ...] = ()
) -> FitData:
    """Rebuild FitData on device (traced; runs inside the fit program)."""
    t = (
        packed.ds_rel[None, :] * packed.t_inv_span[:, None]
        - packed.t_off[:, None]
    )
    finite = jnp.isfinite(packed.y)
    y = jnp.where(finite, packed.y, jnp.zeros_like(packed.y))
    mask = finite.astype(y.dtype)
    cap = packed.cap
    if cap.shape[-1] == 1:
        cap = jnp.broadcast_to(cap, packed.y.shape)
    r = packed.X_reg.shape[-1] + packed.X_reg_bits.shape[-1]
    f32_cols = tuple(j for j in range(r) if j not in reg_u8_cols)
    if reg_u8_cols:
        x_u8 = _bitunpack_time(packed.X_reg_bits, packed.y.shape[-1])
    cols = [None] * r
    for i, j in enumerate(f32_cols):
        cols[j] = packed.X_reg[..., i]
    for i, j in enumerate(reg_u8_cols):
        cols[j] = x_u8[..., i].astype(y.dtype)
    x_reg = (
        jnp.stack(cols, axis=-1) if cols
        else jnp.zeros(packed.y.shape + (0,), packed.y.dtype)
    )
    return FitData(
        t=t,
        y=y,
        mask=mask,
        s=packed.s,
        cap=cap,
        X_season=packed.X_season,
        X_reg=x_reg,
        prior_scales=packed.prior_scales,
        mult_mask=packed.mult_mask,
    )


# Full-f32 accumulation for every model matmul/einsum: TPU MXU contractions
# on f32 inputs default to single-pass bfloat16 (~4e-3 relative error),
# which measurably moves optima vs the f64-free CPU oracle (the parity
# criterion, BASELINE.json:2).  These contractions are bandwidth-bound at
# our shapes, so the extra MXU passes are effectively free.
_PREC = jax.lax.Precision.HIGHEST


def _component(beta: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """beta (B, F) times features (T, F) or (B, T, F) -> (B, T)."""
    if x.shape[-1] == 0:
        return jnp.zeros(beta.shape[:-1] + x.shape[-2:-1], beta.dtype)
    if x.ndim == 2:
        return jnp.einsum("bf,tf->bt", beta, x, precision=_PREC)
    return jnp.einsum("bf,btf->bt", beta, x, precision=_PREC)


def trend_fn(
    params: ProphetParams, data: FitData, config: ProphetConfig
) -> jnp.ndarray:
    if config.growth == "linear":
        return trend.piecewise_linear(data.t, params.k, params.m, params.delta, data.s)
    if config.growth == "logistic":
        return trend.logistic(
            data.t, data.cap, params.k, params.m, params.delta, data.s
        )
    return trend.flat(data.t, params.m)


def seasonal_split(
    theta: jnp.ndarray, data: FitData, config: ProphetConfig
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(additive_total, multiplicative_total) in scaled units, each (B, T)."""
    p = unpack(theta, config)
    fs = config.num_seasonal_features
    beta_season, beta_reg = p.beta[..., :fs], p.beta[..., fs:]
    mm_season, mm_reg = data.mult_mask[:fs], data.mult_mask[fs:]

    add = _component(beta_season * (1.0 - mm_season), data.X_season)
    add = add + _component(beta_reg * (1.0 - mm_reg), data.X_reg)
    mult = _component(beta_season * mm_season, data.X_season)
    mult = mult + _component(beta_reg * mm_reg, data.X_reg)
    return add, mult


def model_yhat(
    theta: jnp.ndarray, data: FitData, config: ProphetConfig
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Batched forward pass in scaled units.

    Returns (yhat, trend) each (B, T):
      yhat = trend * (1 + X_mult @ beta_mult) + X_add @ beta_add
    """
    p = unpack(theta, config)
    g = trend_fn(p, data, config)
    add, mult = seasonal_split(theta, data, config)
    return g * (1.0 + mult) + add, g


# Chunked backends fit one batch as many prepare_fit_data calls; this flag
# (set via the context manager) silences the per-chunk out-of-span warning
# so the backend can emit ONE full-batch warning instead of dozens of
# near-identical per-chunk copies whose counts never describe the batch.
_CP_SPAN_WARNING_DISABLED = False


@_contextlib.contextmanager
def changepoint_span_warning_suppressed():
    global _CP_SPAN_WARNING_DISABLED
    prev = _CP_SPAN_WARNING_DISABLED
    _CP_SPAN_WARNING_DISABLED = True
    try:
        yield
    finally:
        _CP_SPAN_WARNING_DISABLED = prev


def _warn_out_of_span(s_scaled: np.ndarray, has_obs: np.ndarray,
                      b: int) -> None:
    out = ((s_scaled <= 0.0) | (s_scaled >= 1.0)) & has_obs[:, None]
    if np.any(out):
        import warnings

        warnings.warn(
            f"{int(out.any(axis=1).sum())} of {b} series have "
            f"explicit changepoints outside their observed span "
            f"({int(out.sum())} (series, changepoint) pairs); these "
            "are inert or shift the base trend rather than kinking it",
            stacklevel=3,
        )


def warn_out_of_span_changepoints(config, ds, y, mask) -> None:
    """Full-batch out-of-span check for chunked backends (see above).

    Computes each observed series' raw-day span directly (the same
    first/last-observation convention as prepare_fit_data) and warns once
    with whole-batch counts.
    """
    if config.changepoints is None or _CP_SPAN_WARNING_DISABLED:
        return
    y = np.asarray(y)
    m = (np.asarray(mask) > 0) if mask is not None else np.isfinite(y)
    b, t_len = m.shape
    has_obs = m.any(axis=-1)
    i0 = m.argmax(axis=-1)
    i1 = t_len - 1 - m[:, ::-1].argmax(axis=-1)
    dsb = np.asarray(ds, np.float64)
    if dsb.ndim == 1:
        dsb = np.broadcast_to(dsb, (b, t_len))
    rows = np.arange(b)
    start = dsb[rows, i0]
    span = np.maximum(dsb[rows, i1] - start, 1e-9)
    cp = np.asarray(config.changepoints, np.float64)
    s = (cp[None, :] - start[:, None]) / span[:, None]
    _warn_out_of_span(s, has_obs, b)


def prepare_fit_data(
    ds: jnp.ndarray,
    y: jnp.ndarray,
    config: ProphetConfig,
    mask: Optional[jnp.ndarray] = None,
    cap: Optional[jnp.ndarray] = None,
    floor: Optional[jnp.ndarray] = None,
    regressors: Optional[jnp.ndarray] = None,
    conditions=None,
    dtype: jnp.dtype = jnp.float32,
    as_numpy: bool = False,
) -> Tuple[FitData, ScalingMeta]:
    """Scale, mask, and assemble a padded batch for fitting.

    Args:
      ds: (T,) shared calendar grid or (B, T) per-series grids, absolute days.
      y:  (B, T) raw observations; NaN marks missing (merged into mask).
      mask: optional (B, T) validity; default = finite(y).
      cap: (B, T) capacities, required for logistic growth (data units).
      floor: (B,) or (B, T) logistic floor, defaults to 0.
      regressors: (B, T, R) raw external regressor values.
      conditions: dict condition_name -> (B, T) truthy values, required when
        any seasonality has a condition_name (seasonality.apply_conditions).
      as_numpy: keep the FitData leaves as host numpy arrays instead of
        device arrays.  For prefetch pipelines on a single-device tunnel:
        a background prep thread must NOT issue device transfers (they
        queue behind the in-flight fit program and serialize the whole
        pipeline); the jitted fit call transfers numpy leaves itself at
        dispatch time on the caller's thread.

    Returns:
      (FitData, ScalingMeta).
    """
    # All scaling statistics are computed HOST-SIDE in float64: ds carries
    # absolute epoch days (~2e4) where float32 quantizes to ~5 minutes, so
    # the (ds - ds_start) subtraction must happen before any f32 cast.  The
    # resulting O(1) tensors are then shipped to the device as f32.
    y_np = np.asarray(y, np.float64)
    if y_np.ndim != 2:
        raise ValueError(f"y must be (B, T), got {y_np.shape}")
    b, t_len = y_np.shape
    ds_np = np.asarray(ds, np.float64)
    shared_grid = ds_np.ndim == 1
    ds_b = np.broadcast_to(ds_np, (b, t_len)) if shared_grid else ds_np

    finite = np.isfinite(y_np)
    if mask is None:
        mask_np = finite.astype(np.float64)
    else:
        mask_np = np.asarray(mask, np.float64) * finite
    y_np = np.where(mask_np > 0, np.nan_to_num(y_np), 0.0)

    # Per-series observed span -> scaled time in [0, 1].  Fully-masked rows
    # (dummy padding series) fall back to the raw grid span so every
    # downstream quantity stays finite.
    any_obs = mask_np.sum(axis=-1) > 0
    big = np.where(mask_np > 0, ds_b, np.inf)
    small = np.where(mask_np > 0, ds_b, -np.inf)
    ds_start = np.where(any_obs, big.min(axis=-1), ds_b.min(axis=-1))
    ds_end = np.where(any_obs, small.max(axis=-1), ds_b.max(axis=-1))
    # Span floor = one grid step, so degenerate (single-observation) series
    # keep future scaled times O(1) instead of exploding.
    grid_span = ds_b.max(axis=-1) - ds_b.min(axis=-1)
    step = grid_span / max(t_len - 1, 1)
    ds_span = np.maximum(ds_end - ds_start, np.maximum(step, 1e-9))
    t = (ds_b - ds_start[:, None]) / ds_span[:, None]

    # Per-series y scaling (Prophet absmax scaling; floor only for logistic).
    if floor is None:
        floor_b = np.zeros((b,))
    else:
        floor_b = np.asarray(floor, np.float64)
        if floor_b.ndim == 2:
            floor_b = floor_b[:, 0]
    y_shift = y_np - floor_b[:, None]
    y_scale = np.maximum(np.max(np.abs(y_shift) * mask_np, axis=-1), 1e-10)
    y_s = np.where(mask_np > 0, y_shift / y_scale[:, None], 0.0)

    if config.growth == "logistic":
        if cap is None:
            raise ValueError("logistic growth requires cap")
        cap_s = (np.asarray(cap, np.float64) - floor_b[:, None]) \
            / y_scale[:, None]
    else:
        cap_s = np.ones((b, t_len))

    # Changepoints in scaled time (the observed span maps to exactly [0, 1]).
    # Host numpy (like every other prep quantity): eager jnp ops here would
    # pay a tiny-XLA-compile + tunnel dispatch on the per-chunk fit path.
    # The chosen grid is recorded in ScalingMeta so prediction, warm-start
    # transfer, and checkpoint restore all reuse the FIT-time locations.
    if config.changepoints is not None:
        # Explicit absolute-day locations (Prophet's ``changepoints=``):
        # shared in absolute time, mapped into each series' scaled time.
        cp = np.asarray(config.changepoints, np.float64)
        s_f64 = (cp[None, :] - ds_start[:, None]) / ds_span[:, None]
        # Upstream Prophet raises when a changepoint falls outside the
        # training window; in a batched fit one shared date can be inside
        # one series' span and outside another's, so warn (loudly, with
        # counts) instead of failing the whole batch.  s < 0 is active
        # from t=0 (perturbs the base slope's prior semantics); s > 1 is
        # inert in-sample but kinks the forecast horizon.  Counting skips
        # rows with no observations (inert chunk-padding dummies), and
        # chunked backends suppress this per-chunk copy in favor of ONE
        # full-batch warning (warn_out_of_span_changepoints).
        if not _CP_SPAN_WARNING_DISABLED:
            has_obs = mask_np.any(axis=-1)
            _warn_out_of_span(s_f64, has_obs, b)
        s = s_f64.astype(dtype)
    elif config.changepoint_placement == "quantile":
        s = quantile_changepoints(
            t, mask_np, config.n_changepoints, config.changepoint_range
        ).astype(dtype)
    else:
        s = trend.uniform_changepoints(
            np.zeros((b,), dtype),
            np.ones((b,), dtype),
            config.n_changepoints,
            config.changepoint_range,
        )

    # Seasonal features from absolute time; shared grid -> shared matrix.
    # (f64 host input: the period fold inside keeps full phase precision.)
    x_season = seasonality.seasonal_feature_matrix(
        ds_np if shared_grid else ds_b, config.seasonalities
    ).astype(dtype)
    # Conditional blocks force a per-series matrix (conditions are data).
    x_season = seasonality.apply_conditions(
        x_season, config.seasonalities, conditions, b
    )

    # External regressors: per-series standardization over observed window.
    r = config.num_regressors
    if r:
        if regressors is None:
            raise ValueError(f"config declares {r} regressors but none given")
        reg = np.asarray(regressors, np.float64)
        if reg.shape != (b, t_len, r):
            raise ValueError(f"regressors shape {reg.shape} != {(b, t_len, r)}")
        n = np.maximum(mask_np.sum(-1), 1.0)[:, None]
        mean = (reg * mask_np[..., None]).sum(1) / n
        var = (((reg - mean[:, None, :]) ** 2) * mask_np[..., None]).sum(1) / n
        std = np.sqrt(np.maximum(var, 0.0))
        # Don't rescale columns the user opted out of, nor (near-)constant
        # or binary-indicator columns (Prophet's standardize='auto' rule).
        opt_out = np.asarray(
            [not rc.standardize for rc in config.regressors], bool
        )[None, :]
        skip = opt_out | np.all(
            (mask_np[..., None] == 0) | (reg == 0) | (reg == 1), axis=1
        ) | (std < 1e-8)
        std_eff = np.where(skip, 1.0, std)
        mean_eff = np.where(skip, 0.0, mean)
        x_reg = (reg - mean_eff[:, None, :]) / std_eff[:, None, :]
    else:
        x_reg = np.zeros((b, t_len, 0))
        mean_eff = np.zeros((b, 0))
        std_eff = np.ones((b, 0))

    xp_cast = (lambda a: np.asarray(a, dtype)) if as_numpy \
        else (lambda a: jnp.asarray(a, dtype))
    data = FitData(
        t=xp_cast(t),
        y=xp_cast(y_s),
        mask=xp_cast(mask_np),
        s=np.asarray(s, dtype) if as_numpy else jnp.asarray(s, dtype),
        cap=xp_cast(cap_s),
        X_season=x_season,
        X_reg=xp_cast(x_reg),
        prior_scales=xp_cast(config.feature_prior_scales()),
        mult_mask=xp_cast(
            [1.0 if m else 0.0 for m in config.feature_modes()]
        ),
    )
    meta = ScalingMeta(
        y_scale=y_scale,
        floor=floor_b,
        ds_start=ds_start,
        ds_span=ds_span,
        reg_mean=mean_eff,
        reg_std=std_eff,
        changepoints=np.asarray(s, np.float64),
    )
    return data, meta


def quantile_changepoints(
    t: np.ndarray,
    mask: np.ndarray,
    n_changepoints: int,
    changepoint_range: float,
) -> np.ndarray:
    """Per-series changepoints at observed-timestamp quantiles (host numpy).

    Mirrors public Prophet's placement: the first ``changepoint_range``
    fraction of each series' OBSERVED rows, with changepoints at evenly
    spaced order statistics of those timestamps.  On a regular grid this
    coincides with the uniform grid; on irregular grids (bursty sampling,
    gaps) it puts trend flexibility where the data actually is.

    Args:
      t: (B, T) scaled times; mask: (B, T) 1.0 where observed.
    Returns:
      (B, n_changepoints) sorted changepoint locations in scaled time.
    """
    b, t_len = t.shape
    if n_changepoints == 0:
        return np.zeros((b, 0), t.dtype)
    # Observed times sorted to the front; padding/missing rows go to +inf.
    sorted_t = np.sort(np.where(mask > 0, t, np.inf), axis=1)
    n_obs = (mask > 0).sum(axis=1)
    hist = np.floor(n_obs * changepoint_range).astype(np.int64)
    # Order-statistic indexes j/n_cp of the first `hist` observations,
    # skipping index 0 (a changepoint at the first observation is
    # unidentifiable) — Prophet's np.linspace(0, hist-1, n_cp+1)[1:].
    fracs = np.arange(1, n_changepoints + 1, dtype=np.float64) / n_changepoints
    idx = np.round(np.maximum(hist - 1, 0)[:, None] * fracs[None, :]).astype(
        np.int64
    )
    q = np.take_along_axis(sorted_t, np.minimum(idx, t_len - 1), axis=1)
    # Degenerate series — fully masked (q non-finite) or too few observed
    # rows to spread a grid over (hist < 2, which would stack every
    # changepoint on one timestamp and make all delta columns colinear) —
    # fall back to the uniform grid.  Ties between neighboring changepoints
    # on merely sparse series are retained (Prophet shrinks n_changepoints
    # instead, but per-series feature counts would break the batched static
    # shapes; coincident changepoints are mathematically benign — their
    # deltas share one location under the same Laplace prior).
    uniform = trend.uniform_changepoints(
        np.zeros((b,), t.dtype), np.ones((b,), t.dtype),
        n_changepoints, changepoint_range,
    )
    bad = (hist < 2)[:, None] | ~np.isfinite(q)
    return np.where(bad, uniform, q)
