"""Parameter layout for the batched Prophet MAP fit.

The solver operates on a flat ``(batch, P)`` float array so the L-BFGS
two-loop recursion is a handful of big fused VPU ops; this module defines the
canonical packing  ``[k, m, log_sigma, delta[0:n_cp], beta[0:F]]``  and
structured views into it.  Slices are static (derived from ProphetConfig), so
everything stays jit/vmap friendly.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from tsspark_tpu.config import ProphetConfig


class ProphetParams(NamedTuple):
    """Structured view of one (or a batch of) parameter vector(s).

    Shapes below are for a batch of B series; unbatched arrays drop the
    leading axis.
    """

    k: jnp.ndarray          # (B,)   base trend growth rate
    m: jnp.ndarray          # (B,)   trend offset
    log_sigma: jnp.ndarray  # (B,)   log observation noise
    delta: jnp.ndarray      # (B, n_changepoints) changepoint rate adjustments
    beta: jnp.ndarray       # (B, F) seasonal + regressor coefficients


def unpack(theta: jnp.ndarray, config: ProphetConfig) -> ProphetParams:
    """Split a flat (..., P) parameter array into structured fields."""
    n_cp = config.n_changepoints
    f = config.num_features
    if theta.shape[-1] != 3 + n_cp + f:
        raise ValueError(
            f"theta last dim {theta.shape[-1]} != expected {3 + n_cp + f}"
        )
    return ProphetParams(
        k=theta[..., 0],
        m=theta[..., 1],
        log_sigma=theta[..., 2],
        delta=theta[..., 3 : 3 + n_cp],
        beta=theta[..., 3 + n_cp :],
    )


def pack(params: ProphetParams) -> jnp.ndarray:
    """Inverse of :func:`unpack`."""
    return jnp.concatenate(
        [
            params.k[..., None],
            params.m[..., None],
            params.log_sigma[..., None],
            params.delta,
            params.beta,
        ],
        axis=-1,
    )


def init_theta(
    config: ProphetConfig,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    t: jnp.ndarray,
) -> jnp.ndarray:
    """Data-driven initialization, batched over series.

    Mirrors Prophet's initializer: k/m from the endpoints of the (scaled)
    series, deltas and betas at zero, sigma at the masked std of y.

    Args:
      y:    (B, T) scaled observations (already divided by per-series scale).
      mask: (B, T) 1.0 where observed.
      t:    (B, T) scaled time in [0, 1] (per series).

    Returns:
      (B, P) flat initial parameters.
    """
    eps = 1e-8
    n = jnp.maximum(mask.sum(axis=-1), 1.0)

    # First/last observed values and times per series (masked argmin/argmax).
    big = jnp.where(mask > 0, t, jnp.inf)
    small = jnp.where(mask > 0, t, -jnp.inf)
    i0 = jnp.argmin(big, axis=-1)
    i1 = jnp.argmax(small, axis=-1)
    b_idx = jnp.arange(y.shape[0])
    t0, t1 = t[b_idx, i0], t[b_idx, i1]
    y0, y1 = y[b_idx, i0], y[b_idx, i1]

    k0 = (y1 - y0) / jnp.maximum(t1 - t0, eps)
    m0 = y0 - k0 * t0

    mean = (y * mask).sum(axis=-1) / n
    var = (((y - mean[:, None]) ** 2) * mask).sum(axis=-1) / n
    sigma0 = jnp.sqrt(jnp.maximum(var, eps))
    log_sigma0 = jnp.log(jnp.maximum(sigma0, 1e-3))

    batch = y.shape[0]
    return pack(
        ProphetParams(
            k=k0,
            m=m0,
            log_sigma=log_sigma0,
            delta=jnp.zeros((batch, config.n_changepoints), y.dtype),
            beta=jnp.zeros((batch, config.num_features), y.dtype),
        )
    )
