"""Solver initialization: closed-form ridge warm start for the MAP fit.

The reference's per-series scipy L-BFGS (``tsspark.fit.prophet``,
BASELINE.json:5) starts from Prophet's endpoint heuristic and pays ~10^2
iterations per series; fanned out over Spark that cost hides inside the
executor pool.  On TPU the iteration count is the wall-clock, so we spend a
few MXU matmuls to start next to the optimum instead:

For additive composition the Prophet mean is LINEAR in every parameter
except the observation noise:

    yhat = k*t + m + sum_j delta_j * relu(t - s_j) + X @ beta

so the MAP problem with the Laplace changepoint prior replaced by its
Gaussian moment-match is a batched masked ridge regression — one
``(B, P, P)`` Gram build (a big batched matmul, ideal MXU shape) plus a
batched Cholesky solve.  L-BFGS then only has to account for the
Laplace-vs-Gaussian prior difference and the sigma coupling, which takes
O(10) iterations instead of O(100).

Non-additive cases degrade gracefully: multiplicative features are treated
as additive for the init (exact at small seasonal amplitude), and non-linear
growth (logistic/flat) keeps the endpoint heuristic for (k, m) and
ridge-solves only the feature betas against the de-trended target.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tsspark_tpu.config import ProphetConfig, SolverConfig
from tsspark_tpu.models.prophet.params import ProphetParams, init_theta, pack, unpack
from tsspark_tpu.models.prophet import trend as trend_mod


def _masked_sigma(resid: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Masked residual std, floored away from log(0)."""
    n = jnp.maximum(mask.sum(axis=-1), 1.0)
    var = jnp.sum(resid * resid * mask, axis=-1) / n
    return jnp.sqrt(jnp.maximum(var, 1e-8))


def _ridge_solve(
    phi: jnp.ndarray,      # (B, T, Q) design columns
    y: jnp.ndarray,        # (B, T) target
    mask: jnp.ndarray,     # (B, T)
    prior_prec: jnp.ndarray,  # (Q,) Gaussian prior precision per column
    sigma2: jnp.ndarray,   # (B,) noise variance estimate
) -> jnp.ndarray:
    """Batched masked ridge: argmin ||mask*(y - phi w)||^2/sigma2 + w'Λw."""
    phi_m = phi * mask[..., None]
    # (B, Q, Q) Gram and (B, Q) moment — batched matmuls, MXU-friendly.
    gram = jnp.einsum("btp,btq->bpq", phi_m, phi, precision=jax.lax.Precision.HIGHEST)
    rhs = jnp.einsum("btp,bt->bp", phi_m, y, precision=jax.lax.Precision.HIGHEST)
    q = phi.shape[-1]
    lam = prior_prec[None, :] * sigma2[:, None] + 1e-6
    a = gram + jnp.eye(q, dtype=phi.dtype)[None] * lam[:, :, None]
    chol = jax.lax.linalg.cholesky(a)
    return jax.lax.linalg.triangular_solve(
        chol,
        jax.lax.linalg.triangular_solve(
            chol, rhs[..., None], left_side=True, lower=True
        ),
        left_side=True, lower=True, transpose_a=True,
    )[..., 0]


def _feature_matrix(data, b: int) -> jnp.ndarray:
    """(B, T, F) stacked seasonal + regressor columns (broadcast shared grid)."""
    xs = data.X_season
    if xs.ndim == 2:
        xs = jnp.broadcast_to(xs[None], (b,) + xs.shape)
    return jnp.concatenate([xs, data.X_reg], axis=-1)


def _logistic_km_init(
    y: jnp.ndarray, mask: jnp.ndarray, t: jnp.ndarray, cap: jnp.ndarray
) -> jnp.ndarray:
    """Batched logit-space endpoint init for the logistic trend.

    In ``cap*sigmoid(k*(t-m))`` the parameters are a RATE and an inflection
    TIME — the linear heuristic (k = slope of y, m = y-intercept, in value
    units) starts the solver absurdly far away and was measured to cost the
    whole iteration budget on eval config 4 (round-3 verdict, Weak #2).
    Instead invert the sigmoid at the observed endpoints (Prophet's
    ``logistic_growth_init`` does the same per series):

        L_i = logit(clip(y_i / cap_i))  =>  k = (L1 - L0) / (t1 - t0),
                                            m = t0 - L0 / k.
    """
    eps = 1e-8
    big = jnp.where(mask > 0, t, jnp.inf)
    small = jnp.where(mask > 0, t, -jnp.inf)
    i0 = jnp.argmin(big, axis=-1)
    i1 = jnp.argmax(small, axis=-1)
    b_idx = jnp.arange(y.shape[0])
    t0, t1 = t[b_idx, i0], t[b_idx, i1]
    cap0 = jnp.maximum(cap[b_idx, i0], eps)
    cap1 = jnp.maximum(cap[b_idx, i1], eps)
    r0 = jnp.clip(y[b_idx, i0] / cap0, 0.01, 0.99)
    r1 = jnp.clip(y[b_idx, i1] / cap1, 0.01, 0.99)
    # Near-identical endpoints leave the rate unidentifiable; nudge r0 so
    # the init still points somewhere definite (Prophet's 1.05 bump).
    r0 = jnp.where(jnp.abs(r0 - r1) <= 0.01, jnp.clip(r0 * 1.05, 0.01, 0.99), r0)
    l0 = jnp.log(r0 / (1.0 - r0))
    l1 = jnp.log(r1 / (1.0 - r1))
    # Degenerate span (single observed point; all-masked padding rows):
    # dividing the nudged Δlogit by the eps floor would manufacture a
    # ±5e6 rate that saturates the sigmoid and leaves the solver
    # descending the prior from nowhere — start those rows flat instead.
    span = t1 - t0
    degenerate = span < 1e-6
    k0 = jnp.where(
        degenerate, 0.0, (l1 - l0) / jnp.maximum(span, eps)
    )
    safe_k = jnp.where(jnp.abs(k0) < eps, jnp.where(k0 < 0, -eps, eps), k0)
    m0 = jnp.where(
        jnp.abs(k0) >= eps, t0 - l0 / safe_k, 0.5 * (t0 + t1)
    )
    return k0, m0


def ridge_init(data, config: ProphetConfig) -> jnp.ndarray:
    """Closed-form warm start (B, P) for the batched MAP solve.

    ``data`` is a design.FitData.  Fully-masked padding rows come out as
    all-zero parameters with floor sigma (their Gram is pure prior), which is
    exactly the inert behavior the chunk-padding path needs.
    """
    y, mask, t = data.y, data.mask, data.t
    b, t_len = y.shape
    n_cp = config.n_changepoints
    f = config.num_features
    dtype = y.dtype

    # Rough sigma estimate for the prior/likelihood balance: masked std of y.
    n = jnp.maximum(mask.sum(axis=-1), 1.0)
    mean = (y * mask).sum(axis=-1) / n
    sigma2_0 = jnp.maximum(_masked_sigma(y - mean[:, None], mask) ** 2, 1e-4)

    feats = [] if f == 0 else [_feature_matrix(data, b)]
    feat_prec = (1.0 / jnp.asarray(config.feature_prior_scales(), dtype)) ** 2

    if config.growth == "linear":
        # Columns in theta packing order minus log_sigma:
        #   [t (k), 1 (m), relu(t - s_j) (delta), features (beta)].
        cols = [t[..., None], jnp.ones_like(t)[..., None]]
        if n_cp:
            cols.append(jnp.maximum(t[..., None] - data.s[:, None, :], 0.0))
        cols += feats
        phi = jnp.concatenate(cols, axis=-1)
        # Laplace(0, b) moment-matched to Normal(0, sqrt(2) b).
        cp_prec = jnp.full((n_cp,), 0.5 / (config.changepoint_prior_scale**2), dtype)
        prior_prec = jnp.concatenate([
            jnp.asarray(
                [1.0 / config.k_prior_scale**2, 1.0 / config.m_prior_scale**2],
                dtype,
            ),
            cp_prec,
            feat_prec,
        ])
        w = _ridge_solve(phi, y, mask, prior_prec, sigma2_0)
        k0, m0 = w[:, 0], w[:, 1]
        delta0 = w[:, 2 : 2 + n_cp]
        beta0 = w[:, 2 + n_cp :]
        yhat = jnp.einsum("btq,bq->bt", phi, w, precision=jax.lax.Precision.HIGHEST)
    else:
        # Non-linear growth: growth-aware endpoint heuristic for (k, m);
        # ridge only for the feature betas against the de-trended target.
        delta0 = jnp.zeros((b, n_cp), dtype)
        if config.growth == "logistic":
            k0, m0 = _logistic_km_init(y, mask, t, data.cap)
            g0 = trend_mod.logistic(t, data.cap, k0, m0, delta0, data.s)
        else:
            # Flat trend: the MAP-optimal constant is the masked mean.
            n_f = jnp.maximum(mask.sum(axis=-1), 1.0)
            k0 = jnp.zeros((b,), dtype)
            m0 = (y * mask).sum(axis=-1) / n_f
            g0 = trend_mod.flat(t, m0)
        if f:
            phi = feats[0]
            w = _ridge_solve(phi, y - g0, mask, feat_prec, sigma2_0)
            beta0 = w
            yhat = g0 + jnp.einsum("btq,bq->bt", phi, w, precision=jax.lax.Precision.HIGHEST)
        else:
            beta0 = jnp.zeros((b, 0), dtype)
            yhat = g0

    sigma = _masked_sigma(y - yhat, mask)
    log_sigma0 = jnp.log(jnp.maximum(sigma, 1e-3))
    return pack(
        ProphetParams(
            k=k0, m=m0, log_sigma=log_sigma0, delta=delta0, beta=beta0
        )
    )


def initial_theta(
    data, config: ProphetConfig, solver_config: SolverConfig
) -> jnp.ndarray:
    """Dispatch on SolverConfig.init: "ridge" (default) or "heuristic"."""
    if solver_config.init == "ridge":
        return ridge_init(data, config)
    return init_theta(config, data.y, data.mask, data.t)


def curvature_diag(data, config: ProphetConfig, theta0: jnp.ndarray
                   ) -> jnp.ndarray:
    """(B, P) inverse Gauss-Newton-diagonal of the MAP objective at theta0.

    Used as the L-BFGS initial metric (ops/lbfgs.py): the Prophet posterior
    mixes parameters whose curvatures differ by orders of magnitude (sigma's
    ~2n against a changepoint column active on a handful of points), and in
    float32 the unpreconditioned solver stalls on such series at objective
    values the scipy oracle beats (measured ~1.4 nats on 64-day series).
    The GN diagonal is exact for every linear parameter; non-linear growth
    reuses the linear-trend columns as scale proxies — preconditioning needs
    magnitudes, not exactness.
    """
    p = unpack(theta0, config)
    mask, t = data.mask, data.t
    b = t.shape[0]
    dtype = t.dtype
    sigma = 1e-5 + jnp.exp(p.log_sigma)  # matches loss._SIGMA_FLOOR
    w = mask / (sigma * sigma)[:, None]  # (B, T) residual precision
    n_obs = mask.sum(axis=-1)

    h_k = jnp.sum(w * t * t, axis=-1) + 1.0 / config.k_prior_scale**2
    h_m = jnp.sum(w, axis=-1) + 1.0 / config.m_prior_scale**2
    # d2/dlog_sigma2 of [n log sigma + SSR/(2 sigma^2)] ~ 2 n at the optimum;
    # floor keeps fully-masked padding rows finite.
    h_sig = jnp.maximum(2.0 * n_obs, 1.0) + 1.0 / config.sigma_prior_scale**2
    parts = [h_k[:, None], h_m[:, None], h_sig[:, None]]
    if config.n_changepoints:
        relu = jnp.maximum(t[:, :, None] - data.s[:, None, :], 0.0)
        h_delta = jnp.einsum("bt,btc->bc", w, relu * relu, precision=jax.lax.Precision.HIGHEST)
        # Laplace(0, b) moment-matched to Normal(0, sqrt(2) b), like the
        # ridge init: the kink curvature (1/(b*eps_huber), ~1e5) would be
        # honest at delta=0 but freezes changepoints the data wants to move.
        h_delta = h_delta + 0.5 / config.changepoint_prior_scale**2
        parts.append(h_delta)
    if config.num_features:
        x = _feature_matrix(data, b)
        h_beta = jnp.einsum("bt,btf->bf", w, x * x, precision=jax.lax.Precision.HIGHEST)
        h_beta = h_beta + (
            1.0 / jnp.asarray(config.feature_prior_scales(), dtype) ** 2
        )
        parts.append(h_beta)
    return 1.0 / jnp.concatenate(parts, axis=-1)
