"""Batched negative log-posterior for the Prophet MAP fit.

Matches the public Prophet probability model (the reference's
``tsspark.fit.prophet`` L-BFGS MAP loop fits the same posterior,
BASELINE.json:5):

  y_t ~ Normal(yhat_t, sigma)                 (masked over padding / missing)
  k ~ Normal(0, k_prior_scale)
  m ~ Normal(0, m_prior_scale)
  delta_j ~ Laplace(0, changepoint_prior_scale)   <- sparsity over changepoints
  beta_f ~ Normal(0, prior_scale_f)
  sigma ~ HalfNormal(sigma_prior_scale)

Everything is per-series independent, so the batch loss is a (B,) vector and
the gradient of its sum w.r.t. the (B, P) parameter block is exactly the
per-series gradients — one backward pass serves the whole batch.

The Laplace prior's |delta| kink is smoothed with a tiny Huber radius so the
fixed-iteration batched L-BFGS (ops/lbfgs.py) sees a C1 objective; the
smoothing radius is far below the parameter noise floor and does not move the
MAP point materially (validated against scipy in
tests/test_backends.py::test_cpu_tpu_smape_parity and eval/parity.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tsspark_tpu.config import ProphetConfig
from tsspark_tpu.models.prophet.design import FitData, model_yhat
from tsspark_tpu.models.prophet.params import unpack

_HUBER_EPS = 1e-4
# Floor on the observation noise (scaled units).  Without it the MAP
# objective is unbounded below for (near-)interpolating series — e.g. a
# single-observation series has nll = n*log(sigma) -> -inf as sigma -> 0 —
# and the solver chases the divergence instead of converging.  1e-5 is three
# orders below any realistic scaled noise level, so regular fits are
# unaffected.
_SIGMA_FLOOR = 1e-5


def _smooth_abs(x: jnp.ndarray, eps: float = _HUBER_EPS) -> jnp.ndarray:
    """C1 approximation of |x| (pseudo-Huber)."""
    return jnp.sqrt(x * x + eps * eps) - eps


def neg_log_posterior(
    theta: jnp.ndarray, data: FitData, config: ProphetConfig
) -> jnp.ndarray:
    """Per-series negative log posterior, shape (B,)."""
    p = unpack(theta, config)
    yhat, _ = model_yhat(theta, data, config)
    sigma = _SIGMA_FLOOR + jnp.exp(p.log_sigma)

    resid = (data.y - yhat) * data.mask
    n_obs = data.mask.sum(axis=-1)
    nll = 0.5 * jnp.sum(resid * resid, axis=-1) / (sigma * sigma) + n_obs * jnp.log(
        sigma
    )

    prior = 0.5 * (p.k / config.k_prior_scale) ** 2
    prior = prior + 0.5 * (p.m / config.m_prior_scale) ** 2
    prior = prior + 0.5 * (sigma / config.sigma_prior_scale) ** 2
    if config.n_changepoints:
        prior = prior + jnp.sum(
            _smooth_abs(p.delta) / config.changepoint_prior_scale, axis=-1
        )
    if config.num_features:
        prior = prior + 0.5 * jnp.sum(
            (p.beta / data.prior_scales) ** 2, axis=-1
        )
    return nll + prior


def value_batch(theta: jnp.ndarray, data: FitData, config: ProphetConfig):
    """Per-series losses (B,) only — no gradient.

    The line search evaluates many trial points and discards everything but
    the loss; skipping the vjp there roughly halves the cost of each trial.
    """
    return neg_log_posterior(theta, data, config)


def value_and_grad_batch(theta: jnp.ndarray, data: FitData, config: ProphetConfig):
    """Per-series losses (B,) and gradients (B, P) in one backward pass.

    Series are independent, so pulling back a ones-cotangent through the (B,)
    loss vector yields each series' own gradient block.
    """
    f, vjp = jax.vjp(lambda th: neg_log_posterior(th, data, config), theta)
    (g,) = vjp(jnp.ones_like(f))
    return f, g
