"""Batched negative log-posterior for the Prophet MAP fit.

Matches the public Prophet probability model (the reference's
``tsspark.fit.prophet`` L-BFGS MAP loop fits the same posterior,
BASELINE.json:5):

  y_t ~ Normal(yhat_t, sigma)                 (masked over padding / missing)
  k ~ Normal(0, k_prior_scale)
  m ~ Normal(0, m_prior_scale)
  delta_j ~ Laplace(0, changepoint_prior_scale)   <- sparsity over changepoints
  beta_f ~ Normal(0, prior_scale_f)
  sigma ~ HalfNormal(sigma_prior_scale)

Everything is per-series independent, so the batch loss is a (B,) vector and
the gradient of its sum w.r.t. the (B, P) parameter block is exactly the
per-series gradients — one backward pass serves the whole batch.

The Laplace prior's |delta| kink is smoothed with a tiny Huber radius so the
fixed-iteration batched L-BFGS (ops/lbfgs.py) sees a C1 objective; the
smoothing radius is far below the parameter noise floor and does not move the
MAP point materially (validated against scipy in
tests/test_backends.py::test_cpu_tpu_smape_parity and eval/parity.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tsspark_tpu.config import ProphetConfig
from tsspark_tpu.models.prophet.design import (
    FitData,
    model_yhat,
    seasonal_split,
    trend_fn,
)
from tsspark_tpu.models.prophet.params import unpack

_HUBER_EPS = 1e-4
# Floor on the observation noise (scaled units).  Without it the MAP
# objective is unbounded below for (near-)interpolating series — e.g. a
# single-observation series has nll = n*log(sigma) -> -inf as sigma -> 0 —
# and the solver chases the divergence instead of converging.  1e-5 is three
# orders below any realistic scaled noise level, so regular fits are
# unaffected.
_SIGMA_FLOOR = 1e-5


def _smooth_abs(x: jnp.ndarray, eps: float = _HUBER_EPS) -> jnp.ndarray:
    """C1 approximation of |x| (pseudo-Huber)."""
    return jnp.sqrt(x * x + eps * eps) - eps


def neg_log_posterior(
    theta: jnp.ndarray, data: FitData, config: ProphetConfig
) -> jnp.ndarray:
    """Per-series negative log posterior, shape (B,).

    NOTE: ``fan_value_closed_form`` re-derives every term below in closed form
    along a search ray — any change here (new prior, likelihood tweak)
    must be mirrored there or linear-additive fits will line-search
    against a stale objective.
    """
    p = unpack(theta, config)
    yhat, _ = model_yhat(theta, data, config)
    sigma = _SIGMA_FLOOR + jnp.exp(p.log_sigma)

    resid = (data.y - yhat) * data.mask
    n_obs = data.mask.sum(axis=-1)
    nll = 0.5 * jnp.sum(resid * resid, axis=-1) / (sigma * sigma) + n_obs * jnp.log(
        sigma
    )

    prior = 0.5 * (p.k / config.k_prior_scale) ** 2
    prior = prior + 0.5 * (p.m / config.m_prior_scale) ** 2
    prior = prior + 0.5 * (sigma / config.sigma_prior_scale) ** 2
    if config.n_changepoints:
        prior = prior + jnp.sum(
            _smooth_abs(p.delta) / config.changepoint_prior_scale, axis=-1
        )
    if config.num_features:
        prior = prior + 0.5 * jnp.sum(
            (p.beta / data.prior_scales) ** 2, axis=-1
        )
    return nll + prior


def value_batch(theta: jnp.ndarray, data: FitData, config: ProphetConfig):
    """Per-series losses (B,) only — no gradient.

    The line search evaluates many trial points and discards everything but
    the loss; skipping the vjp there roughly halves the cost of each trial.
    """
    return neg_log_posterior(theta, data, config)


def has_closed_form_fan(config: ProphetConfig) -> bool:
    """True when the line-search fan has a closed form along a ray: linear
    growth (any feature modes — additive features make yhat linear in the
    step, multiplicative ones quadratic; both are exactly summable, see
    fan_value_closed_form).  Logistic/flat growth is not polynomial in the
    trend parameters, so those configs use the stacked fan."""
    return config.growth == "linear"


def fan_value_closed_form(
    theta: jnp.ndarray,      # (B, P) current point
    direction: jnp.ndarray,  # (B, P) search direction
    ladder: jnp.ndarray,     # (K, B) candidate step sizes
    data: FitData,
    config: ProphetConfig,
) -> jnp.ndarray:
    """Closed-form losses (K, B) for the whole Armijo step ladder.

    For linear growth the trend and both feature totals are LINEAR maps of
    the parameters (sigma enters only the likelihood), so along a search
    ray ``theta + s*d`` the model mean is an exact polynomial in ``s``:

        yhat(theta + s d) = (g0 + s gd) * (1 + m0 + s md) + a0 + s ad
                          = c0 + s c1 + s^2 c2,   c2 = gd * md

    (purely additive configs have m0 = md = 0, collapsing to the linear
    case).  The masked sum of squares then expands into SIX reductions
    computed once; every Gaussian prior is quadratic in ``s``, sigma terms
    are exact per step, and only the smoothed Laplace prior needs per-step
    work — over (K, B, n_cp), a few thousandths of the (B, T) grid.  The
    entire K-step line search costs TWO model evaluations instead of K+1:
    this is the difference between the solver being line-search-bound and
    gradient-bound, and it matches evaluating each trial directly to
    float32 rounding (tests/test_lbfgs.py).
    """
    p0 = unpack(theta, config)
    pd = unpack(direction, config)
    g0 = trend_fn(p0, data, config)
    gd = trend_fn(pd, data, config)        # linear map of d's trend block
    a0, m0 = seasonal_split(theta, data, config)
    ad, md = seasonal_split(direction, data, config)

    mask = data.mask
    c0 = g0 * (1.0 + m0) + a0
    c1 = gd * (1.0 + m0) + g0 * md + ad
    c2 = gd * md
    r0 = (data.y - c0) * mask
    c1m = c1 * mask
    c2m = c2 * mask
    s00 = jnp.sum(r0 * r0, axis=-1)       # (B,)
    s01 = jnp.sum(r0 * c1m, axis=-1)
    s02 = jnp.sum(r0 * c2m, axis=-1)
    s11 = jnp.sum(c1m * c1m, axis=-1)
    s12 = jnp.sum(c1m * c2m, axis=-1)
    s22 = jnp.sum(c2m * c2m, axis=-1)
    n_obs = mask.sum(axis=-1)

    s = ladder                             # (K, B)
    s2_ = s * s
    sigma = _SIGMA_FLOOR + jnp.exp(p0.log_sigma[None] + s * pd.log_sigma[None])
    # Sum of squares of (r0 - s c1 - s^2 c2): exact polynomial in s.  The
    # true value is >= 0 by construction; the expanded form can go slightly
    # negative from f32 cancellation when a step nearly zeroes the residual,
    # and 1/sigma^2 would amplify that into a falsely negative loss the
    # direct evaluation could never produce.
    ssr = jnp.maximum(
        s00[None]
        - 2.0 * s * s01[None]
        + s2_ * (s11[None] - 2.0 * s02[None])
        + 2.0 * s * s2_ * s12[None]
        + s2_ * s2_ * s22[None],
        0.0,
    )
    nll = 0.5 * ssr / (sigma * sigma) + n_obs[None] * jnp.log(sigma)

    # Gaussian priors: 0.5*((a + s b)/c)^2 summed -> quadratic in s.
    def quad(a, b, c):
        return (
            0.5 * jnp.sum((a / c) ** 2, axis=-1)[None]
            + s * jnp.sum(a * b / (c * c), axis=-1)[None]
            + 0.5 * s * s * jnp.sum((b / c) ** 2, axis=-1)[None]
        )

    k_scale = jnp.asarray([config.k_prior_scale, config.m_prior_scale],
                          theta.dtype)
    prior = quad(
        jnp.stack([p0.k, p0.m], -1), jnp.stack([pd.k, pd.m], -1), k_scale
    )
    if config.num_features:
        prior = prior + quad(p0.beta, pd.beta, data.prior_scales)
    prior = prior + 0.5 * (sigma / config.sigma_prior_scale) ** 2
    if config.n_changepoints:
        delta_s = p0.delta[None] + s[..., None] * pd.delta[None]  # (K, B, C)
        prior = prior + jnp.sum(
            _smooth_abs(delta_s) / config.changepoint_prior_scale, axis=-1
        )
    return nll + prior


def value_and_grad_batch(theta: jnp.ndarray, data: FitData, config: ProphetConfig):
    """Per-series losses (B,) and gradients (B, P) in one backward pass.

    Series are independent, so pulling back a ones-cotangent through the (B,)
    loss vector yields each series' own gradient block.
    """
    f, vjp = jax.vjp(lambda th: neg_log_posterior(th, data, config), theta)
    (g,) = vjp(jnp.ones_like(f))
    return f, g
