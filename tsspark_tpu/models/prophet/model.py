"""High-level batched Prophet model: fit / predict on padded arrays.

This is the array-level API the backends (backends/tpu.py, backends/cpu.py)
and the DataFrame front-end (frame.py) sit on.  One ``fit`` call fits ALL
series in the batch simultaneously — the TPU-native collapse of the
reference's Spark fan-out (collect -> shard -> fit -> scatter,
BASELINE.json:5).  The fit core is a single jitted program: design tensors
in, MAP parameters out.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tsspark_tpu.config import McmcConfig, ProphetConfig, SolverConfig
from tsspark_tpu.models.prophet import predict as predict_mod
from tsspark_tpu.models.prophet.design import (
    FitData,
    ScalingMeta,
    pack_fit_data,
    packable_batch,
    prepare_fit_data,
)
from tsspark_tpu.models.prophet.init import curvature_diag, initial_theta
from tsspark_tpu.models.prophet.loss import (
    fan_value_closed_form,
    has_closed_form_fan,
    value_and_grad_batch,
    value_batch,
)
from tsspark_tpu.ops import hmc, lbfgs


class FitState(NamedTuple):
    """Fitted parameters + scaling metadata + solver diagnostics (all (B,...)).

    ``status`` is the per-series termination reason (ops/lbfgs.STATUS_*):
    gtol / ftol / float32-noise-floor / stalled.  ``None`` on synthetic or
    restored states that never ran the solver.
    """

    theta: jnp.ndarray
    meta: ScalingMeta
    loss: jnp.ndarray
    grad_norm: jnp.ndarray
    converged: jnp.ndarray
    n_iters: jnp.ndarray
    status: Optional[jnp.ndarray] = None


@functools.partial(jax.jit, static_argnames=("config", "solver_config"))
def fit_core(
    data: FitData,
    theta0: Optional[jnp.ndarray],
    config: ProphetConfig,
    solver_config: SolverConfig,
    max_iters_dynamic: Optional[jnp.ndarray] = None,
    gn_precond_dynamic: Optional[jnp.ndarray] = None,
    use_theta0_dynamic: Optional[jnp.ndarray] = None,
) -> lbfgs.LbfgsResult:
    """The jitted batched MAP solve: the whole fit is one XLA program.

    ``theta0=None`` computes the warm start (closed-form ridge by default,
    init.py) inside the same program — no extra dispatch, no host round-trip.

    ``max_iters_dynamic`` / ``gn_precond_dynamic`` / ``use_theta0_dynamic``:
    optional TRACED solve depth, GN-diagonal-metric switch, and
    warm-start-vs-ridge-init switch.  Passing these (instead of baking them
    into the static ``solver_config`` / the static presence of ``theta0``)
    lets callers drive shallow ridge-initialized passes AND deep
    warm-started preconditioned passes through ONE compiled program — the
    bench's two phases share a single executable this way.  When
    ``gn_precond_dynamic`` is given, the curvature diagonal is always
    computed (a few (B, T) passes) and blended to ones where the flag is
    off; when ``use_theta0_dynamic`` is given, the ridge init is always
    computed and ``theta0`` (required) is selected where the flag is on.
    """
    if use_theta0_dynamic is not None:
        ridge = initial_theta(data, config, solver_config)
        theta0 = jnp.where(use_theta0_dynamic, theta0, ridge)
    elif theta0 is None:
        theta0 = initial_theta(data, config, solver_config)
    if gn_precond_dynamic is not None:
        diag = curvature_diag(data, config, theta0)
        precond = jnp.where(gn_precond_dynamic, diag, jnp.ones_like(diag))
    else:
        precond = (
            curvature_diag(data, config, theta0)
            if solver_config.resolved_precond(config.growth) == "gn_diag"
            else None
        )
    fun = lambda th: value_and_grad_batch(th, data, config)
    fval = lambda th: value_batch(th, data, config)
    fan = (lambda th, d, s: fan_value_closed_form(th, d, s, data, config)) \
        if has_closed_form_fan(config) else None
    return lbfgs.minimize(fun, theta0, solver_config, fun_value=fval,
                          precond=precond, fan_value=fan,
                          max_iters_dynamic=max_iters_dynamic)


@functools.partial(
    jax.jit, static_argnames=("config", "solver_config", "reg_u8_cols")
)
def fit_core_packed(
    packed,
    theta0: Optional[jnp.ndarray],
    config: ProphetConfig,
    solver_config: SolverConfig,
    reg_u8_cols: Tuple[int, ...] = (),
    max_iters_dynamic: Optional[jnp.ndarray] = None,
    gn_precond_dynamic: Optional[jnp.ndarray] = None,
    use_theta0_dynamic: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """fit_core over a transfer-optimized PackedFitData (design.py).

    The unpack (t reconstruction, mask cast, cap broadcast) is traced into
    the SAME program as the solve, so the expanded (B, T) tensors never
    cross the host<->device link in either direction.  The result is packed
    too: (theta (B, P), stats (5, B) f32 rows = loss, grad_norm, converged,
    n_iters, status) — two readbacks instead of six (each device->host
    buffer is a separate ~40 ms round trip on the tunneled runtime).

    ``max_iters_dynamic`` / ``gn_precond_dynamic``: traced depth / metric
    switch (see fit_core) — one compiled program for both bench phases.
    """
    from tsspark_tpu.models.prophet.design import unpack_fit_data

    res = fit_core(
        unpack_fit_data(packed, reg_u8_cols), theta0, config, solver_config,
        max_iters_dynamic=max_iters_dynamic,
        gn_precond_dynamic=gn_precond_dynamic,
        use_theta0_dynamic=use_theta0_dynamic,
    )
    f32 = res.f.dtype
    stats = jnp.stack([
        res.f,
        res.grad_norm,
        res.converged.astype(f32),
        res.n_iters.astype(f32),
        res.status.astype(f32),
    ])
    return res.theta, stats


@functools.partial(jax.jit, static_argnames=("config", "solver_config"))
def fit_init_core(
    data: FitData,
    theta0: Optional[jnp.ndarray],
    config: ProphetConfig,
    solver_config: SolverConfig,
) -> lbfgs.LbfgsState:
    """Jitted solver-state construction (for the segmented fit path)."""
    if theta0 is None:
        theta0 = initial_theta(data, config, solver_config)
    precond = (
        curvature_diag(data, config, theta0)
        if solver_config.resolved_precond(config.growth) == "gn_diag"
        else None
    )
    fun = lambda th: value_and_grad_batch(th, data, config)
    return lbfgs.init_state(fun, theta0, solver_config, precond)


@functools.partial(
    jax.jit, static_argnames=("config", "solver_config", "num_iters"),
    donate_argnames=("state",),
)
def fit_segment_core(
    data: FitData,
    state: lbfgs.LbfgsState,
    config: ProphetConfig,
    solver_config: SolverConfig,
    num_iters: int,
) -> lbfgs.LbfgsState:
    """Advance a batched solve by ``num_iters`` iterations in ONE short XLA
    program.  Chaining these reproduces fit_core's trajectory exactly (the
    full LbfgsState round-trips), while bounding per-dispatch execution time
    — the knob TpuBackend(iter_segment=...) exposes."""
    fun = lambda th: value_and_grad_batch(th, data, config)
    fval = lambda th: value_batch(th, data, config)
    fan = (lambda th, d, s: fan_value_closed_form(th, d, s, data, config)) \
        if has_closed_form_fan(config) else None
    return lbfgs.run_segment(fun, state, solver_config, num_iters,
                             fun_value=fval, fan_value=fan)


# Default keep-best margin for multi-start / rescue selection: safely above
# solve-to-solve float noise at the typical loss scale (~1e3 nats, where a
# re-solve of the same basin lands within ~1e-3), and below any rescue gain
# worth taking (measured real gains start ~0.1 nats).
KEEP_BEST_MARGIN = 0.05


def select_better_state(a: "FitState", b: "FitState",
                        margin: float = 0.0) -> "FitState":
    """Per-series argmin-loss merge of two fits of the SAME data.

    The multi-start selector: non-finite losses always lose; ties keep
    ``a``.  Meta is identical by construction (same rows, deterministic
    prep), so ``a``'s is carried.

    ``margin``: the challenger ``b`` must beat ``a`` by MORE than this
    many nats to win.  Rescue/multi-start callers use a small positive
    margin so an equal-quality restart cannot replace the incumbent
    parameters on float noise — near-flat posteriors have multiple
    equal-loss optima with different theta, and basin-hopping on epsilons
    breaks warm-start continuity (a streaming replay must reproduce the
    params it already stored).
    """
    la = np.asarray(a.loss)
    lb = np.asarray(b.loss)
    take_b = np.isfinite(lb) & (~np.isfinite(la) | (lb < la - margin))

    def pick(xa, xb):
        if xa is None or xb is None:
            return xa
        xa, xb = np.asarray(xa), np.asarray(xb)
        shaped = take_b.reshape(take_b.shape + (1,) * (xa.ndim - 1))
        return np.where(shaped, xb, xa)

    return FitState(
        theta=pick(a.theta, b.theta),
        meta=a.meta,
        loss=pick(a.loss, b.loss),
        grad_norm=pick(a.grad_norm, b.grad_norm),
        converged=pick(a.converged, b.converged),
        n_iters=pick(a.n_iters, b.n_iters),
        status=pick(a.status, b.status),
    )


def _run_segments_compacted(
    data: FitData,
    ls: lbfgs.LbfgsState,
    config: ProphetConfig,
    solver: SolverConfig,
    iter_segment: int,
    n_seg: int,
    on_segment,
    recorder,
    floor: int,
    multiple: int,
) -> lbfgs.LbfgsResult:
    """The convergence-compacting segment scheduler.

    The batched solver already FREEZES converged series (their updates
    are masked to zero), but frozen rows still ride every objective
    evaluation — on the M5 shape, mean iterations to converge is ~3
    while the lockstep batch pays full width for its slowest member.
    Between segment dispatches this scheduler GATHERS the surviving
    (unconverged) rows into the next power-of-2 width
    (``parallel.sharding.compacted_width``: pow-2 ladder, 32-row floor,
    shard-count multiple) and continues the solve at that width, so
    per-iteration cost tracks the LIVE set instead of the original
    batch.  Departing rows' results are harvested into full-width host
    buffers at the moment they leave; the final result scatters the
    remaining live rows back.

    Parity: every per-series quantity in the solver and the design
    tensors is row-local (``lbfgs.take_state`` / ``design.
    take_fit_data``), pad rows are converged duplicates the active mask
    freezes, and frozen rows never change after convergence — so the
    compacted schedule is BITWISE identical to the full-width segmented
    solve per series (tests/test_compaction.py).  Shrunk widths reuse
    the pow-2 programs the chunk padding already compiles, so no
    per-live-set-size recompiles.
    """
    from tsspark_tpu.models.prophet.design import take_fit_data
    from tsspark_tpu.parallel.sharding import compacted_width

    b_full = int(data.y.shape[0])
    live = np.arange(b_full)  # original row of each current REAL row
    n_real = b_full           # rows [0:n_real) are real; the rest pads
    buf = None                # full-width host result buffers

    def harvest(res, rows_local, rows_orig):
        nonlocal buf
        res_np = {
            f: np.asarray(getattr(res, f)) for f in lbfgs.LbfgsResult._fields
        }
        if buf is None:
            buf = {
                f: np.empty((b_full,) + a.shape[1:], a.dtype)
                for f, a in res_np.items()
            }
        for f, a in res_np.items():
            buf[f][rows_orig] = a[rows_local]

    for seg_i in range(n_seg):
        width = int(data.y.shape[0])
        with (recorder.dispatch(width, live=n_real, kind="segment")
              if recorder is not None else contextlib.nullcontext()):
            ls = fit_segment_core(data, ls, config, solver, iter_segment)
            # Block per segment: bounds dispatch time AND the converged
            # mask must be concrete before the compaction decision.
            jax.block_until_ready(ls.theta)
        if on_segment is not None:
            on_segment()
        conv = np.asarray(ls.converged)
        if conv.all() or seg_i == n_seg - 1:
            break
        running = np.flatnonzero(~conv[:n_real])
        new_w = compacted_width(running.size, floor=floor, multiple=multiple)
        if new_w >= width:
            continue
        res = lbfgs.to_result(ls)
        done_local = np.flatnonzero(conv[:n_real])
        harvest(res, done_local, live[done_local])
        # Pads are converged rows repeated: the solver's active mask
        # freezes them, so they add no lockstep depth and their outputs
        # are never scattered back.  done_local is nonempty whenever
        # compaction fires: width == compacted_width(previous live set),
        # so a shrink requires some row to have converged since.
        pad = new_w - running.size
        gather = (
            np.concatenate([running, np.resize(done_local, pad)])
            if pad else running
        )
        gidx = jnp.asarray(gather.astype(np.int32))
        ls = lbfgs.take_state(ls, gidx)
        data = take_fit_data(data, gidx)
        live = live[gather]
        n_real = running.size

    res = lbfgs.to_result(ls)
    if buf is None:
        return res  # never compacted: device-resident result, as before
    rows = np.arange(n_real)
    harvest(res, rows, live[:n_real])
    return lbfgs.LbfgsResult(**buf)


def fitstate_from_packed(theta, stats, meta: ScalingMeta) -> "FitState":
    """FitState from fit_core_packed's (theta, (5, B) stats) result."""
    stats = np.asarray(stats)
    return FitState(
        theta=theta,
        meta=meta,
        loss=stats[0],
        grad_norm=stats[1],
        converged=stats[2].astype(bool),
        n_iters=stats[3].astype(np.int32),
        status=stats[4].astype(np.int32),
    )


class McmcState(NamedTuple):
    """Full-posterior fit: (S, B, P) draws + scaling metadata + diagnostics.

    ``map_state`` is the MAP fit the chains were initialized from — callers
    get the point-estimate surface (components, deterministic predict) for
    free alongside the posterior draws.  ``rhat``/``ess`` are per-(series,
    parameter) split-R-hat and bulk ESS (ops/hmc.split_rhat_ess) — the
    convergence gate Stan users read off its summary.
    """

    samples: jnp.ndarray
    meta: ScalingMeta
    accept_rate: jnp.ndarray
    step_size: jnp.ndarray
    divergences: jnp.ndarray
    map_state: "FitState"
    rhat: Optional[jnp.ndarray] = None   # (B, P)
    ess: Optional[jnp.ndarray] = None    # (B, P)


@functools.partial(jax.jit, static_argnames=("config", "mcmc_config"))
def mcmc_core(
    data: FitData,
    theta0: jnp.ndarray,
    key: jax.Array,
    config: ProphetConfig,
    mcmc_config: McmcConfig,
) -> hmc.HmcResult:
    """The jitted batched posterior sample: one HMC chain per series.

    The log density is the negative MAP loss plus the log-Jacobian of the
    unconstraining sigma transform — the same model/parameterization split
    upstream Prophet gets from Stan (``optimize`` omits the Jacobian,
    ``mcmc_samples`` includes it).
    """

    def logdensity(th):
        # Sampling needs the log-Jacobian of the sigma = exp(log_sigma)
        # transform (+log_sigma, d/dlog_sigma = 1), which MAP optimization
        # legitimately omits (Stan's optimize vs. sample make the same
        # distinction); without it sigma draws are biased low.
        f, g = value_and_grad_batch(th, data, config)
        lp = -f + th[..., 2]
        grad = (-g).at[..., 2].add(1.0)
        return lp, grad

    k_jit, k_run = jax.random.split(key)
    jitter = mcmc_config.init_jitter * jax.random.normal(
        k_jit, theta0.shape, theta0.dtype
    )
    return hmc.sample(logdensity, theta0 + jitter, k_run, mcmc_config)


class ProphetModel:
    """Batched Prophet-style forecaster.

    Example:
      model = ProphetModel(ProphetConfig(seasonalities=(YEARLY, WEEKLY)))
      state = model.fit(ds_days, y)          # y: (n_series, n_timesteps)
      fc = model.predict(state, future_days)  # dict of (n_series, horizon)
    """

    def __init__(
        self,
        config: ProphetConfig = ProphetConfig(),
        solver_config: SolverConfig = SolverConfig(),
    ):
        from tsspark_tpu.utils.platform import (
            enable_persistent_compile_cache,
        )

        # Model-level chokepoint (covers fit/predict/mcmc entry points
        # without per-method calls): persistent compile cache across
        # processes (round-3 verdict, Weak #5).
        enable_persistent_compile_cache()
        self.config = config
        self.solver_config = solver_config

    # -- fitting ---------------------------------------------------------------

    def prepare(self, ds, y, **kw):
        return prepare_fit_data(ds, y, self.config, **kw)

    def fit(
        self,
        ds: jnp.ndarray,
        y: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        cap: Optional[jnp.ndarray] = None,
        floor: Optional[jnp.ndarray] = None,
        regressors: Optional[jnp.ndarray] = None,
        init: Optional[jnp.ndarray] = None,
        iter_segment: Optional[int] = None,
        on_segment=None,
        conditions=None,
        reg_u8_cols: Optional[Tuple[int, ...]] = None,
        max_iters_dynamic=None,
        gn_precond_dynamic=None,
        use_init_dynamic=None,
        recorder=None,
        compact: bool = False,
        compact_floor: int = 32,
        compact_multiple: int = 1,
    ) -> FitState:
        """Fit every series in the (B, T) batch.

        ``init`` warm-starts the solver from previous parameters (the
        streaming incremental-refit path, BASELINE.json:11).

        ``iter_segment`` splits the solve into several short XLA executions
        of at most that many iterations each, with the full solver state
        carried across — the trajectory is IDENTICAL to one long program;
        only the dispatch granularity changes.  Use it to bound
        per-dispatch execution time (fragile tunneled runtimes) or to create
        preemption points for elastic schedulers.

        ``on_segment`` (no-arg callable) fires after every completed segment
        dispatch — a liveness hook for external watchdogs that cannot tell a
        long-running solve from a wedged runtime (the bench orchestrator's
        stall detector is the motivating consumer).

        Transfer path: shared-grid batches with an exact 0/1 mask run as
        ONE packed-transfer program (design.PackedFitData — the mask
        travels folded into y as NaN, indicator regressors bit-packed,
        unpack fused into the fit, ~1/3 of the plain bytes over the
        host<->device link); segmented solves, per-series grids, and
        fractional masks keep the plain FitData path.  ``reg_u8_cols``
        pins which regressor columns travel bit-packed (chunked callers
        must decide once per dataset — see pack_fit_data).

        ``max_iters_dynamic`` / ``gn_precond_dynamic`` / ``use_init_dynamic``:
        TRACED phase controls (see fit_core) letting a two-phase caller
        drive shallow ridge-initialized and deep warm-started solves
        through one compiled program.  On the non-packable fallback they
        are honored semantically (folded into an equivalent static solver
        config), just without the shared-program benefit.

        ``recorder`` (tsspark_tpu.perf.PerfRecorder): per-dispatch
        telemetry — wall time, dispatched width, live-set width,
        compile-cache misses.  Timing requires blocking per dispatch,
        so passing one trades dispatch-pipeline overlap for telemetry.

        ``compact`` enables the convergence-compacting segment schedule
        on the segmented path (see ``_run_segments_compacted``): the
        lockstep batch shrinks to the unconverged set between segments
        (``compact_floor``/``compact_multiple`` bound the width ladder).
        Bitwise-identical per-series results; per-iteration cost
        proportional to the live set.
        """
        data, meta = prepare_fit_data(
            ds, y, self.config, mask=mask, cap=cap, floor=floor,
            regressors=regressors, conditions=conditions, as_numpy=True,
        )
        packable = (
            not (iter_segment and iter_segment < self.solver_config.max_iters)
            and packable_batch(ds, data.mask)
        )
        dynamic = any(
            v is not None
            for v in (max_iters_dynamic, gn_precond_dynamic, use_init_dynamic)
        )
        if dynamic:
            # Partial traced controls are normalized to the full triple so
            # every path (the packed one-program path AND the static
            # fallback) keeps the exact semantics of the static config it
            # replaces: missing depth = the solver's own cap, missing
            # metric flag = resolved_precond (NOT a silent "none" — the
            # "auto" default resolves to gn_diag), missing init flag =
            # honor a caller-supplied init.
            if max_iters_dynamic is None:
                max_iters_dynamic = np.int32(self.solver_config.max_iters)
            if gn_precond_dynamic is None:
                gn_precond_dynamic = np.bool_(
                    self.solver_config.resolved_precond(self.config.growth)
                    == "gn_diag"
                )
            if use_init_dynamic is None:
                use_init_dynamic = np.bool_(init is not None)
        if packable:
            # Not guarded by try/except: pack_fit_data's remaining failure
            # mode (reg_u8_cols naming a non-0/1 column) is a caller
            # contract violation that must surface, not silently fall back.
            packed, u8 = pack_fit_data(
                data, meta, ds, reg_u8_cols=reg_u8_cols,
                collapse_cap=self.config.growth != "logistic",
            )
            theta0 = init
            if dynamic and theta0 is None:
                # use_init flag off: the array is never selected, but the
                # traced program needs a concrete operand.
                theta0 = np.zeros(
                    (np.asarray(data.y).shape[0], self.config.num_params),
                    np.float32,
                )
            kw = dict(
                reg_u8_cols=u8,
                max_iters_dynamic=max_iters_dynamic,
                gn_precond_dynamic=gn_precond_dynamic,
                use_theta0_dynamic=use_init_dynamic,
            )
            if recorder is not None:
                with recorder.dispatch(np.asarray(data.y).shape[0],
                                       kind="fit"):
                    theta, stats = fit_core_packed(
                        packed, theta0, self.config, self.solver_config,
                        **kw,
                    )
                    jax.block_until_ready(theta)
            else:
                theta, stats = fit_core_packed(
                    packed, theta0, self.config, self.solver_config, **kw
                )
            if on_segment is not None:
                on_segment()
            return fitstate_from_packed(theta, stats, meta)
        if dynamic:
            # Fallback path: fold the (normalized) traced phase controls
            # into an equivalent static solver — semantics preserved; the
            # shared-program benefit only exists on the packed path.
            solver = dataclasses.replace(
                self.solver_config,
                max_iters=int(max_iters_dynamic),
                precond="gn_diag" if bool(gn_precond_dynamic) else "none",
            )
            fallback = ProphetModel(self.config, solver)
            theta0 = init if bool(use_init_dynamic) else None
            return fallback._fit_prepared(
                data, meta, theta0, iter_segment, on_segment,
                recorder=recorder, compact=compact,
                compact_floor=compact_floor,
                compact_multiple=compact_multiple,
            )
        return self._fit_prepared(
            data, meta, init, iter_segment, on_segment,
            recorder=recorder, compact=compact, compact_floor=compact_floor,
            compact_multiple=compact_multiple,
        )

    def _fit_prepared(
        self,
        data: FitData,
        meta: ScalingMeta,
        init: Optional[jnp.ndarray],
        iter_segment: Optional[int] = None,
        on_segment=None,
        recorder=None,
        compact: bool = False,
        compact_floor: int = 32,
        compact_multiple: int = 1,
    ) -> FitState:
        # None -> warm start computed inside the jitted program (init.py).
        theta0 = init
        solver = self.solver_config
        if iter_segment and iter_segment < solver.max_iters:
            # Transfer once: numpy FitData leaves would be re-uploaded on
            # EVERY segment dispatch (jit device_puts numpy args per call,
            # no cross-call caching — ~56 MB per re-ship at bench shape).
            data = jax.tree.map(jnp.asarray, data)
            ls = fit_init_core(data, theta0, self.config, solver)
            n_seg = -(-solver.max_iters // iter_segment)
            if compact:
                # Convergence-compacting schedule: shrink the lockstep
                # batch to the unconverged set between segments (bitwise-
                # identical per series — see _run_segments_compacted).
                res = _run_segments_compacted(
                    data, ls, self.config, solver, iter_segment, n_seg,
                    on_segment, recorder, compact_floor, compact_multiple,
                )
            else:
                width = int(data.y.shape[0])
                for _ in range(n_seg):
                    with (recorder.dispatch(width, kind="segment")
                          if recorder is not None
                          else contextlib.nullcontext()):
                        ls = fit_segment_core(
                            data, ls, self.config, solver, iter_segment
                        )
                        # Block per segment: keeps every dispatch short
                        # AND surfaces a dead runtime at the segment
                        # boundary, not downstream.
                        jax.block_until_ready(ls.theta)
                    if on_segment is not None:
                        on_segment()
                    if bool(ls.converged.all()):
                        break
                res = lbfgs.to_result(ls)
        elif recorder is not None:
            with recorder.dispatch(int(np.asarray(data.y).shape[0]),
                                   kind="fit"):
                res = fit_core(data, theta0, self.config, solver)
                jax.block_until_ready(res.theta)
        else:
            res = fit_core(data, theta0, self.config, solver)
        return FitState(
            theta=res.theta,
            meta=meta,
            loss=res.f,
            grad_norm=res.grad_norm,
            converged=res.converged,
            n_iters=res.n_iters,
            status=res.status,
        )

    def fit_mcmc(
        self,
        ds: jnp.ndarray,
        y: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        cap: Optional[jnp.ndarray] = None,
        floor: Optional[jnp.ndarray] = None,
        regressors: Optional[jnp.ndarray] = None,
        mcmc_config: McmcConfig = McmcConfig(),
        seed: int = 0,
        init: Optional[jnp.ndarray] = None,
        conditions=None,
    ) -> McmcState:
        """Full-posterior fit: MAP solve, then one HMC chain per series.

        The TPU analog of upstream Prophet's ``mcmc_samples=N`` (Stan NUTS):
        intervals from :meth:`predict_mcmc` carry seasonality and regressor
        uncertainty, which the MAP path's trend-only simulation cannot.
        """
        data, meta = prepare_fit_data(
            ds, y, self.config, mask=mask, cap=cap, floor=floor,
            regressors=regressors, conditions=conditions,
        )
        map_state = self._fit_prepared(data, meta, init)
        res = mcmc_core(
            data, map_state.theta, jax.random.PRNGKey(seed), self.config,
            mcmc_config,
        )
        rhat, ess = hmc.split_rhat_ess(res.samples)
        return McmcState(
            samples=res.samples,
            meta=meta,
            accept_rate=res.accept_rate,
            step_size=res.step_size,
            divergences=res.divergences,
            map_state=map_state,
            rhat=rhat,
            ess=ess,
        )

    # -- prediction ------------------------------------------------------------

    def predict(
        self,
        state: FitState,
        ds: jnp.ndarray,
        cap: Optional[jnp.ndarray] = None,
        regressors: Optional[jnp.ndarray] = None,
        seed: int = 0,
        num_samples: Optional[int] = None,
        conditions=None,
        return_samples: bool = False,
    ) -> Dict[str, jnp.ndarray]:
        """Forecast on an arbitrary time grid (in-sample and/or future)."""
        data = predict_mod.prepare_predict_data(
            ds, state.meta, self.config, cap=cap, regressors=regressors,
            conditions=conditions,
        )
        key = jax.random.PRNGKey(seed)
        return predict_mod.forecast_jit(
            state.theta, data, state.meta, self.config,
            key=key, num_samples=num_samples, return_samples=return_samples,
        )

    def predict_mcmc(
        self,
        state: McmcState,
        ds: jnp.ndarray,
        cap: Optional[jnp.ndarray] = None,
        regressors: Optional[jnp.ndarray] = None,
        seed: int = 0,
        max_draws: Optional[int] = None,
        conditions=None,
        return_samples: bool = False,
    ) -> Dict[str, jnp.ndarray]:
        """Posterior-predictive forecast from the MCMC draws."""
        data = predict_mod.prepare_predict_data(
            ds, state.meta, self.config, cap=cap, regressors=regressors,
            conditions=conditions,
        )
        samples = state.samples
        if max_draws is not None and samples.shape[0] > max_draws:
            idx = jnp.linspace(0, samples.shape[0] - 1, max_draws).astype(int)
            samples = samples[idx]
        return predict_mod.forecast_from_draws(
            samples, data, state.meta, self.config, jax.random.PRNGKey(seed),
            return_samples=return_samples,
        )

    def components(self, state: FitState, ds, cap=None, regressors=None,
                   conditions=None):
        data = predict_mod.prepare_predict_data(
            ds, state.meta, self.config, cap=cap, regressors=regressors,
            conditions=conditions,
        )
        return predict_mod.component_breakdown(
            state.theta, data, state.meta, self.config
        )
