"""High-level batched Prophet model: fit / predict on padded arrays.

This is the array-level API the backends (backends/tpu.py, backends/cpu.py)
and the DataFrame front-end (frame.py) sit on.  One ``fit`` call fits ALL
series in the batch simultaneously — the TPU-native collapse of the
reference's Spark fan-out (collect -> shard -> fit -> scatter,
BASELINE.json:5).  The fit core is a single jitted program: design tensors
in, MAP parameters out.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

from tsspark_tpu.config import ProphetConfig, SolverConfig
from tsspark_tpu.models.prophet import predict as predict_mod
from tsspark_tpu.models.prophet.design import (
    FitData,
    ScalingMeta,
    prepare_fit_data,
)
from tsspark_tpu.models.prophet.loss import value_and_grad_batch
from tsspark_tpu.models.prophet.params import init_theta
from tsspark_tpu.ops import lbfgs


class FitState(NamedTuple):
    """Fitted parameters + scaling metadata + solver diagnostics (all (B,...))."""

    theta: jnp.ndarray
    meta: ScalingMeta
    loss: jnp.ndarray
    grad_norm: jnp.ndarray
    converged: jnp.ndarray
    n_iters: jnp.ndarray


@functools.partial(jax.jit, static_argnames=("config", "solver_config"))
def fit_core(
    data: FitData,
    theta0: jnp.ndarray,
    config: ProphetConfig,
    solver_config: SolverConfig,
) -> lbfgs.LbfgsResult:
    """The jitted batched MAP solve: the whole fit is one XLA program."""
    fun = lambda th: value_and_grad_batch(th, data, config)
    return lbfgs.minimize(fun, theta0, solver_config)


class ProphetModel:
    """Batched Prophet-style forecaster.

    Example:
      model = ProphetModel(ProphetConfig(seasonalities=(YEARLY, WEEKLY)))
      state = model.fit(ds_days, y)          # y: (n_series, n_timesteps)
      fc = model.predict(state, future_days)  # dict of (n_series, horizon)
    """

    def __init__(
        self,
        config: ProphetConfig = ProphetConfig(),
        solver_config: SolverConfig = SolverConfig(),
    ):
        self.config = config
        self.solver_config = solver_config

    # -- fitting ---------------------------------------------------------------

    def prepare(self, ds, y, **kw):
        return prepare_fit_data(ds, y, self.config, **kw)

    def fit(
        self,
        ds: jnp.ndarray,
        y: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        cap: Optional[jnp.ndarray] = None,
        floor: Optional[jnp.ndarray] = None,
        regressors: Optional[jnp.ndarray] = None,
        init: Optional[jnp.ndarray] = None,
    ) -> FitState:
        """Fit every series in the (B, T) batch.

        ``init`` warm-starts the solver from previous parameters (the
        streaming incremental-refit path, BASELINE.json:11).
        """
        data, meta = prepare_fit_data(
            ds, y, self.config, mask=mask, cap=cap, floor=floor,
            regressors=regressors,
        )
        theta0 = init if init is not None else init_theta(
            self.config, data.y, data.mask, data.t
        )
        res = fit_core(data, theta0, self.config, self.solver_config)
        return FitState(
            theta=res.theta,
            meta=meta,
            loss=res.f,
            grad_norm=res.grad_norm,
            converged=res.converged,
            n_iters=res.n_iters,
        )

    # -- prediction ------------------------------------------------------------

    def predict(
        self,
        state: FitState,
        ds: jnp.ndarray,
        cap: Optional[jnp.ndarray] = None,
        regressors: Optional[jnp.ndarray] = None,
        seed: int = 0,
        num_samples: Optional[int] = None,
    ) -> Dict[str, jnp.ndarray]:
        """Forecast on an arbitrary time grid (in-sample and/or future)."""
        data = predict_mod.prepare_predict_data(
            ds, state.meta, self.config, cap=cap, regressors=regressors
        )
        key = jax.random.PRNGKey(seed)
        return predict_mod.forecast(
            state.theta, data, state.meta, self.config,
            key=key, num_samples=num_samples,
        )

    def components(self, state: FitState, ds, cap=None, regressors=None):
        data = predict_mod.prepare_predict_data(
            ds, state.meta, self.config, cap=cap, regressors=regressors
        )
        return predict_mod.component_breakdown(
            state.theta, data, state.meta, self.config
        )
