"""Batched limited-memory BFGS with backtracking Armijo line search.

This is the TPU-native replacement for the reference's per-series L-BFGS MAP
inner loop (``tsspark.fit.prophet``, BASELINE.json:5): instead of B
independent scipy solves fanned out over Spark CPU executors, ONE solver
instance advances all B series simultaneously on (B, P) parameter blocks.

Design constraints that shaped this implementation:

  * XLA wants static control flow: the outer loop is a ``lax.while_loop``
    bounded by ``max_iters`` whose body is fully batched; per-series
    convergence is a (B,) mask that freezes finished series (their updates
    are multiplied to zero) rather than exiting early.  The loop exits when
    every series is converged or the iteration cap is hit — so well-behaved
    batches finish early while stragglers never stall the compile shape.
  * The two-loop recursion over the history window is unrolled over
    ``history`` (default 10) static steps; each step is a (B,) dot-product
    (``sum over P``) plus an axpy — pure fused VPU work, no MXU needed, no
    per-series divergence.
  * The line search is a *batched fan*: the geometric ladder of candidate
    steps is known upfront, so all K trials (plus a tiny-gradient-step
    fallback row) are evaluated in ONE objective call on a (K+1, B, P)
    stack, and each series picks its largest Armijo-accepted step with a
    gather.  This replaces up to K *sequential* full-batch evaluations per
    iteration (the round-2 design, where the search ran until ALL series
    accepted — nearly never early) with a single fused pass whose marginal
    rows are almost free on a memory-bound objective.  The accepted point
    per series is mathematically identical to sequential backtracking.
  * Safeguards: non-finite trial losses are treated as rejection; if no
    ladder step passes Armijo for a series, it falls back to the tiny
    gradient step evaluated in the same fan; curvature pairs with
    non-positive ``s.y`` are dropped from the history (their rho is zeroed)
    to keep the inverse-Hessian estimate positive definite.
  * Convergence distinguishes WHY a series stopped (``status``): gradient
    tolerance, relative-decrease tolerance, stationarity at the float32
    noise floor (consecutive iterations whose decrease is below a few ulps
    of the objective — such series cannot make further progress in f32 and
    burning more iterations on them is pure waste), or a failed search.

The objective callable must map (B, P) params -> ((B,) losses, (B, P) grads).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from tsspark_tpu.config import SolverConfig


# Per-series termination reasons (LbfgsState.status / LbfgsResult.status).
STATUS_RUNNING = 0   # still iterating (or hit the iteration cap while moving)
STATUS_GTOL = 1      # gradient inf-norm below gtol
STATUS_FTOL = 2      # relative objective decrease below tol
STATUS_FLOOR = 3     # stationary at the float32 noise floor (see SolverConfig)
STATUS_STALLED = 4   # no acceptable step anywhere (ladder + fallback failed)


class LbfgsState(NamedTuple):
    theta: jnp.ndarray      # (B, P)
    f: jnp.ndarray          # (B,)
    grad: jnp.ndarray       # (B, P)
    s_hist: jnp.ndarray     # (M, B, P) parameter displacements
    y_hist: jnp.ndarray     # (M, B, P) gradient displacements
    rho: jnp.ndarray        # (M, B) 1 / (s.y); 0 marks an invalid/empty slot
    iteration: jnp.ndarray  # () int32
    converged: jnp.ndarray  # (B,) bool
    n_iters: jnp.ndarray    # (B,) int32 — iterations each series actually ran
    prev_step: jnp.ndarray  # (B,) last accepted line-search step (seeds the next)
    floor_count: jnp.ndarray  # (B,) int32 consecutive noise-floor iterations
    ftol_count: jnp.ndarray   # (B,) int32 consecutive sub-ftol iterations
    status: jnp.ndarray     # (B,) int32 STATUS_* termination reason
    precond: jnp.ndarray    # (B, P) inverse-curvature diag (initial metric)


class LbfgsResult(NamedTuple):
    theta: jnp.ndarray
    f: jnp.ndarray
    grad_norm: jnp.ndarray
    converged: jnp.ndarray
    n_iters: jnp.ndarray
    status: jnp.ndarray


def _dot(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Batched inner product over the parameter axis: (..., B, P) -> (..., B)."""
    return jnp.sum(a * b, axis=-1)


def _two_loop_direction(state: LbfgsState, history: int) -> jnp.ndarray:
    """Batched two-loop recursion: approximate -H^{-1} g for every series.

    History slots are ring-indexed newest-first relative to the iteration
    counter; empty/invalid slots carry rho == 0 and contribute nothing.
    """
    q = state.grad
    m = history
    # Newest-first order of ring slots.
    newest = (state.iteration - 1) % m
    order = (newest - jnp.arange(m)) % m  # (M,) newest ... oldest

    alphas = []
    for i in range(m):
        idx = order[i]
        s_i = state.s_hist[idx]
        y_i = state.y_hist[idx]
        r_i = state.rho[idx]  # (B,)
        alpha = r_i * _dot(s_i, q)  # (B,)
        q = q - jnp.where(r_i[:, None] != 0, alpha[:, None] * y_i, 0.0)
        alphas.append((idx, alpha))

    # Initial metric H0 = gamma * D, D = diag inverse-curvature preconditioner
    # (ones when disabled).  gamma = s.y / (y.D y) of the newest valid pair —
    # the standard scaled-L-BFGS H0; with empty history the direction is the
    # preconditioned gradient -D g (a Newton-diagonal step, which is what
    # rescues ill-conditioned series the plain -g step stalls on in f32).
    d2 = state.precond
    s_n, y_n, r_n = state.s_hist[newest], state.y_hist[newest], state.rho[newest]
    yy = _dot(y_n * d2, y_n)
    gamma = jnp.where(
        (r_n != 0) & (yy > 0), _dot(s_n, y_n) / jnp.maximum(yy, 1e-30), 1.0
    )
    r = q * gamma[:, None] * d2

    for idx, alpha in reversed(alphas):
        s_i = state.s_hist[idx]
        y_i = state.y_hist[idx]
        r_i = state.rho[idx]
        beta = r_i * _dot(y_i, r)
        r = r + jnp.where(
            r_i[:, None] != 0, (alpha - beta)[:, None] * s_i, 0.0
        )
    return -r


def init_state(
    fun: Callable[[jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]],
    theta0: jnp.ndarray,
    config: SolverConfig = SolverConfig(),
    precond: Optional[jnp.ndarray] = None,
) -> LbfgsState:
    """Fresh solver state at theta0 (one objective evaluation).

    ``precond``: optional (B, P) inverse-curvature diagonal used as the
    L-BFGS initial metric (see _two_loop_direction); None disables it.
    """
    b, p = theta0.shape
    m = config.history
    f0, g0 = fun(theta0)
    if precond is None:
        precond = jnp.ones_like(theta0)
    return LbfgsState(
        theta=theta0,
        f=f0,
        grad=g0,
        s_hist=jnp.zeros((m, b, p), theta0.dtype),
        y_hist=jnp.zeros((m, b, p), theta0.dtype),
        rho=jnp.zeros((m, b), theta0.dtype),
        iteration=jnp.zeros((), jnp.int32),
        converged=jnp.zeros((b,), bool),
        n_iters=jnp.zeros((b,), jnp.int32),
        prev_step=jnp.full((b,), config.init_step, theta0.dtype),
        floor_count=jnp.zeros((b,), jnp.int32),
        ftol_count=jnp.zeros((b,), jnp.int32),
        status=jnp.zeros((b,), jnp.int32),
        precond=precond,
    )


def to_result(state: LbfgsState) -> LbfgsResult:
    return LbfgsResult(
        theta=state.theta,
        f=state.f,
        grad_norm=jnp.max(jnp.abs(state.grad), axis=-1),
        converged=state.converged,
        n_iters=state.n_iters,
        status=state.status,
    )


def run_segment(
    fun: Callable[[jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]],
    state: LbfgsState,
    config: SolverConfig,
    num_iters: Optional[int] = None,
    fun_value: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = None,
    fan_value=None,
    max_iters_dynamic: Optional[jnp.ndarray] = None,
) -> LbfgsState:
    """Advance the solver by up to ``num_iters`` iterations (bounded by
    ``config.max_iters`` overall).

    Resumable: feeding the returned state back continues the EXACT same
    trajectory as one long run — history ring, per-series convergence masks,
    and line-search step memory all carry across segments.  This is what
    lets a driver split one logical solve into several short XLA executions
    (bounded per-dispatch time for fragile runtimes, preemption points for
    elastic schedulers) without changing the mathematics.

    ``fan_value``: optional ``(theta, direction, ladder (K, B)) -> (K, B)``
    losses for the whole step ladder in one call.  When the model mean is
    polynomial in the step along a ray (Prophet linear-growth models of
    any feature mode: loss.fan_value_closed_form) this replaces K stacked
    model evaluations with closed-form reductions — the trial LOSSES are
    identical to the stacked path up to float32 rounding.

    ``max_iters_dynamic``: optional TRACED scalar overriding
    ``config.max_iters`` as the total-iteration cap (still clamped by it).
    Because ``lax.while_loop`` takes dynamic trip counts, callers can run
    shallow and deep solves through ONE compiled program instead of one
    program per static depth (config.max_iters is part of the jit static
    key) — the bench's two-phase fit shares a single program this way.
    """
    if fun_value is None:
        fun_value = lambda th: fun(th)[0]
    b, p = state.theta.shape
    m = config.history
    cap = (
        config.max_iters if max_iters_dynamic is None
        else jnp.minimum(max_iters_dynamic, config.max_iters)
    )
    stop_at = jnp.minimum(
        state.iteration + (config.max_iters if num_iters is None else num_iters),
        cap,
    )

    def cond(state: LbfgsState):
        return (state.iteration < stop_at) & ~jnp.all(state.converged)

    def body(state: LbfgsState) -> LbfgsState:
        direction = _two_loop_direction(state, m)
        # Descent safeguard: if the two-loop direction is not a descent
        # direction (stale/indefinite history), fall back to the
        # preconditioned steepest descent -D g (D > 0 keeps it a descent
        # direction; D = ones when preconditioning is off).
        pgrad = state.precond * state.grad
        dg = _dot(direction, state.grad)  # (B,)
        bad = dg >= 0
        direction = jnp.where(bad[:, None], -pgrad, direction)
        dg = jnp.where(bad, -_dot(pgrad, state.grad), dg)

        # --- batched-fan Armijo line search ---------------------------------
        # The whole geometric step ladder is evaluated in ONE objective call
        # on a (K+1, B, P) stack (last row = tiny-gradient-step fallback);
        # each series then gathers its largest accepted step.  Identical
        # accepted points to sequential backtracking, at the cost of one
        # fused memory-bound pass instead of up to K+1 sequential ones.
        k_steps = config.ls_max_steps
        # Seed from the last accepted step (grown 4x, capped at init_step):
        # on ill-conditioned series whose usable step is ~2^-15, restarting
        # every search at 1.0 burns the whole backtracking budget and can
        # accept microscopic steps whose decrease trips the ftol test far
        # from the optimum (false convergence).  ls_seed_prev=False always
        # restarts the ladder at init_step.
        step0 = (
            jnp.minimum(state.prev_step * 4.0, config.init_step)
            if config.ls_seed_prev
            else jnp.full_like(state.prev_step, config.init_step)
        )
        shrinks = config.ls_shrink ** jnp.arange(k_steps, dtype=state.f.dtype)
        ladder = step0[None, :] * shrinks[:, None]  # (K, B)

        gnorm = jnp.linalg.norm(pgrad, axis=-1)
        tiny = 1e-3 / jnp.maximum(gnorm, 1.0)
        fb_theta = state.theta - tiny[:, None] * pgrad

        if fan_value is not None:
            # Closed-form ladder (linear-in-parameters objectives): no
            # (K, B, P) trial stack is ever materialized; the fallback row
            # is one direct evaluation, skipped entirely in the common
            # all-accepted case.
            f_trials = fan_value(state.theta, direction, ladder)  # (K, B)
            fb_f = None
        else:
            trials = jnp.concatenate(
                [
                    state.theta[None] + ladder[:, :, None] * direction[None],
                    fb_theta[None],
                ],
                axis=0,
            )  # (K+1, B, P)
            f_all = jax.vmap(fun_value)(trials)  # (K+1, B)
            f_trials, fb_f = f_all[:k_steps], f_all[k_steps]

        ok = jnp.isfinite(f_trials) & (
            f_trials <= state.f[None] + config.ls_armijo_c1 * ladder * dg[None]
        )  # (K, B)
        accepted = jnp.any(ok, axis=0)
        if fb_f is None:
            fb_f = jax.lax.cond(
                jnp.all(accepted | state.converged),
                lambda: jnp.full_like(state.f, jnp.inf),
                lambda: fun_value(fb_theta),
            )
        first = jnp.argmax(ok, axis=0)  # first True = largest accepted step
        bidx = jnp.arange(b)
        step_out = ladder[first, bidx]
        new_theta = jnp.where(
            accepted[:, None],
            state.theta + step_out[:, None] * direction,
            state.theta,
        )
        new_f = jnp.where(accepted, f_trials[first, bidx], state.f)

        # Ladder exhausted: tiny gradient step (keeps making progress on
        # pathological curvature instead of freezing).
        use_fb = ~accepted & jnp.isfinite(fb_f) & (fb_f < state.f)
        new_theta = jnp.where(use_fb[:, None], fb_theta, new_theta)
        new_f = jnp.where(use_fb, fb_f, new_f)
        moved = accepted | use_fb

        # Freeze converged series.
        active = ~state.converged
        new_theta = jnp.where(active[:, None], new_theta, state.theta)
        new_f = jnp.where(active, new_f, state.f)

        _, new_grad = fun(new_theta)

        # --- history update -------------------------------------------------
        s_vec = new_theta - state.theta
        y_vec = new_grad - state.grad
        sy = _dot(s_vec, y_vec)
        valid = (sy > 1e-12) & moved & active
        rho_new = jnp.where(valid, 1.0 / jnp.maximum(sy, 1e-30), 0.0)
        slot = state.iteration % m
        s_hist = state.s_hist.at[slot].set(jnp.where(valid[:, None], s_vec, 0.0))
        y_hist = state.y_hist.at[slot].set(jnp.where(valid[:, None], y_vec, 0.0))
        rho = state.rho.at[slot].set(rho_new)

        # --- convergence ----------------------------------------------------
        f_decrease = (state.f - new_f) / jnp.maximum(jnp.abs(state.f), 1.0)
        g_inf = jnp.max(jnp.abs(new_grad), axis=-1)

        # Float32 noise floor: a series whose accepted decrease is below a
        # few ulps of its objective for several consecutive iterations is
        # stationary *in this precision* — gtol=1e-6 may be unreachable for
        # it, and burning the remaining iteration budget cannot improve it.
        eps = jnp.asarray(jnp.finfo(state.f.dtype).eps, state.f.dtype)
        at_floor = moved & (f_decrease <= config.floor_ulps * eps)
        floor_count = jnp.where(
            active,
            jnp.where(at_floor, state.floor_count + 1, 0),
            state.floor_count,
        )

        hit_gtol = g_inf < config.gtol
        # ftol needs PATIENCE: a single accepted-but-microscopic step (the
        # fan can accept a bottom-rung trial on an ill-conditioned series
        # whose top rungs overshoot) must not read as convergence — round-4
        # measurement on eval config 3 found the whole holdout-delta tail
        # was series "converged" via single-shot ftol at n_iters 2-3 with
        # losses up to 5.5 nats above the oracle.  Only ftol_patience
        # CONSECUTIVE sub-tol iterations end the solve.
        sub_ftol = moved & (f_decrease < config.tol)
        ftol_count = jnp.where(
            active,
            jnp.where(sub_ftol, state.ftol_count + 1, 0),
            state.ftol_count,
        )
        hit_ftol = ftol_count >= config.ftol_patience
        hit_floor = floor_count >= config.floor_patience
        newly = active & (hit_gtol | hit_ftol | hit_floor | ~moved)
        status_new = jnp.where(
            hit_gtol,
            STATUS_GTOL,
            jnp.where(
                hit_ftol,
                STATUS_FTOL,
                jnp.where(hit_floor, STATUS_FLOOR, STATUS_STALLED),
            ),
        ).astype(jnp.int32)
        status = jnp.where(active & newly, status_new, state.status)

        prev_step = jnp.where(
            accepted & active,
            jnp.maximum(step_out, 2.0 ** -16),
            state.prev_step,
        )

        return LbfgsState(
            theta=new_theta,
            f=new_f,
            grad=jnp.where(active[:, None], new_grad, state.grad),
            s_hist=s_hist,
            y_hist=y_hist,
            rho=rho,
            iteration=state.iteration + 1,
            converged=state.converged | newly,
            n_iters=state.n_iters + active.astype(jnp.int32),
            prev_step=prev_step,
            floor_count=floor_count,
            ftol_count=ftol_count,
            status=status,
            precond=state.precond,
        )

    return jax.lax.while_loop(cond, body, state)


def take_state(state: LbfgsState, idx: jnp.ndarray) -> LbfgsState:
    """Gather a row subset of a solver state: the compaction primitive.

    ``idx`` indexes the series axis — axis 0 for the (B, ...) leaves,
    axis 1 for the (M, B, P)/(M, B) history ring; the shared iteration
    counter is carried as-is.  Because every per-series quantity in the
    solver (history ring, rho, line-search step memory, convergence
    counters, preconditioner) is row-local, a gathered state continues
    each selected series' trajectory BITWISE identically to the
    full-width solve — this is what lets a segment scheduler shrink the
    batch to the unconverged set between ``run_segment`` calls
    (tests/test_compaction.py pins the parity).
    """
    idx = jnp.asarray(idx)
    return LbfgsState(
        theta=jnp.take(state.theta, idx, axis=0),
        f=jnp.take(state.f, idx, axis=0),
        grad=jnp.take(state.grad, idx, axis=0),
        s_hist=jnp.take(state.s_hist, idx, axis=1),
        y_hist=jnp.take(state.y_hist, idx, axis=1),
        rho=jnp.take(state.rho, idx, axis=1),
        iteration=state.iteration,
        converged=jnp.take(state.converged, idx, axis=0),
        n_iters=jnp.take(state.n_iters, idx, axis=0),
        prev_step=jnp.take(state.prev_step, idx, axis=0),
        floor_count=jnp.take(state.floor_count, idx, axis=0),
        ftol_count=jnp.take(state.ftol_count, idx, axis=0),
        status=jnp.take(state.status, idx, axis=0),
        precond=jnp.take(state.precond, idx, axis=0),
    )


def minimize(
    fun: Callable[[jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]],
    theta0: jnp.ndarray,
    config: SolverConfig = SolverConfig(),
    fun_value: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = None,
    precond: Optional[jnp.ndarray] = None,
    fan_value=None,
    max_iters_dynamic: Optional[jnp.ndarray] = None,
) -> LbfgsResult:
    """Minimize a batch of independent objectives with shared compute.

    Args:
      fun: (B, P) -> ((B,) per-series losses, (B, P) per-series grads).
      theta0: (B, P) initial parameters.
      fun_value: optional value-only objective for line-search trials
        (defaults to ``fun(th)[0]``, which wastes the gradient).
      precond: optional (B, P) inverse-curvature diagonal (initial metric).
      fan_value: optional closed-form ladder evaluator (see run_segment).
      max_iters_dynamic: optional traced iteration cap (see run_segment).

    Returns:
      LbfgsResult with per-series optimum, loss, grad inf-norm, convergence
      flag and iteration count.
    """
    return to_result(
        run_segment(
            fun, init_state(fun, theta0, config, precond), config,
            fun_value=fun_value, fan_value=fan_value,
            max_iters_dynamic=max_iters_dynamic,
        )
    )
