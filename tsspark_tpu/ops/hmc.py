"""Batched Hamiltonian Monte Carlo with Stan-style warmup adaptation.

The reference's MAP loop (``tsspark.fit.prophet``, BASELINE.json:5) is a
point estimate; upstream Prophet optionally runs full-posterior NUTS via Stan
(``mcmc_samples=N``) to get seasonality uncertainty.  This module is the
TPU-native equivalent: ONE chain PER SERIES, all chains advanced in lockstep
as a single ``lax.scan`` program — a (B, P) leapfrog step is a handful of
fused VPU ops, so 30k chains cost barely more than one.

Adaptation follows Stan's scheme, simplified to two static-shape phases so it
lives inside one scan with no data-dependent control flow:

  phase A (first half of warmup): dual-averaging step size (Nesterov; per
    chain) against a unit metric while a Welford accumulator estimates the
    posterior variance;
  phase B (second half): metric is set to the phase-A variance estimate,
    dual averaging restarts, Welford restarts; at the end the metric is
    updated again and the step size freezes at the averaged iterate.

Momenta are sampled per chain with the diagonal metric M^-1 = var(theta), the
standard choice that rescales ill-conditioned Prophet posteriors (trend rates
vs. Fourier betas live on very different scales).  Trajectory length is a
fixed number of leapfrog steps with multiplicative step-size jitter to avoid
periodic-orbit resonance.  Divergences (non-finite Hamiltonian) auto-reject
for the affected chain only.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from tsspark_tpu.config import McmcConfig

# Dual-averaging constants (Hoffman & Gelman 2014, as used by Stan).
_DA_GAMMA = 0.05
_DA_T0 = 10.0
_DA_KAPPA = 0.75


class _ChainState(NamedTuple):
    theta: jnp.ndarray        # (B, P) current positions
    logp: jnp.ndarray         # (B,)   cached log density
    grad: jnp.ndarray         # (B, P) cached gradient of log density
    inv_mass: jnp.ndarray     # (B, P) diagonal metric M^-1 (~ posterior var)
    # dual averaging (per chain)
    log_step: jnp.ndarray     # (B,)
    log_step_avg: jnp.ndarray # (B,)
    da_stat: jnp.ndarray      # (B,)   running H_t statistic
    da_mu: jnp.ndarray        # (B,)   shrinkage target
    # Welford variance accumulator
    w_count: jnp.ndarray      # ()
    w_mean: jnp.ndarray       # (B, P)
    w_m2: jnp.ndarray         # (B, P)


class HmcResult(NamedTuple):
    samples: jnp.ndarray      # (S, B, P) post-warmup draws
    accept_rate: jnp.ndarray  # (B,) mean acceptance prob over sampling
    step_size: jnp.ndarray    # (B,) adapted step size
    inv_mass: jnp.ndarray     # (B, P) adapted diagonal metric
    divergences: jnp.ndarray  # (B,) divergent-transition count over sampling


def split_rhat_ess(samples) -> Tuple[np.ndarray, np.ndarray]:
    """Split-R-hat and bulk ESS per (series, parameter) from (S, B, P) draws.

    One chain per series is what the lockstep sampler produces, so the
    single chain is split in half (Stan's split-R-hat): the halves disagree
    when the chain is still drifting, which is exactly the non-convergence
    mode a short warmup causes.  ESS follows Stan's FFT autocovariance +
    Geyer initial-monotone-positive-sequence truncation, averaged over the
    two half-chains.  Host numpy: this runs once, after sampling.

    Returns (rhat (B, P), ess (B, P)).
    """
    x = np.asarray(samples, np.float64)
    s = x.shape[0]
    if s < 4:
        raise ValueError(f"need >= 4 draws for split diagnostics, got {s}")
    n = s // 2
    ch = np.stack([x[:n], x[s - n:]], axis=0)          # (2, n, B, P)
    mean_c = ch.mean(axis=1)                           # (2, B, P)
    var_c = ch.var(axis=1, ddof=1)                     # (2, B, P)
    w = var_c.mean(axis=0)                             # within-chain
    b_var = n * mean_c.var(axis=0, ddof=1)             # between-chain
    var_hat = (n - 1) / n * w + b_var / n
    # Degenerate (constant) marginals: perfectly converged by convention.
    degen = (w < 1e-300) | (var_hat < 1e-300)
    rhat = np.where(degen, 1.0, np.sqrt(var_hat / np.where(degen, 1.0, w)))

    # FFT autocovariance per half-chain (biased, as Stan uses).
    xc = ch - mean_c[:, None]
    m = 1
    while m < 2 * n:
        m *= 2
    f = np.fft.rfft(xc, n=m, axis=1)
    acov = np.fft.irfft(f * np.conj(f), n=m, axis=1)[:, :n].real / n
    rho = 1.0 - (w[None] - acov.mean(axis=0)) / np.where(
        degen, 1.0, var_hat
    )[None]                                            # (n, B, P)
    rho[0] = 1.0

    n_pairs = n // 2
    pair = rho[0:2 * n_pairs:2] + rho[1:2 * n_pairs:2]  # (n_pairs, B, P)
    pos = pair > 0
    first_neg = np.argmin(pos, axis=0)                 # 0 when all positive
    k_stop = np.where(pos.all(axis=0), n_pairs, first_neg)
    pair_mono = np.minimum.accumulate(pair, axis=0)
    keep = np.arange(n_pairs)[:, None, None] < k_stop[None]
    # tau = 1 + 2*sum_{t>=1} rho_t = 2*sum_k pair_k - 1  (pair_0 holds rho_0).
    tau = np.maximum(2.0 * (pair_mono * keep).sum(axis=0) - 1.0, 1.0)
    total = 2 * n
    ess = np.where(degen, float(total), np.clip(total / tau, 1.0, total))
    return rhat, ess


def _leapfrog(logdensity_and_grad, theta, r, grad, eps, inv_mass, n_steps):
    """n_steps of leapfrog; eps is per-chain (B, 1)."""

    def step(carry, _):
        th, mom, g = carry
        mom_half = mom + 0.5 * eps * g
        th_new = th + eps * inv_mass * mom_half
        logp_new, g_new = logdensity_and_grad(th_new)
        mom_new = mom_half + 0.5 * eps * g_new
        return (th_new, mom_new, g_new), logp_new

    (theta_f, r_f, grad_f), logps = jax.lax.scan(
        step, (theta, r, grad), None, length=n_steps
    )
    return theta_f, r_f, grad_f, logps[-1]


def _hmc_transition(key, state: _ChainState, logdensity_and_grad, config: McmcConfig):
    """One batched HMC proposal + per-chain Metropolis accept.

    Returns (new_state, accept_prob (B,), divergent (B,)).
    """
    b, p = state.theta.shape
    k_mom, k_jit, k_acc = jax.random.split(key, 3)

    # r ~ N(0, M): std = 1/sqrt(inv_mass).
    z = jax.random.normal(k_mom, (b, p), state.theta.dtype)
    r0 = z / jnp.sqrt(jnp.maximum(state.inv_mass, 1e-12))

    eps = jnp.exp(state.log_step)
    if config.step_jitter > 0:
        # Explicit dtype: uniform's default is the x64-dependent float,
        # and an f64 jitter here would promote the whole leapfrog carry
        # (caught by the analysis contract checker's x64 trace).
        jit = jax.random.uniform(
            k_jit, (b,), dtype=eps.dtype,
            minval=1.0 - config.step_jitter,
            maxval=1.0 + config.step_jitter,
        )
        eps = eps * jit
    eps = eps[:, None]

    theta1, r1, grad1, logp1 = _leapfrog(
        logdensity_and_grad, state.theta, r0, state.grad, eps,
        state.inv_mass, config.num_leapfrog,
    )

    kin0 = 0.5 * jnp.sum(r0 * r0 * state.inv_mass, axis=-1)
    kin1 = 0.5 * jnp.sum(r1 * r1 * state.inv_mass, axis=-1)
    h0 = -state.logp + kin0
    h1 = -logp1 + kin1
    log_alpha = jnp.minimum(0.0, h0 - h1)
    divergent = ~jnp.isfinite(h1) | ((h1 - h0) > config.divergence_threshold)
    accept_prob = jnp.where(divergent, 0.0, jnp.exp(log_alpha))

    u = jax.random.uniform(k_acc, (b,), dtype=accept_prob.dtype)
    accept = (u < accept_prob) & ~divergent
    acc = accept[:, None]
    new_state = state._replace(
        theta=jnp.where(acc, theta1, state.theta),
        logp=jnp.where(accept, logp1, state.logp),
        grad=jnp.where(acc, grad1, state.grad),
    )
    return new_state, accept_prob, divergent


def _da_update(state: _ChainState, accept_prob, i, config: McmcConfig):
    """Per-chain Nesterov dual averaging toward target acceptance."""
    t = i + _DA_T0
    eta = 1.0 / t
    stat = (1.0 - eta) * state.da_stat + eta * (config.target_accept - accept_prob)
    log_step = state.da_mu - jnp.sqrt(t) / _DA_GAMMA * stat
    w = t ** (-_DA_KAPPA)
    log_step_avg = w * log_step + (1.0 - w) * state.log_step_avg
    return state._replace(
        da_stat=stat, log_step=log_step, log_step_avg=log_step_avg
    )


def _welford_update(state: _ChainState, theta):
    c = state.w_count + 1.0
    d = theta - state.w_mean
    mean = state.w_mean + d / c
    m2 = state.w_m2 + d * (theta - mean)
    return state._replace(w_count=c, w_mean=mean, w_m2=m2)


def _welford_var(state: _ChainState, regularize: bool = True):
    n = jnp.maximum(state.w_count - 1.0, 1.0)
    var = state.w_m2 / n
    if regularize:  # lint-ok[trace-branch]: concrete Python bool — every caller passes a literal, so the branch is resolved at trace time (two cached programs, not a tracer branch)
        # Stan's shrinkage toward unit metric for short windows.
        w = state.w_count / (state.w_count + 5.0)
        var = w * var + (1.0 - w) * 1e-3
    return jnp.maximum(var, 1e-10)


def sample(
    logdensity_fn: Callable[[jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]],
    theta0: jnp.ndarray,
    key: jax.Array,
    config: McmcConfig,
) -> HmcResult:
    """Run B parallel HMC chains from theta0 (B, P).

    Args:
      logdensity_fn: (B, P) -> ((B,) log densities, (B, P) gradients).  The
        whole batch in one call — callers use the same one-backward-pass vjp
        trick as the MAP loss.
      theta0: per-chain initial positions (typically the MAP fit, jittered).
      key: PRNG key.
      config: sampler settings.

    Returns:
      HmcResult with (num_samples, B, P) draws.
    """
    theta0 = jnp.asarray(theta0)
    b, p = theta0.shape
    logp0, grad0 = logdensity_fn(theta0)

    init_log_step = jnp.full((b,), jnp.log(config.init_step_size), theta0.dtype)
    state = _ChainState(
        theta=theta0,
        logp=logp0,
        grad=grad0,
        inv_mass=jnp.ones((b, p), theta0.dtype),
        log_step=init_log_step,
        log_step_avg=init_log_step,
        da_stat=jnp.zeros((b,), theta0.dtype),
        da_mu=jnp.log(10.0) + init_log_step,
        w_count=jnp.zeros((), theta0.dtype),
        w_mean=jnp.zeros((b, p), theta0.dtype),
        w_m2=jnp.zeros((b, p), theta0.dtype),
    )

    warmup = config.num_warmup
    phase_a = warmup // 2

    def warmup_step(carry, inp):
        state, da_i = carry
        i, k = inp
        state, accept_prob, _ = _hmc_transition(k, state, logdensity_fn, config)
        state = _da_update(state, accept_prob, da_i, config)
        state = _welford_update(state, state.theta)

        # Phase switch: install the estimated metric, restart adaptation.
        def switch(s: _ChainState) -> _ChainState:
            var = _welford_var(s)
            ls = s.log_step_avg  # keep the adapted scale as the new start
            return s._replace(
                inv_mass=var,
                log_step=ls,
                log_step_avg=ls,
                da_stat=jnp.zeros_like(s.da_stat),
                da_mu=jnp.log(10.0) + ls,
                w_count=jnp.zeros_like(s.w_count),
                w_mean=jnp.zeros_like(s.w_mean),
                w_m2=jnp.zeros_like(s.w_m2),
            )

        at_switch = i == (phase_a - 1)
        state = jax.tree.map(
            lambda a, b_: jnp.where(at_switch, a, b_), switch(state), state
        )
        da_i = jnp.where(at_switch, 0.0, da_i + 1.0)
        return (state, da_i), None

    keys = jax.random.split(key, warmup + config.num_samples + 1)
    (state, _), _ = jax.lax.scan(
        warmup_step,
        (state, jnp.ones((), theta0.dtype)),
        (jnp.arange(warmup), keys[:warmup]),
    )

    # Freeze: final metric from phase-B stats, step size = averaged iterate.
    state = state._replace(
        inv_mass=_welford_var(state),
        log_step=state.log_step_avg,
    )

    def sample_step(state, k):
        state, accept_prob, divergent = _hmc_transition(
            k, state, logdensity_fn, config
        )
        return state, (state.theta, accept_prob, divergent)

    state, (draws, accepts, divs) = jax.lax.scan(
        sample_step, state, keys[warmup : warmup + config.num_samples]
    )

    return HmcResult(
        samples=draws,
        accept_rate=accepts.mean(axis=0),
        step_size=jnp.exp(state.log_step),
        inv_mass=state.inv_mass,
        divergences=divs.sum(axis=0).astype(jnp.int32),
    )
