"""Real-dataset file loaders: M5 (Kaggle format) and M4 competition CSVs.

The synthetic generators in :mod:`tsspark_tpu.data.datasets` stand in when no
data files exist on the machine (this image has zero egress); these loaders
read the ACTUAL competition file formats so a user with the real files gets
the real benchmarks:

  * M5: ``sales_train_validation.csv`` (wide: id, item/dept/cat/store/state
    ids, then d_1..d_N unit-sales columns), ``calendar.csv`` (maps d_k to
    dates, events, SNAP flags), ``sell_prices.csv`` (store_id, item_id,
    wm_yr_wk, sell_price).  Produces the same (B, T) + regressor layout the
    bench/eval config-3 path consumes: holiday indicator (any event day),
    per-series price, per-series SNAP/promo flag.
  * M4: ``<Freq>-train.csv`` (id, V1..Vmax, ragged rows padded with NaN) with
    a synthetic hourly/daily calendar grid (M4 publishes no timestamps —
    frequency only), matching eval config 2's batched layout.

Everything returns :class:`~tsspark_tpu.data.datasets.SeriesBatch`; parsing
is pandas/numpy host-side work.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np
import pandas as pd

from tsspark_tpu.data.datasets import SeriesBatch

_EPOCH = pd.Timestamp("1970-01-01")


def load_m5(
    sales_csv: str,
    calendar_csv: str,
    prices_csv: Optional[str] = None,
    n_series: Optional[int] = None,
) -> SeriesBatch:
    """Load the Kaggle M5 file set into the eval-config-3 batch layout.

    Args:
      sales_csv: sales_train_validation.csv / sales_train_evaluation.csv.
      calendar_csv: calendar.csv (d_k -> date, events, SNAP flags).
      prices_csv: optional sell_prices.csv; without it the price regressor
        is constant zero (standardization then neutralizes the column).
      n_series: optional row limit (full file = 30,490 series).

    Returns:
      SeriesBatch with regressors (B, T, 3) = [holiday, price, promo],
      matching bench.py's model config for eval config 3.
    """
    sales = pd.read_csv(sales_csv, nrows=n_series)
    cal = pd.read_csv(calendar_csv)
    d_cols = [c for c in sales.columns if c.startswith("d_")]
    # Calendar rows beyond the sales horizon (the 28-day eval tail) drop.
    cal = cal.set_index("d").loc[d_cols].reset_index()
    dates = pd.to_datetime(cal["date"])
    ds = ((dates - _EPOCH) / pd.Timedelta(days=1)).to_numpy(np.float64)

    y = sales[d_cols].to_numpy(np.float64)
    b, t_len = y.shape
    mask = np.ones_like(y)

    # Holiday indicator: any named event that day (either event slot).
    holiday = np.zeros(t_len)
    for col in ("event_name_1", "event_name_2"):
        if col in cal.columns:
            holiday = np.maximum(holiday, cal[col].notna().to_numpy(float))
    holiday_b = np.broadcast_to(holiday, (b, t_len))

    # SNAP/promo flag: the series' own state's SNAP column.
    snap_cols = {c[len("snap_"):]: c for c in cal.columns
                 if c.startswith("snap_")}
    if snap_cols and "state_id" in sales.columns:
        snap_by_state = {
            st: cal[col].to_numpy(float) for st, col in snap_cols.items()
        }
        promo = np.stack([
            snap_by_state.get(st, np.zeros(t_len))
            for st in sales["state_id"].astype(str)
        ])
    else:
        promo = np.zeros((b, t_len))

    # Price: weekly sell_price joined on (store_id, item_id, wm_yr_wk),
    # forward/back-filled over weeks the item was not listed.
    price = np.zeros((b, t_len))
    if prices_csv is not None and os.path.exists(prices_csv):
        prices = pd.read_csv(prices_csv)
        wk = cal["wm_yr_wk"].to_numpy()
        key = prices.set_index(["store_id", "item_id", "wm_yr_wk"])[
            "sell_price"
        ]
        for i, (store, item) in enumerate(
            zip(sales["store_id"].astype(str), sales["item_id"].astype(str))
        ):
            try:
                by_wk = key.loc[(store, item)]
            except KeyError:
                continue
            series = pd.Series(wk).map(by_wk).ffill().bfill()
            price[i] = series.fillna(0.0).to_numpy(np.float64)

    reg = np.stack([holiday_b, price, promo], axis=-1)
    return SeriesBatch(
        ds=ds, y=y, mask=mask,
        series_ids=sales["id"].astype(str).to_numpy(),
        regressors=reg,
        regressor_names=("holiday", "price", "promo"),
    )


def load_m4(
    train_csv: str,
    freq_hours: float = 1.0,
    start_day: float = 17167.0,
    n_series: Optional[int] = None,
) -> SeriesBatch:
    """Load an M4 competition training CSV (id, V1..Vmax; ragged rows).

    M4 publishes frequencies but not timestamps, so rows are placed on a
    shared synthetic grid at ``freq_hours`` spacing, RIGHT-ALIGNED the way
    the M4 evaluation treats series (each series' last observation is the
    common forecast origin); leading entries of shorter series are NaN and
    masked.
    """
    df = pd.read_csv(train_csv, nrows=n_series)
    ids = df.iloc[:, 0].astype(str).to_numpy()
    vals = df.iloc[:, 1:].to_numpy(np.float64)
    lengths = (~np.isnan(vals)).sum(axis=1)
    t_len = int(lengths.max())
    b = len(ids)
    y = np.full((b, t_len), np.nan)
    for i in range(b):
        n = lengths[i]
        y[i, t_len - n:] = vals[i, :n]
    mask = (~np.isnan(y)).astype(np.float64)
    step = freq_hours / 24.0
    ds = start_day + step * np.arange(t_len, dtype=np.float64)
    return SeriesBatch(ds=ds, y=y, mask=mask, series_ids=ids)
