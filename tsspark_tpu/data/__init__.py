"""Public data API: generators, loaders, and the columnar data plane.

Call sites import from here instead of deep-importing submodules::

    from tsspark_tpu import data
    batch = data.m5_like(n_series=512)
    ddir = data.ensure(data.DatasetSpec("m5", 30490, 1941))

The CSV loaders (pandas-backed) resolve lazily so importing the package
in a lean child process (an orchestrate fit worker, the ingest pool)
never pays the pandas import.
"""

from __future__ import annotations

from tsspark_tpu.data.datasets import (
    SEED_BLOCK,
    SeriesBatch,
    dataset_ids,
    demo_weekly_rows,
    m4_hourly_like,
    m5_like,
    m5_rows,
    peyton_manning_like,
    wiki_logistic_like,
)
from tsspark_tpu.data.plane import (
    DatasetSpec,
    GENERATORS,
    advanced_since,
    dataset_fingerprint,
    default_root,
    delta_seq,
    ensure,
    generate_rows,
    import_batch,
    land_delta,
    land_synthetic_delta,
    open_batch,
    ready_coverage,
)

__all__ = [
    "SEED_BLOCK", "SeriesBatch", "dataset_ids", "demo_weekly_rows",
    "m4_hourly_like", "m5_like", "m5_rows", "peyton_manning_like",
    "wiki_logistic_like",
    "DatasetSpec", "GENERATORS", "advanced_since",
    "dataset_fingerprint", "default_root", "delta_seq", "ensure",
    "generate_rows", "import_batch", "land_delta",
    "land_synthetic_delta", "open_batch", "ready_coverage",
    "load_m4", "load_m5",
]

_LAZY = {"load_m4", "load_m5"}


def __getattr__(name: str):
    if name in _LAZY:
        from tsspark_tpu.data import loaders

        return getattr(loaders, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
