"""Synthetic dataset generators standing in for the driver's eval datasets.

The machine has zero network egress and no bundled copies of Peyton-Manning /
M4 / M5 / Wikipedia-pageviews, so each generator produces series with the
same shape, calendar, and statistical character as its namesake
(BASELINE.json:7-11): sizes match (414 series for M4-Hourly, 30,490 for M5),
and the generating processes exercise exactly the model features each eval
config targets (changepoints, multi-seasonality, holidays/external
regressors, logistic saturation, warm-start drift).

All generators are deterministic in their seed and return plain numpy arrays
(host-side data prep; device work starts at prepare_fit_data).
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional

import numpy as np


class SeriesBatch(NamedTuple):
    """A padded batch of series on a shared calendar grid."""

    ds: np.ndarray            # (T,) absolute days since epoch
    y: np.ndarray             # (B, T) observations, NaN where missing
    mask: np.ndarray          # (B, T) 1.0 where observed
    series_ids: np.ndarray    # (B,) string ids
    cap: Optional[np.ndarray] = None         # (B, T) logistic capacity
    regressors: Optional[np.ndarray] = None  # (B, T, R)
    regressor_names: tuple = ()


def _trend_with_changepoints(rng, t, n_cp=4, base_slope=1.0, cp_scale=1.5):
    """Piecewise-linear trend on t in [0, 1]."""
    cps = np.sort(rng.uniform(0.05, 0.9, n_cp))
    deltas = rng.normal(0, cp_scale, n_cp)
    g = base_slope * t
    for c, d in zip(cps, deltas):
        g = g + d * np.maximum(t - c, 0.0)
    return g


def peyton_manning_like(
    n_days: int = 2905, start_day: float = 10957.0, seed: int = 0
) -> SeriesBatch:
    """One daily series shaped like log Wikipedia pageviews of a celebrity:
    ~8 years, strong yearly + weekly seasonality, a few trend changepoints,
    heavy-ish noise, occasional missing days.  Stands in for eval config 1."""
    rng = np.random.default_rng(seed)
    ds = start_day + np.arange(n_days, dtype=np.float64)
    t = np.linspace(0, 1, n_days)
    trend = 8.0 + _trend_with_changepoints(rng, t, n_cp=5, base_slope=-0.5)
    yearly = (
        0.45 * np.sin(2 * np.pi * ds / 365.25)
        + 0.25 * np.cos(2 * np.pi * ds / 365.25)
        + 0.18 * np.sin(4 * np.pi * ds / 365.25)
    )
    dow = ds.astype(np.int64) % 7
    weekly = np.asarray([0.12, 0.3, 0.22, 0.18, 0.1, -0.35, -0.42])[dow]
    y = trend + yearly + weekly + rng.normal(0, 0.25, n_days)
    miss = rng.uniform(size=n_days) < 0.02
    y[miss] = np.nan
    mask = (~miss).astype(np.float64)
    return SeriesBatch(
        ds=ds, y=y[None, :], mask=mask[None, :],
        series_ids=np.asarray(["peyton_manning_like"]),
    )


def m4_hourly_like(
    n_series: int = 414, max_len: int = 960, seed: int = 1,
    min_len: Optional[int] = None,
) -> SeriesBatch:
    """414 hourly series with daily + weekly seasonality and ragged lengths
    (M4-Hourly lengths span 700-960).  Stands in for eval config 2."""
    rng = np.random.default_rng(seed)
    if min_len is None:
        min_len = min(700, max(2, int(0.73 * max_len)))
    hours = np.arange(max_len, dtype=np.float64)
    ds = 15000.0 + hours / 24.0  # days, hourly grid
    y = np.full((n_series, max_len), np.nan)
    mask = np.zeros((n_series, max_len))
    lengths = rng.integers(min_len, max_len + 1, n_series)
    for i in range(n_series):
        n = lengths[i]
        t = np.linspace(0, 1, n)
        level = rng.uniform(10, 5000)
        trend = level * (1 + 0.3 * _trend_with_changepoints(rng, t, 3, 0.5, 0.8))
        hod = ds[:n] * 24 % 24
        daily = 0.25 * level * np.sin(2 * np.pi * hod / 24 + rng.uniform(0, 2 * np.pi))
        daily += 0.1 * level * np.sin(4 * np.pi * hod / 24 + rng.uniform(0, 2 * np.pi))
        dow = (ds[:n].astype(np.int64)) % 7
        weekly = 0.12 * level * np.asarray(
            [1.0, 0.9, 0.85, 0.9, 1.0, 1.3, 1.4]
        )[dow] - 0.12 * level
        noise = rng.normal(0, 0.05 * level, n)
        # Right-align on the shared grid (all series end "now", like M4).
        y[i, max_len - n:] = (trend + daily + weekly + noise)[:n]
        mask[i, max_len - n:] = 1.0
    ids = np.asarray([f"H{i+1}" for i in range(n_series)])
    return SeriesBatch(ds=ds, y=y, mask=mask, series_ids=ids)


def m5_like(
    n_series: int = 30490, n_days: int = 1941, seed: int = 2,
    with_regressors: bool = True,
) -> SeriesBatch:
    """M5-scale retail batch: 30,490 daily series, 1,941 days, holiday
    indicator + price + promo regressors.  Stands in for eval config 3.

    Generation is vectorized (30k x 1941 is ~59M points; a Python loop over
    series would take minutes)."""
    rng = np.random.default_rng(seed)
    ds = 13514.0 + np.arange(n_days, dtype=np.float64)
    t = np.linspace(0, 1, n_days)

    level = rng.lognormal(1.0, 1.0, (n_series, 1))
    slope = rng.normal(0.2, 0.4, (n_series, 1))
    n_cp = 3
    cps = np.sort(rng.uniform(0.1, 0.9, (n_series, n_cp)), axis=-1)
    deltas = rng.normal(0, 0.5, (n_series, n_cp))
    trend = 1.0 + slope * t[None, :]
    for j in range(n_cp):
        trend += deltas[:, j : j + 1] * np.maximum(t[None, :] - cps[:, j : j + 1], 0)

    dow = ds.astype(np.int64) % 7
    wk_pattern = rng.normal(0, 0.15, (n_series, 7))
    weekly = np.take_along_axis(
        wk_pattern, np.broadcast_to(dow[None, :], (n_series, n_days)), axis=1
    )
    yearly_phase = rng.uniform(0, 2 * np.pi, (n_series, 1))
    yearly = 0.2 * np.sin(2 * np.pi * ds[None, :] / 365.25 + yearly_phase)

    # Holiday calendar: ~12 fixed days/year, shared; per-series effect size.
    doy = ds.astype(np.int64) % 365
    holiday_days = np.asarray([0, 31, 59, 120, 151, 185, 243, 304, 327, 330, 358, 359])
    is_holiday = np.isin(doy, holiday_days).astype(np.float64)
    hol_effect = rng.normal(0.3, 0.2, (n_series, 1))

    price = 1.0 + 0.1 * np.cumsum(rng.normal(0, 0.02, (n_series, n_days)), axis=1)
    promo = (rng.uniform(size=(n_series, n_days)) < 0.05).astype(np.float64)
    price_beta = rng.normal(-0.3, 0.1, (n_series, 1))
    promo_beta = rng.normal(0.4, 0.15, (n_series, 1))

    signal = (
        trend
        + weekly
        + yearly
        + hol_effect * is_holiday[None, :]
        + price_beta * (price - 1.0)
        + promo_beta * promo
    )
    y = level * np.maximum(signal + rng.normal(0, 0.15, (n_series, n_days)), 0.0)

    # Leading zeros before "product launch" (M5's onset pattern): mask them.
    launch = rng.integers(0, n_days // 3, n_series)
    mask = (np.arange(n_days)[None, :] >= launch[:, None]).astype(np.float64)
    y = np.where(mask > 0, y, np.nan)

    reg = None
    names: tuple = ()
    if with_regressors:
        reg = np.stack([is_holiday[None, :].repeat(n_series, 0), price, promo], axis=-1)
        names = ("holiday", "price", "promo")
    ids = np.asarray([f"M5_{i:05d}" for i in range(n_series)])
    return SeriesBatch(
        ds=ds, y=y, mask=mask, series_ids=ids, regressors=reg,
        regressor_names=names,
    )


def wiki_logistic_like(
    n_series: int = 8, n_days: int = 1200, seed: int = 3
) -> SeriesBatch:
    """Saturating-growth pageview series with known capacity (eval config 4)."""
    rng = np.random.default_rng(seed)
    ds = 14000.0 + np.arange(n_days, dtype=np.float64)
    t = np.linspace(0, 1, n_days)
    caps = rng.uniform(5e3, 5e4, (n_series, 1))
    k = rng.uniform(4, 10, (n_series, 1))
    m = rng.uniform(0.2, 0.5, (n_series, 1))
    base = caps / (1.0 + np.exp(-k * (t[None, :] - m)))
    dow = ds.astype(np.int64) % 7
    weekly_mult = 1.0 + 0.1 * np.asarray([0.5, 1, 0.8, 0.6, 0.2, -1.5, -1.8])[dow]
    y = base * weekly_mult[None, :] * (1 + rng.normal(0, 0.04, (n_series, n_days)))
    ids = np.asarray([f"wiki_{i}" for i in range(n_series)])
    return SeriesBatch(
        ds=ds, y=y, mask=np.ones_like(y), series_ids=ids,
        cap=np.broadcast_to(caps * 1.1, y.shape).copy(),
    )
