"""Synthetic dataset generators standing in for the driver's eval datasets.

The machine has zero network egress and no bundled copies of Peyton-Manning /
M4 / M5 / Wikipedia-pageviews, so each generator produces series with the
same shape, calendar, and statistical character as its namesake
(BASELINE.json:7-11): sizes match (414 series for M4-Hourly, 30,490 for M5),
and the generating processes exercise exactly the model features each eval
config targets (changepoints, multi-seasonality, holidays/external
regressors, logistic saturation, warm-start drift).

All generators are deterministic in their seed and return plain numpy arrays
(host-side data prep; device work starts at prepare_fit_data).
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional

import numpy as np


class SeriesBatch(NamedTuple):
    """A padded batch of series on a shared calendar grid."""

    ds: np.ndarray            # (T,) absolute days since epoch
    y: np.ndarray             # (B, T) observations, NaN where missing
    mask: np.ndarray          # (B, T) 1.0 where observed
    series_ids: np.ndarray    # (B,) string ids
    cap: Optional[np.ndarray] = None         # (B, T) logistic capacity
    regressors: Optional[np.ndarray] = None  # (B, T, R)
    regressor_names: tuple = ()


def _trend_with_changepoints(rng, t, n_cp=4, base_slope=1.0, cp_scale=1.5):
    """Piecewise-linear trend on t in [0, 1]."""
    cps = np.sort(rng.uniform(0.05, 0.9, n_cp))
    deltas = rng.normal(0, cp_scale, n_cp)
    g = base_slope * t
    for c, d in zip(cps, deltas):
        g = g + d * np.maximum(t - c, 0.0)
    return g


def peyton_manning_like(
    n_days: int = 2905, start_day: float = 10957.0, seed: int = 0
) -> SeriesBatch:
    """One daily series shaped like log Wikipedia pageviews of a celebrity:
    ~8 years, strong yearly + weekly seasonality, a few trend changepoints,
    heavy-ish noise, occasional missing days.  Stands in for eval config 1."""
    rng = np.random.default_rng(seed)
    ds = start_day + np.arange(n_days, dtype=np.float64)
    t = np.linspace(0, 1, n_days)
    trend = 8.0 + _trend_with_changepoints(rng, t, n_cp=5, base_slope=-0.5)
    yearly = (
        0.45 * np.sin(2 * np.pi * ds / 365.25)
        + 0.25 * np.cos(2 * np.pi * ds / 365.25)
        + 0.18 * np.sin(4 * np.pi * ds / 365.25)
    )
    dow = ds.astype(np.int64) % 7
    weekly = np.asarray([0.12, 0.3, 0.22, 0.18, 0.1, -0.35, -0.42])[dow]
    y = trend + yearly + weekly + rng.normal(0, 0.25, n_days)
    miss = rng.uniform(size=n_days) < 0.02
    y[miss] = np.nan
    mask = (~miss).astype(np.float64)
    return SeriesBatch(
        ds=ds, y=y[None, :], mask=mask[None, :],
        series_ids=np.asarray(["peyton_manning_like"]),
    )


def m4_hourly_like(
    n_series: int = 414, max_len: int = 960, seed: int = 1,
    min_len: Optional[int] = None,
) -> SeriesBatch:
    """414 hourly series with daily + weekly seasonality and ragged lengths
    (M4-Hourly lengths span 700-960).  Stands in for eval config 2."""
    rng = np.random.default_rng(seed)
    if min_len is None:
        min_len = min(700, max(2, int(0.73 * max_len)))
    hours = np.arange(max_len, dtype=np.float64)
    ds = 15000.0 + hours / 24.0  # days, hourly grid
    y = np.full((n_series, max_len), np.nan)
    mask = np.zeros((n_series, max_len))
    lengths = rng.integers(min_len, max_len + 1, n_series)
    for i in range(n_series):
        n = lengths[i]
        t = np.linspace(0, 1, n)
        level = rng.uniform(10, 5000)
        trend = level * (1 + 0.3 * _trend_with_changepoints(rng, t, 3, 0.5, 0.8))
        hod = ds[:n] * 24 % 24
        daily = 0.25 * level * np.sin(2 * np.pi * hod / 24 + rng.uniform(0, 2 * np.pi))
        daily += 0.1 * level * np.sin(4 * np.pi * hod / 24 + rng.uniform(0, 2 * np.pi))
        dow = (ds[:n].astype(np.int64)) % 7
        weekly = 0.12 * level * np.asarray(
            [1.0, 0.9, 0.85, 0.9, 1.0, 1.3, 1.4]
        )[dow] - 0.12 * level
        noise = rng.normal(0, 0.05 * level, n)
        # Right-align on the shared grid (all series end "now", like M4).
        y[i, max_len - n:] = (trend + daily + weekly + noise)[:n]
        mask[i, max_len - n:] = 1.0
    ids = np.asarray([f"H{i+1}" for i in range(n_series)])
    return SeriesBatch(ds=ds, y=y, mask=mask, series_ids=ids)


def m5_like(
    n_series: int = 30490, n_days: int = 1941, seed: int = 2,
    with_regressors: bool = True,
) -> SeriesBatch:
    """M5-scale retail batch: 30,490 daily series, 1,941 days, holiday
    indicator + price + promo regressors.  Stands in for eval config 3.

    Generation is vectorized (30k x 1941 is ~59M points; a Python loop over
    series would take minutes)."""
    rng = np.random.default_rng(seed)
    ds = 13514.0 + np.arange(n_days, dtype=np.float64)
    t = np.linspace(0, 1, n_days)

    level = rng.lognormal(1.0, 1.0, (n_series, 1))
    slope = rng.normal(0.2, 0.4, (n_series, 1))
    n_cp = 3
    cps = np.sort(rng.uniform(0.1, 0.9, (n_series, n_cp)), axis=-1)
    deltas = rng.normal(0, 0.5, (n_series, n_cp))
    trend = 1.0 + slope * t[None, :]
    for j in range(n_cp):
        trend += deltas[:, j : j + 1] * np.maximum(t[None, :] - cps[:, j : j + 1], 0)

    dow = ds.astype(np.int64) % 7
    wk_pattern = rng.normal(0, 0.15, (n_series, 7))
    weekly = np.take_along_axis(
        wk_pattern, np.broadcast_to(dow[None, :], (n_series, n_days)), axis=1
    )
    yearly_phase = rng.uniform(0, 2 * np.pi, (n_series, 1))
    yearly = 0.2 * np.sin(2 * np.pi * ds[None, :] / 365.25 + yearly_phase)

    # Holiday calendar: ~12 fixed days/year, shared; per-series effect size.
    doy = ds.astype(np.int64) % 365
    holiday_days = np.asarray([0, 31, 59, 120, 151, 185, 243, 304, 327, 330, 358, 359])
    is_holiday = np.isin(doy, holiday_days).astype(np.float64)
    hol_effect = rng.normal(0.3, 0.2, (n_series, 1))

    price = 1.0 + 0.1 * np.cumsum(rng.normal(0, 0.02, (n_series, n_days)), axis=1)
    promo = (rng.uniform(size=(n_series, n_days)) < 0.05).astype(np.float64)
    price_beta = rng.normal(-0.3, 0.1, (n_series, 1))
    promo_beta = rng.normal(0.4, 0.15, (n_series, 1))

    signal = (
        trend
        + weekly
        + yearly
        + hol_effect * is_holiday[None, :]
        + price_beta * (price - 1.0)
        + promo_beta * promo
    )
    y = level * np.maximum(signal + rng.normal(0, 0.15, (n_series, n_days)), 0.0)

    # Leading zeros before "product launch" (M5's onset pattern): mask them.
    launch = rng.integers(0, n_days // 3, n_series)
    mask = (np.arange(n_days)[None, :] >= launch[:, None]).astype(np.float64)
    y = np.where(mask > 0, y, np.nan)

    reg = None
    names: tuple = ()
    if with_regressors:
        reg = np.stack([is_holiday[None, :].repeat(n_series, 0), price, promo], axis=-1)
        names = ("holiday", "price", "promo")
    ids = np.asarray([f"M5_{i:05d}" for i in range(n_series)])
    return SeriesBatch(
        ds=ds, y=y, mask=mask, series_ids=ids, regressors=reg,
        regressor_names=names,
    )


# ---------------------------------------------------------------------------
# block-seeded row generators (the data plane's canonical generation)
# ---------------------------------------------------------------------------
#
# The whole-batch generators above draw one sequential rng stream over the
# full batch, so rows [lo, hi) cannot be generated without generating
# everything before them — which is exactly what made datagen 74% of the
# bench wall (BENCH_builder_r06).  The generators below seed per FIXED
# block of ``SEED_BLOCK`` rows instead: any row range can be produced
# independently (and in parallel processes) and is bitwise-identical to
# the same rows of a full-batch call, because both are slices of the same
# per-block streams.  ``tsspark_tpu.data.plane`` builds its shard cache on
# this property; the seeding-block width is part of the data's identity
# and must NEVER change without rotating the datagen fingerprint.

#: Rows per seeding block.  Fixed — independent of the plane's I/O shard
#: width and of the orchestrator's claim widths, so retuning either never
#: changes the generated data.
SEED_BLOCK = 1024

#: M5-like hierarchy shape (store -> dept -> item): row i belongs to
#: store i % 10, dept (i // 10) % 7 — every block mixes all stores.
HIER_STORES = 10
HIER_DEPTS = 7


def _block_rng(seed: int, block: int, tag: int = 0):
    """The rng for one (seed, block) cell.  ``tag`` separates auxiliary
    streams (e.g. the hierarchy's shared level tables) from the row
    stream so adding one never shifts the other."""
    return np.random.default_rng(
        [0x7355, int(seed) & 0xFFFFFFFF, int(block), int(tag)]
    )


def dataset_calendar(generator: str, n_timesteps: int) -> np.ndarray:
    """The shared float64 calendar grid of a named block generator —
    a closed formula, so dataset creation never has to generate a full
    seed block just to learn the grid.  Pinned equal to the grid the
    row generators emit by tests/test_plane.py."""
    if generator == "demo_weekly":
        return np.arange(n_timesteps, dtype=np.float64)
    return 13514.0 + np.arange(n_timesteps, dtype=np.float64)


def _zero_pad(idx: np.ndarray, width: int) -> np.ndarray:
    """``f"{i:0{width}d}"`` vectorized.  ``np.char.zfill`` TRUNCATES to
    its width argument, so values with more natural digits than
    ``width`` (row 10000 of a 4-wide scheme — exactly the million-series
    regime) must keep their own digits, like the f-spec does."""
    # Explicit natural width: int->str astype defaults to U21 (int64's
    # worst case), which would quadruple the id columns' bytes at 1M
    # rows for digits no id ever uses.
    natw = len(str(int(idx.max()))) if idx.size else 1
    s = idx.astype(f"<U{natw}")
    maxw = max(width, natw)
    out = s.astype(f"<U{maxw}")
    short = np.char.str_len(s) < width
    if short.any():
        out[short] = np.char.zfill(s[short], width)
    return out


def dataset_ids(generator: str, lo: int, hi: int) -> np.ndarray:
    """Series ids for rows [lo, hi) of a named block generator —
    deterministic formulas, so a warm cache reader never regenerates
    data just to learn the ids.  Vectorized end to end: the former
    per-row f-string comprehension was an O(n_series) interpreter pass
    on every publish and scale-ladder rung (ROADMAP item 2; at 1M
    series it dominated the id path)."""
    idx = np.arange(lo, hi)
    if generator == "m5_hier":
        store = idx % HIER_STORES
        dept = (idx // HIER_STORES) % HIER_DEPTS
        item = idx // (HIER_STORES * HIER_DEPTS)
        # Width-bounded astypes: a bare astype(np.str_) defaults to U21
        # per component and np.char.add SUMS itemsizes, which would
        # quadruple the id columns' bytes for digits no id ever uses.
        s_w = len(str(HIER_STORES - 1))
        d_w = len(str(HIER_DEPTS - 1))
        out = np.char.add("S", store.astype(f"<U{s_w}"))
        out = np.char.add(out, "_D")
        out = np.char.add(out, dept.astype(f"<U{d_w}"))
        out = np.char.add(out, "_I")
        return np.char.add(out, _zero_pad(item, 5))
    if generator == "demo_weekly":
        return np.char.add("s", _zero_pad(idx, 4))
    return np.char.add("M5_", _zero_pad(idx, 5))


def _m5_block(rng, n_days: int, ds: np.ndarray, scenario: str,
              seed: int, row0: int):
    """One full SEED_BLOCK of m5-like rows (same generating process as
    :func:`m5_like`, per-block stream).  Returns (y, mask, reg)."""
    S = SEED_BLOCK
    t = np.linspace(0, 1, n_days)
    level = rng.lognormal(1.0, 1.0, (S, 1))
    slope = rng.normal(0.2, 0.4, (S, 1))
    n_cp = 3
    cps = np.sort(rng.uniform(0.1, 0.9, (S, n_cp)), axis=-1)
    deltas = rng.normal(0, 0.5, (S, n_cp))
    trend = 1.0 + slope * t[None, :]
    for j in range(n_cp):
        trend += deltas[:, j:j + 1] * np.maximum(t[None, :] - cps[:, j:j + 1], 0)

    dow = ds.astype(np.int64) % 7
    wk_pattern = rng.normal(0, 0.15, (S, 7))
    weekly = np.take_along_axis(
        wk_pattern, np.broadcast_to(dow[None, :], (S, n_days)), axis=1
    )
    yearly_phase = rng.uniform(0, 2 * np.pi, (S, 1))
    if scenario == "hier":
        # Shared store/dept structure: level and seasonality phase are
        # composed from per-store/per-dept tables drawn from a dedicated
        # stream (a function of the seed only — every block must see the
        # SAME tables).
        trng = _block_rng(seed, 0, tag=1)
        store_boost = trng.normal(0, 0.5, HIER_STORES)
        dept_boost = trng.normal(0, 0.35, HIER_DEPTS)
        store_phase = trng.uniform(0, 2 * np.pi, HIER_STORES)
        idx = np.arange(row0, row0 + S)
        store = idx % HIER_STORES
        dept = (idx // HIER_STORES) % HIER_DEPTS
        level = level * np.exp(store_boost[store] + dept_boost[dept])[:, None]
        yearly_phase = (store_phase[store][:, None]
                        + 0.2 * (yearly_phase - np.pi))
    yearly = 0.2 * np.sin(2 * np.pi * ds[None, :] / 365.25 + yearly_phase)

    doy = ds.astype(np.int64) % 365
    holiday_days = np.asarray(
        [0, 31, 59, 120, 151, 185, 243, 304, 327, 330, 358, 359]
    )
    is_holiday = np.isin(doy, holiday_days).astype(np.float64)
    hol_effect = rng.normal(0.3, 0.2, (S, 1))

    price = 1.0 + 0.1 * np.cumsum(rng.normal(0, 0.02, (S, n_days)), axis=1)
    promo = (rng.uniform(size=(S, n_days)) < 0.05).astype(np.float64)
    price_beta = rng.normal(-0.3, 0.1, (S, 1))
    promo_beta = rng.normal(0.4, 0.15, (S, 1))

    signal = (
        trend + weekly + yearly
        + hol_effect * is_holiday[None, :]
        + price_beta * (price - 1.0)
        + promo_beta * promo
    )
    y = level * np.maximum(signal + rng.normal(0, 0.15, (S, n_days)), 0.0)

    if scenario == "cold_start":
        # Half the block launches with only the trailing 2-30% of the
        # calendar observed — the late-onset series a production fleet
        # keeps gaining.
        late = rng.uniform(size=S) < 0.5
        launch = np.where(
            late,
            rng.integers(int(0.70 * n_days), max(int(0.98 * n_days), 1), S),
            rng.integers(0, max(n_days // 3, 1), S),
        )
    else:
        launch = rng.integers(0, max(n_days // 3, 1), S)
    mask = (np.arange(n_days)[None, :] >= launch[:, None]).astype(np.float64)

    if scenario == "irregular":
        # Irregular cadence: per-series dropout of 5-40% of otherwise
        # observed days, so the observed grid is ragged within the
        # shared calendar (exercises the mask path end to end).
        rate = rng.uniform(0.05, 0.4, (S, 1))
        drop = rng.uniform(size=(S, n_days)) < rate
        mask = np.where(drop, 0.0, mask)
    elif scenario == "missing_windows":
        # 1-3 contiguous outage windows per series, each ~3-10% of the
        # calendar (sensor gaps, stockouts).
        k = 3
        starts = rng.integers(0, n_days, (S, k))
        lens = rng.integers(max(n_days // 33, 2), max(n_days // 10, 3),
                            (S, k))
        active = rng.uniform(size=(S, k)) < 0.7
        grid = np.arange(n_days)
        win = ((grid[None, None, :] >= starts[:, :, None])
               & (grid[None, None, :] < (starts + lens)[:, :, None])
               & active[:, :, None]).any(axis=1)
        mask = np.where(win, 0.0, mask)

    y = np.where(mask > 0, y, np.nan)
    reg = np.stack(
        [is_holiday[None, :].repeat(S, 0), price, promo], axis=-1
    )
    return y, mask, reg


_M5_SCENARIOS = {
    "m5": "base",
    "m5_irregular": "irregular",
    "m5_missing_windows": "missing_windows",
    "m5_cold_start": "cold_start",
    "m5_hier": "hier",
}


def m5_rows(
    lo: int, hi: int, n_days: int = 1941, seed: int = 2,
    scenario: str = "base", with_regressors: bool = True,
) -> SeriesBatch:
    """Rows [lo, hi) of the block-seeded m5-like family.

    ``m5_rows(lo, hi, ...)`` is bitwise-identical to
    ``m5_rows(0, N, ...)`` sliced to [lo, hi) for any covering N — the
    property the data plane's parallel shard ingestion rests on."""
    if not 0 <= lo < hi:
        raise ValueError(f"bad row range [{lo}, {hi})")
    ds = 13514.0 + np.arange(n_days, dtype=np.float64)
    ys, masks, regs = [], [], []
    for block in range(lo // SEED_BLOCK, (hi - 1) // SEED_BLOCK + 1):
        row0 = block * SEED_BLOCK
        y_b, m_b, r_b = _m5_block(
            _block_rng(seed, block), n_days, ds, scenario, seed, row0
        )
        s = slice(max(lo, row0) - row0, min(hi, row0 + SEED_BLOCK) - row0)
        ys.append(y_b[s])
        masks.append(m_b[s])
        regs.append(r_b[s])
    gen = next(
        (k for k, v in _M5_SCENARIOS.items() if v == scenario), "m5"
    )
    return SeriesBatch(
        ds=ds,
        y=np.concatenate(ys, axis=0),
        mask=np.concatenate(masks, axis=0),
        series_ids=dataset_ids(gen, lo, hi),
        regressors=np.concatenate(regs, axis=0) if with_regressors else None,
        regressor_names=("holiday", "price", "promo") if with_regressors
        else (),
    )


def demo_weekly_rows(
    lo: int, hi: int, n_steps: int = 180, seed: int = 0
) -> SeriesBatch:
    """Block-seeded smooth weekly-cycle series (level + slope + sine) —
    the demo workload the serve loadgen and streaming replay share via
    the data plane (it used to be generated privately in
    ``serve.__main__._build_demo_registry``)."""
    if not 0 <= lo < hi:
        raise ValueError(f"bad row range [{lo}, {hi})")
    t = np.arange(n_steps, dtype=np.float64)
    ys = []
    for block in range(lo // SEED_BLOCK, (hi - 1) // SEED_BLOCK + 1):
        rng = _block_rng(seed, block, tag=2)
        S = SEED_BLOCK
        level = rng.uniform(5.0, 50.0, (S, 1))
        slope = rng.uniform(-0.02, 0.05, (S, 1))
        amp = rng.uniform(0.5, 3.0, (S, 1))
        y_b = (level + slope * t[None, :]
               + amp * np.sin(2 * np.pi * t[None, :] / 7.0)
               + rng.normal(0, 0.2, (S, n_steps)))
        row0 = block * SEED_BLOCK
        ys.append(y_b[max(lo, row0) - row0:
                      min(hi, row0 + SEED_BLOCK) - row0])
    y = np.concatenate(ys, axis=0)
    return SeriesBatch(
        ds=t, y=y, mask=np.ones_like(y),
        series_ids=dataset_ids("demo_weekly", lo, hi),
    )


def wiki_logistic_like(
    n_series: int = 8, n_days: int = 1200, seed: int = 3
) -> SeriesBatch:
    """Saturating-growth pageview series with known capacity (eval config 4)."""
    rng = np.random.default_rng(seed)
    ds = 14000.0 + np.arange(n_days, dtype=np.float64)
    t = np.linspace(0, 1, n_days)
    caps = rng.uniform(5e3, 5e4, (n_series, 1))
    k = rng.uniform(4, 10, (n_series, 1))
    m = rng.uniform(0.2, 0.5, (n_series, 1))
    base = caps / (1.0 + np.exp(-k * (t[None, :] - m)))
    dow = ds.astype(np.int64) % 7
    weekly_mult = 1.0 + 0.1 * np.asarray([0.5, 1, 0.8, 0.6, 0.2, -1.5, -1.8])[dow]
    y = base * weekly_mult[None, :] * (1 + rng.normal(0, 0.04, (n_series, n_days)))
    ids = np.asarray([f"wiki_{i}" for i in range(n_series)])
    return SeriesBatch(
        ds=ds, y=y, mask=np.ones_like(y), series_ids=ids,
        cap=np.broadcast_to(caps * 1.1, y.shape).copy(),
    )
