"""Columnar on-disk dataset cache: the package's shared data plane.

BENCH_builder_r06 spent 356 s of its 480 s wall generating synthetic
data — 74% of the benchmark measured datagen, not fitting.  This module
replaces every ad-hoc in-memory/private datagen path (bench.py's ``/tmp``
npy cache, serve loadgen's inline demo batch, streaming's hand-rolled
frames) with ONE cache of memmap column shards:

* **Layout** — one directory per dataset under :func:`default_root`,
  keyed by (generator, shape, seed, shard width, datagen fingerprint).
  Inside: ``spec.json`` (identity, written first), ``ds.npy`` (shared
  calendar, float64), preallocated float32 column files ``y.npy`` /
  ``mask.npy`` / ``reg.npy`` / ``cap.npy`` in exactly the layout
  ``orchestrate._load_data`` mmaps — a complete dataset dir IS a valid
  orchestrate ``--data`` dir — plus one ``shardok_<lo>_<hi>.json``
  sentinel per landed shard and a final ``plane_manifest.json``.

* **Lifecycle** — column files are preallocated memmaps filled shard by
  shard; a shard's rows become visible ONLY once its sentinel (written
  atomically, payload CRCs inside) lands, and the manifest (atomic,
  written last after sentinel coverage is complete) marks the dataset
  warm.  Readers never trust bytes a sentinel doesn't cover, so a torn
  shard can never be consumed; concurrent producers are safe because
  generation is deterministic — racers write identical bytes and the
  last identical sentinel wins whole.

* **Determinism** — generation is block-seeded
  (:data:`~tsspark_tpu.data.datasets.SEED_BLOCK`): rows [lo, hi) of a
  dataset are bitwise-identical whether produced by one process, a
  shard pool, or a fit worker self-healing a stalled ingest
  (``tests/test_plane.py`` pins cache == direct generation).

* **Overlap** — :mod:`tsspark_tpu.data.ingest` produces shards in a
  background process pool while orchestrate fit workers consume
  already-landed coverage (:func:`ready_coverage`), so a cold run
  starts fitting before ingestion finishes and a warm run is pure
  memmap reads.

Scenario packs (irregular cadence, missing windows, cold start, M5
store->dept->item hierarchy) are first-class named datasets behind the
same manifest — see :data:`GENERATORS`.
"""

from __future__ import annotations

import dataclasses
import glob
import hashlib
import json
import os
import tempfile
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tsspark_tpu.data import datasets
from tsspark_tpu.data.datasets import SeriesBatch
from tsspark_tpu.io import (
    atomic_write,
    attach_array,
    gate_ingest,
    hardlink,
    is_missing,
    open_memmap,
    reraise_classified,
)
from tsspark_tpu.plane.protocol import (
    read_json,
    shard_crcs,
    write_sentinel,
)
from tsspark_tpu.plane.protocol import shard_ranges as _plane_shard_ranges
from tsspark_tpu.resilience import integrity

#: Cache-format revision: bump when the on-disk layout (NOT the data)
#: changes incompatibly; part of every spec record.
PLANE_VERSION = 1

#: Default I/O shard width — a multiple of every pow-2 claim width the
#: orchestrator's autotuner dispatches (floor 128, historical cap 1024),
#: so fit claims always nest inside whole shards.
DEFAULT_SHARD_ROWS = 1024

#: Column files, in orchestrate._DATA_FIELDS naming (float32 on disk;
#: ``ds.npy`` rides separately and stays float64).
COLUMN_FIELDS = ("y", "mask", "reg", "cap")

_SPEC_FILE = "spec.json"
_MANIFEST_FILE = "plane_manifest.json"

#: name -> row generator ``fn(lo, hi, n_timesteps, seed) -> SeriesBatch``.
#: Every generator is block-seeded: rows are independent of the total
#: series count, so datasets extend without regeneration.
GENERATORS: Dict[str, Callable[..., SeriesBatch]] = {
    "m5": lambda lo, hi, t, seed: datasets.m5_rows(
        lo, hi, n_days=t, seed=seed, scenario="base"),
    "m5_irregular": lambda lo, hi, t, seed: datasets.m5_rows(
        lo, hi, n_days=t, seed=seed, scenario="irregular"),
    "m5_missing_windows": lambda lo, hi, t, seed: datasets.m5_rows(
        lo, hi, n_days=t, seed=seed, scenario="missing_windows"),
    "m5_cold_start": lambda lo, hi, t, seed: datasets.m5_rows(
        lo, hi, n_days=t, seed=seed, scenario="cold_start"),
    "m5_hier": lambda lo, hi, t, seed: datasets.m5_rows(
        lo, hi, n_days=t, seed=seed, scenario="hier"),
    "demo_weekly": lambda lo, hi, t, seed: datasets.demo_weekly_rows(
        lo, hi, n_steps=t, seed=seed),
}


@dataclasses.dataclass(frozen=True)
class DatasetSpec:
    """Identity of one cached dataset (the manifest key)."""

    generator: str
    n_series: int
    n_timesteps: int
    seed: int = 2
    shard_rows: int = DEFAULT_SHARD_ROWS

    def __post_init__(self):
        if self.generator not in GENERATORS \
                and not self.generator.startswith("import:"):
            raise ValueError(
                f"unknown generator {self.generator!r}; known: "
                f"{sorted(GENERATORS)} (or 'import:<name>')"
            )
        if self.n_series <= 0 or self.n_timesteps <= 0:
            raise ValueError("n_series and n_timesteps must be positive")
        if self.shard_rows <= 0:
            raise ValueError("shard_rows must be positive")

    def cache_key(self) -> str:
        return (
            f"{self.generator}_{self.n_series}x{self.n_timesteps}"
            f"_s{self.seed}_r{self.shard_rows}_{dataset_fingerprint()}"
        )

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "DatasetSpec":
        return cls(**{
            k: d[k] for k in
            ("generator", "n_series", "n_timesteps", "seed", "shard_rows")
        })


_FP_CACHE: Dict[str, str] = {}


def dataset_fingerprint() -> str:
    """Hash of the WHOLE data package (datasets + loaders + plane +
    ingest): a change to any of them rotates every cache key, so a
    loader/plane change can never serve stale cached arrays (ISSUE 9 —
    the old bench fingerprint hashed ``datasets.py`` alone)."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    if pkg in _FP_CACHE:
        return _FP_CACHE[pkg]
    h = hashlib.md5()
    h.update(str(PLANE_VERSION).encode())
    for path in sorted(glob.glob(os.path.join(pkg, "*.py"))):
        with open(path, "rb") as fh:
            h.update(fh.read())
    _FP_CACHE[pkg] = h.hexdigest()[:8]
    return _FP_CACHE[pkg]


def default_root() -> str:
    """The shared cache root: ``$TSSPARK_DATA_ROOT`` or a stable temp
    location (all subsystems — bench, serve loadgen, streaming replay —
    default here, which is what makes the plane SHARED)."""
    return os.environ.get("TSSPARK_DATA_ROOT") or os.path.join(
        tempfile.gettempdir(), "tsspark_plane"
    )


def dataset_dir(spec: DatasetSpec, root: Optional[str] = None) -> str:
    return os.path.join(root or default_root(), spec.cache_key())


def shard_ranges(spec: DatasetSpec) -> List[Tuple[int, int]]:
    return _plane_shard_ranges(spec.n_series, spec.shard_rows)


def generate_rows(spec: DatasetSpec, lo: int, hi: int) -> SeriesBatch:
    """Canonical in-memory generation of rows [lo, hi) — what the cache
    must match bitwise (after the float32/nan_to_num disk conversion)."""
    if spec.generator.startswith("import:"):
        raise ValueError(
            "imported datasets have no generator; read the cache"
        )
    return GENERATORS[spec.generator](
        lo, hi, spec.n_timesteps, spec.seed
    )


def series_ids(spec: DatasetSpec, lo: int = 0,
               hi: Optional[int] = None) -> np.ndarray:
    return datasets.dataset_ids(
        spec.generator, lo, spec.n_series if hi is None else hi
    )


# ---------------------------------------------------------------------------
# disk conversion
# ---------------------------------------------------------------------------


def batch_columns(batch: SeriesBatch) -> Dict[str, np.ndarray]:
    """SeriesBatch -> the float32 column dict the cache stores (NaN
    holes become zeros; the mask carries observedness — the exact
    conversion bench.py's old private cache applied)."""
    cols = {
        "y": np.nan_to_num(np.asarray(batch.y)).astype(np.float32),
        "mask": np.asarray(batch.mask, np.float32),
    }
    if batch.regressors is not None:
        cols["reg"] = np.asarray(batch.regressors, np.float32)
    if batch.cap is not None:
        cols["cap"] = np.asarray(batch.cap, np.float32)
    return cols


def _sentinel_path(dset_dir: str, lo: int, hi: int) -> str:
    return os.path.join(dset_dir, f"shardok_{lo:09d}_{hi:09d}.json")


def _land_shard_sentinel(dset_dir: str, lo: int, hi: int,
                         cols: Dict[str, np.ndarray]) -> None:
    """Publish (or re-publish) one shard's visibility sentinel: atomic,
    payload CRCs inside.  ONE writer for the base-ingest path AND the
    delta path — a delta that mutates landed rows must re-land the
    sentinel with fresh CRCs or ``verify_shard``/``repair`` would treat
    the advanced rows as corruption and roll them back to base."""
    sentinel = {
        "lo": lo, "hi": hi, "unix": round(time.time(), 3),
        "crc": shard_crcs(cols), "pid": os.getpid(),
    }
    write_sentinel(_sentinel_path(dset_dir, lo, hi), sentinel)


# ---------------------------------------------------------------------------
# writers
# ---------------------------------------------------------------------------


def _column_shapes(spec: DatasetSpec,
                   fields: Sequence[str]) -> Dict[str, Tuple[int, ...]]:
    n, t = spec.n_series, spec.n_timesteps
    shapes: Dict[str, Tuple[int, ...]] = {}
    for f in fields:
        if f == "reg":
            # Regressor count comes from a 1-row probe at create time
            # and is recorded in spec.json; see create_columns.
            continue
        shapes[f] = (n, t)
    return shapes


def _prealloc_column(path: str, shape: Tuple[int, ...]) -> None:
    """Preallocate one column file WITHOUT ever clobbering an existing
    one: the memmap is built under a dot-temp name and published with
    ``os.link`` (atomic create-if-absent — it FAILS when the path
    exists, unlike rename).  Two cold producers racing the same spec
    then cannot truncate rows — or orphan sentinels — the other has
    already landed; the loser simply adopts the winner's file."""
    if os.path.exists(path):
        return
    d, base = os.path.split(os.path.abspath(path))
    tmp = os.path.join(d, f".{base}.tmp.{os.getpid()}")
    mm = open_memmap(tmp, mode="w+", dtype=np.float32, shape=shape)
    del mm
    try:
        hardlink(tmp, path)
    except FileExistsError:
        pass  # a racer published first; keep theirs (rows may be landed)
    finally:
        try:
            os.remove(tmp)
        except OSError as e:
            # A temp that is already gone is fine; a disk that refuses
            # the unlink (EIO, EROFS) is not — surface it typed.
            if not is_missing(e):
                reraise_classified(e)


def read_spec(dset_dir: str) -> Optional[Dict]:
    """The dataset's identity record, or None when ``dset_dir`` is not
    a plane dataset (e.g. a plain ``orchestrate.spill_data`` dir).
    Absence and torn JSON read as None; a real disk failure raises its
    typed storage error (``tsspark_tpu.io.errors``)."""
    return read_json(os.path.join(dset_dir, _SPEC_FILE))


def create_columns(spec: DatasetSpec, root: Optional[str] = None) -> str:
    """Create (or adopt) the dataset dir: write ``spec.json`` + the
    shared calendar atomically and preallocate the column memmaps.

    Idempotent and race-safe: the column bytes are deterministic, so two
    creators racing the same spec produce identical files; preallocation
    itself is NOT atomic but no reader ever touches column rows before
    their shard sentinel exists (the sentinel, not the column file, is
    the unit of visibility)."""
    dset_dir = dataset_dir(spec, root)
    os.makedirs(dset_dir, exist_ok=True)
    record = read_spec(dset_dir)
    if record is not None:
        return dset_dir
    if spec.generator.startswith("import:"):
        raise ValueError("import_batch owns imported dataset creation")
    # Field/regressor discovery probes a TINY grid (fields and reg count
    # are per-generator constants, independent of T); the real calendar
    # comes from the closed-form grid so creation never generates a
    # full seed block on a consumer's blocked path.
    probe = generate_rows(
        dataclasses.replace(spec, n_timesteps=min(spec.n_timesteps, 8)),
        0, 1,
    )
    cols = batch_columns(probe)
    fields = sorted(cols)
    atomic_write(
        os.path.join(dset_dir, "ds.npy"),
        lambda fh: np.save(fh, datasets.dataset_calendar(
            spec.generator, spec.n_timesteps)),
    )
    for f in fields:
        shape = ((spec.n_series, spec.n_timesteps)
                 + cols[f].shape[2:])
        _prealloc_column(os.path.join(dset_dir, f"{f}.npy"), shape)
    record = dict(spec.to_dict(), fields=fields,
                  fingerprint=dataset_fingerprint(),
                  plane_version=PLANE_VERSION,
                  reg_names=list(probe.regressor_names))
    atomic_write(
        os.path.join(dset_dir, _SPEC_FILE),
        lambda fh: json.dump(record, fh, indent=1), mode="w",
    )
    return dset_dir


def write_shard(spec: DatasetSpec, shard_index: int,
                root: Optional[str] = None) -> Tuple[int, int]:
    """Generate and land one shard: fill the column memmap rows, flush,
    then publish the sentinel (atomic, CRCs inside) that makes the rows
    visible.  Emits a ``datagen.shard`` span + shard counters when a
    trace is bound.  Returns the (lo, hi) landed."""
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS

    t0 = time.time()
    dset_dir = create_columns(spec, root)
    lo, hi = shard_ranges(spec)[shard_index]
    batch = generate_rows(spec, lo, hi)
    cols = batch_columns(batch)
    for f, rows in cols.items():
        mm = open_memmap(
            os.path.join(dset_dir, f"{f}.npy"), mode="r+", lo=lo, hi=hi
        )
        mm[lo:hi] = rows
        mm.flush()
        del mm
    _land_shard_sentinel(dset_dir, lo, hi, cols)
    # Regenerating a shard that LANDED deltas (repair of a torn shard,
    # a re-produced range) must replay them: base bytes + the landed
    # patch stream IS the shard's committed state, and the sentinel
    # above only certifies the base.
    if _replay_deltas(dset_dir, lo, hi):
        _reland_sentinel_from_disk(dset_dir, lo, hi)
    dur = time.time() - t0
    if obs.active():
        obs.record("datagen.shard", t0, dur, lo=lo, hi=hi,
                   generator=spec.generator, rows=hi - lo)
        METRICS.counter("tsspark_datagen_shards_total").inc()
        METRICS.counter("tsspark_datagen_rows_total").inc(hi - lo)
        METRICS.histogram("tsspark_datagen_shard_seconds").observe(dur)
    return lo, hi


def finalize(spec: DatasetSpec, root: Optional[str] = None) -> str:
    """Write the manifest once sentinel coverage is complete (atomic,
    LAST — the manifest is the warm-cache hit marker, so it must never
    exist before every shard it certifies)."""
    dset_dir = dataset_dir(spec, root)
    missing = missing_shards(spec, root)
    if missing:
        raise RuntimeError(
            f"cannot finalize {dset_dir}: shards {missing} not landed"
        )
    record = dict(read_spec(dset_dir) or spec.to_dict(),
                  complete=True, unix=round(time.time(), 3),
                  shards=[list(r) for r in shard_ranges(spec)])
    atomic_write(
        os.path.join(dset_dir, _MANIFEST_FILE),
        lambda fh: json.dump(record, fh, indent=1), mode="w",
    )
    return dset_dir


def import_batch(batch: SeriesBatch, name: str,
                 root: Optional[str] = None,
                 shard_rows: int = DEFAULT_SHARD_ROWS) -> str:
    """Bring an externally-loaded batch (e.g. the real M5 CSVs via
    ``data.loaders``) under the same manifest: columns + sentinels +
    manifest, keyed ``import:<name>`` with a content hash so a changed
    file set never aliases a stale cache."""
    cols = batch_columns(batch)
    content = hashlib.md5()
    for f in sorted(cols):
        content.update(np.ascontiguousarray(cols[f]).tobytes())
    n, t = cols["y"].shape
    spec = DatasetSpec(
        generator=f"import:{name}_{content.hexdigest()[:8]}",
        n_series=n, n_timesteps=t, seed=0, shard_rows=shard_rows,
    )
    dset_dir = dataset_dir(spec, root)
    if is_complete(dset_dir):
        return dset_dir
    os.makedirs(dset_dir, exist_ok=True)
    atomic_write(
        os.path.join(dset_dir, "ds.npy"),
        lambda fh: np.save(fh, np.asarray(batch.ds, np.float64)),
    )
    fields = sorted(cols)
    for f in fields:
        path = os.path.join(dset_dir, f"{f}.npy")
        _prealloc_column(path, cols[f].shape)
        mm = open_memmap(path, mode="r+")
        mm[:] = cols[f]
        mm.flush()
        del mm
    record = dict(spec.to_dict(), fields=fields,
                  fingerprint=dataset_fingerprint(),
                  plane_version=PLANE_VERSION,
                  reg_names=list(batch.regressor_names),
                  series_ids=[str(s) for s in batch.series_ids])
    atomic_write(
        os.path.join(dset_dir, _SPEC_FILE),
        lambda fh: json.dump(record, fh, indent=1), mode="w",
    )
    for lo, hi in shard_ranges(spec):
        _land_shard_sentinel(dset_dir, lo, hi,
                             {f: cols[f][lo:hi] for f in fields})
    return finalize(spec, root)


# ---------------------------------------------------------------------------
# readers / coverage
# ---------------------------------------------------------------------------


def is_complete(dset_dir: str) -> bool:
    """Warm-cache hit test: a readable manifest marked complete."""
    try:
        with open(os.path.join(dset_dir, _MANIFEST_FILE)) as fh:
            return bool(json.load(fh).get("complete"))
    except (OSError, ValueError):
        return False


def landed_ranges(dset_dir: str) -> List[Tuple[int, int]]:
    """Merged row coverage of all landed shard sentinels (a torn
    sentinel — its writer died inside atomic_write, which cannot happen,
    but a hand-corrupted one can — reads as absent)."""
    spans = []
    for p in glob.glob(os.path.join(dset_dir, "shardok_*.json")):
        stem = os.path.basename(p)[len("shardok_"):-len(".json")]
        try:
            lo, hi = (int(x) for x in stem.split("_"))
        except ValueError:
            continue
        spans.append((lo, hi))
    merged: List[Tuple[int, int]] = []
    for lo, hi in sorted(spans):
        if merged and lo <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], hi))
        else:
            merged.append((lo, hi))
    return merged


def covers(ranges: Sequence[Tuple[int, int]], lo: int, hi: int) -> bool:
    """True when [lo, hi) lies inside the merged coverage."""
    for r_lo, r_hi in ranges:
        if r_lo <= lo and hi <= r_hi:
            return True
    return False


def ready_coverage(data_dir: str,
                   n_series: Optional[int] = None
                   ) -> Optional[List[Tuple[int, int]]]:
    """The row ranges a consumer may read RIGHT NOW, or None when no
    gating applies (a plain spill dir, or a complete dataset): the fit
    worker's claim filter during overlapped ingestion."""
    if read_spec(data_dir) is None:
        return None  # not a plane dataset: everything is ready
    if is_complete(data_dir):
        return None
    ranges = landed_ranges(data_dir)
    if n_series is not None:
        ranges = [(lo, min(hi, n_series)) for lo, hi in ranges
                  if lo < n_series]
    return ranges


def ingest_pending(data_dir: str, n_series: Optional[int] = None) -> bool:
    """True while a plane dataset's sentinel coverage is still
    incomplete (the consumer should wait — or self-produce — rather
    than give up)."""
    spec_rec = read_spec(data_dir)
    if spec_rec is None or is_complete(data_dir):
        return False
    total = spec_rec.get("n_series", 0)
    if n_series is not None:
        total = min(total, n_series)
    merged = landed_ranges(data_dir)
    covered = sum(min(hi, total) - lo for lo, hi in merged if lo < total)
    return covered < total


def missing_shards(spec: DatasetSpec,
                   root: Optional[str] = None) -> List[int]:
    dset_dir = dataset_dir(spec, root)
    landed = landed_ranges(dset_dir)
    return [
        i for i, (lo, hi) in enumerate(shard_ranges(spec))
        if not covers(landed, lo, hi)
    ]


def produce_next_missing(data_dir: str) -> bool:
    """Self-healing consumer path: generate + land the first missing
    shard inline (deterministic — identical bytes to whatever the dead
    ingest driver would have written).  Returns False when nothing is
    missing or the dir is not a generated plane dataset."""
    rec = read_spec(data_dir)
    if rec is None or str(rec.get("generator", "")).startswith("import:"):
        return False
    spec = DatasetSpec.from_dict(rec)
    root = os.path.dirname(os.path.abspath(data_dir))
    if os.path.abspath(dataset_dir(spec, root)) \
            != os.path.abspath(data_dir):
        # The dir was keyed under a different fingerprint (source edited
        # since creation): self-producing would land shards in a NEW dir
        # this consumer never reads — decline instead.
        return False
    missing = missing_shards(spec, root=root)
    if not missing:
        return False
    write_shard(spec, missing[0], root=root)
    return True


def verify_shard(dset_dir: str, lo: int, hi: int) -> bool:
    """Deep integrity check of one landed shard: recompute the column
    CRCs over the memmap rows and compare with the sentinel's.  False
    means the shard is torn/corrupt (reject it; :func:`repair` re-lands
    it)."""
    try:
        with open(_sentinel_path(dset_dir, lo, hi)) as fh:
            sentinel = json.load(fh)
    except (OSError, ValueError):
        return False
    crcs = sentinel.get("crc") or {}
    for f, want in crcs.items():
        path = os.path.join(dset_dir, f"{f}.npy")
        try:
            mm = attach_array(path)
        except (OSError, ValueError):
            return False
        got = zlib.crc32(np.ascontiguousarray(mm[lo:hi]).tobytes())
        del mm
        if got != int(want):
            return False
    return True


# ---------------------------------------------------------------------------
# row-advance deltas (the always-on ingest half of the delta-refit loop)
# ---------------------------------------------------------------------------
#
# Production data never stops arriving: after a dataset's base shards
# land, later observations arrive for a SUBSET of series.  A delta lands
# those advances under the same spec-first / sentinel-last discipline as
# base shards:
#
#   1. ``deltapatch_<seq>.npz``  — the patch payload (changed rows, the
#      new trailing-window values), atomic + CRC-stamped FIRST: the
#      patch file, not the memmap mutation, is the replayable record;
#   2. the column memmaps are mutated IN PLACE for the changed rows'
#      trailing window (unchanged rows' bytes never move — the
#      block-seeded layout stays bitwise-stable for everything that did
#      not advance);
#   3. every touched shard's ``shardok_*`` sentinel is RE-LANDED with
#      fresh CRCs (``verify_shard`` stays truthful over advanced rows);
#   4. ``deltaok_<seq>.json`` lands atomically LAST — the unit of
#      visibility.  ``advanced_since(stamp)`` unions the changed rows of
#      every delta with seq > stamp, which is exactly the claim set the
#      delta-refit engine (``tsspark_tpu.refit``) plans over.
#
# Crash story: a writer killed before step 4 leaves either (a) a patch
# with untouched memmaps — invisible, the re-land with the same seq
# overwrites it whole — or (b) mutated memmaps whose sentinels were not
# all re-landed — ``verify_shard`` rejects those shards and ``repair``
# regenerates base bytes THEN replays the landed (visible) patch stream
# (``write_shard`` replays deltas after base regeneration), so a torn
# delta can never half-appear.  Replays read the patch files, so
# re-application is bitwise idempotent.

#: Trailing timesteps one synthetic delta revises per advanced series.
DELTA_WINDOW = 8

_DELTA_OK_PREFIX = "deltaok_"
_DELTA_PATCH_PREFIX = "deltapatch_"


def _delta_ok_path(dset_dir: str, seq: int) -> str:
    return os.path.join(dset_dir, f"{_DELTA_OK_PREFIX}{seq:06d}.json")


def _delta_patch_path(dset_dir: str, seq: int) -> str:
    return os.path.join(dset_dir, f"{_DELTA_PATCH_PREFIX}{seq:06d}.npz")


def delta_records(dset_dir: str) -> List[Dict]:
    """Landed delta records, ascending by seq (a torn/corrupt record
    reads as absent — its delta never became visible)."""
    out = []
    for p in glob.glob(os.path.join(dset_dir, f"{_DELTA_OK_PREFIX}*.json")):
        try:
            with open(p) as fh:
                rec = json.load(fh)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict) and isinstance(rec.get("seq"), int):
            out.append(rec)
    return sorted(out, key=lambda r: r["seq"])


def delta_seq(dset_dir: str) -> int:
    """The dataset's delta coverage stamp: highest landed delta seq
    (0 = base data only).  Snapshots publish the stamp they were fitted
    at; ``advanced_since`` turns two stamps into a claim set."""
    recs = delta_records(dset_dir)
    return recs[-1]["seq"] if recs else 0


def delta_seq_since(dset_dir: str, after: int) -> int:
    """The coverage stamp by INCREMENTAL probe: walk the visibility
    records upward from a stamp already known landed.  Seqs are
    contiguous (allocation is serialized under the delta flock), so an
    always-on poller pays O(new deltas) per poll instead of the full
    glob+parse of every historical record ``delta_seq`` does — the
    difference between an idle daemon stat-ing one missing file per
    tick and re-reading a 10k-record history 20 times a second."""
    seq = max(0, int(after))
    while os.path.exists(_delta_ok_path(dset_dir, seq + 1)):
        seq += 1
    return seq


def _load_patch(dset_dir: str, seq: int) -> Optional[Dict]:
    """One delta's patch payload (CRC-verified), or None when absent or
    corrupt — a visible delta whose patch cannot be read is treated as
    corruption by ``repair`` (the shard CRCs catch the bytes)."""
    path = _delta_patch_path(dset_dir, seq)
    try:
        z = np.load(path)
    except Exception:
        # Not just OSError/ValueError: a torn zip surfaces as
        # BadZipFile (same breadth as orchestrate.load_prep).
        return None
    try:
        if not integrity.verify_arrays(z):
            return None
        return {
            "rows": np.asarray(z["rows"], np.int64),
            "window": int(z["window"]),
            "y": np.asarray(z["y"], np.float32),
            "mask": np.asarray(z["mask"], np.float32),
        }
    except Exception:
        return None  # truncated member mid-read: same as corrupt
    finally:
        z.close()


def delta_patch(dset_dir: str, seq: int) -> Optional[Dict]:
    """Public read of ONE visible delta's full patch payload
    (CRC-verified ``{"rows", "window", "y", "mask"}``), or None when
    absent/corrupt — the anomaly scorer's feed (``tsspark_tpu.alerts``),
    which needs the landed values themselves, not just the row set."""
    return _load_patch(dset_dir, int(seq))


def delta_rows(dset_dir: str, seq: int) -> Optional[np.ndarray]:
    """The changed-row set of ONE visible delta (the arrival-model feed
    for the always-on scheduler's speculation), or None when the patch
    is unreadable — callers wanting claim-set semantics must use
    :func:`advanced_since`, which widens unreadable patches instead of
    dropping them."""
    patch = _load_patch(dset_dir, int(seq))
    return None if patch is None else patch["rows"]


def advanced_since(dset_dir: str, coverage_stamp: int) -> np.ndarray:
    """Sorted unique series rows that advanced after ``coverage_stamp``
    — the delta-refit engine's changed set.  A snapshot fitted at stamp
    S is stale exactly for ``advanced_since(dir, S)``; refit cost scales
    with this set, not with the fleet.

    A VISIBLE delta whose patch file is unreadable must not silently
    shrink the set: the memmaps already carry its bytes (sentinels were
    re-landed over them), so dropping the record would leave those
    series stale FOREVER once a later refit advances the stamp.  The
    record's touched shards widen to their full row ranges instead —
    over-refit is correct, under-refit is permanent staleness."""
    import warnings

    rec0 = read_spec(dset_dir) or {}
    n = int(rec0.get("n_series", 0))
    shard_rows_n = int(rec0.get("shard_rows", DEFAULT_SHARD_ROWS))
    rows: List[np.ndarray] = []
    for rec in delta_records(dset_dir):
        if rec["seq"] <= int(coverage_stamp):
            continue
        patch = _load_patch(dset_dir, rec["seq"])
        if patch is not None:
            rows.append(patch["rows"])
            continue
        warnings.warn(
            f"{dset_dir}: delta {rec['seq']} is visible but its patch "
            "file is unreadable; widening its touched shards to whole "
            "row ranges so the advanced series are refit rather than "
            "left permanently stale",
            RuntimeWarning,
        )
        for si in rec.get("shards") or ():
            lo = int(si) * shard_rows_n
            hi = min(lo + shard_rows_n, n)
            rows.append(np.arange(lo, hi, dtype=np.int64))
    if not rows:
        return np.empty(0, np.int64)
    return np.unique(np.concatenate(rows))


def _apply_patch(dset_dir: str, n_timesteps: int, patch: Dict,
                 lo: Optional[int] = None,
                 hi: Optional[int] = None) -> int:
    """Scatter one patch into the column memmaps (optionally restricted
    to rows in [lo, hi) — the repair replay path).  Returns the number
    of rows written.  Absolute values, so re-application is bitwise
    idempotent."""
    rows, w = patch["rows"], patch["window"]
    if lo is not None:
        keep = (rows >= lo) & (rows < hi)
        rows = rows[keep]
        y_vals, m_vals = patch["y"][keep], patch["mask"][keep]
    else:
        y_vals, m_vals = patch["y"], patch["mask"]
    if not len(rows):
        return 0
    t0 = n_timesteps - w
    for f, vals in (("y", y_vals), ("mask", m_vals)):
        mm = open_memmap(os.path.join(dset_dir, f"{f}.npy"), mode="r+")
        mm[rows, t0:] = vals
        mm.flush()
        del mm
    return int(len(rows))


def _replay_deltas(dset_dir: str, lo: int, hi: int) -> int:
    """Re-apply every VISIBLE delta's rows inside [lo, hi) in seq order
    (base regeneration just rolled them back).  Returns rows replayed."""
    rec0 = read_spec(dset_dir)
    if rec0 is None:
        return 0
    n = 0
    for rec in delta_records(dset_dir):
        patch = _load_patch(dset_dir, rec["seq"])
        if patch is not None:
            n += _apply_patch(dset_dir, int(rec0["n_timesteps"]), patch,
                              lo=lo, hi=hi)
    return n


def _reland_sentinel_from_disk(dset_dir: str, lo: int, hi: int) -> None:
    """Re-land one shard's sentinel with CRCs recomputed from the
    memmaps' CURRENT bytes (the post-delta state)."""
    rec = read_spec(dset_dir) or {}
    cols = {}
    for f in rec.get("fields") or ("mask", "y"):
        mm = attach_array(os.path.join(dset_dir, f"{f}.npy"))
        cols[f] = np.ascontiguousarray(mm[lo:hi])
        del mm
    _land_shard_sentinel(dset_dir, lo, hi, cols)


def _rows_covered(ranges: Sequence[Tuple[int, int]],
                  rows: np.ndarray) -> np.ndarray:
    """Vectorized membership of each row in the merged coverage: one
    searchsorted over the range starts instead of a per-row Python
    ``covers`` scan (a 30% churn at 1M series is 300k rows on the
    latency-measured land path)."""
    if not len(ranges):
        return np.zeros(len(rows), bool)
    starts = np.asarray([r[0] for r in ranges], np.int64)
    ends = np.asarray([r[1] for r in ranges], np.int64)
    idx = np.searchsorted(starts, rows, side="right") - 1
    ok = idx >= 0
    ok[ok] = rows[ok] < ends[idx[ok]]
    return ok


def land_delta(data_dir: str, rows, y_tail,
               mask_tail=None) -> Dict:
    """Land one row-advance delta: new trailing-window observations for
    the series in ``rows`` (absolute row indices; ``y_tail`` is
    ``(len(rows), window)``).  Patch first, memmap scatter, touched
    sentinels re-landed, visibility record LAST — see the section
    comment for the crash story.  Returns the landed delta record.

    Landers serialize on an advisory flock for the whole
    seq-allocation -> visibility-record window: deltas are NOT
    deterministic racers like base shards (two landers allocating the
    same seq would have the last ``deltaok`` rename swallow the
    loser's record whole — its rows scattered into the memmaps but
    never claimable, the permanent-staleness failure mode)."""
    import fcntl

    # Degradation-ladder backpressure: below the pause-ingest headroom
    # threshold a lander fails fast (BackpressureError) instead of
    # racing the reaper for the last bytes on the device.
    gate_ingest(data_dir)
    rec = read_spec(data_dir)
    if rec is None:
        raise ValueError(f"{data_dir} is not a plane dataset")
    n, t_len = int(rec["n_series"]), int(rec["n_timesteps"])
    rows = np.unique(np.asarray(rows, np.int64))
    y_tail = np.asarray(y_tail, np.float32)
    if y_tail.ndim != 2 or y_tail.shape[0] != len(rows):
        raise ValueError(
            f"y_tail {y_tail.shape} does not match {len(rows)} rows"
        )
    w = int(y_tail.shape[1])
    if w > t_len or len(rows) and (rows[0] < 0 or rows[-1] >= n):
        raise ValueError("delta rows/window outside the dataset grid")
    covered = _rows_covered(landed_ranges(data_dir), rows)
    if not covered.all():
        bad = rows[~covered][:5].tolist()
        raise ValueError(
            f"rows {bad} have not landed; deltas only advance landed "
            "rows"
        )
    if mask_tail is None:
        mask_tail = np.ones_like(y_tail)
    lock = open(os.path.join(data_dir, ".delta.lock"), "a")
    try:
        fcntl.flock(lock, fcntl.LOCK_EX)
        seq = delta_seq(data_dir) + 1
        patch = {
            "rows": rows, "window": np.asarray(w),
            "y": y_tail, "mask": np.asarray(mask_tail, np.float32),
        }
        atomic_write(
            _delta_patch_path(data_dir, seq),
            lambda fh: np.savez(fh, **integrity.stamp(patch)),
        )
        _apply_patch(data_dir, t_len, {
            "rows": rows, "window": w, "y": y_tail,
            "mask": np.asarray(mask_tail, np.float32),
        })
        shard_rows_n = int(rec.get("shard_rows", DEFAULT_SHARD_ROWS))
        touched = np.unique(rows // shard_rows_n).tolist()
        for si in touched:
            lo, hi = si * shard_rows_n, min((si + 1) * shard_rows_n, n)
            _reland_sentinel_from_disk(data_dir, lo, hi)
        record = {
            "seq": seq, "n_changed": int(len(rows)), "window": w,
            "shards": touched, "unix": round(time.time(), 3),
            "pid": os.getpid(),
        }
        atomic_write(
            _delta_ok_path(data_dir, seq),
            lambda fh: json.dump(record, fh), mode="w",
        )
    finally:
        fcntl.flock(lock, fcntl.LOCK_UN)
        lock.close()
    from tsspark_tpu.obs import context as obs
    if obs.active():
        obs.record("datagen.delta", time.time(), 0.0, seq=seq,
                   n_changed=int(len(rows)), window=w)
    return record


def land_synthetic_delta(data_dir: str, frac: float,
                         window: int = DELTA_WINDOW,
                         seed: int = 0,
                         rows=None) -> Dict:
    """Synthesize one advance event: a seeded ``frac`` of the fleet
    gains a revised trailing window (current values + a small seeded
    drift — the warm-start-friendly shape of real late-arriving data).
    The changed-row choice and the perturbation are deterministic in
    (dataset key, next seq, seed); the landed patch file is the
    replayable record either way.  ``rows`` pins the advancing series
    explicitly (``frac`` is then ignored) — the freshness bench uses a
    hot-biased row stream so the scheduler's arrival model has a real
    per-series cadence to learn."""
    rec = read_spec(data_dir)
    if rec is None:
        raise ValueError(f"{data_dir} is not a plane dataset")
    n, t_len = int(rec["n_series"]), int(rec["n_timesteps"])
    w = min(int(window), t_len)
    seq = delta_seq(data_dir) + 1
    key = zlib.crc32(
        f"{rec.get('generator')}:{rec.get('seed')}:{seq}:{seed}".encode()
    )
    rng = np.random.default_rng([int(rec.get("seed", 0)), seq, seed, key])
    if rows is not None:
        rows = np.unique(np.asarray(rows, np.int64))
        if not len(rows):
            raise ValueError("explicit rows must be non-empty")
    else:
        k = max(1, int(round(float(frac) * n))) if frac > 0 else 0
        if k == 0:
            raise ValueError("frac too small: no series would advance")
        rows = np.sort(rng.choice(n, size=min(k, n), replace=False))
    y_mm = attach_array(os.path.join(data_dir, "y.npy"))
    cur = np.asarray(y_mm[rows, t_len - w:], np.float32)
    del y_mm
    drift = rng.normal(0.0, 0.05, cur.shape).astype(np.float32)
    scale = np.maximum(np.abs(cur), 1.0)
    y_tail = cur + drift * scale
    return land_delta(data_dir, rows, y_tail)


def repair(spec: DatasetSpec, root: Optional[str] = None,
           deep: bool = True) -> List[Tuple[int, int]]:
    """Re-land every missing or (with ``deep``) CRC-failing shard and
    drop a stale manifest first so a corrupt dataset can never keep its
    warm-hit marker.  Returns the ranges rewritten."""
    dset_dir = dataset_dir(spec, root)
    bad: List[Tuple[int, int]] = []
    ranges = shard_ranges(spec)
    for i, (lo, hi) in enumerate(ranges):
        landed = covers(landed_ranges(dset_dir), lo, hi)
        if landed and (not deep or verify_shard(dset_dir, lo, hi)):
            continue
        bad.append((lo, hi))
        try:
            os.remove(os.path.join(dset_dir, _MANIFEST_FILE))
        except OSError as e:
            # No manifest to drop is the common case; a disk refusing
            # the unlink must not let a corrupt dataset keep its
            # warm-hit marker silently.
            if not is_missing(e):
                reraise_classified(e)
        write_shard(spec, i, root)
    if bad and not missing_shards(spec, root):
        finalize(spec, root)
    return bad


def open_batch(dset_dir: str, mmap: bool = True) -> SeriesBatch:
    """Read a COMPLETE dataset as a SeriesBatch of memmap columns (the
    warm path: zero generation, zero copies until a consumer slices)."""
    if not is_complete(dset_dir):
        raise FileNotFoundError(
            f"{dset_dir} has no complete plane manifest (cold cache? "
            "run ensure()/ingest first)"
        )
    rec = read_spec(dset_dir) or {}
    mode = "r" if mmap else None
    load = lambda f: attach_array(os.path.join(dset_dir, f"{f}.npy"),
                                  mmap_mode=mode)
    fields = rec.get("fields") or ["mask", "y"]
    ids = rec.get("series_ids")
    if ids is None:
        ids = datasets.dataset_ids(
            rec.get("generator", "m5"), 0, int(rec.get("n_series", 0))
        )
    else:
        ids = np.asarray(ids)
    return SeriesBatch(
        ds=np.load(os.path.join(dset_dir, "ds.npy")),
        y=load("y"), mask=load("mask"), series_ids=ids,
        regressors=load("reg") if "reg" in fields else None,
        cap=load("cap") if "cap" in fields else None,
        regressor_names=tuple(rec.get("reg_names") or ()),
    )


#: A dataset untouched this long is reaped by the cold-path sweep: the
#: datagen fingerprint is part of every key, so each data-package edit
#: strands the previous keys' full-size dirs forever otherwise.
STALE_DATASET_S = 7 * 24 * 3600.0


def sweep_stale_datasets(root: Optional[str] = None,
                         max_age_s: float = STALE_DATASET_S) -> int:
    """Remove dataset dirs whose NEWEST file mtime is older than
    ``max_age_s`` (same age-gated pattern as bench's scratch reaper: a
    dir any producer or landing shard touched recently is live).  Runs
    on the cold ingest path only — warm hits never pay the scan.
    Unlinking under a concurrent reader is safe: its mmap keeps the
    bytes until unmapped.  Returns the count removed."""
    import shutil

    root = root or default_root()
    removed = 0
    try:
        entries = [os.path.join(root, n) for n in os.listdir(root)]
    except OSError as e:
        if is_missing(e):
            return 0  # no cache root yet: nothing to sweep
        reraise_classified(e)
    now = time.time()
    for d in entries:
        if not os.path.isdir(d):
            continue
        try:
            newest = max(
                (os.path.getmtime(p) for p in
                 glob.glob(os.path.join(d, "**"), recursive=True)),
                default=os.path.getmtime(d),
            )
        except OSError as e:
            if is_missing(e):
                continue  # a racer removed the dir mid-scan
            reraise_classified(e)
        if now - newest > max_age_s:
            shutil.rmtree(d, ignore_errors=True)
            removed += 1
    return removed


def ensure(spec: DatasetSpec, root: Optional[str] = None,
           processes: int = 0) -> str:
    """The front door: return the dataset dir, ingesting first when the
    cache misses (``processes`` > 1 fans shard generation out to a
    process pool via :mod:`tsspark_tpu.data.ingest`).  Emits cache
    hit/miss counters into the obs registry."""
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS

    dset_dir = dataset_dir(spec, root)
    if is_complete(dset_dir):
        METRICS.counter("tsspark_datagen_cache_hits_total").inc()
        return dset_dir
    METRICS.counter("tsspark_datagen_cache_misses_total").inc()
    from tsspark_tpu.data import ingest

    return ingest.run_ingest(spec, root=root, processes=processes)
